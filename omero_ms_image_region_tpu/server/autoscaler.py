"""Elastic fleet autoscaler: the controller that DECIDES fleet size.

Every elasticity primitive already exists — PR 9's zero-downtime
drain with warm shard handoff, PR 11's undrain pre-stage-back, PR 9's
pressure governor, PR 10's session model — but until now a human with
curl closed the loop.  This module is the TPU build's analogue of the
reference adding/removing clustered verticle instances (PAPER.md
L0/L3): a tick-driven policy (hysteresis + cooldown, the same
injectable-clock idiom as ``server.pressure``) reads the fleet's
queue pressure, the pressure governor's level and the session model's
predicted demand, and scales a PRE-PROVISIONED member set between a
floor and a ceiling:

* **scale-down** = ``FleetRouter.drain_member(intent="autoscale")`` —
  the member finishes in-flight work, its HBM shard pre-stages WARM
  onto its ring successors, and it stops taking routes.  The
  ``autoscale`` intent keeps the drain out of ``drain.fail-readyz``'s
  503 posture: a routine scale-down of one member must not read like
  an operator pulling the whole instance from LB rotation.
* **scale-up** = ``FleetRouter.undrain_member`` — the member rejoins
  its ring arcs and the drain-time shard manifest replays BACK into
  it (pre-stage-back), so a joiner serves its first routed requests
  from HBM instead of paying the cold reads the drill gates on.

Safety invariants (property-tested in tests/test_autoscaler.py):

* the number of non-draining members never goes below ``floor``, and
  a scale-down is refused when the ROUTABLE (healthy, non-draining)
  count would — member deaths count against the budget, so a failover
  plus a concurrent scale-down tick cannot race the fleet to zero;
* at most ONE scale operation is in flight (ticks during an active
  drain are ``blocked:busy``; the draining reservation is taken
  SYNCHRONOUSLY on the tick's loop step, so two ticks cannot pick the
  same victim);
* the autoscaler only ever undrains members IT drained — an
  operator's ``/admin/drain`` stays drained until the operator says
  otherwise;
* transitions are separated by ``cooldown-s`` (the flapping bound the
  drill asserts) and gated on ``hold-ticks`` consecutive over/under
  readings (the hysteresis that keeps one bursty tick from scaling).

Surfaces: ``autoscaler:`` config, ``GET /admin/autoscaler`` status,
``imageregion_autoscaler_*`` telemetry, ``autoscale.up`` /
``autoscale.down`` / ``autoscale.blocked`` flight events (rendered in
``scripts/trace_report.py``'s self-preservation footer).  How to size
floor/ceiling from a measured CAPACITY record: deploy/DEPLOY.md
"Capacity & autoscaling".
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, List, Optional

from ..utils import decisions, telemetry

log = logging.getLogger("omero_ms_image_region_tpu.autoscaler")

# Closed blocked-reason vocabulary (the ``reason`` label on
# imageregion_autoscaler_blocked_total — never caller-minted).
BLOCKED_REASONS = ("busy", "cooldown", "floor", "ceiling", "no-member",
                   "quorum")


class Autoscaler:
    """Tick-driven elastic controller over a ``FleetRouter``.

    ``demand_source`` (optional) returns the session model's predicted
    offered load in requests/s (e.g. viewport-tracked sessions x the
    per-session steady rate); with ``lane-capacity-tps`` calibrated
    from a CAPACITY record it becomes the third scale signal alongside
    queue depth and the pressure level.  ``clock`` is injectable so
    tests drive cooldown/hold deterministically (the
    ``server.pressure`` idiom)."""

    def __init__(self, config, router, governor=None,
                 demand_source: Optional[Callable[[], Optional[float]]]
                 = None,
                 drain_kwargs: Optional[dict] = None,
                 lifecycle=None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self.router = router
        self.governor = governor
        self.demand_source = demand_source
        self.drain_kwargs = dict(drain_kwargs or {})
        # Sidecar-unit process lifecycle (server.sidecar
        # SidecarUnitLifecycle duck type: sync ``stop(name)`` /
        # ``start(name)``, both idempotent): with it, a scale-down
        # actually STOPS the parked member's process once its drain
        # settles (the shard handoff must finish first — the bytes
        # live in that process), and a scale-up RESTARTS the unit
        # (blocking until its socket accepts) BEFORE undraining, so
        # routes never land on a dead socket.  None = the
        # pre-provisioned posture (park/rejoin warm processes).
        self.lifecycle = lifecycle
        self.clock = clock
        self._up_streak = 0
        self._down_streak = 0
        # Far enough in the past that the first transition is never
        # cooldown-blocked (clock() may legally start at 0).
        self._last_transition: Optional[float] = None
        self._op: Optional[asyncio.Task] = None
        # LIFO of members THIS controller drained: scale-up rejoins
        # the most recently parked member (its manifest is freshest).
        self._scaled_down: List[str] = []
        self.transitions: List[dict] = []
        self.last_blocked: Optional[str] = None
        # Decision-ledger state: monotonically counted ticks key the
        # measured-outcome probes (N ticks after a verdict, did the
        # queue actually move?), and the steady flag makes "steady" a
        # TRANSITION record, not a per-tick drumbeat.
        self._tick_no = 0
        self._outcome_probes: List[dict] = []
        self._steady = False
        telemetry.AUTOSCALER.set_bounds(self.config.floor,
                                        self.ceiling())

    # -------------------------------------------------------- membership

    def ceiling(self) -> int:
        c = self.config.ceiling
        return len(self.router.order) if c <= 0 \
            else min(c, len(self.router.order))

    def active_members(self) -> List[str]:
        """Members currently accepting routes (not draining) — the
        figure the floor invariant is stated over."""
        return [n for n in self.router.order
                if not self.router.members[n].draining]

    def routable_members(self) -> List[str]:
        return [n for n in self.active_members()
                if self.router.members[n].healthy]

    # ----------------------------------------------------------- signals

    def signals(self) -> dict:
        routable = self.routable_members()
        lanes = self.router.lane_width * max(1, len(routable))
        depth = self.router.queue_depth()
        demand = None
        if self.demand_source is not None:
            try:
                demand = self.demand_source()
            except Exception:
                demand = None
        level = self.governor.level if self.governor is not None else 0
        capacity_tps = (len(routable) * self.router.lane_width
                        * self.config.lane_capacity_tps)
        # Hot-key replica pressure (parallel.fleet): the hottest
        # promoted route's heat in promotion-threshold units —
        # "one plane is outrunning one member", a reason to grow that
        # plain queue depth can miss while balancing absorbs the skew.
        replica_fn = getattr(self.router, "replica_pressure", None)
        replica_pressure = 0.0
        if replica_fn is not None:
            try:
                replica_pressure = float(replica_fn() or 0.0)
            except Exception:
                replica_pressure = 0.0
        return {
            "queue_depth": depth,
            "queue_per_lane": depth / lanes,
            "pressure_level": level,
            "demand_tps": demand,
            "capacity_tps": capacity_tps,
            "replica_pressure": replica_pressure,
        }

    def _hot_scale_factor(self) -> float:
        """The replica-pressure scale-up trigger (``hotkey.scale-
        factor`` off the router's config; 0 disables)."""
        hotkey = getattr(self.router, "hotkey", None)
        try:
            return float(getattr(hotkey, "scale_factor", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _wants(self, sig: dict) -> Optional[str]:
        c = self.config
        up = sig["queue_per_lane"] >= c.queue_high_per_lane
        if sig["pressure_level"] >= 2:       # critical: grow early
            up = True
        hot_factor = self._hot_scale_factor()
        if hot_factor > 0 \
                and sig.get("replica_pressure", 0.0) >= hot_factor:
            # Sustained demand on one plane is holding multiples of
            # the promotion threshold: replicas are absorbing it for
            # now, but the set is bounded — grow the fleet so the
            # chain prefix has more members to spread over.
            up = True
        demand = sig["demand_tps"]
        if (demand is not None and c.lane_capacity_tps > 0
                and demand > sig["capacity_tps"]):
            up = True
        if up:
            return "up"
        routable = len(self.routable_members())
        down = (sig["queue_per_lane"] <= c.queue_low_per_lane
                and sig["pressure_level"] == 0)
        if down and demand is not None and c.lane_capacity_tps > 0:
            # Shrinking must leave enough measured capacity for the
            # PREDICTED demand, not just the instantaneous queue — a
            # quiet second inside a busy day must not shed a member
            # the next minute needs back.
            after = ((routable - 1) * self.router.lane_width
                     * c.lane_capacity_tps)
            down = demand <= after
        return "down" if down else None

    # ---------------------------------------------------- decision ledger

    @staticmethod
    def _snap(sig: dict) -> dict:
        """The signal snapshot a decision record carries: everything
        the policy read this tick, so the ledger answers "why" without
        a second source."""
        return {
            "queue_depth": sig["queue_depth"],
            "queue_per_lane": round(sig["queue_per_lane"], 4),
            "pressure_level": sig["pressure_level"],
            "demand_tps": sig["demand_tps"],
            "capacity_tps": sig["capacity_tps"],
            "replica_pressure": round(
                sig.get("replica_pressure", 0.0), 4),
        }

    def _decide(self, verdict: str, sig: dict, member: str = "",
                **detail) -> None:
        """One ledger record for this tick's verdict, plus an outcome
        probe that measures the queue ``outcome-horizon-ticks`` ticks
        from now — the record says what the controller believed, the
        outcome says whether the fleet agreed."""
        doc = dict(detail)
        doc["signals"] = self._snap(sig)
        seq = decisions.record("autoscaler", verdict, member=member,
                               detail=doc)
        if seq >= 0:
            self._outcome_probes.append({
                "seq": seq, "tick": self._tick_no,
                "queue_depth": sig["queue_depth"],
                "active": len(self.active_members()),
            })

    def _resolve_outcomes(self, sig: dict) -> None:
        """Attach measured outcomes to verdicts whose horizon has
        elapsed (ring + spool via ``decisions.resolve``)."""
        horizon = max(1, decisions.LEDGER.outcome_horizon_ticks)
        due = [p for p in self._outcome_probes
               if self._tick_no - p["tick"] >= horizon]
        if not due:
            return
        self._outcome_probes = [p for p in self._outcome_probes
                                if self._tick_no - p["tick"] < horizon]
        active = len(self.active_members())
        for probe in due:
            decisions.resolve(probe["seq"], {
                "ticks": self._tick_no - probe["tick"],
                "queue_depth": sig["queue_depth"],
                "queue_depth_delta":
                    sig["queue_depth"] - probe["queue_depth"],
                "active": active,
                "active_delta": active - probe["active"],
            })

    # ------------------------------------------------------------ policy

    def _blocked(self, reason: str, want: str, sig: dict) -> str:
        telemetry.AUTOSCALER.count_blocked(reason)
        if reason != self.last_blocked:
            # Tape hygiene: a fleet parked at its floor refuses the
            # same want every tick — the counter carries the rate,
            # the flight ring records the TRANSITION (a steady
            # blocked:floor at 3 ticks/s would evict every useful
            # event from the black box within minutes).  The decision
            # ledger shares the transition gate: one "blocked" record
            # per posture change, with the signals that forced it.
            telemetry.FLIGHT.record("autoscale.blocked",
                                    reason=reason, want=want)
            self._decide("blocked", sig, reason=reason, want=want)
        self.last_blocked = reason
        self._steady = False
        return f"blocked:{reason}"

    def _publish(self) -> None:
        telemetry.AUTOSCALER.set_active(len(self.active_members()))
        telemetry.AUTOSCALER.set_bounds(self.config.floor,
                                        self.ceiling())

    def tick(self) -> Optional[str]:
        """One policy evaluation.  Returns "up"/"down" on a
        transition, "blocked:<reason>" when one was wanted but
        refused, None when steady — the drill and the property tests
        read this verdict directly."""
        now = self.clock()
        sig = self.signals()
        self._tick_no += 1
        self._resolve_outcomes(sig)
        want = self._wants(sig)
        if want == "up":
            self._up_streak += 1
            self._down_streak = 0
        elif want == "down":
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0
        try:
            if want is None:
                if not self._steady:
                    # "steady" is a transition record too: the tick
                    # the controller STOPPED wanting anything closes
                    # the previous episode in the ledger.
                    self._decide("steady", sig)
                    self._steady = True
                return None
            hold = self.config.hold_ticks
            if (want == "up" and self._up_streak < hold) \
                    or (want == "down" and self._down_streak < hold):
                # Held by hysteresis: not yet a decision — the ledger
                # records verdicts, not the debounce.
                return None
            if self._op is not None and not self._op.done():
                return self._blocked("busy", want, sig)
            if (self._last_transition is not None
                    and now - self._last_transition
                    < self.config.cooldown_s):
                return self._blocked("cooldown", want, sig)
            from ..parallel import federation
            if not federation.quorum_allow("autoscaler"):
                # Fenced minority: a membership transition is exactly
                # the ring change a partition forbids — the majority
                # side may be scaling the SAME units right now.
                return self._blocked("quorum", want, sig)
            if want == "up":
                return self._scale_up(now, sig)
            return self._scale_down(now, sig)
        finally:
            self._publish()

    def _record(self, action: str, member: str, now: float,
                sig: dict) -> None:
        self._last_transition = now
        self._up_streak = 0
        self._down_streak = 0
        self.last_blocked = None
        self._steady = False
        self._decide(action, sig, member=member)
        doc = {"action": action, "member": member, "t": now,
               "active": len(self.active_members()),
               "queue_depth": sig["queue_depth"]}
        self.transitions.append(doc)
        if len(self.transitions) > 64:
            # Bounded history: status() shows the recent tail, the
            # counters/flight ring carry the totals — a long-lived
            # oscillating fleet must not grow this list forever.
            del self.transitions[:-64]
        telemetry.AUTOSCALER.count_transition(action)
        telemetry.FLIGHT.record(
            f"autoscale.{action}", member=member,
            active=doc["active"], queue=sig["queue_depth"],
            demand=sig["demand_tps"])
        log.info("autoscale %s: member %s (active %d, queue %d)",
                 action, member, doc["active"], sig["queue_depth"])

    def _scale_up(self, now: float, sig: dict) -> str:
        if len(self.active_members()) + 1 > self.ceiling():
            return self._blocked("ceiling", "up", sig)
        # Only members THIS controller parked are candidates: an
        # operator's drain is an operator's decision.
        while self._scaled_down:
            name = self._scaled_down[-1]
            member = self.router.members.get(name)
            if (member is not None and member.draining
                    and getattr(member, "drain_intent",
                                None) == "autoscale"):
                break
            self._scaled_down.pop()      # operator took it over
        else:
            return self._blocked("no-member", "up", sig)
        name = self._scaled_down.pop()
        if self.lifecycle is not None:
            # Unit-managed member: restart its process FIRST (blocking
            # spawn + socket wait, off-loop), undrain only once the
            # socket accepts.  The reservation (popped above) and the
            # transition record are taken synchronously on this tick,
            # so concurrent ticks see the op in flight (blocked:busy).
            async def _up() -> None:
                try:
                    await asyncio.to_thread(self.lifecycle.start, name)
                except Exception:
                    # Spawn failed: re-park the member for the next
                    # attempt; it is still draining, still ours.
                    log.warning("autoscale unit start of %s failed; "
                                "re-parked", name, exc_info=True)
                    self._scaled_down.append(name)
                    return
                member = self.router.members.get(name)
                if member is not None and hasattr(member, "revive"):
                    member.revive()
                self.router.undrain_member(name)

            if self._has_loop():
                self._op = asyncio.get_running_loop().create_task(_up())
            else:
                # Sync caller with no loop: do the start + undrain
                # INLINE (blocking is the sync caller's bargain) —
                # discarding the coroutine would leak the member:
                # stopped process, still draining, no longer parked.
                self._op = None
                try:
                    self.lifecycle.start(name)
                except Exception:
                    log.warning("autoscale unit start of %s failed; "
                                "re-parked", name, exc_info=True)
                    self._scaled_down.append(name)
                    return self._blocked("no-member", "up", sig)
                member = self.router.members.get(name)
                if member is not None and hasattr(member, "revive"):
                    member.revive()
                self.router.undrain_member(name)
            self._record("up", name, now, sig)
            return "up"
        # undrain is synchronous (the pre-stage-back replay rides it
        # as a background task the router tracks).
        self.router.undrain_member(name)
        self._record("up", name, now, sig)
        return "up"

    def _scale_down(self, now: float, sig: dict) -> str:
        routable = self.routable_members()
        if len(routable) - 1 < self.config.floor \
                or len(self.active_members()) - 1 < self.config.floor:
            # Routable AND active: deaths spend the shrink budget too
            # (a dead-but-undrained member still owes the floor its
            # comeback), and either bound alone could be gamed by the
            # other's race.
            return self._blocked("floor", "down", sig)
        # The LAST routable member in stack order (never member 0 —
        # the mesh/bulk lane — while anything else can go).
        routable_set = set(routable)
        candidates = [n for n in reversed(self.router.order)
                      if n in routable_set]
        victim = None
        for name in candidates:
            if name != self.router.order[0] or len(candidates) == 1:
                victim = name
                break
        if victim is None:
            return self._blocked("no-member", "down", sig)
        member = self.router.members[victim]
        # SYNCHRONOUS reservation on this loop step: the member stops
        # being active/routable NOW, so a concurrent tick (or a
        # concurrent floor check) sees the post-drain world before the
        # drain coroutine has even started.
        member.draining = True
        member.drain_intent = "autoscale"
        self._scaled_down.append(victim)

        async def _drain() -> None:
            try:
                await self.router.drain_member(
                    victim, intent="autoscale", **self.drain_kwargs)
            except Exception:
                log.warning("autoscale drain of %s failed", victim,
                            exc_info=True)
                return
            if self.lifecycle is not None:
                # Drain settled and the shard handed off: stop the
                # parked member's PROCESS — elasticity that releases
                # real memory/devices, not a warm park.  Strictly
                # after the handoff (the warm bytes live in that
                # process until it finishes).
                try:
                    await asyncio.to_thread(self.lifecycle.stop,
                                            victim)
                except Exception:
                    log.warning("autoscale unit stop of %s failed",
                                victim, exc_info=True)

        if self._has_loop():
            self._op = asyncio.get_running_loop().create_task(_drain())
        else:
            # Sync caller with no loop (property tests drive the
            # policy alone): the reservation stands; the settle and
            # handoff belong to the async path.
            self._op = None
            telemetry.DRAIN.set_state(victim, "draining")
        self._record("down", victim, now, sig)
        return "down"

    @staticmethod
    def _has_loop() -> bool:
        try:
            asyncio.get_running_loop()
            return True
        except RuntimeError:
            return False

    async def wait_op(self) -> None:
        """Await the in-flight scale operation, if any (drills and
        scripted rolls)."""
        if self._op is not None:
            await self._op

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        sig = self.signals()
        now = self.clock()
        cooldown_left = 0.0
        if self._last_transition is not None:
            cooldown_left = max(
                0.0, self.config.cooldown_s
                - (now - self._last_transition))
        return {
            "enabled": True,
            "floor": self.config.floor,
            "ceiling": self.ceiling(),
            "active": self.active_members(),
            "routable": self.routable_members(),
            "autoscale_drained": [
                n for n in self.router.order
                if self.router.members[n].draining
                and getattr(self.router.members[n], "drain_intent",
                            None) == "autoscale"],
            "cooldown_remaining_s": round(cooldown_left, 3),
            "op_in_flight": (self._op is not None
                             and not self._op.done()),
            "last_blocked": self.last_blocked,
            "transitions": self.transitions[-16:],
            "signals": sig,
        }

    def summary(self) -> str:
        """One-line /readyz annotation."""
        return (f"{len(self.active_members())}/{self.ceiling()} "
                f"active (floor {self.config.floor})")

    # ------------------------------------------------------------ runner

    async def run(self) -> None:
        """Asyncio tick loop (the governor's idiom); the app's
        robustness startup hook owns the task."""
        interval = max(0.05, self.config.interval_s)
        while True:
            await asyncio.sleep(interval)
            try:
                self.tick()
            except Exception:
                log.warning("autoscaler tick failed", exc_info=True)
