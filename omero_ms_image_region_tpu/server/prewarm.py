"""Startup pre-warming of the hot render executables.

Everything under ``jit`` compiles on first use — 20-40 s per program on
a remote-attached chip (cached across restarts by the persistent
compilation cache, but a fresh deployment pays it once per shape).
Without this, the FIRST interactive request of each shape eats that
compile; the reference's analogue is the Bio-Formats memoizer wait that
front-loads reader construction cost at startup
(``beanRefContext.xml:19-21``).

``renderer.prewarm`` lists the tile shapes a deployment expects, e.g.::

    renderer:
        prewarm: ["4x1024", "3x512@90", "2x1024:uint8"]

Each spec is ``<channels>x<tile-edge>[@quality][:dtype]`` (quality
defaults to the LocalCompress default; ``:dtype`` names the images'
storage dtype, default uint16 — serving stages storage dtype in both
cache postures, and the dtype keys the compiled program).  For every
spec the serving-path programs are compiled through the real ops entry
points with the renderer's own wire engine(s):

- the batched JPEG program at EVERY launchable padded batch shape up
  to ``max_batch`` (``batcher._BATCH_SHAPES``: batch 1 is the idle
  lone-tile path single-tile p50 rides, max_batch the loaded steady
  state, and the intermediate shapes — including the non-power-of-two
  3 and 6 — are what the inflight-aware group split launches);
- the packed-RGBA program at batch 1 (png/tif formats).

Settings use the ramp-weight table form (plain color channels; LUT
renders compile on first use).
"""

from __future__ import annotations

import logging
import re
import time
from typing import List, Sequence, Tuple

import numpy as np

from ..codecs import DEFAULT_JPEG_QUALITY

logger = logging.getLogger(__name__)

_SPEC_RE = re.compile(r"^(\d+)x(\d+)(?:@(\d+))?(?::([a-z0-9]+))?$")

# Storage dtypes a pixel source can stage — imported from the TIFF
# reader's sample table so the two can never drift.
from ..io.tiff import STORAGE_DTYPE_NAMES as _SPEC_DTYPES  # noqa: E402


def parse_spec(spec: str) -> Tuple[int, int, int, "np.dtype"]:
    """``"4x1024[@90][:uint8]"`` -> (channels, edge, quality, dtype).

    The dtype suffix names the images' STORAGE dtype (serving stages
    storage dtype in both cache postures, and dtype keys the compiled
    program); default uint16, the WSI class.
    """
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"renderer.prewarm spec {spec!r} is not "
            f"'<channels>x<tile-edge>[@quality][:dtype]'")
    channels, edge, q = (int(m.group(1)), int(m.group(2)),
                        int(m.group(3)) if m.group(3)
                        else round(DEFAULT_JPEG_QUALITY * 100))
    if not (1 <= channels <= 64):
        raise ValueError(f"prewarm channels out of range: {spec!r}")
    if not (16 <= edge <= 8192) or edge % 16:
        raise ValueError(
            f"prewarm tile edge must be a multiple of 16 in "
            f"[16, 8192]: {spec!r}")
    if not (1 <= q <= 100):
        raise ValueError(f"prewarm quality out of range: {spec!r}")
    dt = m.group(4) or "uint16"
    if dt not in _SPEC_DTYPES:
        raise ValueError(
            f"prewarm dtype {dt!r} not one of {_SPEC_DTYPES}: {spec!r}")
    return channels, edge, q, np.dtype(dt)


def _warm_stage_shapes(B: int, C: int, bh: int, bw: int,
                       raw_dtype) -> None:
    """Warm the FETCH-STAGE half of the two-stage group dispatch.

    The pipelined batcher ships each group's stacked raw through the
    packed stager (``io.staging.stage``) before taking a device lane;
    the on-device unpack is shape-jitted per (array shape, ladder word
    length), so the first pipelined group of a shape would otherwise
    eat a seconds-scale XLA compile mid-serving.  Content word counts
    are data-dependent but ladder-quantized, so compiling the ladder
    lengths bracketing typical pixel entropy (~0.3-0.8x raw bytes)
    covers serving traffic; off-lattice or sub-threshold shapes take
    the uncompiled plain transfer and need no warming.
    """
    from ..io import staging

    shape = (B, C, bh, bw)
    nbytes = int(np.prod(shape)) * np.dtype(raw_dtype).itemsize
    if (np.dtype(raw_dtype) != np.uint16
            or nbytes < staging._MIN_STAGE_BYTES
            or int(np.prod(shape)) > staging._MAX_STAGE_SAMPLES
            or not staging._regular_shape(shape)):
        return
    import jax
    import jax.numpy as jnp

    n_rows = B * C * bh
    widths = jax.device_put(
        np.zeros(n_rows * ((bw + 31) // 32), np.uint8))
    raw_words = nbytes // 4
    lengths = sorted({staging._pad_words(int(raw_words * f))
                      for f in (0.35, 0.55, 0.8)})
    for n_words in lengths:
        np.asarray(staging.unpack16_device(
            jnp.zeros(n_words, jnp.uint32), widths, shape))


def _warm_one(C: int, edge: int, quality: int, batch_sizes: Sequence[int],
              engines: Sequence[str], buckets, raw_dtype,
              exec_cache=None) -> None:
    from ..flagship import flagship_settings
    from ..ops.jpegenc import render_batch_to_jpeg
    from ..ops.render import render_tile_batch_packed
    from .batcher import pick_bucket

    bh, bw = pick_bucket(edge, edge, buckets)
    _, settings = flagship_settings(C)
    for B in dict.fromkeys(batch_sizes):   # de-dup, keep order
        # Zeros: programs are content-independent.  The dtype must
        # match what serving stacks (it keys the compiled program);
        # both cache postures stage the images' STORAGE dtype.
        raw = np.zeros((B, C, bh, bw), raw_dtype)
        stacked = {
            k: (np.stack([v] * B) if getattr(v, "ndim", 0) else v)
            for k, v in settings.items()
        }
        args = (raw, stacked["window_start"], stacked["window_end"],
                stacked["family"], stacked["coefficient"],
                stacked["reverse"], settings["cd_start"],
                settings["cd_end"], stacked["tables"])
        for engine in engines:
            # tune=False: these all-zero compile probes must never
            # feed the per-workload Huffman tuning — tables fitted to
            # a black tile would be published permanently and serve
            # every real tile of this shape with mismatched codes.
            render_batch_to_jpeg(*args, quality=quality,
                                 dims=[(edge, edge)] * B, engine=engine,
                                 tune=False)
        if B == 1:
            if exec_cache is not None:
                # Persistence posture: the packed program loads from a
                # prior life's serialized executable (no trace, no
                # compile) or compiles once and is serialized for the
                # next life; either way the registered program is what
                # serving groups of this signature will call.
                fn = exec_cache.ensure("render_tile_batch_packed",
                                       render_tile_batch_packed, args)
                np.asarray(fn(*args) if fn is not None
                           else render_tile_batch_packed(*args))
            else:
                np.asarray(render_tile_batch_packed(*args))
        # The pipelined dispatch's fetch-stage half (packed-staging
        # unpack programs for this stacked group shape).
        _warm_stage_shapes(B, C, bh, bw, raw_dtype)


def prewarm_batch_sizes(max_batch: int) -> tuple:
    """Every padded batch shape the dispatcher can launch at or below
    ``max_batch`` — imported from the batcher's own shape table so the
    two can never drift.  Warming only (1, max_batch) left the
    intermediate entries (3, 6) to lazy XLA compiles on the first 3-/
    6-tile group (seconds on tunnel-attached chips)."""
    from .batcher import _BATCH_SHAPES
    sizes = tuple(s for s in _BATCH_SHAPES if s <= max_batch)
    return sizes if max_batch in sizes else sizes + (max_batch,)


def prewarm_renderer(specs: List[str], engines: Sequence[str],
                     max_batch: int, buckets,
                     cpu_fallback_max_px: int = 0,
                     exec_cache=None) -> None:
    """Compile the serving programs for each spec; failures are logged,
    never fatal (serving still works, it just compiles lazily).

    Each spec carries its images' storage dtype (default uint16) — the
    dtype serving stacks in either cache posture, which keys the
    compiled program.  Specs at or below ``cpu_fallback_max_px`` are
    skipped: the handler routes those renders to the host kernel, so a
    device program would never be hit.  ``/readyz`` reports degraded
    while this runs (telemetry.READINESS).
    """
    from ..utils.telemetry import READINESS
    # Malformed specs raise HERE, before the readiness flag flips or
    # any compile starts (the loader's contract: config errors are
    # loud, and a caller spawning this on a background thread gets the
    # raise before the thread — never a silently-degraded prewarm or a
    # stuck-pending /readyz).
    parsed = [(spec,) + tuple(parse_spec(spec)) for spec in specs]
    batch_sizes = prewarm_batch_sizes(max_batch)
    READINESS.prewarm_pending = bool(specs)
    try:
        for spec, C, edge, quality, raw_dtype in parsed:
            if edge * edge <= cpu_fallback_max_px:
                logger.info(
                    "prewarm %s skipped: %dx%d px is at/below "
                    "renderer.cpu-fallback-max-px (%d) and serves on "
                    "the host kernel", spec, edge, edge,
                    cpu_fallback_max_px)
                continue
            t0 = time.perf_counter()
            try:
                _warm_one(C, edge, quality, batch_sizes, engines,
                          buckets, raw_dtype, exec_cache=exec_cache)
            except Exception:
                # Per-spec: one shape's dead compile must not strand
                # the others (serving still works, it compiles lazily).
                logger.warning("prewarm %s failed; first requests of "
                               "this shape will compile lazily", spec,
                               exc_info=True)
            else:
                logger.info("prewarmed %s (engines %s, batches %s, %s) "
                            "in %.1fs", spec, "/".join(engines),
                            "/".join(map(str, batch_sizes)),
                            np.dtype(raw_dtype).name,
                            time.perf_counter() - t0)
    finally:
        READINESS.prewarm_pending = False
