"""Stuck-lane / hung-wire watchdog with targeted self-healing.

The PR 3 fault layer reacts to components that are DEAD (a connection
that errored, a breaker that tripped).  This watchdog covers the worse
class: components that are merely STUCK — a device lane whose group
render has been running N x its historical p99 (a wedged XLA dispatch,
a hung wire fetch inside the render), or a sidecar connection that
stopped producing frames while requests are parked on it (a peer
wedged mid-frame).  Neither errors; both hold callers hostage until
their deadlines, and nothing before this module would ever recycle
them.

The healing ladder is SMALLEST-SCOPE-FIRST, per the reference's
recycle-one-verticle posture:

1. **requeue the group** — a stuck batcher group's unsettled waiters
   are requeued at the head of their bucket queue and re-rendered by a
   healthy pipeline slot; the wedged thread, when (if) it finishes,
   settles into already-done futures (``server.batcher`` implements
   this as its ``watchdog_scan``).
2. **drop the connection** — a hung sidecar wire (in-flight requests,
   no received frame past the hang bound) is dropped so the retry
   policy re-issues idempotent calls on a fresh connection
   (``server.sidecar.SidecarClient.watchdog_scan``).
3. **escalate** — only a victim that was already healed
   ``escalate-after - 1`` times escalates: the event carries
   ``escalate=True`` and the wired callback (the PR 3 supervisor's
   restart, an operator pager) decides the bigger hammer.

Targets implement one duck-typed method::

    watchdog_scan(now) -> [ {"action": str, "target": str,
                             "escalate": bool, ...}, ... ]

performing their own smallest-scope healing and RETURNING what they
did; the watchdog is the cadence, the accounting
(``imageregion_watchdog_fires_total``), the flight-recorder events,
and the escalation relay.  A scan that raises is logged and never
stops the loop — a buggy target must not kill the component that
exists to survive bugs.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

from ..utils import telemetry

log = logging.getLogger("omero_ms_image_region_tpu.watchdog")


class Watchdog:
    """Tick-driven scan over registered targets."""

    def __init__(self, interval_s: float = 2.0,
                 escalate_cb: Optional[Callable[[dict], None]] = None):
        self.interval_s = max(0.05, interval_s)
        self.escalate_cb = escalate_cb
        self._targets: List[object] = []
        self.fires_total = 0

    def add_target(self, target) -> None:
        if not hasattr(target, "watchdog_scan"):
            raise TypeError(
                f"watchdog target {target!r} has no watchdog_scan")
        self._targets.append(target)

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One scan over every target; returns all fire events (tests
        drive this directly; the runner calls it on the interval)."""
        now = time.monotonic() if now is None else now
        events: List[dict] = []
        for target in self._targets:
            try:
                fired = target.watchdog_scan(now) or []
            except Exception:
                log.warning("watchdog scan failed on %r", target,
                            exc_info=True)
                continue
            events.extend(fired)
        for event in events:
            self.fires_total += 1
            telemetry.WATCHDOG.count_fire(event.get("action", "?"))
            telemetry.FLIGHT.record("watchdog.fire", **{
                k: v for k, v in event.items() if k != "escalate"})
            log.warning("watchdog fired: %s on %s (%s)",
                        event.get("action"), event.get("target"),
                        {k: v for k, v in event.items()
                         if k not in ("action", "target")})
            if event.get("escalate") and self.escalate_cb is not None:
                try:
                    self.escalate_cb(event)
                except Exception:
                    log.warning("watchdog escalation callback failed",
                                exc_info=True)
        return events

    async def run(self) -> None:
        """Asyncio cadence loop (started by ``server.app`` /
        ``sidecar_main`` when ``watchdog.enabled``)."""
        import asyncio

        while True:
            await asyncio.sleep(self.interval_s)
            self.tick()


def build_watchdog(config, renderer=None, clients=(),
                   escalate_cb=None) -> Watchdog:
    """The standard wiring: the batcher (stuck device lanes) and any
    sidecar clients (hung wires) under one cadence, with the config's
    thresholds pushed onto each target."""
    wd = Watchdog(interval_s=config.interval_s,
                  escalate_cb=escalate_cb)
    if renderer is not None and hasattr(renderer, "watchdog_scan"):
        renderer.watchdog_stall_factor = config.stall_factor
        renderer.watchdog_stall_min_s = config.stall_min_s
        renderer.watchdog_escalate_after = config.escalate_after
        wd.add_target(renderer)
    for client in clients:
        if hasattr(client, "watchdog_scan"):
            client.wire_hang_s = config.wire_hang_s
            client.watchdog_escalate_after = config.escalate_after
            wd.add_target(client)
    return wd
