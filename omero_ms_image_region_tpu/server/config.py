"""Service configuration (≙ ``src/dist/conf/config.yaml`` + the Vert.x
ConfigRetriever, ``ImageRegionMicroserviceVerticle.java:98-118``).

YAML keys keep the reference's names where a setting has a direct analogue
(``port``, ``cache-control-header``, ``omero.web.session_cookie_name``,
``session-store``, ``redis-cache``, per-cache ``enabled`` flags,
``omero.server.omero.pixeldata.max_tile_length``) so an existing deployment
file ports by deleting the Java-only blocks and adding ``data-dir``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import yaml

from ..services.cache import CacheConfig
from ..utils.faultinject import FaultInjectionConfig


@dataclass
class BatcherConfig:
    enabled: bool = True
    max_batch: int = 8
    # Queue-pressure growth bound; None = 2x max_batch (measured
    # on-chip: exec rates hold at batch 16, degrade past it).
    max_batch_limit: Optional[int] = None
    linger_ms: float = 2.0
    # Concurrent group renders per bucket key: group k+1's device
    # dispatch overlaps group k's wire fetch + host entropy encode.
    # Default 4: each group's fetch pays the link round-trip (~100 ms
    # on a tunnel), so two in-flight groups cannot keep the wire busy
    # once RTT rivals transfer time — measured closed-loop on-chip
    # (scripts/exp_pipeline_depth.py, congested-window interleaved
    # pairs): depth 4 never lost to 2 and recovered 15-60% in the
    # high-RTT windows (huffman 24.9->31.5, sparse 11.1->17.6 tiles/s).
    pipeline_depth: int = 4
    # Preferred concurrent group count under backlog: >1 makes the
    # dispatcher split a burst across that many wire streams instead
    # of popping max_batch-sized convoys.  Default 1 (off): measured
    # closed-loop on-chip (scripts/exp_inflight.py, interleaved
    # windows), max_batch convoys beat 3-way splitting 31.2 vs 21.8
    # tiles/s — B=8 execution efficiency and fewer dispatches outweigh
    # the extra RTT hiding.  Kept as a knob for low-RTT deployments.
    # Single-host only; multi-host meshes always pop max_batch.
    target_inflight: int = 1
    # Bounded device-execute stage of the two-stage group pipeline:
    # each group render splits into fetch/stage (stack + host->device
    # upload) and device-execute halves, and at most this many groups
    # occupy the execute stage at once.  Default 2 (double-buffered):
    # group N+1's upload overlaps group N's execute without letting
    # every pipeline_depth group pile onto the device.  Multi-host
    # meshes force 1 (SPMD launch order).
    device_lanes: int = 2


@dataclass
class RawCacheConfig:
    """HBM-resident raw tile tier (io.devicecache.DeviceRawCache)."""

    enabled: bool = True
    max_bytes: int = 2 * 1024 * 1024 * 1024
    prefetch: bool = True              # pan-ahead neighbor staging
    # Content-digest index over the cache: planes whose bytes are
    # already HBM-resident (under any key — wire pushes included) are
    # never re-shipped over the host->device link, and the sidecar
    # answers digest probes (wire protocol v2) from it.  Costs one
    # BLAKE2b pass per cold host read (~ms per 8 MB tile).
    digest_dedup: bool = True


@dataclass
class RendererConfig:
    """Render path selection knobs."""

    # Renders of at most this many pixels take the CPU reference kernel
    # (refimpl) instead of a device round trip.  0 disables.  Default is
    # the measured break-even: at 256x256 single-channel the host kernel
    # (~2 ms) matches co-located dispatch+fetch overhead and beats any
    # network-attached device by orders of magnitude; beyond it batched
    # device renders win.  Tunnel-attached deployments (device RTT in the
    # 100 ms class) may want this much larger.
    cpu_fallback_max_px: int = 256 * 256
    # Device JPEG wire format: "sparse" (18-bit coefficient entries +
    # host entropy coding — wins on fast links), "huffman" (device
    # fixed-table Huffman stream, ~3x fewer wire bytes — wins on slow or
    # congested links; batcher-compatible), or "bitpack" (the legacy
    # full-grid device Huffman; direct renderer only).
    jpeg_engine: str = "sparse"
    # JAX persistent compilation cache directory: restarts reuse
    # compiled executables instead of paying first-compile (~20-40 s
    # per program shape on tunnel-attached chips; measured 11 s -> 1.5 s
    # cross-process).  None disables.
    compilation_cache_dir: Optional[str] = None
    # Render kernel for the direct (unbatched) renderer: "xla" (the
    # portable reference, ops.render) or "pallas" — the experimental
    # VMEM-resident fused kernel as a COMPILE-GUARDED option: it serves
    # only ramp-weight renders (no LUT files) on a real TPU backend,
    # and ANY compile/runtime failure falls back permanently to the XLA
    # kernel, so the option can only ever remove work.  Stage profiling
    # shows the XLA render is already ~free (the wire packers dominate
    # device time), so "xla" stays the default.
    kernel: str = "xla"
    # Tile shapes ("<channels>x<tile-edge>[@quality][:dtype]", e.g.
    # "4x1024" or "3x1024:uint8" — :dtype is the images' storage dtype,
    # default uint16) whose serving programs compile at STARTUP instead
    # of on the first request of that shape (server.prewarm; ≙ the
    # reference's Bio-Formats memoizer wait, beanRefContext.xml:19-21).
    # Batched postures only.  Empty = lazy compiles.
    prewarm: Tuple[str, ...] = ()


@dataclass
class SidecarConfig:
    """Frontend/compute process split (≙ the reference's event-bus seam,
    ``ImageRegionVerticle.java:128-136``): N frontend processes forward
    serialized request ctxs over a unix socket — or TCP when ``socket``
    is ``host:port``, for frontends on other hosts — to ONE
    device-owning sidecar process.

    role:
      combined — single process, HTTP + device (default; socket unused)
      frontend — HTTP only; forward renders to ``socket``
      sidecar  — device only; serve renders on ``socket``
      split    — spawn a sidecar child, then serve as a frontend
    """

    socket: Optional[str] = None
    role: str = "combined"


@dataclass
class WireConfig:
    """Frontend<->sidecar transport knobs (wire protocol v3 — see
    deploy/DEPLOY.md "Wire transport").  All three legs degrade
    per-feature against previous-round peers, so a mixed-version fleet
    keeps serving on the v2 behavior."""

    # Scatter-gather frame coalescing: queued frames flush as ONE
    # vectored write + ONE drain(), bounded per flush by these two
    # knobs.  Purely sender-local (the byte stream is identical), so
    # it needs no negotiation and no version gate.
    coalesce_max_frames: int = 64
    coalesce_max_bytes: int = 1 * 1024 * 1024
    # Same-host shared-memory ring per connection direction: bodies of
    # at least ring-min-body-bytes ride the ring with only a
    # descriptor frame on the socket.  0 disables (and declines peer
    # hellos offering one).  Negotiation failure or ring exhaustion
    # falls back to socket bodies automatically.
    ring_bytes: int = 32 * 1024 * 1024
    ring_min_body_bytes: int = 4096
    # Progressive first-tile-out streaming: render responses leave as
    # per-tile chunk frames the moment the tile's encode slice lands,
    # and the HTTP frontend forwards them as a chunked response.
    streaming: bool = True
    chunk_max_bytes: int = 256 * 1024


@dataclass
class FleetConfig:
    """Data-parallel device fleet (``parallel.fleet``) — the TPU-native
    analogue of the reference's Hazelcast-clustered verticle fleet: N
    members each own a shard of the hot HBM state, requests route by a
    consistent hash of their plane identity, load skew is handled by
    bounded work stealing, and a dead member's shard fails over
    hash-ring-next.  See deploy/DEPLOY.md "Fleet serving"."""

    enabled: bool = False
    # Combined role: N in-process member lanes (member 0 is the base
    # stack — the lockstep mesh lane in mesh deployments; members
    # 1..N-1 get their own renderer + DeviceRawCache shard).  NOTE:
    # one JAX process — members shard cache/queues but all dispatch
    # to the process's default device; real per-member device SETS
    # are the ``sockets`` topology (one pinned sidecar process each).
    members: int = 2
    # Frontend role: one render sidecar per address; each sidecar owns
    # its own device set.  Overrides ``members``.
    sockets: Tuple[str, ...] = ()
    # Concurrent renders per member (models the member's device
    # lanes); fleet admission sees lane-width x members as the
    # service parallelism.
    lane_width: int = 2
    # An idle member lane steals the OLDEST queued request from the
    # most-backlogged peer once that backlog reaches this depth; the
    # stolen render runs from source bytes without adopting cache
    # ownership.  0 disables stealing.
    steal_min_backlog: int = 2
    # Virtual nodes per member on the hash ring (higher = smoother
    # key-space split; the golden-assignment tests pin 64).
    hash_replicas: int = 64
    # Fail a dead member's shard over hash-ring-next (and re-assign
    # its queued work).  Off = its requests fail as the member does.
    failover: bool = True
    # How long a remote member stays out of the ring after its
    # connection died through every policy retry (the supervisor's
    # restart window); the first successful call re-admits it.
    down_cooldown_s: float = 5.0


@dataclass
class HotkeyConfig:
    """Hot-plane replication (``parallel.fleet`` popularity tier) —
    survive the viral image: routes whose decayed request heat passes
    ``threshold`` get an R>1 replica set drawn deterministically from
    the ring chain, reads balance least-queued across live replicas,
    and heat decay demotes back to R=1 (replica HBM reclaimed by the
    cache-pressure ladder, not eagerly).  See deploy/DEPLOY.md
    "Hot objects"."""

    enabled: bool = False
    # Promotion threshold in units of decayed requests: under a
    # sustained rate of r req/s a route's heat converges to
    # r * decay_s, so the default promotes a plane holding more than
    # ~12/decay_s req/s of one member's demand.
    threshold: float = 12.0
    # Heat decay time constant (seconds): how fast popularity ages
    # out.  Demotion happens below threshold * demote_fraction.
    decay_s: float = 20.0
    # Replica-set size for promoted routes (chain prefix, owner
    # included): 2 = owner + one replica.  Capped by fleet size.
    max_replicas: int = 2
    # Bounded heat-table cardinality (top-K routes tracked).
    top_k: int = 128
    # Hysteresis: demote when heat falls below threshold * this.
    demote_fraction: float = 0.5
    # Autoscaler coupling: replica pressure (hottest route's heat /
    # threshold) at or past this factor wants a scale-up, distinct
    # from queue depth.  0 disables the signal.
    scale_factor: float = 2.0


@dataclass
class FederationConfig:
    """Cross-host fleet federation (``parallel.federation``) — the
    rack-scale Hazelcast analogue: the fleet's membership becomes a
    VERSIONED MANIFEST every host carries identically (member names,
    hosts, addresses, ring seed, shard epoch), agreed by digest at
    join time over the ``manifest_hello`` wire op, gossiped for
    cross-host drain/death propagation, with cross-host drains handing
    warm HBM bytes over ``shard_transfer``.  See deploy/DEPLOY.md
    "Multi-host federation"."""

    enabled: bool = False
    # This process's host identity — must name the ``host`` of at
    # least one manifest member (those build in-process; the rest are
    # reached over their addresses).
    host: str = ""
    # The SHARD EPOCH: bump it with every membership/ring change.
    # Agreement is epoch-ordered — a peer carrying a higher epoch
    # wins; equal epochs must match digest-exactly (split-brain is a
    # refused join).
    shard_epoch: int = 1
    # Folded into every hash-ring point so two federations sharing
    # member names can never share a key space.  "" keeps the
    # single-host golden assignments bit-exact.
    ring_seed: str = ""
    # Virtual ring nodes per member (part of the agreed manifest).
    hash_replicas: int = 64
    # Seconds between membership gossip rounds (each process jitters
    # its ticks ±20%, seeded, so fleets never herd their bursts).
    gossip_interval_s: float = 5.0
    # Quorum membership (deploy/DEPLOY.md "Partitions & quorum"):
    # when on, a host that cannot exchange gossip with a strict
    # MAJORITY of manifest hosts within ``suspect_after_s`` FENCES —
    # it keeps serving reads it can prove from its own shards/byte
    # tier but refuses shard adoption, byte-tier write authority,
    # hot-key promotions, autoscaler transitions and epoch rolls
    # until the partition heals.  Off keeps the trusting PR 15
    # behavior bit-exact.
    quorum: bool = False
    # Silence window before a manifest host is counted unreachable
    # for the quorum verdict (monotonic clock; gossip and any inbound
    # federation op from the host both refresh it).
    suspect_after_s: float = 10.0
    # Per-host ack wait during the two-phase roll's propose leg.
    roll_ack_timeout_s: float = 5.0
    # The full fleet-wide member list, in ring order: dicts of
    # {name, host, address?} — address required for members other
    # hosts must reach (unix socket path or host:port TCP).
    members: Tuple[dict, ...] = ()


@dataclass
class ParallelConfig:
    """Mesh-sharded serving (≙ the reference's ``-cluster`` mode:
    Hazelcast-clustered worker verticles,
    ``ImageRegionMicroserviceVerticle.java:406-424``).  When enabled the
    service renders every coalesced group through a ``(data, chan)``
    ``jax.sharding.Mesh`` — tiles data-parallel, channels optionally
    tensor-parallel with a ``psum`` composite over ICI."""

    enabled: bool = False
    chan_parallel: int = 1
    # None = every visible device (multi-host: the whole slice via
    # jax.distributed).  A number requests that mesh width, falling back
    # to the virtual host mesh when the default platform is narrower.
    n_devices: Optional[int] = None
    # Explicit jax.distributed coordinates for multi-host deployments
    # outside auto-discovering environments (TPU pods, Slurm, K8s).
    # When coordinator-address is set, a failed cluster join is LOUD —
    # the service refuses to silently serve standalone.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


@dataclass
class FaultToleranceConfig:
    """The fault-tolerant serving chain's knobs (the reference leaned
    on Vert.x supervisor restarts and bounded event-loop backpressure;
    these are the TPU build's equivalents — see deploy/DEPLOY.md's
    failure-mode runbook)."""

    # Per-request time budget, opened at the HTTP frontend and carried
    # over the sidecar wire; queued work whose budget is spent is
    # cancelled cooperatively (504), never rendered for nobody.
    # 0 disables deadlines.
    request_deadline_ms: float = 0.0
    # Sidecar circuit breaker: this many CONSECUTIVE connection
    # failures trip it open; after breaker-reset-s one trial call is
    # admitted (half-open).  Open = calls fail fast with 503.
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 5.0
    # Op-aware sidecar retry: idempotent ops (render, probe, ping)
    # get up to this many total attempts with capped exponential
    # backoff + jitter; plane_put is NEVER auto-retried.
    retry_max_attempts: int = 3
    retry_base_backoff_ms: float = 25.0
    retry_max_backoff_ms: float = 1000.0
    # Admission control: at most this many admitted-but-unfinished
    # renders; beyond it (or when the estimated wait exceeds the
    # caller's remaining deadline) requests shed with 503 +
    # Retry-After instead of queueing toward a timeout.  0 disables.
    admission_max_queue: int = 512
    shed_retry_after_s: float = 1.0
    # Degraded mode: while the sidecar is unreachable (connection dead
    # or breaker open), frontends render on the in-process CPU
    # reference path (refimpl) so tiles stay servable at reduced rate.
    # Off by default: it requires the frontend host to mount data-dir.
    degraded_mode: bool = False
    # --role split: supervise the sidecar child — restart with capped
    # backoff on crash; the respawn gate (socket accept + prewarm via
    # /readyz) holds traffic until the device stack is back.
    supervise: bool = True
    supervisor_max_backoff_s: float = 30.0


@dataclass
class PressureConfig:
    """Resource-pressure governor + brownout ladder
    (``server.pressure``): a periodic sampler folds HBM occupancy,
    host RSS, disk-cache fill, queue depth and event-loop lag into a
    pressure level (ok/elevated/critical, per-signal hysteresis) and
    walks the configured degradation ladder so overload costs quality
    before it costs availability.  See deploy/DEPLOY.md "Overload &
    rolling restarts"."""

    enabled: bool = False
    interval_s: float = 1.0
    # Per-signal watermarks: enter elevated at ``high``, exit only
    # below ``low`` (the hysteresis band); a signal at
    # ``high * critical-factor`` reads critical.  high 0 disables the
    # signal.
    hbm_high: float = 0.90
    hbm_low: float = 0.75
    host_rss_high_mb: float = 0.0      # 0 disables (set to ~80% of
    host_rss_low_mb: float = 0.0       # the cgroup/host limit)
    disk_high: float = 0.95
    disk_low: float = 0.85
    queue_high: int = 48
    queue_low: int = 16
    loop_lag_high_ms: float = 250.0
    loop_lag_low_ms: float = 50.0
    critical_factor: float = 1.25
    # Ladder pacing: engage the next step after this many consecutive
    # elevated ticks (critical engages one step EVERY tick); release
    # the last step after this many consecutive ok ticks.
    step_hold_ticks: int = 2
    release_hold_ticks: int = 3
    # The ordered degradation ladder (server.pressure.KNOWN_STEPS).
    # Engages front-to-back, releases back-to-front; shed_bulk must
    # precede tighten_admission (interactive tiles are never shed
    # before bulk/projection work — validated at load).
    ladder: Tuple[str, ...] = (
        "pause_prefetch", "pause_snapshots", "evict_caches",
        "cap_lanes", "drop_quality", "shed_bulk",
        "tighten_admission")
    # Step parameters.
    quality_cap: int = 60              # drop_quality: JPEG ceiling
    evict_to_frac: float = 0.70        # evict_caches: low-water target
    lane_cap: int = 1                  # cap_lanes: concurrent groups
    admission_scale: float = 0.25      # tighten_admission multiplier
    # Continuous prefetch budget by level (PressureGovernor
    # .prefetch_budget): speculative staging scales down with pressure
    # BEFORE the binary pause_prefetch step engages (which floors the
    # budget at 0), and restores in exact reverse on release.
    prefetch_budget_elevated: float = 0.5
    prefetch_budget_critical: float = 0.25


@dataclass
class WatchdogConfig:
    """Stuck-lane / hung-wire watchdog (``server.watchdog``): detects
    a device lane stuck past ``stall-factor`` x its observed p99 (with
    the ``stall-min-s`` floor) or a wire connection wedged mid-frame
    past ``wire-hang-s``, and heals the smallest thing that works —
    requeue the group / drop the connection — escalating to the
    supervisor hook only on repeated fire."""

    enabled: bool = True
    interval_s: float = 2.0
    # A group render is stuck past max(stall-min-s, stall-factor x
    # observed p99 group duration).  The floor keeps cold compiles
    # (tens of seconds on some backends) from reading as stalls.
    stall_factor: float = 8.0
    stall_min_s: float = 30.0
    # A connection with in-flight requests and no received frame for
    # this long is wedged mid-frame; 0 disables the wire check.
    wire_hang_s: float = 60.0
    # The Nth fire on the same victim escalates (supervisor restart
    # hook) instead of re-healing.
    escalate_after: int = 2


@dataclass
class DrainConfig:
    """Zero-downtime rolling drains (``/admin/drain`` +
    ``parallel.fleet``): a draining member finishes in-flight work,
    stops accepting routes, snapshots its shard manifest and
    pre-stages it WARM onto its hash-ring successors."""

    # Pre-stage the drained member's shard manifest onto its ring
    # successors (off = the successors cold-miss instead).
    prestage: bool = True
    prestage_max_planes: int = 256
    # How long a drain waits for the member's in-flight work to
    # settle before reporting (the work itself is never cancelled).
    settle_timeout_s: float = 30.0
    # Surface drain state to load balancers: while ANY member is
    # draining, /readyz answers 503 so nginx/k8s pull the instance
    # from rotation during a rolling restart.  Off (default) keeps
    # the PR 9 annotation-only posture — the survivors serve every
    # shard, so readiness is honest either way; this flag is for LBs
    # that should route around the roll.
    fail_readyz: bool = False


@dataclass
class LoadModelConfig:
    """Open-loop load model (``services.loadmodel``): the simulated
    viewer population ``bench.py --smoke --capacity`` replays against
    a real in-process fleet to measure the latency-vs-offered-load
    curve and the capacity knee.  Deterministic by seed — same seed,
    same event stream.  See deploy/DEPLOY.md "Capacity &
    autoscaling"."""

    seed: int = 1234
    # Simulated viewer sessions per generated window (10^4..10^6 at
    # measurement scale; the smoke sweep uses a small population
    # time-compressed to each offered rate).
    viewers: int = 10000
    # Heavy-tailed per-viewer think time between requests (lognormal:
    # median + sigma; sigma ~1 gives the long-pause tail real viewers
    # have).
    think_time_median_ms: float = 350.0
    think_time_sigma: float = 1.0
    # Heavy-tailed session length in requests (lognormal).
    session_length_median: float = 24.0
    session_length_sigma: float = 1.2
    # Diurnal intensity: session starts bunch toward the peak of a
    # half-sine "day" (0 = flat arrivals, toward 1 = sharp peak).
    diurnal_amplitude: float = 0.6
    # Request-class mix (remainder is interactive tiles).  pyramid =
    # a build-job submission (bulk, rare); animation = a z/t strip
    # stream (PR 20 workload classes).
    bulk_fraction: float = 0.02
    mask_fraction: float = 0.0
    pyramid_fraction: float = 0.0
    animation_fraction: float = 0.0
    # Fraction of pan steps that change zoom level.
    zoom_fraction: float = 0.05
    # Trending-traffic skew: each session picks its image from a
    # zipf(s=skew) rank-frequency law over ``image_population`` ranks
    # (rank 0 hottest).  0 (or a population of 1) keeps every session
    # on image rank 0 — the pre-skew stream, bit-exact.
    skew: float = 0.0
    image_population: int = 1


@dataclass
class WorkloadsConfig:
    """Device-workloads plane (PR 20): the batched mask rasterizer,
    the overlay-composite endpoint, and the z/t animation streamer.
    See deploy/DEPLOY.md "Device workloads"."""

    # Route mask rasterization through the renderer's batched device
    # group path when the wired renderer has one (byte-identical to
    # the host rasterizer by contract; off = host path everywhere).
    device_masks: bool = True
    # Serve GET /webgateway/render_overlay (region + ROI mask
    # composite in one device pass).
    overlay_enabled: bool = True
    # Serve GET /webgateway/render_animation (z/t strip streamed as
    # ordered length-prefixed frames over chunked transport).
    animation_enabled: bool = True
    # Hard cap on frames per animation request (each frame is a full
    # region render; the cap bounds what one URL can pin).
    animation_max_frames: int = 64


@dataclass
class PyramidConfig:
    """Crash-safe background pyramid builds (``server.jobs``): POST
    /pyramid queues a device-downsampled NGFF build for an unpyramided
    source; ``ingest.py pyramid`` drives the same code path from the
    CLI.  See deploy/DEPLOY.md "Device workloads"."""

    # Serve POST /pyramid + GET /pyramid/{jobId} and run the
    # background job runner.
    enabled: bool = True
    # NGFF chunk edge (pixels) for written levels.
    chunk: int = 256
    # Stop halving when the next level's min dimension would fall
    # below this (the store/ngff writers' shared rule).
    min_level_size: int = 256
    # Chunk codec for written levels: zlib | gzip | none.
    compressor: str = "zlib"
    # Poll cadence while a build is parked behind the shed_bulk
    # pressure step (bulk class never starves interactive).
    defer_poll_s: float = 0.25


@dataclass
class AutoscalerConfig:
    """Elastic fleet autoscaler (``server.autoscaler``): closes the
    loop between measured pressure / predicted demand and fleet size,
    using the drain/undrain machinery (scale-down = warm shard
    handoff, scale-up = pre-stage-back).  Requires a fleet topology.
    See deploy/DEPLOY.md "Capacity & autoscaling"."""

    enabled: bool = False
    interval_s: float = 2.0
    # The member-count band the controller may move within.  floor is
    # a hard serving invariant (property-tested: concurrent ticks +
    # member deaths can never shrink past it); ceiling 0 = every
    # configured member.
    floor: int = 1
    ceiling: int = 0
    # Queue-depth watermarks, per active lane (fleet depth / (lanes x
    # routable members)): sustained >= high scales up, sustained <=
    # low scales down — the hysteresis band.
    queue_high_per_lane: float = 3.0
    queue_low_per_lane: float = 0.5
    # Consecutive over/under ticks before acting, and the minimum
    # spacing between transitions (the flapping bound the elasticity
    # drill asserts).
    hold_ticks: int = 2
    cooldown_s: float = 30.0
    # Measured per-lane service capacity in requests/s — read it off
    # the newest CAPACITY record (knee / total lanes).  > 0 arms the
    # predicted-demand signal: scale up when the session model's
    # predicted offered load exceeds the routable capacity, refuse to
    # scale down below it.  0 = queue/pressure signals only.
    lane_capacity_tps: float = 0.0
    # Predicted per-session steady request rate (requests/s) used to
    # turn viewport-tracked sessions into predicted demand.
    session_tps: float = 2.0
    # Diurnal demand prediction (services.loadmodel.DiurnalEstimator):
    # a single-tone harmonic fit over observed request arrivals scales
    # the predicted demand by where "now + horizon" sits in the fitted
    # day.  period-s 0 disables (flat prediction, the pre-PR-15
    # behavior); horizon-s is how far ahead the multiplier looks —
    # scale for the demand a drain/undrain completes INTO, not the
    # demand at tick time.
    diurnal_period_s: float = 86400.0
    diurnal_horizon_s: float = 300.0
    # Sidecar-unit process lifecycle (server.sidecar
    # SidecarUnitLifecycle): with a config path here and a
    # fleet.sockets topology, the FRONTEND spawns every member's
    # sidecar unit itself at startup, and the autoscaler actually
    # STOPS a parked member's process after its drain settles and
    # RESTARTS it (waiting for its socket) before undraining on
    # scale-up — elasticity that releases real memory/devices instead
    # of parking warm processes.  "" = pre-provisioned members
    # (operator-owned processes), the default.
    unit_config: str = ""


@dataclass
class SessionsConfig:
    """Session-aware serving (services.viewport + the admission token
    buckets): model the CLIENT, not just the request.  The session
    identity is the one the stack already resolves —
    ``ctx.omero_session_key`` from the session store middleware, the
    same key the fleet single-flight folds (PR 8) — never a second
    resolution path.  See deploy/DEPLOY.md "Sessions & QoS"."""

    enabled: bool = False
    # Per-session admission token bucket: refill rate (requests/s of
    # steady budget) and burst (the pan-flurry allowance).  An
    # interactive tile draws 1 token; bulk/projection work draws
    # ``qos.bulk-cost``.  Over-budget requests shed 503 + Retry-After
    # with the "fairness" reason BEFORE global admission tightens.
    bucket_refill_per_s: float = 20.0
    bucket_burst: float = 40.0
    # Bounded LRU over live sessions (buckets AND viewport states);
    # an evicted session restarts with a full burst.
    max_tracked: int = 4096
    # Viewport predictor depth: how many pan steps ahead the
    # trajectory extrapolates (services.viewport -> prefetch).
    prefetch_lookahead: int = 2


@dataclass
class QosConfig:
    """Tiered QoS: interactive tile vs bulk export/projection
    (classified by ``pressure.is_bulk`` — the ONE classification the
    brownout ladder and the fleet pin already share).  With it on, the
    fleet router dequeues through a weighted two-class queue so
    interactive work jumps bulk backlogs, and bulk requests draw
    ``bulk-cost`` session tokens each."""

    enabled: bool = False
    # Weighted dequeue: up to this many interactive units pop for
    # every bulk unit while both classes wait (bulk never starves —
    # after the quota one bulk unit always pops).
    interactive_weight: int = 4
    # Session-bucket token cost of one bulk/projection request.
    bulk_cost: float = 4.0


@dataclass
class PersistenceConfig:
    """Warm-state persistence tier (services.diskcache +
    services.warmstate + server.execcache): what survives a restart.
    Off by default — enabling it turns every deploy/respawn/crash from
    minutes of wire fetches and XLA compiles (BENCH_r05: 0.73 cold vs
    26 warm tiles/s) into a disk read."""

    enabled: bool = False
    # Root directory; the tier lays out bytecache/, executables/ and
    # manifest.json under it.  Must be service-user-owned (executables
    # are pickles, same trust model as jax's compilation cache).
    dir: str = "./warm-state"
    # Disk byte-cache budget (LRU by mtime; evicts to 90% on breach).
    disk_cache_max_bytes: int = 1024 * 1024 * 1024
    # Serialize compiled render executables
    # (jax.experimental.serialize_executable); restarts deserialize
    # instead of re-tracing + re-compiling.  The trace cache
    # (renderer.compilation-cache-dir) remains the fallback when the
    # backend cannot serialize.
    executables: bool = True
    # Manifest cadence; SIGTERM always snapshots through the shutdown
    # chain regardless.  0 disables the timer.
    snapshot_interval_s: float = 60.0
    # Hot-set bounds recorded per snapshot.
    snapshot_top_k: int = 512
    max_plane_entries: int = 256
    # Boot rehydrate: replay the manifest in the background.
    rehydrate: bool = True
    rehydrate_concurrency: int = 2


@dataclass
class TelemetryConfig:
    """Tracing / health-probe knobs (utils.telemetry; ≙ the reference's
    optional metrics beans, ``beanRefContext.xml:36-46`` — Graphite
    there, Prometheus scrape + trace waterfalls here)."""

    # Requests slower than this dump their full span waterfall as JSON
    # into slow_request_dir (scripts/trace_report.py renders them).
    # 0 disables the tracer.
    slow_request_ms: float = 0.0
    slow_request_dir: str = "./slow-traces"
    # One-line JSON access log per request (route, status, bytes, cache
    # tier, queue-wait/render/encode ms, trace id, cost ledger) on the
    # "omero_ms_image_region_tpu.access" logger.
    access_log: bool = True
    # /readyz reports degraded (503) when the batcher backlog exceeds
    # this many queued requests.
    ready_max_queue_depth: int = 64
    # Black-box flight recorder (utils.telemetry.FLIGHT): bounded ring
    # of structured events (admission sheds, batch formation, breaker
    # transitions, deadline cancels, cache evictions, compiles) that
    # snapshots to flight_recorder_dir on SIGTERM, on SLO breach, or
    # via /debug/flightrecorder?dump=1.
    flight_recorder_events: int = 512
    flight_recorder_dir: str = "./flight-recorder"
    # /debug/profile?ms=N artifacts (jax.profiler traces) land here;
    # requests are clamped to profile_max_ms.
    profile_dir: str = "./profiles"
    profile_max_ms: float = 10000.0
    # Echo each successful response's provenance record (serving
    # member, byte-source tier, steal/failover/drain flags, QoS class,
    # engaged ladder prefix, tokens charged) as an
    # ``X-Image-Region-Provenance`` debug header.  Off by default
    # (operator debugging surface); NEVER emitted on errors.
    provenance_header: bool = False


@dataclass
class SloConfig:
    """Service-level objectives evaluated as multi-window burn rates
    (utils.telemetry.SloEngine); gauges on /metrics, an annotation on
    /readyz, and a flight-recorder dump on breach.  Both objectives
    default off."""

    # Availability objective: target fraction of requests answering
    # below 500 (sheds and deadline 504s spend the budget).  0 = off.
    availability_target: float = 0.0
    # Latency objective: latency_target fraction of successful
    # requests must finish under latency_ms (p99 tile latency ex-RTT
    # when latency_ms is set to the interactive bound minus the
    # deployment's measured RTT floor).  latency_ms 0 = off.
    latency_ms: float = 0.0
    latency_target: float = 0.99
    # Multi-window burn evaluation: breach = burn rate over threshold
    # in BOTH windows (fast catches the cliff, slow filters blips).
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    breach_burn_rate: float = 14.4


@dataclass
class DecisionsConfig:
    """Control-plane decision ledger (utils.decisions.LEDGER): every
    autoscaler verdict, epoch roll, manifest agreement, gossip
    convergence transition and drain lifecycle move lands in one
    bounded ring surfaced on /debug/decisions (federated frontends
    merge every host's into one timeline)."""

    # In-memory ring size (records); clamped to >= 16.
    ring_size: int = 256
    # JSONL spool directory (decisions.jsonl, one-file rotation);
    # "" disables spooling — the ring alone carries the story.
    spool_dir: str = ""
    # Autoscaler verdicts get their MEASURED outcome (queue delta,
    # active-member delta) attached this many ticks later.
    outcome_horizon_ticks: int = 3


@dataclass
class SentinelConfig:
    """Live perf-regression sentinel (``server.sentinel``): always-on
    per-route/per-shape quantile sketches, a tick-driven drift engine
    judging live p50/p99 and served-tiles/s against BOTH a
    self-learned rolling baseline (persisted through the warm-state
    manifest) and the committed bench watermarks, and an automatic
    forensic incident bundle on confirmed drift.  Annotation-only on
    /readyz; never fails a request."""

    enabled: bool = True
    # Drift evaluation cadence; each tick closes one quantile window.
    tick_interval_s: float = 5.0
    # Multi-window confirmation: a breach must hold this many
    # consecutive ticks before the drift verdict fires (one slow
    # request — or one slow window — never pages anyone).
    confirm_ticks: int = 3
    # Clean consecutive ticks that clear a confirmed verdict.
    recover_ticks: int = 3
    # A window with fewer observations than this gives no verdict
    # either way and teaches the baseline nothing.
    min_samples: int = 32
    # Baseline windows to learn before drift can be judged at all.
    warmup_ticks: int = 3
    # Live p99 above baseline-p99 x ratio = one breached window.
    drift_ratio: float = 1.5
    # EWMA step for the rolling baseline (non-breaching windows only).
    baseline_alpha: float = 0.2
    # Served-tiles/s under watermark x ratio (with real traffic) is
    # throughput drift even when the learned baseline sagged with it.
    throughput_floor_ratio: float = 0.5
    # Incident bundles: directory ("" disables capture — verdicts and
    # events still fire), retention cap, device-profile duration.
    bundle_dir: str = ""
    max_bundles: int = 8
    profile_ms: int = 200
    # Where the committed BENCH_r*/OFFLOAD_r* records (and
    # scripts/bench_gate.py) live; "" skips the watermark floors.
    records_dir: str = "."


@dataclass
class HttpConfig:
    """Request parse limits (≙ ``config.yaml:5-12`` — the Vert.x
    ``HttpServerOptions`` line/header limits, mapped onto aiohttp's
    ``max_line_size`` / ``max_field_size`` / ``max_headers`` knobs)."""

    max_initial_line_length: int = 4096    # max-initial-line-length
    max_header_size: int = 8192            # max-header-size (per field)
    max_headers: int = 32768               # header count bound


@dataclass
class HttpCacheConfig:
    """Edge-cache-grade conditional HTTP (``server.httpcache``;
    deploy/DEPLOY.md "Edge caching"): content-addressed ETags on
    region/tile/mask responses, ``If-None-Match`` -> 304 with zero
    render/admission/token work, honest ``Cache-Control``/``Vary``,
    and the fleet's peer byte-fetch short-circuit."""

    enabled: bool = True
    # Deployment cache epoch: folded into (and visible in) every ETag.
    # Bumping it invalidates EVERY edge-cached entry at once — the
    # knob to turn when source data or the render pipeline changes
    # under live URLs.  Token characters only ([A-Za-z0-9._-]).
    # The literal "auto" derives the epoch from the data tree's
    # ingest/source mtimes at startup (httpcache.derive_epoch) —
    # re-ingesting any image then bumps the deployment epoch
    # mechanically; an explicit value stays the operator override.
    epoch: str = "0"
    # Cache-Control max-age for 200s.  0 (default) emits ``no-cache``:
    # edges store but revalidate every serve — safe because the 304
    # answer is free.  >0 lets edges serve without revalidation for
    # that window (an epoch bump then takes up to max-age-s to
    # propagate).
    max_age_s: int = 0
    # Emit ``Vary: <session cookie header>`` (+ ``private``) on
    # ACL-gated images so shared caches key entries per session;
    # public images stay ``public`` with no Vary.  Off = everything
    # private+Vary (the conservative posture for deployments that
    # cannot probe ACL at the edge process).
    vary_acl: bool = True
    # Fleet-global byte tier: on a byte miss, digest-probe the plane's
    # ring authority and fetch the bytes over the idempotent
    # byte_probe/byte_fetch wire ops before any re-render.
    peer_fetch: bool = True
    # Bound on one peer probe+fetch round-trip; past it the render
    # path proceeds (the peer tier may only ever REMOVE work).
    peer_timeout_ms: float = 500.0


@dataclass
class LoggingConfig:
    """≙ ``logback.xml.example:1-26``: console always; optional
    time-rolling file appender; per-subsystem level."""

    level: str = "INFO"
    file: Optional[str] = None             # enables the rolling appender
    when: str = "midnight"                 # TimedRotatingFileHandler unit
    backup_count: int = 7


@dataclass
class AppConfig:
    port: int = 8080
    # None = 2 x cores, the reference's worker verticle default
    # (``config.yaml:3-4``, ``ImageRegionMicroserviceVerticle.java:83-85``);
    # sizes the asyncio default executor every render offload runs on.
    worker_pool_size: Optional[int] = None
    data_dir: str = "./data"
    # OMERO binary-repository mount (``omero.server:
    # omero.data.dir``, reference ``config.yaml:19-20``): when set and
    # the metadata backend is postgres, images resolve from the DB's
    # fileset/originalfile rows under <root>/ManagedRepository (legacy
    # images under <root>/Pixels) with zero re-arrangement.
    omero_data_dir: Optional[str] = None
    max_tile_length: int = 2048            # omero.pixeldata.max_tile_length
    cache_control_header: str = ""         # cache-control-header
    session_cookie_name: str = "sessionid"  # omero.web.session_cookie_name
    session_store_type: Optional[str] = None   # redis | postgres | static
    session_store_uri: Optional[str] = None
    # Reject requests whose cookie does not resolve to an OMERO session
    # (the reference's session handler is mandatory and fails them:
    # ImageRegionMicroserviceVerticle.java:199-212).  None = default on
    # for redis/postgres stores, off for static/no store (the standalone
    # ACL-only posture stays available as an explicit opt-out).
    session_store_required: Optional[bool] = None
    lut_root: Optional[str] = None         # omero.script_repo_root analogue
    # Metadata/ACL backend: "local" (filesystem acl.json + meta.json) or
    # "postgres" (OMERO-schema DB, ≙ the backbone services the reference
    # reaches over the bus — ImageRegionRequestHandler.java:316-427).
    metadata_backend: str = "local"
    metadata_dsn: Optional[str] = None
    # In-flight render dedup (server.handler.SingleFlight): concurrent
    # identical requests coalesce onto one pipeline run instead of each
    # paying the full read/stage/render/encode.  Off only for A/B
    # measurement — coalescing is semantics-free (ACL still runs per
    # caller; followers get the exact bytes the byte cache would).
    single_flight: bool = True
    caches: CacheConfig = field(default_factory=CacheConfig)
    batcher: BatcherConfig = field(default_factory=BatcherConfig)
    raw_cache: RawCacheConfig = field(default_factory=RawCacheConfig)
    renderer: RendererConfig = field(default_factory=RendererConfig)
    http: HttpConfig = field(default_factory=HttpConfig)
    http_cache: HttpCacheConfig = field(default_factory=HttpCacheConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    hotkey: HotkeyConfig = field(default_factory=HotkeyConfig)
    federation: FederationConfig = field(
        default_factory=FederationConfig)
    sidecar: SidecarConfig = field(default_factory=SidecarConfig)
    wire: WireConfig = field(default_factory=WireConfig)
    persistence: PersistenceConfig = field(
        default_factory=PersistenceConfig)
    sessions: SessionsConfig = field(default_factory=SessionsConfig)
    loadmodel: LoadModelConfig = field(
        default_factory=LoadModelConfig)
    workloads: WorkloadsConfig = field(
        default_factory=WorkloadsConfig)
    pyramid: PyramidConfig = field(
        default_factory=PyramidConfig)
    autoscaler: AutoscalerConfig = field(
        default_factory=AutoscalerConfig)
    qos: QosConfig = field(default_factory=QosConfig)
    pressure: PressureConfig = field(default_factory=PressureConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    drain: DrainConfig = field(default_factory=DrainConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    decisions: DecisionsConfig = field(
        default_factory=DecisionsConfig)
    sentinel: SentinelConfig = field(
        default_factory=SentinelConfig)
    fault_tolerance: FaultToleranceConfig = field(
        default_factory=FaultToleranceConfig)
    # Seeded chaos layer (utils.faultinject); seed absent = disabled.
    fault_injection: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig)

    @classmethod
    def from_yaml(cls, path: str) -> "AppConfig":
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "AppConfig":
        cfg = cls()
        cfg.port = int(raw.get("port", cfg.port))
        if raw.get("worker_pool_size") is not None:
            cfg.worker_pool_size = int(raw["worker_pool_size"])
            if cfg.worker_pool_size <= 0:
                raise ValueError("worker_pool_size must be positive")
        http_defaults = HttpConfig()
        cfg.http = HttpConfig(
            max_initial_line_length=int(raw.get(
                "max-initial-line-length",
                http_defaults.max_initial_line_length)),
            max_header_size=int(raw.get(
                "max-header-size", http_defaults.max_header_size)),
            max_headers=int(raw.get(
                "max-headers", http_defaults.max_headers)),
        )
        logging_block = raw.get("logging", {}) or {}
        log_defaults = LoggingConfig()
        cfg.logging = LoggingConfig(
            level=str(logging_block.get("level", log_defaults.level)),
            file=logging_block.get("file"),
            when=str(logging_block.get("when", log_defaults.when)),
            backup_count=int(logging_block.get(
                "backup-count", log_defaults.backup_count)),
        )
        cfg.data_dir = raw.get("data-dir", cfg.data_dir)
        server_block = raw.get("omero.server", {}) or {}
        cfg.max_tile_length = int(server_block.get(
            "omero.pixeldata.max_tile_length", cfg.max_tile_length))
        cfg.omero_data_dir = server_block.get("omero.data.dir",
                                              cfg.omero_data_dir)
        cfg.lut_root = server_block.get("omero.script_repo_root",
                                        cfg.lut_root)
        cfg.cache_control_header = raw.get("cache-control-header",
                                           cfg.cache_control_header)
        hc = raw.get("http-cache", {}) or {}
        hc_defaults = HttpCacheConfig()
        cfg.http_cache = HttpCacheConfig(
            enabled=bool(hc.get("enabled", hc_defaults.enabled)),
            epoch=str(hc.get("epoch", hc_defaults.epoch)),
            max_age_s=int(hc.get("max-age-s", hc_defaults.max_age_s)),
            vary_acl=bool(hc.get("vary-acl", hc_defaults.vary_acl)),
            peer_fetch=bool(hc.get("peer-fetch",
                                   hc_defaults.peer_fetch)),
            peer_timeout_ms=float(hc.get(
                "peer-timeout-ms", hc_defaults.peer_timeout_ms)),
        )
        from .httpcache import EPOCH_RE
        if not EPOCH_RE.match(cfg.http_cache.epoch):
            # The epoch rides inside the quoted ETag header: a stray
            # quote/comma/space would corrupt every response header.
            raise ValueError(
                "http-cache.epoch must match [A-Za-z0-9._-]+, got "
                f"{cfg.http_cache.epoch!r}")
        if cfg.http_cache.max_age_s < 0:
            raise ValueError("http-cache.max-age-s must be >= 0 "
                             "(0 = no-cache, revalidate every serve)")
        if cfg.http_cache.peer_timeout_ms <= 0:
            raise ValueError("http-cache.peer-timeout-ms must be > 0")
        web = raw.get("omero.web", {}) or {}
        cfg.session_cookie_name = web.get("session_cookie_name",
                                          cfg.session_cookie_name)
        store = raw.get("session-store", {}) or {}
        cfg.session_store_type = store.get("type")
        cfg.session_store_uri = store.get("uri")
        if store.get("required") is not None:
            cfg.session_store_required = bool(store["required"])
        meta = raw.get("metadata-service", {}) or {}
        cfg.metadata_backend = str(meta.get("type", cfg.metadata_backend))
        cfg.metadata_dsn = meta.get("dsn")
        if cfg.metadata_backend not in ("local", "postgres"):
            raise ValueError(
                "metadata-service.type must be 'local' or 'postgres', "
                f"got {cfg.metadata_backend!r}")
        if cfg.metadata_backend == "postgres" and not cfg.metadata_dsn:
            raise ValueError("metadata-service.type 'postgres' requires "
                             "a dsn")

        redis_cache = raw.get("redis-cache", {}) or {}
        cfg.caches = CacheConfig(
            redis_uri=redis_cache.get("uri"),
            image_region=bool((raw.get("image-region-cache") or {})
                              .get("enabled", False)),
            pixels_metadata=bool((raw.get("pixels-metadata-cache") or {})
                                 .get("enabled", False)),
            shape_mask=bool((raw.get("shape-mask-cache") or {})
                            .get("enabled", False)),
        )
        batcher = raw.get("batcher", {}) or {}
        defaults = BatcherConfig()
        cfg.batcher = BatcherConfig(
            enabled=bool(batcher.get("enabled", defaults.enabled)),
            max_batch=int(batcher.get("max-batch", defaults.max_batch)),
            max_batch_limit=(int(batcher["max-batch-limit"])
                             if batcher.get("max-batch-limit")
                             is not None else None),
            linger_ms=float(batcher.get("linger-ms", defaults.linger_ms)),
            pipeline_depth=int(batcher.get("pipeline-depth",
                                           defaults.pipeline_depth)),
            target_inflight=int(batcher.get("target-inflight",
                                            defaults.target_inflight)),
            device_lanes=int(batcher.get("device-lanes",
                                         defaults.device_lanes)),
        )
        if cfg.batcher.pipeline_depth < 1:
            raise ValueError("batcher.pipeline-depth must be >= 1")
        if cfg.batcher.target_inflight < 1:
            raise ValueError("batcher.target-inflight must be >= 1")
        if cfg.batcher.device_lanes < 1:
            raise ValueError("batcher.device-lanes must be >= 1")
        # An EMPTY "single-flight:" section (all children commented
        # out, the standard pattern in the example config) parses as
        # YAML null and must keep the default — only an explicit value
        # changes it.
        sf = raw.get("single-flight")
        if isinstance(sf, dict):
            cfg.single_flight = bool(sf.get("enabled",
                                            cfg.single_flight))
        elif sf is not None:
            cfg.single_flight = bool(sf)
        rc = raw.get("raw-cache", {}) or {}
        rc_defaults = RawCacheConfig()
        cfg.raw_cache = RawCacheConfig(
            enabled=bool(rc.get("enabled", rc_defaults.enabled)),
            max_bytes=int(rc.get("max-bytes", rc_defaults.max_bytes)),
            prefetch=bool(rc.get("prefetch", rc_defaults.prefetch)),
            digest_dedup=bool(rc.get("digest-dedup",
                                     rc_defaults.digest_dedup)),
        )
        sc = raw.get("sidecar", {}) or {}
        sc_defaults = SidecarConfig()
        cfg.sidecar = SidecarConfig(
            socket=sc.get("socket", sc_defaults.socket),
            role=str(sc.get("role", sc_defaults.role)),
        )
        if cfg.sidecar.role not in ("combined", "frontend", "sidecar",
                                    "split"):
            raise ValueError(f"invalid sidecar.role {cfg.sidecar.role!r}")
        _fleet_raw = raw.get("fleet") or {}
        if cfg.sidecar.role != "combined" and not cfg.sidecar.socket \
                and not (cfg.sidecar.role == "frontend"
                         and _fleet_raw.get("enabled")
                         and _fleet_raw.get("sockets")):
            # A frontend may address a FLEET of sidecars instead of
            # one socket (fleet.enabled + fleet.sockets, parsed
            # below) — enabled must be set too, because create_app
            # only takes the fleet topology when it is.
            raise ValueError(f"sidecar.role {cfg.sidecar.role!r} "
                             f"requires sidecar.socket (or an "
                             f"enabled fleet.sockets list)")
        wi = raw.get("wire", {}) or {}
        wi_defaults = WireConfig()
        cfg.wire = WireConfig(
            coalesce_max_frames=int(wi.get(
                "coalesce-max-frames", wi_defaults.coalesce_max_frames)),
            coalesce_max_bytes=int(wi.get(
                "coalesce-max-bytes", wi_defaults.coalesce_max_bytes)),
            ring_bytes=int(wi.get("ring-bytes", wi_defaults.ring_bytes)),
            ring_min_body_bytes=int(wi.get(
                "ring-min-body-bytes", wi_defaults.ring_min_body_bytes)),
            streaming=bool(wi.get("streaming", wi_defaults.streaming)),
            chunk_max_bytes=int(wi.get(
                "chunk-max-bytes", wi_defaults.chunk_max_bytes)),
        )
        if cfg.wire.coalesce_max_frames < 1:
            raise ValueError("wire.coalesce-max-frames must be >= 1")
        if cfg.wire.coalesce_max_bytes < 4096:
            raise ValueError("wire.coalesce-max-bytes must be >= 4096")
        if cfg.wire.ring_bytes != 0 and cfg.wire.ring_bytes < 1024 * 1024:
            raise ValueError("wire.ring-bytes must be 0 (disabled) or "
                             ">= 1 MiB")
        if cfg.wire.ring_min_body_bytes < 1:
            raise ValueError("wire.ring-min-body-bytes must be >= 1")
        if cfg.wire.chunk_max_bytes < 4096:
            raise ValueError("wire.chunk-max-bytes must be >= 4096")
        fl = raw.get("fleet", {}) or {}
        fl_defaults = FleetConfig()
        cfg.fleet = FleetConfig(
            enabled=bool(fl.get("enabled", fl_defaults.enabled)),
            members=int(fl.get("members", fl_defaults.members)),
            sockets=tuple(str(s) for s in fl.get("sockets", ())
                          or ()),
            lane_width=int(fl.get("lane-width",
                                  fl_defaults.lane_width)),
            steal_min_backlog=int(fl.get(
                "steal-min-backlog", fl_defaults.steal_min_backlog)),
            hash_replicas=int(fl.get("hash-replicas",
                                     fl_defaults.hash_replicas)),
            failover=bool(fl.get("failover", fl_defaults.failover)),
            down_cooldown_s=float(fl.get(
                "down-cooldown-s", fl_defaults.down_cooldown_s)),
        )
        if cfg.fleet.enabled:
            if not cfg.fleet.sockets and cfg.fleet.members < 2:
                raise ValueError("fleet.enabled requires members >= 2 "
                                 "or a fleet.sockets list")
        if cfg.fleet.members < 1:
            raise ValueError("fleet.members must be >= 1")
        if cfg.fleet.lane_width < 1:
            raise ValueError("fleet.lane-width must be >= 1")
        if cfg.fleet.steal_min_backlog < 0:
            raise ValueError("fleet.steal-min-backlog must be >= 0 "
                             "(0 disables stealing)")
        if cfg.fleet.hash_replicas < 1:
            raise ValueError("fleet.hash-replicas must be >= 1")
        if cfg.fleet.down_cooldown_s < 0:
            raise ValueError("fleet.down-cooldown-s must be >= 0")
        hk = raw.get("hotkey", {}) or {}
        hk_defaults = HotkeyConfig()
        cfg.hotkey = HotkeyConfig(
            enabled=bool(hk.get("enabled", hk_defaults.enabled)),
            threshold=float(hk.get("threshold",
                                   hk_defaults.threshold)),
            decay_s=float(hk.get("decay-s", hk_defaults.decay_s)),
            max_replicas=int(hk.get("max-replicas",
                                    hk_defaults.max_replicas)),
            top_k=int(hk.get("top-k", hk_defaults.top_k)),
            demote_fraction=float(hk.get(
                "demote-fraction", hk_defaults.demote_fraction)),
            scale_factor=float(hk.get("scale-factor",
                                      hk_defaults.scale_factor)),
        )
        if cfg.hotkey.threshold <= 0:
            raise ValueError("hotkey.threshold must be > 0")
        if cfg.hotkey.decay_s <= 0:
            raise ValueError("hotkey.decay-s must be > 0")
        if cfg.hotkey.max_replicas < 2:
            raise ValueError("hotkey.max-replicas must be >= 2 "
                             "(R=1 is the unreplicated ring)")
        if cfg.hotkey.top_k < 1:
            raise ValueError("hotkey.top-k must be >= 1")
        if not 0.0 < cfg.hotkey.demote_fraction < 1.0:
            raise ValueError("hotkey.demote-fraction must be in "
                             "(0, 1) — the promotion/demotion "
                             "hysteresis band")
        if cfg.hotkey.scale_factor < 0:
            raise ValueError("hotkey.scale-factor must be >= 0 "
                             "(0 disables the autoscaler signal)")
        fe = raw.get("federation", {}) or {}
        fe_defaults = FederationConfig()
        members_raw = fe.get("members", ()) or ()
        if not isinstance(members_raw, (list, tuple)):
            raise ValueError("federation.members must be a list of "
                             "{name, host, address?} entries")
        fed_members = []
        for i, m in enumerate(members_raw):
            if not isinstance(m, dict) or not m.get("name") \
                    or not m.get("host"):
                raise ValueError(
                    f"federation.members[{i}] must be a mapping with "
                    f"at least name and host")
            fed_members.append({
                "name": str(m["name"]), "host": str(m["host"]),
                "address": str(m.get("address") or "")})
        cfg.federation = FederationConfig(
            enabled=bool(fe.get("enabled", fe_defaults.enabled)),
            host=str(fe.get("host", fe_defaults.host) or ""),
            shard_epoch=int(fe.get("shard-epoch",
                                   fe_defaults.shard_epoch)),
            ring_seed=str(fe.get("ring-seed",
                                 fe_defaults.ring_seed) or ""),
            hash_replicas=int(fe.get("hash-replicas",
                                     fe_defaults.hash_replicas)),
            gossip_interval_s=float(fe.get(
                "gossip-interval-s", fe_defaults.gossip_interval_s)),
            quorum=bool(fe.get("quorum", fe_defaults.quorum)),
            suspect_after_s=float(fe.get(
                "suspect-after-s", fe_defaults.suspect_after_s)),
            roll_ack_timeout_s=float(fe.get(
                "roll-ack-timeout-s",
                fe_defaults.roll_ack_timeout_s)),
            members=tuple(fed_members),
        )
        if cfg.federation.shard_epoch < 1:
            raise ValueError("federation.shard-epoch must be >= 1 "
                             "(bump it with every membership change)")
        if cfg.federation.hash_replicas < 1:
            raise ValueError("federation.hash-replicas must be >= 1")
        if cfg.federation.gossip_interval_s <= 0:
            raise ValueError("federation.gossip-interval-s must be "
                             "> 0")
        if cfg.federation.suspect_after_s <= 0:
            raise ValueError("federation.suspect-after-s must be > 0")
        if cfg.federation.roll_ack_timeout_s <= 0:
            raise ValueError("federation.roll-ack-timeout-s must be "
                             "> 0")
        if cfg.federation.quorum and not cfg.federation.enabled:
            raise ValueError("federation.quorum requires "
                             "federation.enabled (quorum is a verdict "
                             "over manifest hosts)")
        if cfg.federation.enabled:
            if len(cfg.federation.members) < 2:
                raise ValueError("federation.enabled requires >= 2 "
                                 "members (one host needs no "
                                 "federation — use fleet.members)")
            names = [m["name"] for m in cfg.federation.members]
            if len(set(names)) != len(names):
                raise ValueError("federation.members names must be "
                                 "unique fleet-wide")
            if not cfg.federation.host:
                # Default this process's identity from the cluster
                # layer (``procN`` when jax.distributed is joined,
                # else the OS hostname) — multi-host manifests stop
                # needing an explicit host string per process.  It
                # must still name a manifest member; the check below
                # catches a hostname the manifest never heard of.
                from ..parallel.cluster import host_identity
                cfg.federation.host = host_identity()
            hosts = {m["host"] for m in cfg.federation.members}
            if cfg.federation.host not in hosts:
                raise ValueError(
                    f"federation.host {cfg.federation.host!r} owns no "
                    f"manifest member (hosts: {sorted(hosts)}); set "
                    f"federation.host explicitly, or name manifest "
                    f"hosts by cluster.host_identity() — the default "
                    f"when the key is omitted")
            # NOTE: remote members' addresses are validated where the
            # topology is actually built (build_federated_members —
            # only a process that ROUTES needs to reach them; a
            # passive sidecar member answering manifest_hello does
            # not), so a member-process config may legally omit
            # addresses it never dials.
            if cfg.fleet.sockets:
                raise ValueError(
                    "federation.enabled and fleet.sockets are "
                    "mutually exclusive — the manifest IS the "
                    "membership; list remote members with addresses "
                    "in federation.members instead")
        par = raw.get("parallel", {}) or {}
        par_defaults = ParallelConfig()
        cfg.parallel = ParallelConfig(
            enabled=bool(par.get("enabled", par_defaults.enabled)),
            chan_parallel=int(par.get("chan-parallel",
                                      par_defaults.chan_parallel)),
            n_devices=(int(par["n-devices"])
                       if par.get("n-devices") is not None else None),
            coordinator_address=par.get("coordinator-address"),
            num_processes=(int(par["num-processes"])
                           if par.get("num-processes") is not None
                           else None),
            process_id=(int(par["process-id"])
                        if par.get("process-id") is not None else None),
        )
        if cfg.parallel.chan_parallel < 1:
            raise ValueError("parallel.chan-parallel must be >= 1")
        if (cfg.parallel.coordinator_address is not None
                and cfg.parallel.num_processes is None):
            raise ValueError("parallel.coordinator-address requires "
                             "num-processes and process-id")
        per = raw.get("persistence", {}) or {}
        per_defaults = PersistenceConfig()
        cfg.persistence = PersistenceConfig(
            enabled=bool(per.get("enabled", per_defaults.enabled)),
            dir=str(per.get("dir", per_defaults.dir)),
            disk_cache_max_bytes=int(per.get(
                "disk-cache-max-bytes",
                per_defaults.disk_cache_max_bytes)),
            executables=bool(per.get("executables",
                                     per_defaults.executables)),
            snapshot_interval_s=float(per.get(
                "snapshot-interval-s",
                per_defaults.snapshot_interval_s)),
            snapshot_top_k=int(per.get("snapshot-top-k",
                                       per_defaults.snapshot_top_k)),
            max_plane_entries=int(per.get(
                "max-plane-entries", per_defaults.max_plane_entries)),
            rehydrate=bool(per.get("rehydrate",
                                   per_defaults.rehydrate)),
            rehydrate_concurrency=int(per.get(
                "rehydrate-concurrency",
                per_defaults.rehydrate_concurrency)),
        )
        if cfg.persistence.disk_cache_max_bytes < 1024 * 1024:
            raise ValueError("persistence.disk-cache-max-bytes must "
                             "be >= 1 MiB")
        if cfg.persistence.snapshot_interval_s < 0:
            raise ValueError("persistence.snapshot-interval-s must be "
                             ">= 0 (0 disables the timer)")
        if cfg.persistence.rehydrate_concurrency < 1:
            raise ValueError("persistence.rehydrate-concurrency must "
                             "be >= 1")
        if cfg.persistence.snapshot_top_k < 1:
            raise ValueError("persistence.snapshot-top-k must be >= 1")
        se = raw.get("sessions", {}) or {}
        se_defaults = SessionsConfig()
        cfg.sessions = SessionsConfig(
            enabled=bool(se.get("enabled", se_defaults.enabled)),
            bucket_refill_per_s=float(se.get(
                "bucket-refill-per-s",
                se_defaults.bucket_refill_per_s)),
            bucket_burst=float(se.get("bucket-burst",
                                      se_defaults.bucket_burst)),
            max_tracked=int(se.get("max-tracked",
                                   se_defaults.max_tracked)),
            prefetch_lookahead=int(se.get(
                "prefetch-lookahead", se_defaults.prefetch_lookahead)),
        )
        if cfg.sessions.bucket_refill_per_s <= 0:
            raise ValueError("sessions.bucket-refill-per-s must be "
                             "> 0")
        if cfg.sessions.bucket_burst < 1:
            raise ValueError("sessions.bucket-burst must be >= 1")
        if cfg.sessions.max_tracked < 1:
            raise ValueError("sessions.max-tracked must be >= 1")
        if cfg.sessions.prefetch_lookahead < 1:
            raise ValueError("sessions.prefetch-lookahead must be "
                             ">= 1")
        lm = raw.get("loadmodel", {}) or {}
        lm_defaults = LoadModelConfig()
        cfg.loadmodel = LoadModelConfig(
            seed=int(lm.get("seed", lm_defaults.seed)),
            viewers=int(lm.get("viewers", lm_defaults.viewers)),
            think_time_median_ms=float(lm.get(
                "think-time-median-ms",
                lm_defaults.think_time_median_ms)),
            think_time_sigma=float(lm.get(
                "think-time-sigma", lm_defaults.think_time_sigma)),
            session_length_median=float(lm.get(
                "session-length-median",
                lm_defaults.session_length_median)),
            session_length_sigma=float(lm.get(
                "session-length-sigma",
                lm_defaults.session_length_sigma)),
            diurnal_amplitude=float(lm.get(
                "diurnal-amplitude", lm_defaults.diurnal_amplitude)),
            bulk_fraction=float(lm.get(
                "bulk-fraction", lm_defaults.bulk_fraction)),
            mask_fraction=float(lm.get(
                "mask-fraction", lm_defaults.mask_fraction)),
            pyramid_fraction=float(lm.get(
                "pyramid-fraction", lm_defaults.pyramid_fraction)),
            animation_fraction=float(lm.get(
                "animation-fraction", lm_defaults.animation_fraction)),
            zoom_fraction=float(lm.get(
                "zoom-fraction", lm_defaults.zoom_fraction)),
            skew=float(lm.get("skew", lm_defaults.skew)),
            image_population=int(lm.get(
                "image-population", lm_defaults.image_population)),
        )
        # The generator itself re-validates at construction; failing
        # at config load keeps a bad block out of a bench round.
        if cfg.loadmodel.viewers < 1:
            raise ValueError("loadmodel.viewers must be >= 1")
        if cfg.loadmodel.think_time_median_ms <= 0 \
                or cfg.loadmodel.session_length_median <= 0:
            raise ValueError("loadmodel medians must be > 0")
        if cfg.loadmodel.think_time_sigma < 0 \
                or cfg.loadmodel.session_length_sigma < 0:
            raise ValueError("loadmodel sigmas must be >= 0")
        if not 0.0 <= cfg.loadmodel.diurnal_amplitude < 1.0:
            raise ValueError("loadmodel.diurnal-amplitude must be in "
                             "[0, 1)")
        for name in ("bulk_fraction", "mask_fraction",
                     "pyramid_fraction", "animation_fraction",
                     "zoom_fraction"):
            v = getattr(cfg.loadmodel, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"loadmodel.{name.replace('_', '-')} must be in "
                    f"[0, 1]")
        if (cfg.loadmodel.bulk_fraction
                + cfg.loadmodel.mask_fraction
                + cfg.loadmodel.pyramid_fraction
                + cfg.loadmodel.animation_fraction) > 1.0:
            raise ValueError("loadmodel bulk-fraction + mask-fraction "
                             "+ pyramid-fraction + animation-fraction "
                             "must sum to <= 1")
        if cfg.loadmodel.skew < 0:
            raise ValueError("loadmodel.skew must be >= 0 "
                             "(0 = every session on one image)")
        if cfg.loadmodel.image_population < 1:
            raise ValueError("loadmodel.image-population must be "
                             ">= 1")
        wl = raw.get("workloads", {}) or {}
        wl_defaults = WorkloadsConfig()
        cfg.workloads = WorkloadsConfig(
            device_masks=bool(wl.get("device-masks",
                                     wl_defaults.device_masks)),
            overlay_enabled=bool(wl.get("overlay-enabled",
                                        wl_defaults.overlay_enabled)),
            animation_enabled=bool(wl.get(
                "animation-enabled", wl_defaults.animation_enabled)),
            animation_max_frames=int(wl.get(
                "animation-max-frames",
                wl_defaults.animation_max_frames)),
        )
        if cfg.workloads.animation_max_frames < 1:
            raise ValueError("workloads.animation-max-frames must be "
                             ">= 1")
        py = raw.get("pyramid", {}) or {}
        py_defaults = PyramidConfig()
        cfg.pyramid = PyramidConfig(
            enabled=bool(py.get("enabled", py_defaults.enabled)),
            chunk=int(py.get("chunk", py_defaults.chunk)),
            min_level_size=int(py.get("min-level-size",
                                      py_defaults.min_level_size)),
            compressor=str(py.get("compressor",
                                  py_defaults.compressor)),
            defer_poll_s=float(py.get("defer-poll-s",
                                      py_defaults.defer_poll_s)),
        )
        if cfg.pyramid.chunk < 16:
            raise ValueError("pyramid.chunk must be >= 16")
        if cfg.pyramid.min_level_size < 1:
            raise ValueError("pyramid.min-level-size must be >= 1")
        if cfg.pyramid.compressor not in ("zlib", "gzip", "none"):
            raise ValueError("pyramid.compressor must be zlib, gzip, "
                             "or none")
        if cfg.pyramid.defer_poll_s <= 0:
            raise ValueError("pyramid.defer-poll-s must be > 0")
        au = raw.get("autoscaler", {}) or {}
        au_defaults = AutoscalerConfig()
        cfg.autoscaler = AutoscalerConfig(
            enabled=bool(au.get("enabled", au_defaults.enabled)),
            interval_s=float(au.get("interval-s",
                                    au_defaults.interval_s)),
            floor=int(au.get("floor", au_defaults.floor)),
            ceiling=int(au.get("ceiling", au_defaults.ceiling)),
            queue_high_per_lane=float(au.get(
                "queue-high-per-lane",
                au_defaults.queue_high_per_lane)),
            queue_low_per_lane=float(au.get(
                "queue-low-per-lane", au_defaults.queue_low_per_lane)),
            hold_ticks=int(au.get("hold-ticks",
                                  au_defaults.hold_ticks)),
            cooldown_s=float(au.get("cooldown-s",
                                    au_defaults.cooldown_s)),
            lane_capacity_tps=float(au.get(
                "lane-capacity-tps", au_defaults.lane_capacity_tps)),
            session_tps=float(au.get("session-tps",
                                     au_defaults.session_tps)),
            diurnal_period_s=float(au.get(
                "diurnal-period-s", au_defaults.diurnal_period_s)),
            diurnal_horizon_s=float(au.get(
                "diurnal-horizon-s", au_defaults.diurnal_horizon_s)),
            unit_config=str(au.get("unit-config",
                                   au_defaults.unit_config) or ""),
        )
        if cfg.autoscaler.interval_s <= 0:
            raise ValueError("autoscaler.interval-s must be > 0")
        if cfg.autoscaler.floor < 1:
            raise ValueError("autoscaler.floor must be >= 1 (the "
                             "fleet must always keep a servable "
                             "member)")
        if cfg.autoscaler.ceiling != 0 \
                and cfg.autoscaler.ceiling < cfg.autoscaler.floor:
            raise ValueError("autoscaler.ceiling must be 0 (all "
                             "members) or >= autoscaler.floor")
        if not 0 <= cfg.autoscaler.queue_low_per_lane \
                < cfg.autoscaler.queue_high_per_lane:
            raise ValueError(
                "autoscaler.queue-low-per-lane must be in [0, "
                "queue-high-per-lane) — the hysteresis band needs "
                "low < high")
        if cfg.autoscaler.hold_ticks < 1:
            raise ValueError("autoscaler.hold-ticks must be >= 1")
        if cfg.autoscaler.cooldown_s < 0:
            raise ValueError("autoscaler.cooldown-s must be >= 0")
        if cfg.autoscaler.lane_capacity_tps < 0:
            raise ValueError("autoscaler.lane-capacity-tps must be "
                             ">= 0 (0 disables the demand signal)")
        if cfg.autoscaler.session_tps <= 0:
            raise ValueError("autoscaler.session-tps must be > 0")
        if cfg.autoscaler.diurnal_period_s < 0:
            raise ValueError("autoscaler.diurnal-period-s must be "
                             ">= 0 (0 disables diurnal prediction)")
        if cfg.autoscaler.diurnal_horizon_s < 0:
            raise ValueError("autoscaler.diurnal-horizon-s must be "
                             ">= 0")
        if cfg.autoscaler.unit_config and not cfg.fleet.sockets:
            raise ValueError(
                "autoscaler.unit-config manages sidecar unit "
                "processes — it requires the fleet.sockets topology")
        if cfg.autoscaler.enabled and not (cfg.fleet.enabled
                                           or cfg.federation.enabled):
            raise ValueError(
                "autoscaler.enabled requires a fleet topology "
                "(fleet.enabled or federation.enabled) — there is "
                "nothing to scale without members")
        if cfg.autoscaler.enabled:
            provisioned = (len(cfg.federation.members)
                           if cfg.federation.enabled
                           else (len(cfg.fleet.sockets)
                                 or cfg.fleet.members))
            if cfg.autoscaler.floor > provisioned:
                # An unachievable floor would block every scale-down
                # forever (blocked:floor) — the bad-block-fails-at-
                # load contract, not a silent mid-serving no-op.
                raise ValueError(
                    f"autoscaler.floor ({cfg.autoscaler.floor}) "
                    f"exceeds the provisioned fleet size "
                    f"({provisioned} members)")
        qo = raw.get("qos", {}) or {}
        qo_defaults = QosConfig()
        cfg.qos = QosConfig(
            enabled=bool(qo.get("enabled", qo_defaults.enabled)),
            interactive_weight=int(qo.get(
                "interactive-weight", qo_defaults.interactive_weight)),
            bulk_cost=float(qo.get("bulk-cost",
                                   qo_defaults.bulk_cost)),
        )
        if cfg.qos.interactive_weight < 1:
            raise ValueError("qos.interactive-weight must be >= 1")
        if cfg.qos.bulk_cost < 1:
            raise ValueError("qos.bulk-cost must be >= 1")
        pr = raw.get("pressure", {}) or {}
        pr_defaults = PressureConfig()
        cfg.pressure = PressureConfig(
            enabled=bool(pr.get("enabled", pr_defaults.enabled)),
            interval_s=float(pr.get("interval-s",
                                    pr_defaults.interval_s)),
            hbm_high=float(pr.get("hbm-high", pr_defaults.hbm_high)),
            hbm_low=float(pr.get("hbm-low", pr_defaults.hbm_low)),
            host_rss_high_mb=float(pr.get(
                "host-rss-high-mb", pr_defaults.host_rss_high_mb)),
            host_rss_low_mb=float(pr.get(
                "host-rss-low-mb", pr_defaults.host_rss_low_mb)),
            disk_high=float(pr.get("disk-high",
                                   pr_defaults.disk_high)),
            disk_low=float(pr.get("disk-low", pr_defaults.disk_low)),
            queue_high=int(pr.get("queue-high",
                                  pr_defaults.queue_high)),
            queue_low=int(pr.get("queue-low", pr_defaults.queue_low)),
            loop_lag_high_ms=float(pr.get(
                "loop-lag-high-ms", pr_defaults.loop_lag_high_ms)),
            loop_lag_low_ms=float(pr.get(
                "loop-lag-low-ms", pr_defaults.loop_lag_low_ms)),
            critical_factor=float(pr.get(
                "critical-factor", pr_defaults.critical_factor)),
            step_hold_ticks=int(pr.get(
                "step-hold-ticks", pr_defaults.step_hold_ticks)),
            release_hold_ticks=int(pr.get(
                "release-hold-ticks", pr_defaults.release_hold_ticks)),
            ladder=tuple(str(s) for s in pr.get("ladder", ()) or ())
            or pr_defaults.ladder,
            quality_cap=int(pr.get("quality-cap",
                                   pr_defaults.quality_cap)),
            evict_to_frac=float(pr.get(
                "evict-to-frac", pr_defaults.evict_to_frac)),
            lane_cap=int(pr.get("lane-cap", pr_defaults.lane_cap)),
            admission_scale=float(pr.get(
                "admission-scale", pr_defaults.admission_scale)),
            prefetch_budget_elevated=float(pr.get(
                "prefetch-budget-elevated",
                pr_defaults.prefetch_budget_elevated)),
            prefetch_budget_critical=float(pr.get(
                "prefetch-budget-critical",
                pr_defaults.prefetch_budget_critical)),
        )
        if cfg.pressure.interval_s <= 0:
            raise ValueError("pressure.interval-s must be > 0")
        from .pressure import KNOWN_STEPS
        seen_steps = set()
        for step in cfg.pressure.ladder:
            if step not in KNOWN_STEPS:
                raise ValueError(
                    f"pressure.ladder step {step!r} is not one of "
                    f"{sorted(KNOWN_STEPS)}")
            if step in seen_steps:
                raise ValueError(
                    f"pressure.ladder repeats step {step!r}")
            seen_steps.add(step)
        if ("shed_bulk" in seen_steps
                and "tighten_admission" in seen_steps
                and cfg.pressure.ladder.index("shed_bulk")
                > cfg.pressure.ladder.index("tighten_admission")):
            # The availability-ordering invariant: interactive tiles
            # are never shed before bulk/projection work.
            raise ValueError(
                "pressure.ladder must engage shed_bulk before "
                "tighten_admission (bulk work sheds first; "
                "interactive availability goes last)")
        for pair in (("hbm_high", "hbm_low"),
                     ("host_rss_high_mb", "host_rss_low_mb"),
                     ("disk_high", "disk_low"),
                     ("queue_high", "queue_low"),
                     ("loop_lag_high_ms", "loop_lag_low_ms")):
            high, low = (getattr(cfg.pressure, pair[0]),
                         getattr(cfg.pressure, pair[1]))
            if high > 0 and not 0 <= low < high:
                raise ValueError(
                    f"pressure.{pair[1].replace('_', '-')} must be in "
                    f"[0, {pair[0].replace('_', '-')}) — the "
                    f"hysteresis band needs low < high")
        if cfg.pressure.critical_factor < 1.0:
            raise ValueError("pressure.critical-factor must be >= 1")
        if cfg.pressure.step_hold_ticks < 1 \
                or cfg.pressure.release_hold_ticks < 1:
            raise ValueError("pressure step/release hold ticks must "
                             "be >= 1")
        if not 1 <= cfg.pressure.quality_cap <= 100:
            raise ValueError("pressure.quality-cap must be in "
                             "[1, 100]")
        if not 0.0 < cfg.pressure.evict_to_frac < 1.0:
            raise ValueError("pressure.evict-to-frac must be in "
                             "(0, 1)")
        if cfg.pressure.lane_cap < 1:
            raise ValueError("pressure.lane-cap must be >= 1")
        if not 0.0 < cfg.pressure.admission_scale <= 1.0:
            raise ValueError("pressure.admission-scale must be in "
                             "(0, 1]")
        if not (0.0 < cfg.pressure.prefetch_budget_critical
                <= cfg.pressure.prefetch_budget_elevated <= 1.0):
            # Monotone by construction: more pressure can never mean
            # MORE speculative staging.
            raise ValueError(
                "pressure prefetch budgets must satisfy 0 < "
                "prefetch-budget-critical <= "
                "prefetch-budget-elevated <= 1")
        wd = raw.get("watchdog", {}) or {}
        wd_defaults = WatchdogConfig()
        cfg.watchdog = WatchdogConfig(
            enabled=bool(wd.get("enabled", wd_defaults.enabled)),
            interval_s=float(wd.get("interval-s",
                                    wd_defaults.interval_s)),
            stall_factor=float(wd.get("stall-factor",
                                      wd_defaults.stall_factor)),
            stall_min_s=float(wd.get("stall-min-s",
                                     wd_defaults.stall_min_s)),
            wire_hang_s=float(wd.get("wire-hang-s",
                                     wd_defaults.wire_hang_s)),
            escalate_after=int(wd.get("escalate-after",
                                      wd_defaults.escalate_after)),
        )
        if cfg.watchdog.interval_s <= 0:
            raise ValueError("watchdog.interval-s must be > 0")
        if cfg.watchdog.stall_factor < 1.0:
            raise ValueError("watchdog.stall-factor must be >= 1")
        if cfg.watchdog.stall_min_s <= 0:
            raise ValueError("watchdog.stall-min-s must be > 0 (the "
                             "floor keeps cold compiles from reading "
                             "as stalls)")
        if cfg.watchdog.wire_hang_s < 0:
            raise ValueError("watchdog.wire-hang-s must be >= 0 "
                             "(0 disables the wire check)")
        if cfg.watchdog.escalate_after < 1:
            raise ValueError("watchdog.escalate-after must be >= 1")
        dr = raw.get("drain", {}) or {}
        dr_defaults = DrainConfig()
        cfg.drain = DrainConfig(
            prestage=bool(dr.get("prestage", dr_defaults.prestage)),
            prestage_max_planes=int(dr.get(
                "prestage-max-planes",
                dr_defaults.prestage_max_planes)),
            settle_timeout_s=float(dr.get(
                "settle-timeout-s", dr_defaults.settle_timeout_s)),
            fail_readyz=bool(dr.get("fail-readyz",
                                    dr_defaults.fail_readyz)),
        )
        if cfg.drain.prestage_max_planes < 1:
            raise ValueError("drain.prestage-max-planes must be >= 1")
        if cfg.drain.settle_timeout_s <= 0:
            raise ValueError("drain.settle-timeout-s must be > 0")
        tel = raw.get("telemetry", {}) or {}
        tel_defaults = TelemetryConfig()
        cfg.telemetry = TelemetryConfig(
            slow_request_ms=float(tel.get("slow-request-ms",
                                          tel_defaults.slow_request_ms)),
            slow_request_dir=str(tel.get(
                "slow-request-dir", tel_defaults.slow_request_dir)),
            access_log=bool(tel.get("access-log",
                                    tel_defaults.access_log)),
            ready_max_queue_depth=int(tel.get(
                "ready-max-queue-depth",
                tel_defaults.ready_max_queue_depth)),
            flight_recorder_events=int(tel.get(
                "flight-recorder-events",
                tel_defaults.flight_recorder_events)),
            flight_recorder_dir=str(tel.get(
                "flight-recorder-dir",
                tel_defaults.flight_recorder_dir)),
            profile_dir=str(tel.get("profile-dir",
                                    tel_defaults.profile_dir)),
            profile_max_ms=float(tel.get(
                "profile-max-ms", tel_defaults.profile_max_ms)),
            provenance_header=bool(tel.get(
                "provenance-header",
                tel_defaults.provenance_header)),
        )
        if cfg.telemetry.slow_request_ms < 0:
            raise ValueError("telemetry.slow-request-ms must be >= 0")
        if cfg.telemetry.ready_max_queue_depth < 1:
            raise ValueError("telemetry.ready-max-queue-depth must be "
                             ">= 1")
        if cfg.telemetry.flight_recorder_events < 16:
            raise ValueError("telemetry.flight-recorder-events must be "
                             ">= 16 (the black box needs some tape)")
        if cfg.telemetry.profile_max_ms <= 0:
            raise ValueError("telemetry.profile-max-ms must be > 0")
        slo = raw.get("slo", {}) or {}
        slo_defaults = SloConfig()
        cfg.slo = SloConfig(
            availability_target=float(slo.get(
                "availability-target",
                slo_defaults.availability_target)),
            latency_ms=float(slo.get("latency-ms",
                                     slo_defaults.latency_ms)),
            latency_target=float(slo.get(
                "latency-target", slo_defaults.latency_target)),
            fast_window_s=float(slo.get(
                "fast-window-s", slo_defaults.fast_window_s)),
            slow_window_s=float(slo.get(
                "slow-window-s", slo_defaults.slow_window_s)),
            breach_burn_rate=float(slo.get(
                "breach-burn-rate", slo_defaults.breach_burn_rate)),
        )
        for name in ("availability_target", "latency_target"):
            v = getattr(cfg.slo, name)
            if not 0.0 <= v < 1.0:
                raise ValueError(
                    f"slo.{name.replace('_', '-')} must be in [0, 1) "
                    f"(a target of 1.0 leaves zero error budget), "
                    f"got {v}")
        if cfg.slo.latency_ms < 0:
            raise ValueError("slo.latency-ms must be >= 0")
        if cfg.slo.fast_window_s <= 0 or cfg.slo.slow_window_s <= 0:
            raise ValueError("slo windows must be > 0 seconds")
        if cfg.slo.breach_burn_rate <= 0:
            raise ValueError("slo.breach-burn-rate must be > 0")
        dec = raw.get("decisions", {}) or {}
        dec_defaults = DecisionsConfig()
        cfg.decisions = DecisionsConfig(
            ring_size=int(dec.get("ring-size",
                                  dec_defaults.ring_size)),
            spool_dir=str(dec.get("spool-dir",
                                  dec_defaults.spool_dir) or ""),
            outcome_horizon_ticks=int(dec.get(
                "outcome-horizon-ticks",
                dec_defaults.outcome_horizon_ticks)),
        )
        if cfg.decisions.ring_size < 16:
            raise ValueError("decisions.ring-size must be >= 16")
        if cfg.decisions.outcome_horizon_ticks < 1:
            raise ValueError(
                "decisions.outcome-horizon-ticks must be >= 1")
        sen = raw.get("sentinel", {}) or {}
        sen_defaults = SentinelConfig()
        cfg.sentinel = SentinelConfig(
            enabled=bool(sen.get("enabled", sen_defaults.enabled)),
            tick_interval_s=float(sen.get(
                "tick-interval-s", sen_defaults.tick_interval_s)),
            confirm_ticks=int(sen.get(
                "confirm-ticks", sen_defaults.confirm_ticks)),
            recover_ticks=int(sen.get(
                "recover-ticks", sen_defaults.recover_ticks)),
            min_samples=int(sen.get(
                "min-samples", sen_defaults.min_samples)),
            warmup_ticks=int(sen.get(
                "warmup-ticks", sen_defaults.warmup_ticks)),
            drift_ratio=float(sen.get(
                "drift-ratio", sen_defaults.drift_ratio)),
            baseline_alpha=float(sen.get(
                "baseline-alpha", sen_defaults.baseline_alpha)),
            throughput_floor_ratio=float(sen.get(
                "throughput-floor-ratio",
                sen_defaults.throughput_floor_ratio)),
            bundle_dir=str(sen.get(
                "bundle-dir", sen_defaults.bundle_dir) or ""),
            max_bundles=int(sen.get(
                "max-bundles", sen_defaults.max_bundles)),
            profile_ms=int(sen.get(
                "profile-ms", sen_defaults.profile_ms)),
            records_dir=str(sen.get(
                "records-dir", sen_defaults.records_dir) or ""),
        )
        if cfg.sentinel.tick_interval_s <= 0:
            raise ValueError("sentinel.tick-interval-s must be > 0")
        if cfg.sentinel.confirm_ticks < 1:
            raise ValueError("sentinel.confirm-ticks must be >= 1 "
                             "(a zero-confirmation sentinel would "
                             "page on one slow window)")
        if cfg.sentinel.recover_ticks < 1:
            raise ValueError("sentinel.recover-ticks must be >= 1")
        if cfg.sentinel.min_samples < 1:
            raise ValueError("sentinel.min-samples must be >= 1")
        if cfg.sentinel.warmup_ticks < 1:
            raise ValueError("sentinel.warmup-ticks must be >= 1")
        if cfg.sentinel.drift_ratio <= 1.0:
            raise ValueError(
                "sentinel.drift-ratio must be > 1.0 (a ratio at or "
                "under 1.0 calls steady state a drift)")
        if not 0.0 < cfg.sentinel.baseline_alpha <= 1.0:
            raise ValueError(
                "sentinel.baseline-alpha must be in (0, 1]")
        if not 0.0 < cfg.sentinel.throughput_floor_ratio <= 1.0:
            raise ValueError(
                "sentinel.throughput-floor-ratio must be in (0, 1]")
        if cfg.sentinel.max_bundles < 1:
            raise ValueError("sentinel.max-bundles must be >= 1")
        if cfg.sentinel.profile_ms < 0:
            raise ValueError("sentinel.profile-ms must be >= 0")
        ft = raw.get("fault-tolerance", {}) or {}
        ft_defaults = FaultToleranceConfig()
        cfg.fault_tolerance = FaultToleranceConfig(
            request_deadline_ms=float(ft.get(
                "request-deadline-ms",
                ft_defaults.request_deadline_ms)),
            breaker_failure_threshold=int(ft.get(
                "breaker-failure-threshold",
                ft_defaults.breaker_failure_threshold)),
            breaker_reset_s=float(ft.get(
                "breaker-reset-s", ft_defaults.breaker_reset_s)),
            retry_max_attempts=int(ft.get(
                "retry-max-attempts", ft_defaults.retry_max_attempts)),
            retry_base_backoff_ms=float(ft.get(
                "retry-base-backoff-ms",
                ft_defaults.retry_base_backoff_ms)),
            retry_max_backoff_ms=float(ft.get(
                "retry-max-backoff-ms",
                ft_defaults.retry_max_backoff_ms)),
            admission_max_queue=int(ft.get(
                "admission-max-queue",
                ft_defaults.admission_max_queue)),
            shed_retry_after_s=float(ft.get(
                "shed-retry-after-s", ft_defaults.shed_retry_after_s)),
            degraded_mode=bool(ft.get("degraded-mode",
                                      ft_defaults.degraded_mode)),
            supervise=bool(ft.get("supervise", ft_defaults.supervise)),
            supervisor_max_backoff_s=float(ft.get(
                "supervisor-max-backoff-s",
                ft_defaults.supervisor_max_backoff_s)),
        )
        if cfg.fault_tolerance.request_deadline_ms < 0:
            raise ValueError("fault-tolerance.request-deadline-ms must "
                             "be >= 0")
        if cfg.fault_tolerance.breaker_failure_threshold < 1:
            raise ValueError("fault-tolerance.breaker-failure-threshold "
                             "must be >= 1")
        if cfg.fault_tolerance.retry_max_attempts < 1:
            raise ValueError("fault-tolerance.retry-max-attempts must "
                             "be >= 1")
        if cfg.fault_tolerance.admission_max_queue < 0:
            raise ValueError("fault-tolerance.admission-max-queue must "
                             "be >= 0 (0 disables admission control)")
        fi = raw.get("fault-injection", {}) or {}
        fi_defaults = FaultInjectionConfig()
        cfg.fault_injection = FaultInjectionConfig(
            seed=(int(fi["seed"]) if fi.get("seed") is not None
                  else None),
            wire_drop_rate=float(fi.get(
                "wire-drop-rate", fi_defaults.wire_drop_rate)),
            wire_truncate_rate=float(fi.get(
                "wire-truncate-rate", fi_defaults.wire_truncate_rate)),
            wire_delay_rate=float(fi.get(
                "wire-delay-rate", fi_defaults.wire_delay_rate)),
            wire_delay_ms=float(fi.get(
                "wire-delay-ms", fi_defaults.wire_delay_ms)),
            device_error_rate=float(fi.get(
                "device-error-rate", fi_defaults.device_error_rate)),
            freeze_rate=float(fi.get(
                "freeze-rate", fi_defaults.freeze_rate)),
            freeze_ms=float(fi.get("freeze-ms", fi_defaults.freeze_ms)),
            freeze_max=int(fi.get("freeze-max",
                                  fi_defaults.freeze_max)),
            die_after_requests=int(fi.get(
                "die-after-requests", fi_defaults.die_after_requests)),
        ).validate()   # rate/delay bounds fail at load, not mid-serving
        if (cfg.fault_injection.seed is not None
                and (raw.get("parallel", {}) or {}).get("enabled")
                and int((raw.get("parallel", {}) or {})
                        .get("num-processes") or 1) > 1):
            # Chaos fires on whatever process installed it; on a
            # multi-host pod that stalls/re-launches ONE process's SPMD
            # lockstep sequence and hangs the slice.  (Auto-discovered
            # pods without explicit coordinates are disarmed at
            # bring-up instead — see build_services.)
            raise ValueError("fault-injection.seed cannot be combined "
                             "with a multi-host parallel config")
        rd = raw.get("renderer", {}) or {}
        rd_defaults = RendererConfig()
        cfg.renderer = RendererConfig(
            cpu_fallback_max_px=int(rd.get(
                "cpu-fallback-max-px", rd_defaults.cpu_fallback_max_px)),
            jpeg_engine=str(rd.get("jpeg-engine",
                                   rd_defaults.jpeg_engine)),
            kernel=str(rd.get("kernel", rd_defaults.kernel)),
            compilation_cache_dir=(
                str(rd["compilation-cache-dir"])
                if rd.get("compilation-cache-dir") is not None
                else rd_defaults.compilation_cache_dir),
            prewarm=tuple(str(s) for s in rd.get("prewarm", ()) or ()),
        )
        from .prewarm import parse_spec
        for spec in cfg.renderer.prewarm:
            parse_spec(spec)   # malformed specs fail at load, not boot
        if cfg.renderer.jpeg_engine not in ("sparse", "huffman",
                                            "bitpack", "auto"):
            raise ValueError(
                f"renderer.jpeg-engine must be 'sparse', 'huffman', "
                f"'bitpack' or 'auto', got {cfg.renderer.jpeg_engine!r}")
        if (cfg.renderer.jpeg_engine == "bitpack"
                and (cfg.batcher.enabled or cfg.parallel.enabled)):
            # Engine/posture parity: bitpack has no batched group form,
            # so a config valid for the direct renderer must fail loudly
            # at load time in the batched/mesh postures instead of
            # silently serving a different engine.
            raise ValueError(
                "renderer.jpeg-engine 'bitpack' is only supported by "
                "the direct (unbatched) renderer; with batcher.enabled "
                "or parallel.enabled use 'sparse', 'huffman' or 'auto'")
        if cfg.renderer.kernel not in ("xla", "pallas"):
            raise ValueError(
                f"renderer.kernel must be 'xla' or 'pallas', "
                f"got {cfg.renderer.kernel!r}")
        return cfg
