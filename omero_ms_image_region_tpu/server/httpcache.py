"""Conditional-HTTP cache semantics: content-addressed ETags, 304s,
and honest ``Cache-Control``/``Vary`` — the L5 layer that lets
nginx/CDN edges absorb repeat viewers without a render, an admission
slot, or a session token.

The reference leans on per-route ``Cache-Control``/content-type
handling so OMERO.web's nginx front can cache tile responses
(``ImageRegionMicroserviceVerticle.java:294-352``); this build goes
one step further and makes revalidation FREE: the ETag derives from
the render-identity key (``settings.render_identity_key`` — the PR 2
canonical sorted-params identity the byte cache and single-flight
already key on) plus a deployment **epoch**, so

* two requests whose params differ only in ordering share one ETag
  (the identity is SipHash over the SORTED params);
* ``/7/0/0/`` and ``/7/0/0`` alias (the route's ``tail`` never
  reaches the params);
* the ETag never touches the pixels — answering ``If-None-Match``
  with 304 requires ZERO render, admission or session-token work, and
  a 304 leaks nothing a client could not derive from the URL itself;
* bumping ``http-cache.epoch`` (a config string) invalidates EVERY
  edge-cached entry at once — the one knob an operator turns when
  source data or the render pipeline changes under live URLs
  (deploy/DEPLOY.md "Edge caching").

Device-free on purpose: frontend proxies and fleet routers evaluate
conditionals without importing the JAX stack.
"""

from __future__ import annotations

import hashlib
import os
import re
from email.utils import formatdate, parsedate_to_datetime
from typing import Optional, Tuple

# ETag schema version: bumping the derivation below MUST bump this
# prefix (a silently changed ETag invalidates every CDN edge at once;
# the golden pin in tests/test_http_cache.py fails loudly instead).
_SCHEMA = "ir1"

# Epochs ride inside the quoted ETag: token characters only, so a
# config typo can never smuggle a quote/comma into the header.
EPOCH_RE = re.compile(r"^[A-Za-z0-9._-]+$")

# ``http-cache.epoch: auto``: derive the epoch from the data tree's
# ingest/source stamps at startup instead of asking the operator to
# bump a string by hand (the explicit value stays the override).
EPOCH_AUTO = "auto"


def etag_for(cache_key: str, epoch: str = "0") -> str:
    """Strong ETag for a render identity under ``epoch``.

    ``cache_key`` is the ctx's canonical identity
    (``render_identity_key`` == ``ImageRegionCtx.cache_key``, or the
    mask ctx's ``cache_key()``).  The digest folds the epoch, and the
    epoch ALSO rides visibly in the tag so an operator can read which
    generation an edge holds straight off a response header."""
    digest = hashlib.blake2b(
        f"{epoch}:{cache_key}".encode(), digest_size=12).hexdigest()
    return f'"{_SCHEMA}-{epoch}-{digest}"'


def if_none_match_matches(header: Optional[str], etag: str) -> bool:
    """RFC 9110 ``If-None-Match`` evaluation against one strong ETag.

    ``*`` matches any current representation; otherwise the header is
    a comma-separated list of entity tags, compared WEAKLY (the
    ``W/`` prefix is stripped — weak comparison is what 304
    revalidation specifies, and our tags are strong anyway)."""
    if not header:
        return False
    header = header.strip()
    if header == "*":
        return True
    for candidate in header.split(","):
        candidate = candidate.strip()
        if candidate.startswith("W/"):
            candidate = candidate[2:]
        if candidate == etag:
            return True
    return False


def cache_headers(max_age_s: int, acl_gated: bool,
                  session_cookie: str = "Cookie"
                  ) -> Tuple[str, Optional[str]]:
    """(Cache-Control, Vary-or-None) for a cacheable 200/304.

    Honesty rules (deploy/DEPLOY.md "Edge caching"):

    * ``max_age_s == 0`` → ``no-cache`` — edges may STORE but must
      revalidate every serve; with free 304s that is the safe default
      posture (every repeat view costs one conditional round-trip,
      never a render).
    * ACL-gated images are ``private`` and vary on the session-bearing
      header, so a shared cache can never serve one session's entry to
      another; public images are ``public`` with NO Vary (the
      cookie-blind entry is safe for everyone, and varying would
      shatter the edge's hit rate per-user for no protection).
    """
    scope = "private" if acl_gated else "public"
    if max_age_s <= 0:
        cc = f"{scope}, no-cache"
    else:
        cc = f"{scope}, max-age={int(max_age_s)}"
    vary = session_cookie if acl_gated else None
    return cc, vary


# ------------------------------------------------------- epoch: auto

def derive_epoch(data_dir: str) -> str:
    """Derive the cache epoch from the data tree's source mtimes.

    The stamp is the NEWEST mtime among each image directory's
    metadata files (meta.json / NGFF .zattrs / a TIFF) plus the image
    directories themselves — exactly the files an ingest touches, so
    re-ingesting any image bumps the deployment epoch and every
    edge-cached entry revalidates fresh.  Deterministic for a given
    tree (pinned in the golden ETag corpus): ``m<seconds>`` with the
    mtime truncated to whole seconds (sub-second noise across
    filesystems must not split a fleet's epochs).

    Shallow on purpose: one listdir of ``data_dir`` + a few stats per
    image — never a recursive walk over chunk stores.  A missing or
    empty tree derives "0" (the default epoch)."""
    newest = 0
    try:
        entries = sorted(os.scandir(data_dir), key=lambda e: e.name)
    except OSError:
        return "0"
    for entry in entries:
        try:
            if not entry.is_dir():
                continue
            newest = max(newest, int(entry.stat().st_mtime))
            for name in ("meta.json", ".zattrs"):
                p = os.path.join(entry.path, name)
                try:
                    newest = max(newest, int(os.stat(p).st_mtime))
                except OSError:
                    pass
            with os.scandir(entry.path) as inner:
                for child in inner:
                    if child.name.endswith((".tif", ".tiff",
                                            ".ome.tif")):
                        newest = max(newest,
                                     int(child.stat().st_mtime))
                    elif child.is_dir():
                        # NGFF group root one level down (the ingest
                        # layout): its .zattrs is the geometry stamp.
                        p = os.path.join(child.path, ".zattrs")
                        try:
                            newest = max(newest,
                                         int(os.stat(p).st_mtime))
                        except OSError:
                            pass
        except OSError:
            continue
    return f"m{newest}" if newest else "0"


# ---------------------------------------------------- Last-Modified

def last_modified_basis(mtime: Optional[float],
                        epoch: str) -> Optional[float]:
    """The instant the stored representation last changed, for
    Last-Modified / If-Modified-Since purposes — the source mtime
    FOLDED with the cache epoch, so bumping the epoch invalidates
    IMS-only clients exactly like it invalidates ETags:

    * default epoch ``"0"``: the source mtime alone;
    * derived ``m<seconds>`` epochs carry their own instant: the
      basis is ``max(mtime, epoch_seconds)`` — an epoch bump moves
      Last-Modified forward, so every stored IMS date goes stale;
    * any OTHER operator epoch is un-ordered in time: None — the
      Last-Modified header is withheld and the IMS-only 304 leg
      disarms (the ETag keeps revalidation free; a 304 judged
      against a pre-bump Last-Modified would revive exactly the
      stale entries the bump was meant to kill)."""
    if mtime is None:
        return None
    if epoch == "0":
        return mtime
    if epoch.startswith("m") and epoch[1:].isdigit():
        return max(float(mtime), float(epoch[1:]))
    return None

def http_date(ts: float) -> str:
    """Unix seconds -> RFC 9110 HTTP-date (IMF-fixdate, GMT)."""
    return formatdate(ts, usegmt=True)


def parse_http_date(value: Optional[str]) -> Optional[float]:
    """HTTP-date header -> unix seconds (None on garbage — a client
    sending a malformed If-Modified-Since just gets the full 200)."""
    if not value:
        return None
    try:
        return parsedate_to_datetime(value).timestamp()
    except (TypeError, ValueError, OverflowError):
        return None


def not_modified_since(ims_header: Optional[str],
                       mtime: Optional[float]) -> bool:
    """RFC 9110 If-Modified-Since evaluation against the source
    mtime.  True = the stored response is still fresh (304).  The
    comparison truncates to whole seconds — HTTP-dates carry no
    sub-second precision, and a sub-second ingest would otherwise
    304 forever under an equal-seconds stamp."""
    if mtime is None:
        return False
    since = parse_http_date(ims_header)
    if since is None:
        return False
    return int(mtime) <= int(since)
