"""Conditional-HTTP cache semantics: content-addressed ETags, 304s,
and honest ``Cache-Control``/``Vary`` — the L5 layer that lets
nginx/CDN edges absorb repeat viewers without a render, an admission
slot, or a session token.

The reference leans on per-route ``Cache-Control``/content-type
handling so OMERO.web's nginx front can cache tile responses
(``ImageRegionMicroserviceVerticle.java:294-352``); this build goes
one step further and makes revalidation FREE: the ETag derives from
the render-identity key (``settings.render_identity_key`` — the PR 2
canonical sorted-params identity the byte cache and single-flight
already key on) plus a deployment **epoch**, so

* two requests whose params differ only in ordering share one ETag
  (the identity is SipHash over the SORTED params);
* ``/7/0/0/`` and ``/7/0/0`` alias (the route's ``tail`` never
  reaches the params);
* the ETag never touches the pixels — answering ``If-None-Match``
  with 304 requires ZERO render, admission or session-token work, and
  a 304 leaks nothing a client could not derive from the URL itself;
* bumping ``http-cache.epoch`` (a config string) invalidates EVERY
  edge-cached entry at once — the one knob an operator turns when
  source data or the render pipeline changes under live URLs
  (deploy/DEPLOY.md "Edge caching").

Device-free on purpose: frontend proxies and fleet routers evaluate
conditionals without importing the JAX stack.
"""

from __future__ import annotations

import hashlib
import re
from typing import Optional, Tuple

# ETag schema version: bumping the derivation below MUST bump this
# prefix (a silently changed ETag invalidates every CDN edge at once;
# the golden pin in tests/test_http_cache.py fails loudly instead).
_SCHEMA = "ir1"

# Epochs ride inside the quoted ETag: token characters only, so a
# config typo can never smuggle a quote/comma into the header.
EPOCH_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def etag_for(cache_key: str, epoch: str = "0") -> str:
    """Strong ETag for a render identity under ``epoch``.

    ``cache_key`` is the ctx's canonical identity
    (``render_identity_key`` == ``ImageRegionCtx.cache_key``, or the
    mask ctx's ``cache_key()``).  The digest folds the epoch, and the
    epoch ALSO rides visibly in the tag so an operator can read which
    generation an edge holds straight off a response header."""
    digest = hashlib.blake2b(
        f"{epoch}:{cache_key}".encode(), digest_size=12).hexdigest()
    return f'"{_SCHEMA}-{epoch}-{digest}"'


def if_none_match_matches(header: Optional[str], etag: str) -> bool:
    """RFC 9110 ``If-None-Match`` evaluation against one strong ETag.

    ``*`` matches any current representation; otherwise the header is
    a comma-separated list of entity tags, compared WEAKLY (the
    ``W/`` prefix is stripped — weak comparison is what 304
    revalidation specifies, and our tags are strong anyway)."""
    if not header:
        return False
    header = header.strip()
    if header == "*":
        return True
    for candidate in header.split(","):
        candidate = candidate.strip()
        if candidate.startswith("W/"):
            candidate = candidate[2:]
        if candidate == etag:
            return True
    return False


def cache_headers(max_age_s: int, acl_gated: bool,
                  session_cookie: str = "Cookie"
                  ) -> Tuple[str, Optional[str]]:
    """(Cache-Control, Vary-or-None) for a cacheable 200/304.

    Honesty rules (deploy/DEPLOY.md "Edge caching"):

    * ``max_age_s == 0`` → ``no-cache`` — edges may STORE but must
      revalidate every serve; with free 304s that is the safe default
      posture (every repeat view costs one conditional round-trip,
      never a render).
    * ACL-gated images are ``private`` and vary on the session-bearing
      header, so a shared cache can never serve one session's entry to
      another; public images are ``public`` with NO Vary (the
      cookie-blind entry is safe for everyone, and varying would
      shatter the edge's hit rate per-user for no protection).
    """
    scope = "private" if acl_gated else "public"
    if max_age_s <= 0:
        cc = f"{scope}, no-cache"
    else:
        cc = f"{scope}, max-age={int(max_age_s)}"
    vary = session_cookie if acl_gated else None
    return cc, vary
