"""One ordered shutdown hook chain for the device-free signal path.

PR 4 gave ``run_app``/``sidecar_main`` a flight-recorder dump on
SIGTERM; the warm-state tier adds a snapshot.  Two ad-hoc calls in two
signal handlers is how one of them silently stops running, so both now
route through this chain: hooks run IN ORDER (snapshot first — it
captures serving state while services are still live; the black-box
dump last — it must exist even if everything before it wedged), and
every hook is guarded so one failing never skips the rest.  ``run``
itself never raises: it is called from signal handlers and ``finally``
blocks where an escape would abort the teardown it exists to protect.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Tuple

log = logging.getLogger("omero_ms_image_region_tpu.shutdown")


class ShutdownChain:
    """Ordered, guarded, once-only shutdown hooks."""

    def __init__(self):
        self._hooks: List[Tuple[str, Callable[[], object]]] = []
        self._ran = False
        self._lock = threading.Lock()

    def add(self, name: str, fn: Callable[[], object]) -> None:
        self._hooks.append((name, fn))

    def run(self, reason: str = "") -> List[Tuple[str, bool]]:
        """Run every hook in registration order; returns
        ``[(name, ok)]``.  Re-entry (SIGTERM then SIGINT in quick
        succession — each starts a chain thread — or signal then
        finally) is a no-op: the claim is taken under a lock, so each
        hook runs at most once process-wide."""
        with self._lock:
            if self._ran:
                return []
            self._ran = True
        results: List[Tuple[str, bool]] = []
        for name, fn in self._hooks:
            try:
                fn()
                results.append((name, True))
            except Exception:
                # A failing snapshot must never skip the flight dump
                # (and vice versa); log and continue.
                try:
                    log.warning("shutdown hook %r failed (%s); "
                                "continuing the chain", name, reason,
                                exc_info=True)
                except Exception:
                    pass
                results.append((name, False))
        return results


def build_shutdown_chain(config, services=None,
                         fleet_router=None) -> ShutdownChain:
    """The standard chain: fleet quiesce first (stop accepting routes
    — flag flips only, signal-safe — so the snapshot below captures a
    settled shard map, and the whole-process exit is at least an
    ORDERLY one: in-flight work keeps draining while the chain runs),
    then the
    warm-state snapshot (serving state is still live), the
    flight-recorder dump last (the black box must land even if the
    snapshot wedged).  ``services`` None (frontend proxy) has no warm
    state to snapshot — the chain is just the dump."""
    from ..utils import telemetry

    chain = ShutdownChain()
    if fleet_router is not None:
        def quiesce():
            # Bool flips only: this runs on the signal-time chain
            # thread, off-loop — it must not await, lock, or touch the
            # router's loop-confined queues.  The lanes observe the
            # flags at their next pop; the per-member drain (with its
            # settle + warm handoff) remains the /admin/drain op's
            # job — at whole-process SIGTERM there is no surviving
            # member to hand TO.
            for name in fleet_router.order:
                fleet_router.members[name].draining = True
                telemetry.DRAIN.set_state(name, "draining")
            telemetry.FLIGHT.record(
                "drain.phase", member="*", phase="quiesce-all",
                reason="shutdown")
        chain.add("fleet-quiesce", quiesce)
    warmstate = getattr(services, "warmstate", None)
    if warmstate is not None:
        chain.add("warmstate-snapshot", warmstate.snapshot_now)
    exec_cache = getattr(getattr(services, "renderer", None),
                         "exec_cache", None)
    if exec_cache is not None:
        # In-flight executable captures get a bounded window to land —
        # a compile serialized now is a compile the next life skips.
        chain.add("execcache-drain",
                  lambda: exec_cache.drain(timeout_s=5.0))

    def dump():
        telemetry.FLIGHT.dump(config.telemetry.flight_recorder_dir,
                              "shutdown")

    chain.add("flight-dump", dump)
    return chain
