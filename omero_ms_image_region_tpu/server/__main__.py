"""``python -m omero_ms_image_region_tpu.server`` — service launcher
(≙ the Vert.x ``io.vertx.core.Launcher`` main class, ``build.gradle:10``)."""

from .app import main

main()
