"""Resource-pressure governor + brownout ladder: degrade by choice
before degrading by accident.

Every fault-tolerance layer so far (breakers, shedding, failover —
PR 3/PR 8) reacts to a component that is DEAD.  Nothing reacted to a
component that is merely *drowning*: HBM occupancy creeping toward the
raw-cache budget, host RSS toward the cgroup limit, the disk byte tier
toward its low-water thrash point, queue depth toward the admission
cliff, the event loop lagging behind its own timers.  The reference
survives production behind nginx because a JVM that bloats gets
recycled (PAPER.md L0/L5); this module is the TPU build's cheaper
answer — notice the drowning EARLY and walk a configurable degradation
ladder so overload costs quality before it costs availability.

Mechanics:

* A periodic sampler (:class:`PressureGovernor.tick`, driven by an
  asyncio task at ``pressure.interval-s``) reads a fixed set of
  signals — HBM fraction from ``DeviceRawCache``, host RSS from
  ``/proc/self/status``, disk byte-cache fill, renderer/fleet queue
  depth, and the governor's own event-loop lag — and folds them into
  ONE level (``ok`` / ``elevated`` / ``critical``) with per-signal
  hysteresis (enter at the ``high`` watermark, exit only below
  ``low``), so a signal hovering at the boundary cannot flap the
  level.
* The **brownout ladder** is an ordered list of steps from
  :data:`KNOWN_STEPS`.  Under sustained ``elevated`` pressure the
  governor engages the next step every ``step-hold-ticks`` ticks;
  under ``critical`` it engages one step EVERY tick; after
  ``release-hold-ticks`` consecutive ``ok`` ticks it releases the last
  engaged step — so for ANY pressure trajectory the engaged set is
  always a PREFIX of the configured ladder, steps engage in order and
  release in exact reverse (the property test in
  ``tests/test_pressure.py`` pins this).
* Config validation (``server.config``) enforces the availability
  ordering invariant: ``shed_bulk`` must precede
  ``tighten_admission``, so interactive tile availability is never
  shed before bulk/projection work.

Consumers read the governor through the module-global
:func:`install`/:func:`active` pair (the ``utils.faultinject`` idiom),
so the hot path pays one ``is None`` check when the governor is off:

* ``services.prefetch.TilePrefetcher.paused`` / ``services.warmstate
  .WarmStateManager.paused`` — flipped by the ``pause_prefetch`` /
  ``pause_snapshots`` actuators;
* ``io.devicecache.DeviceRawCache.evict_to_fraction`` and the disk
  tier's ``evict_to_fraction`` — re-applied every tick while
  ``evict_caches`` is engaged (traffic refills what one evict freed);
* ``server.batcher.BatchingRenderer.set_lane_cap`` — ``cap_lanes``;
* ``server.handler`` — ``drop_quality`` caps interactive-tile JPEG
  quality, ``shed_bulk`` sheds full-plane/projection work with
  503 + Retry-After;
* ``server.admission.AdmissionController`` — ``tighten_admission``
  scales the effective queue bound down, so shedding becomes
  pressure-aware, not just depth-aware.

Every level transition and every ladder step engage/release is a
flight-recorder event and an ``imageregion_pressure_*`` series.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import telemetry

log = logging.getLogger("omero_ms_image_region_tpu.pressure")

# Ladder-step vocabulary; config validation rejects anything else.
KNOWN_STEPS = (
    "pause_prefetch",     # stop pan-ahead staging (frees link + HBM)
    "pause_snapshots",    # stop warm-state manifest writes (disk/CPU)
    "evict_caches",       # walk HBM + disk byte tier to low water
    "cap_lanes",          # bound concurrent group renders
    "drop_quality",       # lower interactive-tile JPEG quality
    "shed_bulk",          # 503 full-plane / z-projection work
    "tighten_admission",  # scale the admission queue bound down
)

LEVEL_OK, LEVEL_ELEVATED, LEVEL_CRITICAL = 0, 1, 2
LEVEL_NAMES = ("ok", "elevated", "critical")


def read_rss_mb() -> Optional[float]:
    """Host RSS in MB from ``/proc/self/status`` (no psutil in the
    image); None where /proc is unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return None


# cgroup v2 exposes the memory limit at memory.max ("max" = unlimited);
# v1 at memory/memory.limit_in_bytes (an absurdly large number =
# unlimited — kernels report PAGE_COUNTER_MAX there).
_CGROUP_V2_LIMIT = "/sys/fs/cgroup/memory.max"
_CGROUP_V1_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"
_CGROUP_UNLIMITED_BYTES = 1 << 60


def read_cgroup_memory_limit_mb(
        v2_path: str = _CGROUP_V2_LIMIT,
        v1_path: str = _CGROUP_V1_LIMIT) -> Optional[float]:
    """The container's memory limit in MB from the cgroup filesystem
    (v2 first, v1 fallback); None when unlimited or not in a cgroup."""
    for path in (v2_path, v1_path):
        try:
            with open(path) as f:
                raw = f.read().strip()
        except OSError:
            continue
        if raw == "max":
            return None
        try:
            limit = int(raw)
        except ValueError:
            continue
        if limit <= 0 or limit >= _CGROUP_UNLIMITED_BYTES:
            return None
        return limit / (1024.0 * 1024.0)
    return None


# Auto-wired host-RSS watermarks as fractions of the cgroup limit:
# enter elevated at 80% (the JVM-recycle class of bloat the reference
# survives behind nginx — PAPER.md L0 — caught BEFORE the OOM killer),
# release below 65%.
_RSS_HIGH_FRAC = 0.80
_RSS_LOW_FRAC = 0.65


def apply_cgroup_rss_defaults(config,
                              limit_mb: Optional[float] = None):
    """Default the host-RSS watermarks from the cgroup memory limit
    when the operator left them unset (``host-rss-high-mb: 0``).  The
    explicit knob always wins; with no cgroup limit the signal simply
    stays disabled, as before.  Returns the config for chaining."""
    if config.host_rss_high_mb > 0:
        return config            # explicit override: never touched
    limit = limit_mb if limit_mb is not None \
        else read_cgroup_memory_limit_mb()
    if limit is None or limit <= 0:
        return config
    config.host_rss_high_mb = round(limit * _RSS_HIGH_FRAC, 1)
    config.host_rss_low_mb = round(limit * _RSS_LOW_FRAC, 1)
    log.info("pressure: host-RSS watermarks defaulted from the cgroup "
             "limit (%.0f MB): high %.0f / low %.0f",
             limit, config.host_rss_high_mb, config.host_rss_low_mb)
    return config


@dataclass
class StepActuator:
    """What a ladder step DOES.  ``engage``/``release`` fire on the
    transition; ``while_engaged`` re-fires every tick the step stays
    engaged (eviction steps need re-applying — traffic refills what
    one pass freed).  All three are guarded: a failing actuator logs
    and never stalls the governor."""

    engage: Optional[Callable[[], None]] = None
    release: Optional[Callable[[], None]] = None
    while_engaged: Optional[Callable[[], None]] = None


class _SignalState:
    __slots__ = ("engaged",)

    def __init__(self):
        self.engaged = False


class PressureGovernor:
    """Tick-driven pressure sampler + brownout ladder walker.

    ``sources`` maps signal name -> zero-arg callable returning the
    current reading (None = signal unavailable this tick); thresholds
    come from the config block.  The governor itself is synchronous —
    :meth:`tick` is called by the asyncio runner in ``server.app`` and
    directly by tests (deterministic trajectories, no clock).
    """

    def __init__(self, config, actuators: Dict[str, StepActuator],
                 sources: Dict[str, Callable[[], Optional[float]]]):
        self.config = config
        self.ladder: Tuple[str, ...] = tuple(config.ladder)
        self.actuators = actuators
        self.sources = sources
        self.level = LEVEL_OK
        self.engaged = 0            # ladder prefix length
        self._hot_streak = 0
        self._ok_streak = 0
        self._signal_states: Dict[str, _SignalState] = {}
        # Set by the async runner (actual vs expected tick interval);
        # read back as the loop_lag_ms signal.
        self.loop_lag_ms = 0.0
        # Last published prefetch budget (change detection for the
        # flight event + gauge — the budget is a pure function of
        # level/ladder state, so publishing on transitions only keeps
        # the tape quiet).
        self._last_prefetch_budget = 1.0
        telemetry.PRESSURE.declare_steps(self.ladder)
        telemetry.PREFETCH.set_budget(1.0)

    # ---------------------------------------------------------- signals

    def _thresholds(self, name: str) -> Tuple[float, float]:
        c = self.config
        return {
            "hbm": (c.hbm_high, c.hbm_low),
            "host_rss_mb": (c.host_rss_high_mb, c.host_rss_low_mb),
            "disk": (c.disk_high, c.disk_low),
            "queue": (float(c.queue_high), float(c.queue_low)),
            "loop_lag_ms": (c.loop_lag_high_ms, c.loop_lag_low_ms),
        }.get(name, (0.0, 0.0))

    def _classify(self, name: str, value: float) -> int:
        """One signal's level with enter-high/exit-low hysteresis."""
        high, low = self._thresholds(name)
        if high <= 0:
            return LEVEL_OK           # signal disabled by config
        state = self._signal_states.setdefault(name, _SignalState())
        if value >= high * self.config.critical_factor:
            state.engaged = True
            return LEVEL_CRITICAL
        if value >= high:
            state.engaged = True
            return LEVEL_ELEVATED
        if state.engaged and value > low:
            # Between the watermarks: stays elevated until it falls
            # below low — the hysteresis that stops level flapping.
            return LEVEL_ELEVATED
        state.engaged = False
        return LEVEL_OK

    def sample(self) -> Dict[str, float]:
        samples: Dict[str, float] = {}
        for name, source in self.sources.items():
            try:
                value = source()
            except Exception:
                value = None
            if value is None:
                continue
            samples[name] = float(value)
            telemetry.PRESSURE.set_signal(name, float(value))
        return samples

    # ------------------------------------------------------------ ladder

    def _run_hook(self, step: str, hook: Optional[Callable]) -> None:
        if hook is None:
            return
        try:
            hook()
        except Exception:
            log.warning("pressure actuator %r failed", step,
                        exc_info=True)

    def _engage_next(self) -> None:
        step = self.ladder[self.engaged]
        self.engaged += 1
        actuator = self.actuators.get(step)
        if actuator is not None:
            self._run_hook(step, actuator.engage)
        telemetry.PRESSURE.set_step(step, True)
        telemetry.FLIGHT.record("pressure.step", step=step,
                                action="engage", engaged=self.engaged)
        log.warning("pressure brownout: engaged ladder step %r "
                    "(%d/%d)", step, self.engaged, len(self.ladder))

    def _release_last(self) -> None:
        self.engaged -= 1
        step = self.ladder[self.engaged]
        actuator = self.actuators.get(step)
        if actuator is not None:
            self._run_hook(step, actuator.release)
        telemetry.PRESSURE.set_step(step, False)
        telemetry.FLIGHT.record("pressure.step", step=step,
                                action="release", engaged=self.engaged)
        log.info("pressure recovered: released ladder step %r (%d/%d)",
                 step, self.engaged, len(self.ladder))

    def tick(self) -> int:
        """One governor evaluation; returns the folded level.  Called
        from the asyncio runner and directly by tests."""
        samples = self.sample()
        level = LEVEL_OK
        for name, value in samples.items():
            level = max(level, self._classify(name, value))
        if level != self.level:
            telemetry.FLIGHT.record(
                "pressure.level", level=LEVEL_NAMES[level],
                prev=LEVEL_NAMES[self.level],
                **{k: round(v, 3) for k, v in samples.items()})
            log.log(logging.WARNING if level > self.level
                    else logging.INFO,
                    "pressure level %s -> %s (%s)",
                    LEVEL_NAMES[self.level], LEVEL_NAMES[level],
                    {k: round(v, 2) for k, v in samples.items()})
        self.level = level
        telemetry.PRESSURE.set_level(level)
        if level >= LEVEL_ELEVATED:
            self._ok_streak = 0
            self._hot_streak += 1
            hold = (1 if level == LEVEL_CRITICAL
                    else self.config.step_hold_ticks)
            if (self.engaged < len(self.ladder)
                    and self._hot_streak >= hold):
                self._engage_next()
                self._hot_streak = 0
        else:
            self._hot_streak = 0
            self._ok_streak += 1
            if (self.engaged > 0
                    and self._ok_streak >= self.config.release_hold_ticks):
                self._release_last()
                self._ok_streak = 0
        # Re-apply sustained-effect steps (eviction) while engaged.
        for i in range(self.engaged):
            actuator = self.actuators.get(self.ladder[i])
            if actuator is not None and actuator.while_engaged:
                self._run_hook(self.ladder[i], actuator.while_engaged)
        # Publish the continuous prefetch budget on transitions: the
        # budget scales DOWN with the level before the binary
        # ``pause_prefetch`` step ever engages, and restores in exact
        # reverse on release (the pause/release pair is just the
        # budget's floor).
        budget = self.prefetch_budget()
        if budget != self._last_prefetch_budget:
            telemetry.PREFETCH.set_budget(budget)
            telemetry.FLIGHT.record(
                "prefetch.budget", scale=budget,
                prev=self._last_prefetch_budget,
                level=LEVEL_NAMES[level],
                paused=self.step_engaged("pause_prefetch"))
            self._last_prefetch_budget = budget
        return level

    # ------------------------------------------------- consumer queries

    def step_engaged(self, step: str) -> bool:
        try:
            return self.ladder.index(step) < self.engaged
        except ValueError:
            return False

    def engaged_steps(self) -> List[str]:
        return list(self.ladder[:self.engaged])

    def quality_cap(self) -> Optional[int]:
        """JPEG quality ceiling for interactive tiles while
        ``drop_quality`` is engaged; None = no cap."""
        if self.step_engaged("drop_quality"):
            return self.config.quality_cap
        return None

    def admission_scale(self) -> float:
        """Multiplier on the admission queue bound (``<= 1``);
        1.0 while ``tighten_admission`` is not engaged."""
        if self.step_engaged("tighten_admission"):
            return self.config.admission_scale
        return 1.0

    def bulk_shed_active(self) -> bool:
        return self.step_engaged("shed_bulk")

    def prefetch_budget(self) -> float:
        """The continuous prefetch budget scale in [0, 1]: a pure
        function of the folded level and the ``pause_prefetch`` ladder
        state, so it is symmetric by construction — whatever path the
        level took down, the identical path back up restores the
        identical budgets in reverse.

        * ok        -> 1.0
        * elevated  -> ``prefetch-budget-elevated`` (default 0.5)
        * critical  -> ``prefetch-budget-critical`` (default 0.25)
        * ``pause_prefetch`` engaged -> 0.0 (the ladder's binary pause
          is now the budget's floor, not a separate mechanism)

        Consumers (``services.prefetch.TilePrefetcher``) multiply this
        into their ``max_pending``, so speculative staging shrinks
        smoothly as the service starts drowning instead of running at
        full tilt until the ladder slams it off.
        """
        if self.step_engaged("pause_prefetch"):
            return 0.0
        if self.level >= LEVEL_CRITICAL:
            return getattr(self.config, "prefetch_budget_critical",
                           0.25)
        if self.level >= LEVEL_ELEVATED:
            return getattr(self.config, "prefetch_budget_elevated",
                           0.5)
        return 1.0

    def summary(self) -> str:
        """One-line /readyz annotation."""
        if self.engaged == 0 and self.level == LEVEL_OK:
            return "ok"
        steps = ",".join(self.engaged_steps()) or "-"
        return f"{LEVEL_NAMES[self.level]}; steps={steps}"

    # ------------------------------------------------------------ runner

    async def run(self) -> None:
        """Asyncio tick loop; measures its own scheduling lag as the
        ``loop_lag_ms`` signal (a loop that cannot keep a sleep on
        schedule is a loop that cannot keep responses on schedule)."""
        import asyncio

        interval = max(0.05, self.config.interval_s)
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(interval)
            lag_ms = max(0.0,
                         (time.monotonic() - t0 - interval) * 1000.0)
            # EWMA so one GC pause doesn't read as sustained lag.
            self.loop_lag_ms += 0.3 * (lag_ms - self.loop_lag_ms)
            self.tick()


def is_bulk(ctx) -> bool:
    """Bulk/projection classification for ``shed_bulk``: z-projection
    jobs and full-plane (no tile, no region) renders — the work class
    the ladder sheds FIRST, before any interactive degradation.

    Shape-mask requests (``ShapeMaskCtx``, identified by their
    ``shape_id``) are QoS-classed INTERACTIVE: a mask overlay is part
    of the viewer's pan loop, and it draws 1 fairness token like a
    tile — the mask-scraping loophole (no tile, no region used to
    read as bulk-or-crash here) closed with the session-model
    satellite of the autoscaler PR."""
    if getattr(ctx, "shape_id", None) is not None:
        return False
    return ctx.projection is not None or (
        ctx.tile is None and ctx.region is None)


def shed_bulk_under_pressure(ctx) -> None:
    """Brownout ladder "shed_bulk": while engaged, full-plane and
    z-projection work sheds with 503 + Retry-After BEFORE any
    read/stage cost — bulk work is the first availability sacrifice,
    always ahead of interactive tiles (the ladder-order invariant
    validated at config load).  Shared by the in-process and fleet
    handlers so the classification cannot drift.  Device-free (this
    module) so proxy-role frontends can call it too."""
    governor = active()
    if governor is None or not governor.bulk_shed_active() \
            or not is_bulk(ctx):
        return
    from .errors import OverloadedError
    telemetry.RESILIENCE.count_shed("pressure-bulk")
    telemetry.FLIGHT.record("admission.shed", reason="pressure-bulk",
                            image=ctx.image_id)
    raise OverloadedError(
        "bulk/projection work shed under resource pressure",
        retry_after_s=5.0)


def pressure_quality(quality: int, ctx) -> int:
    """Brownout ladder "drop_quality": cap INTERACTIVE tile JPEG
    quality while engaged (full-plane/bulk work is the shed step's
    problem, not this one's).  A capped render marks the ctx so the
    byte-cache write-back is skipped — lower-quality bytes must never
    be cached under the full-quality request key and outlive the
    brownout."""
    governor = active()
    if governor is None or ctx.tile is None:
        return quality
    cap = governor.quality_cap()
    if cap is not None and quality > cap:
        ctx._pressure_quality_capped = True
        return cap
    return quality


def build_sources(services=None, renderer=None, router=None,
                  governor_ref: Optional[list] = None
                  ) -> Dict[str, Callable[[], Optional[float]]]:
    """The standard signal set over a service stack.  Every source is
    duck-typed and None-safe, so one missing subsystem just drops its
    signal rather than failing the governor."""
    raw_cache = getattr(services, "raw_cache", None)
    caches = getattr(services, "caches", None)
    disk = getattr(caches, "disk", None)
    renderer = renderer or getattr(services, "renderer", None)

    def hbm() -> Optional[float]:
        if raw_cache is None or not getattr(raw_cache, "max_bytes", 0):
            return None
        return raw_cache.size_bytes / raw_cache.max_bytes

    def disk_frac() -> Optional[float]:
        if disk is None or not getattr(disk, "max_bytes", 0):
            return None
        return disk.size_bytes / disk.max_bytes

    def queue() -> Optional[float]:
        depth = None
        if router is not None:
            depth = router.queue_depth()
        elif hasattr(renderer, "queue_depth"):
            depth = renderer.queue_depth()
        return None if depth is None else float(depth)

    def loop_lag() -> Optional[float]:
        if governor_ref:
            return governor_ref[0].loop_lag_ms
        return None

    return {
        "hbm": hbm,
        "host_rss_mb": lambda: read_rss_mb(),
        "disk": disk_frac,
        "queue": queue,
        "loop_lag_ms": loop_lag,
    }


def build_actuators(config, services=None, renderer=None, router=None
                    ) -> Dict[str, StepActuator]:
    """The standard actuator set.  Flag-only steps (``drop_quality``,
    ``shed_bulk``, ``tighten_admission``) carry no actuator — their
    consumers query the governor directly.  ``router`` (a FleetRouter)
    lets the evict step demote hot-route replica sets first: replica
    HBM is the cheapest thing to give back under pressure (the ring
    owner still holds the plane)."""
    prefetcher = getattr(services, "prefetcher", None)
    warmstate = getattr(services, "warmstate", None)
    raw_cache = getattr(services, "raw_cache", None)
    disk = getattr(getattr(services, "caches", None), "disk", None)
    renderer = renderer or getattr(services, "renderer", None)
    actuators: Dict[str, StepActuator] = {}

    if prefetcher is not None:
        def _pf(paused):
            def hook():
                prefetcher.paused = paused
            return hook
        actuators["pause_prefetch"] = StepActuator(
            engage=_pf(True), release=_pf(False))

    if warmstate is not None:
        def _ws(paused):
            def hook():
                warmstate.paused = paused
            return hook
        actuators["pause_snapshots"] = StepActuator(
            engage=_ws(True), release=_ws(False))

    def evict():
        # Replica demotion FIRST: hot-route replica planes are
        # redundant by construction (the ring owner keeps its copy),
        # so shedding them turns the subsequent LRU pass into the one
        # that reclaims them — the "eviction deferred to cache
        # pressure" half of the hot-key lifecycle.
        if router is not None and hasattr(router, "shed_replicas"):
            try:
                router.shed_replicas()
            except Exception:
                log.debug("replica shed failed", exc_info=True)
        frac = config.evict_to_frac
        if raw_cache is not None and hasattr(raw_cache,
                                             "evict_to_fraction"):
            raw_cache.evict_to_fraction(frac)
        if disk is not None and hasattr(disk, "evict_to_fraction"):
            disk.evict_to_fraction(frac)

    if raw_cache is not None or disk is not None or router is not None:
        actuators["evict_caches"] = StepActuator(
            engage=evict, while_engaged=evict)

    if renderer is not None and hasattr(renderer, "set_lane_cap"):
        actuators["cap_lanes"] = StepActuator(
            engage=lambda: renderer.set_lane_cap(config.lane_cap),
            release=lambda: renderer.set_lane_cap(0))

    return actuators


# ------------------------------------------------------- module global

_INSTALLED: Optional[PressureGovernor] = None


def install(governor: Optional[PressureGovernor]
            ) -> Optional[PressureGovernor]:
    """Install the process-global governor (None uninstalls); the
    faultinject idiom — consumers pay one ``is None`` check when the
    layer is off."""
    global _INSTALLED
    _INSTALLED = governor
    return _INSTALLED


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = None


def active() -> Optional[PressureGovernor]:
    return _INSTALLED
