"""Crash-safe background pyramid-build jobs (PR 20 leg 2).

An unpyramided source (single-level store, bare TIFF) costs a full-res
read per tile at every zoom level; the reference ecosystem solves this
offline with Bio-Formats pyramid generation.  Here the server itself
builds the missing levels — batched device downsampling
(``ops.pyramid``, bit-exact vs the host reduction) written back as an
OME-NGFF group next to the source, which the ``PixelsService`` backend
sniff then picks up for every subsequent open: the normal serving path,
no special reader.

Crash safety is structural, not transactional:

* each level is written into a ``.lvl-<n>.tmp`` sibling and
  ``os.replace``d to ``<root>/<n>`` — a kill mid-level leaves only a
  tmp dir the next run deletes;
* the group markers (``.zgroup`` + multiscales ``.zattrs``) are written
  LAST — ``find_ngff``/``NgffZarrSource`` refuse a root without them,
  so a half-built pyramid is invisible to the serving path;
* every level derives deterministically from the source (integer
  device math, fixed chunk grid, zlib level 1), so a resumed build
  re-creates byte-identical levels and simply skips the ones already
  committed.

Jobs are QoS-classed BULK: while the pressure governor's shed_bulk
step is engaged the build parks between levels (state ``deferred``)
and interactive traffic keeps its devices.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("imageregion.jobs")

# Job states, closed vocabulary (mirrored by the telemetry actions).
QUEUED = "queued"
RUNNING = "running"
DEFERRED = "deferred"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

_TMP_PREFIX = ".lvl-"
_TMP_SUFFIX = ".tmp"


def pyramid_root(source_dir: str) -> str:
    """Where a source directory's built pyramid lives.  A ``*.zarr``
    child is exactly what ``io.ngff.find_ngff`` looks for, so the
    moment the group commits, ``PixelsService._sniff`` prefers it over
    the unpyramided TIFF for every new open."""
    return os.path.join(source_dir, "pyramid.zarr")


@dataclass
class PyramidJob:
    job_id: str
    source: str                      # image dir (or file) to read
    dest: str                        # NGFF root being built
    image_id: Optional[int] = None
    state: str = QUEUED
    levels_total: int = 0
    levels_done: int = 0
    resumed: bool = False
    error: Optional[str] = None
    t_submit: float = field(default_factory=time.time)
    t_done: Optional[float] = None
    _cancel: bool = False

    def to_doc(self) -> dict:
        return {
            "jobId": self.job_id,
            "imageId": self.image_id,
            "source": self.source,
            "dest": self.dest,
            "state": self.state,
            "levelsTotal": self.levels_total,
            "levelsDone": self.levels_done,
            "resumed": self.resumed,
            "error": self.error,
            "qosClass": "bulk",
            "submittedAt": self.t_submit,
            "doneAt": self.t_done,
        }


def _open_readable(path: str):
    """``ingest._open_source`` without the SystemExit (server context)."""
    try:
        from ..ingest import _open_source
        return _open_source(path)
    except SystemExit as e:
        raise ValueError(str(e)) from None


class PyramidJobManager:
    """Submit/track/run pyramid build jobs.

    One job runs at a time (the build is device- and IO-bound bulk
    work; concurrency would only fight interactive traffic harder).
    The runner task starts from ``server.app``'s robustness startup
    hook; the ``ingest.py pyramid`` CLI drives the identical
    ``run_job_sync`` code path without a loop.
    """

    def __init__(self, pixels_service=None,
                 chunk=(256, 256), min_level_size: int = 256,
                 compressor: Optional[str] = "zlib",
                 defer_poll_s: float = 0.25):
        self.pixels_service = pixels_service
        self.chunk = tuple(chunk)
        self.min_level_size = min_level_size
        self.compressor = compressor
        self.defer_poll_s = defer_poll_s
        self._jobs: Dict[str, PyramidJob] = {}
        self._order: List[str] = []
        self._queue: "asyncio.Queue[PyramidJob]" = None  # lazy (needs loop)
        self._seq = 0

    # ------------------------------------------------------------ submit

    def submit(self, source: str, image_id: Optional[int] = None
               ) -> PyramidJob:
        """Queue a build for ``source``.  Dedup: an unfinished job for
        the same destination is returned as-is (idempotent POST)."""
        source = os.path.abspath(source)
        if not os.path.exists(source):
            raise FileNotFoundError(source)
        dest = pyramid_root(source if os.path.isdir(source)
                            else os.path.dirname(source))
        for jid in reversed(self._order):
            j = self._jobs[jid]
            if j.dest == dest and j.state in (QUEUED, RUNNING, DEFERRED):
                return j
        self._seq += 1
        job = PyramidJob(job_id=f"pj-{self._seq}", source=source,
                         dest=dest, image_id=image_id)
        self._jobs[job.job_id] = job
        self._order.append(job.job_id)
        from ..utils import telemetry
        telemetry.WORKLOADS.count_job("submitted")
        telemetry.FLIGHT.record("pyramid.submit", job=job.job_id,
                                source=source)
        self._write_sidecar(job)
        if self._queue is not None:
            self._queue.put_nowait(job)
        return job

    def submit_image(self, image_id: int) -> PyramidJob:
        if self.pixels_service is None:
            raise ValueError("no pixels service configured")
        return self.submit(self.pixels_service.image_dir(image_id),
                           image_id=image_id)

    def get(self, job_id: str) -> Optional[PyramidJob]:
        return self._jobs.get(job_id)

    def jobs(self) -> List[PyramidJob]:
        return [self._jobs[j] for j in self._order]

    def cancel(self, job_id: str) -> bool:
        job = self._jobs.get(job_id)
        if job is None or job.state in (DONE, FAILED, CANCELLED):
            return False
        job._cancel = True
        return True

    def job_for_source(self, source: str) -> Optional[dict]:
        """Latest job touching ``source``'s pyramid — the explain
        plane's probe.  Falls back to the on-disk sidecar (a previous
        process's job) so a restarted frontend still answers."""
        source = os.path.abspath(source)
        dest = pyramid_root(source if os.path.isdir(source)
                            else os.path.dirname(source))
        for jid in reversed(self._order):
            if self._jobs[jid].dest == dest:
                return self._jobs[jid].to_doc()
        try:
            with open(dest + ".job.json") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------ runner

    async def run(self) -> None:
        """Background runner: drain the queue, one build at a time,
        parking between levels while bulk shed is engaged."""
        self._queue = asyncio.Queue()
        for jid in self._order:          # pre-loop submits (startup)
            if self._jobs[jid].state == QUEUED:
                self._queue.put_nowait(self._jobs[jid])
        while True:
            job = await self._queue.get()
            if job.state != QUEUED:
                continue
            await self._execute(job)

    async def _execute(self, job: PyramidJob) -> None:
        from ..utils import telemetry
        telemetry.WORKLOADS.job_started()
        job.state = RUNNING
        self._write_sidecar(job)
        try:
            cur, n_levels = await asyncio.to_thread(self._prepare, job)
            for n in range(n_levels):
                await self._wait_pressure(job)
                if job._cancel:
                    raise asyncio.CancelledError()
                cur = await asyncio.to_thread(
                    self._level_step, job, cur, n, n_levels)
            await asyncio.to_thread(self._commit, job, n_levels)
            job.state = DONE
            telemetry.WORKLOADS.count_job("completed")
            telemetry.FLIGHT.record("pyramid.done", job=job.job_id,
                                    levels=n_levels,
                                    resumed=int(job.resumed))
        except asyncio.CancelledError:
            job.state = CANCELLED
            telemetry.WORKLOADS.count_job("cancelled")
            if not job._cancel:      # runner torn down, not job cancel
                raise
        except Exception as e:
            job.state = FAILED
            job.error = str(e)
            telemetry.WORKLOADS.count_job("failed")
            log.warning("pyramid job %s failed: %s", job.job_id, e)
        finally:
            job.t_done = time.time()
            telemetry.WORKLOADS.job_finished()
            self._write_sidecar(job)

    def run_job_sync(self, job: PyramidJob) -> PyramidJob:
        """The CLI drive (``ingest.py pyramid``): same prepare / level /
        commit steps, no loop, no pressure parking (a CLI build is the
        operator's explicit foreground intent)."""
        from ..utils import telemetry
        telemetry.WORKLOADS.job_started()
        job.state = RUNNING
        self._write_sidecar(job)
        try:
            cur, n_levels = self._prepare(job)
            for n in range(n_levels):
                cur = self._level_step(job, cur, n, n_levels)
            self._commit(job, n_levels)
            job.state = DONE
            telemetry.WORKLOADS.count_job("completed")
        except Exception as e:
            job.state = FAILED
            job.error = str(e)
            telemetry.WORKLOADS.count_job("failed")
            raise
        finally:
            job.t_done = time.time()
            telemetry.WORKLOADS.job_finished()
            self._write_sidecar(job)
        return job

    async def _wait_pressure(self, job: PyramidJob) -> None:
        """Park while the shed_bulk ladder step is engaged — the build
        is bulk-classed and must never starve interactive renders."""
        from ..utils import telemetry
        from . import pressure
        deferred = False
        while True:
            gov = pressure.active()
            if gov is None or not gov.bulk_shed_active() \
                    or job._cancel:
                break
            if not deferred:
                deferred = True
                job.state = DEFERRED
                telemetry.WORKLOADS.count_job("deferred")
                telemetry.FLIGHT.record("pyramid.deferred",
                                        job=job.job_id,
                                        level=job.levels_done)
                self._write_sidecar(job)
            await asyncio.sleep(self.defer_poll_s)
        if deferred:
            job.state = RUNNING
            self._write_sidecar(job)

    # ------------------------------------------------------- build steps

    def _prepare(self, job: PyramidJob):
        """Open the source, load level 0, plan the level count, and
        clear any tmp debris a killed predecessor left behind."""
        from ..ingest import _gather_planes
        from ..ops.pyramid import n_pyramid_levels

        if os.path.exists(os.path.join(job.dest, ".zattrs")):
            # A committed pyramid is already serving; nothing to build.
            job.resumed = True
        src, _backend = _open_readable(job.source)
        try:
            planes = _gather_planes(src)
        finally:
            src.close()
        h, w = planes.shape[-2:]
        n_levels = n_pyramid_levels(h, w, self.min_level_size)
        job.levels_total = n_levels
        if os.path.isdir(job.dest):
            for name in os.listdir(job.dest):
                if name.startswith(_TMP_PREFIX) \
                        and name.endswith(_TMP_SUFFIX):
                    shutil.rmtree(os.path.join(job.dest, name),
                                  ignore_errors=True)
                    log.info("pyramid job %s: removed stale %s",
                             job.job_id, name)
            if any(c.isdigit() and os.path.exists(
                    os.path.join(job.dest, c, ".zarray"))
                    for c in os.listdir(job.dest)):
                job.resumed = True
        if job.resumed:
            from ..utils import telemetry
            telemetry.WORKLOADS.count_job("resumed")
        return planes, n_levels

    def _level_step(self, job: PyramidJob, cur, n: int, n_levels: int):
        """Write level ``n`` (unless already committed) and derive the
        next level's planes on device.  The tmp-dir + ``os.replace``
        pair is the atomic per-level commit."""
        from ..io.ngff import write_ngff_level_dir
        from ..ops.pyramid import downsample2_batch
        from ..utils import telemetry

        final = os.path.join(job.dest, str(n))
        if not os.path.exists(os.path.join(final, ".zarray")):
            tmp = os.path.join(job.dest,
                               f"{_TMP_PREFIX}{n}{_TMP_SUFFIX}")
            os.makedirs(job.dest, exist_ok=True)
            shutil.rmtree(tmp, ignore_errors=True)
            write_ngff_level_dir(tmp, cur, self.chunk, self.compressor)
            os.replace(tmp, final)
            telemetry.WORKLOADS.count_level_committed()
            telemetry.FLIGHT.record("pyramid.level", job=job.job_id,
                                    level=n, of=n_levels)
        job.levels_done = n + 1
        self._write_sidecar(job)
        if n + 1 < n_levels:
            return downsample2_batch(cur)
        return cur

    def _commit(self, job: PyramidJob, n_levels: int) -> None:
        """Write the group markers LAST — the build's commit point —
        then drop the source's cached open handle so the very next
        request re-sniffs and serves the pyramid."""
        from ..io.ngff import write_ngff_group_meta
        write_ngff_group_meta(job.dest, n_levels)
        if self.pixels_service is not None and job.image_id is not None:
            invalidate = getattr(self.pixels_service, "invalidate", None)
            if invalidate is not None:
                invalidate(job.image_id)

    # ----------------------------------------------------------- sidecar

    def _write_sidecar(self, job: PyramidJob) -> None:
        """Atomic job-state sidecar next to the dest root: status and
        explain survive a process restart (and the drill's kill)."""
        path = job.dest + ".job.json"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(job.to_doc(), f)
            os.replace(tmp, path)
        except OSError:
            log.debug("pyramid sidecar write failed", exc_info=True)
