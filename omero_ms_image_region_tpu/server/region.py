"""Region / pyramid geometry.

Pure-function re-expression of the region math in
``ImageRegionRequestHandler.java``: region selection (``getRegionDef``
``:789-832``), bounds truncation (``truncateRegionDef`` ``:751-758``),
pre-flip mirroring (``flipRegionDef`` ``:770-780``), plane-bounds clamping
(``checkPlaneDef`` ``:651-681``), and OMERO resolution-order inversion
(``setResolutionLevel`` ``:840-853``).

These are host-side and shape-producing: they decide exactly which raw
rectangle the IO layer reads and which padded bucket the device kernel
receives, so they stay in Python and stay pure (the reference's own tests
test them the same way; SURVEY.md section 4).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass
class RegionDef:
    """A rectangular region (= omeis.providers.re.data.RegionDef)."""

    x: int = 0
    y: int = 0
    width: int = 0
    height: int = 0

    def as_tuple(self) -> Tuple[int, int, int, int]:
        return (self.x, self.y, self.width, self.height)


def truncate_region(size_x: int, size_y: int, region: RegionDef) -> RegionDef:
    """Clamp width/height so the region fits the image
    (= truncateRegionDef, ``:751-758``)."""
    region.width = min(region.width, size_x - region.x)
    region.height = min(region.height, size_y - region.y)
    return region


def flip_region(size_x: int, size_y: int, region: RegionDef,
                flip_horizontal: bool, flip_vertical: bool) -> RegionDef:
    """Mirror the region origin for flipped rendering so the flipped output
    of the mirrored read equals the straight read of the requested region
    (= flipRegionDef, ``:770-780``)."""
    if flip_horizontal:
        region.x = size_x - region.width - region.x
    if flip_vertical:
        region.y = size_y - region.height - region.y
    return region


def clamp_region_to_plane(resolution_levels: Sequence[Sequence[int]],
                          resolution: Optional[int],
                          region: Optional[RegionDef]) -> Optional[RegionDef]:
    """Reset out-of-bounds width/height against the selected resolution's
    plane size (= checkPlaneDef, ``:651-681``)."""
    if region is None:
        return None
    res = resolution or 0
    size_x, size_y = resolution_levels[res][0], resolution_levels[res][1]
    if region.width + region.x > size_x:
        region.width = size_x - region.x
    if region.height + region.y > size_y:
        region.height = size_y - region.y
    return region


def get_region_def(
    resolution_levels: Sequence[Sequence[int]],
    resolution: Optional[int],
    tile: Optional[RegionDef],
    region: Optional[RegionDef],
    image_tile_size: Tuple[int, int],
    max_tile_length: int,
    flip_horizontal: bool = False,
    flip_vertical: bool = False,
) -> RegionDef:
    """Resolve the pixel region to read (= getRegionDef, ``:789-832``).

    Tile requests use the tile's own width/height if given, else the
    image's native tile size, clamped to ``max_tile_length``; the offset is
    in tile units.  Region requests are pixel-space.  Neither => the whole
    plane at the selected resolution (returned WITHOUT truncate/flip, as in
    the reference's early return ``:822-827``).
    """
    res = resolution or 0
    size_x, size_y = resolution_levels[res][0], resolution_levels[res][1]
    out = RegionDef()
    if tile is not None:
        tile_w, tile_h = tile.width, tile.height
        if tile_w == 0:
            tile_w = image_tile_size[0]
        if tile_w > max_tile_length:
            tile_w = max_tile_length
        if tile_h == 0:
            tile_h = image_tile_size[1]
        if tile_h > max_tile_length:
            tile_h = max_tile_length
        out.width = tile_w
        out.height = tile_h
        out.x = tile.x * tile_w
        out.y = tile.y * tile_h
    elif region is not None:
        out.x, out.y = region.x, region.y
        out.width, out.height = region.width, region.height
    else:
        out.x, out.y = 0, 0
        out.width, out.height = size_x, size_y
        return out
    truncate_region(size_x, size_y, out)
    flip_region(size_x, size_y, out, flip_horizontal, flip_vertical)
    return out


# NOTE: the reference's setResolutionLevel inversion (``level = n - res - 1``,
# ``:845-852``) is deliberately NOT reproduced here: it converts between the
# largest-first descriptions order and OMERO's smallest-first PixelBuffer
# level order.  Our PixelSource numbers levels largest-first like the
# descriptions, so the request resolution IS the read level (see
# ImageRegionHandler._get_region).
