"""Request contexts: URL-parameter parsing, validation, cache keys.

Re-expression of ``ImageRegionCtx.java:122-402`` and ``ShapeMaskCtx.java``.
Contexts are plain dataclasses (JSON-serializable — the analogue of the
reference's Jackson round-trip over the event bus, which its tests lock
down; SURVEY.md section 4).

Cache keys intentionally reproduce the reference's exact byte format —
``<java class name>:k=v...`` hashed with Guava-seeded SipHash-2-4
(``ImageRegionCtx.java:165-177``) and ``ome.model.roi.Mask:<id>:<color>``
(``ShapeMaskCtx.java:35-36,77-81``) — so a deployment can share a warm
Redis cache with the Java service it replaces.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, asdict
from typing import Dict, List, Mapping, Optional, Tuple

from ..models.rendering import Projection
from ..utils.siphash import guava_siphash24_hex
from .region import RegionDef

# Exact strings used by the reference for cache-key derivation.
_IMAGE_CTX_CLASS = "com.glencoesoftware.omero.ms.image.region.ImageRegionCtx"
_MASK_CLASS = "ome.model.roi.Mask"
_PIXELS_CLASS = "ome.model.core.Pixels"


class BadRequestError(ValueError):
    """Parameter validation failure -> HTTP 400 (the reference's
    IllegalArgumentException path, ``ImageRegionVerticle.java:163-188``)."""


def _require(params: Mapping[str, str], key: str) -> str:
    value = params.get(key)
    if value is None:
        raise BadRequestError(f"Missing parameter '{key}'")
    return value


def _parse_int(value: str, what: str = "parameter value") -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        raise BadRequestError(f"Incorrect format for {what} '{value}'")


@dataclass
class ImageRegionCtx:
    """Parsed ``render_image_region`` / ``render_image`` request."""

    image_id: int = 0
    z: int = 0
    t: int = 0
    tile: Optional[RegionDef] = None
    resolution: Optional[int] = None
    region: Optional[RegionDef] = None
    channels: Optional[List[int]] = None
    windows: Optional[List[Tuple[Optional[float], Optional[float]]]] = None
    colors: Optional[List[Optional[str]]] = None
    m: Optional[str] = None
    maps: Optional[List[dict]] = None
    compression_quality: Optional[float] = None
    projection: Optional[int] = None
    projection_start: Optional[int] = None
    projection_end: Optional[int] = None
    inverted_axis: Optional[bool] = None
    format: str = "jpeg"
    flip_horizontal: bool = False
    flip_vertical: bool = False
    cache_key: str = ""
    omero_session_key: Optional[str] = None

    # ------------------------------------------------------------- parsing

    @classmethod
    def from_params(cls, params: Mapping[str, str],
                    omero_session_key: Optional[str] = None
                    ) -> "ImageRegionCtx":
        ctx = cls(omero_session_key=omero_session_key)
        ctx.image_id = _parse_int(_require(params, "imageId"),
                                  "imageid parameter")
        ctx.z = _parse_int(_require(params, "theZ"))
        ctx.t = _parse_int(_require(params, "theT"))
        ctx._parse_tile(params.get("tile"))
        ctx._parse_region(params.get("region"))
        ctx._parse_channels(params.get("c"))
        ctx._parse_model(params.get("m"))
        q = params.get("q")
        if q is not None:
            try:
                ctx.compression_quality = float(q)
            except ValueError:
                raise BadRequestError(
                    f"Incorrect format for parameter value '{q}'")
        ia = params.get("ia")
        # The reference parses with Boolean.parseBoolean ("true"/"false");
        # webgateway sends 0/1, accepted here too.
        ctx.inverted_axis = (
            None if ia is None else ia.lower() in ("true", "1")
        )
        ctx._parse_projection(params.get("p"))
        maps = params.get("maps")
        if maps is not None:
            try:
                ctx.maps = json.loads(maps)
            except json.JSONDecodeError:
                raise BadRequestError(f"Malformed maps JSON '{maps}'")
        flip = (params.get("flip") or "").lower()
        ctx.flip_horizontal = "h" in flip
        ctx.flip_vertical = "v" in flip
        ctx.format = params.get("format") or "jpeg"
        ctx.cache_key = cls.create_cache_key(params)
        return ctx

    def _parse_tile(self, tile_string: Optional[str]) -> None:
        """``res,x,y[,w,h]`` (= getTileFromString, ``:232-245``)."""
        if tile_string is None:
            return
        parts = tile_string.split(",")
        try:
            self.tile = RegionDef(x=int(parts[1]), y=int(parts[2]))
            if len(parts) == 5:
                self.tile.width = int(parts[3])
                self.tile.height = int(parts[4])
            self.resolution = int(parts[0])
        except (ValueError, IndexError):
            raise BadRequestError(
                f"Improper tile string '{tile_string}'")

    def _parse_region(self, region_string: Optional[str]) -> None:
        """``x,y,w,h`` (= getRegionFromString, ``:252-273``)."""
        if region_string is None:
            return
        parts = region_string.split(",")
        if len(parts) != 4:
            raise BadRequestError(
                "Region string format incorrect. Should be 'x,y,w,h'")
        try:
            self.region = RegionDef(
                x=int(parts[0]), y=int(parts[1]),
                width=int(parts[2]), height=int(parts[3]),
            )
        except ValueError:
            raise BadRequestError(
                f"Improper number formatting in region string {region_string}")

    def _parse_channels(self, channel_info: Optional[str]) -> None:
        """``[-]i|min:max$RRGGBB,...`` (= getChannelInfoFromString,
        ``:281-326``; including its requirement that a ``|`` clause carries a
        ``$color`` — the reference NPEs into a 400 otherwise)."""
        if channel_info is None:
            return
        self.channels, self.windows, self.colors = [], [], []
        for chunk in channel_info.split(","):
            try:
                head, _, rest = chunk.partition("|")
                color = None
                window: Tuple[Optional[float], Optional[float]] = (None, None)
                if "$" in head:
                    head, _, color = head.partition("$")
                self.channels.append(int(head))
                if rest:
                    if "$" in rest:
                        window_str, _, color = rest.partition("$")
                    else:
                        # Reference behavior: window.split on a null window
                        raise ValueError("window clause without color")
                    lo, sep, hi = window_str.partition(":")
                    if sep:
                        window = (float(lo), float(hi))
                self.colors.append(color)
                self.windows.append(window)
            except ValueError:
                raise BadRequestError(f"Failed to parse channel '{chunk}'")

    def _parse_model(self, color_model: Optional[str]) -> None:
        """g -> greyscale, c -> rgb, else None (= ``:333-341``)."""
        if color_model == "g":
            self.m = "greyscale"
        elif color_model == "c":
            self.m = "rgb"
        else:
            self.m = None

    def _parse_projection(self, projection: Optional[str]) -> None:
        """``intmax|start:end`` etc. (= getProjectionFromString,
        ``:370-402``; malformed start/end silently ignored)."""
        if projection is None:
            return
        parts = projection.split("|")
        mode = {
            "intmax": int(Projection.MAXIMUM_INTENSITY),
            "intmean": int(Projection.MEAN_INTENSITY),
            "intsum": int(Projection.SUM_INTENSITY),
        }.get(parts[0])
        if mode is not None:
            self.projection = mode
        if len(parts) != 2:
            return
        lo, _, hi = parts[1].partition(":")
        # Malformed interval tolerated; a failure after start is parsed
        # leaves start set (matching the reference's single try block).
        try:
            self.projection_start = int(lo)
        except ValueError:
            return
        try:
            self.projection_end = int(hi)
        except ValueError:
            pass

    # ----------------------------------------------------------- cache key

    @staticmethod
    def create_cache_key(params: Mapping[str, str]) -> str:
        """SipHash-2-4 over the class name + sorted ``:k=v`` pairs
        (= createCacheKey, ``ImageRegionCtx.java:165-177``)."""
        pieces = [_IMAGE_CTX_CLASS]
        for key in sorted(set(params.keys())):
            pieces.append(f":{key}={params[key]}")
        return guava_siphash24_hex("".join(pieces))

    @staticmethod
    def pixels_metadata_cache_key(image_id: int) -> str:
        """Key for cached pixels metadata
        (= ``ImageRegionRequestHandler.java:317-318``)."""
        return f"{_PIXELS_CLASS}:Image:{image_id}"

    # --------------------------------------------------------------- wire

    def to_json(self) -> dict:
        d = asdict(self)
        d["tile"] = None if self.tile is None else self.tile.as_tuple()
        d["region"] = None if self.region is None else self.region.as_tuple()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ImageRegionCtx":
        d = dict(d)
        for key in ("tile", "region"):
            if d.get(key) is not None:
                d[key] = RegionDef(*d[key])
        if d.get("windows") is not None:
            d["windows"] = [tuple(w) for w in d["windows"]]
        return cls(**d)


@dataclass
class ShapeMaskCtx:
    """Parsed ``render_shape_mask`` request (= ShapeMaskCtx.java)."""

    shape_id: int = 0
    color: Optional[str] = None
    flip_horizontal: bool = False
    flip_vertical: bool = False
    omero_session_key: Optional[str] = None

    @classmethod
    def from_params(cls, params: Mapping[str, str],
                    omero_session_key: Optional[str] = None) -> "ShapeMaskCtx":
        ctx = cls(omero_session_key=omero_session_key)
        ctx.shape_id = _parse_int(_require(params, "shapeId"),
                                  "shapeId parameter")
        ctx.color = params.get("color")
        flip = (params.get("flip") or "").lower()
        ctx.flip_horizontal = "h" in flip
        ctx.flip_vertical = "v" in flip
        return ctx

    def cache_key(self) -> str:
        """``ome.model.roi.Mask:<id>:<color>`` (= CACHE_KEY_FORMAT,
        ``ShapeMaskCtx.java:35-36,77-81``; color "None" when unset matches
        the reference's null-formatted-as-"null" only in spirit — we emit
        the Python ``None`` the same way Java emits ``null``)."""
        color = "null" if self.color is None else self.color
        return f"{_MASK_CLASS}:{self.shape_id}:{color}"

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "ShapeMaskCtx":
        return cls(**d)
