"""Protocol layer: HTTP routes, request contexts, region math, orchestration.

Replaces the reference's L5-L2 (SURVEY.md section 1): the Vert.x verticles
and request handlers become asyncio host code; the only thing that leaves
this layer for the device is a raw tile plus packed settings.
"""
