"""Faithful CPU (numpy) implementation of the renderer semantics.

This is the project's stand-in for the reference's Java
``omeis.providers.re.Renderer`` — used as (a) the golden-value oracle the JAX
kernels are tested against, and (b) the CPU baseline ``bench.py`` compares
the TPU path to (SURVEY.md section 6: the reference publishes no numbers, so
the baseline is constructed here).

It deliberately shares no code with ``ops/``: quantization is computed value-
wise (no table folding), color/LUT/model application is branch-per-channel,
composition is an explicit accumulate — mirroring the structure of the Java
pipeline (quantize -> codomain chain -> color -> composite;
``ImageRegionRequestHandler.java:559`` and ``updateSettings`` ``:689-741``)
so a bug in the clever path can't hide in both.
"""

from __future__ import annotations

import numpy as np

from .models.rendering import Family, RenderingDef, RenderingModel, Projection


def _family_transform(x: np.ndarray, family: Family, k: float) -> np.ndarray:
    if family == Family.LINEAR:
        return x
    if family == Family.POLYNOMIAL:
        return np.sign(x) * np.power(np.abs(x), k)
    if family == Family.LOGARITHMIC:
        return np.log(np.maximum(x, 1.0))
    if family == Family.EXPONENTIAL:
        # Unreachable from quantize_ref, which evaluates the exponential
        # family in shifted form to avoid overflow; see its branch below.
        raise ValueError("exponential family is handled in quantize_ref")
    raise ValueError(family)


def quantize_ref(values: np.ndarray, window_start: float, window_end: float,
                 family: Family = Family.LINEAR, coefficient: float = 1.0,
                 cd_start: int = 0, cd_end: int = 255) -> np.ndarray:
    """Value-wise quantization (= QuantumStrategy for one channel)."""
    def _spow(v, k):
        return np.sign(v) * np.power(np.abs(v), k)

    x = np.clip(values.astype(np.float64),
                min(window_start, window_end),
                max(window_start, window_end))
    step = (values.astype(np.float64) >= window_end).astype(np.float64)
    if family == Family.EXPONENTIAL:
        k = coefficient
        pe = _spow(np.float64(window_end), k)
        es = np.exp(np.minimum(_spow(np.float64(window_start), k) - pe, 0.0))
        ex = np.exp(np.minimum(_spow(x, k) - pe, 0.0))
        den = 1.0 - es
        ratio = step if abs(den) < 1e-12 else (ex - es) / den
    else:
        fs = _family_transform(np.float64(window_start), family, coefficient)
        fe = _family_transform(np.float64(window_end), family, coefficient)
        fx = _family_transform(x, family, coefficient)
        den = fe - fs
        # Window degenerate under the family transform (ws == we, or e.g.
        # log over [0, 1]): all-or-nothing step on the raw value.
        ratio = step if abs(den) < 1e-12 else (fx - fs) / den
    ratio = np.clip(ratio, 0.0, 1.0)
    return np.round(cd_start + (cd_end - cd_start) * ratio).astype(np.int32)


def render_ref(raw: np.ndarray, rdef: RenderingDef,
               lut_provider=None) -> np.ndarray:
    """Render a raw [C, H, W] tile to u8[H, W, 4] RGBA.

    Follows the Java pipeline shape: per active channel quantize, apply the
    codomain chain, map through LUT or RGBA color, then composite.
    """
    C, H, W = raw.shape
    accum = np.zeros((H, W, 3), dtype=np.float64)
    greyscale = rdef.model == RenderingModel.GREYSCALE

    for c in range(C):
        cb = rdef.channel_bindings[c]
        if not cb.active:
            continue
        q = quantize_ref(
            raw[c], cb.input_start, cb.input_end, cb.family, cb.coefficient,
            rdef.quantum.cd_start, rdef.quantum.cd_end,
        )
        if cb.reverse_intensity:
            q = rdef.quantum.cd_end - q + rdef.quantum.cd_start
        if greyscale:
            # GreyScaleStrategy: first active channel only, value as grey.
            accum[..., 0] = q
            accum[..., 1] = q
            accum[..., 2] = q
            break
        lut_table = None
        if cb.lut is not None and lut_provider is not None:
            lut_table = lut_provider.get(cb.lut)
        if lut_table is not None:
            rgb = lut_table[q].astype(np.float64)
        else:
            color = np.array([cb.red, cb.green, cb.blue], dtype=np.float64)
            rgb = (q[..., None] / 255.0) * color
        accum += rgb * (cb.alpha / 255.0)

    rgb8 = np.clip(np.round(accum), 0, 255).astype(np.uint8)
    alpha = np.full((H, W, 1), 255, dtype=np.uint8)
    return np.concatenate([rgb8, alpha], axis=-1)


def flip_ref(src: np.ndarray, flip_horizontal: bool,
             flip_vertical: bool) -> np.ndarray:
    """Index-for-index port of the reference flip loop semantics
    (``ImageRegionRequestHandler.java:629-641``), used to prove the device
    flip matches."""
    if not flip_horizontal and not flip_vertical:
        return src
    if src is None:
        raise ValueError("Attempted to flip null image")
    H, W = src.shape[:2]
    if H == 0 or W == 0:
        raise ValueError("Attempted to flip image with 0 size")
    out = src.copy()
    y_idx = np.arange(H)
    x_idx = np.arange(W)
    dy = np.abs((H - y_idx - 1)) if flip_vertical else y_idx
    dx = np.abs((W - x_idx - 1)) if flip_horizontal else x_idx
    out[dy[:, None], dx[None, :]] = src
    return out


def project_ref(stack: np.ndarray, algorithm: Projection, start: int,
                end: int, stepping: int = 1,
                type_max: float = 255.0) -> np.ndarray:
    """Scalar-faithful projection (= ProjectionService loops, with the
    reference's inclusive-max / exclusive-mean-sum ranges and clamps)."""
    algorithm = Projection(algorithm)
    x = stack.astype(np.float64)
    if algorithm == Projection.MAXIMUM_INTENSITY:
        zs = range(start, end + 1, stepping)
        planes = [x[z] for z in zs]
        out = np.zeros_like(x[0])
        for p in planes:
            out = np.maximum(out, p)
        return out
    zs = list(range(start, end, stepping))
    out = np.zeros_like(x[0])
    for z in zs:
        out = out + x[z]
    if algorithm == Projection.MEAN_INTENSITY and zs:
        out = out / len(zs)
    return np.minimum(out, type_max)
