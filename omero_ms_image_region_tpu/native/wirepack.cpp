// Packed host->device staging: block bit-packed zigzag row deltas.
//
// The cold serving path is bounded by host->HBM wire bytes (a
// network-attached TPU moves ~20-30 MB/s; one 4-ch uint16 1024^2 tile
// is 8 MB raw).  Microscopy content is smooth signal + sensor noise:
// row deltas cost ~11.5 bits/sample instead of 16 (measured on the
// benchmark's content class), and a FIXED-WIDTH per-block layout keeps
// the decode fully vectorizable on the device (gather + shift + cumsum
// — no sequential entropy decode, which a TPU cannot do).
//
// Layout, per row of `width` samples, blocks of 32 along the row:
//   widths[r*bpr + b] = w  (bits per sample in block b; 0..17)
//   payload: each block occupies exactly 32*w bits (partial edge
//   blocks pad with zero samples), samples LSB-first at bit
//   offset(block) + j*w, where offset = 32 * cumsum(widths).
// Sample encoding: zigzag(delta) with delta[0] = row[0] (absolute).
//
// The device-side inverse lives in io/staging.py (unpack16_device).

#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// Returns words written, or -1 if words_cap is too small.
long long wirepack_pack16(const uint16_t* src, long long n_rows,
                          int width, uint8_t* widths_out,
                          uint32_t* words_out, long long words_cap) {
    if (width <= 0 || n_rows < 0) return -1;
    const int bpr = (width + 31) / 32;
    uint64_t accum = 0;
    int nbits = 0;
    long long w_idx = 0;
    for (long long r = 0; r < n_rows; ++r) {
        const uint16_t* row = src + r * width;
        for (int b = 0; b < bpr; ++b) {
            const int c0 = b * 32;
            const int c1 = std::min(c0 + 32, width);
            uint32_t zz[32];
            uint32_t all = 0;
            for (int c = c0; c < c1; ++c) {
                const int32_t d = (c == 0)
                    ? (int32_t)row[c]
                    : (int32_t)row[c] - (int32_t)row[c - 1];
                const uint32_t z = (d >= 0)
                    ? ((uint32_t)d << 1)
                    : (((uint32_t)(-d) << 1) - 1);
                zz[c - c0] = z;
                all |= z;
            }
            int w = 0;
            while (all >> w) ++w;                 // bit length of max
            widths_out[r * bpr + b] = (uint8_t)w;
            if (w == 0) continue;                 // block contributes 0 bits
            for (int j = 0; j < 32; ++j) {
                const uint32_t z = (j < c1 - c0) ? zz[j] : 0;
                accum |= (uint64_t)z << nbits;
                nbits += w;
                if (nbits >= 32) {
                    if (w_idx >= words_cap) return -1;
                    words_out[w_idx++] = (uint32_t)accum;
                    accum >>= 32;
                    nbits -= 32;
                }
            }
        }
    }
    if (nbits > 0) {
        if (w_idx >= words_cap) return -1;
        words_out[w_idx++] = (uint32_t)accum;
    }
    return w_idx;
}

}  // extern "C"
