// Sharded LRU byte cache — the process-local tile-cache tier.
//
// Native analogue of the reference's shared byte cache role (omero-ms-core
// RedisCacheVerticle + Hazelcast memo maps; SURVEY.md §2b).  The render
// path calls this from Python worker threads through ctypes, which drops
// the GIL for the duration of the call: gets/puts of megabyte tile bodies
// run concurrently across shards instead of serializing on the interpreter
// lock the way a pure-Python LRU does.
//
// C ABI only (no pybind11 in this image); every function is
// exception-free.  Values are copied in and out — the cache owns its
// memory, callers own theirs, and tc_free releases buffers returned by
// tc_get.

#include <cstdint>
#include <cstring>
#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#endif
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Entry {
    std::string key;
    std::vector<uint8_t> value;
};

class Shard {
  public:
    // list front = most recent; map points into the list.
    std::mutex mu;
    std::list<Entry> lru;
    std::unordered_map<std::string, std::list<Entry>::iterator> index;
    size_t bytes = 0;
    uint64_t hits = 0, misses = 0;
};

class TileCache {
  public:
    TileCache(size_t max_bytes, unsigned n_shards)
        : max_bytes_(max_bytes),
          shards_(n_shards ? n_shards : 1) {}

    Shard& shard_for(const std::string& key) {
        return shards_[hasher_(key) % shards_.size()];
    }

    size_t shard_budget() const { return max_bytes_ / shards_.size(); }

    size_t max_bytes_;
    std::vector<Shard> shards_;
    std::hash<std::string> hasher_;
};

void evict_to_budget(Shard& s, size_t budget) {
    while (s.bytes > budget && !s.lru.empty()) {
        Entry& victim = s.lru.back();
        s.bytes -= victim.value.size();
        s.index.erase(victim.key);
        s.lru.pop_back();
    }
}

}  // namespace

extern "C" {

void* tc_create(size_t max_bytes, unsigned n_shards) {
    return new (std::nothrow) TileCache(max_bytes, n_shards);
}

void tc_destroy(void* handle) {
    delete static_cast<TileCache*>(handle);
}

int tc_put(void* handle, const char* key_data, size_t key_len,
           const uint8_t* value, size_t value_len) {
    auto* cache = static_cast<TileCache*>(handle);
    if (!cache || !key_data) return -1;
    std::string key(key_data, key_len);
    Shard& s = cache->shard_for(key);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(key);
    if (it != s.index.end()) {
        s.bytes -= it->second->value.size();
        s.lru.erase(it->second);
        s.index.erase(it);
    }
    s.lru.push_front(Entry{key, {value, value + value_len}});
    s.index[key] = s.lru.begin();
    s.bytes += value_len;
    evict_to_budget(s, cache->shard_budget());
    return 0;
}

// Returns value length and a malloc'd copy in *out (caller frees with
// tc_free), or -1 on miss.
long long tc_get(void* handle, const char* key_data, size_t key_len,
                 uint8_t** out) {
    auto* cache = static_cast<TileCache*>(handle);
    if (!cache || !key_data || !out) return -1;
    std::string key(key_data, key_len);
    Shard& s = cache->shard_for(key);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(key);
    if (it == s.index.end()) {
        ++s.misses;
        return -1;
    }
    ++s.hits;
    s.lru.splice(s.lru.begin(), s.lru, it->second);  // mark most-recent
    const std::vector<uint8_t>& v = it->second->value;
    uint8_t* copy = static_cast<uint8_t*>(malloc(v.size() ? v.size() : 1));
    if (!copy) return -1;
    if (!v.empty()) memcpy(copy, v.data(), v.size());
    *out = copy;
    return static_cast<long long>(v.size());
}

void tc_free(uint8_t* p) { free(p); }

uint64_t tc_hits(void* handle) {
    auto* cache = static_cast<TileCache*>(handle);
    uint64_t n = 0;
    for (Shard& s : cache->shards_) {
        std::lock_guard<std::mutex> lock(s.mu);
        n += s.hits;
    }
    return n;
}

uint64_t tc_misses(void* handle) {
    auto* cache = static_cast<TileCache*>(handle);
    uint64_t n = 0;
    for (Shard& s : cache->shards_) {
        std::lock_guard<std::mutex> lock(s.mu);
        n += s.misses;
    }
    return n;
}

uint64_t tc_size_bytes(void* handle) {
    auto* cache = static_cast<TileCache*>(handle);
    uint64_t n = 0;
    for (Shard& s : cache->shards_) {
        std::lock_guard<std::mutex> lock(s.mu);
        n += s.bytes;
    }
    return n;
}

// ---------------------------------------------------------------- bit ops

// MSB-first 1-bit unpack (ome.util.PixelData "bit" order): n output bytes
// of 0/1 from ceil(n/8) packed input bytes.
void bits_unpack_msb(const uint8_t* src, size_t n_bits, uint8_t* dst) {
    for (size_t i = 0; i < n_bits; ++i) {
        dst[i] = (src[i >> 3] >> (7 - (i & 7))) & 1;
    }
}

// TIFF-variant LZW decode (TIFF 6.0 section 13: MSB-first codes, 9-bit
// start, ClearCode 256 / EOI 257, EARLY code-width bump).  Returns the
// decoded byte count, or -1 if dst_cap would overflow / the stream is
// malformed.  The table stores (prev_code, first_byte, suffix_byte,
// length) so no per-entry allocations happen; entries are emitted by
// walking the chain backwards into the output slot.
long long tiff_lzw_decode(const uint8_t* src, size_t n,
                          uint8_t* dst, size_t dst_cap) {
    const int MAXC = 4096;
    static thread_local int prev_of[4096];
    static thread_local uint8_t suffix[4096];
    static thread_local uint8_t first[4096];
    static thread_local int length[4096];
    for (int i = 0; i < 256; ++i) {
        prev_of[i] = -1;
        suffix[i] = first[i] = static_cast<uint8_t>(i);
        length[i] = 1;
    }
    int next = 258;
    int code_bits = 9;
    uint32_t buf = 0;
    int nbits = 0;
    int prev = -1;
    size_t out = 0;
    for (size_t i = 0; i < n; ++i) {
        buf = (buf << 8) | src[i];
        nbits += 8;
        while (nbits >= code_bits) {
            nbits -= code_bits;
            int code = (buf >> nbits) & ((1 << code_bits) - 1);
            if (code == 256) {              // ClearCode
                next = 258;
                code_bits = 9;
                prev = -1;
                continue;
            }
            if (code == 257) return static_cast<long long>(out);  // EOI
            int entry;
            if (prev < 0) {
                if (code >= 256) return -1;
                entry = code;
            } else if (code < next) {
                entry = code;
                if (next < MAXC) {
                    prev_of[next] = prev;
                    suffix[next] = first[entry];
                    first[next] = first[prev];
                    length[next] = length[prev] + 1;
                    ++next;
                }
            } else if (code == next && next < MAXC) {   // KwKwK
                prev_of[next] = prev;
                suffix[next] = first[prev];
                first[next] = first[prev];
                length[next] = length[prev] + 1;
                entry = next++;
            } else {
                return -1;
            }
            const size_t len = static_cast<size_t>(length[entry]);
            if (out + len > dst_cap) return -1;
            size_t pos = out + len;
            for (int c = entry; c >= 0; c = prev_of[c]) {
                dst[--pos] = suffix[c];
            }
            out += len;
            prev = entry;
            if (next >= (1 << code_bits) - 1 && code_bits < 12) {
                ++code_bits;
            }
        }
    }
    return static_cast<long long>(out);
}

// ---- mask overlay: one tile's blend, scalar and AVX2 forms ----------
//
// (x + 127) / 255 rounds x/255 to nearest for x >= 0.  The vector form
// uses the exact divide-by-255 identity q = (x + 1 + (x >> 8)) >> 8,
// verified exhaustively over every (base, fill, alpha) u8 triple —
// note the +1: the widespread (x + (x >> 8)) >> 8 variant is off by
// one at x = 255.

static void blend_plane_scalar(const uint8_t* bp, const uint8_t* gp,
                               const uint8_t* f, uint8_t* op,
                               size_t plane) {
    const uint32_t fr = f[0], fg = f[1], fb = f[2], fa = f[3];
    for (size_t i = 0; i < plane; ++i) {
        const uint32_t a = gp[i] ? fa : 0;
        const uint32_t ia = 255 - a;
        op[4 * i + 0] =
            static_cast<uint8_t>((bp[4 * i + 0] * ia + fr * a + 127)
                                 / 255);
        op[4 * i + 1] =
            static_cast<uint8_t>((bp[4 * i + 1] * ia + fg * a + 127)
                                 / 255);
        op[4 * i + 2] =
            static_cast<uint8_t>((bp[4 * i + 2] * ia + fb * a + 127)
                                 / 255);
        op[4 * i + 3] = bp[4 * i + 3];
    }
}

#if defined(__x86_64__) || defined(__i386__)
// 8 pixels per iteration: 8 mask bytes expand to 32 alpha bytes (the
// fill alpha on color lanes, 0 on the alpha lane — a = 0 reduces the
// formula to (b*255 + 127)/255 = b, so base alpha passes through with
// no special case), then the blend runs in u16 halves.  Bit-identical
// to the scalar loop (same integer formula, exact /255 identity);
// measured 7-8x on one core — the scalar form's per-pixel select and
// division resist auto-vectorization.
__attribute__((target("avx2")))
static void blend_plane_avx2(const uint8_t* bp, const uint8_t* gp,
                             const uint8_t* f, uint8_t* op,
                             size_t plane) {
    const __m128i rep_lo = _mm_setr_epi8(0, 0, 0, -128, 1, 1, 1, -128,
                                         2, 2, 2, -128, 3, 3, 3, -128);
    const __m128i rep_hi = _mm_setr_epi8(4, 4, 4, -128, 5, 5, 5, -128,
                                         6, 6, 6, -128, 7, 7, 7, -128);
    const __m256i fav = _mm256_set1_epi8(static_cast<char>(f[3]));
    uint32_t fw;
    std::memcpy(&fw, f, 4);
    const __m256i fillv =
        _mm256_set1_epi32(static_cast<int>(fw & 0x00FFFFFFu));
    const __m256i v255 = _mm256_set1_epi16(255);
    const __m256i v127 = _mm256_set1_epi16(127);
    const __m256i one16 = _mm256_set1_epi16(1);
    const __m256i zero = _mm256_setzero_si256();
    size_t i = 0;
    for (; i + 8 <= plane; i += 8) {
        __m128i m8 = _mm_loadl_epi64(
            reinterpret_cast<const __m128i*>(gp + i));
        __m128i on = _mm_xor_si128(
            _mm_cmpeq_epi8(m8, _mm_setzero_si128()), _mm_set1_epi8(-1));
        __m256i sel = _mm256_set_m128i(_mm_shuffle_epi8(on, rep_hi),
                                       _mm_shuffle_epi8(on, rep_lo));
        __m256i av = _mm256_and_si256(sel, fav);
        __m256i bv = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(bp + 4 * i));
        __m256i a_lo = _mm256_unpacklo_epi8(av, zero);
        __m256i a_hi = _mm256_unpackhi_epi8(av, zero);
        __m256i b_lo = _mm256_unpacklo_epi8(bv, zero);
        __m256i b_hi = _mm256_unpackhi_epi8(bv, zero);
        __m256i f_lo = _mm256_unpacklo_epi8(fillv, zero);
        __m256i f_hi = _mm256_unpackhi_epi8(fillv, zero);
        __m256i x_lo = _mm256_add_epi16(
            _mm256_add_epi16(
                _mm256_mullo_epi16(b_lo, _mm256_sub_epi16(v255, a_lo)),
                _mm256_mullo_epi16(f_lo, a_lo)), v127);
        __m256i x_hi = _mm256_add_epi16(
            _mm256_add_epi16(
                _mm256_mullo_epi16(b_hi, _mm256_sub_epi16(v255, a_hi)),
                _mm256_mullo_epi16(f_hi, a_hi)), v127);
        x_lo = _mm256_srli_epi16(
            _mm256_add_epi16(_mm256_add_epi16(x_lo, one16),
                             _mm256_srli_epi16(x_lo, 8)), 8);
        x_hi = _mm256_srli_epi16(
            _mm256_add_epi16(_mm256_add_epi16(x_hi, one16),
                             _mm256_srli_epi16(x_hi, 8)), 8);
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(op + 4 * i),
                            _mm256_packus_epi16(x_lo, x_hi));
    }
    if (i < plane)
        blend_plane_scalar(bp + 4 * i, gp + i, f, op + 4 * i, plane - i);
}
#endif  // x86

// Alpha-composite B mask fills over B RGBA tiles (straight alpha,
// integer math; ≙ the BufferedImage+IndexColorModel overlay a client of
// ShapeMaskRequestHandler.java:185-203 performs).  out may alias base.
void mask_overlay_u8(const uint8_t* base, const uint8_t* grids,
                     const uint8_t* fills, uint8_t* out,
                     int B, int H, int W) {
    const size_t plane = static_cast<size_t>(H) * W;
    void (*blend)(const uint8_t*, const uint8_t*, const uint8_t*,
                  uint8_t*, size_t) = blend_plane_scalar;
#if defined(__x86_64__) || defined(__i386__)
    if (__builtin_cpu_supports("avx2")) blend = blend_plane_avx2;
#endif
#pragma omp parallel for schedule(static)
    for (int b = 0; b < B; ++b) {
        blend(base + static_cast<size_t>(b) * plane * 4,
              grids + static_cast<size_t>(b) * plane,
              fills + static_cast<size_t>(b) * 4,
              out + static_cast<size_t>(b) * plane * 4, plane);
    }
}

// Flip a packed u32 image in place-free form (the reference's CPU flip,
// ImageRegionRequestHandler.java:616-642, as a single native pass).
void flip_u32(const uint32_t* src, uint32_t* dst, int height, int width,
              int flip_horizontal, int flip_vertical) {
    for (int y = 0; y < height; ++y) {
        int sy = flip_vertical ? height - 1 - y : y;
        const uint32_t* row = src + static_cast<size_t>(sy) * width;
        uint32_t* out = dst + static_cast<size_t>(y) * width;
        if (flip_horizontal) {
            for (int x = 0; x < width; ++x) out[x] = row[width - 1 - x];
        } else {
            memcpy(out, row, static_cast<size_t>(width) * 4);
        }
    }
}

}  // extern "C"
