// Baseline JFIF entropy encoder over device-produced JPEG coefficients.
//
// Native fast path for the Python reference in ../jfif.py — the two
// implement the same deterministic algorithm (ITU T.81 Annex K.2 optimal
// Huffman construction, canonical code assignment, 4:2:0 interleaved MCU
// scan, byte-stuffed bit packing) and must produce byte-identical streams;
// tests/test_jpeg.py asserts that equality.
//
// Replaces the serial half of the reference's CPU JPEG stage
// (LocalCompress.compressToStream, ImageRegionRequestHandler.java:580-582).
// The lossy half (DCT/quantization) runs on TPU (../ops/jpegenc.py).
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ----------------------------------------------------------- tables

const int kBaseLuma[64] = {
    16, 11, 10, 16, 24, 40, 51, 61, 12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56, 14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77, 24, 35, 55, 64, 81, 104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101, 72, 92, 95, 98, 112, 100, 103, 99};

const int kBaseChroma[64] = {
    17, 18, 24, 47, 99, 99, 99, 99, 18, 21, 26, 66, 99, 99, 99, 99,
    24, 26, 56, 99, 99, 99, 99, 99, 47, 66, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99};

void quant_tables(int quality, uint8_t qy[64], uint8_t qc[64]) {
  quality = std::max(1, std::min(100, quality));
  int scale = quality < 50 ? 5000 / quality : 200 - 2 * quality;
  for (int i = 0; i < 64; i++) {
    int a = (kBaseLuma[i] * scale + 50) / 100;
    int b = (kBaseChroma[i] * scale + 50) / 100;
    qy[i] = static_cast<uint8_t>(std::max(1, std::min(255, a)));
    qc[i] = static_cast<uint8_t>(std::max(1, std::min(255, b)));
  }
}

// Zigzag: flat index into a row-major 8x8 block per zigzag position,
// generated the same way as ops/jpegenc.py zigzag_order().
void zigzag_order(int zig[64]) {
  struct RC { int r, c; };
  std::vector<RC> order;
  for (int r = 0; r < 8; r++)
    for (int c = 0; c < 8; c++) order.push_back({r, c});
  std::sort(order.begin(), order.end(), [](const RC& a, const RC& b) {
    int sa = a.r + a.c, sb = b.r + b.c;
    if (sa != sb) return sa < sb;
    int ka = (sa % 2 == 0) ? a.c : a.r;
    int kb = (sb % 2 == 0) ? b.c : b.r;
    return ka < kb;
  });
  for (int i = 0; i < 64; i++) zig[i] = order[i].r * 8 + order[i].c;
}

// ----------------------------------------------------------- huffman K.2

struct HuffTable {
  int bits[33] = {0};       // bits[1..16] used after limiting
  std::vector<uint8_t> huffval;
  uint32_t code_of[256] = {0};
  int len_of[256] = {0};
};

void build_huffman(const int64_t freq_in[256], HuffTable* t) {
  int64_t freq[257];
  std::memcpy(freq, freq_in, sizeof(int64_t) * 256);
  freq[256] = 1;  // reserved: no real symbol gets the all-ones code
  int codesize[257] = {0};
  int others[257];
  std::fill(others, others + 257, -1);

  for (;;) {
    // v1: smallest nonzero frequency, ties -> largest symbol value.
    int v1 = -1, v2 = -1;
    int64_t f1 = INT64_MAX, f2 = INT64_MAX;
    for (int i = 0; i < 257; i++) {
      if (freq[i] <= 0) continue;
      if (freq[i] <= f1) { f1 = freq[i]; v1 = i; }
    }
    for (int i = 0; i < 257; i++) {
      if (freq[i] <= 0 || i == v1) continue;
      if (freq[i] <= f2) { f2 = freq[i]; v2 = i; }
    }
    if (v2 < 0) break;
    freq[v1] += freq[v2];
    freq[v2] = 0;
    codesize[v1]++;
    while (others[v1] != -1) { v1 = others[v1]; codesize[v1]++; }
    others[v1] = v2;
    codesize[v2]++;
    while (others[v2] != -1) { v2 = others[v2]; codesize[v2]++; }
  }

  for (int i = 0; i < 257; i++)
    if (codesize[i] > 0) t->bits[codesize[i]]++;

  // ADJUST_BITS (figure K.3).
  int i = 32;
  while (i > 16) {
    if (t->bits[i] > 0) {
      int j = i - 2;
      while (t->bits[j] == 0) j--;
      t->bits[i] -= 2;
      t->bits[i - 1] += 1;
      t->bits[j + 1] += 2;
      t->bits[j] -= 1;
    } else {
      i--;
    }
  }
  i = 16;
  while (t->bits[i] == 0) i--;
  t->bits[i] -= 1;

  // HUFFVAL ordered by (code length, symbol value); canonical codes.
  for (int len = 1; len <= 32; len++)
    for (int s = 0; s < 256; s++)
      if (codesize[s] == len) t->huffval.push_back(static_cast<uint8_t>(s));

  uint32_t code = 0;
  size_t k = 0;
  for (int len = 1; len <= 16; len++) {
    for (int n = 0; n < t->bits[len]; n++) {
      uint8_t sym = t->huffval[k++];
      t->code_of[sym] = code;
      t->len_of[sym] = len;
      code++;
    }
    code <<= 1;
  }
}

// ----------------------------------------------------------- bit writer

struct BitWriter {
  std::vector<uint8_t>& out;
  uint64_t acc = 0;
  int nbits = 0;
  explicit BitWriter(std::vector<uint8_t>& o) : out(o) {}
  inline void put(uint32_t code, int length) {
    if (length == 0) return;
    acc = (acc << length) | (code & ((1ull << length) - 1));
    nbits += length;
    while (nbits >= 8) {
      nbits -= 8;
      uint8_t byte = static_cast<uint8_t>((acc >> nbits) & 0xFF);
      out.push_back(byte);
      if (byte == 0xFF) out.push_back(0x00);
    }
    acc &= (1ull << nbits) - 1;
  }
  void flush() {
    if (nbits) {
      int pad = 8 - nbits;
      put((1u << pad) - 1, pad);
    }
  }
};

inline int category(int v) {
  unsigned a = v < 0 ? -v : v;
  int s = 0;
  while (a) { s++; a >>= 1; }
  return s;
}

inline uint32_t amplitude_bits(int v, int size) {
  return static_cast<uint32_t>(v >= 0 ? v : v + (1 << size) - 1);
}

// Per-block symbol record: DC category/value + AC (symbol, value) list.
struct BlockSyms {
  int dc_sym;
  int dc_val;
  int dc_abs;  // absolute DC (the next block's predictor)
  // packed (symbol << 16) | (value & 0xFFFF); at most 63 ACs + EOB.
  int n_ac;
  uint32_t ac[64];
};

// Sparse variant: the block is given as `n` (position, value) entries with
// strictly ascending zigzag positions — exactly what the device's
// sparse_pack emits.  Positions absent from the list are zero.  Returns
// false on a malformed buffer (n > 64, positions not strictly ascending
// or > 63) rather than trusting wire data into fixed-size arrays.
// Read the 18-bit entry at index j of the packed stream (MSB-first at
// bit 18j): 6-bit zigzag position << 12 | 12-bit two's-complement value.
// `stream` must be readable for 4 bytes from byte (18j)/8 — the encoder
// wrapper pads its host copy, so prefix fetches stay safe.
static inline uint32_t read_entry18(const uint8_t* stream, long long j) {
  long long bit = j * 18;
  const uint8_t* p = stream + (bit >> 3);
  int shift = static_cast<int>(bit & 7);
  uint32_t window = (static_cast<uint32_t>(p[0]) << 24)
                  | (static_cast<uint32_t>(p[1]) << 16)
                  | (static_cast<uint32_t>(p[2]) << 8)
                  | static_cast<uint32_t>(p[3]);
  return (window >> (32 - 18 - shift)) & 0x3FFFF;
}

static inline int entry_val(uint32_t field) {
  int v = static_cast<int>(field & 0xFFF);
  return v >= 2048 ? v - 4096 : v;
}

bool block_symbols_sparse(const uint8_t* stream, long long first, int n,
                          int pred, BlockSyms* bs,
                          int64_t* dc_freq, int64_t* ac_freq) {
  // Entries [first, first+n) of the 18-bit packed stream.
  if (n < 0 || n > 64) return false;
  int k = 0;
  int dc = 0;
  if (n > 0) {
    uint32_t f = read_entry18(stream, first);
    if ((f >> 12) == 0) { dc = entry_val(f); k = 1; }
  }
  int dc_diff = dc - pred;
  bs->dc_sym = category(dc_diff);
  bs->dc_val = dc_diff;
  bs->dc_abs = dc;
  dc_freq[bs->dc_sym]++;
  bs->n_ac = 0;
  int last = 0;
  for (; k < n; k++) {
    uint32_t f = read_entry18(stream, first + k);
    int p = static_cast<int>(f >> 12);
    if (p <= last || p > 63) return false;
    int run = p - last - 1;
    last = p;
    while (run >= 16) {
      bs->ac[bs->n_ac++] = (0xF0u << 16);
      ac_freq[0xF0]++;
      run -= 16;
    }
    int v = entry_val(f);
    uint32_t sym = (static_cast<uint32_t>(run) << 4) | category(v);
    bs->ac[bs->n_ac++] = (sym << 16) | (static_cast<uint32_t>(v) & 0xFFFF);
    ac_freq[sym]++;
  }
  if (last != 63) {
    bs->ac[bs->n_ac++] = 0;  // EOB
    ac_freq[0x00]++;
  }
  return true;
}

void block_symbols(const int16_t* block, int pred, BlockSyms* bs,
                   int64_t* dc_freq, int64_t* ac_freq) {
  int dc_diff = static_cast<int>(block[0]) - pred;
  bs->dc_sym = category(dc_diff);
  bs->dc_val = dc_diff;
  bs->dc_abs = block[0];
  dc_freq[bs->dc_sym]++;
  bs->n_ac = 0;
  int run = 0;
  int last = 0;  // index of last nonzero (1-based into block), 0 = none yet
  for (int i = 1; i < 64; i++) {
    if (block[i] == 0) continue;
    run = i - last - 1;
    last = i;
    while (run >= 16) {
      bs->ac[bs->n_ac++] = (0xF0u << 16);
      ac_freq[0xF0]++;
      run -= 16;
    }
    int v = block[i];
    uint32_t sym = (static_cast<uint32_t>(run) << 4) | category(v);
    bs->ac[bs->n_ac++] = (sym << 16) | (static_cast<uint32_t>(v) & 0xFFFF);
    ac_freq[sym]++;
  }
  if (last != 63) {
    bs->ac[bs->n_ac++] = 0;  // EOB
    ac_freq[0x00]++;
  }
}

void emit_marker(std::vector<uint8_t>& out, uint8_t tag,
                 const std::vector<uint8_t>& payload) {
  out.push_back(0xFF);
  out.push_back(tag);
  size_t n = payload.size() + 2;
  out.push_back(static_cast<uint8_t>(n >> 8));
  out.push_back(static_cast<uint8_t>(n & 0xFF));
  out.insert(out.end(), payload.begin(), payload.end());
}

// Shared framing + Huffman build + bit-packing over collected symbols.
long long emit_jfif(const std::vector<BlockSyms>& ysyms,
                    const std::vector<BlockSyms>& cbsyms,
                    const std::vector<BlockSyms>& crsyms,
                    const int64_t y_dcf[256], const int64_t y_acf[256],
                    const int64_t c_dcf[256], const int64_t c_acf[256],
                    int width, int height, int quality,
                    uint8_t* out_buf, size_t out_cap) {
  int h16 = (height + 15) / 16, w16 = (width + 15) / 16;
  int n_mcu = h16 * w16;

  uint8_t qy[64], qc[64];
  quant_tables(quality, qy, qc);
  int zig[64];
  zigzag_order(zig);

  HuffTable dc0, ac0, dc1, ac1;
  build_huffman(y_dcf, &dc0);
  build_huffman(y_acf, &ac0);
  build_huffman(c_dcf, &dc1);
  build_huffman(c_acf, &ac1);

  std::vector<uint8_t> out;
  out.reserve(static_cast<size_t>(n_mcu) * 96 + 1024);
  out.push_back(0xFF); out.push_back(0xD8);  // SOI
  emit_marker(out, 0xE0, {'J', 'F', 'I', 'F', 0, 1, 1, 0, 0, 1, 0, 1, 0, 0});
  {
    std::vector<uint8_t> p(65);
    p[0] = 0;
    for (int i = 0; i < 64; i++) p[1 + i] = qy[zig[i]];
    emit_marker(out, 0xDB, p);
    p[0] = 1;
    for (int i = 0; i < 64; i++) p[1 + i] = qc[zig[i]];
    emit_marker(out, 0xDB, p);
  }
  emit_marker(out, 0xC0, {8,
      static_cast<uint8_t>(height >> 8), static_cast<uint8_t>(height & 0xFF),
      static_cast<uint8_t>(width >> 8), static_cast<uint8_t>(width & 0xFF),
      3, 1, 0x22, 0, 2, 0x11, 1, 3, 0x11, 1});
  const HuffTable* dht_tables[4] = {&dc0, &ac0, &dc1, &ac1};
  const int dht_cls[4] = {0, 1, 0, 1};
  const int dht_id[4] = {0, 0, 1, 1};
  for (int k = 0; k < 4; k++) {
    const HuffTable* t = dht_tables[k];
    std::vector<uint8_t> p;
    p.push_back(static_cast<uint8_t>((dht_cls[k] << 4) | dht_id[k]));
    for (int i = 1; i <= 16; i++) p.push_back(static_cast<uint8_t>(t->bits[i]));
    p.insert(p.end(), t->huffval.begin(), t->huffval.end());
    emit_marker(out, 0xC4, p);
  }
  emit_marker(out, 0xDA, {3, 1, 0x00, 2, 0x11, 3, 0x11, 0, 63, 0});

  BitWriter bw(out);
  auto put_block = [&bw](const BlockSyms& bs, const HuffTable& dc,
                         const HuffTable& ac) {
    bw.put(dc.code_of[bs.dc_sym], dc.len_of[bs.dc_sym]);
    if (bs.dc_sym) bw.put(amplitude_bits(bs.dc_val, bs.dc_sym), bs.dc_sym);
    for (int i = 0; i < bs.n_ac; i++) {
      uint32_t sym = bs.ac[i] >> 16;
      int v = static_cast<int16_t>(bs.ac[i] & 0xFFFF);
      bw.put(ac.code_of[sym], ac.len_of[sym]);
      int size = sym & 0x0F;
      if (size) bw.put(amplitude_bits(v, size), size);
    }
  };
  int yi = 0;
  for (int m = 0; m < n_mcu; m++) {
    for (int k = 0; k < 4; k++) put_block(ysyms[yi++], dc0, ac0);
    put_block(cbsyms[m], dc1, ac1);
    put_block(crsyms[m], dc1, ac1);
  }
  bw.flush();
  out.push_back(0xFF); out.push_back(0xD9);  // EOI

  if (out.size() > out_cap)
    return -static_cast<long long>(out.size());
  std::memcpy(out_buf, out.data(), out.size());
  return static_cast<long long>(out.size());
}

}  // namespace

extern "C" {

// Encode one image's zigzagged raster-order coefficient blocks to JFIF.
// y: (h16*2)*(w16*2) blocks of 64 int16; cb, cr: h16*w16 blocks each,
// where h16 = ceil(height/16), w16 = ceil(width/16).  Returns the number
// of bytes written to out, or -needed if out_cap is too small, or -1 on
// invalid arguments.
long long jpeg_encode(const int16_t* y, const int16_t* cb, const int16_t* cr,
                      int width, int height, int quality,
                      uint8_t* out_buf, size_t out_cap) {
  if (width <= 0 || height <= 0 || !y || !cb || !cr || !out_buf) return -1;
  int h16 = (height + 15) / 16, w16 = (width + 15) / 16;
  int n_mcu = h16 * w16;
  int yw = w16 * 2;

  uint8_t qy[64], qc[64];
  quant_tables(quality, qy, qc);
  int zig[64];
  zigzag_order(zig);

  // Pass 1: symbols + frequencies in MCU scan order.
  std::vector<BlockSyms> ysyms(n_mcu * 4), cbsyms(n_mcu), crsyms(n_mcu);
  int64_t y_dcf[256] = {0}, y_acf[256] = {0};
  int64_t c_dcf[256] = {0}, c_acf[256] = {0};
  int ypred = 0, cbpred = 0, crpred = 0;
  int yi = 0;
  for (int my = 0; my < h16; my++) {
    for (int mx = 0; mx < w16; mx++) {
      const int yidx[4] = {
          (2 * my) * yw + 2 * mx, (2 * my) * yw + 2 * mx + 1,
          (2 * my + 1) * yw + 2 * mx, (2 * my + 1) * yw + 2 * mx + 1};
      for (int k = 0; k < 4; k++) {
        const int16_t* blk = y + static_cast<size_t>(yidx[k]) * 64;
        block_symbols(blk, ypred, &ysyms[yi++], y_dcf, y_acf);
        ypred = blk[0];
      }
      int ci = my * w16 + mx;
      const int16_t* cbb = cb + static_cast<size_t>(ci) * 64;
      const int16_t* crb = cr + static_cast<size_t>(ci) * 64;
      block_symbols(cbb, cbpred, &cbsyms[ci], c_dcf, c_acf);
      cbpred = cbb[0];
      block_symbols(crb, crpred, &crsyms[ci], c_dcf, c_acf);
      crpred = crb[0];
    }
  }

  return emit_jfif(ysyms, cbsyms, crsyms, y_dcf, y_acf, c_dcf, c_acf,
                   width, height, quality, out_buf, out_cap);
}

// Encode one image straight from the device's sparse wire buffer
// (ops/jpegenc.py sparse_pack layout: [total i32 LE | counts u8[nb] |
// packed 18-bit (pos << 12 | val) entries], blocks ordered luma raster,
// Cb raster, Cr raster).  `buf` may be a prefix fetch: any length >=
// 4 + nb + ceil(18*total/8) decodes — the caller (ctypes wrapper) pads
// its copy by 4 bytes so the 32-bit window reads at the tail stay in
// bounds.  Returns bytes written, -needed if out_cap is short, -1 on
// invalid arguments, -2 if the buffer overflowed `cap` (entries dropped;
// caller must take the dense path).
long long jpeg_encode_sparse(const uint8_t* buf, size_t buf_len,
                             int width, int height, int quality, int cap,
                             uint8_t* out_buf, size_t out_cap) {
  if (!buf || !out_buf || width <= 0 || height <= 0 || cap <= 0) return -1;
  int h16 = (height + 15) / 16, w16 = (width + 15) / 16;
  int n_mcu = h16 * w16;
  int nb_y = n_mcu * 4, nb_c = n_mcu;
  int nb = nb_y + 2 * nb_c;
  if (buf_len < 4 + static_cast<size_t>(nb)) return -1;

  int32_t total;
  std::memcpy(&total, buf, 4);
  if (total > cap) return -2;
  if (total < 0 ||
      buf_len < 4 + static_cast<size_t>(nb) +
                    (static_cast<size_t>(total) * 18 + 7) / 8) return -1;
  const uint8_t* counts = buf + 4;
  const uint8_t* stream = buf + 4 + nb;

  // Per-block entry offsets (prefix sum of counts, flat block order).
  std::vector<int> start(nb + 1);
  for (int b = 0; b < nb; b++) start[b + 1] = start[b] + counts[b];
  if (start[nb] != total) return -1;

  std::vector<BlockSyms> ysyms(nb_y), cbsyms(nb_c), crsyms(nb_c);
  int64_t y_dcf[256] = {0}, y_acf[256] = {0};
  int64_t c_dcf[256] = {0}, c_acf[256] = {0};
  int ypred = 0, cbpred = 0, crpred = 0;
  int yw = w16 * 2;
  int yi = 0;
  for (int my = 0; my < h16; my++) {
    for (int mx = 0; mx < w16; mx++) {
      const int yidx[4] = {
          (2 * my) * yw + 2 * mx, (2 * my) * yw + 2 * mx + 1,
          (2 * my + 1) * yw + 2 * mx, (2 * my + 1) * yw + 2 * mx + 1};
      for (int k = 0; k < 4; k++) {
        int b = yidx[k];
        if (!block_symbols_sparse(stream, start[b],
                                  start[b + 1] - start[b], ypred,
                                  &ysyms[yi++], y_dcf, y_acf))
          return -1;
        ypred = ysyms[yi - 1].dc_abs;
      }
      int ci = my * w16 + mx;
      int b = nb_y + ci;
      if (!block_symbols_sparse(stream, start[b],
                                start[b + 1] - start[b], cbpred,
                                &cbsyms[ci], c_dcf, c_acf))
        return -1;
      cbpred = cbsyms[ci].dc_abs;
      b = nb_y + nb_c + ci;
      if (!block_symbols_sparse(stream, start[b],
                                start[b + 1] - start[b], crpred,
                                &crsyms[ci], c_dcf, c_acf))
        return -1;
      crpred = crsyms[ci].dc_abs;
    }
  }
  return emit_jfif(ysyms, cbsyms, crsyms, y_dcf, y_acf, c_dcf, c_acf,
                   width, height, quality, out_buf, out_cap);
}

}  // extern "C"
