"""Native runtime pieces: sharded LRU tile cache + pixel bit ops.

C++ with a plain C ABI, loaded through ctypes (no pybind11 in this image).
The shared library is compiled on first import with g++ into
``_build/libtilecache.so`` next to this file; if no toolchain is available
the import raises ImportError and callers fall back to pure Python
(``services.cache.make_cache`` does exactly that).

ctypes calls release the GIL, so cache traffic from render worker threads
runs concurrently across shards — the point of having this tier in C++.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_SOURCE = os.path.join(_HERE, "tilecache.cpp")
_LIB_PATH = os.path.join(_BUILD_DIR, "libtilecache.so")
_JPEG_SOURCE = os.path.join(_HERE, "jpegenc.cpp")
_JPEG_LIB_PATH = os.path.join(_BUILD_DIR, "libjpegenc.so")
_JPEGDEC_SOURCE = os.path.join(_HERE, "jpegdec.cpp")
_JPEGDEC_LIB_PATH = os.path.join(_BUILD_DIR, "libjpegdec.so")
_JP2KT1_SOURCE = os.path.join(_HERE, "jp2kt1.cpp")
_JP2KT1_LIB_PATH = os.path.join(_BUILD_DIR, "libjp2kt1.so")
_WIREPACK_SOURCE = os.path.join(_HERE, "wirepack.cpp")
_WIREPACK_LIB_PATH = os.path.join(_BUILD_DIR, "libwirepack.so")
_BUILD_LOCK = threading.Lock()


def _compile_lib(source: str, lib_path: str) -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
        "-o", lib_path + ".tmp", source,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(lib_path + ".tmp", lib_path)


class _NativeLib:
    """Build-on-first-use loader for one shared library: double-checked
    lock, mtime-based staleness rebuild, cached first failure (so hot
    paths probing availability per batch don't re-spawn a doomed g++
    attempt every call), and per-lib ctypes prototype setup."""

    def __init__(self, source: str, lib_path: str, what: str,
                 configure) -> None:
        self.source = source
        self.lib_path = lib_path
        self.what = what
        self.configure = configure
        self.lib: Optional[ctypes.CDLL] = None
        self.error: Optional[str] = None

    def load(self) -> ctypes.CDLL:
        if self.lib is not None:
            return self.lib
        if self.error is not None:
            raise ImportError(self.error)
        with _BUILD_LOCK:
            if self.lib is not None:
                return self.lib
            if self.error is not None:
                raise ImportError(self.error)
            if (not os.path.exists(self.lib_path)
                    or os.path.getmtime(self.lib_path)
                    < os.path.getmtime(self.source)):
                try:
                    _compile_lib(self.source, self.lib_path)
                except (OSError, subprocess.CalledProcessError) as e:
                    self.error = f"{self.what} unavailable: {e}"
                    raise ImportError(self.error)
            lib = ctypes.CDLL(self.lib_path)
            self.configure(lib)
            self.lib = lib
            return lib


def _configure_tilecache(lib: ctypes.CDLL) -> None:
    lib.tc_create.restype = ctypes.c_void_p
    lib.tc_create.argtypes = [ctypes.c_size_t, ctypes.c_uint]
    lib.tc_destroy.argtypes = [ctypes.c_void_p]
    lib.tc_put.restype = ctypes.c_int
    lib.tc_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_size_t, ctypes.c_char_p,
                           ctypes.c_size_t]
    lib.tc_get.restype = ctypes.c_longlong
    lib.tc_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_size_t,
                           ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.tc_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    for fn in ("tc_hits", "tc_misses", "tc_size_bytes"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.bits_unpack_msb.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                    ctypes.c_char_p]
    lib.flip_u32.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_int, ctypes.c_int,
                             ctypes.c_int, ctypes.c_int]
    lib.mask_overlay_u8.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int]
    lib.tiff_lzw_decode.restype = ctypes.c_longlong
    lib.tiff_lzw_decode.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                    ctypes.c_void_p, ctypes.c_size_t]


def _configure_jpegenc(lib: ctypes.CDLL) -> None:
    lib.jpeg_encode.restype = ctypes.c_longlong
    lib.jpeg_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_size_t,
    ]
    lib.jpeg_encode_sparse.restype = ctypes.c_longlong
    lib.jpeg_encode_sparse.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_size_t,
    ]


def _configure_jpegdec(lib: ctypes.CDLL) -> None:
    lib.jpeg_decode_baseline.restype = ctypes.c_longlong
    lib.jpeg_decode_baseline.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]


def _configure_jp2kt1(lib: ctypes.CDLL) -> None:
    lib.jp2k_t1_decode.restype = ctypes.c_longlong
    lib.jp2k_t1_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p,
    ]


_TILECACHE = _NativeLib(_SOURCE, _LIB_PATH, "native tilecache",
                        _configure_tilecache)
_JPEGENC = _NativeLib(_JPEG_SOURCE, _JPEG_LIB_PATH,
                      "native jpeg encoder", _configure_jpegenc)
_JPEGDEC = _NativeLib(_JPEGDEC_SOURCE, _JPEGDEC_LIB_PATH,
                      "native jpeg decoder", _configure_jpegdec)
_JP2KT1 = _NativeLib(_JP2KT1_SOURCE, _JP2KT1_LIB_PATH,
                     "native jpeg2000 tier-1", _configure_jp2kt1)


def _configure_wirepack(lib: ctypes.CDLL) -> None:
    lib.wirepack_pack16.restype = ctypes.c_longlong
    lib.wirepack_pack16.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
    ]


_WIREPACK = _NativeLib(_WIREPACK_SOURCE, _WIREPACK_LIB_PATH,
                       "native wire packer", _configure_wirepack)


def wirepack_available() -> bool:
    try:
        _WIREPACK.load()
        return True
    except ImportError:
        return False


def wirepack_pack16(arr) -> "tuple":
    """Pack a C-contiguous uint16 array (rows = all leading dims) into
    (words u32[n], widths u8[n_rows*ceil(W/32)]).  See wirepack.cpp for
    the layout; the device inverse is io.staging.unpack16_device."""
    import numpy as np
    lib = _WIREPACK.load()
    arr = np.ascontiguousarray(arr, dtype=np.uint16)
    width = arr.shape[-1]
    n_rows = arr.size // max(width, 1)
    bpr = (width + 31) // 32
    widths = np.empty(n_rows * bpr, np.uint8)
    # Worst case: every block at 17 bits/sample x 32 slots (edge blocks
    # occupy full 32-sample slots), i.e. 17 words per block.
    cap = n_rows * bpr * 17 + 2
    words = np.empty(cap, np.uint32)
    n = lib.wirepack_pack16(arr.ctypes.data, n_rows, width,
                            widths.ctypes.data, words.ctypes.data, cap)
    if n < 0:
        raise RuntimeError("wirepack capacity underestimate (bug)")
    return words[:n].copy(), widths


def _load() -> ctypes.CDLL:
    return _TILECACHE.load()


def _load_jpeg() -> ctypes.CDLL:
    return _JPEGENC.load()


def _load_jpegdec() -> ctypes.CDLL:
    return _JPEGDEC.load()


def _load_jp2kt1() -> ctypes.CDLL:
    return _JP2KT1.load()


def jp2k_t1_decode(data: bytes, w: int, h: int, npasses: int,
                   msbs: int, orient: int, segsym: bool,
                   half_at_zero: bool):
    """EBCOT Tier-1 decode of one code-block (native mirror of
    ``io.jp2k._t1_decode``; GIL released for the whole block)."""
    import numpy as np
    lib = _load_jp2kt1()
    out = np.zeros((h, w), np.float64)
    rc = lib.jp2k_t1_decode(data, len(data), w, h, npasses, msbs,
                            orient, int(segsym), int(half_at_zero),
                            out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise ValueError("jp2k_t1_decode: invalid arguments")
    return out


class NativeLRUCache:
    """CacheTier over the C++ sharded LRU (drop-in for MemoryLRUCache)."""

    def __init__(self, max_bytes: int = 256 * 1024 * 1024,
                 shards: int = 16):
        lib = _load()
        self._lib = lib
        self._handle = lib.tc_create(max_bytes, shards)
        if not self._handle:
            raise MemoryError("tc_create failed")
        self.max_bytes = max_bytes

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.tc_destroy(handle)
            self._handle = None

    # -- sync face (executor threads; GIL released inside the C calls) ----

    def get_sync(self, key: str) -> Optional[bytes]:
        kb = key.encode()
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.tc_get(self._handle, kb, len(kb), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.tc_free(out)

    def set_sync(self, key: str, value: bytes) -> None:
        kb = key.encode()
        self._lib.tc_put(self._handle, kb, len(kb), value, len(value))

    # -- async face (CacheTier protocol) ----------------------------------

    async def get(self, key: str) -> Optional[bytes]:
        return self.get_sync(key)

    async def set(self, key: str, value: bytes) -> None:
        self.set_sync(key, value)

    # -- stats ------------------------------------------------------------

    @property
    def hits(self) -> int:
        return int(self._lib.tc_hits(self._handle))

    @property
    def misses(self) -> int:
        return int(self._lib.tc_misses(self._handle))

    @property
    def size_bytes(self) -> int:
        return int(self._lib.tc_size_bytes(self._handle))


def unpack_bits_msb(data: bytes, n_bits: int):
    """MSB-first 1-bit unpack to a u8 0/1 array (native fast path)."""
    import numpy as np
    lib = _load()
    out = np.empty(n_bits, dtype=np.uint8)
    lib.bits_unpack_msb(data, n_bits,
                        out.ctypes.data_as(ctypes.c_char_p))
    return out


def tiff_lzw_decode(data: bytes, dst_cap: int) -> bytes:
    """TIFF-variant LZW decode (native; GIL released for the whole
    stream).  Raises ValueError on malformed input or cap overflow."""
    lib = _load()
    out = ctypes.create_string_buffer(dst_cap)
    n = lib.tiff_lzw_decode(data, len(data), out, dst_cap)
    if n < 0:
        raise ValueError("malformed TIFF LZW stream (or output cap "
                         "exceeded)")
    return ctypes.string_at(out, n)   # single copy (raw[:n] would do two)


def mask_overlay_u8(base_rgba, mask_grids, fills):
    """Batched integer alpha-composite, OpenMP across the batch
    (GIL released for the whole blend)."""
    import numpy as np
    lib = _load()
    base = np.ascontiguousarray(base_rgba, dtype=np.uint8)
    grids = np.ascontiguousarray(mask_grids, dtype=np.uint8)
    f = np.ascontiguousarray(fills, dtype=np.uint8)
    if base.ndim != 4 or base.shape[-1] != 4:
        raise ValueError(f"base_rgba must be [B, H, W, 4], "
                         f"got {base.shape}")
    B, H, W, _ = base.shape
    # The C kernel trusts these shapes; mismatches would read/write out
    # of bounds where the numpy path raised a broadcast error.
    if grids.shape != (B, H, W):
        raise ValueError(f"mask_grids must be {(B, H, W)}, "
                         f"got {grids.shape}")
    if f.shape != (B, 4):
        raise ValueError(f"fills must be {(B, 4)}, got {f.shape}")
    out = np.empty_like(base)
    lib.mask_overlay_u8(
        base.ctypes.data_as(ctypes.c_void_p),
        grids.ctypes.data_as(ctypes.c_void_p),
        f.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        B, H, W)
    return out


class SparseOverflowError(ValueError):
    """The device wire buffer dropped entries (content denser than cap)."""


def jpeg_native_available() -> bool:
    """Eagerly probe (and build) the native encoder.

    The module-level symbols exist whether or not a toolchain does —
    compilation is deferred to first use — so ``import`` success is NOT a
    native-availability signal.  Fallback decisions must call this.
    """
    try:
        _load_jpeg()
        return True
    except ImportError:
        return False


def jpeg_encode_native(y, cb, cr, width: int, height: int,
                       quality: int) -> bytes:
    """Entropy-encode device JPEG coefficients to a JFIF stream (C++).

    ``y``/``cb``/``cr`` are the int16 zigzagged raster-order block arrays of
    :func:`..ops.jpegenc.packed_to_jpeg_coefficients` for ONE image.  The
    GIL is released inside the call, so a thread pool encodes a whole tile
    batch concurrently.
    """
    import numpy as np
    lib = _load_jpeg()
    y = np.ascontiguousarray(y, dtype=np.int16)
    cb = np.ascontiguousarray(cb, dtype=np.int16)
    cr = np.ascontiguousarray(cr, dtype=np.int16)
    h16, w16 = (height + 15) // 16, (width + 15) // 16
    if (y.size != h16 * w16 * 4 * 64 or cb.size != h16 * w16 * 64
            or cr.size != cb.size):
        raise ValueError(
            f"coefficient sizes {y.size}/{cb.size}/{cr.size} do not match "
            f"a {w16}x{h16}-MCU frame"
        )
    # emit_jfif buffers internally and returns -needed on a short cap, at
    # the price of a full re-encode — so start at a safe worst case
    # (~4 bytes/coefficient covers even max-entropy tiles).
    cap = (y.size + cb.size + cr.size) * 4 + 4096
    while True:
        out = ctypes.create_string_buffer(cap)
        n = lib.jpeg_encode(
            y.ctypes.data, cb.ctypes.data, cr.ctypes.data,
            width, height, quality, out, cap,
        )
        if n >= 0:
            return out.raw[:n]
        if n == -1:
            raise ValueError("jpeg_encode: invalid arguments")
        cap = -n


def jpeg_encode_sparse_native(buf, width: int, height: int, quality: int,
                              cap: int) -> bytes:
    """JFIF-encode one tile straight from the device sparse wire buffer.

    ``buf`` is the u8[...] row from ``ops.jpegenc.render_to_jpeg_sparse``.
    Raises :class:`SparseOverflowError` when the tile's coefficient density
    exceeded ``cap`` and the dense path must be taken instead.
    """
    import numpy as np
    lib = _load_jpeg()
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    true_len = buf.size
    # Pad so the decoder's 32-bit window reads at the 18-bit stream tail
    # stay in bounds (jpegenc.cpp read_entry18); prefix fetches
    # especially.  The TRUE length is what the decoder validates against
    # — counting the pad would let a truncated buffer decode its last
    # entry from zeros instead of erroring.
    buf = np.pad(buf, (0, 4))
    out_cap = buf.size * 4 + 65536
    while True:
        out = ctypes.create_string_buffer(out_cap)
        n = lib.jpeg_encode_sparse(
            buf.ctypes.data, true_len, width, height, quality, cap,
            out, out_cap,
        )
        if n >= 0:
            return out.raw[:n]
        if n == -2:
            raise SparseOverflowError(
                f"sparse buffer overflow (cap={cap})")
        if n == -1:
            raise ValueError("jpeg_encode_sparse: invalid arguments")
        out_cap = -n


def jpeg_decode_baseline(data: bytes, tables: "bytes | None"):
    """Decode one JPEG (optionally abbreviated, with a TIFF JPEGTables
    stream) to ``u8[h, w, ncomp]`` raw components.

    Native mirror of ``io.jpegdec.decode_baseline_jpeg`` — same scope
    (SOF0/1 baseline AND SOF2 progressive, sampling 1-2, DRI/RST,
    inter-scan table updates), GIL released for the whole decode.
    Raises ImportError when no toolchain built the library and
    ValueError on malformed/unsupported streams.
    """
    import numpy as np
    lib = _load_jpegdec()
    w = ctypes.c_int()
    h = ctypes.c_int()
    nc = ctypes.c_int()
    tb = tables or b""
    # First call with zero cap: the decoder sizes the frame from the
    # headers (before entropy decode), fills out_w/h/ncomp and returns
    # the cap-too-small code (-2; -1 = malformed).
    n = lib.jpeg_decode_baseline(data, len(data), tb, len(tb), None, 0,
                                 ctypes.byref(w), ctypes.byref(h),
                                 ctypes.byref(nc))
    if n != -2:
        raise ValueError("malformed or unsupported JPEG stream")
    need = w.value * h.value * nc.value
    out = np.empty(need, np.uint8)
    n2 = lib.jpeg_decode_baseline(data, len(data), tb, len(tb),
                                  out.ctypes.data_as(ctypes.c_void_p),
                                  out.size, ctypes.byref(w),
                                  ctypes.byref(h), ctypes.byref(nc))
    if n2 != need:
        raise ValueError("malformed or unsupported JPEG stream")
    return out.reshape(h.value, w.value, nc.value)


def flip_u32(packed, flip_horizontal: bool, flip_vertical: bool):
    """Native single-pass flip of a u32[H, W] packed image."""
    import numpy as np
    lib = _load()
    src = np.ascontiguousarray(packed, dtype=np.uint32)
    h, w = src.shape
    dst = np.empty_like(src)
    lib.flip_u32(src.ctypes.data, dst.ctypes.data, h, w,
                 int(flip_horizontal), int(flip_vertical))
    return dst
