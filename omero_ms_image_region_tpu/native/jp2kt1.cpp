// EBCOT Tier-1 decoder (ITU-T T.800) — native mirror of the Python
// implementation in io/jp2k.py::_t1_decode (MQ coder per Annex C,
// significance-propagation / magnitude-refinement / cleanup passes,
// dead-zone mid-point reconstruction).  This is where ~95% of JPEG 2000
// decode time goes (per-coefficient per-bit-plane work); everything
// else (markers, tag trees, packet walk, inverse DWT) stays in
// Python/numpy.  Plain C ABI for ctypes; the GIL is released for the
// whole call.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct MqState {
  uint16_t qe;
  uint8_t nmps, nlps, sw;
};

constexpr MqState kMq[47] = {
    {0x5601, 1, 1, 1},   {0x3401, 2, 6, 0},   {0x1801, 3, 9, 0},
    {0x0AC1, 4, 12, 0},  {0x0521, 5, 29, 0},  {0x0221, 38, 33, 0},
    {0x5601, 7, 6, 1},   {0x5401, 8, 14, 0},  {0x4801, 9, 14, 0},
    {0x3801, 10, 14, 0}, {0x3001, 11, 17, 0}, {0x2401, 12, 18, 0},
    {0x1C01, 13, 20, 0}, {0x1601, 29, 21, 0}, {0x5601, 15, 14, 1},
    {0x5401, 16, 14, 0}, {0x5101, 17, 15, 0}, {0x4801, 18, 16, 0},
    {0x3801, 19, 17, 0}, {0x3401, 20, 18, 0}, {0x3001, 21, 19, 0},
    {0x2801, 22, 19, 0}, {0x2401, 23, 20, 0}, {0x2201, 24, 21, 0},
    {0x1C01, 25, 22, 0}, {0x1801, 26, 23, 0}, {0x1601, 27, 24, 0},
    {0x1401, 28, 25, 0}, {0x1201, 29, 26, 0}, {0x1101, 30, 27, 0},
    {0x0AC1, 31, 28, 0}, {0x09C1, 32, 29, 0}, {0x08A1, 33, 30, 0},
    {0x0521, 34, 31, 0}, {0x0441, 35, 32, 0}, {0x02A1, 36, 33, 0},
    {0x0221, 37, 34, 0}, {0x0141, 38, 35, 0}, {0x0111, 39, 36, 0},
    {0x0085, 40, 37, 0}, {0x0049, 41, 38, 0}, {0x0025, 42, 39, 0},
    {0x0015, 43, 40, 0}, {0x0009, 44, 41, 0}, {0x0005, 45, 42, 0},
    {0x0001, 45, 43, 0}, {0x5601, 46, 46, 0},
};

constexpr int kCtxRl = 17;
constexpr int kCtxUni = 18;
constexpr int kNCtx = 19;

struct Mq {
  const uint8_t* data;
  size_t len;
  size_t bp = 0;
  uint32_t c = 0;
  uint32_t a = 0;
  int ct = 0;
  uint8_t idx[kNCtx];
  uint8_t mps[kNCtx];

  uint8_t b(size_t k = 0) const {
    size_t p = bp + k;
    return p < len ? data[p] : 0xFF;
  }
  void bytein() {
    if (b() == 0xFF) {
      if (b(1) > 0x8F) {
        c += 0xFF00;
        ct = 8;
      } else {
        bp += 1;
        c += (uint32_t)b() << 9;
        ct = 7;
      }
    } else {
      bp += 1;
      c += (uint32_t)b() << 8;
      ct = 8;
    }
  }
  void init(const uint8_t* d, size_t n) {
    data = d;
    len = n;
    std::memset(idx, 0, sizeof(idx));
    std::memset(mps, 0, sizeof(mps));
    idx[kCtxUni] = 46;
    idx[kCtxRl] = 3;
    idx[0] = 4;
    bp = 0;
    c = (uint32_t)(n ? d[0] : 0xFF) << 16;
    bytein();
    c <<= 7;
    ct -= 7;
    a = 0x8000;
  }
  int decode(int cx) {
    const MqState& s = kMq[idx[cx]];
    uint32_t qe = s.qe;
    int d;
    a -= qe;
    if (((c >> 16) & 0xFFFF) < qe) {
      if (a < qe) {
        d = mps[cx];
        idx[cx] = s.nmps;
      } else {
        d = 1 - mps[cx];
        if (s.sw) mps[cx] = 1 - mps[cx];
        idx[cx] = s.nlps;
      }
      a = qe;
    } else {
      c -= qe << 16;
      if (a & 0x8000) return mps[cx];
      if (a < qe) {
        d = 1 - mps[cx];
        if (s.sw) mps[cx] = 1 - mps[cx];
        idx[cx] = s.nlps;
      } else {
        d = mps[cx];
        idx[cx] = s.nmps;
      }
    }
    do {
      if (ct == 0) bytein();
      a = (a << 1) & 0xFFFF;
      c <<= 1;
      ct -= 1;
    } while (!(a & 0x8000));
    return d;
  }
};

// Zero-coding context (T.800 Table D.1), h/v clamped to 2, d to 4.
inline int zc_context(int h, int v, int d, int orient) {
  int hh, vv;
  if (orient == 3) {  // HH
    int hv = h + v;
    if (d >= 3) return 8;
    if (d == 2) return hv >= 1 ? 7 : 6;
    if (d == 1) return hv >= 2 ? 5 : (hv == 1 ? 4 : 3);
    return hv >= 2 ? 2 : hv;
  }
  if (orient == 1) {  // HL swaps h and v
    hh = v;
    vv = h;
  } else {            // LL / LH
    hh = h;
    vv = v;
  }
  if (hh == 2) return 8;
  if (hh == 1) return vv >= 1 ? 7 : (d >= 1 ? 6 : 5);
  if (vv == 2) return 4;
  if (vv == 1) return 3;
  return d >= 2 ? 2 : d;
}

constexpr int kScCtx[3][3] = {{13, 12, 11}, {10, 9, 10}, {11, 12, 13}};
constexpr int kScXor[3][3] = {{1, 1, 1}, {1, 0, 0}, {0, 0, 0}};

}  // namespace

extern "C" {

// Decode one code-block.  out is f64[h*w] row-major signed values.
// Returns 0 on success, -1 on invalid arguments.
long long jp2k_t1_decode(const uint8_t* data, size_t len, int w, int h,
                         int npasses, int msbs, int orient, int segsym,
                         int half_at_zero, double* out) {
  if (!out || w <= 0 || h <= 0 || w > 4096 || h > 4096) return -1;
  std::memset(out, 0, sizeof(double) * (size_t)w * h);
  if (msbs <= 0 || npasses <= 0 || !data) return 0;

  const int W = w + 2, H = h + 2;
  std::vector<uint8_t> sig((size_t)W * H, 0);
  std::vector<int8_t> sgn((size_t)W * H, 0);
  std::vector<uint8_t> visited((size_t)W * H, 0);
  std::vector<uint8_t> refined((size_t)W * H, 0);
  std::vector<int64_t> mag((size_t)w * h, 0);
  Mq mq;
  mq.init(data, len);

  auto at = [W](int py, int px) { return (size_t)py * W + px; };
  auto nbr = [&](int py, int px, int* hn, int* vn, int* dn) {
    *hn = sig[at(py, px - 1)] + sig[at(py, px + 1)];
    *vn = sig[at(py - 1, px)] + sig[at(py + 1, px)];
    *dn = sig[at(py - 1, px - 1)] + sig[at(py - 1, px + 1)] +
          sig[at(py + 1, px - 1)] + sig[at(py + 1, px + 1)];
  };
  auto decode_sign = [&](int py, int px) -> int {
    int hc = sgn[at(py, px - 1)] + sgn[at(py, px + 1)];
    hc = hc > 1 ? 1 : (hc < -1 ? -1 : hc);
    int vc = sgn[at(py - 1, px)] + sgn[at(py + 1, px)];
    vc = vc > 1 ? 1 : (vc < -1 ? -1 : vc);
    int bit = mq.decode(kScCtx[hc + 1][vc + 1]);
    return (bit ^ kScXor[hc + 1][vc + 1]) ? -1 : 1;
  };

  int plane = msbs - 1;
  int pass_kind = 2;  // first pass is a cleanup
  for (int p = 0; p < npasses; ++p) {
    if (plane < 0) break;
    int64_t bitval = (int64_t)1 << plane;
    if (pass_kind == 0) {
      for (int y0 = 0; y0 < h; y0 += 4) {
        int ylim = y0 + 4 < h ? y0 + 4 : h;
        for (int x = 0; x < w; ++x) {
          for (int y = y0; y < ylim; ++y) {
            int py = y + 1, px = x + 1;
            if (sig[at(py, px)]) continue;
            int hn, vn, dn;
            nbr(py, px, &hn, &vn, &dn);
            if (hn + vn + dn == 0) continue;
            visited[at(py, px)] = 1;
            if (mq.decode(zc_context(hn > 2 ? 2 : hn, vn > 2 ? 2 : vn,
                                     dn > 4 ? 4 : dn, orient))) {
              int s = decode_sign(py, px);
              sig[at(py, px)] = 1;
              sgn[at(py, px)] = (int8_t)s;
              mag[(size_t)y * w + x] = bitval;
            }
          }
        }
      }
      pass_kind = 1;
    } else if (pass_kind == 1) {
      for (int y0 = 0; y0 < h; y0 += 4) {
        int ylim = y0 + 4 < h ? y0 + 4 : h;
        for (int x = 0; x < w; ++x) {
          for (int y = y0; y < ylim; ++y) {
            int py = y + 1, px = x + 1;
            if (!sig[at(py, px)] || visited[at(py, px)]) continue;
            int ctx;
            if (!refined[at(py, px)]) {
              int hn, vn, dn;
              nbr(py, px, &hn, &vn, &dn);
              ctx = (hn + vn + dn) ? 15 : 14;
              refined[at(py, px)] = 1;
            } else {
              ctx = 16;
            }
            if (mq.decode(ctx)) mag[(size_t)y * w + x] |= bitval;
          }
        }
      }
      pass_kind = 2;
    } else {
      for (int y0 = 0; y0 < h; y0 += 4) {
        int ylim = y0 + 4 < h ? y0 + 4 : h;
        for (int x = 0; x < w; ++x) {
          int y = y0;
          if (ylim - y0 == 4) {
            bool runnable = true;
            for (int yy = y0; yy < ylim; ++yy) {
              int py = yy + 1, px = x + 1;
              if (sig[at(py, px)] || visited[at(py, px)]) {
                runnable = false;
                break;
              }
              int hn, vn, dn;
              nbr(py, px, &hn, &vn, &dn);
              if (hn + vn + dn) {
                runnable = false;
                break;
              }
            }
            if (runnable) {
              if (!mq.decode(kCtxRl)) {
                for (int yy = y0; yy < ylim; ++yy)
                  visited[at(yy + 1, x + 1)] = 0;
                continue;
              }
              int r2 = (mq.decode(kCtxUni) << 1) | mq.decode(kCtxUni);
              y = y0 + r2;
              int py = y + 1, px = x + 1;
              int s = decode_sign(py, px);
              sig[at(py, px)] = 1;
              sgn[at(py, px)] = (int8_t)s;
              mag[(size_t)y * w + x] = bitval;
              y += 1;
            }
          }
          for (; y < ylim; ++y) {
            int py = y + 1, px = x + 1;
            if (sig[at(py, px)] || visited[at(py, px)]) {
              visited[at(py, px)] = 0;
              continue;
            }
            int hn, vn, dn;
            nbr(py, px, &hn, &vn, &dn);
            if (mq.decode(zc_context(hn > 2 ? 2 : hn, vn > 2 ? 2 : vn,
                                     dn > 4 ? 4 : dn, orient))) {
              int s = decode_sign(py, px);
              sig[at(py, px)] = 1;
              sgn[at(py, px)] = (int8_t)s;
              mag[(size_t)y * w + x] = bitval;
            }
          }
        }
      }
      if (segsym) {
        for (int k = 0; k < 4; ++k) mq.decode(kCtxUni);
      }
      std::fill(visited.begin(), visited.end(), 0);
      plane -= 1;
      pass_kind = 0;
    }
  }

  int last_plane = plane + 1;
  double half = 0.0;
  if (last_plane > 0 || half_at_zero) {
    int lp = last_plane > 0 ? last_plane : 0;
    half = 0.5 * (double)((int64_t)1 << lp);
  }
  for (int y = 0; y < h; ++y) {
    for (int x = 0; x < w; ++x) {
      int64_t m = mag[(size_t)y * w + x];
      if (!m) continue;
      double v = (double)m + half;
      if (sgn[at(y + 1, x + 1)] < 0) v = -v;
      out[(size_t)y * w + x] = v;
    }
  }
  return 0;
}

}  // extern "C"
