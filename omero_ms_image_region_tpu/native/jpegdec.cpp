// JPEG decoder (ITU-T T.81) — the native fast path behind
// io/jpegdec.py (same scope: SOF0/1 baseline AND SOF2 progressive,
// 8-bit, 1..4 components, sampling 1-2, abbreviated streams with
// external JPEGTables, DRI/RST, progressive spectral selection +
// successive approximation with inter-scan DHT/DQT/DRI updates).
// Plain C ABI for ctypes; the GIL is released for the whole decode.
//
// Validation contract mirrors the Python decoder exactly (byte-parity
// tests depend on identical accept/reject behavior): frame-scaled
// block-visit budget, scan-script succession checks (DC-before-AC,
// Ah continuing the band's Al).
//
// Return contract (jpeg_decode_baseline):
//   >= 0  bytes written to out (h*w*ncomp, interleaved)
//   -1    malformed / unsupported stream
//   -2    out_cap too small; *out_w/*out_h/*out_ncomp are set, so the
//         caller sizes the buffer as w*h*ncomp and retries

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

constexpr int kZigzag[64] = {
    0,  1,  8, 16,  9,  2,  3, 10, 17, 24, 32, 25, 18, 11,  4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13,  6,  7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

struct Huff {
  // 16-bit left-aligned prefix -> value/length; len 0 = invalid.
  std::vector<uint8_t> val, len;
  bool present = false;
  bool build(const uint8_t* bits, const uint8_t* values, int nvals) {
    val.assign(65536, 0);
    len.assign(65536, 0);
    uint32_t code = 0;
    int k = 0;
    for (int length = 1; length <= 16; ++length) {
      for (int i = 0; i < bits[length - 1]; ++i) {
        if (k >= nvals) return false;
        uint32_t aligned = code << (16 - length);
        uint32_t span = 1u << (16 - length);
        if (aligned + span > 65536) return false;
        for (uint32_t j = 0; j < span; ++j) {
          val[aligned + j] = values[k];
          len[aligned + j] = (uint8_t)length;
        }
        ++code;
        ++k;
      }
      code <<= 1;
    }
    present = true;
    return true;
  }
};

struct Component {
  int ident = 0, h = 1, v = 1, tq = 0, td = 0, ta = 0;
};

struct Tables {
  int32_t quant[4][64];
  bool quant_present[4] = {false, false, false, false};
  Huff dc[4], ac[4];
  int restart_interval = 0;
};

struct Frame {
  int w = 0, h = 0, ncomp = 0;
  Component comp[4];
  bool present = false;
};

struct BitReader {
  const uint8_t* data;
  size_t len;
  size_t pos;
  uint64_t buf = 0;
  int nbits = 0;
  int marker = -1;  // -1: none seen

  void fill() {
    while (nbits <= 48) {
      if (marker >= 0 || pos >= len) {
        buf = (buf << 8) | 0xFF;  // T.81 F.2.2.5 pad bits
        nbits += 8;
        continue;
      }
      uint8_t b = data[pos];
      if (b == 0xFF) {
        uint8_t nxt = (pos + 1 < len) ? data[pos + 1] : 0xD9;
        if (nxt == 0x00) {
          pos += 2;
        } else {
          marker = nxt;  // RST handled by restart(), EOI/other stops
          continue;
        }
      } else {
        pos += 1;
      }
      buf = (buf << 8) | b;
      nbits += 8;
    }
  }
  inline uint32_t peek16() {
    if (nbits < 16) fill();
    return (uint32_t)((buf >> (nbits - 16)) & 0xFFFF);
  }
  inline void skip(int n) {
    nbits -= n;
    buf &= (nbits >= 64) ? ~0ull : ((1ull << nbits) - 1);
  }
  inline int receive(int n) {
    if (n == 0) return 0;
    if (nbits < n) fill();
    int v = (int)((buf >> (nbits - n)) & ((1ull << n) - 1));
    skip(n);
    return v;
  }
  bool restart() {
    buf = 0;
    nbits = 0;
    if (marker >= 0xD0 && marker <= 0xD7) {
      pos += 2;
      marker = -1;
      return true;
    }
    while (pos + 1 < len) {
      if (data[pos] == 0xFF && data[pos + 1] >= 0xD0 &&
          data[pos + 1] <= 0xD7) {
        pos += 2;
        marker = -1;  // stale non-RST marker must not pad out the rest
        return true;
      }
      ++pos;
    }
    return false;
  }
};

inline int extend(int v, int t) {
  return (t && v < (1 << (t - 1))) ? v - (1 << t) + 1 : v;
}

inline int decode_huff(BitReader& br, const Huff& h, bool* ok) {
  uint32_t prefix = br.peek16();
  int length = h.len[prefix];
  if (length == 0) {
    *ok = false;
    return 0;
  }
  br.skip(length);
  return h.val[prefix];
}

struct Scan {
  int ns = 0;
  int ci[4] = {0, 0, 0, 0};  // indices into Frame.comp
  int ss = 0, se = 63, ah = 0, al = 0;
};

bool handle_dqt(const uint8_t* body, size_t blen, Tables& t) {
  size_t i = 0;
  while (i < blen) {
    int pq = body[i] >> 4, tq = body[i] & 0xF;
    ++i;
    if (tq > 3) return false;
    if (pq == 0) {
      if (i + 64 > blen) return false;
      for (int j = 0; j < 64; ++j) t.quant[tq][j] = body[i + j];
      i += 64;
    } else {
      if (i + 128 > blen) return false;
      for (int j = 0; j < 64; ++j)
        t.quant[tq][j] =
            ((int32_t)body[i + 2 * j] << 8) | body[i + 2 * j + 1];
      i += 128;
    }
    t.quant_present[tq] = true;
  }
  return true;
}

bool handle_dht(const uint8_t* body, size_t blen, Tables& t) {
  size_t i = 0;
  while (i + 17 <= blen) {
    int tc = body[i] >> 4, th = body[i] & 0xF;
    if (th > 3 || tc > 1) return false;
    const uint8_t* bits = body + i + 1;
    int n = 0;
    for (int j = 0; j < 16; ++j) n += bits[j];
    if (i + 17 + (size_t)n > blen) return false;
    Huff& h = (tc == 0) ? t.dc[th] : t.ac[th];
    if (!h.build(bits, body + i + 17, n)) return false;
    i += 17 + n;
  }
  return true;
}

// SOS body -> Scan (and td/ta on the named components).  Progressive
// scans may name any subset; baseline requires all components.
bool parse_sos(const uint8_t* body, size_t blen, Frame& f,
               bool progressive, Scan& scan) {
  if (!f.present || blen < 1) return false;
  int ns = body[0];
  if (ns < 1 || ns > 4 || blen < 1 + 2 * (size_t)ns + 3) return false;
  if (!progressive && ns != f.ncomp) return false;
  scan.ns = ns;
  for (int si = 0; si < ns; ++si) {
    int cs = body[1 + 2 * si];
    int td = body[2 + 2 * si] >> 4, ta = body[2 + 2 * si] & 0xF;
    bool found = false;
    for (int ci = 0; ci < f.ncomp; ++ci) {
      if (f.comp[ci].ident == cs) {
        if (td > 3 || ta > 3) return false;
        f.comp[ci].td = td;
        f.comp[ci].ta = ta;
        scan.ci[si] = ci;
        found = true;
        break;
      }
    }
    if (!found) return false;
  }
  scan.ss = body[1 + 2 * ns];
  scan.se = body[2 + 2 * ns];
  int ahal = body[3 + 2 * ns];
  scan.ah = ahal >> 4;
  scan.al = ahal & 0xF;
  if (progressive) {
    if (scan.ss > scan.se || scan.se > 63 || scan.al > 13 ||
        scan.ah > 13)
      return false;
    if (scan.ss == 0 && scan.se != 0) return false;
    if (scan.ss > 0 && ns != 1) return false;
  }
  return true;
}

// Walk marker segments until SOS/EOI.  Returns scan start offset, or
// 0 on EOI (tables-only), or SIZE_MAX on error.
size_t parse_segments(const uint8_t* data, size_t len, Tables& t,
                      Frame& f, bool* progressive, Scan* scan) {
  if (len < 2 || data[0] != 0xFF || data[1] != 0xD8) return SIZE_MAX;
  size_t pos = 2;
  while (pos + 2 <= len) {
    if (data[pos] != 0xFF) return SIZE_MAX;
    uint8_t marker = data[pos + 1];
    if (marker == 0xD9) return 0;  // EOI
    if (marker == 0x01 || (marker >= 0xD0 && marker <= 0xD7)) {
      pos += 2;
      continue;
    }
    if (pos + 4 > len) return SIZE_MAX;
    size_t seglen = ((size_t)data[pos + 2] << 8) | data[pos + 3];
    if (seglen < 2 || pos + 2 + seglen > len) return SIZE_MAX;
    const uint8_t* body = data + pos + 4;
    size_t blen = seglen - 2;
    if (marker == 0xDB) {
      if (!handle_dqt(body, blen, t)) return SIZE_MAX;
    } else if (marker == 0xC4) {
      if (!handle_dht(body, blen, t)) return SIZE_MAX;
    } else if (marker == 0xDD) {  // DRI
      if (blen < 2) return SIZE_MAX;
      t.restart_interval = ((int)body[0] << 8) | body[1];
    } else if (marker == 0xC0 || marker == 0xC1 ||
               marker == 0xC2) {  // SOF0/1 baseline, SOF2 progressive
      if (blen < 6) return SIZE_MAX;
      if (body[0] != 8) return SIZE_MAX;  // 8-bit only
      f.h = ((int)body[1] << 8) | body[2];
      f.w = ((int)body[3] << 8) | body[4];
      f.ncomp = body[5];
      if (f.h == 0 || f.w == 0 || f.ncomp < 1 || f.ncomp > 4)
        return SIZE_MAX;
      // Hostile headers must not drive allocations (bad_alloc across
      // the C ABI would terminate the process).
      if ((int64_t)f.h * f.w * f.ncomp > ((int64_t)1 << 28))
        return SIZE_MAX;
      if (blen < 6 + 3 * (size_t)f.ncomp) return SIZE_MAX;
      for (int ci = 0; ci < f.ncomp; ++ci) {
        const uint8_t* e = body + 6 + 3 * ci;
        f.comp[ci].ident = e[0];
        f.comp[ci].h = e[1] >> 4;
        f.comp[ci].v = e[1] & 0xF;
        f.comp[ci].tq = e[2];
        if (f.comp[ci].h < 1 || f.comp[ci].h > 2 || f.comp[ci].v < 1 ||
            f.comp[ci].v > 2 || f.comp[ci].tq > 3)
          return SIZE_MAX;
      }
      f.present = true;
      if (progressive) *progressive = (marker == 0xC2);
    } else if (marker == 0xC3 || (marker >= 0xC5 && marker <= 0xC7) ||
               (marker >= 0xC9 && marker <= 0xCB) ||
               (marker >= 0xCD && marker <= 0xCF)) {
      return SIZE_MAX;  // unsupported JPEG process
    } else if (marker == 0xDA) {  // SOS
      Scan local;
      Scan& s = scan ? *scan : local;
      bool prog = progressive && *progressive;
      if (!parse_sos(body, blen, f, prog, s)) return SIZE_MAX;
      return pos + 2 + seglen;
    }
    pos += 2 + seglen;
  }
  return SIZE_MAX;
}

// IDCT basis as a C++11 magic static: decodes run with the GIL
// released, so first-use init must be thread-safe (a hand-rolled
// static bool would race).
struct IdctBasis {
  float M[8][8];
  IdctBasis() {
    for (int u = 0; u < 8; ++u)
      for (int x = 0; x < 8; ++x)
        M[u][x] = (u == 0 ? std::sqrt(0.125f) : 0.5f) *
                  std::cos((2 * x + 1) * u * (float)M_PI / 16.0f);
  }
};

// Separable float IDCT on one dequantized 8x8 block (row-major input).
void idct8x8(const float* in, float* out) {
  static const IdctBasis basis;
  const auto& M = basis.M;
  float tmp[8][8];
  for (int u = 0; u < 8; ++u)  // tmp = in^T applied: tmp[x][v]
    for (int v = 0; v < 8; ++v) {
      float s = 0.f;
      for (int k = 0; k < 8; ++k) s += M[k][u] * in[k * 8 + v];
      tmp[u][v] = s;
    }
  for (int x = 0; x < 8; ++x)
    for (int y = 0; y < 8; ++y) {
      float s = 0.f;
      for (int k = 0; k < 8; ++k) s += tmp[x][k] * M[k][y];
      out[x * 8 + y] = s;
    }
}

// ------------------------------------------------------- progressive

// A component's TRUE (non-interleaved) block-grid dimensions.
void comp_block_dims(const Component& c, int h, int w, int hmax,
                     int vmax, int* nby, int* nbx) {
  int cw = (w * c.h + hmax - 1) / hmax;
  int ch = (h * c.v + vmax - 1) / vmax;
  *nby = (ch + 7) / 8;
  *nbx = (cw + 7) / 8;
}

// First non-RST, non-stuffing marker at/after pos (between scans).
size_t next_marker_pos(const uint8_t* data, size_t len, size_t pos) {
  while (pos + 1 < len) {
    if (data[pos] == 0xFF && data[pos + 1] != 0x00 &&
        data[pos + 1] != 0xFF &&
        !(data[pos + 1] >= 0xD0 && data[pos + 1] <= 0xD7))
      return pos;
    ++pos;
  }
  return SIZE_MAX;
}

// T.81 G.2.2 first pass over one AC band; returns new eobrun or -1.
long long ac_first_block(BitReader& br, const Huff& ach, int32_t* block,
                         int ss, int se, int al, long long eobrun) {
  if (eobrun) return eobrun - 1;
  bool ok = true;
  int k = ss;
  while (k <= se) {
    int rs = decode_huff(br, ach, &ok);
    if (!ok) return -1;
    int r = rs >> 4, s = rs & 0xF;
    if (s == 0) {
      if (r == 15) {
        k += 16;  // ZRL
        continue;
      }
      long long run = 1ll << r;
      if (r) run += br.receive(r);
      return run - 1;  // covers this block
    }
    k += r;
    if (k > se) return -1;
    block[k] = extend(br.receive(s), s) << al;
    ++k;
  }
  return 0;
}

// T.81 G.2.3 correction pass (the jdphuff.c refinement walk).
long long ac_refine_block(BitReader& br, const Huff& ach, int32_t* block,
                          int ss, int se, int al, long long eobrun) {
  const int32_t p1 = 1 << al;
  const int32_t m1 = -(1 << al);
  bool ok = true;
  int k = ss;
  if (!eobrun) {
    while (k <= se) {
      int rs = decode_huff(br, ach, &ok);
      if (!ok) return -1;
      int r = rs >> 4, s = rs & 0xF;
      int32_t val = 0;
      if (s == 0) {
        if (r != 15) {
          eobrun = 1ll << r;
          if (r) eobrun += br.receive(r);
          break;
        }
        // r == 15: run of 16 zero-history coefficients
      } else {
        if (s != 1) return -1;
        val = br.receive(1) ? p1 : m1;
      }
      bool placed = false;
      while (k <= se) {
        if (block[k]) {
          if (br.receive(1) && !(block[k] & p1))
            block[k] += (block[k] >= 0) ? p1 : m1;
        } else {
          if (r == 0) {
            if (val) block[k] = val;
            ++k;
            placed = true;
            break;
          }
          --r;
        }
        ++k;
      }
      if (!placed && val) return -1;  // value past band end
    }
  }
  if (eobrun) {
    while (k <= se) {
      if (block[k]) {
        if (br.receive(1) && !(block[k] & p1))
          block[k] += (block[k] >= 0) ? p1 : m1;
      }
      ++k;
    }
    --eobrun;
  }
  return eobrun;
}

struct ProgState {
  // Scan-script succession state (mirrors the Python decoder): the
  // DC approximation level per component, and per-coefficient AC
  // levels; -2 = not coded yet.
  int dc_al[4] = {-2, -2, -2, -2};
  int ac_al[4][64];
  ProgState() {
    for (auto& row : ac_al)
      for (int& v : row) v = -2;
  }
};

// One progressive scan's succession validation + state update.
bool validate_scan_script(const Frame& f, const Scan& s,
                          ProgState& st) {
  if (s.ss == 0) {
    for (int si = 0; si < s.ns; ++si) {
      int ci = s.ci[si];
      if (s.ah == 0) {
        if (st.dc_al[ci] != -2) return false;  // duplicate first scan
      } else {
        if (st.dc_al[ci] != s.ah || s.al != s.ah - 1) return false;
      }
      st.dc_al[ci] = s.al;
    }
    return true;
  }
  int ci = s.ci[0];
  if (st.dc_al[ci] == -2) return false;  // AC before the DC first scan
  for (int k = s.ss; k <= s.se; ++k) {
    if (s.ah == 0) {
      if (st.ac_al[ci][k] != -2) return false;
    } else {
      if (st.ac_al[ci][k] != s.ah || s.al != s.ah - 1) return false;
    }
    st.ac_al[ci][k] = s.al;
  }
  return true;
}

long long decode_progressive(const uint8_t* data, size_t len, Tables& t,
                             Frame& f, Scan scan, size_t scan_pos,
                             uint8_t* out) {
  int hmax = 1, vmax = 1;
  for (int ci = 0; ci < f.ncomp; ++ci) {
    if (f.comp[ci].h > hmax) hmax = f.comp[ci].h;
    if (f.comp[ci].v > vmax) vmax = f.comp[ci].v;
  }
  int mcux = (f.w + 8 * hmax - 1) / (8 * hmax);
  int mcuy = (f.h + 8 * vmax - 1) / (8 * vmax);

  // Per-component coefficient grids [by][bx][64], zigzag order.
  std::vector<std::vector<int32_t>> grids(f.ncomp);
  int gw[4], gh[4];
  long long total_blocks = 0;
  for (int ci = 0; ci < f.ncomp; ++ci) {
    gw[ci] = mcux * f.comp[ci].h;
    gh[ci] = mcuy * f.comp[ci].v;
    grids[ci].assign((size_t)gw[ci] * gh[ci] * 64, 0);
    total_blocks += (long long)gw[ci] * gh[ci];
  }
  // Frame-scaled cumulative visit budget (shared rule with the Python
  // decoder): legitimately deep scan scripts over large frames pass,
  // tiny streams declaring huge frames with scan amplification fail.
  // The scale term is CAPPED (1<<25) so attacker-declared dimensions
  // cannot push the pure-Python fallback's wall time past ~seconds.
  const long long max_visits = std::max(
      (long long)1 << 23,
      std::min(64 * total_blocks, (long long)1 << 25));
  long long visits = 0;
  ProgState st;
  // The Python decoder requires every component's quant table before
  // the first scan (parity contract).
  for (int ci = 0; ci < f.ncomp; ++ci)
    if (!t.quant_present[f.comp[ci].tq]) return -1;

  for (int nscan = 0; nscan < 256; ++nscan) {
    if (!validate_scan_script(f, scan, st)) return -1;
    BitReader br{data, len, scan_pos};
    long long eobrun = 0;
    long long unit = 0;
    int ri = t.restart_interval;
    if (scan.ss == 0) {
      // DC scan: interleaved MCU walk, or the lone component's true
      // block grid.
      for (int si = 0; si < scan.ns; ++si) {
        int ci = scan.ci[si];
        if (scan.ah == 0 && !t.dc[f.comp[ci].td].present) return -1;
      }
      int preds[4] = {0, 0, 0, 0};
      bool ok = true;
      auto visit = [&](int ci, int by, int bx) {
        const Component& c = f.comp[ci];
        int32_t* block =
            grids[ci].data() + ((size_t)by * gw[ci] + bx) * 64;
        if (scan.ah == 0) {
          int tcat = decode_huff(br, t.dc[c.td], &ok);
          if (!ok || tcat > 15) {
            ok = false;
            return;
          }
          preds[ci] += extend(br.receive(tcat), tcat);
          block[0] = preds[ci] << scan.al;
        } else {
          if (br.receive(1)) block[0] |= (1 << scan.al);
        }
      };
      if (scan.ns > 1) {
        // Same accounting as the Python decoder: every coded block of
        // every selected component counts.
        for (int si = 0; si < scan.ns; ++si) {
          const Component& c = f.comp[scan.ci[si]];
          visits += (long long)mcux * c.h * mcuy * c.v;
        }
        if (visits > max_visits) return -1;
        for (int my = 0; my < mcuy && ok; ++my)
          for (int mx = 0; mx < mcux && ok; ++mx) {
            if (ri && unit && unit % ri == 0) {
              if (!br.restart()) return -1;
              preds[0] = preds[1] = preds[2] = preds[3] = 0;
            }
            ++unit;
            for (int si = 0; si < scan.ns && ok; ++si) {
              int ci = scan.ci[si];
              const Component& c = f.comp[ci];
              for (int by = 0; by < c.v && ok; ++by)
                for (int bx = 0; bx < c.h && ok; ++bx)
                  visit(ci, my * c.v + by, mx * c.h + bx);
            }
          }
      } else {
        int ci = scan.ci[0];
        int nby, nbx;
        comp_block_dims(f.comp[ci], f.h, f.w, hmax, vmax, &nby, &nbx);
        visits += (long long)nby * nbx;
        if (visits > max_visits) return -1;
        for (int by = 0; by < nby && ok; ++by)
          for (int bx = 0; bx < nbx && ok; ++bx) {
            if (ri && unit && unit % ri == 0) {
              if (!br.restart()) return -1;
              preds[0] = preds[1] = preds[2] = preds[3] = 0;
            }
            ++unit;
            visit(ci, by, bx);
          }
      }
      if (!ok) return -1;
    } else {
      // AC scan: always single-component, TRUE block grid.
      int ci = scan.ci[0];
      const Component& c = f.comp[ci];
      if (!t.ac[c.ta].present) return -1;
      const Huff& ach = t.ac[c.ta];
      int nby, nbx;
      comp_block_dims(c, f.h, f.w, hmax, vmax, &nby, &nbx);
      visits += (long long)nby * nbx;
      if (visits > max_visits) return -1;
      for (int by = 0; by < nby; ++by)
        for (int bx = 0; bx < nbx; ++bx) {
          if (ri && unit && unit % ri == 0) {
            if (!br.restart()) return -1;
            eobrun = 0;
          }
          ++unit;
          int32_t* block =
              grids[ci].data() + ((size_t)by * gw[ci] + bx) * 64;
          eobrun = (scan.ah == 0)
                       ? ac_first_block(br, ach, block, scan.ss,
                                        scan.se, scan.al, eobrun)
                       : ac_refine_block(br, ach, block, scan.ss,
                                         scan.se, scan.al, eobrun);
          if (eobrun < 0) return -1;
        }
    }

    // Inter-scan segments: DHT/DQT/DRI updates, next SOS, or EOI.
    size_t pos = next_marker_pos(data, len, br.pos);
    if (pos == SIZE_MAX) return -1;
    bool have_scan = false;
    bool saw_eoi = false;
    while (pos + 2 <= len) {
      uint8_t marker = data[pos + 1];
      if (marker == 0xD9) {  // EOI: reconstruct below
        saw_eoi = true;
        break;
      }
      if (pos + 4 > len) return -1;
      size_t seglen = ((size_t)data[pos + 2] << 8) | data[pos + 3];
      if (seglen < 2 || pos + 2 + seglen > len) return -1;
      const uint8_t* body = data + pos + 4;
      size_t blen = seglen - 2;
      if (marker == 0xDA) {
        if (!parse_sos(body, blen, f, true, scan)) return -1;
        scan_pos = pos + 2 + seglen;
        have_scan = true;
        break;
      } else if (marker == 0xDB) {
        if (!handle_dqt(body, blen, t)) return -1;
      } else if (marker == 0xC4) {
        if (!handle_dht(body, blen, t)) return -1;
      } else if (marker == 0xDD) {
        if (blen < 2) return -1;
        t.restart_interval = ((int)body[0] << 8) | body[1];
      }  // APPn/COM: skipped
      pos += 2 + seglen;
    }
    if (have_scan) continue;
    // Data exhausted without EOI: malformed (parity with the Python
    // decoder's "ended without EOI").
    if (!saw_eoi) return -1;

    // EOI: dequant + IDCT + upsample + interleave + crop.
    int pw = mcux * 8 * hmax, ph = mcuy * 8 * vmax;
    std::vector<std::vector<uint8_t>> planes(
        f.ncomp, std::vector<uint8_t>((size_t)pw * ph));
    float deq[64], spatial[64];
    for (int ci = 0; ci < f.ncomp; ++ci) {
      const Component& c = f.comp[ci];
      const int32_t* q = t.quant[c.tq];
      int sx = hmax / c.h, sy = vmax / c.v;
      uint8_t* plane = planes[ci].data();
      for (int by = 0; by < gh[ci]; ++by) {
        for (int bx = 0; bx < gw[ci]; ++bx) {
          const int32_t* block =
              grids[ci].data() + ((size_t)by * gw[ci] + bx) * 64;
          for (int j = 0; j < 64; ++j)
            deq[kZigzag[j]] = (float)(block[j] * q[j]);
          idct8x8(deq, spatial);
          int ox = bx * 8, oy = by * 8;
          for (int yy = 0; yy < 8; ++yy)
            for (int xx = 0; xx < 8; ++xx) {
              float v = spatial[yy * 8 + xx] + 128.0f;
              int p = (int)std::lrintf(v);
              uint8_t u = (uint8_t)(p < 0 ? 0 : (p > 255 ? 255 : p));
              int gy0 = (oy + yy) * sy, gx0 = (ox + xx) * sx;
              for (int ry = 0; ry < sy; ++ry)
                for (int rx = 0; rx < sx; ++rx)
                  plane[(size_t)(gy0 + ry) * pw + gx0 + rx] = u;
            }
        }
      }
    }
    for (int y = 0; y < f.h; ++y)
      for (int ci = 0; ci < f.ncomp; ++ci) {
        const uint8_t* row = planes[ci].data() + (size_t)y * pw;
        uint8_t* dst = out + ((size_t)y * f.w) * f.ncomp + ci;
        for (int x = 0; x < f.w; ++x) dst[(size_t)x * f.ncomp] = row[x];
      }
    return (long long)f.w * f.h * f.ncomp;
  }
  return -1;  // > 256 scans
}

}  // namespace

extern "C" {

long long jpeg_decode_baseline(const uint8_t* data, size_t len,
                               const uint8_t* tables, size_t tables_len,
                               uint8_t* out, size_t out_cap, int* out_w,
                               int* out_h, int* out_ncomp) {
  if (!data || !out_w || !out_h || !out_ncomp) return -1;
  Tables t;
  if (tables && tables_len) {
    Frame tf;
    bool tp = false;
    if (parse_segments(tables, tables_len, t, tf, &tp, nullptr) ==
        SIZE_MAX)
      return -1;
  }
  Frame f;
  bool progressive = false;
  Scan first_scan;
  size_t scan =
      parse_segments(data, len, t, f, &progressive, &first_scan);
  if (scan == SIZE_MAX || scan == 0 || !f.present) return -1;

  size_t need = (size_t)f.w * f.h * f.ncomp;
  if (out_cap < need) {
    *out_w = f.w;
    *out_h = f.h;
    *out_ncomp = f.ncomp;
    return -2;
  }
  if (progressive) {
    long long n = decode_progressive(data, len, t, f, first_scan,
                                     scan, out);
    if (n < 0) return -1;
    *out_w = f.w;
    *out_h = f.h;
    *out_ncomp = f.ncomp;
    return n;
  }

  int hmax = 1, vmax = 1;
  for (int ci = 0; ci < f.ncomp; ++ci) {
    if (f.comp[ci].h > hmax) hmax = f.comp[ci].h;
    if (f.comp[ci].v > vmax) vmax = f.comp[ci].v;
  }
  int mcux = (f.w + 8 * hmax - 1) / (8 * hmax);
  int mcuy = (f.h + 8 * vmax - 1) / (8 * vmax);

  for (int ci = 0; ci < f.ncomp; ++ci) {
    const Component& c = f.comp[ci];
    if (!t.quant_present[c.tq] || !t.dc[c.td].present ||
        !t.ac[c.ta].present)
      return -1;
  }

  // Decoded full-resolution component planes (MCU-grid sized).
  int pw = mcux * 8 * hmax, ph = mcuy * 8 * vmax;
  std::vector<std::vector<uint8_t>> planes(
      f.ncomp, std::vector<uint8_t>((size_t)pw * ph));

  BitReader br{data, len, scan};
  int preds[4] = {0, 0, 0, 0};
  int ri = t.restart_interval;
  long long mcu_index = 0;
  float deq[64], spatial[64];
  int32_t block[64];
  bool ok = true;
  for (int my = 0; my < mcuy && ok; ++my) {
    for (int mx = 0; mx < mcux && ok; ++mx) {
      if (ri && mcu_index && mcu_index % ri == 0) {
        if (!br.restart()) return -1;
        preds[0] = preds[1] = preds[2] = preds[3] = 0;
      }
      ++mcu_index;
      for (int ci = 0; ci < f.ncomp && ok; ++ci) {
        const Component& c = f.comp[ci];
        const Huff& dch = t.dc[c.td];
        const Huff& ach = t.ac[c.ta];
        const int32_t* q = t.quant[c.tq];
        for (int by = 0; by < c.v && ok; ++by) {
          for (int bx = 0; bx < c.h && ok; ++bx) {
            std::memset(block, 0, sizeof(block));
            int tcat = decode_huff(br, dch, &ok);
            if (!ok) break;
            if (tcat > 15) {
              ok = false;
              break;
            }
            preds[ci] += extend(br.receive(tcat), tcat);
            block[0] = preds[ci];
            int k = 1;
            while (k < 64) {
              int rs = decode_huff(br, ach, &ok);
              if (!ok) break;
              int r = rs >> 4, s = rs & 0xF;
              if (s == 0) {
                if (r == 15) {
                  k += 16;
                  continue;
                }
                break;  // EOB
              }
              k += r;
              if (k > 63) {
                ok = false;
                break;
              }
              block[k] = extend(br.receive(s), s);
              ++k;
            }
            if (!ok) break;
            for (int j = 0; j < 64; ++j)
              deq[kZigzag[j]] = (float)(block[j] * q[j]);
            idct8x8(deq, spatial);
            // Store with replication upsampling folded in.
            int sx = hmax / c.h, sy = vmax / c.v;
            int ox = (mx * c.h + bx) * 8, oy = (my * c.v + by) * 8;
            uint8_t* plane = planes[ci].data();
            for (int yy = 0; yy < 8; ++yy) {
              for (int xx = 0; xx < 8; ++xx) {
                float v = spatial[yy * 8 + xx] + 128.0f;
                int p = (int)std::lrintf(v);
                uint8_t u = (uint8_t)(p < 0 ? 0 : (p > 255 ? 255 : p));
                int gy0 = (oy + yy) * sy, gx0 = (ox + xx) * sx;
                for (int ry = 0; ry < sy; ++ry)
                  for (int rx = 0; rx < sx; ++rx)
                    plane[(size_t)(gy0 + ry) * pw + gx0 + rx] = u;
              }
            }
          }
        }
      }
    }
  }
  if (!ok) return -1;

  // Interleave + crop.
  for (int y = 0; y < f.h; ++y) {
    for (int ci = 0; ci < f.ncomp; ++ci) {
      const uint8_t* row = planes[ci].data() + (size_t)y * pw;
      uint8_t* dst = out + ((size_t)y * f.w) * f.ncomp + ci;
      for (int x = 0; x < f.w; ++x) dst[(size_t)x * f.ncomp] = row[x];
    }
  }
  *out_w = f.w;
  *out_h = f.h;
  *out_ncomp = f.ncomp;
  return (long long)need;
}

}  // extern "C"
