"""Lookup-table parsing and registry.

Replaces the consumed surface of ``ome.model.display.LutReader`` /
``LutReaderFactory`` as used by ``LutProviderImpl.java:42-58`` (scan a
directory tree for ``*.lut`` files at startup, key by basename) and
``:63-73`` (resolve readers for channel bindings).

Supported formats (the ImageJ family the OMERO LutReaderFactory reads):
  * binary, 768 bytes: 256 R then 256 G then 256 B
  * binary, 800 bytes: 32-byte NIH Image header then the 768 payload
  * binary, N*3 planar (3 consecutive channel planes) for N<=256, stretched
    to 256 entries
  * text: whitespace/comma separated rows of ``r g b`` or ``index r g b``

Parsed LUTs become rows of a single device-resident ``(N, 256, 3)`` uint8
array, so applying a LUT on TPU is one gather — no per-request host work.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np


def parse_lut_bytes(data: bytes) -> np.ndarray:
    """Parse one .lut payload into a (256, 3) uint8 table."""
    n = len(data)
    if n == 768:
        arr = np.frombuffer(data, dtype=np.uint8)
        return arr.reshape(3, 256).T.copy()
    if n == 800:
        return parse_lut_bytes(data[32:])
    # Try text
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        text = None
    if text is not None and any(c.isdigit() for c in text):
        rows: List[Tuple[int, int, int]] = []
        for line in text.replace(",", " ").splitlines():
            parts = line.split()
            if not parts:
                continue
            try:
                vals = [int(float(p)) for p in parts]
            except ValueError:
                continue
            if len(vals) >= 4:
                vals = vals[1:4]  # index r g b
            if len(vals) >= 3:
                rows.append((vals[0], vals[1], vals[2]))
        if rows:
            table = np.array(rows, dtype=np.int64)
            table = np.clip(table, 0, 255).astype(np.uint8)
            return _pad_to_256(table)
    # Fallback: planar binary of arbitrary length divisible by 3
    if n % 3 == 0 and 0 < n <= 768:
        m = n // 3
        arr = np.frombuffer(data, dtype=np.uint8)
        return _pad_to_256(arr.reshape(3, m).T.copy())
    raise ValueError(f"Unrecognized LUT payload of {n} bytes")


def _pad_to_256(table: np.ndarray) -> np.ndarray:
    if table.shape[0] == 256:
        return table
    if table.shape[0] > 256:
        return table[:256]
    # Stretch by nearest-neighbour to 256 entries.
    idx = np.linspace(0, table.shape[0] - 1, 256).round().astype(np.int64)
    return table[idx]


class LutProvider:
    """Startup-scanned LUT registry (= LutProviderImpl).

    Scans ``root`` recursively for ``*.lut`` files, keyed by lower-cased
    basename (the reference keys by ``getName().toLowerCase()``,
    ``LutProviderImpl.java:50-55``).  Unparseable files are skipped, matching
    the reference's warn-and-continue behavior.
    """

    def __init__(self, root: Optional[str] = None):
        self.tables: Dict[str, int] = {}
        self._rows: List[np.ndarray] = []
        if root and os.path.isdir(root):
            for dirpath, _dirnames, filenames in os.walk(root):
                for fn in sorted(filenames):
                    if not fn.lower().endswith(".lut"):
                        continue
                    path = os.path.join(dirpath, fn)
                    try:
                        with open(path, "rb") as f:
                            table = parse_lut_bytes(f.read())
                    except (ValueError, OSError):
                        continue
                    self.add(fn.lower(), table)

    def add(self, name: str, table: np.ndarray) -> int:
        """Register a (256,3) uint8 table under ``name``; returns its row."""
        if table.shape != (256, 3):
            raise ValueError(f"LUT table must be (256,3), got {table.shape}")
        name = name.lower()
        if name in self.tables:
            self._rows[self.tables[name]] = table.astype(np.uint8)
            return self.tables[name]
        idx = len(self._rows)
        self._rows.append(table.astype(np.uint8))
        self.tables[name] = idx
        return idx

    def get(self, name: str) -> Optional[np.ndarray]:
        idx = self.tables.get(name.lower())
        return None if idx is None else self._rows[idx]

    def names(self) -> List[str]:
        return sorted(self.tables)

    def as_array(self) -> np.ndarray:
        """All tables stacked as (N, 256, 3) uint8 (N>=1; row 0 is identity
        grey if the registry is empty so device code can always gather)."""
        if not self._rows:
            ramp = np.arange(256, dtype=np.uint8)
            return np.stack([ramp] * 3, axis=-1)[None]
        return np.stack(self._rows, axis=0)
