"""Image flip op.

Replaces ``ImageRegionRequestHandler.flip`` (``:616-642``) — the reference's
O(w*h) per-pixel CPU loop — with ``jnp.flip`` on device, where it fuses into
the render kernel's output write instead of being a second pass over memory.

Validation semantics match the reference: flipping a null or zero-sized image
raises; no-op when neither flag is set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("flip_horizontal",
                                             "flip_vertical"))
def _flip_jit(img, flip_horizontal: bool, flip_vertical: bool):
    axes = []
    if flip_vertical:
        axes.append(0)  # rows
    if flip_horizontal:
        axes.append(1)  # columns
    return jnp.flip(img, axis=axes)


def flip_image(img, flip_horizontal: bool = False,
               flip_vertical: bool = False):
    """Flip an [H, W, ...] image. Mirrors the reference's argument checks
    (``ImageRegionRequestHandler.java:619-627``)."""
    if not flip_horizontal and not flip_vertical:
        return img
    if img is None:
        raise ValueError("Attempted to flip null image")
    if img.shape[0] == 0 or img.shape[1] == 0:
        raise ValueError("Attempted to flip image with 0 size")
    return _flip_jit(img, flip_horizontal, flip_vertical)
