"""Batched device downsampling for on-TPU pyramid builds (PR 20).

The serving stack's host reduction is ``io.store._downsample2``:
mean-pool by 2 in float64, ``np.round`` for integer dtypes, cast back.
The device kernel here reproduces it BIT-FOR-BIT for the storage dtypes
the pyramid job handles (integer, itemsize <= 2): a 2x2 sum of uint16
values is <= 4 * 65535 = 262140 < 2^24, so the int32 accumulate is
exact, the divide-by-4 is a power-of-two float32 scale (exact), and
``jnp.round`` is round-half-to-even exactly like ``np.round``.  Wider
or floating dtypes fall back to the host formula — correctness over
residency for the long tail.

That exactness is the crash-safety contract's foundation: a killed and
resumed build re-derives byte-identical levels because every reduction
is deterministic integer math, never accelerator float accumulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _mean2_int_jit(v):
    """int32[N, 2h, 2w] -> f32[N, h, w] rounded 2x2 means (exact for
    sums < 2^24; see module docstring)."""
    s = (v[:, 0::2, 0::2].astype(jnp.int32)
         + v[:, 0::2, 1::2] + v[:, 1::2, 0::2] + v[:, 1::2, 1::2])
    return jnp.round(s.astype(jnp.float32) / 4.0)


def _device_exact(dtype: np.dtype) -> bool:
    return np.issubdtype(dtype, np.integer) and dtype.itemsize <= 2


def downsample2_batch(planes: np.ndarray) -> np.ndarray:
    """Mean-pool a stack of planes by 2: [..., H, W] -> [..., H//2, W//2].

    Matches ``io.store._downsample2`` bit-for-bit per plane (including
    its tiny-plane guard: a dimension that cannot halve collapses the
    plane to [..., 1, 1]).  Integer dtypes up to 16 bits take ONE
    batched device dispatch; everything else computes the host formula
    vectorized over the batch.
    """
    *lead, H, W = planes.shape
    h, w = H // 2, W // 2
    if h < 1 or w < 1:
        return np.ascontiguousarray(planes[..., :1, :1])
    v = planes.reshape(-1, H, W)[:, : h * 2, : w * 2]
    if _device_exact(planes.dtype):
        out = np.asarray(_mean2_int_jit(v.astype(np.int32)))
        out = out.astype(planes.dtype)
    else:
        m = v.astype(np.float64).reshape(-1, h, 2, w, 2).mean(axis=(2, 4))
        if np.issubdtype(planes.dtype, np.integer):
            m = np.round(m)
        out = m.astype(planes.dtype)
    return out.reshape(*lead, h, w)


def n_pyramid_levels(height: int, width: int,
                     min_level_size: int = 256) -> int:
    """How many levels a full build yields — the ``io.ngff.write_ngff``
    halving rule (halve while ``min(h//2, w//2) >= min_level_size``),
    so job plans and the writer can never disagree on level count."""
    n, h, w = 1, height, width
    while min(h // 2, w // 2) >= min_level_size:
        h, w = h // 2, w // 2
        n += 1
    return n
