"""Shape-mask rasterization ops.

Replaces the pixel path of ``ShapeMaskRequestHandler`` (``:165-221``): 1-bit
packed mask bytes -> bit grid -> optional flip -> 2-entry palette raster.

Bit order matches ``ome.util.PixelData``'s "bit" accessor (MSB-first within
each byte, bits continuous across rows — ``convertBitsToBytes``,
``ShapeMaskRequestHandler.java:214-221``).

Deviation from the reference, by design: the reference applies its byte-wise
``flip`` to the still-packed buffer when ``width % 8 == 0`` (``:174-181``),
which indexes out of bounds for any flipped byte-aligned mask; here flips
always operate on the unpacked bit grid, which is what the un-aligned path
(and the reference's own tests) exercise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models.mask import Mask


def unpack_mask_bits(data: bytes, width: int, height: int) -> np.ndarray:
    """Unpack 1-bit packed mask bytes to a u8[H, W] 0/1 grid."""
    total = width * height
    bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8))
    if bits.size < total:
        raise ValueError(
            f"Mask payload too small: {bits.size} bits < {width}x{height}"
        )
    return bits[:total].reshape(height, width)


def flip_mask(grid: np.ndarray, flip_horizontal: bool,
              flip_vertical: bool) -> np.ndarray:
    """Flip a mask grid (argument checks as ShapeMaskRequestHandler.flip
    ``:128-154``)."""
    if not flip_horizontal and not flip_vertical:
        return grid
    if grid is None:
        raise ValueError("Attempted to flip null image")
    if grid.shape[0] == 0 or grid.shape[1] == 0:
        raise ValueError("Attempted to flip image with 0 size")
    if flip_vertical:
        grid = grid[::-1, :]
    if flip_horizontal:
        grid = grid[:, ::-1]
    return np.ascontiguousarray(grid)


def rasterize_mask(mask: Mask, color=None, flip_horizontal: bool = False,
                   flip_vertical: bool = False) -> tuple:
    """Rasterize a mask to (palette_indices u8[H,W], rgba_palette (2,4)).

    Palette row 0 is fully transparent, row 1 the resolved fill color —
    exactly the 2-entry IndexColorModel the reference builds (``:188-196``).
    """
    fill = mask.resolved_fill_color(color)
    grid = unpack_mask_bits(mask.bytes_, mask.width, mask.height)
    grid = flip_mask(grid, flip_horizontal, flip_vertical)
    palette = np.array([(0, 0, 0, 0), fill], dtype=np.uint8)
    return grid.astype(np.uint8), palette


def mask_to_rgba(mask: Mask, color=None, flip_horizontal: bool = False,
                 flip_vertical: bool = False) -> np.ndarray:
    """Full RGBA expansion of a mask (used by the batched overlay path)."""
    grid, palette = rasterize_mask(mask, color, flip_horizontal,
                                   flip_vertical)
    return palette[grid]


def overlay_masks_batch(base_rgba: np.ndarray,
                        mask_grids: np.ndarray,
                        fills: np.ndarray) -> np.ndarray:
    """Alpha-composite a batch of masks over a batch of RGBA tiles.

    Used by the batched-ROI bench config (BASELINE.json config 5).
    Prefers the native OpenMP integer blend (``native/tilecache.cpp::
    mask_overlay_u8``, GIL released for the whole pass); the numpy
    fallback computes the identical integer formula —
    ``(base*(255-a) + fill*a + 127) // 255`` with per-pixel
    ``a = (mask != 0) * fill_alpha`` (any nonzero mask byte is "on",
    matching the C kernel) — so outputs are bit-equal either way.

    Args:
      base_rgba:  u8[B, H, W, 4]
      mask_grids: u8[B, H, W], nonzero = masked
      fills:      u8[B, 4] RGBA fill per mask
    """
    try:
        from ..native import mask_overlay_u8
        return mask_overlay_u8(base_rgba, mask_grids, fills)
    except ImportError:
        pass
    a = ((mask_grids != 0).astype(np.uint32)
         * fills[:, None, None, 3].astype(np.uint32))[..., None]
    ia = 255 - a
    base = base_rgba.astype(np.uint32)
    fill_rgb = fills[:, None, None, :3].astype(np.uint32)
    out = base_rgba.copy()
    out[..., :3] = ((base[..., :3] * ia + fill_rgb * a + 127)
                    // 255).astype(np.uint8)
    return out


# --------------------------------------------------------------- device path
#
# Batched device rasterization (the PR 20 workloads plane).  The contract
# is BYTE IDENTITY with the host path above: the device kernel produces
# the exact 0/1 grid ``rasterize_mask`` produces (same MSB-first unpack,
# same flip semantics), and the caller feeds it to the identical
# ``codecs.encode_mask_png`` tail — so the served PNG bytes cannot
# diverge between paths.  Integer-only ops throughout; nothing here can
# drift with accelerator float semantics.

def packed_nbytes(width: int, height: int) -> int:
    """Packed payload bytes one mask needs (bits continuous across rows)."""
    return (width * height + 7) // 8


def pack_mask_payload(data: bytes, width: int, height: int) -> np.ndarray:
    """Validate + normalize one packed payload to exactly ``packed_nbytes``
    (the host path's size check; over-long payloads carry unused trailing
    bits the unpack slices off anyway)."""
    need = packed_nbytes(width, height)
    buf = np.frombuffer(data, dtype=np.uint8)
    if buf.size * 8 < width * height:
        raise ValueError(
            f"Mask payload too small: {buf.size * 8} bits "
            f"< {width}x{height}")
    return buf[:need]


@functools.partial(
    jax.jit, static_argnames=("width", "height", "flip_horizontal",
                              "flip_vertical"))
def _rasterize_batch_jit(packed, width: int, height: int,
                         flip_horizontal: bool, flip_vertical: bool):
    """u8[B, nbytes] packed -> u8[B, H, W] 0/1 grids, on device.

    MSB-first unpack (``jnp.unpackbits`` default) matches
    ``np.unpackbits`` bit-for-bit; flips are static so each (shape,
    flips) group compiles once, the ``ops.flip`` idiom."""
    bits = jnp.unpackbits(packed, axis=-1)
    grids = bits[:, : width * height].reshape(-1, height, width)
    axes = []
    if flip_vertical:
        axes.append(1)
    if flip_horizontal:
        axes.append(2)
    if axes:
        grids = jnp.flip(grids, axis=tuple(axes))
    return grids


def rasterize_packed_batch(packed: np.ndarray, width: int, height: int,
                           flip_horizontal: bool = False,
                           flip_vertical: bool = False) -> np.ndarray:
    """Rasterize a stacked batch of same-shape packed masks on device.

    Args:
      packed: u8[B, packed_nbytes(width, height)] (see
        ``pack_mask_payload``)
    Returns u8[B, H, W] 0/1 grids, host-resident, byte-identical to
    running ``unpack_mask_bits`` + ``flip_mask`` per member.
    """
    if (flip_horizontal or flip_vertical) and (width == 0 or height == 0):
        raise ValueError("Attempted to flip image with 0 size")
    out = _rasterize_batch_jit(np.ascontiguousarray(packed), width,
                               height, flip_horizontal, flip_vertical)
    return np.asarray(out)


def rasterize_mask_device(mask: Mask, color=None,
                          flip_horizontal: bool = False,
                          flip_vertical: bool = False) -> tuple:
    """Device twin of ``rasterize_mask`` — same (grid, palette) contract,
    one-mask batch.  Exists for the parity tests and the non-batched
    callers; the serving path batches through
    ``server.batcher.BatchingRenderer.rasterize_mask``."""
    fill = mask.resolved_fill_color(color)
    packed = pack_mask_payload(mask.bytes_, mask.width, mask.height)
    grid = rasterize_packed_batch(packed[None, :], mask.width,
                                  mask.height, flip_horizontal,
                                  flip_vertical)[0]
    palette = np.array([(0, 0, 0, 0), fill], dtype=np.uint8)
    return grid.astype(np.uint8), palette


@jax.jit
def _overlay_batch_jit(base_rgba, mask_grids, fills):
    """The ``overlay_masks_batch`` integer blend, verbatim, in jnp:
    ``(base*(255-a) + fill*a + 127) // 255`` with
    ``a = (mask != 0) * fill_alpha`` — uint32 throughout, so the device
    result is bit-equal to the host/native kernels."""
    a = ((mask_grids != 0).astype(jnp.uint32)
         * fills[:, None, None, 3].astype(jnp.uint32))[..., None]
    ia = 255 - a
    base = base_rgba.astype(jnp.uint32)
    fill_rgb = fills[:, None, None, :3].astype(jnp.uint32)
    rgb = ((base[..., :3] * ia + fill_rgb * a + 127) // 255) \
        .astype(jnp.uint8)
    return jnp.concatenate([rgb, base_rgba[..., 3:]], axis=-1)


def overlay_masks_device(base_rgba: np.ndarray,
                         mask_grids: np.ndarray,
                         fills: np.ndarray) -> np.ndarray:
    """Device twin of ``overlay_masks_batch`` (same shapes, bit-equal
    output) — the overlay endpoint's one-dispatch composite."""
    out = _overlay_batch_jit(
        np.ascontiguousarray(base_rgba, dtype=np.uint8),
        np.ascontiguousarray(mask_grids, dtype=np.uint8),
        np.ascontiguousarray(fills, dtype=np.uint8))
    return np.asarray(out)
