"""JAX compute kernels — the TPU replacement for the reference's L1 pixel
layer (``omeis.providers.re.Renderer`` and friends; SURVEY.md section 2b).

Everything in this package is pure, jittable, and batch-friendly:

  quantum.py     per-channel window + family quantization to the 8-bit
                 codomain (= QuantumFactory strategies)
  lut.py         .lut file parsing -> (256,3) tables (= LutReader)
  render.py      the fused render kernel: quantize -> per-channel 256x3
                 table gather -> additive composite (= Renderer.renderAsPackedInt)
  flip.py        horizontal/vertical flip (= ImageRegionRequestHandler.flip)
  projection.py  max/mean/sum Z-projection (= ProjectionService)
  maskops.py     1-bit mask expansion + palette rasterization
                 (= ShapeMaskRequestHandler render path)
"""

from .quantum import quantize
from .render import build_channel_tables, render_tile, render_tile_batch
from .flip import flip_image
from .projection import project_stack
from .maskops import unpack_mask_bits, rasterize_mask

__all__ = [
    "quantize",
    "build_channel_tables",
    "render_tile",
    "render_tile_batch",
    "flip_image",
    "project_stack",
    "unpack_mask_bits",
    "rasterize_mask",
]
