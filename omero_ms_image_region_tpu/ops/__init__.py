"""JAX compute kernels — the TPU replacement for the reference's L1 pixel
layer (``omeis.providers.re.Renderer`` and friends; SURVEY.md section 2b).

Everything in this package is pure, jittable, and batch-friendly:

  quantum.py     per-channel window + family quantization to the 8-bit
                 codomain (= QuantumFactory strategies)
  lut.py         .lut file parsing -> (256,3) tables (= LutReader)
  render.py      the fused render kernel: quantize -> per-channel 256x3
                 table gather -> additive composite (= Renderer.renderAsPackedInt)
  flip.py        horizontal/vertical flip (= ImageRegionRequestHandler.flip)
  projection.py  max/mean/sum Z-projection (= ProjectionService)
  maskops.py     1-bit mask expansion + palette rasterization
                 (= ShapeMaskRequestHandler render path)
"""

# Lazy re-exports (PEP 562): importing the package must NOT pull the
# JAX device stack — frontend proxy processes import jax-free modules
# like ops.lut through this package and must stay device-free.
_EXPORTS = {
    "quantize": ".quantum",
    "build_channel_tables": ".render",
    "render_tile": ".render",
    "render_tile_batch": ".render",
    "flip_image": ".flip",
    "project_stack": ".projection",
    "unpack_mask_bits": ".maskops",
    "rasterize_mask": ".maskops",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(mod, __name__), name)
