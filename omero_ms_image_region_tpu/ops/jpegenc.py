"""TPU-side JPEG front end: color transform + 8x8 DCT + quantization.

The reference encodes JPEG on the CPU from the packed-int render output
(``LocalCompress.compressToStream``, call site
``ImageRegionRequestHandler.java:580-582``).  On TPU the economics invert:
the rendered tile lives in HBM and the host link is the bottleneck, while
the 8x8 block DCT is a pair of small matmuls — exactly what the MXU does
best.  So the lossy half of baseline JPEG (BT.601 YCbCr conversion, 4:2:0
chroma subsampling, blockwise DCT-II, quantization, zigzag) runs on device
as one fused jitted kernel over the whole tile batch, and only the
quantized coefficients — far smaller and far more wire-compressible than
raw RGBA — cross to the host, where the serial entropy coding (Huffman,
byte stuffing, JFIF framing) runs in native code (``native/jpegenc.cpp``)
with a pure-Python fallback (:mod:`.jfif`).

Coefficient layout contract with the entropy coder:
  * ``y``  i16[B, (H/8)*(W/8),   64]  — luma blocks, raster order, zigzagged
  * ``cb`` i16[B, (H/16)*(W/16), 64]  — subsampled chroma, raster, zigzagged
  * ``cr`` i16[B, (H/16)*(W/16), 64]
H and W must be multiples of 16 (one 4:2:0 MCU); callers pad odd tiles by
edge replication before encode and patch the true size into the SOF0 header
dimensions (the JPEG spec decodes only the declared WxH).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------- tables

# Annex K base quantization tables (natural 8x8 order).
BASE_LUMA_QUANT = np.array([
    [16, 11, 10, 16, 24, 40, 51, 61],
    [12, 12, 14, 19, 26, 58, 60, 55],
    [14, 13, 16, 24, 40, 57, 69, 56],
    [14, 17, 22, 29, 51, 87, 80, 62],
    [18, 22, 37, 56, 68, 109, 103, 77],
    [24, 35, 55, 64, 81, 104, 113, 92],
    [49, 64, 78, 87, 103, 121, 120, 101],
    [72, 92, 95, 98, 112, 100, 103, 99],
], dtype=np.int32)

BASE_CHROMA_QUANT = np.array([
    [17, 18, 24, 47, 99, 99, 99, 99],
    [18, 21, 26, 66, 99, 99, 99, 99],
    [24, 26, 56, 99, 99, 99, 99, 99],
    [47, 66, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
], dtype=np.int32)


def quant_tables(quality: int) -> tuple[np.ndarray, np.ndarray]:
    """IJG quality scaling of the Annex K tables -> (luma, chroma) u8[8,8]."""
    quality = int(max(1, min(100, quality)))
    scale = 5000 // quality if quality < 50 else 200 - 2 * quality
    def scaled(base):
        t = (base * scale + 50) // 100
        return np.clip(t, 1, 255).astype(np.uint8)
    return scaled(BASE_LUMA_QUANT), scaled(BASE_CHROMA_QUANT)


@functools.lru_cache(maxsize=1)
def zigzag_order() -> np.ndarray:
    """Flat indices (into a row-major 8x8 block) in JPEG zigzag order."""
    order = sorted(
        ((r, c) for r in range(8) for c in range(8)),
        key=lambda rc: (rc[0] + rc[1],
                        rc[1] if (rc[0] + rc[1]) % 2 == 0 else rc[0]),
    )
    return np.array([r * 8 + c for r, c in order], dtype=np.int32)


@functools.lru_cache(maxsize=1)
def dct_matrix() -> np.ndarray:
    """Orthonormal 8-point DCT-II matrix == the JPEG FDCT normalization."""
    k = np.arange(8)
    D = np.cos((2 * k[None, :] + 1) * k[:, None] * np.pi / 16) * 0.5
    D[0] *= 1.0 / np.sqrt(2.0)
    return D.astype(np.float32)


# ---------------------------------------------------------------- kernel

def _blockify(x):
    """[B, H, W] -> [B, (H/8)*(W/8), 8, 8] in raster block order."""
    Bq, H, W = x.shape
    x = x.reshape(Bq, H // 8, 8, W // 8, 8)
    return x.transpose(0, 1, 3, 2, 4).reshape(Bq, -1, 8, 8)


def _dct_quant_zigzag(planes, qtable, zig, D):
    """[B, H, W] level-shifted samples -> i16[B, nb, 64] zigzag coeffs."""
    blocks = _blockify(planes)
    coeffs = jnp.einsum("ux,bnxy,vy->bnuv", D, blocks, D,
                        preferred_element_type=jnp.float32)
    q = jnp.round(coeffs / qtable[None, None].astype(jnp.float32))
    q = jnp.clip(q, -2047.0, 2047.0).astype(jnp.int16)
    flat = q.reshape(q.shape[0], q.shape[1], 64)
    return jnp.take(flat, zig, axis=-1)


@jax.jit
def packed_to_jpeg_coefficients(packed, qy, qc):
    """Packed RGBA render output -> quantized zigzag JPEG coefficients.

    Args:
      packed: u32[B, H, W] little-endian R,G,B,A packed pixels (the render
              kernel's native output; H, W multiples of 16).
      qy:     i32[8, 8] luma quantization table (natural order).
      qc:     i32[8, 8] chroma quantization table.

    Returns:
      (y, cb, cr) int16 coefficient arrays in the module-docstring layout.
    """
    r = (packed & 0xFF).astype(jnp.float32)
    g = ((packed >> 8) & 0xFF).astype(jnp.float32)
    b = ((packed >> 16) & 0xFF).astype(jnp.float32)

    # BT.601 full-range YCbCr; the +128 chroma bias and the JPEG -128 level
    # shift cancel, so only luma is shifted.
    y = 0.299 * r + 0.587 * g + 0.114 * b - 128.0
    cb = -0.168736 * r - 0.331264 * g + 0.5 * b
    cr = 0.5 * r - 0.418688 * g - 0.081312 * b

    # 4:2:0: 2x2 mean subsample of the chroma planes.
    def sub(x):
        Bq, H, W = x.shape
        return x.reshape(Bq, H // 2, 2, W // 2, 2).mean(axis=(2, 4))

    zig = jnp.asarray(zigzag_order())
    D = jnp.asarray(dct_matrix())
    return (
        _dct_quant_zigzag(y, qy, zig, D),
        _dct_quant_zigzag(sub(cb), qc, zig, D),
        _dct_quant_zigzag(sub(cr), qc, zig, D),
    )


@jax.jit
def rgb_to_jpeg_coefficients(rgb, qy, qc):
    """u8/f32[B, H, W, 3] RGB -> coefficients (CPU-reference-path variant)."""
    rgb = rgb.astype(jnp.uint32)
    packed = (rgb[..., 0] | (rgb[..., 1] << 8) | (rgb[..., 2] << 16))
    return packed_to_jpeg_coefficients(packed, qy, qc)


@jax.jit
def render_to_jpeg_coefficients(raw, window_start, window_end, family,
                                coefficient, reverse, cd_start, cd_end,
                                tables, qy, qc):
    """Fused batched render + JPEG front end, one device dispatch.

    The packed-RGBA intermediate stays in HBM; only the quantized
    coefficients cross the host link.  Argument order matches
    :func:`..ops.render.render_tile_batch_packed` plus the two quant tables.
    """
    from .render import _render_packed_impl

    packed = _render_packed_impl(raw, window_start, window_end, family,
                                 coefficient, reverse, cd_start, cd_end,
                                 tables)
    return packed_to_jpeg_coefficients(packed, qy, qc)


ENTRY_BITS = 18      # 6-bit zigzag position + 12-bit value (two's compl.)


def sparse_wire_width(H: int, W: int, cap: int) -> int:
    """Total device wire-buffer bytes per tile (the static shape)."""
    h16, w16 = (H + 15) // 16, (W + 15) // 16
    nb = h16 * w16 * 6
    return 4 + nb + (ENTRY_BITS * cap + 7) // 8


def sparse_prefix_bytes(total: int, H: int, W: int) -> int:
    """Bytes of a tile's wire buffer actually carrying data: the header,
    the per-block counts, and ``total`` 18-bit entries."""
    h16, w16 = (H + 15) // 16, (W + 15) // 16
    nb = h16 * w16 * 6
    return 4 + nb + (ENTRY_BITS * int(total) + 7) // 8


def sparse_pack(y, cb, cr, cap: int):
    """Compact nonzero coefficients into one u8 wire buffer per tile.

    The host link, not compute, bounds this service's TPU throughput (the
    tunnel moves ~15-30 MB/s device-to-host), so the device ships only the
    entropy-bearing bytes: for each tile a buffer

        [ total_entries i32 LE | per-block nonzero counts u8[nb] |
          packed 18-bit entries u8[ceil(18*cap/8)] ]

    where entry j (MSB-first at bit ``18*j``) is ``pos << 12 | val``:
    the 6-bit zigzag position and the 12-bit two's-complement value (the
    quantizer clips to ±2047, so 12 bits are exact) of the j-th nonzero
    in (block, zigzag) scan order — exactly the run-length stream
    baseline JPEG entropy-codes, so the host encoder
    (``jpeg_encode_sparse``) reads it directly.  Block order is luma
    raster, then Cb raster, then Cr raster.  Entries beyond ``cap`` are
    dropped (detected host-side via total_entries > cap; the caller then
    falls back to the dense path).

    Layout and algorithm are both wire-aware:

      * at 2.25 bytes/entry the used bytes are one contiguous prefix
        (``sparse_prefix_bytes``), so the host fetches only that prefix —
        comparable in size to the final JPEG itself — instead of the full
        ``cap``-sized buffer (``SparseWireFetcher``);
      * compaction is one set-scatter with unique, ascending targets
        (out-of-bounds-dropped tails), which XLA lowers to plain stores —
        measured ~3x faster than the equivalent non-unique scatter; the
        18-bit bitstream is then assembled by a pure gather pass (each
        output byte reads its ≤2 contributing entries arithmetically).
    """
    B = y.shape[0]
    flat = jnp.concatenate(
        [y.reshape(B, -1), cb.reshape(B, -1), cr.reshape(B, -1)], axis=1
    ).astype(jnp.int32)
    N = flat.shape[1]
    nb = N // 64
    mask = flat != 0
    counts = mask.reshape(B, nb, 64).sum(-1).astype(jnp.uint8)
    wi = jnp.cumsum(mask, axis=1) - 1                      # [B, N]
    total = (wi[:, -1] + 1).astype(jnp.int32)
    pos = jnp.arange(N, dtype=jnp.int32) % 64
    field = (pos << 12) | (flat & 0xFFF)                   # 18-bit entries

    def compact_one(m, w, f):
        tgt = jnp.where(m & (w < cap), w, jnp.int32(1) << 30)
        return jnp.zeros(cap, jnp.int32).at[tgt].set(
            f, mode="drop", unique_indices=True)

    comp = jax.vmap(compact_one)(mask, wi, field)          # [B, cap]

    # Assemble the 18-bit stream byte-by-byte: byte b covers bits
    # [8b, 8b+8), which intersect entries e0 = (8b)//18 and possibly
    # e0 + 1 (a field is 18 > 8 bits, so never more than two).
    nbytes = (ENTRY_BITS * cap + 7) // 8
    bitpos = jnp.arange(nbytes, dtype=jnp.int32) * 8
    e0 = bitpos // ENTRY_BITS
    off = bitpos - e0 * ENTRY_BITS                          # 0..17
    compz = jnp.pad(comp, ((0, 0), (0, 1)))                 # e0+1 guard

    def assemble_one(c_row):
        f0 = c_row[e0]
        f1 = c_row[e0 + 1]
        part0 = ((f0 << off) & 0x3FFFF) >> 10
        part1 = jnp.where(off > 10, f1 >> (28 - off), 0)
        return ((part0 | part1) & 0xFF).astype(jnp.uint8)

    stream = jax.vmap(assemble_one)(compz)                  # [B, nbytes]
    tot_u8 = jax.lax.bitcast_convert_type(
        total[:, None], jnp.uint8).reshape(B, -1)
    return jnp.concatenate([tot_u8, counts, stream], axis=1)


@functools.partial(jax.jit, static_argnames=("cap",))
def render_to_jpeg_sparse(raw, window_start, window_end, family,
                          coefficient, reverse, cd_start, cd_end, tables,
                          qy, qc, cap: int):
    """Fused render + JPEG front end + sparse wire packing, one dispatch."""
    y, cb, cr = render_to_jpeg_coefficients(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables, qy, qc)
    return sparse_pack(y, cb, cr, cap)


class SparseWireFetcher:
    """Predictive prefix fetch of sparse wire buffers.

    The wire buffer's used bytes are one contiguous prefix
    (``sparse_prefix_bytes``), so on a slow host link only that prefix
    need cross.  The fetcher predicts the next batch's prefix from the
    largest tile seen so far (with headroom), rounds to a granule so the
    device slice comes from a small, cached set of compiled shapes, and
    completes any under-predicted row with a follow-up fetch.
    """

    GRANULE = 16 * 1024

    def __init__(self, H: int, W: int, cap: int, headroom: float = 1.06):
        h16, w16 = (H + 15) // 16, (W + 15) // 16
        self.nb = h16 * w16 * 6
        self.cap = cap
        self.width = sparse_wire_width(H, W, cap)
        self.headroom = headroom
        # First fetch: a third of the worst case, floor one granule.
        self._k = self._round(max(self.GRANULE, self.width // 3))

    def _round(self, n: int) -> int:
        g = self.GRANULE
        return min(self.width, ((n + g - 1) // g) * g)

    def start(self, buf):
        """Slice the predicted prefix and start its async host copy.

        ``buf`` is the device u8[B, width] array from
        :func:`render_to_jpeg_sparse`.  Returns an opaque handle for
        :meth:`finish`.
        """
        k = self._k
        pre = buf if k >= self.width else buf[:, :k]
        if hasattr(pre, "copy_to_host_async"):
            pre.copy_to_host_async()
        return pre, buf, k

    def _needed(self, host: np.ndarray) -> np.ndarray:
        """Per-row used-prefix bytes, from the fetched headers.
        Overflowed tiles (total > cap) need only the header to be
        detected; clamp so prediction tracks real prefixes."""
        totals = host[:, :4].copy().view(np.int32).ravel()
        return (4 + self.nb
                + (ENTRY_BITS * np.clip(totals, 0, self.cap) + 7) // 8)

    def finish(self, handle) -> np.ndarray:
        """Complete a fetch: host u8[B, >=prefix] rows, decodable by
        the matching decoder."""
        import time as _time

        pre, buf, k = handle
        t0 = _time.perf_counter()
        host = np.asarray(pre)
        # Conflated: this wait covers the device render completing, not
        # just the wire, so its rate is only a lower bound on the link.
        _observe_fetch(host.nbytes, _time.perf_counter() - t0,
                       conflated=True)
        needed = self._needed(host)
        mx = int(needed.max(initial=0))
        self._k = self._round(int(mx * self.headroom))
        if mx <= k:
            return host
        # Under-predicted: complete ALL rows with one batched slice (a
        # per-row fetch would pay the link's latency floor B times).
        end = self._round(mx)
        t0 = _time.perf_counter()
        rest = np.asarray(buf[:, k:end])
        _observe_fetch(rest.nbytes, _time.perf_counter() - t0)
        return np.concatenate([host, rest], axis=1)

    def fetch(self, buf) -> np.ndarray:
        return self.finish(self.start(buf))


_FETCHERS: dict = {}
_FETCHERS_LOCK = threading.Lock()

# Optional wire-fetch observer: fn(nbytes, seconds), fed by the
# fetchers so an adaptive engine controller (utils.adaptive) can track
# the live device->host rate.  None = disabled (zero overhead).
_FETCH_OBSERVER = None


def set_fetch_observer(fn) -> None:
    global _FETCH_OBSERVER
    _FETCH_OBSERVER = fn


def _observe_fetch(nbytes: int, seconds: float,
                   conflated: bool = False) -> None:
    """``conflated``: the timed window synchronized on device EXECUTION
    as well as the transfer (the first fetch of a dispatched program),
    so bytes/seconds is a LOWER BOUND on the link rate, not a
    measurement of it."""
    # The link-health EWMA gauge (/metrics imageregion_link_mb_s) rides
    # every fetch, independent of whether an adaptive controller is
    # wired — it is what settles "weather or regression?" when a bench
    # headline moves.
    from ..utils.telemetry import LINK
    try:
        LINK.observe(nbytes, seconds, conflated)
    except Exception:       # pragma: no cover - telemetry must never
        pass                # break the serving path
    obs = _FETCH_OBSERVER
    if obs is not None:
        try:
            obs(nbytes, seconds, conflated)
        except Exception:   # pragma: no cover - observer bugs must not
            pass            # break the serving path


def wire_fetcher(H: int, W: int, cap: int) -> SparseWireFetcher:
    """Process-wide fetcher per (tile shape, cap): prediction state is
    shared across requests so the serving path warms up once."""
    key = (H, W, cap)
    with _FETCHERS_LOCK:
        f = _FETCHERS.get(key)
        if f is None:
            f = _FETCHERS[key] = SparseWireFetcher(H, W, cap)
        return f


def _compact_rows(bufs, lengths):
    """Device-side wire compaction: pack each row's used prefix
    contiguously so the host fetch carries exactly the needed bytes.

    ``bufs`` is u8[B, width] (either engine's wire layout), ``lengths``
    i32[B] gives each row's used-byte count (0 for rows the caller wants
    excluded, e.g. batch padding).  Returns u8[4*B + B*width]:

        [ lengths i32 LE x B | row0[:len0] | row1[:len1] | ... ]

    The prefix-fetch economics this enables: the old per-batch fetch
    sliced a COMMON prefix ``bufs[:, :k]`` with k predicted from the
    largest row — under per-request settings variance that over-fetches
    every smaller row (measured 1.8x wire waste at service load) and
    pads rows cost full freight.  Compacted, prediction tracks the SUM
    of row sizes (far lower relative variance), pad rows cost zero, and
    a group's wire bytes equal its entropy bytes.

    Formulated as ONE unique-index set-scatter (source byte (b, i)
    lands at ``cum[b] + i``; bytes past a row's length route out of
    bounds and drop): row ranges partition the output and offsets
    within a row are distinct, so XLA lowers it to plain stores.  The
    previous formulation ran backwards — per OUTPUT byte, a
    searchsorted over the row bounds plus a random-access 2-D gather —
    and that B*width-element gather dominated the packers' device
    profile (gathers serialize per element on TPU; unique-index stores
    do not).
    """
    B, width = bufs.shape
    lengths = lengths.astype(jnp.int32)
    cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                           jnp.cumsum(lengths)])
    col = jnp.arange(width, dtype=jnp.int32)
    tgt = jnp.where(col[None, :] < lengths[:, None],
                    cum[:-1, None] + col[None, :],
                    jnp.int32(1) << 30)
    data = jnp.zeros(B * width, jnp.uint8).at[tgt.reshape(-1)].set(
        bufs.reshape(-1), mode="drop", unique_indices=True)
    header = jax.lax.bitcast_convert_type(
        cum[1:] - cum[:-1], jnp.uint8).reshape(-1)
    return jnp.concatenate([header, data])


@functools.partial(jax.jit, static_argnames=("cap",))
def render_to_jpeg_sparse_compact(raw, window_start, window_end, family,
                                  coefficient, reverse, cd_start, cd_end,
                                  tables, qy, qc, n_valid, *, cap: int):
    """Fused render + sparse wire + device compaction, one dispatch.

    ``n_valid`` (traced i32) masks trailing batch-padding rows to zero
    wire bytes.  Overflowed rows (total > cap) compact to just their
    header + counts — enough for the host to detect the overflow and
    take the dense path without shipping a dropped-entry stream.
    """
    bufs = render_to_jpeg_sparse(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables, qy, qc, cap=cap)
    B = bufs.shape[0]
    H, W = raw.shape[-2:]
    nb = ((H + 15) // 16) * ((W + 15) // 16) * 6
    total = jax.lax.bitcast_convert_type(
        bufs[:, :4].reshape(B, 1, 4), jnp.int32).reshape(B)
    used = 4 + nb + (ENTRY_BITS * jnp.minimum(total, cap) + 7) // 8
    lengths = jnp.where(total <= cap, used, 4 + nb)
    lengths = jnp.where(jnp.arange(B) < n_valid, lengths, 0)
    return _compact_rows(bufs, lengths)


@functools.partial(jax.jit,
                   static_argnames=("cap", "cap_words", "h16", "w16"))
def render_to_jpeg_huffman_compact(raw, window_start, window_end, family,
                                   coefficient, reverse, cd_start, cd_end,
                                   tables, qy, qc, dc_code, dc_len,
                                   ac_code, ac_len, n_valid, *,
                                   h16: int, w16: int,
                                   cap: int, cap_words: int):
    """Fused render + device Huffman + device compaction, one dispatch.

    Overflowed rows (entries > cap or bits > word budget) compact to
    their 8-byte header only; the host detects and dense-falls-back.
    """
    bufs = render_to_jpeg_huffman(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables, qy, qc, dc_code, dc_len, ac_code,
        ac_len, h16=h16, w16=w16, cap=cap, cap_words=cap_words)
    B = bufs.shape[0]
    hdr = jax.lax.bitcast_convert_type(
        bufs[:, :8].reshape(B, 2, 4), jnp.int32)
    total, bits = hdr[:, 0], hdr[:, 1]
    ok = (total <= cap) & (bits <= cap_words * 32)
    words = jnp.where(ok, (bits + 31) // 32, 0)
    lengths = (8 + 4 * words).astype(jnp.int32)
    lengths = jnp.where(jnp.arange(B) < n_valid, lengths, 0)
    return _compact_rows(bufs, lengths)


class CompactWireFetcher:
    """Predictive prefix fetch of a COMPACTED wire buffer.

    The buffer is ``[lengths i32 x B | concatenated used prefixes]``
    (:func:`_compact_rows`), so prediction tracks the batch's total
    used bytes — much lower relative variance than the per-row max the
    uncompacted fetchers must bound.  Under-prediction costs ~1 link
    RTT (~100 ms on a tunnel — as dear as ~400 KB of transfer), so the
    headroom adapts asymmetrically: a miss raises it sharply, on-target
    batches decay it slowly back toward the floor.
    """

    GRANULE = 32 * 1024
    HEADROOM_FLOOR = 1.06
    HEADROOM_CEIL = 1.6
    # Fetch sizes snap UP to a geometric ladder (ratio 2^(1/4), <=19%
    # over-fetch) instead of a fine arithmetic granule: every distinct
    # device slice shape costs an XLA compile (seconds on a
    # tunnel-attached chip), so the shape set must be small and stable
    # while predictions drift with content.
    LADDER_RATIO = 2.0 ** 0.25

    def __init__(self, B: int, width: int, prior_row_bytes: int = None):
        self.B = B
        self.hdr = 4 * B
        self.width = self.hdr + B * width     # full device buffer bytes
        self.headroom = self.HEADROOM_FLOOR
        # The fetcher is shared process-wide per (engine, shape, caps,
        # batch) while up to pipeline_depth workers render groups of
        # the same bucket concurrently; the _k/headroom read-modify-
        # write must not interleave or the prefix prediction mis-trains
        # (each mis-prediction costs ~1 link RTT).
        self._lock = threading.Lock()
        ladder = []
        step = float(self.GRANULE)
        while step < self.width:
            ladder.append(int(step))
            step *= self.LADDER_RATIO
        ladder.append(self.width)
        self._ladder = ladder
        # First fetch: the caller's content prior (e.g. measured
        # bytes/px for the engine's stream class) with generous slack —
        # a first-touch miss pays a link RTT AND a one-time slice-shape
        # compile, both far dearer than a fat first fetch.
        prior = (int(prior_row_bytes * B * 1.5) if prior_row_bytes
                 else self.width // 8)
        self._k = self._round(max(self.GRANULE, prior))

    def _round(self, n: int) -> int:
        n = max(n, self.hdr)
        for step in self._ladder:
            if step >= n:
                return step
        return self.width

    def start(self, buf):
        with self._lock:
            k = self._k
        pre = buf if k >= self.width else buf[:k]
        if hasattr(pre, "copy_to_host_async"):
            pre.copy_to_host_async()
        return pre, buf, k

    def finish(self, handle) -> list:
        """Complete a fetch -> per-row u8 arrays (length B; excluded
        rows come back empty)."""
        import time as _time

        from ..utils.stopwatch import REGISTRY as _REG

        pre, buf, k = handle
        t0 = _time.perf_counter()
        host = np.asarray(pre)
        dt = _time.perf_counter() - t0
        _REG.record("wire.fetch", dt * 1000.0)
        _observe_fetch(host.nbytes, dt, conflated=True)
        lengths = host[:self.hdr].view(np.int32)
        total = self.hdr + int(lengths.sum())
        missed = total > k
        if missed:
            end = self._round(total)
            t0 = _time.perf_counter()
            rest = np.asarray(buf[k:end])
            dt = _time.perf_counter() - t0
            _REG.record("wire.fetch2", dt * 1000.0)
            _observe_fetch(rest.nbytes, dt)
            host = np.concatenate([host, rest])
        # Atomic prediction update: the fetches themselves run
        # unlocked (concurrent groups overlap on the wire by design);
        # only the read-modify-write of the shared training state is
        # serialized.
        with self._lock:
            if missed:
                self.headroom = min(self.HEADROOM_CEIL,
                                    self.headroom * 1.2)
            else:
                self.headroom = max(self.HEADROOM_FLOOR,
                                    self.headroom * 0.995)
            self._k = self._round(int(total * self.headroom))
        offs = self.hdr + np.concatenate(
            [[0], np.cumsum(lengths, dtype=np.int64)])
        return [host[offs[i]:offs[i + 1]] for i in range(self.B)]

    def fetch(self, buf) -> list:
        return self.finish(self.start(buf))


def compact_fetcher(engine: str, H: int, W: int, cap: int,
                    cap_words: int, B: int) -> CompactWireFetcher:
    """Process-wide prediction state per (engine, shape, caps, batch)."""
    if engine == "huffman":
        width = 8 + 4 * cap_words
        # Measured q85 fixed-table streams on WSI-class content run
        # ~0.10-0.12 B/px; 0.14 as the first-touch prior.
        prior = 8 + int(H * W * 0.14)
    else:
        width = sparse_wire_width(H, W, cap)
        # Sparse wire: counts (6 B per 16x16 MCU region... nb bytes)
        # plus ~3.6x the huffman stream's entropy bytes.
        prior = 4 + ((H + 15) // 16) * ((W + 15) // 16) * 6 \
            + int(H * W * 0.5)
    key = ("compact", engine, H, W, cap, cap_words, B)
    with _FETCHERS_LOCK:
        f = _FETCHERS.get(key)
        if f is None:
            f = _FETCHERS[key] = CompactWireFetcher(B, width, prior)
        return f


def _quality_widen(quality: "int | None") -> int:
    """Cap multiplier for high-quality quant tables: measured WSI
    content runs ~5% coefficient density at q80 but ~12% at q90 — past
    the 1/8 default budgets, which would silently drop every tile to
    the per-tile host dense path (~170 ms each).  One shared rule so
    the direct, batched, mesh and bitpack engines all stay on the
    device path at high quality."""
    return 2 if quality is not None and quality >= 88 else 1


def wire_header_i32(bufs: np.ndarray, word: int) -> np.ndarray:
    """The per-row i32 header field ``word`` of fetched wire buffers
    (one place for the layout; both engines lead with LE i32 words)."""
    return bufs[:, 4 * word:4 * word + 4].copy().view(np.int32).ravel()


def row_header_i32(row: np.ndarray, word: int) -> int:
    """Header field of ONE wire row (compacted rows may sit at
    unaligned offsets, so go through bytes, not a view)."""
    return int.from_bytes(row[4 * word:4 * word + 4].tobytes(),
                          "little", signed=True)


# Process-wide overflow memo: once a (shape, quality, engine) workload
# overflows its default cap, later groups start at the doubled cap
# instead of paying a wasted base dispatch per group.
_CAP_MEMO: dict = {}

# Per-workload TUNED Huffman tables for the device wire: the packer's
# code/length tables are runtime arrays, so swapping in tables built
# from the workload's own symbol statistics costs nothing on device and
# shrinks every stream ~4-8% (wire time AND payload).  Keyed
# (H, W, quality); value = ((dc_code, dc_len, ac_code, ac_len) i32
# kernel arrays, jfif 8-tuple spec for framing), or None when tuning
# failed (never retried).  Computed ONCE per workload on a background
# thread from a sample tile's dense coefficients; groups serve the
# fixed profile until the tuned tables are ready.  Single-process
# serving only — the mesh path keeps the fixed pod-agreed tables.
_TUNED_TABLES: dict = {}
_TUNED_PENDING: set = set()
_TUNED_LOCK = threading.Lock()


def spec_kernel_arrays(spec8) -> tuple:
    """A jfif 8-tuple spec -> the (dc_code, dc_len, ac_code, ac_len)
    i32 arrays the device packer takes — ONE projection shared by the
    serving tuner and the bench (a drifted duplicate would silently
    decouple what the bench measures from what serving runs)."""
    return (spec8[2].astype(np.int32), spec8[3].astype(np.int32),
            spec8[6].astype(np.int32), spec8[7].astype(np.int32))


def _compute_tuned_tables(key, dense_coefficients) -> None:
    """Build and publish the tuned spec for ``key``; any failure
    (device error, odd content) publishes None so serving never
    retries or blocks on tuning."""
    from ..jfif import symbol_frequencies, tuned_huffman_spec
    try:
        y, cb, cr = dense_coefficients(0)
        spec8 = tuned_huffman_spec(*symbol_frequencies(y, cb, cr))
        result = (spec_kernel_arrays(spec8), spec8)
    except Exception:       # pragma: no cover - tuning must never break
        result = None       # serving; the fixed profile keeps working
    with _TUNED_LOCK:
        _TUNED_TABLES[key] = result
        _TUNED_PENDING.discard(key)


def _maybe_start_tuning(key, dense_coefficients) -> None:
    with _TUNED_LOCK:
        if key in _TUNED_TABLES or key in _TUNED_PENDING:
            return
        _TUNED_PENDING.add(key)
    threading.Thread(
        target=_compute_tuned_tables, args=(key, dense_coefficients),
        name=f"hufftune-{key[0]}x{key[1]}", daemon=True).start()


def default_sparse_cap(H: int, W: int, quality: "int | None" = None
                       ) -> int:
    """Wire-buffer entry budget per tile: 1/8 of all coefficient slots
    (1/4 for quality >= 88, see :func:`_quality_widen`).

    Measured densities: synthetic WSI content ~3%, worst-case uniform
    noise ~45% (which overflows and takes the dense fallback — by design).
    """
    return max_sparse_cap(H, W) // 8 * _quality_widen(quality)


def max_sparse_cap(H: int, W: int) -> int:
    """Every coefficient slot of the (16-aligned) frame — the cap at which
    no tile can overflow (tests and noise workloads)."""
    nb = (H // 8) * (W // 8) + 2 * (H // 16) * (W // 16)
    return nb * 64


def sparse_to_dense(buf: np.ndarray, H: int, W: int, cap: int):
    """Rebuild (y, cb, cr) dense coefficient blocks from one wire buffer.

    Returns None if the buffer overflowed ``cap`` (entries were dropped).
    Pure-numpy; used by tests and the Python fallback encoder.  ``buf``
    may be a prefix fetch: any length >= ``sparse_prefix_bytes(total)``
    decodes.
    """
    # The wire buffer is packed for the 16-aligned (MCU-padded) grid, so
    # block counts use ceil — H/W may be the tile's true, unaligned size
    # (the native encoder does the same, jpegenc.cpp jpeg_encode_sparse).
    h16, w16 = (H + 15) // 16, (W + 15) // 16
    nb_y = h16 * w16 * 4
    nb_c = h16 * w16
    nb = nb_y + 2 * nb_c
    total = int(buf[:4].view(np.int32)[0])
    if total > cap:
        return None
    need = 4 + nb + (ENTRY_BITS * total + 7) // 8
    if len(buf) < need:
        raise ValueError(
            f"sparse buffer too short: {len(buf)} bytes < {need} needed")
    counts = buf[4:4 + nb].astype(np.int64)
    if int(counts.sum()) != total:
        raise ValueError("sparse buffer malformed: counts do not sum to "
                         "total")
    # Vectorized 18-bit field extraction: entry j lives MSB-first at bit
    # 18j; read a 32-bit big-endian window at its byte and shift.
    stream = np.pad(buf[4 + nb:], (0, 4)).astype(np.uint32)
    j = np.arange(total)
    bit = j * ENTRY_BITS
    byte0 = bit >> 3
    shift = bit & 7
    window = ((stream[byte0] << 24) | (stream[byte0 + 1] << 16)
              | (stream[byte0 + 2] << 8) | stream[byte0 + 3])
    field = (window >> (32 - 18 - shift)) & 0x3FFFF
    ps = (field >> 12).astype(np.int64)
    vs = (field & 0xFFF).astype(np.int16)
    vs = np.where(vs >= 2048, vs - 4096, vs).astype(np.int16)
    dense = np.zeros((nb, 64), np.int16)
    block_ids = np.repeat(np.arange(nb), counts)
    dense[block_ids, ps] = vs
    return (dense[:nb_y].reshape(nb_y, 64),
            dense[nb_y:nb_y + nb_c].reshape(nb_c, 64),
            dense[nb_y + nb_c:].reshape(nb_c, 64))


def encode_tiles_jpeg(packed, quality: int = 85, width: int | None = None,
                      height: int | None = None, executor=None) -> list:
    """Full TPU JPEG pipeline for a batch: packed RGBA -> JFIF bytes.

    Device: color transform + DCT + quantize + zigzag.  Host: entropy code
    each tile (native C++ when available, Python fallback), fanned out over
    ``executor`` threads when given (the ctypes call releases the GIL).

    ``packed`` is u32[B, H, W] with H, W multiples of 16; ``width``/
    ``height`` override the SOF0 dimensions for MCU-padded tiles.
    """
    B, H, W = packed.shape
    width = W if width is None else width
    height = H if height is None else height
    qy, qc = quant_tables(quality)
    y, cb, cr = packed_to_jpeg_coefficients(
        jnp.asarray(packed), qy.astype(np.int32), qc.astype(np.int32)
    )
    for a in (y, cb, cr):
        a.copy_to_host_async()
    y, cb, cr = np.asarray(y), np.asarray(cb), np.asarray(cr)

    from ..native import jpeg_native_available
    if jpeg_native_available():
        from ..native import jpeg_encode_native as _encode
    else:
        from ..jfif import encode_jfif as _encode

    def one(i):
        return _encode(y[i], cb[i], cr[i], width, height, quality)

    if executor is None:
        return [one(i) for i in range(B)]
    return list(executor.map(one, range(B)))


# ------------------------------------------------- device bit packing

@functools.lru_cache(maxsize=16)
def _mcu_scan_index(h16: int, w16: int) -> np.ndarray:
    """[n_mcu, 6] flat block indices (into [Y|Cb|Cr] raster blocks) in
    interleaved MCU scan order: 2x2 Y, then Cb, then Cr (T.81 A.2.3)."""
    nb_y = h16 * w16 * 4
    yw = w16 * 2
    my, mx = np.divmod(np.arange(h16 * w16), w16)
    idx = np.stack([
        (2 * my) * yw + 2 * mx, (2 * my) * yw + 2 * mx + 1,
        (2 * my + 1) * yw + 2 * mx, (2 * my + 1) * yw + 2 * mx + 1,
        nb_y + my * w16 + mx,
        nb_y + h16 * w16 + my * w16 + mx,
    ], axis=1)
    return idx.astype(np.int32)


def _category(x):
    """JPEG magnitude category of an i32 array, branchlessly (<= 11)."""
    a = jnp.abs(x)
    return sum((a >= (1 << b)).astype(jnp.int32) for b in range(11))


def _amplitude(x, s):
    """Amplitude bits: value as-is if positive, ones'-complement if not."""
    return jnp.where(x >= 0, x, x + jnp.left_shift(1, s) - 1)


def _bitpack_fixed(blocks, scan_idx, dc_code, dc_len, ac_code, ac_len,
                   cap_words: int):
    """Huffman bit-pack one tile's coefficient blocks on device.

    The serial half of JPEG vectorizes: per-coefficient (code, length)
    gathers from the fixed tables, a cumsum turns lengths into global bit
    offsets, and each field scatter-adds into at most two u32 stream words
    — different fields own disjoint bits, so add IS bitwise-or.  The one
    remaining serial step (0xFF byte stuffing) runs on the host over the
    finished ~100 KB stream (:func:`..jfif.finish_fixed_stream`).

    Args: ``blocks`` i16[nb, 64] zigzag coefficients ([Y|Cb|Cr] raster),
    ``scan_idx`` from :func:`_mcu_scan_index`, code/len arrays from
    :func:`..jfif.fixed_huffman_spec` (u32/i32), ``cap_words`` stream
    capacity.  Returns ``(words u32[cap_words], total_bits i32)``; a tile
    whose stream exceeds the cap is detected host-side via total_bits.
    """
    # All bit arithmetic in int32 (field values use at most 27 bits, and
    # disjoint-bit scatter-adds never carry, so signed adds are bitwise
    # exact); the stream is bitcast to u32 words at the end.
    v = blocks[scan_idx].astype(jnp.int32)        # [n_mcu, 6, 64]
    n_mcu = v.shape[0]

    # DC difference chains, one per component.
    dc = v[..., 0]
    def chain(x):
        flat = x.reshape(-1)
        return (flat - jnp.pad(flat[:-1], (1, 0))).reshape(x.shape)
    dcdiff = jnp.concatenate([
        chain(dc[:, :4]), chain(dc[:, 4:5]), chain(dc[:, 5:6]),
    ], axis=1)
    s_dc = _category(dcdiff)
    dc_f_val = jnp.left_shift(dc_code[s_dc], s_dc) | _amplitude(dcdiff, s_dc)
    dc_f_len = dc_len[s_dc] + s_dc

    # AC run-lengths from the gap to the previous nonzero position.
    ac = v[..., 1:]                               # [n_mcu, 6, 63]
    nz = ac != 0
    k = jnp.arange(1, 64, dtype=jnp.int32)
    posk = jnp.where(nz, k, 0)
    prev_incl = jax.lax.cummax(posk, axis=posk.ndim - 1)
    prev = jnp.pad(prev_incl[..., :-1], ((0, 0), (0, 0), (1, 0)))
    run = k - prev - 1
    z = jnp.where(nz, run >> 4, 0)
    rem = run & 15
    s_ac = _category(ac)
    sym = jnp.left_shift(rem, 4) | s_ac
    f2_val = jnp.left_shift(ac_code[sym], s_ac) | _amplitude(ac, s_ac)
    f2_len = jnp.where(nz, ac_len[sym] + s_ac, 0)
    f2_val = jnp.where(nz, f2_val, 0)

    zc, zl = ac_code[0xF0], ac_len[0xF0]          # ZRL
    f0_len = jnp.minimum(z, 2) * zl
    f0_val = jnp.where(
        z >= 2, jnp.left_shift(zc, zl) | zc, jnp.where(z == 1, zc, 0))
    f1_len = jnp.where(z >= 3, zl, 0)
    f1_val = jnp.where(z >= 3, zc, 0)

    has_eob = prev_incl[..., -1] < 63
    eob_val = jnp.where(has_eob, ac_code[0x00], 0)
    eob_len = jnp.where(has_eob, ac_len[0x00], 0)

    # Stream offsets, computed arithmetically rather than by materializing
    # an interleaved [.., 191]-field array (a minor dim of 191 pads to 256
    # lanes on TPU and multiplies HBM traffic ~6x; this was measured at
    # 1.2 s/batch vs ~0.1 s for the arithmetic form).  Stream order per
    # block is [dc | (f0 f1 f2) per coeff | eob]; blocks follow MCU scan
    # order, which dim order (n_mcu, 6) already is.
    coeff_len = f0_len + f1_len + f2_len                  # [n_mcu, 6, 63]
    within = jnp.cumsum(coeff_len, axis=2)
    block_ac_bits = within[..., -1]                       # [n_mcu, 6]
    block_bits = dc_f_len + block_ac_bits + eob_len
    block_end = jnp.cumsum(block_bits.reshape(-1)).reshape(n_mcu, 6)
    block_start = block_end - block_bits
    total_bits = block_end[-1, -1]

    dc_start = block_start
    f0_start = (block_start + dc_f_len)[..., None] + (within - coeff_len)
    f1_start = f0_start + f0_len
    f2_start = f1_start + f1_len
    eob_start = block_start + dc_f_len + block_ac_bits

    # ONE coalesced deposit pass over every field stream (non-unique
    # scatter-adds serialize on TPU, so the five per-field passes —
    # ten scatters — collapse to two): disjoint-bit adds commute, so
    # the packed stream is bit-identical to the per-field form.
    val = jnp.concatenate([a.reshape(-1) for a in (
        dc_f_val, f0_val, f1_val, f2_val, eob_val)])
    length = jnp.concatenate([a.reshape(-1) for a in (
        dc_f_len, f0_len, f1_len, f2_len, eob_len)])
    start = jnp.concatenate([a.reshape(-1) for a in (
        dc_start, f0_start, f1_start, f2_start, eob_start)])
    words = jnp.zeros(cap_words, jnp.int32)
    w = start >> 5
    r = start & 31
    sh0 = 32 - r - length                      # in [-30, 32]
    # Field values never set bit 31, so arithmetic >> == logical >>.
    c0 = jnp.where(
        sh0 >= 0,
        jnp.left_shift(val, jnp.minimum(sh0, 31)),
        jnp.right_shift(val, jnp.minimum(-sh0, 31)),
    )
    sh1 = 64 - r - length                      # in [2, 64]
    c1 = jnp.where(
        sh1 < 32, jnp.left_shift(val, jnp.maximum(sh1, 0) & 31), 0)
    live = length > 0
    c0 = jnp.where(live, c0, 0)
    c1 = jnp.where(live, c1, 0)
    words = words.at[w].add(c0, mode="drop")
    words = words.at[w + 1].add(c1, mode="drop")
    return (jax.lax.bitcast_convert_type(words, jnp.uint32),
            total_bits.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("cap_words",))
def render_to_jpeg_bits(raw, window_start, window_end, family, coefficient,
                        reverse, cd_start, cd_end, tables, qy, qc,
                        scan_idx, dc_code, dc_len, ac_code, ac_len,
                        cap_words: int):
    """Fully fused batched render -> entropy-coded JPEG bitstream words.

    Everything from raw pixels to Huffman-packed stream bits runs in one
    device dispatch; the host only 0xFF-stuffs and frames the result
    (:func:`..jfif.finish_fixed_stream`).  Returns
    ``(words u32[B, cap_words], total_bits i32[B])``.
    """
    y, cb, cr = render_to_jpeg_coefficients(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables, qy, qc)
    B = y.shape[0]
    blocks = jnp.concatenate(
        [y.reshape(B, -1, 64), cb.reshape(B, -1, 64),
         cr.reshape(B, -1, 64)], axis=1)
    return jax.vmap(
        lambda b: _bitpack_fixed(b, scan_idx, dc_code, dc_len, ac_code,
                                 ac_len, cap_words)
    )(blocks)


# ------------------------------------ compacted-entry device Huffman

def default_words_cap(H: int, W: int, quality: "int | None" = None
                      ) -> int:
    """Stream-word budget per tile for the compacted Huffman packer:
    H*W/8 bytes (~1.6x the measured fixed-table stream at benchmark
    density, doubled for quality >= 88; overflow falls back to the
    dense host path)."""
    return (H * W) // 8 // 4 * _quality_widen(quality)


def _scan_order_flat(h16: int, w16: int) -> np.ndarray:
    """[nb] flat indices mapping raster [Y|Cb|Cr] blocks into the JPEG
    interleaved MCU scan order (2x2 Y, Cb, Cr per MCU)."""
    return _mcu_scan_index(h16, w16).reshape(-1)


@functools.partial(jax.jit,
                   static_argnames=("cap", "cap_words", "h16", "w16"))
def huffman_pack(y, cb, cr, cap: int, cap_words: int,
                 dc_code, dc_len, ac_code, ac_len, *, h16: int, w16: int):
    """Entropy-code quantized coefficients on device with fixed tables.

    The wire-optimal sibling of :func:`sparse_pack`: instead of 18-bit
    (pos, val) entries the device emits the actual Huffman bitstream
    (``jfif.fixed_huffman_spec`` tables — one DC + one AC table for all
    components), so only ~Huffman-entropy bytes cross the link and the
    host merely 0xFF-stuffs and frames (``jfif.finish_fixed_stream``).

    The legacy full-grid device-Huffman path (``_bitpack_fixed``) paid a
    deposit scatter for EVERY coefficient slot (~15M updates/tile).
    Here all per-entry work runs
    on the ``cap``-sized COMPACTED stream (one unique-index set-scatter,
    the same trick as ``sparse_pack``), and the bit deposits touch
    ~1.3M update slots/tile across TWO coalesced scatter passes: the
    dense per-block fields (DC diff + EOB, over ``2*nb``) ride one and
    the per-entry fields (folded ZRLs + main code+amplitude, over
    ``2*cap``) the other — non-unique scatter-adds serialize on TPU,
    so halving the pass count matters as much as the slot count.

    Per tile the output is ``[total_entries i32 | total_bits i32 |
    stream words u32[cap_words]]`` as LE bytes; the used prefix is
    ``8 + 4*ceil(total_bits/32)``.  Overflow (entries > cap or bits >
    32*cap_words) is detected host-side from the header.
    """
    B = y.shape[0]
    nb = y.shape[1] + cb.shape[1] + cr.shape[1]
    N = nb * 64
    # Interleaved MCU scan order: everything downstream — DC chains,
    # entry order, bit offsets — follows the JPEG scan.  The reorder is
    # a static permutation with MCU structure, so it lowers to reshapes
    # + one transpose (HBM block copies) rather than a 1.5M-element
    # gather: raster Y block (2my+dy, 2mx+dx) -> scan slot (my, mx, dy,
    # dx); Cb/Cr raster order already matches the MCU scan.
    yi = (y.astype(jnp.int32)
          .reshape(B, h16, 2, w16, 2, 64)
          .transpose(0, 1, 3, 2, 4, 5)
          .reshape(B, h16 * w16, 4, 64))
    blocks = jnp.concatenate(
        [yi, cb.astype(jnp.int32)[:, :, None],
         cr.astype(jnp.int32)[:, :, None]], axis=2,
    ).reshape(B, nb, 64)                                 # [B, nb, 64]
    mask = blocks != 0
    counts = mask.sum(-1)                                # [B, nb]
    total = counts.sum(-1).astype(jnp.int32)             # [B]

    # Dense per-block DC fields: diff against the previous block of the
    # same component in scan order.  The predecessor pattern is
    # structural per MCU slot (Y1..Y3 <- the Y before them in the same
    # MCU; Y0/Cb/Cr <- the same slot's value one MCU back), so it is
    # shifted slices, not a gather — TPU gathers cost ~100ns/element.
    dc = blocks[..., 0]
    n_mcu = nb // 6
    d6 = dc.reshape(B, n_mcu, 6)
    prev_mcu = jnp.pad(d6[:, :-1], ((0, 0), (1, 0), (0, 0)))
    pred = jnp.concatenate([
        prev_mcu[:, :, 3:4],        # Y0 <- previous MCU's Y3
        d6[:, :, 0:3],              # Y1..Y3 <- Y0..Y2
        prev_mcu[:, :, 4:6],        # Cb/Cr <- previous MCU's Cb/Cr
    ], axis=2).reshape(B, nb)
    dcdiff = dc - pred
    s_dc = _category(dcdiff)
    # One fused (len << 16 | code) table -> one gather instead of two.
    dc_cl = (jnp.left_shift(dc_len, 16) | dc_code)[s_dc]
    dc_fval = (jnp.left_shift(dc_cl & 0xFFFF, s_dc)
               | _amplitude(dcdiff, s_dc))
    dc_flen = jnp.right_shift(dc_cl, 16) + s_dc
    has_eob = ~mask[..., 63]
    eob_val = jnp.where(has_eob, ac_code[0x00], 0)
    eob_len = jnp.where(has_eob, ac_len[0x00], 0)

    # Compacted (pos, val) entry stream, scan-ordered.
    flat_scan = blocks.reshape(B, N)
    m = flat_scan != 0
    wi = jnp.cumsum(m, axis=1) - 1
    pos64 = jnp.arange(N, dtype=jnp.int32) % 64
    fieldc = (pos64 << 12) | (flat_scan & 0xFFF)

    def compact_one(m_row, w_row, f_row):
        tgt = jnp.where(m_row & (w_row < cap), w_row, jnp.int32(1) << 30)
        return jnp.zeros(cap, jnp.int32).at[tgt].set(
            f_row, mode="drop", unique_indices=True)

    comp = jax.vmap(compact_one)(m, wi, fieldc)          # [B, cap]
    epos = comp >> 12
    ev = comp & 0xFFF
    evals = jnp.where(ev >= 2048, ev - 4096, ev)
    jidx = jnp.arange(cap, dtype=jnp.int32)
    evalid = jidx[None, :] < total[:, None]

    # First-of-block flags (scattered at each nonempty block's first
    # entry slot).
    nonempty = counts > 0
    S = jnp.cumsum(counts, axis=1) - counts              # exclusive

    def flag_one(S_row, ne_row):
        tgt = jnp.where(ne_row & (S_row < cap), S_row, jnp.int32(1) << 30)
        return jnp.zeros(cap, jnp.int32).at[tgt].set(
            1, mode="drop", unique_indices=True)

    first = jax.vmap(flag_one)(S, nonempty)

    # AC fields per entry (DC entries — pos 0, always a block's first
    # entry — carry no AC field; the dense pass above covers them).
    prevpos = jnp.pad(epos[:, :-1], ((0, 0), (1, 0)))
    prev = jnp.where(first == 1, 0, prevpos)
    run = epos - prev - 1
    ac_live = evalid & (epos != 0)
    s_ac = _category(evals)
    z = jnp.clip(run >> 4, 0, 3)
    rem = jnp.where(ac_live, run & 15, 0)
    sym = jnp.left_shift(rem, 4) | s_ac
    # One fused (len << 16 | code) gather over the [B, cap] stream.
    ac_cl = (jnp.left_shift(ac_len, 16) | ac_code)[sym]
    main_val = (jnp.left_shift(ac_cl & 0xFFFF, s_ac)
                | _amplitude(evals, s_ac))
    main_len = jnp.where(ac_live, jnp.right_shift(ac_cl, 16) + s_ac, 0)
    main_val = jnp.where(ac_live, main_val, 0)
    # Up to three folded ZRL codes as ONE field: the fixed spec's ZRL is
    # 10 bits, so 3 x 10 = 30 fits an i32 deposit (one pass, not two).
    zc, zl = ac_code[0xF0], ac_len[0xF0]
    nz_ = jnp.where(ac_live, z, 0)
    zrl_len = nz_ * zl
    one = zc
    two = jnp.left_shift(zc, zl) | zc
    three = jnp.left_shift(two, zl) | zc
    zrl_val = jnp.where(nz_ == 3, three,
                        jnp.where(nz_ == 2, two,
                                  jnp.where(nz_ == 1, one, 0)))
    ent_len = zrl_len + main_len

    # Bit offsets, all arithmetic: entry cumsum + per-block bases.
    ac_excl = jnp.cumsum(ent_len, axis=1) - ent_len      # [B, cap]
    ac_tot = (ac_excl[:, -1] + ent_len[:, -1])[:, None]
    acX = jnp.concatenate([ac_excl, ac_tot], axis=1)     # [B, cap+1]
    e0 = jnp.minimum(S, cap)
    e1 = jnp.minimum(S + counts, cap)
    block_ac = (jnp.take_along_axis(acX, e1, 1)
                - jnp.take_along_axis(acX, e0, 1))
    block_bits = dc_flen + block_ac + eob_len
    block_start = jnp.cumsum(block_bits, axis=1) - block_bits
    total_bits = (block_start[:, -1] + block_bits[:, -1]).astype(jnp.int32)

    # Per-entry bit base: scatter each nonempty block's base into its
    # first entry slot, then carry it across the block's entries with a
    # running max — NOT a [B, cap] gather.  Valid because the bases are
    # provably non-decreasing across nonempty blocks: for consecutive
    # nonempty b < b', base_{b'} - base_b = (sum of block_bits over
    # [b, b')) + dc_flen_{b'} - dc_flen_b - block_ac_b
    # >= eob_b + dc_flen_{b'} >= 0 (empty blocks between them only add
    # their dc+eob bits), and base_0 = dc_flen_0 >= 0, so zero-filled
    # gaps never win the max.
    base_b = block_start + dc_flen - jnp.take_along_axis(acX, e0, 1)

    def base_first_one(S_row, ne_row, vals):
        tgt = jnp.where(ne_row & (S_row < cap), S_row, jnp.int32(1) << 30)
        return jnp.zeros(cap, jnp.int32).at[tgt].set(
            vals, mode="drop", unique_indices=True)

    base_at_first = jax.vmap(base_first_one)(S, nonempty, base_b)
    carried = jax.lax.cummax(base_at_first, axis=1)
    estart = jnp.where(ac_live, carried + ac_excl, 0)

    oob = jnp.int32(1) << 30

    def deposit(words, val, length, start):
        w = start >> 5
        rb = start & 31
        sh0 = 32 - rb - length
        c0 = jnp.where(
            sh0 >= 0,
            jnp.left_shift(val, jnp.minimum(sh0, 31)),
            jnp.right_shift(val, jnp.minimum(-sh0, 31)),
        )
        sh1 = 64 - rb - length
        c1 = jnp.where(
            sh1 < 32, jnp.left_shift(val, jnp.maximum(sh1, 0) & 31), 0)
        # Route dead lanes (zero-length fields; second words the field
        # never crosses into) out of bounds: drop-mode scatters skip
        # them, and most fields are < 32 bits so this halves the
        # effective update stream.
        live = length > 0
        w0 = jnp.where(live, w, oob)
        w1 = jnp.where(live & (rb + length > 32), w + 1, oob)
        words = words.at[w0].add(c0, mode="drop")
        words = words.at[w1].add(c1, mode="drop")
        return words

    def pack_one(dcv, dcl, bst, bac, ev_, el_, zv, zlen, mv, ml, est):
        words = jnp.zeros(cap_words + 1, jnp.int32)
        # Coalesced deposits: the two dense per-block fields (DC diff,
        # EOB) ride one scatter pass and the two per-entry fields
        # (folded ZRLs, main code+amplitude) ride another — 2 deposit
        # passes (4 scatter-adds) instead of 4 (8).  Scatter-adds over
        # disjoint bits commute, so the stream is bit-identical; the
        # win is fewer serialized non-unique scatter ops per tile.
        words = deposit(words,
                        jnp.concatenate([dcv, ev_]),
                        jnp.concatenate([dcl, el_]),
                        jnp.concatenate([bst, bst + dcl + bac]))
        words = deposit(words,
                        jnp.concatenate([zv, mv]),
                        jnp.concatenate([zlen, ml]),
                        jnp.concatenate([est, est + zlen]))
        return words[:cap_words]

    words = jax.vmap(pack_one)(
        dc_fval, dc_flen, block_start, block_ac, eob_val, eob_len,
        zrl_val, zrl_len, main_val, main_len, estart)

    words_u8 = jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(words, jnp.uint32), jnp.uint8
    ).reshape(B, -1)
    hdr = jax.lax.bitcast_convert_type(
        jnp.stack([total, total_bits], axis=1), jnp.uint8).reshape(B, -1)
    return jnp.concatenate([hdr, words_u8], axis=1)


@functools.partial(jax.jit,
                   static_argnames=("cap", "cap_words", "h16", "w16"))
def render_to_jpeg_huffman(raw, window_start, window_end, family,
                           coefficient, reverse, cd_start, cd_end, tables,
                           qy, qc, dc_code, dc_len, ac_code, ac_len,
                           *, h16: int, w16: int,
                           cap: int, cap_words: int):
    """Fused render + JPEG front end + device Huffman, one dispatch."""
    y, cb, cr = render_to_jpeg_coefficients(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables, qy, qc)
    return huffman_pack(y, cb, cr, cap, cap_words,
                        dc_code, dc_len, ac_code, ac_len,
                        h16=h16, w16=w16)


class HuffmanWireFetcher(SparseWireFetcher):
    """Prefix fetch for the Huffman wire: needed = 8 + stream bytes."""

    def __init__(self, H: int, W: int, cap: int, cap_words: int,
                 headroom: float = 1.06):
        self.cap = cap
        self.cap_words = cap_words
        self.width = 8 + 4 * cap_words
        self.headroom = headroom
        self._k = self._round(max(self.GRANULE, self.width // 3))

    def _needed(self, host: np.ndarray) -> np.ndarray:
        bits = host[:, 4:8].copy().view(np.int32).ravel()
        bits = np.clip(bits, 0, self.cap_words * 32)
        return 8 + 4 * ((bits + 31) // 32)


def huffman_spec_arrays():
    """(dc_code, dc_len, ac_code, ac_len) i32 arrays for the packer."""
    from ..jfif import fixed_huffman_spec
    _, _, dc_code, dc_len, _, _, ac_code, ac_len = fixed_huffman_spec()
    return (dc_code.astype(np.int32), dc_len.astype(np.int32),
            ac_code.astype(np.int32), ac_len.astype(np.int32))


def finish_huffman_batch(bufs, dims, H: int, W: int,
                         quality: int, cap: int, cap_words: int,
                         dense_fallback=None, spec=None,
                         on_tile=None) -> list:
    """Fetched Huffman wire rows -> JFIF bytes per tile.

    ``bufs`` indexes per-row u8 buffers: a 2D [B, >=prefix] array (the
    uncompacted wire) or a list of per-row arrays (the compacted wire,
    where rows carry exactly their used bytes).  Host work is O(stream
    bytes): byte-swap + 0xFF-stuff + frame (``jfif.finish_fixed_stream``).
    Overflowed tiles (entries > cap or bits > capacity) — and tiles whose
    ``dims`` entry is None (callers mark tiles the packed stream cannot
    serve, e.g. bucket-padded ones) — go through
    ``dense_fallback(i) -> bytes``.

    ``spec`` (jfif 8-tuple) frames with TUNED shared tables when the
    device packed the stream with them; None = the fixed profile.
    """
    from ..jfif import finish_fixed_stream, finish_stream_with_spec

    out = []
    for i, dim in enumerate(dims):
        if dim is None:
            if dense_fallback is None:
                raise ValueError("tile %d needs the dense path but no "
                                 "fallback was given" % i)
            out.append(dense_fallback(i))
            if on_tile is not None:
                on_tile(i, out[-1])
            continue
        w_, h_ = dim
        row = bufs[i]
        total = row_header_i32(row, 0)
        bits = row_header_i32(row, 1)
        if total > cap or bits > cap_words * 32:
            if dense_fallback is None:
                raise ValueError(
                    f"huffman wire overflow (entries={total}, bits={bits})")
            out.append(dense_fallback(i))
            if on_tile is not None:
                on_tile(i, out[-1])
            continue
        nwords = (bits + 31) // 32
        # Compacted rows can sit at unaligned offsets in the fetched
        # stream; ascontiguousarray re-bases so the u32 view is legal.
        words = np.ascontiguousarray(
            row[8:8 + 4 * nwords]).view("<u4")
        out.append(finish_fixed_stream(words, bits, w_, h_, quality)
                   if spec is None else
                   finish_stream_with_spec(words, bits, w_, h_,
                                           quality, spec))
        if on_tile is not None:
            on_tile(i, out[-1])
    return out


class TpuJpegEncoder:
    """Host-side driver for the fully-fused JPEG path at one tile shape.

    Holds the per-shape constants (MCU scan map, fixed Huffman code
    tables, quant tables, stream capacity) and finishes fetched streams
    into JFIF files, falling back to the dense coefficient path for tiles
    whose stream overflows the capacity.
    """

    def __init__(self, H: int, W: int, quality: int = 85,
                 cap_bytes: int | None = None):
        from ..jfif import fixed_huffman_spec
        if H % 16 or W % 16:
            raise ValueError("tile shape must be MCU (16) aligned")
        self.H, self.W, self.quality = H, W, quality
        self.cap_words = (cap_bytes or
                          (H * W) // 4 * _quality_widen(quality)) // 4
        _, _, dc_code, dc_len, _, _, ac_code, ac_len = fixed_huffman_spec()
        self.consts = (
            jnp.asarray(_mcu_scan_index(H // 16, W // 16)),
            jnp.asarray(dc_code.astype(np.int32)),   # codes fit 16 bits
            jnp.asarray(dc_len.astype(np.int32)),
            jnp.asarray(ac_code.astype(np.int32)),
            jnp.asarray(ac_len.astype(np.int32)),
        )
        qy, qc = quant_tables(quality)
        self.qy = jnp.asarray(qy.astype(np.int32))
        self.qc = jnp.asarray(qc.astype(np.int32))

    def render_batch(self, raw, *settings_args):
        """Dispatch the fused kernel; returns (words, total_bits) handles."""
        words, bits = render_to_jpeg_bits(
            raw, *settings_args, self.qy, self.qc, *self.consts,
            cap_words=self.cap_words)
        words.copy_to_host_async()
        bits.copy_to_host_async()
        return words, bits

    def finish_batch(self, words, bits, dense_fallback=None,
                     executor=None) -> list:
        """Fetched stream words -> JFIF bytes per tile."""
        from ..jfif import finish_fixed_stream
        words = np.asarray(words)
        bits = np.asarray(bits)

        def one(i):
            if bits[i] > self.cap_words * 32:
                if dense_fallback is None:
                    raise ValueError(
                        f"stream overflow: {bits[i]} bits > cap")
                return dense_fallback(i)
            return finish_fixed_stream(words[i], int(bits[i]), self.W,
                                       self.H, self.quality)

        if executor is None:
            return [one(i) for i in range(words.shape[0])]
        return list(executor.map(one, range(words.shape[0])))

    def encode_batch(self, raw, *settings_args, dense_fallback=None,
                     executor=None) -> list:
        return self.finish_batch(
            *self.render_batch(raw, *settings_args),
            dense_fallback=dense_fallback, executor=executor)


def dense_encoder():
    """The per-tile dense-coefficient entropy coder: native if available,
    else Python.  Returns ``encode(y, cb, cr, width, height, quality) ->
    bytes``."""
    from ..native import jpeg_native_available
    if jpeg_native_available():
        from ..native import jpeg_encode_native
        return jpeg_encode_native
    from ..jfif import encode_jfif
    return encode_jfif


def sparse_encoder():
    """The per-tile sparse entropy coder: native if available, else Python.

    Returns ``encode(buf, width, height, quality, cap) -> bytes``, raising
    ``native.SparseOverflowError`` when the buffer dropped entries.
    """
    from ..native import SparseOverflowError, jpeg_native_available
    if jpeg_native_available():
        from ..native import jpeg_encode_sparse_native
        return jpeg_encode_sparse_native

    from ..jfif import encode_jfif

    def _encode(buf, w, h, q, cap_):
        dense = sparse_to_dense(buf, h, w, cap_)
        if dense is None:
            raise SparseOverflowError(f"overflow (cap={cap_})")
        y, cb, cr = dense
        return encode_jfif(y, cb, cr, w, h, q)

    return _encode


def encode_sparse_buffers(bufs: np.ndarray, width: int, height: int,
                          quality: int, cap: int, executor=None,
                          dense_fallback=None) -> list:
    """Entropy-encode a batch of fetched sparse wire buffers to JFIF.

    ``bufs`` indexes per-row u8 buffers: the host u8[B, ...] array from
    :func:`render_to_jpeg_sparse`, or a list of per-row arrays (the
    compacted wire).  Tiles whose coefficient density overflowed ``cap``
    are re-encoded via ``dense_fallback(i) -> bytes`` when given (else
    ValueError propagates).
    """
    from ..native import SparseOverflowError
    _encode = sparse_encoder()

    def one(i):
        try:
            return _encode(bufs[i], width, height, quality, cap)
        except SparseOverflowError:
            if dense_fallback is None:
                raise
            return dense_fallback(i)

    if executor is None:
        return [one(i) for i in range(len(bufs))]
    return list(executor.map(one, range(len(bufs))))


_HUFF_FETCHERS: dict = {}


def huffman_wire_fetcher(H: int, W: int, cap: int,
                         cap_words: int) -> "HuffmanWireFetcher":
    key = (H, W, cap, cap_words)
    with _FETCHERS_LOCK:
        f = _HUFF_FETCHERS.get(key)
        if f is None:
            f = _HUFF_FETCHERS[key] = HuffmanWireFetcher(H, W, cap,
                                                         cap_words)
        return f


def render_batch_to_jpeg(raw, window_start, window_end, family, coefficient,
                         reverse, cd_start, cd_end, tables, quality: int,
                         dims, cap: int | None = None,
                         engine: str = "sparse",
                         tune: bool = True, on_tile=None) -> list:
    """Serving-path helper: one batched device dispatch -> JFIF per tile.

    ``raw`` is [B, C, H, W] with H, W multiples of 16 (callers edge-pad;
    render is pointwise so padding commutes with it) and per-tile settings
    stacked along B as in :func:`render_to_jpeg_sparse`.  ``dims`` gives
    each tile's true ``(width, height)`` written into its SOF0 header —
    the decoder crops the MCU padding away.  A tile whose own ceil-16
    grid is smaller than (H, W) (spatial bucketing bounding the compile
    set) is entropy-coded from the top-left block subgrid on the host.
    Overflowing tiles re-run through the dense coefficient path.

    ``engine`` selects the device wire format: ``"sparse"`` (18-bit
    coefficient entries + host entropy coding — wins on fast links) or
    ``"huffman"`` (device fixed-table Huffman, ~3x fewer wire bytes —
    wins on slow/congested links).  The packed Huffman stream covers the
    full (H, W) grid, so a group containing bucket-padded tiles (true
    grid smaller than (H, W)) falls back to the sparse engine as a
    whole — one dispatch either way, never per-tile re-renders.

    ``on_tile(i, jpeg_bytes)`` (optional) fires the moment tile ``i``'s
    encode slice lands — the batcher's first-tile-out settlement hook:
    tile 0's waiter can be answered while tile N-1 is still entropy
    coding, instead of every waiter parking behind the batch tail.  The
    bytes passed are EXACTLY the returned list's entry (byte-identity is
    the streaming contract); callback exceptions are the caller's.
    """
    B, C, H, W = raw.shape
    if cap is None:
        cap = default_sparse_cap(H, W, quality)
    qy, qc = (np.asarray(t, np.int32) for t in quant_tables(quality))

    def dense_coefficients(i):
        y, cb, cr = render_to_jpeg_coefficients(
            raw[i:i + 1],
            *(a[i:i + 1] if getattr(a, "ndim", 0) else a
              for a in (window_start, window_end, family, coefficient,
                        reverse)),
            cd_start, cd_end,
            tables[i:i + 1], qy, qc)
        return np.asarray(y)[0], np.asarray(cb)[0], np.asarray(cr)[0]

    n = len(dims)
    all_exact = all((h_ + 15) // 16 * 16 == H
                    and (w_ + 15) // 16 * 16 == W for (w_, h_) in dims)
    if engine == "huffman" and all_exact:
        # Tuned per-workload tables when ready (fixed profile until
        # then, and forever if tuning failed); the framing below must
        # declare whichever tables coded the stream.
        tuned = _TUNED_TABLES.get((H, W, quality))
        if tuned is not None:
            spec_arrays, frame_spec = tuned
        else:
            spec_arrays, frame_spec = huffman_spec_arrays(), None

        def dispatch_huffman(c, cw):
            bufs = render_to_jpeg_huffman_compact(
                raw, window_start, window_end, family, coefficient,
                reverse, cd_start, cd_end, tables, qy, qc,
                *spec_arrays, np.int32(n),
                h16=H // 16, w16=W // 16, cap=c, cap_words=cw)
            return compact_fetcher("huffman", H, W, c, cw,
                                   B).fetch(bufs)[:n]

        cap_words = default_words_cap(H, W, quality)
        memo_key = ("huffman", H, W, quality)
        if _CAP_MEMO.get(memo_key):
            cap, cap_words = cap * 2, cap_words * 2
        rows = dispatch_huffman(cap, cap_words)
        totals = np.array([row_header_i32(r, 0) for r in rows])
        bits = np.array([row_header_i32(r, 1) for r in rows])
        over = (totals > cap) | (bits > cap_words * 32)
        rescuable = ((totals <= 2 * cap)
                     & (bits <= 2 * cap_words * 32))
        if memo_key not in _CAP_MEMO and (over & rescuable).any():
            # Cap overflow (dense content, narrow windows): ONE retry of
            # the whole batch at doubled caps instead of per-tile dense
            # re-renders, whose full-coefficient fetches (~6 MB/tile)
            # can cost seconds each on a congested link.  Skipped when
            # every overflowing tile exceeds even the doubled caps (the
            # retry could rescue nothing).  First retry per (shape,
            # quality) compiles the 2x variant — a one-time stall the
            # memo (and the persistent compilation cache) then avoids by
            # starting such workloads at 2x.
            _CAP_MEMO[memo_key] = True
            cap, cap_words = cap * 2, cap_words * 2
            rows = dispatch_huffman(cap, cap_words)

        _dense_encode = dense_encoder()

        def dense_tile(i):
            # Still overflowing at 2x: re-encode from dense coefficients.
            w_, h_ = dims[i]
            return _dense_encode(*dense_coefficients(i), w_, h_, quality)

        if tuned is None and tune:
            # One-time background tuning from this workload's first
            # group (a single dense-coefficient sample).  ``tune=False``
            # callers (prewarm's all-zero compile probes) must never
            # seed the tables real traffic will be served with.
            _maybe_start_tuning((H, W, quality), dense_coefficients)
        from ..utils.stopwatch import stopwatch
        with stopwatch("jfif.encodeBatch"):
            return finish_huffman_batch(
                rows, dims, H, W, quality, cap, cap_words,
                dense_fallback=dense_tile, spec=frame_spec,
                on_tile=on_tile)

    def dispatch_sparse(c):
        bufs = render_to_jpeg_sparse_compact(
            raw, window_start, window_end, family, coefficient, reverse,
            cd_start, cd_end, tables, qy, qc, np.int32(n), cap=c)
        return compact_fetcher("sparse", H, W, c, 0, B).fetch(bufs)[:n]

    memo_key = ("sparse", H, W, quality)
    if _CAP_MEMO.get(memo_key):
        cap = cap * 2
    rows = dispatch_sparse(cap)
    totals = np.array([row_header_i32(r, 0) for r in rows])
    if (memo_key not in _CAP_MEMO
            and ((totals > cap) & (totals <= 2 * cap)).any()):
        # Same one-shot widening + memo as the huffman engine above.
        _CAP_MEMO[memo_key] = True
        cap = cap * 2
        rows = dispatch_sparse(cap)

    from ..utils.stopwatch import stopwatch
    with stopwatch("jfif.encodeBatch"):
        return finish_sparse_to_jpegs(rows, dims, H, W, quality, cap,
                                      dense_coefficients,
                                      on_tile=on_tile)


def finish_sparse_to_jpegs(bufs, dims, H: int, W: int, quality: int,
                           cap: int, dense_coefficients,
                           on_tile=None) -> list:
    """Host tail of the sparse serving path: fetched wire rows -> JFIF.

    ``dims`` gives each tile's true ``(width, height)``; tiles whose own
    ceil-16 grid is smaller than the bucketed (H, W) are entropy-coded
    from the top-left block subgrid, and tiles that overflowed ``cap``
    re-render through ``dense_coefficients(i) -> (y, cb, cr)``.
    """
    from ..native import SparseOverflowError

    _encode = sparse_encoder()
    _dense_encode = dense_encoder()

    out = []
    for i, (w_, h_) in enumerate(dims):
        exact = ((h_ + 15) // 16 * 16 == H and (w_ + 15) // 16 * 16 == W)
        try:
            if exact:
                out.append(_encode(bufs[i], w_, h_, quality, cap))
                if on_tile is not None:
                    on_tile(i, out[-1])
                continue
            dense = sparse_to_dense(bufs[i], H, W, cap)
            if dense is None:
                raise SparseOverflowError(f"overflow (cap={cap})")
        except SparseOverflowError:
            dense = dense_coefficients(i)
        y, cb, cr = slice_block_subgrid(*dense, H, W, w_, h_) \
            if not exact else dense
        out.append(_dense_encode(y, cb, cr, w_, h_, quality))
        if on_tile is not None:
            on_tile(i, out[-1])
    return out


def pad_to_mcu(rgba: np.ndarray) -> np.ndarray:
    """Edge-replicate u8[H, W, ...] so H and W are multiples of 16."""
    H, W = rgba.shape[:2]
    ph, pw = (-H) % 16, (-W) % 16
    if ph == 0 and pw == 0:
        return rgba
    pad = [(0, ph), (0, pw)] + [(0, 0)] * (rgba.ndim - 2)
    return np.pad(rgba, pad, mode="edge")


def pad_planes_to_mcu(raw, target_h: int | None = None,
                      target_w: int | None = None):
    """Edge-replicate [C, h, w] planes to a 16-aligned grid.

    Render is pointwise, so padding raw and rendering equals rendering and
    edge-replicating the image; replication (not zeros) keeps the padding
    out of the edge blocks' DCT energy.  ``target_h``/``target_w`` pad to
    a larger (bucketed) grid; default is the tile's own ceil-16 grid.
    Device-resident input (the HBM raw-tile cache) pads on device.
    """
    h, w = raw.shape[-2:]
    th = target_h if target_h is not None else h + (-h) % 16
    tw = target_w if target_w is not None else w + (-w) % 16
    if th % 16 or tw % 16 or th < h or tw < w:
        raise ValueError(f"bad MCU pad target ({th}, {tw}) for ({h}, {w})")
    if (th, tw) == (h, w):
        return raw
    xp = np if isinstance(raw, np.ndarray) else jnp
    return xp.pad(raw, ((0, 0), (0, th - h), (0, tw - w)), mode="edge")


def slice_block_subgrid(y, cb, cr, grid_h: int, grid_w: int,
                        width: int, height: int):
    """Take the top-left ceil-16 subgrid of dense coefficient blocks.

    The wire buffer may cover a bucketed (grid_h, grid_w) frame larger
    than the tile; baseline JPEG decodes exactly ceil(h/16) x ceil(w/16)
    MCUs from the SOF0 dims, so the surplus blocks must be dropped before
    entropy coding.
    """
    gh16, gw16 = grid_h // 16, grid_w // 16
    th16, tw16 = (height + 15) // 16, (width + 15) // 16
    y = y.reshape(gh16 * 2, gw16 * 2, 64)[:th16 * 2, :tw16 * 2]
    cb = cb.reshape(gh16, gw16, 64)[:th16, :tw16]
    cr = cr.reshape(gh16, gw16, 64)[:th16, :tw16]
    return (np.ascontiguousarray(y).reshape(-1, 64),
            np.ascontiguousarray(cb).reshape(-1, 64),
            np.ascontiguousarray(cr).reshape(-1, 64))
