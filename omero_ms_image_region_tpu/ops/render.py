"""The fused tile render kernel.

TPU-native replacement for ``omeis.providers.re.Renderer.renderAsPackedInt``
(reference call site ``ImageRegionRequestHandler.java:559``) and the settings
application in ``updateSettings`` (``:689-741``).

Design (deliberately different from the reference's per-pixel Java pipeline):
the entire post-quantization chain — codomain maps (reverse intensity), LUT
vs RGBA color, alpha weighting, greyscale-vs-rgb model, channel activity — is
folded on the host into one ``(C, 256, 3)`` float32 table per render
(:func:`build_channel_tables`).  The device kernel is then just

    quantize (window + family curve)  ->  per-channel table gather
    ->  additive composite (sum over C)  ->  clip  ->  u8 RGBA

which XLA fuses into a single pass over HBM, and which is identical work for
every (C, H, W) shape — so one compiled executable serves every request of a
given tile bucket, and ``vmap`` batches concurrent requests for free.

Semantics preserved from the reference renderer:
  * quantum over codomain [cd_start, cd_end], default [0,255]
    (``ImageRegionRequestHandler.java:273-276``)
  * reverse-intensity codomain op q -> cd_start + cd_end - q, applied to the
    quantized value before color mapping (``:717-730``)
  * LUT color = table gather; RGBA color = linear ramp * color * alpha
    (``:705-715``)
  * greyscale model renders only the first active channel as grey
    (Renderer.MODEL_GREYSCALE; ``:735-740``)
  * rgb model composites active channels additively with clamp
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.rendering import RenderingDef, RenderingModel
from .quantum import quantize


def build_channel_tables(
    rdef: RenderingDef, lut_provider=None
) -> np.ndarray:
    """Fold color/LUT/alpha/model/codomain chain into (C, 256, 3) tables.

    Row semantics: ``rgb_contribution = table[channel][quantized_value]``.
    Inactive channels are all-zero rows, so the composite sum can run over
    every channel unconditionally (no ragged/active-set shapes on device).
    """
    C = len(rdef.channel_bindings)
    tables = np.zeros((C, 256, 3), dtype=np.float32)
    ramp = np.arange(256, dtype=np.float32)

    greyscale = rdef.model == RenderingModel.GREYSCALE
    first_active = next(
        (i for i, cb in enumerate(rdef.channel_bindings) if cb.active), None
    )

    for c, cb in enumerate(rdef.channel_bindings):
        if not cb.active:
            continue
        if greyscale:
            if c != first_active:
                continue
            # Grey ramp: quantized value becomes the grey level directly.
            table = np.stack([ramp, ramp, ramp], axis=-1)
        else:
            lut_table = None
            if cb.lut is not None and lut_provider is not None:
                lut_table = lut_provider.get(cb.lut)
            if lut_table is not None:
                table = lut_table.astype(np.float32) * (cb.alpha / 255.0)
            else:
                color = np.array(
                    [cb.red, cb.green, cb.blue], dtype=np.float32
                )
                table = (ramp[:, None] / 255.0) * color[None, :] * (
                    cb.alpha / 255.0
                )
        tables[c] = table
    return tables


def _render_tile_impl(raw, window_start, window_end, family, coefficient,
                      reverse, cd_start, cd_end, tables):
    q = quantize(raw, window_start, window_end, family, coefficient,
                 cd_start, cd_end)  # [C,H,W] in [cd_start, cd_end]
    # Reverse-intensity codomain op (ReverseIntensityContext,
    # ImageRegionRequestHandler.java:717-730): mirror within the codomain.
    q = jnp.where(reverse[:, None, None] != 0, cd_start + cd_end - q, q)
    # Per-channel gather of the folded color tables, then additive composite.
    contrib = jax.vmap(lambda table, qc: table[qc])(tables, q)  # [C,H,W,3]
    rgb = jnp.clip(jnp.round(jnp.sum(contrib, axis=0)), 0.0, 255.0)
    rgb = rgb.astype(jnp.uint8)
    alpha = jnp.full(rgb.shape[:2] + (1,), 255, dtype=jnp.uint8)
    return jnp.concatenate([rgb, alpha], axis=-1)


@jax.jit
def render_tile(raw, window_start, window_end, family, coefficient,
                reverse, cd_start, cd_end, tables):
    """Render one raw multi-channel tile to RGBA.

    Args:
      raw:          f32[C, H, W] raw channel planes.
      window_start: f32[C]
      window_end:   f32[C]
      family:       i32[C] quantum family ids
      coefficient:  f32[C] family curve coefficients
      reverse:      i32[C] 1 to apply reverse-intensity, else 0
      cd_start:     i32[] codomain start (QuantumDef)
      cd_end:       i32[] codomain end (QuantumDef)
      tables:       f32[C, 256, 3] channel tables from
                    :func:`build_channel_tables`.

    Returns:
      u8[H, W, 4] RGBA tile (alpha fully opaque, as the reference's packed
      ARGB output renders).
    """
    return _render_tile_impl(raw, window_start, window_end, family,
                             coefficient, reverse, cd_start, cd_end, tables)


@jax.jit
def render_tile_batch(raw, window_start, window_end, family, coefficient,
                      reverse, cd_start, cd_end, tables):
    """Batched render: per-tile args gain a leading batch dim B.

    This is the micro-batched hot path (SURVEY.md section 7 step 5): the
    worker coalesces concurrent tile requests of one bucket shape into a
    single device dispatch.

    Args:
      raw:    f32[B, C, H, W]
      cd_start/cd_end: scalars, shared across the batch.
      others: as :func:`render_tile` with a leading B axis.
    Returns:
      u8[B, H, W, 4]
    """
    return jax.vmap(
        lambda r, ws, we, f, k, rev, t: _render_tile_impl(
            r, ws, we, f, k, rev, cd_start, cd_end, t
        )
    )(raw, window_start, window_end, family, coefficient, reverse, tables)


def pack_settings(rdef: RenderingDef, lut_provider=None):
    """Host-side packing of a RenderingDef into kernel arguments.

    Returns a dict of numpy arrays ready to splat into :func:`render_tile`.
    """
    cbs = rdef.channel_bindings
    return {
        "window_start": np.array([cb.input_start for cb in cbs], np.float32),
        "window_end": np.array([cb.input_end for cb in cbs], np.float32),
        "family": np.array([cb.family.index for cb in cbs], np.int32),
        "coefficient": np.array([cb.coefficient for cb in cbs], np.float32),
        "reverse": np.array(
            [1 if cb.reverse_intensity else 0 for cb in cbs], np.int32
        ),
        "cd_start": np.int32(rdef.quantum.cd_start),
        "cd_end": np.int32(rdef.quantum.cd_end),
        "tables": build_channel_tables(rdef, lut_provider),
    }
