"""The fused tile render kernel.

TPU-native replacement for ``omeis.providers.re.Renderer.renderAsPackedInt``
(reference call site ``ImageRegionRequestHandler.java:559``) and the settings
application in ``updateSettings`` (``:689-741``).

Design (deliberately different from the reference's per-pixel Java pipeline):
the entire post-quantization chain — codomain maps (reverse intensity), LUT
vs RGBA color, alpha weighting, greyscale-vs-rgb model, channel activity — is
folded on the host into one ``(C, 256, 3)`` float32 table per render
(:func:`build_channel_tables`).  The device kernel is then just

    quantize (window + family curve)  ->  per-channel table gather
    ->  additive composite (sum over C)  ->  clip  ->  u8 RGBA

which XLA fuses into a single pass over HBM, and which is identical work for
every (C, H, W) shape — so one compiled executable serves every request of a
given tile bucket, and ``vmap`` batches concurrent requests for free.

Semantics preserved from the reference renderer:
  * quantum over codomain [cd_start, cd_end], default [0,255]
    (``ImageRegionRequestHandler.java:273-276``)
  * reverse-intensity codomain op q -> cd_start + cd_end - q, applied to the
    quantized value before color mapping (``:717-730``)
  * LUT color = table gather; RGBA color = linear ramp * color * alpha
    (``:705-715``)
  * greyscale model renders only the first active channel as grey
    (Renderer.MODEL_GREYSCALE; ``:735-740``)
  * rgb model composites active channels additively with clamp
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.rendering import RenderingDef, RenderingModel
from .quantum import quantize


def build_channel_tables(
    rdef: RenderingDef, lut_provider=None
) -> np.ndarray:
    """Fold color/LUT/alpha/model/codomain chain into (C, 256, 3) tables.

    Row semantics: ``rgb_contribution = table[channel][quantized_value]``.
    Inactive channels are all-zero rows, so the composite sum can run over
    every channel unconditionally (no ragged/active-set shapes on device).
    """
    C = len(rdef.channel_bindings)
    tables = np.zeros((C, 256, 3), dtype=np.float32)
    ramp = np.arange(256, dtype=np.float32)

    greyscale = rdef.model == RenderingModel.GREYSCALE
    first_active = next(
        (i for i, cb in enumerate(rdef.channel_bindings) if cb.active), None
    )

    for c, cb in enumerate(rdef.channel_bindings):
        if not cb.active:
            continue
        if greyscale:
            if c != first_active:
                continue
            # Grey ramp: quantized value becomes the grey level directly.
            table = np.stack([ramp, ramp, ramp], axis=-1)
        else:
            lut_table = None
            if cb.lut is not None and lut_provider is not None:
                lut_table = lut_provider.get(cb.lut)
            if lut_table is not None:
                table = lut_table.astype(np.float32) * (cb.alpha / 255.0)
            else:
                color = np.array(
                    [cb.red, cb.green, cb.blue], dtype=np.float32
                )
                table = (ramp[:, None] / 255.0) * color[None, :] * (
                    cb.alpha / 255.0
                )
        tables[c] = table
    return tables


def build_ramp_weights(rdef: RenderingDef, lut_provider=None):
    """Fold the color chain into per-channel linear weights, if possible.

    Every non-LUT channel's (C, 256, 3) table is a ramp — ``table[q] =
    q * w`` with ``w = color * alpha / 255**2`` (grey model: ``w = 1``) —
    so the composite collapses to one multiply-add contraction over
    channels, with no per-pixel table gather at all.  TPU has no per-lane
    gather; the measured gap on a 8x4x1024^2 batch is ~9x (0.89 s table
    gathers vs 0.10 s arithmetic).  Returns f32[C, 3] weights, or None
    when any active channel resolves an actual LUT file (the gather path
    must run; :func:`build_channel_tables`).
    """
    C = len(rdef.channel_bindings)
    w = np.zeros((C, 3), dtype=np.float32)
    greyscale = rdef.model == RenderingModel.GREYSCALE
    first_active = next(
        (i for i, cb in enumerate(rdef.channel_bindings) if cb.active), None
    )
    for c, cb in enumerate(rdef.channel_bindings):
        if not cb.active:
            continue
        if greyscale:
            if c == first_active:
                w[c] = 1.0
            continue
        if (cb.lut is not None and lut_provider is not None
                and lut_provider.get(cb.lut) is not None):
            return None
        color = np.array([cb.red, cb.green, cb.blue], dtype=np.float32)
        w[c] = (color / 255.0) * (cb.alpha / 255.0)
    return w


def composite_ramp_packed(q, weights):
    """Arithmetic composite for ramp-only renders (no table gather).

    ``q`` [..., C, H, W] quantized values, ``weights`` [..., C, 3] from
    :func:`build_ramp_weights` sharing the same leading dims.  Same packed
    u32 output as :func:`composite_packed`.
    """
    qf = q.astype(jnp.float32)
    out = []
    for comp in range(3):
        v = jnp.einsum("...chw,...c->...hw", qf, weights[..., comp])
        v = jnp.clip(jnp.round(v), 0.0, 255.0).astype(jnp.uint32)
        out.append(v)
    r, g, b = out
    return r | (g << 8) | (b << 16) | jnp.uint32(0xFF000000)


def composite_packed(q, tables):
    """Table lookup + additive composite + ABGR pack, TPU-layout-native.

    ``q`` [..., C, H, W] quantized values, ``tables`` [..., C, 256, 3]
    folded color tables sharing the same leading dims.

    Two deliberate layout decisions (both forced by the TPU memory tiling,
    where the minor-most dim is padded to 128 lanes):

      * The lookup runs as three flat shared-operand gathers — one per color
        component — over a ``[prod(lead)*256]`` vector, with each plane's
        indices offset into its own 256-entry block.  A vmapped per-plane
        ``table[q]`` becomes a batched gather that XLA expands into a
        one-hot contraction (OOM), and any big ``[..., 3]`` intermediate
        pads 3 -> 128 lanes (observed: 42.7x HBM expansion, 20 GB for an
        8x4x1024x1024 batch).

      * The result is the reference's packed-int form
        (``Renderer.renderAsPackedInt``, ``ImageRegionRequestHandler.java:559``):
        u32[..., H, W] with bytes R|G<<8|B<<16|A<<24, i.e. little-endian
        memory order R,G,B,A — so the host gets RGBA by ``.view(uint8)``
        with zero copies and the device never materializes a
        4-wide minor axis.
    """
    lead = q.shape[:-2]          # (..., C)
    n_planes = 1
    for d in lead:
        n_planes *= d
    flat = tables.reshape(n_planes * 256, 3)
    idx = q + (jnp.arange(n_planes, dtype=q.dtype) * 256).reshape(
        lead + (1, 1)
    )
    out = []
    for comp in range(3):
        v = jnp.take(flat[:, comp], idx, axis=0)     # f32 [..., C, H, W]
        v = jnp.sum(v, axis=-3)                      # composite over C
        v = jnp.clip(jnp.round(v), 0.0, 255.0).astype(jnp.uint32)
        out.append(v)
    r, g, b = out
    return r | (g << 8) | (b << 16) | jnp.uint32(0xFF000000)


def _render_packed_impl(raw, window_start, window_end, family, coefficient,
                        reverse, cd_start, cd_end, tables):
    """Shared impl over arbitrary leading dims: raw [..., C, H, W]."""
    shape = raw.shape
    H, W = shape[-2:]
    n_planes = 1
    for d in shape[:-2]:
        n_planes *= d
    q = quantize(
        raw.reshape(n_planes, H, W),
        window_start.reshape(n_planes),
        window_end.reshape(n_planes),
        family.reshape(n_planes),
        coefficient.reshape(n_planes),
        cd_start,
        cd_end,
    )
    # Reverse-intensity codomain op (ReverseIntensityContext,
    # ImageRegionRequestHandler.java:717-730): mirror within the codomain.
    q = jnp.where(
        reverse.reshape(n_planes)[:, None, None] != 0,
        cd_start + cd_end - q, q,
    ).reshape(shape)
    # Shape-dispatch: ramp weights [..., C, 3] (one dim fewer than the
    # [..., C, 256, 3] gather tables) take the arithmetic path.
    if tables.ndim == raw.ndim - 1:
        return composite_ramp_packed(q, tables)
    return composite_packed(q, tables)


@jax.jit
def render_tile_packed(raw, window_start, window_end, family, coefficient,
                       reverse, cd_start, cd_end, tables):
    """Render one raw multi-channel tile to packed RGBA ints.

    Args:
      raw:          f32[C, H, W] raw channel planes.
      window_start: f32[C]
      window_end:   f32[C]
      family:       i32[C] quantum family ids
      coefficient:  f32[C] family curve coefficients
      reverse:      i32[C] 1 to apply reverse-intensity, else 0
      cd_start:     i32[] codomain start (QuantumDef)
      cd_end:       i32[] codomain end (QuantumDef)
      tables:       f32[C, 256, 3] channel tables from
                    :func:`build_channel_tables`.

    Returns:
      u32[H, W] packed pixels, little-endian byte order R,G,B,A with alpha
      fully opaque (the reference's packed ARGB analogue).
    """
    return _render_packed_impl(raw, window_start, window_end, family,
                               coefficient, reverse, cd_start, cd_end,
                               tables)


@jax.jit
def render_tile_batch_packed(raw, window_start, window_end, family,
                             coefficient, reverse, cd_start, cd_end, tables):
    """Batched render to packed ints: per-tile args gain a leading dim B.

    This is the micro-batched hot path (SURVEY.md section 7 step 5): the
    worker coalesces concurrent tile requests of one bucket shape into a
    single device dispatch.

    Args:
      raw:    f32[B, C, H, W]
      cd_start/cd_end: scalars, shared across the batch.
      others: as :func:`render_tile_packed` with a leading B axis.
    Returns:
      u32[B, H, W]
    """
    return _render_packed_impl(raw, window_start, window_end, family,
                               coefficient, reverse, cd_start, cd_end,
                               tables)


def unpack_rgba(packed: np.ndarray) -> np.ndarray:
    """u32[..., H, W] packed pixels -> u8[..., H, W, 4] RGBA, zero-copy."""
    packed = np.ascontiguousarray(np.asarray(packed))
    le = packed.astype("<u4", copy=False)
    return le.view(np.uint8).reshape(packed.shape + (4,))


def render_tile(raw, window_start, window_end, family, coefficient,
                reverse, cd_start, cd_end, tables):
    """Host-convenience single-tile render -> u8[H, W, 4] RGBA numpy."""
    return unpack_rgba(render_tile_packed(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables,
    ))


def render_tile_batch(raw, window_start, window_end, family, coefficient,
                      reverse, cd_start, cd_end, tables):
    """Host-convenience batched render -> u8[B, H, W, 4] RGBA numpy."""
    return unpack_rgba(render_tile_batch_packed(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables,
    ))


def pack_settings(rdef: RenderingDef, lut_provider=None):
    """Host-side packing of a RenderingDef into kernel arguments.

    Returns a dict of numpy arrays ready to splat into :func:`render_tile`.
    ``tables`` is f32[C, 3] ramp weights when no active channel uses a LUT
    (the kernels' fast arithmetic path), else the full f32[C, 256, 3]
    gather tables.
    """
    cbs = rdef.channel_bindings
    weights = build_ramp_weights(rdef, lut_provider)
    return {
        "window_start": np.array([cb.input_start for cb in cbs], np.float32),
        "window_end": np.array([cb.input_end for cb in cbs], np.float32),
        "family": np.array([cb.family.index for cb in cbs], np.int32),
        "coefficient": np.array([cb.coefficient for cb in cbs], np.float32),
        "reverse": np.array(
            [1 if cb.reverse_intensity else 0 for cb in cbs], np.int32
        ),
        "cd_start": np.int32(rdef.quantum.cd_start),
        "cd_end": np.int32(rdef.quantum.cd_end),
        "tables": (weights if weights is not None
                   else build_channel_tables(rdef, lut_provider)),
    }
