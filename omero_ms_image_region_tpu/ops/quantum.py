"""Window + family quantization to the 8-bit codomain.

TPU-native reconstruction of the quantization semantics of
``omeis.providers.re.quantum.QuantumFactory`` / ``QuantumStrategy`` as
consumed by the reference (``ImageRegionRequestHandler.java:259,273-276,433``
builds an 8-bit quantum over [cdStart, cdEnd] = [0, 255];
``ImageRegionVerticle.java:72-76`` enumerates the four families).

The mapping, for a pixel value ``v``, window ``[ws, we]``, family transform
``F`` with curve coefficient ``k``:

    q(v) = round(cd_start + (cd_end - cd_start) *
                 (F(clamp(v, ws, we)) - F(ws)) / (F(we) - F(ws)))

with family transforms (omeis.providers.re.quantum value mappers):

    linear       F(x) = x
    polynomial   F(x) = sign(x) * |x|**k     (monotone extension of x**k so
                                              signed pixel types stay defined)
    logarithmic  F(x) = log(max(x, 1))       (<=0 guarded as in LogarithmicMap)
    exponential  F(x) = exp(x**k)            (evaluated in shifted form
                                              exp(F - F(we)) so float32 never
                                              overflows; identical ratio)

All four are computed branchlessly and selected per channel, so a mixed batch
of channels with different families stays one fused XLA kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

FAMILY_LINEAR = 0
FAMILY_POLYNOMIAL = 1
FAMILY_LOGARITHMIC = 2
FAMILY_EXPONENTIAL = 3

_EPS = 1e-12


def _signed_pow(x, k):
    return jnp.sign(x) * jnp.power(jnp.abs(x), k)


def _safe_log(x):
    return jnp.log(jnp.maximum(x, 1.0))


def _ratio(x, x_raw, ws, we, family, k):
    """Normalized position of x in the window under the family curve.

    ``x`` is already clamped to [ws, we]; ``x_raw`` is the unclamped value
    (needed for the degenerate ws == we step function).  Shapes: x is
    [..., H, W] with ws/we/family/k broadcastable against the leading dims.
    """
    # linear
    den_lin = we - ws
    r_lin = (x - ws) / jnp.where(jnp.abs(den_lin) < _EPS, 1.0, den_lin)

    # polynomial
    ps, pe, px = _signed_pow(ws, k), _signed_pow(we, k), _signed_pow(x, k)
    den_poly = pe - ps
    r_poly = (px - ps) / jnp.where(jnp.abs(den_poly) < _EPS, 1.0, den_poly)

    # logarithmic
    ls, le, lx = _safe_log(ws), _safe_log(we), _safe_log(x)
    den_log = le - ls
    r_log = (lx - ls) / jnp.where(jnp.abs(den_log) < _EPS, 1.0, den_log)

    # exponential, shifted by F(we) so every exponent is <= 0:
    #   (e^{F(x)} - e^{F(ws)}) / (e^{F(we)} - e^{F(ws)})
    # = (e^{F(x)-F(we)} - e^{F(ws)-F(we)}) / (1 - e^{F(ws)-F(we)})
    es = jnp.exp(jnp.minimum(ps - pe, 0.0))
    ex = jnp.exp(jnp.minimum(px - pe, 0.0))
    den_exp = 1.0 - es
    r_exp = (ex - es) / jnp.where(jnp.abs(den_exp) < _EPS, 1.0, den_exp)

    r = jnp.where(
        family == FAMILY_LINEAR, r_lin,
        jnp.where(
            family == FAMILY_POLYNOMIAL, r_poly,
            jnp.where(family == FAMILY_LOGARITHMIC, r_log, r_exp),
        ),
    )
    # A window degenerate under the selected family transform (ws == we, or
    # both endpoints collapsing under F, e.g. log over [0, 1]) becomes an
    # all-or-nothing step on the unclamped value.
    den_sel = jnp.where(
        family == FAMILY_LINEAR, den_lin,
        jnp.where(
            family == FAMILY_POLYNOMIAL, den_poly,
            jnp.where(family == FAMILY_LOGARITHMIC, den_log, den_exp),
        ),
    )
    degenerate = jnp.abs(den_sel) < _EPS
    r_deg = jnp.where(x_raw >= we, 1.0, 0.0)
    return jnp.where(degenerate, r_deg, r)


def quantize(
    raw,
    window_start,
    window_end,
    family,
    coefficient,
    cd_start=0,
    cd_end=255,
):
    """Quantize raw channel planes into the 8-bit codomain.

    Args:
      raw:           f32[C, H, W] raw pixel values (already cast from the
                     source dtype).
      window_start:  f32[C] per-channel window start.
      window_end:    f32[C] per-channel window end.
      family:        i32[C] family id (FAMILY_* above).
      coefficient:   f32[C] family curve coefficient.
      cd_start/end:  codomain interval (QuantumDef; default [0, 255]).

    Returns:
      i32[C, H, W] quantized values in [cd_start, cd_end].
    """
    ws = window_start[:, None, None].astype(jnp.float32)
    we = window_end[:, None, None].astype(jnp.float32)
    fam = family[:, None, None]
    k = coefficient[:, None, None].astype(jnp.float32)

    x_raw = raw.astype(jnp.float32)
    x = jnp.clip(x_raw, jnp.minimum(ws, we), jnp.maximum(ws, we))
    r = jnp.clip(_ratio(x, x_raw, ws, we, fam, k), 0.0, 1.0)
    q = jnp.round(cd_start + (cd_end - cd_start) * r)
    return q.astype(jnp.int32)
