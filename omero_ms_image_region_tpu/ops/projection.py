"""Z-stack intensity projection.

TPU-native replacement for ``ProjectionService.java`` (reference: CPU
per-pixel loops at ``:176-199`` (max) and ``:259-291`` (mean/sum)).  Instead
of slicing the stack per request (which would recompile per Z-range), the
kernel always reduces over the full Z axis with a dynamic 0/1 weight vector
derived from (start, end, stepping) — one compiled executable per stack
shape, Z-range fully dynamic.

Reference semantics preserved exactly, including its quirks:
  * max:  z runs ``start..end`` INCLUSIVE (``:184``), and the accumulator
          starts at 0 (``:183``) — an all-negative column projects to 0.
  * mean/sum: z runs ``start..end`` EXCLUSIVE of end (``:271``), result is
          clamped above by the pixel type's max (``:280-282``), never below.
  * mean divides by the number of planes actually used (``:277-279``).

Bounds validation mirrors ``projectStack`` (``ProjectionService.java:52-64``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models.rendering import Projection


def check_projection_bounds(start: int, end: int, stepping: int,
                            channel: int, timepoint: int,
                            size_z: int, size_c: int, size_t: int) -> None:
    """Host-side validation (= zIntervalBoundsCheck / outOfBounds* checks)."""
    if start < 0 or end < 0:
        raise ValueError("Z interval value cannot be negative.")
    if start >= size_z or end >= size_z:
        raise ValueError(f"Z interval value cannot be >= {size_z}")
    if stepping is not None and stepping <= 0:
        raise ValueError(f"stepping: {stepping} <= 0")
    if channel is not None:
        if channel < 0:
            raise ValueError(f"channel: {channel} < 0")
        if channel >= size_c:
            raise ValueError(f"channel index must be <{size_c}")
    if timepoint is not None:
        if timepoint < 0:
            raise ValueError(f"timepoint: {timepoint} < 0")
        if timepoint >= size_t:
            raise ValueError(f"timepoint must be <{size_t}")


@functools.partial(jax.jit, static_argnames=("algorithm",))
def _project(stack, start, end, stepping, type_max, algorithm: int):
    Z = stack.shape[0]
    idx = jnp.arange(Z)
    on_step = ((idx - start) % stepping) == 0
    x = stack.astype(jnp.float32)

    if algorithm == Projection.MAXIMUM_INTENSITY:
        w = (idx >= start) & (idx <= end) & on_step          # inclusive end
        masked = jnp.where(w[:, None, None], x, -jnp.inf)
        # Accumulator starts at 0 in the reference (:183): clamp from below.
        return jnp.maximum(jnp.max(masked, axis=0), 0.0)

    # mean / sum: exclusive end (:271)
    w = ((idx >= start) & (idx < end) & on_step).astype(jnp.float32)
    total = jnp.sum(x * w[:, None, None], axis=0)
    if algorithm == Projection.MEAN_INTENSITY:
        count = jnp.maximum(jnp.sum(w), 1.0)
        total = total / count
    # Clamp to the destination type maximum (:280-282); no lower clamp.
    return jnp.minimum(total, type_max)


@jax.jit
def _fold_max(acc, plane):
    return jnp.maximum(acc, plane.astype(jnp.float32))


@jax.jit
def _fold_sum(acc, plane):
    return acc + plane.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("algorithm",))
def _finalize(acc, count, type_max, algorithm: int):
    if algorithm == Projection.MAXIMUM_INTENSITY:
        return jnp.maximum(acc, 0.0)     # 0-floor accumulator (:183)
    if algorithm == Projection.MEAN_INTENSITY:
        acc = acc / jnp.maximum(count, 1.0)
    return jnp.minimum(acc, type_max)    # type-max clamp (:280-282)


def _finalize_host(acc: np.ndarray, count: int, type_max: float,
                   algorithm) -> np.ndarray:
    """Numpy mirror of :func:`_finalize` (identical reference
    semantics: 0-floor max accumulator, mean divide, type-max clamp)."""
    if algorithm == Projection.MAXIMUM_INTENSITY:
        return np.maximum(acc, 0.0)
    if algorithm == Projection.MEAN_INTENSITY:
        acc = acc / max(float(count), 1.0)
    return np.minimum(acc, np.float32(type_max))


def _resolve_placement(placement: str, sample) -> str:
    """``auto`` folds where the data lives: a host-resident source
    (numpy reads) folds on host and ships ONE projected plane across
    the link — a projection is a reduction, so uploading Z planes to
    reduce them device-side pays Z plane transfers to save host work
    that is memory-bound anyway (measured on the tunnel: 32x1024^2 u16
    cold projections went 0.14/s device-fold -> host-fold at memory
    speed).  Device-resident sources keep the device fold (zero
    transfers either way).  Co-located deployments with fast links can
    force ``device``."""
    if placement == "auto":
        return "host" if isinstance(sample, np.ndarray) else "device"
    if placement not in ("host", "device"):
        raise ValueError(f"unknown placement {placement!r}")
    return placement


def project_planes(get_plane, algorithm, size_z: int, start: int,
                   end: int, stepping: int = 1,
                   type_max: float = 255.0, shape=None,
                   placement: str = "auto"):
    """Stream a Z-projection plane by plane — WSI-scale memory bound.

    Where :func:`project_stack` needs the whole ``[Z, H, W]`` stack
    resident (matching ``PixelBuffer.getStack`` at
    ``ProjectionService.java:72``, which stalls and swaps on real WSI
    stacks), this reads ONLY the planes inside the Z window via
    ``get_plane(z) -> [H, W]`` and folds each into an accumulator:
    peak memory is one plane + the accumulator, independent of Z.

    ``placement`` picks where the fold runs (see
    :func:`_resolve_placement`: ``auto`` folds where the data lives, so
    host sources never upload the stack just to reduce it).

    Reference semantics are identical to :func:`project_stack`
    (inclusive max / exclusive mean-sum windows, stepping, 0-floor max
    accumulator, type-max clamp).

    Returns f32[H, W] on device.
    """
    algorithm, zs, inclusive = _validate_and_window(
        algorithm, size_z, start, end, stepping)
    acc = None
    first = get_plane(zs[0]) if zs else None
    if zs:
        placement = _resolve_placement(placement, first)
    if placement == "host" and zs:
        acc = np.asarray(first, np.float32)
        for z in zs[1:]:
            plane = np.asarray(get_plane(z), np.float32)
            acc = np.maximum(acc, plane) if inclusive else acc + plane
        return jnp.asarray(_finalize_host(acc, len(zs), type_max,
                                          algorithm))
    fold = _fold_max if inclusive else _fold_sum
    for i, z in enumerate(zs):
        plane = jnp.asarray(first if i == 0 else get_plane(z))
        acc = (plane.astype(jnp.float32) if acc is None
               else fold(acc, plane))
    if acc is None:
        # Empty mean/sum window (start == end): all-zero plane, the
        # full-stack kernel's result for a zero weight vector.  With
        # ``shape`` provided (the serving path knows the plane geometry)
        # no plane is read at all — a WSI-scale probe read just for its
        # shape would defeat the bounded-reads contract.
        if shape is None:
            shape = np.asarray(get_plane(start)).shape
        acc = jnp.zeros(shape, jnp.float32)
    return _finalize(acc, jnp.asarray(float(len(zs)), jnp.float32),
                     jnp.asarray(type_max, jnp.float32), int(algorithm))


@functools.partial(jax.jit, static_argnames=("alg",))
def _fold_chunk(acc, chunk, alg: int):
    """Fold a [zc, h, W] chunk into a [h, W] band accumulator in ONE
    dispatch (vs one dispatch per plane in the plain stream)."""
    x = chunk.astype(jnp.float32)
    if alg == Projection.MAXIMUM_INTENSITY:
        return jnp.maximum(acc, jnp.max(x, axis=0))
    return acc + jnp.sum(x, axis=0)


@jax.jit
def _stitch(out, band, y0):
    return jax.lax.dynamic_update_slice(out, band, (y0, 0))


def _validate_and_window(algorithm, size_z: int, start: int, end: int,
                         stepping: int):
    """Shared validation + Z-window derivation for the streaming
    projections (one copy of the reference's window semantics: max is
    end-INclusive, mean/sum end-EXclusive, ``ProjectionService.java
    :184,:271``).  Returns (algorithm, zs, inclusive)."""
    algorithm = Projection(algorithm)
    if algorithm not in (
        Projection.MAXIMUM_INTENSITY,
        Projection.MEAN_INTENSITY,
        Projection.SUM_INTENSITY,
    ):
        raise ValueError(f"Unknown algorithm: {algorithm}")
    if start < 0 or end < 0:
        raise ValueError("Z interval value cannot be negative.")
    if start >= size_z or end >= size_z:
        raise ValueError(f"Z interval value cannot be >= {size_z}")
    if stepping <= 0:
        raise ValueError(f"stepping: {stepping} <= 0")
    inclusive = algorithm == Projection.MAXIMUM_INTENSITY
    stop = end + 1 if inclusive else end
    zs = [z for z in range(start, stop) if (z - start) % stepping == 0]
    return algorithm, zs, inclusive


def project_region_banded(get_band, algorithm, size_z: int, start: int,
                          end: int, stepping: int = 1,
                          type_max: float = 255.0, plane_shape=None,
                          band_rows: int = 256, z_chunk: int = 8,
                          get_chunk=None, placement: str = "auto"):
    """Spatially-banded streamed Z-projection — peak HOST footprint is
    chunk-sized, not plane-sized.

    :func:`project_planes` bounds memory in Z but still reads (and
    uploads) FULL planes; at real WSI scale (80k x 80k u16 => 12.8 GB
    per host plane) that breaks the host long before the device.  Here
    the plane is processed in horizontal bands of ``band_rows`` rows:
    ``get_band(z, y0, h) -> [h, W]`` reads only a band, ``z_chunk``
    bands stack into one device fold dispatch, and finished band
    accumulators stitch into the output plane on device.  Peak host
    memory is one ``[z_chunk, band_rows, W]`` chunk.  Peak DEVICE
    memory is still the f32 output plane (plus one band accumulator
    and one chunk) — the projected plane feeds the render, which needs
    it whole, so the largest projectable plane is bounded by HBM
    exactly as any renderable plane is (the reference materializes
    full byte[] planes at the same point, ``ProjectionService.java
    :72``).

    The last band is aligned to ``H - band_rows`` (fixed shapes keep
    one compiled executable); its overlap rows recompute identical
    values, so the stitch is idempotent.  Reference semantics match
    :func:`project_stack` exactly (inclusive max / exclusive mean-sum
    windows, stepping, 0-floor max accumulator, type-max clamp —
    ``ProjectionService.java:176-291``).

    ``placement`` picks where the folds run (``auto`` = where the data
    lives, :func:`_resolve_placement`): a host source folds each band
    in numpy and only the finished [H, W] plane crosses the link.

    Returns f32[H, W] on device.
    """
    algorithm, zs, inclusive = _validate_and_window(
        algorithm, size_z, start, end, stepping)
    if plane_shape is None:
        raise ValueError("plane_shape is required")
    H, W = plane_shape
    band_h = min(band_rows, H)
    alg = int(algorithm)

    # Auto-placement probes are REUSED as the first loop read, so auto
    # costs no extra I/O: the band probe is (band 0, z0); the chunk
    # probe reads the full first [z_chunk, band, W] block.
    probe = probe_chunk = None
    first_chunk_zs = tuple(zs[:z_chunk])
    if zs and placement == "auto":
        if get_chunk is not None:
            sample = probe_chunk = get_chunk(list(first_chunk_zs), 0,
                                             band_h)
        else:
            sample = probe = get_band(zs[0], 0, band_h)
        placement = _resolve_placement(placement, sample)

    def read_band(z, y0, h):
        nonlocal probe
        if probe is not None and z == zs[0] and y0 == 0:
            band, probe = probe, None
            return band
        return get_band(z, y0, h)

    def read_chunk(chunk_zs, y0, h):
        nonlocal probe_chunk
        if (probe_chunk is not None and y0 == 0
                and tuple(chunk_zs) == first_chunk_zs):
            chunk, probe_chunk = probe_chunk, None
            return chunk
        return get_chunk(chunk_zs, y0, h)

    if placement == "host" and zs:
        out = np.zeros((H, W), np.float32)
        for bi in range(-(-H // band_h)):
            y0 = min(bi * band_h, H - band_h)
            acc = (np.full((band_h, W), -np.inf, np.float32)
                   if inclusive else np.zeros((band_h, W), np.float32))
            for ci in range(0, len(zs), z_chunk):
                chunk_zs = zs[ci:ci + z_chunk]
                if get_chunk is not None:
                    chunk = np.asarray(
                        read_chunk(chunk_zs, y0, band_h), np.float32)
                else:
                    chunk = np.stack([
                        np.asarray(read_band(z, y0, band_h), np.float32)
                        for z in chunk_zs])
                if inclusive:
                    acc = np.maximum(acc, chunk.max(axis=0))
                else:
                    acc += chunk.sum(axis=0)
            out[y0:y0 + band_h] = acc
        return jnp.asarray(_finalize_host(out, len(zs), type_max,
                                          algorithm))

    out = jnp.zeros((H, W), jnp.float32)
    n_bands = -(-H // band_h)
    for bi in range(n_bands):
        y0 = min(bi * band_h, H - band_h)
        if not zs:
            # Empty mean/sum window: the zero output plane stands.
            break
        acc = (jnp.full((band_h, W), -jnp.inf, jnp.float32) if inclusive
               else jnp.zeros((band_h, W), jnp.float32))
        for ci in range(0, len(zs), z_chunk):
            chunk_zs = zs[ci:ci + z_chunk]
            if get_chunk is not None:
                # Sources that can serve a [z, band, W] block in one
                # read (device-resident stacks especially: per-plane
                # slicing costs a dispatch each, which a tunnel-attached
                # deployment pays in round trips).
                chunk = get_chunk(chunk_zs, y0, band_h)
                if len(chunk_zs) < z_chunk:
                    xp = np if isinstance(chunk, np.ndarray) else jnp
                    pad = (chunk[:1] if inclusive
                           else xp.zeros_like(chunk[:1]))
                    chunk = xp.concatenate(
                        [chunk] + [pad] * (z_chunk - len(chunk_zs)))
            else:
                bands = [read_band(z, y0, band_h) for z in chunk_zs]
                if len(bands) < z_chunk:
                    # Fixed chunk shape = one compiled fold.  Max pads
                    # by repeating a real band (idempotent); sum pads
                    # zeros.
                    pad = (bands[0] if inclusive
                           else np.zeros_like(np.asarray(bands[0])))
                    bands = bands + [pad] * (z_chunk - len(bands))
                xp = jnp if any(not isinstance(b, np.ndarray)
                                for b in bands) else np
                chunk = xp.stack(bands)
            acc = _fold_chunk(acc, chunk, alg)
        out = _stitch(out, acc, jnp.asarray(y0, jnp.int32))
    return _finalize(out, jnp.asarray(float(len(zs)), jnp.float32),
                     jnp.asarray(type_max, jnp.float32), alg)


def project_stack(stack, algorithm, start: int, end: int,
                  stepping: int = 1, type_max: float = 255.0):
    """Project a Z-stack.

    Args:
      stack:     f32[Z, H, W] one channel/timepoint stack
                 (= PixelBuffer.getStack slice, ``ProjectionService.java:72``).
      algorithm: models.rendering.Projection
      start/end: Z interval (see module docstring for in/exclusivity).
      stepping:  use every ``stepping``-th section (``:166-170``).
      type_max:  pixel type maximum for the mean/sum clamp.

    Returns:
      f32[H, W] projected plane.
    """
    algorithm = Projection(algorithm)
    if algorithm not in (
        Projection.MAXIMUM_INTENSITY,
        Projection.MEAN_INTENSITY,
        Projection.SUM_INTENSITY,
    ):
        raise ValueError(f"Unknown algorithm: {algorithm}")
    # Z-interval validation (= zIntervalBoundsCheck at the projectStack
    # entry, ProjectionService.java:52-54); channel/timepoint bounds are the
    # caller's (check_projection_bounds) since only it knows those sizes.
    if start < 0 or end < 0:
        raise ValueError("Z interval value cannot be negative.")
    if start >= stack.shape[0] or end >= stack.shape[0]:
        raise ValueError(f"Z interval value cannot be >= {stack.shape[0]}")
    if stepping <= 0:
        raise ValueError(f"stepping: {stepping} <= 0")
    return _project(
        stack,
        jnp.asarray(start, jnp.int32),
        jnp.asarray(end, jnp.int32),
        jnp.asarray(stepping, jnp.int32),
        jnp.asarray(type_max, jnp.float32),
        int(algorithm),
    )
