"""Ingest/export tooling: ``python -m omero_ms_image_region_tpu.ingest``.

The reference's deployments lean on OMERO's importer (Bio-Formats) to
populate the binary repository; this CLI covers the same operational
needs for a standalone data directory:

  info <image_dir|tiff|zarr>         print geometry, levels, backend
  tiff-to-store <tiff> <image_dir>   OME-TIFF -> chunked pyramid layout
  store-to-tiff <image_dir> <tiff>   chunked pyramid -> tiled OME-TIFF
  to-ngff <src> <zarr_dir>           any readable source -> OME-NGFF
                                     (zarr v2 multiscales)

Conversions read plane by plane but do hold ONE full-resolution
[T, C, Z, H, W] copy (plus ~1/3 extra for the rebuilt pyramid levels)
while writing — size the host accordingly for WSI-scale inputs.  The
storage dtype is preserved; pyramid levels are rebuilt with the same
mean-pool reduction both writers share.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _open_source(path: str):
    import os

    from .io.ometiff import OmeTiffSource, find_tiff
    from .io.store import ChunkedPyramidStore

    from .io.ngff import NgffZarrSource, find_ngff

    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "meta.json")):
            return ChunkedPyramidStore(path), "chunked"
        ngff = find_ngff(path)
        if ngff is not None:
            return NgffZarrSource(ngff), "ome-ngff"
        tiff = find_tiff(path)
        if tiff is not None:
            return OmeTiffSource(tiff), "ome-tiff"
        raise SystemExit(
            f"{path}: no meta.json, NGFF markers, or TIFF found")
    return OmeTiffSource(path), "ome-tiff"


def cmd_info(args) -> int:
    src, backend = _open_source(args.path)
    try:
        sx, sy = src.resolution_descriptions()[0]
        print(f"backend:  {backend}")
        print(f"plane:    {sx} x {sy}")
        print(f"z/c/t:    {src.size_z} / {src.size_c} / {src.size_t}")
        print(f"dtype:    {np.dtype(src.dtype).name}")
        print(f"levels:   {src.resolution_descriptions()}")
        print(f"tile:     {src.tile_size()}")
    finally:
        src.close()
    return 0


def _gather_planes(src):
    """[T, C, Z, H, W] assembled via the sources' own stack reads."""
    sx, sy = src.resolution_descriptions()[0]
    out = np.empty((src.size_t, src.size_c, src.size_z, sy, sx),
                   dtype=src.dtype)
    for t in range(src.size_t):
        for c in range(src.size_c):
            out[t, c] = src.get_stack(c, t)
    return out


def cmd_tiff_to_store(args) -> int:
    from .io.ometiff import OmeTiffSource
    from .io.store import build_pyramid

    src = OmeTiffSource(args.tiff)
    try:
        planes = _gather_planes(src)
    finally:
        src.close()
    build_pyramid(planes, args.image_dir, chunk=(args.tile, args.tile),
                  min_level_size=args.min_level)
    print(f"wrote chunked pyramid at {args.image_dir}")
    return 0


def cmd_store_to_tiff(args) -> int:
    from .io.store import ChunkedPyramidStore
    from .io.tiffwrite import _OME_TYPE, write_ome_tiff

    src = ChunkedPyramidStore(args.image_dir)
    if np.dtype(src.dtype).name not in _OME_TYPE:
        src.close()
        raise SystemExit(
            f"{args.image_dir}: dtype {np.dtype(src.dtype).name} has no "
            f"OME-TIFF pixel type (supported: "
            f"{', '.join(sorted(_OME_TYPE))})")
    try:
        planes = _gather_planes(src)
    finally:
        src.close()
    write_ome_tiff(planes, args.tiff, tile=(args.tile, args.tile),
                   compression=args.compression,
                   min_level_size=args.min_level)
    print(f"wrote OME-TIFF at {args.tiff}")
    return 0


def cmd_to_ngff(args) -> int:
    from .io.ngff import write_ngff

    src, backend = _open_source(args.src)
    try:
        planes = _gather_planes(src)
    finally:
        src.close()
    write_ngff(planes, args.zarr_dir, chunk=(args.tile, args.tile),
               min_level_size=args.min_level,
               compressor=(None if args.compression == "none"
                           else args.compression))
    print(f"wrote OME-NGFF at {args.zarr_dir} (from {backend})")
    return 0


def cmd_pyramid(args) -> int:
    """Build an unpyramided source's multiscale NGFF levels through
    the SAME crash-safe job path the server's ``POST /pyramid`` runs
    (``server.jobs.PyramidJobManager``): device downsample, atomic
    per-level commits, resume-after-kill."""
    from .server.jobs import PyramidJobManager

    manager = PyramidJobManager(
        chunk=(args.tile, args.tile), min_level_size=args.min_level,
        compressor=(None if args.compression == "none"
                    else args.compression))
    try:
        job = manager.submit(args.src)
    except FileNotFoundError as e:
        print(f"error: no such source: {e}", file=sys.stderr)
        return 2
    try:
        manager.run_job_sync(job)
    except Exception as e:
        print(f"error: pyramid build failed: {e}", file=sys.stderr)
        return 1
    print(f"built {job.levels_done}/{job.levels_total} levels at "
          f"{job.dest}" + (" (resumed)" if job.resumed else ""))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m omero_ms_image_region_tpu.ingest",
        description="Convert/inspect image-region data directories")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("info", help="print an image's geometry")
    p.add_argument("path")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("tiff-to-store",
                       help="OME-TIFF -> chunked pyramid dir")
    p.add_argument("tiff")
    p.add_argument("image_dir")
    p.add_argument("--tile", type=int, default=256)
    p.add_argument("--min-level", type=int, default=256)
    p.set_defaults(fn=cmd_tiff_to_store)

    p = sub.add_parser("store-to-tiff",
                       help="chunked pyramid dir -> tiled OME-TIFF")
    p.add_argument("image_dir")
    p.add_argument("tiff")
    p.add_argument("--tile", type=int, default=256)
    p.add_argument("--min-level", type=int, default=256)
    p.add_argument("--compression", choices=["none", "deflate"],
                   default="deflate")
    p.set_defaults(fn=cmd_store_to_tiff)

    p = sub.add_parser("to-ngff",
                       help="any readable source -> OME-NGFF zarr")
    p.add_argument("src")
    p.add_argument("zarr_dir")
    p.add_argument("--tile", type=int, default=256)
    p.add_argument("--min-level", type=int, default=256)
    p.add_argument("--compression", choices=["none", "zlib", "gzip"],
                   default="zlib")
    p.set_defaults(fn=cmd_to_ngff)

    p = sub.add_parser("pyramid",
                       help="build multiscale NGFF levels in place "
                            "(the server's POST /pyramid job path)")
    p.add_argument("src")
    p.add_argument("--tile", type=int, default=256)
    p.add_argument("--min-level", type=int, default=256)
    p.add_argument("--compression", choices=["none", "zlib", "gzip"],
                   default="zlib")
    p.set_defaults(fn=cmd_pyramid)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
