"""Experimental kernels — NOT on any serving path.

Code here is kept for reference and future work; nothing in server/,
ops/ or parallel/ imports it.  See each module's docstring for why it
was demoted.
"""
