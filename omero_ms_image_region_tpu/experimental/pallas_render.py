"""Pallas TPU kernel for the fused tile render — EXPERIMENTAL, demoted
off the serving path (round 3).

Why demoted, with the on-chip evidence (v5e via tunnel, 2026-07-30):

* Trivial Mosaic kernels now compile and run on the real chip (the
  earlier remote-compile breakage is gone), but THIS kernel's one-hot
  MXU formulation needs a ``(bh, W) -> (bh*W, 1)`` flatten that Mosaic
  rejects: ``infer-vector-layout: unsupported shape cast`` for
  ``tpu.reshape (256x1024) -> (262144x1)``.  Parity therefore still
  holds only in interpret mode (tests/test_pallas.py).
* More decisively: stage profiling on the real chip shows the XLA
  render+DCT+quant path costs ~3 ms per 8-tile 1024^2 batch — the
  render is already fused and effectively free, with the JPEG wire
  packers' compaction/deposit scatters dominating device time.  A
  faster render kernel has no headroom to win; the serving path should
  not carry a dead config option for it
  (``Renderer.renderAsPackedInt``, ``ImageRegionRequestHandler
  .java:559``, is fully served by ``ops.render``).

Kept as an experiment: the one-hot-as-MXU-contraction pattern and the
SMEM scalar-prefetch layout are reusable if a VMEM-resident fusion ever
becomes the bottleneck.

Alternative device path to ``ops.render``'s XLA-fused gather: the whole
pipeline — per-channel window/family quantization, reverse-intensity, color
table application, additive composite, u32 pack — runs in one pallas kernel
per (batch, row-block) grid step, with the color lookup expressed as a
**one-hot contraction on the MXU** instead of a gather:

    onehot(q)[N, 256] @ table[256, 3]  ==  table[q]

The VPU builds the one-hot by comparing q against a [256]-iota; the MXU
contracts it with the channel's 256x3 table.  At 256 classes that is
256x2 FLOPs per pixel-component — trivial against the MXU's throughput —
and it avoids dynamic-index gathers, which TPUs have no vector unit for.

Everything stays in VMEM for a row block: raw f32[C, bh, W], tables
f32[C*256, 3 padded], out u32[bh, W].  Settings are per-channel scalars
prefetched to SMEM.

Used when ``jax.default_backend() == "tpu"`` (interpret mode covers CPU
tests); ``ops.render`` remains the portable reference path.  Replaces the
same reference surface (``Renderer.renderAsPackedInt``,
``ImageRegionRequestHandler.java:559``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.quantum import _ratio as _quantum_ratio

# Row-block height per grid step; W is never blocked (tiles are <= 2048
# wide and a full row keeps the lane dim dense).
_BLOCK_H = 256


def pick_block_h(H: int, max_block: int = _BLOCK_H) -> int:
    """Largest divisor of H at most ``max_block``.

    The grid covers H in equal row blocks, so bh must divide H exactly;
    the production buckets (256/512/1024/2048) all take ``max_block``,
    while odd heights fall back to their largest small divisor (worst
    case 1 for a large prime — correct, never fast; bucket such shapes
    upstream).
    """
    bh = min(max_block, H)
    while H % bh:
        bh -= 1
    return bh


def _render_kernel(ws_ref, we_ref, fam_ref, coef_ref, rev_ref, cd_ref,
                   raw_ref, tables_ref, out_ref):
    """One (batch, row-block) grid step.

    raw_ref:    f32[C, bh, W]       (VMEM; already loaded block)
    tables_ref: f32[C, 256, 128]    (VMEM; only cols 0..2 are live)
    out_ref:    u32[1, bh, W]       (VMEM ref; leading block dim)
    scalars (SMEM, prefetched): ws/we/fam/coef/rev f32|i32[C], cd i32[2]
    """
    C, bh, W = raw_ref.shape
    cd_start = cd_ref[0]
    cd_end = cd_ref[1]
    k_max = (cd_end - cd_start).astype(jnp.float32)

    acc_r = jnp.zeros((bh, W), jnp.float32)
    acc_g = jnp.zeros((bh, W), jnp.float32)
    acc_b = jnp.zeros((bh, W), jnp.float32)

    for c in range(C):  # C is a static block dim: unrolled at trace time
        x = raw_ref[c]
        ws = ws_ref[c]
        we = we_ref[c]
        fam = fam_ref[c]
        k = coef_ref[c]

        # Window clamp + family curve: the exact closed forms the XLA
        # kernel uses (ops.quantum._ratio), evaluated on VMEM blocks, so
        # the two paths agree bit-for-bit for every family.
        x_clamped = jnp.clip(x, jnp.minimum(ws, we), jnp.maximum(ws, we))
        ratio = jnp.clip(
            _quantum_ratio(x_clamped, x, ws, we, fam, k), 0.0, 1.0)
        q = jnp.round(cd_start.astype(jnp.float32) + k_max * ratio)
        # Reverse-intensity codomain op.
        q = jnp.where(rev_ref[c] != 0,
                      (cd_start + cd_end).astype(jnp.float32) - q, q)
        q = jnp.clip(q, 0.0, 255.0)

        # One-hot contraction on the MXU: [bh*W, 256] @ [256, 128].
        # (Integer compare: Mosaic rejects float iota.)
        qi = q.astype(jnp.int32).reshape(bh * W, 1)
        classes = jax.lax.broadcasted_iota(jnp.int32, (1, 256), 1)
        onehot = (qi == classes).astype(jnp.float32)
        rgb = jnp.dot(onehot, tables_ref[c],
                      preferred_element_type=jnp.float32)
        acc_r += rgb[:, 0].reshape(bh, W)
        acc_g += rgb[:, 1].reshape(bh, W)
        acc_b += rgb[:, 2].reshape(bh, W)

    # Mosaic has no direct f32->u32 cast; go through i32 (values <= 255).
    r = jnp.clip(jnp.round(acc_r), 0.0, 255.0).astype(jnp.int32)
    g = jnp.clip(jnp.round(acc_g), 0.0, 255.0).astype(jnp.int32)
    b = jnp.clip(jnp.round(acc_b), 0.0, 255.0).astype(jnp.int32)
    packed = r | (g << 8) | (b << 16) | jnp.int32(-0x1000000)  # A=0xFF
    out_ref[0] = jax.lax.bitcast_convert_type(packed, jnp.uint32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def render_tile_batch_packed_pallas(raw, window_start, window_end, family,
                                    coefficient, reverse, cd_start, cd_end,
                                    tables, *, interpret=False):
    """Pallas fused batched render: f32[B, C, H, W] -> u32[B, H, W].

    Same contract as ``ops.render.render_tile_batch_packed`` except the
    per-channel settings are shared across the batch (the batcher keys
    groups by settings when using this path), so they arrive unbatched:
    window_start/window_end/coefficient f32[C], family/reverse i32[C],
    tables f32[C, 256, 3].
    """
    B, C, H, W = raw.shape
    bh = pick_block_h(H)

    # Pad table color axis 3 -> 128 so the MXU contraction output is
    # lane-aligned; dead columns contract to zeros.
    tables_padded = jnp.zeros((C, 256, 128), jnp.float32)
    tables_padded = tables_padded.at[:, :, :3].set(
        tables.astype(jnp.float32))
    cd = jnp.stack([jnp.asarray(cd_start, jnp.int32),
                    jnp.asarray(cd_end, jnp.int32)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(B, H // bh),
        in_specs=[
            pl.BlockSpec((1, C, bh, W), lambda b, h, *_: (b, 0, h, 0)),
            pl.BlockSpec((C, 256, 128), lambda b, h, *_: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bh, W), lambda b, h, *_: (b, h, 0)),
    )

    def kernel(ws, we, fam, coef, rev, cdv, raw_blk, tab_blk, out_blk):
        _render_kernel(ws, we, fam, coef, rev, cdv,
                       raw_blk[0], tab_blk, out_blk)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, W), jnp.uint32),
        interpret=interpret,
    )(window_start.astype(jnp.float32), window_end.astype(jnp.float32),
      family.astype(jnp.int32), coefficient.astype(jnp.float32),
      reverse.astype(jnp.int32), cd,
      raw.astype(jnp.float32), tables_padded)
