"""Pallas TPU kernels for the fused tile render.

Two kernels, matching :mod:`..ops.render`'s own shape dispatch:

* **Ramp kernel** (``tables`` = f32[C, 3] weights) — the serving-path
  formulation, promoted in round 6 as a COMPILE-GUARDED option
  (``renderer.kernel: pallas``; ``server.handler.Renderer`` falls back
  to the XLA kernel on any compile/runtime failure, so the option can
  only ever remove work).  ``pack_settings`` emits ramp weights
  whenever no active channel resolves an actual LUT file — the
  overwhelmingly common case — and the ramp composite is pure
  elementwise arithmetic: window clamp, family curve, round, per-channel
  multiply-accumulate, clip, u32 pack.  No gather, no one-hot, no
  reshape — nothing in the Mosaic-unsupported layout classes.  This is
  the same reformulation the XLA path itself made
  (``ops.render.composite_ramp_packed``: arithmetic beats table gathers
  ~9x on TPU), applied to the Pallas formulation: the round-3 blocker —
  a ``(bh, W) -> (bh*W, 1)`` flatten Mosaic rejects
  (``infer-vector-layout: unsupported shape cast``, minor dim cast to
  1) — existed only to feed the one-hot MXU contraction, and the ramp
  path needs neither.

* **One-hot LUT kernel** (``tables`` = f32[C, 256, 3]) — the original
  round-3 experiment, kept for real-LUT renders and as the
  one-hot-as-MXU-contraction reference:

      onehot(q)[N, 256] @ table[256, 3]  ==  table[q]

  Still EXPERIMENTAL on hardware: the pixel flatten feeding the MXU is
  now expressed as a leading-dim collapse ``(bh, W, 256) ->
  (bh*W, 256)`` (minor dim preserved — the shape-cast class Mosaic
  supports) instead of the rejected minor-dim-1 cast, and the row block
  is sized so the one-hot fits VMEM, but the final per-component
  un-flatten remains a layout hazard; parity is proven in interpret
  mode (tests/test_pallas.py) and the serving option never routes LUT
  renders here.

Stage profiling on-chip (v5e via tunnel, 2026-07-30) shows the XLA
render+DCT+quant path costs ~3 ms per 8-tile 1024^2 batch — the wire
packers dominate device time — which is why the Pallas kernel lands as
an option rather than the default: ``ops.render`` remains the portable
reference, and the option exists for deployments where a VMEM-resident
fusion measures faster.

Replaces the same reference surface (``Renderer.renderAsPackedInt``,
``ImageRegionRequestHandler.java:559``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.quantum import _ratio as _quantum_ratio

# Row-block height per grid step; W is never blocked (tiles are <= 2048
# wide and a full row keeps the lane dim dense).
_BLOCK_H = 256
# LUT (one-hot) kernel budget: the materialized one-hot is
# f32[bh*W, 256] (1 KB per pixel), so the row block is capped to keep
# it ~4 MB of VMEM.
_ONEHOT_MAX_PIXELS = 4096


def pick_block_h(H: int, max_block: int = _BLOCK_H) -> int:
    """Largest divisor of H at most ``max_block``.

    The grid covers H in equal row blocks, so bh must divide H exactly;
    the production buckets (256/512/1024/2048) all take ``max_block``,
    while odd heights fall back to their largest small divisor (worst
    case 1 for a large prime — correct, never fast; bucket such shapes
    upstream).
    """
    bh = min(max_block, H)
    while H % bh:
        bh -= 1
    return bh


def _quantize_channel(x, ws, we, fam, k, cd_start, cd_end, rev):
    """One channel's window clamp + family curve + reverse, in f32.

    The exact closed forms the XLA kernel uses (ops.quantum._ratio),
    evaluated on VMEM blocks, so the two paths agree bit-for-bit for
    every family.
    """
    k_max = (cd_end - cd_start).astype(jnp.float32)
    x_clamped = jnp.clip(x, jnp.minimum(ws, we), jnp.maximum(ws, we))
    ratio = jnp.clip(
        _quantum_ratio(x_clamped, x, ws, we, fam, k), 0.0, 1.0)
    q = jnp.round(cd_start.astype(jnp.float32) + k_max * ratio)
    q = jnp.where(rev != 0,
                  (cd_start + cd_end).astype(jnp.float32) - q, q)
    return jnp.clip(q, 0.0, 255.0)


def _pack_u32(acc_r, acc_g, acc_b):
    """Clip/round the composites and pack to the u32 RGBA layout.

    Mosaic has no direct f32->u32 cast; go through i32 (values <= 255).
    """
    r = jnp.clip(jnp.round(acc_r), 0.0, 255.0).astype(jnp.int32)
    g = jnp.clip(jnp.round(acc_g), 0.0, 255.0).astype(jnp.int32)
    b = jnp.clip(jnp.round(acc_b), 0.0, 255.0).astype(jnp.int32)
    packed = r | (g << 8) | (b << 16) | jnp.int32(-0x1000000)  # A=0xFF
    return jax.lax.bitcast_convert_type(packed, jnp.uint32)


def _render_kernel_ramp(ws_ref, we_ref, fam_ref, coef_ref, rev_ref,
                        cd_ref, w_ref, raw_ref, out_ref):
    """One (batch, row-block) grid step of the RAMP composite.

    raw_ref: f32[C, bh, W] (VMEM; already loaded block)
    out_ref: u32[1, bh, W] (VMEM ref; leading block dim)
    scalars (SMEM, prefetched): ws/we/coef f32[C], fam/rev i32[C],
    cd i32[2], w f32[C*3] flattened ramp weights.

    Entirely elementwise — the serving formulation with no layout
    hazards (see module docstring).
    """
    C, bh, W = raw_ref.shape
    cd_start = cd_ref[0]
    cd_end = cd_ref[1]

    acc_r = jnp.zeros((bh, W), jnp.float32)
    acc_g = jnp.zeros((bh, W), jnp.float32)
    acc_b = jnp.zeros((bh, W), jnp.float32)

    for c in range(C):  # C is a static block dim: unrolled at trace time
        q = _quantize_channel(raw_ref[c], ws_ref[c], we_ref[c],
                              fam_ref[c], coef_ref[c], cd_start,
                              cd_end, rev_ref[c])
        acc_r += q * w_ref[3 * c]
        acc_g += q * w_ref[3 * c + 1]
        acc_b += q * w_ref[3 * c + 2]

    out_ref[0] = _pack_u32(acc_r, acc_g, acc_b)


def _render_kernel_lut(ws_ref, we_ref, fam_ref, coef_ref, rev_ref,
                       cd_ref, raw_ref, tables_ref, out_ref):
    """One (batch, row-block) grid step of the one-hot LUT composite.

    raw_ref:    f32[C, bh, W]       (VMEM; already loaded block)
    tables_ref: f32[C, 256, 128]    (VMEM; only cols 0..2 are live)
    out_ref:    u32[1, bh, W]       (VMEM ref; leading block dim)
    scalars (SMEM, prefetched): ws/we/fam/coef/rev f32|i32[C], cd i32[2]
    """
    C, bh, W = raw_ref.shape
    cd_start = cd_ref[0]
    cd_end = cd_ref[1]

    acc_r = jnp.zeros((bh, W), jnp.float32)
    acc_g = jnp.zeros((bh, W), jnp.float32)
    acc_b = jnp.zeros((bh, W), jnp.float32)

    for c in range(C):
        q = _quantize_channel(raw_ref[c], ws_ref[c], we_ref[c],
                              fam_ref[c], coef_ref[c], cd_start,
                              cd_end, rev_ref[c])
        # One-hot contraction on the MXU: [bh*W, 256] @ [256, 128].
        # The one-hot is built 3-D with the class axis MINOR and the
        # pixel flatten expressed as a leading-dim collapse (minor dim
        # preserved) — the shape-cast class Mosaic supports, unlike the
        # round-3 (bh, W) -> (bh*W, 1) minor-dim cast it rejected.
        # (Integer compare: Mosaic rejects float iota.)
        qi = q.astype(jnp.int32)
        classes = jax.lax.broadcasted_iota(jnp.int32, (bh, W, 256), 2)
        qb = jax.lax.broadcast_in_dim(qi, (bh, W, 256), (0, 1))
        onehot = (qb == classes).astype(jnp.float32).reshape(
            bh * W, 256)
        rgb = jnp.dot(onehot, tables_ref[c],
                      preferred_element_type=jnp.float32)
        acc_r += rgb[:, 0].reshape(bh, W)
        acc_g += rgb[:, 1].reshape(bh, W)
        acc_b += rgb[:, 2].reshape(bh, W)

    out_ref[0] = _pack_u32(acc_r, acc_g, acc_b)


@functools.partial(jax.jit, static_argnames=("interpret",))
def render_tile_batch_packed_pallas(raw, window_start, window_end, family,
                                    coefficient, reverse, cd_start, cd_end,
                                    tables, *, interpret=False):
    """Pallas fused batched render: f32[B, C, H, W] -> u32[B, H, W].

    Same contract as ``ops.render.render_tile_batch_packed`` except the
    per-channel settings are shared across the batch (the direct
    renderer's case; the batcher keys groups by settings when using
    this path), so they arrive unbatched: window_start/window_end/
    coefficient f32[C], family/reverse i32[C], and ``tables`` either
    f32[C, 3] ramp weights (the serving ramp kernel) or f32[C, 256, 3]
    LUT tables (the experimental one-hot kernel) — the same shape
    dispatch as ``ops.render._render_packed_impl``.
    """
    B, C, H, W = raw.shape
    cd = jnp.stack([jnp.asarray(cd_start, jnp.int32),
                    jnp.asarray(cd_end, jnp.int32)])
    scalars = (window_start.astype(jnp.float32),
               window_end.astype(jnp.float32),
               family.astype(jnp.int32),
               coefficient.astype(jnp.float32),
               reverse.astype(jnp.int32), cd)

    if tables.ndim == 2:
        # Ramp weights [C, 3]: the elementwise serving kernel.  The
        # weights ride SMEM with the other per-channel scalars.
        bh = pick_block_h(H)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=7,
            grid=(B, H // bh),
            in_specs=[
                pl.BlockSpec((1, C, bh, W), lambda b, h, *_: (b, 0, h, 0)),
            ],
            out_specs=pl.BlockSpec((1, bh, W), lambda b, h, *_: (b, h, 0)),
        )

        def kernel(ws, we, fam, coef, rev, cdv, w, raw_blk, out_blk):
            _render_kernel_ramp(ws, we, fam, coef, rev, cdv, w,
                                raw_blk[0], out_blk)

        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, H, W), jnp.uint32),
            interpret=interpret,
        )(*scalars, tables.astype(jnp.float32).reshape(C * 3),
          raw.astype(jnp.float32))

    # LUT tables [C, 256, 3]: pad the color axis 3 -> 128 so the MXU
    # contraction output is lane-aligned; dead columns contract to
    # zeros.  Row block capped so the materialized one-hot fits VMEM.
    bh = pick_block_h(H, max_block=max(1, _ONEHOT_MAX_PIXELS // W))
    tables_padded = jnp.zeros((C, 256, 128), jnp.float32)
    tables_padded = tables_padded.at[:, :, :3].set(
        tables.astype(jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(B, H // bh),
        in_specs=[
            pl.BlockSpec((1, C, bh, W), lambda b, h, *_: (b, 0, h, 0)),
            pl.BlockSpec((C, 256, 128), lambda b, h, *_: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bh, W), lambda b, h, *_: (b, h, 0)),
    )

    def kernel(ws, we, fam, coef, rev, cdv, raw_blk, tab_blk, out_blk):
        _render_kernel_lut(ws, we, fam, coef, rev, cdv,
                           raw_blk[0], tab_blk, out_blk)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, W), jnp.uint32),
        interpret=interpret,
    )(*scalars, raw.astype(jnp.float32), tables_padded)


def render_tile_packed_pallas(raw, window_start, window_end, family,
                              coefficient, reverse, cd_start, cd_end,
                              tables, *, interpret=False):
    """Single-tile convenience: f32[C, H, W] -> u32[H, W] (the direct
    renderer's call shape)."""
    return render_tile_batch_packed_pallas(
        raw[None], window_start, window_end, family, coefficient,
        reverse, cd_start, cd_end, tables, interpret=interpret)[0]
