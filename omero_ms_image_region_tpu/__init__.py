"""TPU-native image-region rendering framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
omero-ms-image-region (reference: /root/reference, a Java 8 / Vert.x
microservice).  The per-tile pixel pipeline (raw read -> per-channel window
quantization -> LUT/color -> RGB composite -> projection -> crop/flip ->
encode) runs as batched, jit-compiled JAX kernels on TPU; the protocol layer
(HTTP routes, sessions, caches, ACL, metadata) is asyncio host code.

Layer map (mirrors SURVEY.md section 1, rebuilt TPU-first):
  server/   - HTTP/API + request contexts + orchestration  (ref L5-L2)
  ops/      - JAX render kernels                           (ref L1 Renderer)
  models/   - rendering metadata value objects             (ref ome.model.*)
  io/       - pixel sources / pyramid access               (ref PixelBuffer)
  codecs/   - JPEG/PNG/TIFF encode stage                   (ref LocalCompress)
  parallel/ - micro-batching + device-mesh sharding        (ref worker pool)
  utils/    - hashing, colors, config, tracing
"""

__version__ = "0.1.0"
