"""Image encoding: RGBA device output -> HTTP bytes.

Replaces the reference's encode stage (``ImageRegionRequestHandler.java:
576-600``): JPEG via the compression service with a float quality
(``LocalCompress``, set at ``:457-460``), PNG via ImageIO, TIFF via the JAI
``TIFFImageWriter``, and the mask path's palettized PNG with a 2-entry
transparent/fill color model (``ShapeMaskRequestHandler.java:185-203``).

Encoding is host-side CPU work downstream of the device kernel; it runs in
worker threads so the event loop and the TPU dispatch never block on it.
"""

from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np
from PIL import Image

# LocalCompressImpl's default JPEG quality when the request carries none.
DEFAULT_JPEG_QUALITY = 0.85

CONTENT_TYPES = {
    "jpeg": "image/jpeg",
    "png": "image/png",
    "tif": "image/tiff",
}


class UnknownFormatError(ValueError):
    """Unsupported output format (the reference logs and returns null,
    surfacing as a 404; ``ImageRegionRequestHandler.java:598-600``)."""


def quality_percent(quality: Optional[float]) -> int:
    """Request 0..1 float -> integer percent, with the LocalCompress
    default; the single source for both the PIL and device JPEG paths."""
    q = DEFAULT_JPEG_QUALITY if quality is None else quality
    return max(1, min(100, round(q * 100)))


def encode_rgba(rgba: np.ndarray, fmt: str,
                quality: Optional[float] = None) -> bytes:
    """Encode an RGBA tile to ``jpeg`` / ``png`` / ``tif`` bytes.

    ``rgba`` is u8[H, W, 4].  The reference builds an opaque
    ``TYPE_INT_RGB`` image from the packed ints (``ImageUtil
    .createBufferedImage``, ``:576-578``), so alpha is dropped for every
    format here too.  ``quality`` is the request's 0..1 float.
    """
    if fmt not in CONTENT_TYPES:
        raise UnknownFormatError(f"Unknown format {fmt}")
    img = Image.fromarray(np.ascontiguousarray(rgba[..., :3]), mode="RGB")
    buf = io.BytesIO()
    if fmt == "jpeg":
        img.save(buf, format="JPEG", quality=quality_percent(quality))
    elif fmt == "png":
        img.save(buf, format="PNG")
    else:
        img.save(buf, format="TIFF")
    return buf.getvalue()


def encode_mask_png(grid: np.ndarray,
                    fill_color: Tuple[int, int, int, int]) -> bytes:
    """Encode a 0/1 mask grid as a palettized PNG.

    Mirrors the reference's 2-entry ``IndexColorModel`` — index 0 fully
    transparent, index 1 the fill color with its alpha
    (``ShapeMaskRequestHandler.java:185-203``).
    """
    grid = np.ascontiguousarray(grid.astype(np.uint8))
    img = Image.fromarray(grid, mode="P")
    r, g, b, a = fill_color
    img.putpalette([0, 0, 0, r, g, b][: 6])
    buf = io.BytesIO()
    img.save(buf, format="PNG", transparency=bytes([0, a]))
    return buf.getvalue()


def decode_to_rgba(data: bytes) -> np.ndarray:
    """Decode any supported image to u8[H, W, 4] (test/verification aid)."""
    img = Image.open(io.BytesIO(data)).convert("RGBA")
    return np.asarray(img)
