"""Multi-host deployment: the distributed communication backend.

The reference scales out by joining microservice JVMs into a Hazelcast
cluster over the Vert.x event bus (``-cluster``; SURVEY.md §5 "distributed
communication backend").  The TPU-native equivalent is JAX's distributed
runtime: each host process joins a coordinator (DCN), after which
``jax.devices()`` spans every chip in the slice and a single
``jax.sharding.Mesh`` over the global device list makes the sharded
serving steps (``parallel.mesh``) span hosts — collectives ride ICI
within a slice, DCN across slices, with no application-level cluster
protocol at all.  Cross-instance *state* (tile cache, canRead memo) rides
Redis (``services.cache``), mirroring the reference's split between
cluster transport and shared maps.

Typical multi-host launch (one process per host, same command)::

    from omero_ms_image_region_tpu.parallel import cluster
    cluster.initialize()                 # env-driven (TPU pods: automatic)
    mesh = cluster.global_mesh(chan_parallel=2)
    step = render_jpeg_step_sharded(mesh)
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .mesh import Mesh, make_mesh, resolve_devices


def _distributed_initialized() -> bool:
    """Has this process already joined ``jax.distributed``?

    ``jax.distributed.is_initialized()`` only exists on newer jax
    releases; older ones (this image ships 0.4.x without it) expose the
    same fact through the private runtime state's client handle.  Both
    probes are backend-free — neither touches XLA, which is the whole
    point of checking before ``initialize()``.
    """
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        # No known probe surface: let initialize() itself decide (it
        # raises cleanly when already joined, which the caller treats
        # as the standalone fallback for auto-discovered setups).
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the JAX distributed runtime (idempotent).

    On Cloud TPU pods every argument is discovered from the environment;
    elsewhere pass the coordinator explicitly.  Safe to call in
    single-process deployments: with no coordinator configured anywhere it
    leaves the process standalone.
    """
    # Idempotency check WITHOUT touching the backend:
    # jax.process_count() would initialize XLA, after which
    # jax.distributed.initialize() permanently refuses — i.e. the old
    # process_count() probe made every explicit multi-host join fail.
    # (Caught by the 2-process simulated-pod test.)
    if _distributed_initialized():
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if coordinator_address is not None:
            raise  # explicit cluster config that failed must be loud
        # No cluster environment: standalone single-process service.


def host_identity() -> str:
    """A stable identity for THIS host, for ``federation.host``
    defaults and diagnostics: the JAX distributed process index when a
    cluster is joined (``procN`` — stable across the slice by
    construction), else the OS hostname.  Backend-free unless a
    cluster was already joined (the :func:`initialize` discipline)."""
    if _distributed_initialized():
        try:
            return f"proc{jax.process_index()}"
        except Exception:
            pass
    import socket
    return socket.gethostname()


def global_mesh(chan_parallel: int = 1,
                n_devices: Optional[int] = None) -> Mesh:
    """A ``(data, chan)`` mesh over every device in the (multi-host) slice.

    With ``jax.distributed`` initialized this spans all hosts; the sharded
    steps built on it (``render_step_sharded`` /
    ``render_jpeg_step_sharded``) then execute one program over the whole
    slice, each host feeding its addressable shard of the batch.

    ``n_devices`` requests a minimum mesh width: when the default platform
    is narrower (e.g. a single local chip during tests) this falls back to
    the virtual host (CPU) mesh exactly like ``mesh.make_mesh`` does, so
    mesh-shape-dependent code paths stay exercisable everywhere.
    """
    devices = np.asarray(resolve_devices(n_devices))
    return make_mesh(len(devices), chan_parallel=chan_parallel,
                     devices=devices)


def local_batch_slice(mesh: Mesh, global_batch: int) -> slice:
    """This process's rows of the global batch (data-axis locality).

    Hosts feed only their addressable shard; the slice maps a global
    [B, ...] workload to the rows this process should stage.
    """
    data_size = mesh.shape["data"]
    if global_batch % data_size:
        raise ValueError(
            f"global batch {global_batch} not divisible by data axis "
            f"{data_size}")
    per_shard = global_batch // data_size
    rows = [i for i, d in enumerate(mesh.devices[:, 0])
            if d.process_index == jax.process_index()]
    if not rows:
        return slice(0, 0)
    if rows != list(range(rows[0], rows[-1] + 1)):
        raise ValueError(
            "this process's data-axis rows are not contiguous "
            f"({rows}); a single slice cannot describe its shard — "
            "reorder the mesh so each process owns a contiguous run "
            "of data rows")
    return slice(rows[0] * per_shard, (rows[-1] + 1) * per_shard)
