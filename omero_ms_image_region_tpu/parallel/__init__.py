"""Device-mesh parallelism for the render pipeline.

The reference scales out with Vert.x worker verticles + a Hazelcast-clustered
event bus (SURVEY.md section 2c).  The TPU-native analogue is a
``jax.sharding.Mesh``: tile batches are data-parallel over the ``data`` axis
and the per-channel quantize/LUT/composite pipeline is tensor-parallel over
the ``chan`` axis, with the additive composite expressed as a ``psum``
collective riding ICI.
"""

from .mesh import (  # noqa: F401
    make_mesh,
    render_step_sharded,
    shard_batch,
)
