"""Mesh-sharded render step (data-parallel tiles x tensor-parallel channels).

The reference's concurrency model is request-level data parallelism over
worker verticles plus cluster scale-out over a Hazelcast event bus
(``ImageRegionMicroserviceVerticle.java:148-165``, SURVEY.md section 2c).
Here that becomes a 2-D ``jax.sharding.Mesh``:

  * ``data`` axis — concurrent tile requests (the micro-batch) are sharded
    across devices: pure DP, no communication.
  * ``chan`` axis — the per-channel pipeline (window/family quantize + LUT
    gather + alpha-weighted contribution) is sharded across channels: each
    device renders its local channel slice and the additive RGB composite
    (``Renderer.renderAsPackedInt``'s sum over active channels) becomes a
    single ``jax.lax.psum`` over the ``chan`` axis — the collective rides
    ICI, replacing the reference's in-JVM accumulation loop.

Everything is expressed with ``shard_map`` so the collective is explicit and
XLA never has to guess the partitioning of the composite.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.quantum import quantize

logger = logging.getLogger(__name__)


def resolve_devices(n_devices: int | None = None):
    """Devices for an ``n_devices``-wide mesh, falling back to the host mesh.

    The default platform may be a single real TPU chip while a virtual
    host-platform mesh (``xla_force_host_platform_device_count``) carries the
    requested width — e.g. the driver's multi-chip dryrun, or test runs where
    a TPU plugin wins the default platform slot.  The fallback is logged:
    a CPU mesh run where a real accelerator mesh was expected should be
    visible in the logs, not silent.
    """
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpu_devices = jax.devices("cpu")
        except RuntimeError:
            cpu_devices = []
        if len(cpu_devices) >= n_devices:
            logger.warning(
                "make_mesh: default platform %r has %d device(s) < %d "
                "requested; using the %d-device virtual host (CPU) mesh",
                devices[0].platform if devices else "?", len(devices),
                n_devices, len(cpu_devices),
            )
            devices = cpu_devices
    return devices


def make_mesh(n_devices: int | None = None, chan_parallel: int = 1,
              devices=None) -> Mesh:
    """Build a ``(data, chan)`` mesh over the available devices.

    ``chan_parallel`` devices cooperate on one tile's channels; the rest of
    the devices replicate that group over the batch.
    """
    if devices is None:
        devices = resolve_devices(n_devices)
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise ValueError(
            f"requested a {n_devices}-device mesh but only "
            f"{len(devices)} device(s) are available"
        )
    devices = np.asarray(devices[:n_devices])
    if n_devices % chan_parallel != 0:
        raise ValueError(
            f"n_devices={n_devices} not divisible by "
            f"chan_parallel={chan_parallel}"
        )
    grid = devices.reshape(n_devices // chan_parallel, chan_parallel)
    return Mesh(grid, ("data", "chan"))


def _local_render(raw, window_start, window_end, family, coefficient,
                  reverse, cd_start, cd_end, tables):
    """Per-device block: quantize + gather local channels, partial composite.

    Block shapes (local to one device): raw f32[Bl, Cl, H, W], params [Cl],
    tables f32[Cl, 256, 3].  Returns the *partial* per-component RGB sum
    f32[3, Bl, H, W] (component axis leading — a trailing 3 would pad to
    128 lanes on TPU); the caller psums it over the ``chan`` axis.
    """
    q = quantize(
        raw.reshape((-1,) + raw.shape[-2:]),
        jnp.tile(window_start, raw.shape[0]),
        jnp.tile(window_end, raw.shape[0]),
        jnp.tile(family, raw.shape[0]),
        jnp.tile(coefficient, raw.shape[0]),
        cd_start,
        cd_end,
    ).reshape(raw.shape)  # i32[Bl, Cl, H, W]
    q = jnp.where(
        reverse[None, :, None, None] != 0, cd_start + cd_end - q, q
    )
    if tables.ndim == 2:
        # Ramp weights [Cl, 3]: arithmetic composite (ops.render
        # .composite_ramp_packed) — no per-pixel gather.
        qf = q.astype(jnp.float32)
        comps = [
            jnp.einsum("bchw,c->bhw", qf, tables[:, comp])
            for comp in range(3)
        ]
        return jnp.stack(comps, axis=0)            # [3, Bl, H, W]
    # Per-component flat shared-operand gather with per-channel block
    # offsets (see ops.render.composite_packed for why not table[q]).
    Cl = tables.shape[0]
    flat = tables.reshape(Cl * 256, 3)
    idx = q + (jnp.arange(Cl, dtype=q.dtype) * 256)[None, :, None, None]
    comps = [
        jnp.sum(jnp.take(flat[:, comp], idx, axis=0), axis=1)  # [Bl, H, W]
        for comp in range(3)
    ]
    return jnp.stack(comps, axis=0)                # [3, Bl, H, W]


# One spec per step argument: raw [B, C, H, W], five per-channel setting
# arrays, the two codomain scalars, and tables/weights [C, ...].
_STEP_IN_SPECS = (
    P("data", "chan"), P("chan"), P("chan"), P("chan"), P("chan"),
    P("chan"), P(), P(), P("chan"),
)


def _composite_step(raw, window_start, window_end, family, coefficient,
                    reverse, cd_start, cd_end, tables):
    """Per-shard render + cross-shard composite -> packed u32[Bl, H, W].

    The additive composite across channel shards is the one collective
    (``psum`` over ICI); the shared body of every sharded step variant.
    """
    partial_rgb = _local_render(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables,
    )                                          # f32 [3, Bl, H, W]
    rgb = jax.lax.psum(partial_rgb, axis_name="chan")
    rgb = jnp.clip(jnp.round(rgb), 0.0, 255.0).astype(jnp.uint32)
    return rgb[0] | (rgb[1] << 8) | (rgb[2] << 16) | jnp.uint32(0xFF000000)


def render_step_sharded(mesh: Mesh):
    """Build the jitted mesh-sharded batched render step.

    Returns a function ``step(raw, window_start, window_end, family,
    coefficient, reverse, cd_start, cd_end, tables) -> u32[B, H, W]``
    (packed little-endian R,G,B,A as in ``ops.render.render_tile_packed``)
    with ``raw`` f32[B, C, H, W] sharded ``P('data', 'chan')`` and
    per-channel arrays sharded ``P('chan')``; output sharded ``P('data')``.
    """
    sharded = shard_map(
        _composite_step,
        mesh=mesh,
        in_specs=_STEP_IN_SPECS,
        out_specs=P("data"),
    )
    return jax.jit(sharded)


def render_jpeg_step_sharded(mesh: Mesh, quality: int = 85,
                             cap: int | None = None):
    """The full mesh-sharded serving step: raw tiles -> JPEG wire buffers.

    Composes the sharded render (data-parallel tiles x channel-parallel
    partial composites joined by ``psum``) with the device JPEG front end
    (YCbCr, 4:2:0, blocked DCT, quantize, zigzag, sparse nonzero packing)
    — everything the single-chip serving path runs, expressed over the
    mesh, so a multi-host deployment shards whole requests end to end.
    After the ``psum`` the packed image is replicated across the ``chan``
    group, so the JPEG stage computes redundantly there and the output is
    simply data-sharded.

    Returns ``step(*shard_batch(...)) -> u8[B, wire_bytes]`` sparse
    buffers (``ops.jpegenc.sparse_pack`` layout; finish host-side with
    ``ops.jpegenc.encode_sparse_buffers``).
    """
    from ..ops.jpegenc import (default_sparse_cap, packed_to_jpeg_coefficients,
                               quant_tables, sparse_pack)

    # Keep the quant tables as host numpy and lift them to device constants
    # only inside the traced step: an eager ``jnp.asarray`` here would land
    # on the *default* platform, which may be a different (even broken)
    # backend than the mesh the step runs on.
    qy_h, qc_h = (np.asarray(t, np.int32) for t in quant_tables(quality))

    def step(*args):
        packed = _composite_step(*args)              # u32[Bl, H, W]
        H, W = packed.shape[-2:]
        local_cap = cap if cap is not None else default_sparse_cap(H, W)
        y, cb, cr = packed_to_jpeg_coefficients(
            packed, jnp.asarray(qy_h), jnp.asarray(qc_h))
        return sparse_pack(y, cb, cr, local_cap)

    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=_STEP_IN_SPECS,
        out_specs=P("data"),
    )
    return jax.jit(sharded)


def _local_render_batched(raw, window_start, window_end, family,
                          coefficient, reverse, cd_start, cd_end, tables):
    """Per-device block with PER-TILE settings: raw f32[Bl, Cl, H, W],
    settings [Bl, Cl], tables [Bl, Cl, ...].  The serving path's form —
    concurrent requests carry their own windows/colors — where
    :func:`_local_render` shares one setting vector across the batch.
    Returns the partial per-component RGB sum f32[3, Bl, H, W]."""
    Bl, Cl = raw.shape[:2]
    q = quantize(
        raw.reshape((-1,) + raw.shape[-2:]),
        window_start.reshape(-1),
        window_end.reshape(-1),
        family.reshape(-1),
        coefficient.reshape(-1),
        cd_start,
        cd_end,
    ).reshape(raw.shape)
    q = jnp.where(reverse[..., None, None] != 0, cd_start + cd_end - q, q)
    if tables.ndim == 3:
        qf = q.astype(jnp.float32)
        comps = [
            jnp.einsum("bchw,bc->bhw", qf, tables[..., comp])
            for comp in range(3)
        ]
        return jnp.stack(comps, axis=0)
    flat = tables.reshape(Bl * Cl * 256, 3)
    offs = (jnp.arange(Bl * Cl, dtype=q.dtype) * 256).reshape(Bl, Cl, 1, 1)
    idx = q + offs
    comps = [
        jnp.sum(jnp.take(flat[:, comp], idx, axis=0), axis=1)
        for comp in range(3)
    ]
    return jnp.stack(comps, axis=0)


# Batched-settings step: every per-channel array gains a leading batch
# dim and shards with the tiles.
_BATCHED_STEP_IN_SPECS = (
    P("data", "chan"), P("data", "chan"), P("data", "chan"),
    P("data", "chan"), P("data", "chan"), P("data", "chan"), P(), P(),
    P("data", "chan"),
)


def _composite_step_batched(raw, window_start, window_end, family,
                            coefficient, reverse, cd_start, cd_end,
                            tables):
    partial_rgb = _local_render_batched(
        raw, window_start, window_end, family, coefficient, reverse,
        cd_start, cd_end, tables)
    rgb = jax.lax.psum(partial_rgb, axis_name="chan")
    rgb = jnp.clip(jnp.round(rgb), 0.0, 255.0).astype(jnp.uint32)
    return rgb[0] | (rgb[1] << 8) | (rgb[2] << 16) | jnp.uint32(0xFF000000)


def render_step_sharded_batched(mesh: Mesh,
                                replicate_output: bool = False):
    """Mesh-sharded render with per-tile settings -> u32[B, H, W].

    ``replicate_output`` finishes with an all-gather over the data axis
    so EVERY process holds the full batch — required on multi-host
    meshes, where a data-sharded global array is not addressable from
    the serving process (the gather rides ICI/DCN once instead of N
    host-to-host fetches)."""
    if replicate_output:
        def fn(*args):
            out = _composite_step_batched(*args)
            return jax.lax.all_gather(out, "data", axis=0, tiled=True)
        out_specs = P()
    else:
        fn = _composite_step_batched
        out_specs = P("data")
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=_BATCHED_STEP_IN_SPECS,
        out_specs=out_specs,
    )
    return jax.jit(sharded)


def render_jpeg_step_sharded_batched(mesh: Mesh, quality: int = 85,
                                     cap: int | None = None,
                                     engine: str = "sparse",
                                     cap_words: int | None = None,
                                     replicate_output: bool = False):
    """Mesh-sharded serving step with per-tile settings: raw tiles ->
    JPEG wire buffers, data-sharded.  The per-request form of
    :func:`render_jpeg_step_sharded`.

    ``engine`` picks the wire format after the ``psum`` composite:
    ``"sparse"`` (18-bit coefficient entries, ``sparse_pack`` layout) or
    ``"huffman"`` (device fixed-table Huffman stream, ``huffman_pack``
    layout — ~3x fewer bytes over DCN/slow links)."""
    from ..ops.jpegenc import (default_sparse_cap, default_words_cap,
                               huffman_pack, huffman_spec_arrays,
                               packed_to_jpeg_coefficients, quant_tables,
                               sparse_pack)

    if engine not in ("sparse", "huffman"):
        raise ValueError(f"mesh jpeg engine must be 'sparse' or "
                         f"'huffman', got {engine!r}")
    qy_h, qc_h = (np.asarray(t, np.int32) for t in quant_tables(quality))
    spec_h = huffman_spec_arrays() if engine == "huffman" else None

    def step(*args):
        packed = _composite_step_batched(*args)
        H, W = packed.shape[-2:]
        local_cap = cap if cap is not None else default_sparse_cap(H, W)
        y, cb, cr = packed_to_jpeg_coefficients(
            packed, jnp.asarray(qy_h), jnp.asarray(qc_h))
        if engine == "huffman":
            local_words = (cap_words if cap_words is not None
                           else default_words_cap(H, W))
            bufs = huffman_pack(
                y, cb, cr, local_cap, local_words,
                *(jnp.asarray(a) for a in spec_h),
                h16=H // 16, w16=W // 16)
        else:
            bufs = sparse_pack(y, cb, cr, local_cap)
        if replicate_output:
            # Multi-host: every process needs the full wire buffers
            # (both to serve and to agree on overflow verdicts without
            # a host collective).
            bufs = jax.lax.all_gather(bufs, "data", axis=0, tiled=True)
        return bufs

    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=_BATCHED_STEP_IN_SPECS,
        out_specs=P() if replicate_output else P("data"),
    )
    return jax.jit(sharded)


def shard_batch_batched(mesh: Mesh, raw, stacked: dict):
    """Device-put a batch with per-tile stacked settings onto the mesh.

    ``stacked`` holds [B, C] settings arrays and [B, C, ...] tables (the
    ``server.batcher`` group form).  Returns the argument tuple for the
    batched sharded steps."""
    put = jax.device_put
    bc = NamedSharding(mesh, P("data", "chan"))
    rep = NamedSharding(mesh, P())
    return (
        put(raw, bc),
        put(stacked["window_start"], bc),
        put(stacked["window_end"], bc),
        put(stacked["family"], bc),
        put(stacked["coefficient"], bc),
        put(stacked["reverse"], bc),
        put(np.int32(stacked["cd_start"]), rep),
        put(np.int32(stacked["cd_end"]), rep),
        put(stacked["tables"], bc),
    )


def shard_batch(mesh: Mesh, raw, settings):
    """Device-put a host batch + packed settings onto the mesh layout.

    ``settings`` is the dict from ``ops.render.pack_settings`` (with a
    possible channel pad so C divides the chan axis).
    """
    put = jax.device_put
    # Scalars are device_put with a replicated sharding over *this* mesh
    # rather than built with ``jnp.int32`` — an eager jnp constant would be
    # committed to the default platform, which need not be the mesh's.
    rep = NamedSharding(mesh, P())
    args = (
        put(raw, NamedSharding(mesh, P("data", "chan"))),
        put(settings["window_start"], NamedSharding(mesh, P("chan"))),
        put(settings["window_end"], NamedSharding(mesh, P("chan"))),
        put(settings["family"], NamedSharding(mesh, P("chan"))),
        put(settings["coefficient"], NamedSharding(mesh, P("chan"))),
        put(settings["reverse"], NamedSharding(mesh, P("chan"))),
        put(np.int32(settings["cd_start"]), rep),
        put(np.int32(settings["cd_end"]), rep),
        put(settings["tables"], NamedSharding(mesh, P("chan"))),
    )
    return args
