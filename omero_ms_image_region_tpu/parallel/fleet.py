"""Data-parallel device fleet: sharded serving across N device sets.

The reference scales horizontally by clustering verticle JVMs over
Hazelcast (``-cluster``): every node consumes the same event-bus
address and the cluster's consistent view decides who serves what.
The TPU-native form here is a :class:`FleetRouter` in the frontend: N
members — in-process device lanes (``--role combined``) or render
sidecars each owning a device set (``--role frontend`` +
``fleet.sockets``) — each own a *shard* of the hot HBM state.

Routing is a consistent hash of the request's **plane identity**
(:func:`plane_route_key`: image, z, t, resolution, tile/region — the
source bytes' address, never the rendering settings), so every render
of one plane lands on the one member whose ``DeviceRawCache`` holds
it: the fleet's HBM tier *shards* instead of duplicating, and
staged-once semantics ride the existing digest probes unchanged.
Re-window/re-color traffic for a hot plane always finds its bytes
already resident on its owner.

Load skew is handled by **bounded work stealing**: each member drains
its own queue through ``lane_width`` worker lanes, and an idle lane
may steal the oldest queued request from the most-backlogged member —
the stolen render runs from source bytes *without adopting cache
ownership* (``adopt_cache=False`` rides the wire as the ``adopt``
header), so stealing never fragments the shard map.

Membership is decided by the PR-3 breaker/supervisor machinery: a
member whose connection died through every policy retry (or whose
breaker is open) is marked down, its shard fails over **hash-ring-
next** (the classic consistent-hash contract: only ~1/N of the key
space moves), and its queued work is re-assigned.  The supervisor
brings the process back; the ring re-adopts it after the cooldown.

Fleet-aware single-flight and admission live *above* the router
(:class:`FleetImageHandler`): identical renders coalesce once
fleet-wide, and shedding sees the fleet's total depth.  The lockstep
``MeshRenderer`` stays behind the router for full-plane/z-projection
jobs — those pin to the first member (the mesh lane) and are never
stolen.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import bisect
import logging
import math
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


# ------------------------------------------------------------ hash ring

class HashRing:
    """Consistent hash ring with virtual nodes.

    Deterministic across processes and runs (BLAKE2b over the literal
    strings — never Python's salted ``hash()``), so a frontend fleet
    restart can never silently reshuffle which member owns which
    plane.  ``replicas`` virtual nodes per member keep the key-space
    split near-uniform; member join/leave moves only the keys whose
    ring arcs changed hands (~1/N of the space — pinned by the remap
    bound test in tier-1).
    """

    def __init__(self, members: Sequence[str], replicas: int = 64,
                 seed: str = ""):
        if not members:
            raise ValueError("hash ring needs at least one member")
        if len(set(members)) != len(members):
            raise ValueError("duplicate fleet member names")
        self.replicas = max(1, int(replicas))
        # Federation namespace (``federation.ring-seed``): folded into
        # every point hash so two federations sharing member NAMES can
        # never silently share a key space.  The empty default keeps
        # every pre-federation ring's golden assignments bit-exact.
        self.seed = str(seed)
        self.members: Tuple[str, ...] = tuple(members)
        self._points: List[int] = []
        self._owners: List[str] = []
        prefix = f"{self.seed}|" if self.seed else ""
        points = []
        for name in self.members:
            for v in range(self.replicas):
                points.append((self._point(f"{prefix}{name}#{v}"),
                               name))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [o for _, o in points]

    @staticmethod
    def _point(s: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(s.encode(), digest_size=8).digest(),
            "big")

    def _key_point(self, key: str) -> int:
        return self._point(f"{self.seed}|{key}" if self.seed else key)

    def chain(self, key: str) -> List[str]:
        """Members in ring order from ``key``'s arc, deduplicated: the
        first entry owns the key; the rest are its failover order
        (hash-ring-next), so one member's death moves each of its keys
        to a *deterministic* successor."""
        if not self._points:
            return []
        i = bisect.bisect(self._points, self._key_point(key)) \
            % len(self._points)
        seen = []
        for step in range(len(self._points)):
            owner = self._owners[(i + step) % len(self._points)]
            if owner not in seen:
                seen.append(owner)
                if len(seen) == len(self.members):
                    break
        return seen

    def member(self, key: str) -> str:
        """The key's owning member."""
        return self.chain(key)[0]


def plane_route_key(ctx) -> str:
    """The request's source-plane identity — everything that pins WHICH
    bytes are read, nothing the rendering settings touch.  All renders
    of one plane (re-window, re-color, LUT flips, format changes) hash
    to the same member, which is exactly what makes the fleet's HBM
    tier shard instead of duplicate."""
    tile = (ctx.tile.x, ctx.tile.y, ctx.tile.width, ctx.tile.height) \
        if ctx.tile is not None else None
    region = (ctx.region.x, ctx.region.y, ctx.region.width,
              ctx.region.height) if ctx.region is not None else None
    parts = (ctx.image_id, ctx.z, ctx.t, ctx.resolution, tile, region)
    return hashlib.blake2b(repr(parts).encode(),
                           digest_size=16).hexdigest()


def _entry_key(entry: dict) -> tuple:
    """Canonical identity of a restageable manifest entry (the region
    key as a hashable tuple) — matches exported bytes back to their
    hint entries across JSON round-trips (lists vs tuples)."""
    try:
        image_id, z, t, level, region, channels = entry["key"]
        return (int(image_id), int(z), int(t), int(level),
                tuple(int(v) for v in region),
                tuple(int(c) for c in channels))
    except (KeyError, TypeError, ValueError):
        return (id(entry),)


# ------------------------------------------------------------- hot keys

class HeatTracker:
    """Decayed per-route request-rate tracker (the hot-key detector).

    Each :func:`plane_route_key` observation adds one unit of heat;
    heat decays exponentially with time constant ``decay_s`` (lazy —
    applied on read, no timer).  Under a sustained rate of ``r``
    requests/s a route's heat converges to ``r * decay_s``, so
    ``threshold`` reads as "this many seconds' worth of one member's
    demand concentrated on one plane".

    Cardinality is bounded at ``top_k`` routes: a new route may enter
    a full table only by evicting a COLDER one (its decayed heat below
    the newcomer's single unit), so the hot set can never be churned
    out by a long tail of one-hit routes — the same guarantee
    space-saving top-K sketches give, in the degenerate form that
    suffices when ``top_k`` is orders of magnitude above the number of
    simultaneously-hot planes.

    ``clock`` is injectable for deterministic trajectory tests.
    """

    def __init__(self, threshold: float, decay_s: float,
                 top_k: int = 128, clock=time.monotonic):
        self.threshold = float(threshold)
        self.decay_s = max(1e-3, float(decay_s))
        self.top_k = max(1, int(top_k))
        self.clock = clock
        self._heat: Dict[str, Tuple[float, float]] = {}

    def _decayed(self, heat: float, last: float, now: float) -> float:
        if now <= last:
            return heat
        return heat * math.exp(-(now - last) / self.decay_s)

    def observe(self, route: str) -> float:
        """Count one request for ``route``; returns its decayed heat
        including this observation."""
        now = self.clock()
        held = self._heat.get(route)
        if held is None:
            if len(self._heat) >= self.top_k:
                coldest = min(
                    self._heat,
                    key=lambda r: self._decayed(*self._heat[r], now))
                if self._decayed(*self._heat[coldest], now) > 1.0:
                    # Table full of hotter routes: the observation is
                    # real but untracked — bounded cardinality wins.
                    return 1.0
                del self._heat[coldest]
            heat = 1.0
        else:
            heat = self._decayed(held[0], held[1], now) + 1.0
        self._heat[route] = (heat, now)
        return heat

    def heat(self, route: str) -> float:
        """Decayed heat without counting a request (sweeps, explain)."""
        held = self._heat.get(route)
        if held is None:
            return 0.0
        return self._decayed(held[0], held[1], self.clock())

    def tracked(self) -> int:
        return len(self._heat)

    def forget(self, route: str) -> None:
        self._heat.pop(route, None)


# -------------------------------------------------------------- members

class MemberDownError(ConnectionError):
    """A member's fast-fail refusal while it is ALREADY marked down.

    The lane must not treat this as a fresh death observation:
    re-marking on every routed request would push ``_down_until``
    forward each time, so any shard seeing >= 1 request per cooldown
    window would keep its member down forever — after the outage
    healed.  Only a failure of a render the member actually accepted
    (re-)marks it down."""


class LocalMember:
    """An in-process device lane: its own renderer + HBM cache behind
    an ``ImageRegionHandler`` (host-side services — pixel stores, byte
    caches, metadata, ACL memo — are shared with the other members).

    Down state is a COOLDOWN, exactly like :class:`RemoteMember`'s: the
    shared host-side services mean one transient outage (a metadata DB
    or network pixel-store hiccup surfacing as ``ConnectionError``) can
    mark every member down within a single failover chain, and a latch
    with no re-admission path would leave the whole fleet dead until a
    process restart.  A served render — or the cooldown expiring —
    re-admits the member.

    ``byte_cache_prechecked`` marks that the fleet handler above the
    router already ran the byte-cache probe and the caller's ACL gate
    for every dispatched ctx (``build_local_members`` sets it — the
    combined role always fronts members with ``FleetImageHandler``),
    so the member's own handler skips its duplicate byte-cache get.

    ``services`` is kept for shard accounting (``raw_cache``) and
    teardown; ``handler`` is duck-typed so tests can wrap it with
    deterministic failure injectors."""

    remote = False

    def __init__(self, name: str, handler, services=None,
                 down_cooldown_s: float = 5.0,
                 byte_cache_prechecked: bool = False,
                 devices: Optional[Sequence] = None):
        self.name = name
        self.handler = handler
        self.services = services
        self.down_cooldown_s = down_cooldown_s
        self.byte_cache_prechecked = byte_cache_prechecked
        # Per-member device set (cross-host federation: the combined
        # role owns REAL devices per member when the host has several
        # — ``federation.partition_local_devices``).  The first device
        # is the member's dispatch pin (``services.pin_device``); an
        # empty set means the process default device, the pre-pinning
        # behavior.
        self.devices: Tuple = tuple(devices or ())
        self._down_until = 0.0
        # Rolling-drain state (router.drain_member): a DRAINING member
        # finishes its in-flight work but accepts no new routes — on
        # purpose, distinct from down (a drain is not a death and must
        # not look like one).  ``drain_intent`` says WHO drained it:
        # "operator" (/admin/drain — the rolling-restart posture the
        # drain.fail-readyz flag surfaces to LBs) or "autoscale" (a
        # routine scale-down that must NOT read as the instance
        # leaving rotation).
        self.draining = False
        self.drain_intent: Optional[str] = None

    @property
    def healthy(self) -> bool:
        return time.monotonic() >= self._down_until

    def mark_down(self) -> None:
        self._down_until = time.monotonic() + self.down_cooldown_s

    def revive(self) -> None:
        self._down_until = 0.0

    async def render(self, ctx, adopt_cache: bool = True) -> bytes:
        if not self.healthy:
            raise MemberDownError(
                f"fleet member {self.name} is down")
        if self.byte_cache_prechecked:
            data = await self.handler.render_image_region(
                ctx, adopt_cache=adopt_cache, skip_byte_cache=True)
        else:
            data = await self.handler.render_image_region(
                ctx, adopt_cache=adopt_cache)
        self.revive()          # a served call re-admits the member
        return data

    def queue_depth(self) -> int:
        renderer = getattr(self.services, "renderer", None)
        return (renderer.queue_depth()
                if hasattr(renderer, "queue_depth") else 0)

    def resident_digests(self):
        cache = getattr(self.services, "raw_cache", None)
        if cache is None or not hasattr(cache, "resident_digests"):
            return set()
        return cache.resident_digests()

    def resident_planes(self) -> int:
        cache = getattr(self.services, "raw_cache", None)
        return len(cache) if cache is not None else 0

    async def shard_manifest(self, limit: int = 0) -> List[dict]:
        """This member's HBM shard as restageable region entries —
        the drain handoff's pre-stage hint list (MRU first, so a
        bounded pre-stage warms the hottest planes)."""
        cache = getattr(self.services, "raw_cache", None)
        if cache is None or not hasattr(cache, "snapshot_entries"):
            return []
        return cache.snapshot_entries(limit)

    async def route_manifest(self, route: str) -> List[dict]:
        """ONE route's restageable entries (hot-plane replication:
        the promotion stager hands exactly the hot plane's shard slice
        to its replicas, not the member's whole manifest)."""
        cache = getattr(self.services, "raw_cache", None)
        if cache is None:
            return []
        if hasattr(cache, "entries_for_route"):
            return cache.entries_for_route(route)
        if not hasattr(cache, "snapshot_entries"):
            return []
        return [e for e in cache.snapshot_entries(0)
                if e.get("route") == route]

    # ---- fleet-global byte tier (combined role shares ONE byte-cache
    # chain across members, so these exist for API symmetry and tests;
    # the router only crosses the wire for REMOTE peers).  ``tier``
    # picks the byte namespace: "region" (rendered tiles, the PR 11
    # identity) or "mask" (ShapeMask PNGs under their cache_key).

    def _byte_stack(self, tier: str = "region"):
        caches = getattr(self.services, "caches", None)
        stack = getattr(caches,
                        "shape_mask" if tier == "mask"
                        else "image_region", None)
        return stack if (stack is not None
                         and getattr(stack, "enabled", False)) else None

    async def byte_probe(self, keys: List[str],
                         tier: str = "region") -> List[bool]:
        stack = self._byte_stack(tier)
        if stack is None:
            return [False] * len(keys)
        return [(await stack.get(str(k))) is not None for k in keys]

    async def byte_fetch(self, key: str, image_id=None,
                         session=None, tier: str = "region",
                         obj: str = "Image") -> Optional[bytes]:
        stack = self._byte_stack(tier)
        if stack is None:
            return None
        data = await stack.get(str(key))
        if data is None or image_id is None:
            return data
        from ..server.handler import check_can_read
        if not await check_can_read(self.services, obj,
                                    int(image_id), session):
            return None
        return data

    async def byte_put(self, key: str, value: bytes,
                       tier: str = "region") -> bool:
        stack = self._byte_stack(tier)
        if stack is None:
            return False
        await stack.set(str(key), bytes(value))
        return True

    async def explain_residency(self, key: str, route: str) -> dict:
        """Dry-run residency report for the explain plane: does this
        member hold the rendered bytes (and in which tier) and/or the
        source plane in HBM?  Read-only — no render, no staging.  ONE
        shared implementation (``server.explain.residency_doc``) so
        combined, fleet-local and remote members cannot drift."""
        from ..server.explain import residency_doc
        return await residency_doc(
            self._byte_stack(),
            getattr(self.services, "raw_cache", None), key, route)

    async def prestage_manifest(self, entries: List[dict]) -> int:
        """Stage a handed-over shard manifest into THIS member's HBM
        (drain handoff, successor side) through the existing staging
        path — digest-deduped, so re-handing an already-warm entry is
        a probe hit, never a duplicate buffer."""
        from ..services.warmstate import restage_plane_entry
        cache = getattr(self.services, "raw_cache", None)
        pixels = getattr(self.services, "pixels_service", None)
        if cache is None or pixels is None:
            return 0

        def stage_all() -> int:
            staged = 0
            for entry in entries:
                try:
                    if restage_plane_entry(cache, pixels, entry):
                        staged += 1
                except Exception:
                    continue    # best-effort: a bad entry is a cold
                    # miss later, never a failed drain
            return staged

        return await asyncio.to_thread(stage_all)

    async def shard_export(self, limit: int = 0) -> List[dict]:
        """This member's HBM shard as entries WITH the plane bytes —
        the cross-host drain handoff's payload (``shard_transfer``):
        a successor on ANOTHER host cannot re-read this host's pixel
        store, so the warm bytes themselves ride the wire.  MRU-first
        like :meth:`shard_manifest`; entries whose buffer is already
        gone (eviction race) are skipped."""
        import numpy as np
        from ..io.devicecache import region_key
        cache = getattr(self.services, "raw_cache", None)
        if cache is None or not hasattr(cache, "snapshot_entries"):
            return []
        entries = cache.snapshot_entries(limit)

        def export() -> List[dict]:
            out = []
            for entry in entries:
                try:
                    image_id, z, t, level, region, channels = \
                        entry["key"]
                    key = region_key(
                        int(image_id), int(z), int(t), int(level),
                        tuple(int(v) for v in region),
                        tuple(int(c) for c in channels))
                except (KeyError, TypeError, ValueError):
                    continue
                arr = cache.get(key)
                if arr is None:
                    continue
                host = np.asarray(arr)
                out.append({**entry, "dtype": str(host.dtype),
                            "shape": list(host.shape),
                            "bytes": host.tobytes()})
            return out

        return await asyncio.to_thread(export)

    async def shard_transfer(self, entries: List[dict]) -> int:
        """Stage handed-over plane BYTES into this member's HBM
        (cross-host handoff, successor side — the in-process mirror of
        the ``shard_transfer`` wire op, so the router's handoff code
        is member-kind-agnostic).  Digest-deduped like every staging
        path: re-handing a resident plane aliases, never duplicates."""
        import numpy as np
        from ..io.devicecache import region_key
        cache = getattr(self.services, "raw_cache", None)
        if cache is None:
            return 0

        def stage_all() -> int:
            staged = 0
            for entry in entries:
                try:
                    image_id, z, t, level, region, channels = \
                        entry["key"]
                    key = region_key(
                        int(image_id), int(z), int(t), int(level),
                        tuple(int(v) for v in region),
                        tuple(int(c) for c in channels))
                    arr = np.frombuffer(
                        entry["bytes"], dtype=entry["dtype"]).reshape(
                        tuple(entry["shape"]))
                except (KeyError, TypeError, ValueError):
                    continue
                cache.get_or_load(key, lambda a=arr: a,
                                  digest=entry.get("digest"),
                                  route_key=entry.get("route"))
                staged += 1
            return staged

        return await asyncio.to_thread(stage_all)


class RemoteMember:
    """A render sidecar owning a device set, reached over the wire.

    Health is the PR-3 machinery's verdict: the client's circuit
    breaker open, or a connection death observed by a lane worker,
    marks the member down for ``down_cooldown_s`` — its shard fails
    over hash-ring-next while the supervisor restarts the process, and
    the ring re-adopts it at the next successful call after cooldown.
    """

    remote = True

    def __init__(self, name: str, client, down_cooldown_s: float = 5.0):
        self.name = name
        self.client = client
        # Stitching dimension: spans the client grafts from this
        # member's process carry its fleet name, so a stolen or
        # failed-over render reads as a multi-member tree.
        try:
            client.member_label = name
        except AttributeError:      # duck-typed test clients
            pass
        self.down_cooldown_s = down_cooldown_s
        self._down_until = 0.0
        self.draining = False
        self.drain_intent: Optional[str] = None

    @property
    def healthy(self) -> bool:
        breaker = getattr(self.client, "breaker", None)
        if breaker is not None and breaker.state == breaker.OPEN:
            return False
        return time.monotonic() >= self._down_until

    def mark_down(self) -> None:
        self._down_until = time.monotonic() + self.down_cooldown_s

    def revive(self) -> None:
        self._down_until = 0.0

    def _fed_span(self, kind: str, t0: float, t1: float,
                  **meta) -> None:
        """One ``fed.hop`` span per cross-HOST wire exchange: {host,
        member, kind} names where the hop landed and why.  Gated on
        ``federation.remote_host_of`` — same-host members (and
        un-federated fleets) record nothing — and ``record_span`` is
        a no-op outside a trace context, so production gossip/drain
        loops pay nothing for it."""
        from . import federation
        from ..utils import telemetry
        host = federation.remote_host_of(self.name)
        if not host:
            return
        telemetry.record_span("fed.hop", t0, (t1 - t0) * 1000.0,
                              host=host, member=self.name,
                              kind=kind, **meta)

    async def render(self, ctx, adopt_cache: bool = True) -> bytes:
        from ..server.sidecar import _map_response
        from ..utils import provenance
        extra = None if adopt_cache else {"adopt": 0}
        resp_header, payload = await self.client.call_full(
            "image", ctx.to_json(), extra=extra)
        self.revive()          # a served call re-admits the member
        provenance.merge_wire(ctx, resp_header.get("prov"))
        if resp_header.get("quality_capped"):
            # The sidecar's brownout ladder capped this render's JPEG
            # quality: mirror the mark onto the FRONTEND's ctx so the
            # byte-tier write-backs here (peer put-back, combined byte
            # cache) keep the PR 9 contract — degraded bytes are never
            # stored under the full-quality key.
            ctx._pressure_quality_capped = True
        return _map_response(resp_header, payload)

    # ---- fleet-global byte tier (the peer transport: the router's
    # probe short-circuit and the thief write-back ride these three
    # idempotent-where-safe wire ops; every failure degrades to None/
    # False — the peer tier may only ever REMOVE work).

    async def byte_probe(self, keys: List[str],
                         tier: str = "region") -> List[bool]:
        import json as _json
        try:
            extra = {"keys": [str(k) for k in keys]}
            if tier != "region":
                extra["tier"] = tier
            status, body = await self.client.call(
                "byte_probe", {}, extra=extra)
            if status != 200 or not body:
                return [False] * len(keys)
            doc = _json.loads(bytes(body).decode())
            present = [bool(p) for p in (doc.get("present") or ())]
            present += [False] * (len(keys) - len(present))
            return present[:len(keys)]
        except Exception:
            return [False] * len(keys)

    async def byte_fetch(self, key: str, image_id=None,
                         session=None, tier: str = "region",
                         obj: str = "Image") -> Optional[bytes]:
        """None = authority MISS (or ACL refusal) — an honest 404;
        transport failures RAISE so the caller can count a fallback
        (a miss means render, a failure means the peer tier is
        degraded — the router's telemetry keeps them distinct)."""
        extra = {"key": str(key)}
        if tier != "region":
            # Tier rides the wire only when non-default: a legacy
            # sidecar ignoring it would serve the WRONG namespace, but
            # mask keys ("<shape>:<color>...") never collide with
            # render identity keys, so the worst case is a miss.
            extra["tier"] = tier
        if image_id is not None:
            # The serving sidecar runs its OWN ACL gate for this
            # session before any byte leaves it — the same
            # contract as the `image` op.
            extra["image_id"] = int(image_id)
            extra["session"] = session
            if obj != "Image":
                extra["obj"] = obj
        t0 = time.perf_counter()
        resp_header, payload = await self.client.call_full(
            "byte_fetch", {}, extra=extra)
        self._fed_span("byte_fetch", t0, time.perf_counter(),
                       hit=int(resp_header.get("status") == 200
                               and payload is not None))
        if resp_header.get("status") != 200 or payload is None:
            return None
        return bytes(payload)

    async def byte_put(self, key: str, value: bytes,
                       tier: str = "region") -> bool:
        import hashlib as _hashlib
        try:
            digest = _hashlib.blake2b(bytes(value),
                                      digest_size=16).hexdigest()
            extra = {"key": str(key), "digest": digest}
            if tier != "region":
                extra["tier"] = tier
            t0 = time.perf_counter()
            status, _body = await self.client.call(
                "byte_put", {}, body=bytes(value),
                extra=extra)
            self._fed_span("byte_put", t0, time.perf_counter(),
                           bytes=len(value))
            return status == 200
        except Exception:
            return False

    def queue_depth(self) -> int:
        return 0               # the sidecar's own gauge carries this

    def resident_digests(self):
        return set()

    def resident_planes(self) -> int:
        return 0

    async def shard_manifest(self, limit: int = 0) -> List[dict]:
        """The sidecar's HBM shard over the wire (``shard_manifest``
        op); unreachable/legacy sidecars answer an empty hint list —
        the drain proceeds, the successor just warms lazily."""
        import json as _json
        try:
            status, body = await self.client.call(
                "shard_manifest", {}, extra={"limit": limit})
            if status != 200 or not body:
                return []
            return list(_json.loads(bytes(body).decode())
                        .get("entries") or ())
        except Exception:
            return []

    async def explain_residency(self, key: str, route: str) -> dict:
        """Residency report over the read-only ``explain`` wire op;
        unreachable/legacy sidecars answer an honest unknown."""
        import json as _json
        try:
            status, body = await self.client.call(
                "explain", {}, extra={"key": key, "route": route})
            if status != 200 or not body:
                return {"error": f"explain op status {status}"}
            return dict(_json.loads(bytes(body).decode()))
        except Exception as e:
            return {"error": str(e)[:120]}

    async def prestage_manifest(self, entries: List[dict]) -> int:
        """Hand the drained shard's hint list to this sidecar
        (``prestage`` op): it re-reads the regions from its own pixel
        store and stages them into its HBM shard."""
        import json as _json
        try:
            t0 = time.perf_counter()
            status, body = await self.client.call(
                "prestage", {}, extra={"entries": entries})
            self._fed_span("remote_prestage", t0, time.perf_counter(),
                           entries=len(entries))
            if status != 200 or not body:
                return 0
            return int(_json.loads(bytes(body).decode())
                       .get("staged", 0))
        except Exception:
            return 0

    # ---- cross-host federation (parallel.federation): manifest
    # agreement at join, membership gossip, and warm shard transfer —
    # the three new v3-wire ops.  manifest_hello / member_gossip are
    # idempotent reads (retried); shard_transfer ships state and is
    # never blind-retried, exactly the plane_put contract.

    async def manifest_hello(self, doc: dict,
                             probe_keys: Optional[List[str]] = None
                             ) -> Optional[dict]:
        """Exchange fleet manifests with this member's process: send
        ours, learn whether the peer's agrees (digest match), and —
        when ``probe_keys`` ride along — the peer's ring owner for
        each, so golden assignments are verified AGAINST THE PEER'S
        OWN MATH, not our copy of it.  None = unreachable/legacy."""
        import json as _json

        from . import federation
        extra = {"manifest": doc,
                 # The sender's host identity: an inbound hello feeds
                 # the receiver's quorum tracker (heard-from proof).
                 "from_host": federation.self_host()}
        if probe_keys:
            extra["probe_keys"] = list(probe_keys)
        try:
            status, body = await self.client.call(
                "manifest_hello", {}, extra=extra)
            if status != 200 or not body:
                return None
            return dict(_json.loads(bytes(body).decode()))
        except Exception:
            return None

    async def member_gossip(self, view: dict) -> Optional[dict]:
        """Swap membership views (name -> health/draining, versioned
        ``(incarnation, seq)``) and the manifest (version, digest) —
        the rack-scale liveness channel that propagates drains and
        deaths between hosts faster than per-request failures would."""
        import json as _json

        from . import federation
        try:
            status, body = await self.client.call(
                "member_gossip", {},
                extra={"view": view,
                       "from_host": federation.self_host()})
            if status != 200 or not body:
                return None
            return dict(_json.loads(bytes(body).decode()))
        except Exception:
            return None

    async def epoch_propose(self, doc: dict) -> Optional[dict]:
        """Two-phase epoch roll, phase 1: offer the next manifest to
        this member's process (it records PENDING and acks — nothing
        activates).  Idempotent by contract, so the retry policy may
        re-issue it.  None = unreachable."""
        import json as _json

        from . import federation
        try:
            status, body = await self.client.call(
                "epoch_propose", {},
                extra={"manifest": doc,
                       "from_host": federation.self_host()})
            if status != 200 or not body:
                return None
            return dict(_json.loads(bytes(body).decode()))
        except Exception:
            return None

    async def epoch_commit(self, doc: dict,
                           digest: str = "") -> Optional[dict]:
        """Two-phase epoch roll, phase 2: commit the agreed manifest
        — the receiver digest-verifies, activates, and swaps its ring.
        Idempotent on the receiver (already-active answers ack), so
        safe to re-push (the gossip loop's anti-entropy catch-up does
        exactly that)."""
        import json as _json

        from . import federation
        extra = {"manifest": doc,
                 "from_host": federation.self_host()}
        if digest:
            extra["digest"] = digest
        try:
            status, body = await self.client.call(
                "epoch_commit", {}, extra=extra)
            if status != 200 or not body:
                return None
            return dict(_json.loads(bytes(body).decode()))
        except Exception:
            return None

    async def shard_transfer(self, entries: List[dict]) -> int:
        """Ship warm plane BYTES into this member's HBM over the wire
        (cross-host drain handoff): one frame per plane — the body is
        the raw buffer (shm-ring eligible), the header carries the
        restage identity (key/digest/route/dtype/shape).  Best-effort
        per entry; a failed ship is a cold miss later, never a failed
        drain."""
        import json as _json
        from . import federation
        staged = 0
        for entry in entries:
            payload = entry.get("bytes")
            if payload is None:
                continue
            meta = {k: entry.get(k) for k in
                    ("key", "digest", "route", "dtype", "shape")}
            try:
                t_send = time.perf_counter()
                status, body = await self.client.call(
                    "shard_transfer", {}, body=bytes(payload),
                    extra={"entry": meta})
                t_recv = time.perf_counter()
                doc = (_json.loads(bytes(body).decode())
                       if status == 200 and body else {})
                self._fed_span("shard_transfer", t_send, t_recv,
                               bytes=len(payload),
                               staged=int(bool(doc.get("staged"))))
                if doc.get("staged"):
                    staged += 1
                    # Counted HERE, per ship that actually landed —
                    # the bytes of failed entries never reach the
                    # transfer gauge.
                    from ..utils import telemetry
                    telemetry.FEDERATION.count_transfer(len(payload))
                    # Remote-side graft: the serving sidecar anchors
                    # its stage work (t_anchor on ITS perf clock, ms)
                    # and the per-host offset from the hello/gossip
                    # exchanges maps it into OUR timeline, clamped
                    # into this call's [send, recv] bracket.  Peers
                    # answering without the anchor fields (older
                    # builds, no derived offset yet) degrade to the
                    # wrapper span alone — never an error.
                    host = federation.remote_host_of(self.name)
                    anchored = federation.anchor_remote_time(
                        doc.get("host") or host, doc.get("t_anchor"),
                        (t_send, t_recv)) if host else None
                    if anchored is not None:
                        dur = max(0.0, min(
                            float(doc.get("ms") or 0.0),
                            (t_recv - anchored) * 1000.0))
                        telemetry.record_span(
                            "fed.hop", anchored, dur,
                            host=doc.get("host") or host,
                            member=self.name, kind="stage")
            except Exception:
                continue
        return staged


# --------------------------------------------------------------- router

class _Work:
    __slots__ = ("ctx", "future", "owner", "stolen", "hops",
                 "deadline", "t_enqueue", "bulk", "trace_ids",
                 "route_key")

    def __init__(self, ctx, future, owner: str, deadline):
        self.ctx = ctx
        self.future = future
        self.owner = owner
        self.stolen = False
        self.hops = 0
        self.deadline = deadline
        self.t_enqueue = time.perf_counter()
        # The requester's trace id(s), captured at enqueue: the lane
        # tasks run OUTSIDE any request context (they must — a lane is
        # long-lived), so every hop span and the member render itself
        # re-adopt these explicitly.  Without this, every lane span
        # would attach to whichever request's context happened to
        # spawn the lanes (the classic contextvars-snapshot leak).
        from ..utils import telemetry
        self.trace_ids = telemetry.current_trace_ids()
        # QoS class, computed ONCE at enqueue: the same
        # ``pressure.is_bulk`` verdict the ladder's shed_bulk step and
        # the mesh-lane pin use — the three must never drift apart.
        from ..server.pressure import is_bulk
        self.bulk = is_bulk(ctx)
        # Routed plane identity (short hash) for hop-span forensics;
        # pinned/bulk work carries the literal "pinned".  Only hashed
        # when a trace is listening (pay-for-what-you-use: untraced
        # internal dispatches skip the digest).
        self.route_key = ("pinned" if self.bulk
                          else plane_route_key(ctx)[:12]
                          if self.trace_ids else "")


class _MemberQueue:
    """One member's pending work as a weighted two-class queue.

    ``qos_weight`` 0 is plain FIFO (the pre-QoS behavior, bit for
    bit).  With weight w > 0, while BOTH classes wait, up to w
    interactive units pop per bulk unit — interactive tiles jump a
    bulk-export backlog instead of convoying behind it, and bulk still
    cannot starve (after the quota one bulk unit always pops).
    Arrival order is preserved WITHIN each class.
    """

    __slots__ = ("_items", "qos_weight", "_ic_run", "_ic")

    def __init__(self, qos_weight: int = 0):
        self._items: Deque[_Work] = collections.deque()
        self.qos_weight = max(0, int(qos_weight))
        self._ic_run = 0
        # Interactive-unit count, maintained O(1) on every mutation:
        # idle lanes poll steal_depth() on every wake evaluation, and
        # a deep bulk backlog must not turn that into a deque walk.
        self._ic = 0

    def append(self, work: _Work) -> None:
        self._items.append(work)
        if not work.bulk:
            self._ic += 1

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def __contains__(self, work) -> bool:
        # O(n) deque scan: tests/diagnostics only — never call this
        # per-dispatch (the _ic counter exists precisely so the hot
        # path needs no queue walks).
        return work in self._items

    def _first_index(self, bulk: bool) -> Optional[int]:
        for i, w in enumerate(self._items):
            if w.bulk == bulk:
                return i
        return None

    def _on_pop(self, work: _Work) -> _Work:
        if not work.bulk:
            self._ic -= 1
        return work

    def popleft(self) -> _Work:
        """The next unit under the weighted-dequeue policy."""
        from ..utils import telemetry
        items = self._items
        if self.qos_weight <= 0:
            return self._on_pop(items.popleft())
        if self._ic == 0 or self._ic == len(items):
            # One class present: plain FIFO, quota resets (the mix is
            # what the quota meters).  O(1) — the scans below only
            # run while the classes are actually interleaved.
            self._ic_run = 0
            work = self._on_pop(items.popleft())
        elif self._ic_run >= self.qos_weight:
            # Quota spent: one bulk unit pops — no starvation.
            i_bulk = self._first_index(True)
            work = self._on_pop(items[i_bulk])
            del items[i_bulk]
            self._ic_run = 0
        else:
            i_ic = self._first_index(False)
            work = self._on_pop(items[i_ic])
            del items[i_ic]
            self._ic_run += 1
            if i_ic > 0:
                # Mixed queue and the first interactive unit was not
                # at the head: it overtook a bulk unit that arrived
                # first — the jump the QoS tier exists for.
                telemetry.QOS.count_jump()
        telemetry.QOS.count_dequeued("bulk" if work.bulk
                                     else "interactive")
        return work

    def pop_raw(self) -> _Work:
        """Arrival-order pop, policy-free (reassign/fail/close paths)."""
        return self._on_pop(self._items.popleft())

    def steal_depth(self) -> int:
        """Stealable units: interactive only — bulk work is pinned to
        the mesh lane by the same is_bulk verdict, never stolen."""
        return self._ic

    def steal_pop(self) -> Optional[_Work]:
        """The OLDEST stealable (interactive) unit, or None."""
        if self._ic == 0:
            return None
        i = self._first_index(False)
        work = self._on_pop(self._items[i])
        del self._items[i]
        return work


class FleetRouter:
    """Consistent-hash request router over N fleet members.

    Per-member queues drained by ``lane_width`` asyncio lanes each (a
    lane models one device lane of that member's set); an idle lane
    steals the oldest request from the most-backlogged peer once that
    backlog reaches ``steal_min_backlog`` — bounded, oldest-first, and
    cache-ownership-neutral (stolen renders carry
    ``adopt_cache=False``).  Member death (ConnectionError through the
    retry policy / breaker) marks the member down, re-assigns its
    queued work hash-ring-next and fails the dead call over the same
    way, so a mid-burst kill yields zero 5xx-without-shed.
    """

    def __init__(self, members: Sequence, lane_width: int = 2,
                 steal_min_backlog: int = 2, hash_replicas: int = 64,
                 failover: bool = True, qos_weight: int = 0,
                 peer_fetch: bool = True,
                 peer_timeout_s: float = 0.5,
                 ring_seed: str = "",
                 wire_handoff: bool = False,
                 hotkey=None):
        if not members:
            raise ValueError("fleet needs at least one member")
        if lane_width < 1:
            raise ValueError("fleet lane_width must be >= 1")
        self.members: Dict[str, object] = {m.name: m for m in members}
        if len(self.members) != len(members):
            raise ValueError("duplicate fleet member names")
        self.order: List[str] = [m.name for m in members]
        self.ring = HashRing(self.order, replicas=hash_replicas,
                             seed=ring_seed)
        # Cross-host drains (parallel.federation): when the draining
        # member is LOCAL and its successor is REMOTE, hand the warm
        # bytes themselves over the shard_transfer op — a successor on
        # another host cannot re-read this host's pixel store, so a
        # hint-list prestage would arrive cold.
        self.wire_handoff = bool(wire_handoff)
        self.lane_width = lane_width
        # 0 disables stealing entirely.
        self.steal_min_backlog = max(0, int(steal_min_backlog))
        self.failover = failover
        # Tiered QoS (config.qos): interactive units jump bulk
        # backlogs at this weight; 0 = plain FIFO (pre-QoS behavior).
        self.qos_weight = max(0, int(qos_weight))
        # The admission controller reads this as the fleet's service
        # parallelism (estimated wait = depth * EWMA / lanes).
        self.device_lanes = lane_width * len(members)
        self._queues: Dict[str, _MemberQueue] = {
            name: _MemberQueue(self.qos_weight)
            for name in self.order}
        self._inflight: Dict[str, int] = {n: 0 for n in self.order}
        # ONE wake event for all idle lanes: stealing means any lane
        # may be interested in any member's new work, and at fleet
        # scale (N <= ~16 members) a broadcast wake is cheaper than a
        # correct per-member + steal-candidate wake dance.
        self._wake: Optional[asyncio.Event] = None
        self._lanes: List[asyncio.Task] = []
        self._closed = False
        # Fleet-global byte tier (deploy/DEPLOY.md "Edge caching"):
        # probe the shard authority's byte cache before any
        # re-render, and write a thief's render back to it.
        self.peer_fetch = peer_fetch
        self.peer_timeout_s = peer_timeout_s
        # Combined-role fleets have no remote peers — every member
        # shares ONE byte-cache chain the handler already probes — so
        # the peer path short-circuits to a single attribute read.
        self._has_remote_members = any(
            getattr(m, "remote", False) for m in members)
        self._putback_tasks: set = set()
        # Per-member shard manifests captured at drain time, replayed
        # BACK into the member on undrain (pre-stage-back); the last
        # replay task is exposed so drills/operators can await it.
        self._drain_manifests: Dict[str, List[dict]] = {}
        self.last_undrain_prestage: Optional[asyncio.Task] = None
        # Hot-plane replication (popularity-aware placement): a
        # decayed heat tracker over the dispatch stream promotes
        # past-threshold routes to an R>1 replica set — a
        # DETERMINISTIC prefix of the ring chain, so every federated
        # host computes the same set — and reads balance least-queued
        # across the live replicas.  Writes and byte-tier authority
        # stay with the ring owner (chain[0]); ``hotkey=None`` or
        # ``enabled=False`` keeps every pre-replication behavior
        # bit-exact.
        self.hotkey = (hotkey if hotkey is not None
                       and getattr(hotkey, "enabled", False)
                       and len(self.order) > 1 else None)
        self._heat: Optional[HeatTracker] = None
        if self.hotkey is not None:
            self._heat = HeatTracker(
                threshold=getattr(self.hotkey, "threshold", 12.0),
                decay_s=getattr(self.hotkey, "decay_s", 20.0),
                top_k=getattr(self.hotkey, "top_k", 128))
        # route -> replica member names (chain prefix; [0] is the ring
        # owner / write authority).  All bookkeeping is loop-confined
        # like the queues.
        self._replica_sets: Dict[str, List[str]] = {}
        # route -> member names already staged THIS promotion epoch
        # (cleared on demote): the never-double-stage guard.
        self._replica_staged: Dict[str, set] = {}
        # Every route ever promoted (bounded): shard accounting
        # separates deliberate replication from duplicate staging.
        self._hot_ever: set = set()

    # ----------------------------------------------------------- routing

    @staticmethod
    def _pinned(ctx) -> bool:
        """Full-plane and z-projection jobs pin to the mesh lane
        (member 0) and are never stolen or ring-routed.  THE bulk
        classification lives in ``server.pressure.is_bulk`` — the
        governor's shed_bulk step and this pin must never drift apart
        (work the ladder stops shedding must be work the fleet still
        pins, and vice versa)."""
        from ..server.pressure import is_bulk
        return is_bulk(ctx)

    def _routable(self, name: str) -> bool:
        """May NEW work land on this member: alive and not draining.
        Draining is deliberately distinct from down — a draining
        member still finishes in-flight work and answers pre-stage
        handoffs, it just accepts no new routes."""
        member = self.members[name]
        return member.healthy and not member.draining

    def owner_of(self, ctx) -> str:
        """The routable member SERVING this request's plane (hash-
        ring-next past down AND draining members; least-queued among
        the live replica set for a promoted hot route).  Full-plane
        and z-projection jobs pin to the first member — the lane whose
        renderer is the lockstep ``MeshRenderer`` in mesh deployments
        — and never shard."""
        if self._pinned(ctx):
            return self._walk_chain(list(self.order))  # 0 = mesh lane
        return self._serving_member(plane_route_key(ctx))

    def _serving_member(self, route: str, record: bool = False) -> str:
        """Replica-balanced read routing: a promoted route picks the
        least-queued of its LIVE replicas (ties break in chain order,
        so the ring owner wins an idle fleet); drained/dead replicas
        drop out via the same ``_routable`` verdict as everything
        else, and a fully-unroutable replica set falls back to the
        plain chain walk — deaths behave exactly like today."""
        replicas = self._replica_sets.get(route) \
            if self._replica_sets else None
        if replicas:
            live = [n for n in replicas if self._routable(n)]
            if live:
                target = min(
                    live,
                    key=lambda n: (len(self._queues[n])
                                   + self._inflight[n],
                                   replicas.index(n)))
                if record and target != replicas[0]:
                    from ..utils import telemetry
                    telemetry.HOTKEY.count_balanced(target)
                return target
        return self._walk_chain(self.ring.chain(route))

    def _walk_chain(self, chain: List[str]) -> str:
        from . import federation
        fenced = self.failover and federation.is_fenced()
        if not self.failover or fenced:
            # Contract symmetry with _fail_queue: failover=false means
            # a dead member's shard FAILS — for queued work and new
            # arrivals alike.  Walking past an unhealthy owner here
            # would silently re-home its planes onto the ring
            # successor (with adopt_cache=True and no failed_over
            # tick), exactly the shard migration the operator
            # disabled.  DRAINING is the exception: a drain is an
            # operator-ordered handoff, so its re-home is the point.
            # A FENCED minority island takes the same no-re-home walk:
            # adopting a silent peer's shard during a netsplit is how
            # split brains write — the owner's call fails over the
            # 503-with-shed contract instead, counted as a refusal.
            for name in chain:
                if not self.members[name].draining:
                    if fenced and not self._routable(name):
                        federation.quorum_allow("adoption")
                    return name
            return chain[0]
        for name in chain:
            if self._routable(name):
                return name
        # Every member down: hand the ring owner the call anyway so
        # the failure surfaces as the ConnectionError -> 503 contract
        # instead of an unroutable internal error.
        return chain[0]

    # ----------------------------------------------- hot-plane replication

    def _observe_heat(self, route: str) -> None:
        """One dispatch observation: bump the route's heat, promote it
        past the threshold, and sweep cooled promotions back down.
        Loop-confined (dispatch only), like all queue bookkeeping."""
        heat = self._heat.observe(route)
        if heat >= self._heat.threshold \
                and route not in self._replica_sets:
            from . import federation
            if federation.quorum_allow("promotion"):
                self._promote_route(route, heat)
            # Fenced: promotion would stage bytes onto replicas this
            # island cannot prove it owns — refused (counted); the
            # route re-promotes on first hot dispatch after restore.
        self._sweep_hot_routes()

    def _promote_route(self, route: str, heat: float) -> None:
        """Give a hot route an R>1 replica set: a deterministic PREFIX
        of its ring chain (chain[0] stays the write / byte-tier
        authority), then stage the owner's warm slice onto the new
        replicas through the digest-deduped staging path —
        fire-and-forget, never blocking the hot dispatch itself."""
        from ..utils import telemetry
        chain = self.ring.chain(route)
        r = min(max(2, int(getattr(self.hotkey, "max_replicas", 2))),
                len(chain))
        replicas = chain[:r]
        self._replica_sets[route] = replicas
        self._hot_ever.add(route)
        while len(self._hot_ever) > 4096:
            self._hot_ever.pop()
        telemetry.HOTKEY.count_promoted()
        telemetry.HOTKEY.set_hot_routes(len(self._replica_sets))
        telemetry.FLIGHT.record("hotkey.promote", route=route[:12],
                                heat=round(heat, 1),
                                replicas=",".join(replicas))
        from ..utils import decisions
        decisions.record("hotkey", "promoted",
                         detail={"route": route[:16],
                                 "heat": round(heat, 2),
                                 "replicas": list(replicas)})
        try:
            task = asyncio.get_running_loop().create_task(
                self._stage_replicas(route, replicas))
        except RuntimeError:
            return                 # no loop (sync tests): lazy warm
        self._putback_tasks.add(task)
        task.add_done_callback(self._putback_tasks.discard)

    async def _stage_replicas(self, route: str,
                              replicas: List[str]) -> int:
        """Stage the hot route's owner slice onto its replicas.  Each
        (route, replica) pair stages at most once per promotion epoch
        (``_replica_staged``), and the staging path itself digest-
        dedups, so re-promotion after a demote is a residency probe
        hit — never a duplicate HBM buffer."""
        from ..utils import telemetry
        owner = self.members.get(replicas[0])
        if owner is None:
            return 0
        route_fn = getattr(owner, "route_manifest", None)
        try:
            if route_fn is not None:
                entries = await route_fn(route)
            else:
                entries = [e for e in await owner.shard_manifest(0)
                           if e.get("route") == route]
        except Exception:
            entries = []
        staged_members = self._replica_staged.setdefault(route, set())
        total = 0
        for name in replicas[1:]:
            if name in staged_members:
                # The never-double-stage guard: a second stage of the
                # same (route, replica) pair in one epoch would be a
                # bookkeeping bug — counted, visible, asserted == 0.
                telemetry.HOTKEY.count_duplicate_staged()
                continue
            member = self.members.get(name)
            if member is None or not member.healthy:
                continue
            staged_members.add(name)
            if not entries:
                # Nothing warm to hand over yet: the replica warms
                # through its own balanced renders (the same
                # digest-deduped staging path) — no work to ship.
                continue
            try:
                n = await member.prestage_manifest(entries)
            except Exception:
                staged_members.discard(name)
                continue
            total += n
            telemetry.HOTKEY.count_staged(n)
            telemetry.FLIGHT.record("hotkey.stage", route=route[:12],
                                    member=name, entries=n)
        return total

    def _sweep_hot_routes(self) -> None:
        """Demote promoted routes whose decayed heat fell under the
        demote fraction of the threshold (hysteresis: promotion at
        ``threshold``, demotion below ``threshold * demote_fraction``
        — no flapping at the boundary).  Replica HBM entries are NOT
        evicted here: reclaim is deferred to the cache-pressure ladder
        (``evict_to_fraction`` takes cold entries LRU-first), so a
        re-heating route finds its replicas still warm."""
        if not self._replica_sets:
            return
        demote_at = (self._heat.threshold
                     * float(getattr(self.hotkey, "demote_fraction",
                                     0.5)))
        for route in list(self._replica_sets):
            if self._heat.heat(route) <= demote_at:
                self._demote_route(route)

    def _demote_route(self, route: str) -> None:
        from ..utils import telemetry
        self._replica_sets.pop(route, None)
        self._replica_staged.pop(route, None)
        telemetry.HOTKEY.count_demoted()
        telemetry.HOTKEY.set_hot_routes(len(self._replica_sets))
        telemetry.FLIGHT.record("hotkey.demote", route=route[:12])
        from ..utils import decisions
        decisions.record("hotkey", "demoted",
                         detail={"route": route[:16]})

    def shed_replicas(self) -> int:
        """Demote EVERY promoted route (the cache-pressure ladder's
        evict step calls this before ``evict_to_fraction``): replicas
        are pure duplicates, so under memory pressure they are the
        first HBM the fleet can afford to lose."""
        routes = list(self._replica_sets)
        for route in routes:
            self._demote_route(route)
        return len(routes)

    def apply_manifest(self, manifest) -> bool:
        """Swap the routing ring to ``manifest``'s geometry at an
        epoch COMMIT — the ONLY moment a live router's ring ever
        changes (a propose leaves routing untouched; in-flight work
        finishes on the old owners, the next dispatch routes on the
        new ring).  Same-membership rolls (seed / replica-count /
        epoch bumps) are the supported surface: a membership change
        needs member construction this router cannot do and raises.
        Promoted hot routes are shed first — their replica sets are
        chain prefixes of the OLD ring and would pin stale owners
        across the swap (re-heating routes re-promote on the new
        ring's chains)."""
        names = set(manifest.names())
        if names != set(self.order):
            raise ValueError(
                "epoch roll changed fleet membership "
                f"({sorted(names ^ set(self.order))}); a live router "
                "only swaps ring geometry — membership changes need "
                "a restart")
        shed = self.shed_replicas()
        self.ring = HashRing(self.order, replicas=manifest.replicas,
                             seed=manifest.ring_seed)
        from ..utils import telemetry
        telemetry.FLIGHT.record("fleet.ring-swap",
                                epoch=manifest.version,
                                seed=str(manifest.ring_seed)[:16],
                                replicas=manifest.replicas,
                                shed_hot=shed)
        return True

    def replica_set(self, route: str) -> List[str]:
        """The route's CURRENT replica set ([owner] when not
        promoted) — /debug/explain's replica-set line."""
        replicas = self._replica_sets.get(route)
        if replicas:
            return list(replicas)
        chain = self.ring.chain(route)
        return chain[:1]

    def route_heat(self, route: str) -> float:
        return self._heat.heat(route) if self._heat is not None else 0.0

    def is_hot_route(self, route: str) -> bool:
        return route in self._replica_sets

    def hot_route_count(self) -> int:
        return len(self._replica_sets)

    def hot_owned(self, name: str) -> int:
        """Promoted routes whose replica set includes ``name`` (the
        gossip view's per-member hot figure)."""
        return sum(1 for reps in self._replica_sets.values()
                   if name in reps)

    def replica_pressure(self) -> float:
        """Sustained hot-route demand in units of the promotion
        threshold: max over promoted routes of heat / threshold.  >= 1
        while a promoted route is still at promotion heat; grows with
        demand concentration — the autoscaler's scale-up signal for
        'one plane is outrunning one member', distinct from plain
        queue depth."""
        if self._heat is None or not self._replica_sets:
            from ..utils import telemetry
            telemetry.HOTKEY.set_pressure(0.0)
            return 0.0
        pressure = max((self._heat.heat(r) / self._heat.threshold
                        for r in self._replica_sets), default=0.0)
        from ..utils import telemetry
        telemetry.HOTKEY.set_pressure(pressure)
        return pressure

    def local_replica_caches(self, route: str) -> List:
        """The HBM caches of the LOCAL replicas of a promoted route,
        balanced-read order (the prefetcher stages a hot route's
        predicted tiles into every balanced reader, not just the ring
        owner).  Empty for unpromoted routes."""
        out = []
        for name in self._replica_sets.get(route, ()):
            if not self._routable(name):
                continue
            member = self.members[name]
            if getattr(member, "remote", False):
                continue
            cache = getattr(getattr(member, "services", None),
                            "raw_cache", None)
            if cache is not None:
                out.append(cache)
        return out

    def queue_depth(self) -> int:
        """Queued + executing across the whole fleet (what fleet-aware
        admission and /readyz see)."""
        return (sum(len(q) for q in self._queues.values())
                + sum(self._inflight.values()))

    def member_depth(self, name: str) -> int:
        return len(self._queues[name])

    def member_inflight(self, name: str) -> int:
        return self._inflight[name]

    def healthy_members(self) -> List[str]:
        return [n for n in self.order if self.members[n].healthy]

    def cache_for_route(self, route_key: str):
        """The HBM raw cache of the member that OWNS ``route_key`` —
        the predictive prefetcher's fleet seam: a predicted plane
        stages into the shard that will serve its future request, so
        prefetch warms the right member and the shard map never
        duplicates.  None for remote members (their sidecars prefetch
        for themselves) or when the owner has no cache."""
        for name in self.ring.chain(route_key):
            if self._routable(name):
                member = self.members[name]
                return getattr(getattr(member, "services", None),
                               "raw_cache", None)
        return None

    def remote_prestage_for_route(self, route_key: str,
                                  entry: dict) -> bool:
        """Shard-aware prefetch, cross-host seam: a PREDICTED plane
        whose ring owner is a REMOTE member stages on ITS owner's
        host — a fire-and-forget ``prestage`` hint (the owner re-reads
        the region from its own pixel store through the digest-deduped
        staging path), so speculation warms the member that will serve
        the request instead of this host's wrong shard.  False when
        the owner is local (``cache_for_route`` handles it in-process)
        or unroutable."""
        for name in self.ring.chain(route_key):
            if not self._routable(name):
                continue
            member = self.members[name]
            if not getattr(member, "remote", False):
                return False
            from ..utils import telemetry

            async def hint() -> None:
                try:
                    await member.prestage_manifest([entry])
                except Exception:
                    pass           # speculation only removes work

            try:
                task = asyncio.get_running_loop().create_task(hint())
            except RuntimeError:
                return False       # no loop: prefetch pool thread
            telemetry.FEDERATION.count_remote_prestage()
            self._putback_tasks.add(task)
            task.add_done_callback(self._putback_tasks.discard)
            return True
        return False

    def draining_members(self, intent: Optional[str] = None
                         ) -> List[str]:
        """Draining member names; ``intent`` filters to one drain
        flavor ("operator" / "autoscale") — the /readyz fail posture
        only counts operator drains, so a routine autoscale
        scale-down never pulls the instance from LB rotation."""
        return [n for n in self.order
                if self.members[n].draining
                and (intent is None
                     or getattr(self.members[n], "drain_intent",
                                None) == intent)]

    # ----------------------------------------------------------- drains

    async def drain_member(self, name: str, prestage: bool = True,
                           max_planes: int = 256,
                           settle_timeout_s: float = 30.0,
                           intent: str = "operator") -> dict:
        """Zero-downtime rolling drain of one member.

        Phases (each a flight-recorder event and a
        ``imageregion_drain_*`` transition):

        1. **draining** — the member stops accepting routes (new
           arrivals and failovers walk past it; its lanes stop
           stealing) and its QUEUED work re-homes hash-ring-next with
           adoption, exactly the failover remap bound (~1/N).
        2. **settle** — in-flight renders finish on the member (a
           drain interrupts nothing; ``settle_timeout_s`` bounds the
           wait, not the work).
        3. **handoff** — the member's HBM shard manifest (MRU-first,
           bounded by ``max_planes``) is handed to each plane's NEW
           ring owner, which pre-stages it through the digest-deduped
           staging path — the shard arrives WARM on the successor
           instead of cold-missing.
        4. **drained** — the member is safe to restart; ``undrain``
           rejoins it with the same remap bound as a ring join.

        Idempotent: draining an already-draining member just re-runs
        the settle + handoff."""
        import time as _time
        from ..utils import decisions, telemetry

        if name not in self.members:
            raise KeyError(f"unknown fleet member {name!r}")
        member = self.members[name]
        member.draining = True
        # The drain FLAVOR: "operator" (rolling restart — what
        # drain.fail-readyz surfaces to LBs) vs "autoscale" (routine
        # scale-down — annotation only, /readyz stays 200).
        member.drain_intent = intent
        telemetry.DRAIN.set_state(name, "draining")
        telemetry.FLIGHT.record("drain.phase", member=name,
                                phase="draining", intent=intent,
                                queued=len(self._queues[name]),
                                inflight=self._inflight[name])
        # Queued work re-homes NOW (the lanes would drain it anyway,
        # but re-homing bounds the drain's tail latency by the
        # in-flight work only).
        self._reassign(name, reason="drain")
        t0 = _time.monotonic()
        while (self._inflight[name] > 0
               and _time.monotonic() - t0 < settle_timeout_s):
            await asyncio.sleep(0.02)
        settled = self._inflight[name] == 0
        manifest = await member.shard_manifest(max_planes)
        # Stashed for the rejoin: undrain replays this manifest BACK
        # through the digest-deduped staging path so the member's
        # shard is warm before its first routed request (a restart
        # drops the HBM cache; the manifest is what it held).
        if manifest:
            self._drain_manifests[name] = manifest
        prestaged = 0
        if prestage and manifest:
            telemetry.FLIGHT.record("drain.phase", member=name,
                                    phase="handoff",
                                    planes=len(manifest))
            prestaged = await self._prestage_handoff(name, manifest)
            telemetry.DRAIN.count_prestaged(prestaged)
        telemetry.DRAIN.set_state(name, "drained")
        telemetry.FLIGHT.record("drain.phase", member=name,
                                phase="drained", settled=settled,
                                planes=len(manifest),
                                prestaged=prestaged)
        logger.info("fleet member %s drained (settled=%s, %d shard "
                    "planes, %d pre-staged on successors)", name,
                    settled, len(manifest), prestaged)
        # Ledger verdict: "failed" means the settle window expired
        # with work still in flight — the drain completed anyway, but
        # the controller's intent (interrupt nothing) did not hold.
        decisions.record("drain", "done" if settled else "failed",
                         member=name, detail={
                             "intent": intent, "settled": settled,
                             "planes": len(manifest),
                             "prestaged": prestaged})
        return {"member": name, "settled": settled, "intent": intent,
                "planes": len(manifest), "prestaged": prestaged}

    async def _prestage_handoff(self, draining: str,
                                manifest: List[dict]) -> int:
        """Hand each manifest plane to the member that will SERVE it:
        its recorded routing identity walks the ring exactly like a
        live request (the draining member is no longer routable, so
        the walk lands on the true successor).  Entries missing a
        route (legacy manifests, wire-pushed planes) spread by their
        raw key — deterministic, and still warm-on-SOME-member."""
        by_successor: Dict[str, List[dict]] = {}
        for entry in manifest:
            route = entry.get("route") or repr(entry.get("key"))
            for candidate in self.ring.chain(route):
                if candidate != draining and self._routable(candidate):
                    by_successor.setdefault(candidate,
                                            []).append(entry)
                    break
        from ..utils import decisions
        staged = 0
        failed = 0
        draining_member = self.members[draining]
        # Cross-host warm handoff: a LOCAL drainer's HBM bytes ship
        # over the wire to REMOTE successors (their host cannot
        # re-read this host's pixel store).  Exported once, bounded by
        # the manifest the drain already capped; any export/ship
        # failure degrades to the hint-list prestage below.
        exported: Dict[tuple, dict] = {}
        if self.wire_handoff and not draining_member.remote and any(
                self.members[s].remote for s in by_successor):
            try:
                for entry in await draining_member.shard_export(
                        len(manifest)):
                    exported[_entry_key(entry)] = entry
            except Exception:
                logger.warning("shard export from %s failed; "
                               "hint-list handoff", draining,
                               exc_info=True)
        for successor, entries in by_successor.items():
            member = self.members[successor]
            try:
                if exported and member.remote:
                    with_bytes = [exported[_entry_key(e)]
                                  for e in entries
                                  if _entry_key(e) in exported]
                    # Ship the warm bytes (shard_transfer counts each
                    # landed entry's bytes itself); entries whose
                    # buffer was already evicted fall back to hints.
                    staged += await member.shard_transfer(with_bytes)
                    rest = [e for e in entries
                            if _entry_key(e) not in exported]
                    if rest:
                        staged += await member.prestage_manifest(rest)
                else:
                    staged += await member.prestage_manifest(entries)
            except Exception:
                failed += 1
                logger.warning("drain handoff to %s failed",
                               successor, exc_info=True)
        decisions.record("handoff", "failed" if failed else "done",
                         member=draining, detail={
                             "planes": len(manifest), "staged": staged,
                             "successors": len(by_successor),
                             "failed_successors": failed})
        return staged

    def undrain_member(self, name: str,
                       prestage_back: bool = True) -> None:
        """Rejoin a drained member: routes flow back onto its ring
        arcs at the next dispatch — the same ~1/N remap bound as a
        ring join (the ring itself never changed).

        **Pre-stage BACK**: the shard manifest captured when this
        member drained replays into it through the digest-deduped
        ``restage_plane_entry`` path, so a member that restarted with
        a cold HBM cache rejoins WARM — its first routed request hits
        instead of paying the cold read/stage the drain existed to
        avoid.  Background + best-effort (the member serves either
        way); the task is exposed as ``last_undrain_prestage`` so the
        drill (and a scripted roll) can await completion."""
        from ..utils import decisions, telemetry
        if name not in self.members:
            raise KeyError(f"unknown fleet member {name!r}")
        member = self.members[name]
        member.draining = False
        member.drain_intent = None
        telemetry.DRAIN.set_state(name, "active")
        telemetry.FLIGHT.record("drain.phase", member=name,
                                phase="undrained")
        decisions.record("undrain", "done", member=name, detail={
            "prestage_back": bool(prestage_back
                                  and self._drain_manifests.get(name))})
        entries = self._drain_manifests.pop(name, None)
        self.last_undrain_prestage = None
        if prestage_back and entries:
            async def _restage_back() -> None:
                try:
                    staged = await member.prestage_manifest(entries)
                except Exception:
                    logger.warning("undrain pre-stage-back into %s "
                                   "failed", name, exc_info=True)
                    return
                telemetry.DRAIN.count_prestaged(staged)
                telemetry.FLIGHT.record(
                    "drain.phase", member=name, phase="prestage-back",
                    planes=len(entries), prestaged=staged)
                logger.info("fleet member %s pre-staged back %d/%d "
                            "shard planes on undrain", name, staged,
                            len(entries))

            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None   # sync caller with no loop: serve cold
            if loop is not None:
                task = loop.create_task(_restage_back())
                self.last_undrain_prestage = task
                # Tracked with the put-back shipments so close()
                # cancels an in-flight replay instead of leaking it.
                self._putback_tasks.add(task)
                task.add_done_callback(self._putback_tasks.discard)
        logger.info("fleet member %s undrained (rejoined the ring)",
                    name)

    # ---------------------------------------------------------- dispatch

    def _ensure_lanes(self) -> None:
        if self._lanes or self._closed:
            return
        from ..utils import transient
        self._wake = asyncio.Event()
        # Lanes are spawned lazily from the FIRST request's context —
        # detach them from its deadline contextvar (create_task
        # snapshots the context), or every render in every lane would
        # permanently inherit that one request's budget and start
        # 504ing fleet-wide the moment it expires.  Each unit's own
        # budget is re-established around its render from
        # ``work.deadline``.
        with transient.deadline_scope(None):
            for name in self.order:
                for lane in range(self.lane_width):
                    self._lanes.append(asyncio.create_task(
                        self._lane(name), name=f"fleet-{name}-l{lane}"))

    async def dispatch(self, ctx) -> bytes:
        """Route one render to its shard owner and await the bytes.
        Runs on the event loop; all queue bookkeeping is loop-confined
        (no lock), like the single-flight table."""
        from ..utils import telemetry, transient

        if self._closed:
            raise ConnectionError("fleet router is closed")
        self._ensure_lanes()
        if self._heat is not None and not self._pinned(ctx):
            # Hot-key tier: every dispatched (non-pinned) request
            # feeds the heat tracker; a promoted route's reads then
            # balance least-queued across its live replicas.
            route = plane_route_key(ctx)
            self._observe_heat(route)
            owner = self._serving_member(route, record=True)
        else:
            owner = self.owner_of(ctx)
        work = _Work(ctx, asyncio.get_running_loop().create_future(),
                     owner, transient.deadline())
        if work.trace_ids:
            # Hop 1 of the stitched waterfall: the ROUTE decision —
            # which member's shard this plane hashed to.  Zero-width
            # span at enqueue time; the render hop below shows where
            # the work actually ran (steal/failover may move it).
            telemetry.record_span(
                "fleet.hop", work.t_enqueue, 0.0,
                trace_ids=work.trace_ids, member=owner, hop="route",
                plane=work.route_key)
        self._queues[owner].append(work)
        telemetry.FLEET.count_routed(owner)
        self._wake.set()
        remaining = transient.remaining_ms()
        if remaining is None:
            return await work.future
        try:
            # The member render enforces its own budget too; this
            # bound covers a lane wedged in an uncancellable render.
            return await asyncio.wait_for(
                asyncio.shield(work.future),
                timeout=max(0.0, remaining) / 1000.0)
        except asyncio.TimeoutError:
            # The waiter is gone: cancel the unit so a lane popping
            # it later skips instead of rendering bytes nobody will
            # retrieve (and so no 'exception never retrieved' noise).
            if not work.future.done():
                work.future.cancel()
            raise transient.DeadlineExceededError(
                "deadline exceeded awaiting fleet render")
        except asyncio.CancelledError:
            if not work.future.done():
                work.future.cancel()
            raise

    async def fetch_peer_bytes(self, ctx) -> Optional[bytes]:
        """The offload ladder's peer rung: when routing would hand
        this render to a member that is NOT the chain's byte
        authority (the ring owner is draining or down and the shard
        moved hash-ring-next), probe the authority's byte tier and
        fetch the already-rendered bytes over the idempotent
        ``byte_probe``/``byte_fetch`` wire ops INSTEAD of re-rendering
        on the successor.  The authority is the first chain member
        alive enough to answer — healthy OR draining (a draining
        member finishes work and serves handoffs by design; its byte
        tier is exactly where the just-rendered bytes live).

        Combined-role members share ONE byte-cache chain the fleet
        handler already probed, so only REMOTE peers are asked.  Every
        failure (timeout, dead peer, ACL refusal, miss) returns None
        and the render path proceeds — the peer tier can only ever
        remove work, never add a failure mode."""
        if not self.peer_fetch or not self._has_remote_members \
                or self._pinned(ctx):
            return None
        from ..utils import telemetry
        serving = self.owner_of(ctx)
        for name in self.ring.chain(plane_route_key(ctx)):
            if name == serving:
                # The serving member probes its own tier first thing
                # in its handler — a frontend pre-probe of the SAME
                # tier would only double the round-trips.
                return None
            member = self.members[name]
            if not member.remote \
                    or not (member.healthy or member.draining):
                continue
            # ONE round-trip: byte_fetch itself is the probe (None =
            # authority miss -> render; the batched byte_probe op
            # exists for bulk callers).  A transport failure counts a
            # FALLBACK — distinct from a miss, so degraded peering is
            # visible on /metrics rather than reading as cold tiles.
            telemetry.HTTPCACHE.count_peer_probe()
            key = ctx.cache_key    # == settings.render_identity_key
            t0 = time.perf_counter()
            try:
                data = await asyncio.wait_for(
                    member.byte_fetch(key, image_id=ctx.image_id,
                                      session=ctx.omero_session_key),
                    self.peer_timeout_s)
            except Exception:
                telemetry.HTTPCACHE.count_peer_fallback()
                return None
            if data is None:
                # The authority has no bytes: nothing newer down the
                # chain would (writes land authority-first) — render.
                return None
            telemetry.HTTPCACHE.count_peer_hit()
            telemetry.HTTPCACHE.count_peer_fetch()
            # Hop span (request context — fetch runs in the handler)
            # + provenance: the bytes came from a PEER's tier.
            telemetry.record_span(
                "fleet.hop", t0,
                (time.perf_counter() - t0) * 1000.0,
                member=name, hop="byte_fetch",
                plane=plane_route_key(ctx)[:12])
            from ..utils import provenance
            provenance.mark(ctx, tier="peer", member=name)
            telemetry.FLIGHT.record("fleet.byte-peer",
                                    authority=name,
                                    serving=serving,
                                    nbytes=len(data))
            return data
        return None

    @staticmethod
    def _mask_route(ctx) -> str:
        """Ring route for a mask's byte authority: its byte-cache key
        (the storage identity the PR 11 ETag folds), namespaced so a
        mask and a render identity can never share an arc owner by
        accident."""
        return f"mask|{ctx.cache_key()}"

    async def fetch_peer_mask(self, ctx) -> Optional[bytes]:
        """Federated byte tier for ShapeMask PNGs: probe the mask's
        ring-authority host over the same idempotent ``byte_fetch``
        wire op as tiles (``tier=mask``) so a mask rendered on one
        host is every host's hit.  Only explicit-color masks are
        byte-cached (the reference's staleness rule), so only those
        are asked for; local members share THIS host's already-probed
        ``shape_mask`` stack and are skipped.  None on miss, ACL
        refusal or any transport failure — the peer tier only ever
        removes work."""
        if not self.peer_fetch or not self._has_remote_members \
                or getattr(ctx, "color", None) is None:
            return None
        from ..utils import provenance, telemetry
        key = str(ctx.cache_key())
        for name in self.ring.chain(self._mask_route(ctx)):
            member = self.members[name]
            if not getattr(member, "remote", False) \
                    or not (member.healthy or member.draining):
                continue
            telemetry.HTTPCACHE.count_peer_probe()
            try:
                data = await asyncio.wait_for(
                    member.byte_fetch(
                        key, image_id=ctx.shape_id,
                        session=ctx.omero_session_key,
                        tier="mask", obj="Mask"),
                    self.peer_timeout_s)
            except Exception:
                telemetry.HTTPCACHE.count_peer_fallback()
                return None
            if data is None:
                return None
            telemetry.HTTPCACHE.count_peer_hit()
            telemetry.HTTPCACHE.count_peer_fetch()
            provenance.mark(ctx, tier="peer", member=name)
            telemetry.FLIGHT.record("fleet.mask-peer", authority=name,
                                    nbytes=len(data))
            return data
        return None

    def put_peer_mask(self, ctx, data: bytes) -> None:
        """Ship a just-rendered explicit-color mask PNG to its ring
        authority's mask byte tier (fire-and-forget ``byte_put``,
        never blind-retried) — the write-back half of the federated
        mask tier.  A local authority needs nothing: the render path
        already wrote this host's shared ``shape_mask`` stack."""
        if not self.peer_fetch or not self._has_remote_members \
                or getattr(ctx, "color", None) is None:
            return
        from ..utils import telemetry
        key = str(ctx.cache_key())
        for name in self.ring.chain(self._mask_route(ctx)):
            member = self.members[name]
            if not (member.healthy or member.draining):
                continue
            if not getattr(member, "remote", False):
                return            # local authority: already stored
            from . import federation
            if not federation.quorum_allow("write_authority"):
                return        # fenced: no cross-split mask write-back
            async def put() -> None:
                try:
                    if await member.byte_put(key, data, tier="mask"):
                        telemetry.HTTPCACHE.count_peer_putback()
                except Exception:
                    pass           # best-effort by contract
            try:
                task = asyncio.get_running_loop().create_task(put())
            except RuntimeError:
                return
            self._putback_tasks.add(task)
            task.add_done_callback(self._putback_tasks.discard)
            return

    def _byte_putback(self, work: _Work, data: bytes) -> None:
        """A thief finished another member's render: ship the bytes to
        the shard AUTHORITY's byte tier (fire-and-forget, over the
        state-changing ``byte_put`` op — never blind-retried, exactly
        the plane_put contract) so the owner answers the next probe
        itself — one member's render becomes every member's hit."""
        if not self.peer_fetch:
            return
        owner = self.members.get(work.owner)
        if owner is None or not owner.remote or not owner.healthy:
            return
        from . import federation
        if not federation.quorum_allow("write_authority"):
            # Fenced minority: the byte-tier authority may have moved
            # on the majority side — writing back across the split
            # would be split-brain state.  Drop the ship (counted);
            # the owner re-renders or re-probes after restore.
            return
        if getattr(work.ctx, "_pressure_quality_capped", False):
            # Brownout-capped bytes never land under the full-quality
            # key (the PR 9 drop_quality contract) — peers included.
            return
        from ..utils import telemetry
        key = work.ctx.cache_key   # == settings.render_identity_key
        if work.trace_ids:
            # Hop: the write-back SHIP (recorded synchronously, before
            # the requester's trace finishes — the put itself is
            # fire-and-forget and lands after the response; its
            # completion is the peer_putbacks counter + flight event).
            telemetry.record_span(
                "fleet.hop", time.perf_counter(), 0.0,
                trace_ids=work.trace_ids, member=work.owner,
                hop="byte_put", plane=work.route_key)

        async def put() -> None:
            try:
                if await owner.byte_put(key, data):
                    telemetry.HTTPCACHE.count_peer_putback()
            except Exception:
                pass               # best-effort by contract

        task = asyncio.get_running_loop().create_task(put())
        self._putback_tasks.add(task)
        task.add_done_callback(self._putback_tasks.discard)

    def _takeable(self, name: str) -> bool:
        """Is there work this member's lanes could take right now —
        its own backlog, or a peer backlog past the steal threshold?"""
        if self._queues[name]:
            return True
        if self.steal_min_backlog <= 0 or not self._routable(name):
            return False
        # Mirrors _pop_work's steal candidates exactly (stealable =
        # INTERACTIVE backlog; pinned/bulk units are never stealable)
        # — a backlog this lane can NEVER steal must park it on the
        # wake event, not busy-spin it.
        return any(
            self._queues[other].steal_depth() >= self.steal_min_backlog
            for other in self.order if other != name)

    def _pop_work(self, name: str) -> Optional[_Work]:
        """This lane's next unit: own queue first (weighted dequeue —
        interactive jumps bulk backlogs when QoS is on); otherwise
        steal the OLDEST interactive request from the most-backlogged
        healthy-owned queue at or past the steal threshold
        (oldest-first keeps the latency tail honest — LIFO stealing
        would starve the convoy head).  Pinned mesh-lane (bulk) jobs
        are never stealable — they exist to run on member 0's lockstep
        renderer, not a single-device lane."""
        queue = self._queues[name]
        if queue:
            return queue.popleft()
        if self.steal_min_backlog <= 0 or not self._routable(name):
            # A draining member's lanes drain their own queue (the
            # reassign empties it) but never steal new work.
            return None
        victim = None
        depth = 0
        for other in self.order:
            if other == name:
                continue
            qlen = self._queues[other].steal_depth()
            if qlen >= self.steal_min_backlog and qlen > depth:
                victim, depth = other, qlen
        if victim is None:
            return None
        work = self._queues[victim].steal_pop()
        if work is None:
            return None
        work.stolen = True
        from ..utils import telemetry
        telemetry.FLEET.count_stolen(name)
        telemetry.FLIGHT.record("fleet.steal", by=name,
                                owner=work.owner, backlog=depth)
        if work.trace_ids:
            # Hop: the steal decision — this unit leaves its owner's
            # queue for the thief's lane (cache-ownership-neutral).
            telemetry.record_span(
                "fleet.hop", time.perf_counter(), 0.0,
                trace_ids=work.trace_ids, member=name, hop="steal",
                plane=work.route_key)
        return work

    def _reassign(self, dead: str, reason: str = "failover") -> None:
        """A member died (or is draining): move its queued work to
        each item's hash-ring-next healthy owner (the failover shard
        owner — the work ADOPTS there, it is not a steal).  ``reason``
        distinguishes the death remap from the operator-ordered drain
        re-home on the hop spans and provenance flags."""
        from ..utils import telemetry
        queue = self._queues[dead]
        moved = 0
        while queue:
            work = queue.pop_raw()
            self._route_failover(work, reason=reason)
            moved += 1
        if moved:
            telemetry.FLIGHT.record("fleet.drain", member=dead,
                                    moved=moved)
            self._wake.set()

    def _fail_queue(self, dead: str, error: Exception) -> None:
        """failover=False: a dead member's queued work fails with it."""
        queue = self._queues[dead]
        while queue:
            work = queue.pop_raw()
            if not work.future.done():
                work.future.set_exception(ConnectionError(str(error)))

    def _route_failover(self, work: _Work,
                        reason: str = "failover") -> None:
        """Re-enqueue one unit on the first healthy ring member.  The
        member that just failed is excluded by the health check alone
        (it was marked down before this runs) — NOT by ``work.owner``:
        for STOLEN work the owner is a healthy member that never
        failed, and it is exactly where the unit should land (a dead
        stealer's loot goes home; a 2-member fleet must not 503 a
        request whose shard owner is alive)."""
        from . import federation
        from ..utils import provenance, telemetry
        if reason == "failover" and not federation.quorum_allow(
                "adoption"):
            # Fenced minority: a death re-home is a shard ADOPTION —
            # refused during a partition (the dead member may be alive
            # and serving on the majority side).  The unit fails over
            # the same ConnectionError -> 503-with-shed contract as an
            # all-down fleet; operator drains stay allowed.
            if not work.future.done():
                work.future.set_exception(ConnectionError(
                    "fenced minority partition: shard adoption "
                    "refused"))
            return
        chain = (list(self.order) if self._pinned(work.ctx)
                 else self.ring.chain(plane_route_key(work.ctx)))
        tried = work.hops
        for name in chain:
            if not self._routable(name):
                continue
            work.owner = name
            work.hops = tried + 1
            work.stolen = False
            self._queues[name].append(work)
            telemetry.FLEET.count_failed_over(name)
            if work.trace_ids:
                # Hop: the re-home — "drain" when an operator ordered
                # it, "failover" when a death did.
                telemetry.record_span(
                    "fleet.hop", time.perf_counter(), 0.0,
                    trace_ids=work.trace_ids, member=name, hop=reason,
                    plane=work.route_key)
            provenance.mark(
                work.ctx,
                **{("drain_rehomed" if reason == "drain"
                    else "failed_over"): True})
            return
        if not work.future.done():
            work.future.set_exception(ConnectionError(
                "no healthy fleet member for shard"))

    async def _lane(self, name: str) -> None:
        from ..utils import provenance, telemetry, transient

        # Lanes are long-lived tasks spawned from the FIRST request's
        # context; detach from its trace ids or every span any render
        # ever records here would graft onto that one request's
        # waterfall (each unit re-adopts its own ids around its
        # render below).
        telemetry.clear_context()
        member = self.members[name]
        while not self._closed:
            work = self._pop_work(name)
            if work is None:
                self._wake.clear()
                # Re-check under the cleared event for work THIS lane
                # could take (a dispatch between pop and clear must
                # not be lost — but peers' sub-threshold backlogs must
                # not busy-spin a lane that cannot steal them).
                if self._takeable(name):
                    continue
                await self._wake.wait()
                continue
            if work.future.done():
                continue              # waiter gave up while queued
            if work.deadline is not None \
                    and time.monotonic() >= work.deadline:
                telemetry.RESILIENCE.count_deadline_cancelled(1)
                if not work.future.done():
                    work.future.set_exception(
                        transient.DeadlineExceededError(
                            "deadline exceeded in fleet queue"))
                continue
            self._inflight[name] += 1
            # Provenance: the member actually serving, and how the
            # unit got there (marked before the render so a failing
            # member still leaves an attributable record).
            provenance.mark(work.ctx, member=name,
                            **({"stolen": True} if work.stolen
                               else {}))
            t_render = time.perf_counter()
            try:
                # A stolen render executes on THIS member from source
                # bytes without adopting cache ownership; owned (and
                # failed-over) work adopts — the failover target IS
                # the shard's new ring owner.  The unit's remaining
                # budget re-enters the context here (the lane task
                # itself is deadline-free), so the member pipeline's
                # own check_deadline / wire deadline_ms still bite.
                # The unit's OWN trace ids re-enter too (group_trace):
                # member-side spans — and, for remote members, the
                # trace id riding the wire — attach to the requester's
                # waterfall, not to whatever context spawned the lane.
                with telemetry.group_trace(work.trace_ids):
                    if work.deadline is not None:
                        remaining_ms = max(
                            1.0, (work.deadline - time.monotonic())
                            * 1000.0)
                        with transient.deadline_scope(remaining_ms):
                            data = await member.render(
                                work.ctx, adopt_cache=not work.stolen)
                    else:
                        data = await member.render(
                            work.ctx, adopt_cache=not work.stolen)
            except (ConnectionError, OSError) as e:
                if not member.remote \
                        and not isinstance(e, ConnectionError):
                    # A LOCAL render's OSError (missing/truncated
                    # pyramid file, EIO) is that one request's
                    # failure, never member death — treating it as
                    # death would cascade a bad file into marking
                    # every member down in failover order.
                    if not work.future.done():
                        work.future.set_exception(e)
                    continue
                if not isinstance(e, MemberDownError):
                    # A fast-fail from an already-down member is not
                    # a new death — re-marking would extend the
                    # cooldown on every request and the member could
                    # never rejoin under steady traffic.
                    member.mark_down()
                    telemetry.FLIGHT.record("fleet.member-down",
                                            member=name,
                                            error=str(e)[:120])
                if not self.failover:
                    # Contract: the shard fails as the member does —
                    # queued work included, never re-homed.
                    logger.warning("fleet member %s down (%s); "
                                   "failover disabled, failing its "
                                   "shard", name, e)
                    self._fail_queue(name, e)
                    if not work.future.done():
                        work.future.set_exception(e)
                    continue
                logger.warning("fleet member %s down (%s); failing "
                               "its shard over hash-ring-next", name, e)
                self._reassign(name)
                if work.hops < len(self.order) - 1:
                    self._route_failover(work)
                    self._wake.set()
                elif not work.future.done():
                    work.future.set_exception(e)
            except asyncio.CancelledError:
                # Router teardown mid-render: waiters sit in HTTP
                # handlers whose ``except Exception`` must map this to
                # a 500, never a dropped connection.
                if not work.future.done():
                    work.future.set_exception(
                        RuntimeError("fleet router shut down"))
                raise
            except Exception as e:
                if not work.future.done():
                    work.future.set_exception(e)
            else:
                if work.trace_ids:
                    # The render hop itself: which member executed,
                    # and under what acquisition (owned / stolen /
                    # failed-over) — the widest lane of the stitched
                    # waterfall.
                    telemetry.record_span(
                        "fleet.hop", t_render,
                        (time.perf_counter() - t_render) * 1000.0,
                        trace_ids=work.trace_ids, member=name,
                        hop="render", plane=work.route_key,
                        **({"stolen": 1} if work.stolen else {}))
                if not work.future.done():
                    work.future.set_result(data)
                if work.stolen:
                    # The thief's render lands on the shard authority's
                    # byte tier too (fire-and-forget byte_put): one
                    # member's render becomes every member's hit.
                    self._byte_putback(work, data)
            finally:
                self._inflight[name] -= 1

    # --------------------------------------------------------- accounting

    def shard_report(self) -> dict:
        """HBM shard accounting across local members: per-member
        resident planes, and how many content digests are resident on
        MORE than one member (the duplicate-staging figure the fleet
        exists to hold at ~0).  Digests whose route was DELIBERATELY
        replicated by the hot-key tier are reported separately
        (``replicated_digests``) — replication must never masquerade
        as, nor mask, a duplicate-staging bug."""
        per_member = {}
        seen: Dict[str, int] = {}
        for name in self.order:
            digests = self.members[name].resident_digests()
            per_member[name] = self.members[name].resident_planes()
            for d in digests:
                seen[d] = seen.get(d, 0) + 1
        duplicates = replicated = 0
        if any(n > 1 for n in seen.values()):
            routes = (self._local_digest_routes()
                      if self._hot_ever else {})
            for d, n in seen.items():
                if n <= 1:
                    continue
                if routes.get(d) in self._hot_ever:
                    replicated += 1
                else:
                    duplicates += 1
        return {
            "members": per_member,
            "resident_digests": len(seen),
            "duplicate_digests": duplicates,
            "replicated_digests": replicated,
        }

    def _local_digest_routes(self) -> Dict[str, str]:
        """digest -> route over every local member's resident entries
        (accounting only — one locked snapshot per member)."""
        out: Dict[str, str] = {}
        for name in self.order:
            cache = getattr(getattr(self.members[name], "services",
                                    None), "raw_cache", None)
            if cache is None or not hasattr(cache, "snapshot_entries"):
                continue
            for entry in cache.snapshot_entries(0):
                digest = entry.get("digest")
                if digest:
                    out[digest] = entry.get("route")
        return out

    async def close(self) -> None:
        self._closed = True
        for task in self._lanes:
            task.cancel()
        if self._lanes:
            await asyncio.gather(*self._lanes, return_exceptions=True)
        self._lanes = []
        for task in list(self._putback_tasks):
            task.cancel()
        if self._putback_tasks:
            await asyncio.gather(*self._putback_tasks,
                                 return_exceptions=True)
        self._putback_tasks.clear()
        for queue in self._queues.values():
            while queue:
                work = queue.pop_raw()
                if not work.future.done():
                    work.future.set_exception(
                        RuntimeError("fleet router shut down"))


# ------------------------------------------------------ frontend handler

class FleetImageHandler:
    """The fleet-topology drop-in for ``ImageRegionHandler`` /
    ``SidecarImageHandler``: byte-cache-first (combined role — hits
    never shed), then fleet-wide single-flight, then fleet-aware
    admission, then the router.

    ``base_services`` (combined role) supplies the shared byte caches
    and the ACL memo; proxy fleets pass None — their sidecars own
    caches and ACL, exactly like the single-sidecar posture, and the
    single-flight key folds the caller's session in (see below).

    ``fallback`` (``server.degraded.DegradedCpuHandler``, proxy fleets
    only) keeps tiles servable when the WHOLE fleet is unreachable —
    same seam as ``SidecarImageHandler``; a live member's own verdict
    (shed, 4xx, deadline) never falls back."""

    def __init__(self, router: FleetRouter, single_flight=None,
                 admission=None, base_services=None, fallback=None):
        self.router = router
        self.single_flight = single_flight
        self.admission = admission
        self.s = base_services
        self.fallback = fallback

    async def _cached(self, ctx) -> Optional[bytes]:
        if self.s is None:
            return None
        from ..server.errors import NotFoundError
        from ..server.handler import check_can_read
        from ..services.cache import get_with_tier
        from ..utils import provenance, telemetry
        t0 = time.perf_counter()
        cached, tier_label = await get_with_tier(
            self.s.caches.image_region, ctx.cache_key)
        if cached is None:
            return None
        if not await check_can_read(self.s, "Image", ctx.image_id,
                                    ctx.omero_session_key):
            raise NotFoundError(f"Cannot find Image:{ctx.image_id}")
        telemetry.record_span("cache.hit", t0,
                              (time.perf_counter() - t0) * 1000.0)
        provenance.mark(ctx, tier=("disk" if tier_label == "disk"
                                   else "byte_cache"))
        return cached

    async def render_image_region(self, ctx) -> bytes:
        from ..server.errors import NotFoundError, OverloadedError
        from ..utils import telemetry, transient

        t0 = time.perf_counter()
        cached = await self._cached(ctx)
        if cached is not None:
            return cached
        if self.s is not None:
            # ACL gates PER CALLER before the shared render is
            # awaited (the render_identity_key contract): a follower
            # must never receive coalesced pixels its session cannot
            # read.
            from ..server.handler import check_can_read
            if not await check_can_read(self.s, "Image", ctx.image_id,
                                        ctx.omero_session_key):
                raise NotFoundError(
                    f"Cannot find Image:{ctx.image_id}")

        # Fleet-global byte tier: before fairness, single-flight and
        # admission (same footing as the byte-cache probe above —
        # already-rendered bytes never shed and never cost a token),
        # ask the shard AUTHORITY's byte tier when routing would land
        # this render elsewhere.  The serving sidecar ACL-gates the
        # fetch for this caller's session; combined role gated above.
        # getattr: drill/test routers are duck-typed dispatchers.
        peer_fetch = getattr(self.router, "fetch_peer_bytes", None)
        peer = (await peer_fetch(ctx)
                if peer_fetch is not None else None)
        if peer is not None:
            if self.s is not None:
                # Local write-back: the shared byte tier answers the
                # next repeat view without even the peer round-trip.
                await self.s.caches.image_region.set(ctx.cache_key,
                                                     peer)
            telemetry.record_span(
                "cache.peer", t0,
                (time.perf_counter() - t0) * 1000.0)
            return peer

        admission = self.admission
        # Per-session fairness runs PER CALLER, before coalescing —
        # like the combined role's ACL gate above: single-flight
        # shares the leader's outcome across sessions, so a hostile
        # session's over-budget 503 inside the producer would
        # propagate to coalesced followers from under-budget
        # sessions.  Every request pays its own token
        # (ctx.omero_session_key — the identity the session
        # middleware resolved and the proxy single-flight key folds)
        # and sheds only itself.
        debit = admission.admit_session(ctx) if admission is not None \
            else None
        if debit is not None:
            from ..utils import provenance
            provenance.mark(ctx, tokens=debit[1])

        async def produce() -> bytes:
            from ..server.pressure import shed_bulk_under_pressure
            shed_bulk_under_pressure(ctx)
            # GLOBAL admission: leader-only (a coalesced follower
            # adds no work, so only the pipeline run claims a slot).
            t_admit = admission.admit() if admission is not None \
                else None
            completed = False
            try:
                transient.check_deadline("fleet render")
                try:
                    data = await self.router.dispatch(ctx)
                except (ConnectionError, OverloadedError):
                    # Degraded mode: only when NO member is left to
                    # serve — a live member's shed/verdict stands.
                    if (self.fallback is None
                            or self.router.healthy_members()):
                        raise
                    telemetry.RESILIENCE.count_degraded_render()
                    from ..utils import provenance
                    provenance.mark(ctx, tier="degraded")
                    data = await \
                        self.fallback.render_image_region(ctx)
                completed = True
                return data
            finally:
                if admission is not None:
                    admission.release(t_admit, completed=completed)

        try:
            if self.single_flight is None:
                remaining = transient.remaining_ms()
                if remaining is None:
                    return await produce()
                try:
                    return await asyncio.wait_for(
                        produce(),
                        timeout=max(0.0, remaining) / 1000.0)
                except asyncio.TimeoutError:
                    raise transient.DeadlineExceededError(
                        "deadline exceeded awaiting fleet render")
            from ..server.settings import render_identity_key
            key = render_identity_key(ctx)
            if self.s is None:
                # Proxy fleet: this process CANNOT check ACL, so
                # identical renders coalesce per-session only — each
                # session's leader carries its own ctx to a sidecar
                # whose handler runs the full ACL gate.  (Combined
                # role checked above, so cross-session coalescing
                # stays.)
                key = f"{key}|{ctx.omero_session_key or ''}"
            data, coalesced = await self.single_flight.run(key,
                                                           produce)
        except OverloadedError:
            # Refused GLOBALLY (queue/deadline/pressure — directly or
            # via the coalesced-onto leader) after the fairness gate
            # debited tokens: refund them — the session never got the
            # render.
            if admission is not None:
                admission.refund_session(debit)
            raise
        if coalesced:
            telemetry.record_span(
                "dedup.coalesced", t0,
                (time.perf_counter() - t0) * 1000.0)
            from ..utils import provenance
            provenance.mark(ctx, coalesced=True)
        return data

    async def render_image_region_stream(self, ctx):
        """Chunked-response surface parity: the fleet answer is one
        body (each member's own first-tile-out settlement already
        pulled its latency in); the HTTP layer keeps its one uniform
        chunked path."""
        yield await self.render_image_region(ctx)


# ---------------------------------------------------------- construction

def build_local_members(config, base_services, n: int,
                        device_sets: Optional[Sequence] = None
                        ) -> List[LocalMember]:
    """N in-process fleet members over a shared host-side service
    stack: member 0 IS the base stack (its renderer may be the
    lockstep ``MeshRenderer``); members 1..N-1 get their own renderer
    + ``DeviceRawCache`` (their shard of HBM) and share everything
    host-side — pixel stores, byte caches, metadata, ACL memo, LUTs.

    One JAX process: the members shard serving state (cache, queues,
    lanes) but all dispatch to the process's default device — this
    topology does NOT spread compute across a multi-chip host.  Real
    per-member device sets are the ``fleet.sockets`` topology, one
    ``JAX_VISIBLE_DEVICES``-pinned sidecar process per member
    (per-member device pinning here is an open roadmap item).

    Member-level single-flight and admission are disabled on the extra
    members: both concerns live fleet-wide above the router."""
    from ..io.devicecache import DeviceRawCache
    from ..server.batcher import BatchingRenderer
    from ..server.handler import (ImageRegionHandler,
                                  ImageRegionServices, Renderer)

    def devices_for(i: int) -> tuple:
        if not device_sets or i >= len(device_sets):
            return ()
        return tuple(device_sets[i] or ())

    cooldown = config.fleet.down_cooldown_s
    # The lockstep MeshRenderer is mesh-topology-bound: it already
    # spans its whole device set and must NEVER be pinned narrower
    # (parallel.serve marks it ``lockstep``) — member 0 then keeps
    # the process default dispatch.
    lockstep = getattr(base_services.renderer, "lockstep", False)
    base_services.pin_device = (devices_for(0)[0]
                                if devices_for(0) and not lockstep
                                else None)
    if base_services.pin_device is not None \
            and hasattr(base_services.renderer, "device"):
        base_services.renderer.device = base_services.pin_device
    members = [LocalMember(
        "m0",
        ImageRegionHandler(base_services), services=base_services,
        down_cooldown_s=cooldown, byte_cache_prechecked=True,
        devices=devices_for(0))]
    for i in range(1, n):
        if config.batcher.enabled and not config.parallel.enabled:
            renderer = BatchingRenderer(
                max_batch=config.batcher.max_batch,
                max_batch_limit=config.batcher.max_batch_limit,
                linger_ms=config.batcher.linger_ms,
                jpeg_engine=(base_services.renderer.jpeg_engine
                             if getattr(base_services.renderer,
                                        "jpeg_engine", None)
                             in ("sparse", "huffman") else "sparse"),
                pipeline_depth=config.batcher.pipeline_depth,
                target_inflight=config.batcher.target_inflight,
                device_lanes=config.batcher.device_lanes)
            renderer.first_tile_out = config.wire.streaming
        else:
            engine = config.renderer.jpeg_engine
            if engine == "auto":
                engine = getattr(base_services.renderer,
                                 "jpeg_engine", "sparse")
            renderer = Renderer(jpeg_engine=engine,
                                kernel=config.renderer.kernel)
        if devices_for(i):
            renderer.device = devices_for(i)[0]
        raw_cache = (DeviceRawCache(
            config.raw_cache.max_bytes,
            digest_index=config.raw_cache.digest_dedup)
            if config.raw_cache.enabled else None)
        services = ImageRegionServices(
            pixels_service=base_services.pixels_service,
            metadata=base_services.metadata,
            caches=base_services.caches,
            can_read_memo=base_services.can_read_memo,
            renderer=renderer,
            lut_provider=base_services.lut_provider,
            max_tile_length=base_services.max_tile_length,
            raw_cache=raw_cache,
            cpu_fallback_max_px=base_services.cpu_fallback_max_px,
            pin_device=(devices_for(i)[0] if devices_for(i)
                        else None),
        )
        members.append(LocalMember(
            f"m{i}",
            ImageRegionHandler(services), services=services,
            down_cooldown_s=cooldown, byte_cache_prechecked=True,
            devices=devices_for(i)))
    return members
