"""Mesh-sharded serving: the micro-batcher dispatching over a device mesh.

The reference *serves* from its cluster — worker verticles on every node
consume the same event-bus address (``-cluster``;
``ImageRegionMicroserviceVerticle.java:148-165, 406-424``).  The TPU-native
form: :class:`MeshRenderer` keeps the micro-batcher's queueing/bucketing
contract (drop-in for ``server.handler.Renderer`` / ``BatchingRenderer``)
but runs every coalesced group through the mesh-sharded steps
(``parallel.mesh.render_step_sharded_batched`` /
``render_jpeg_step_sharded_batched``): tiles data-parallel across the
mesh, channels optionally tensor-parallel with the additive composite as
one ``psum`` over ICI.

Group padding makes the fixed mesh shapes hold: the batch pads up to a
multiple of the ``data`` axis (repeating the last tile) and the channel
count pads up to a multiple of the ``chan`` axis with inert channels
(unit window, zero color tables — they contribute nothing to the
composite).
"""

from __future__ import annotations

import logging
from typing import List

import numpy as np

from ..server.batcher import BatchingRenderer, _Pending
from ..utils.stopwatch import stopwatch
from .mesh import (Mesh, render_jpeg_step_sharded_batched,
                   render_step_sharded_batched, shard_batch_batched)

logger = logging.getLogger(__name__)


def _pad_group(raw: np.ndarray, stacked: dict, data: int, chan: int):
    """Pad [B, C, H, W] + stacked settings to the mesh's divisibility."""
    B, C = raw.shape[:2]
    Bp = -(-B // data) * data
    Cp = -(-C // chan) * chan
    if Bp != B:
        reps = [raw[-1:]] * (Bp - B)
        raw = np.concatenate([raw] + reps, axis=0) \
            if isinstance(raw, np.ndarray) else _jnp_cat(raw, reps)
        stacked = {
            k: (np.concatenate([v] + [v[-1:]] * (Bp - B), axis=0)
                if getattr(v, "ndim", 0) else v)
            for k, v in stacked.items()
        }
    if Cp != C:
        pad_c = Cp - C
        xp = np if isinstance(raw, np.ndarray) else _jnp()
        raw = xp.concatenate(
            [raw, xp.zeros(raw.shape[:1] + (pad_c,) + raw.shape[2:],
                           raw.dtype)], axis=1)
        Bp = raw.shape[0]

        def padc(v, fill):
            ext = np.full((Bp, pad_c) + v.shape[2:], fill, v.dtype)
            return np.concatenate([v, ext], axis=1)

        stacked = dict(stacked)
        stacked["window_start"] = padc(stacked["window_start"], 0.0)
        stacked["window_end"] = padc(stacked["window_end"], 1.0)
        stacked["family"] = padc(stacked["family"], 0)
        stacked["coefficient"] = padc(stacked["coefficient"], 1.0)
        stacked["reverse"] = padc(stacked["reverse"], 0)
        stacked["tables"] = padc(stacked["tables"], 0.0)
    return raw, stacked


def _jnp():
    import jax.numpy as jnp
    return jnp


def _global_overflow_verdict(local: bool) -> bool:
    """Agree on the cap-widening retry across every mesh process.

    Each process fetches only its addressable shard of the wire totals, so
    a tile overflowing on one host's shard is invisible to the others.  The
    retry re-dispatches a *different* (2x-cap) sharded program — and also
    flips ``_CAP_MEMO`` for every later group — so if processes decide
    from local data alone their SPMD launch sequences diverge and the pod
    hangs.  A one-bool all-gather makes the verdict global.  The caller
    must gate this only on process-deterministic state (the memo), never
    on shard-local data, so every process reaches the collective.
    """
    import jax
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([local], np.bool_))
    return bool(np.asarray(flags).any())


def _jnp_cat(raw, reps):
    jnp = _jnp()
    return jnp.concatenate([raw] + reps, axis=0)


class MeshRenderer(BatchingRenderer):
    """Drop-in renderer serving every group through the sharded steps."""

    def __init__(self, mesh: Mesh, max_batch: int | None = None,
                 linger_ms: float = 2.0, buckets=None,
                 jpeg_engine: str = "sparse", pipeline_depth: int = 2,
                 max_batch_limit: int = None):
        data = mesh.shape["data"]
        if max_batch is None:
            max_batch = max(8, 2 * data)
        if jpeg_engine not in ("sparse", "huffman"):
            raise ValueError(f"mesh jpeg engine must be 'sparse' or "
                             f"'huffman', got {jpeg_engine!r}")
        import jax
        multihost = jax.process_count() > 1
        if multihost and pipeline_depth != 1:
            # On a multi-host global mesh every process must launch the
            # same programs in the same order (SPMD); overlapped group
            # renders make local launch order racy, so pipelining is
            # single-host only.
            logger.warning("multi-host mesh: forcing pipeline_depth=1 "
                           "(was %d) — sharded launches must not "
                           "overlap", pipeline_depth)
            pipeline_depth = 1
        kwargs = {} if buckets is None else {"buckets": buckets}
        super().__init__(max_batch=max_batch, linger_ms=linger_ms,
                         pipeline_depth=pipeline_depth,
                         max_batch_limit=max_batch_limit, **kwargs)
        if multihost:
            # One launch slot shared across ALL bucket keys: without it,
            # two keys' dispatchers would interleave sharded launches in
            # a host-local order.  NOTE this serializes launches but
            # does not by itself give every host the same group stream —
            # multi-host pods must feed all processes an identical
            # request schedule (see deploy/DEPLOY.md, driver process).
            import asyncio as _asyncio
            self._shared_slots = _asyncio.Semaphore(1)
            # Host-local queue-pressure batch growth would launch
            # program shapes the other processes never compile (SPMD);
            # the pod serves the configured max_batch only.
            self._growth_enabled = False
        self.mesh = mesh
        self.jpeg_engine = jpeg_engine
        import threading
        # Group renders run on up to pipeline_depth concurrent worker
        # threads; without the lock a cold start would build (and
        # mesh-wide-compile) the same step twice.
        self._steps_lock = threading.Lock()
        self._render_steps: dict = {}
        self._jpeg_steps: dict = {}
        self._multihost = multihost
        # Multi-host only: number of clean (globally-agreed no-overflow)
        # groups seen per memo key.  Past the cap the steady-state hot
        # path stops paying a cross-host collective per group; a later
        # overflow then lands on the per-tile dense fallback instead of
        # widening.  Counts advance only on agreed verdicts, so the
        # counter — and therefore the launch sequence — stays identical
        # on every process.
        self._verdict_checks: dict = {}

    _VERDICT_CHECK_CAP = 8

    def _should_check_overflow(self, memo_key) -> bool:
        if not self._multihost:
            return True
        return self._verdict_checks.get(memo_key, 0) < self._VERDICT_CHECK_CAP

    def _record_clean_verdict(self, memo_key) -> None:
        if self._multihost:
            self._verdict_checks[memo_key] = \
                self._verdict_checks.get(memo_key, 0) + 1

    # ------------------------------------------------------------- steps

    def _render_step(self):
        with self._steps_lock:
            step = self._render_steps.get("render")
            if step is None:
                step = self._render_steps["render"] = \
                    render_step_sharded_batched(self.mesh)
            return step

    def _jpeg_step(self, quality: int, cap: int, engine: str = "sparse",
                   cap_words: int | None = None):
        key = (engine, quality, cap, cap_words)
        with self._steps_lock:
            step = self._jpeg_steps.get(key)
            if step is None:
                step = self._jpeg_steps[key] = \
                    render_jpeg_step_sharded_batched(self.mesh, quality,
                                                     cap=cap,
                                                     engine=engine,
                                                     cap_words=cap_words)
            return step

    # ------------------------------------------------------------ groups

    def _stacked(self, group: List[_Pending]):
        raw, stack = self._group_arrays(group)
        s0 = group[0].settings
        stacked = {
            "window_start": stack("window_start"),
            "window_end": stack("window_end"),
            "family": stack("family"),
            "coefficient": stack("coefficient"),
            "reverse": stack("reverse"),
            "tables": stack("tables"),
            "cd_start": s0["cd_start"],
            "cd_end": s0["cd_end"],
        }
        raw, stacked = _pad_group(
            np.asarray(raw, np.float32) if isinstance(raw, np.ndarray)
            else raw,
            stacked, self.mesh.shape["data"], self.mesh.shape["chan"])
        return raw, stacked

    def _render_group(self, group: List[_Pending]) -> List[np.ndarray]:
        n = len(group)
        raw, stacked = self._stacked(group)
        args = shard_batch_batched(self.mesh, raw, stacked)
        with stopwatch("Renderer.renderAsPackedInt.mesh"):
            out = self._render_step()(*args)
            host = np.asarray(out)
        self._count_batch(n)
        return [host[i, :p.h, :p.w] for i, p in enumerate(group[:n])]

    @staticmethod
    def _dense_coefficients(raw, stacked, qy, qc, i):
        """Single-tile dense coefficients on the default device — the
        rare-overflow fallback shared by both wire engines."""
        from ..ops.jpegenc import render_to_jpeg_coefficients

        y, cb, cr = render_to_jpeg_coefficients(
            np.asarray(raw[i:i + 1], np.float32),
            np.asarray(stacked["window_start"][i:i + 1]),
            np.asarray(stacked["window_end"][i:i + 1]),
            np.asarray(stacked["family"][i:i + 1]),
            np.asarray(stacked["coefficient"][i:i + 1]),
            np.asarray(stacked["reverse"][i:i + 1]),
            stacked["cd_start"], stacked["cd_end"],
            np.asarray(stacked["tables"][i:i + 1]), qy, qc)
        return np.asarray(y)[0], np.asarray(cb)[0], np.asarray(cr)[0]

    def _render_group_jpeg(self, group: List[_Pending]) -> List[bytes]:
        from ..ops.jpegenc import (default_sparse_cap,
                                   finish_sparse_to_jpegs,
                                   quant_tables, wire_fetcher)

        n = len(group)
        raw, stacked = self._stacked(group)
        H, W = raw.shape[-2:]
        quality = group[0].quality
        # Quality-aware cap: deterministic in (H, W, quality), so every
        # process of a multi-host mesh — fed the same group stream —
        # compiles the same sharded program.  Overflow retries are
        # agreed globally via _global_overflow_verdict, so the memo
        # (and the launch sequence) stays identical on every process.
        from ..ops.jpegenc import _CAP_MEMO, wire_header_i32
        cap = default_sparse_cap(H, W, quality)
        # The packed Huffman stream covers the full (H, W) grid, so the
        # wire-optimal engine applies when every tile in the group is
        # grid-exact (same policy as ``render_batch_to_jpeg``); mixed
        # groups fall back to the sparse engine as a whole.  Each
        # engine applies its own overflow memo to the base cap.
        all_exact = all((p.h + 15) // 16 * 16 == H
                        and (p.w + 15) // 16 * 16 == W for p in group)
        if self.jpeg_engine == "huffman" and all_exact:
            return self._render_group_jpeg_huffman(
                group, raw, stacked, H, W, cap, quality)
        memo_key = ("mesh-sparse", H, W, quality)
        if _CAP_MEMO.get(memo_key):
            cap *= 2
        args = shard_batch_batched(self.mesh, raw, stacked)
        with stopwatch("Renderer.renderAsPackedInt.mesh"):
            bufs = self._jpeg_step(quality, cap)(*args)
            bufs = wire_fetcher(H, W, cap).fetch(bufs)
            totals = wire_header_i32(bufs, 0)
            local_over = bool(((totals > cap)
                               & (totals <= 2 * cap)).any())
            if (memo_key not in _CAP_MEMO
                    and self._should_check_overflow(memo_key)):
                if _global_overflow_verdict(local_over):
                    # One-shot widening, mirroring render_batch_to_jpeg:
                    # a rescuable overflow re-dispatches the group at 2x
                    # instead of per-tile dense re-renders.  The verdict
                    # is all-gathered so every process re-dispatches (or
                    # not) in lockstep; the gates are deterministic.
                    _CAP_MEMO[memo_key] = True
                    cap *= 2
                    bufs = self._jpeg_step(quality, cap)(*args)
                    bufs = wire_fetcher(H, W, cap).fetch(bufs)
                else:
                    self._record_clean_verdict(memo_key)

        qy, qc = (np.asarray(t, np.int32) for t in quant_tables(quality))
        jpegs = finish_sparse_to_jpegs(
            bufs, [(p.w, p.h) for p in group], H, W, quality, cap,
            lambda i: self._dense_coefficients(raw, stacked, qy, qc, i))
        self._count_batch(n)
        return jpegs

    def _render_group_jpeg_huffman(self, group, raw, stacked, H, W, cap,
                                   quality) -> List[bytes]:
        from ..ops.jpegenc import (_CAP_MEMO, default_words_cap,
                                   dense_encoder, finish_huffman_batch,
                                   huffman_wire_fetcher, quant_tables,
                                   wire_header_i32)

        n = len(group)
        cap_words = default_words_cap(H, W, quality)
        memo_key = ("mesh-huffman", H, W, quality)
        if _CAP_MEMO.get(memo_key):
            cap, cap_words = cap * 2, cap_words * 2
        args = shard_batch_batched(self.mesh, raw, stacked)
        with stopwatch("Renderer.renderAsPackedInt.mesh"):
            bufs = self._jpeg_step(quality, cap, "huffman",
                                   cap_words)(*args)
            bufs = huffman_wire_fetcher(H, W, cap, cap_words).fetch(bufs)
            totals = wire_header_i32(bufs, 0)
            bits = wire_header_i32(bufs, 1)
            over = (totals > cap) | (bits > cap_words * 32)
            rescuable = ((totals <= 2 * cap)
                         & (bits <= 2 * cap_words * 32))
            local_over = bool((over & rescuable).any())
            if (memo_key not in _CAP_MEMO
                    and self._should_check_overflow(memo_key)):
                if _global_overflow_verdict(local_over):
                    # One-shot widening (see render_batch_to_jpeg);
                    # verdict all-gathered across processes — see
                    # _global_overflow_verdict.
                    _CAP_MEMO[memo_key] = True
                    cap, cap_words = cap * 2, cap_words * 2
                    bufs = self._jpeg_step(quality, cap, "huffman",
                                           cap_words)(*args)
                    bufs = huffman_wire_fetcher(H, W, cap,
                                                cap_words).fetch(bufs)
                else:
                    self._record_clean_verdict(memo_key)

        qy, qc = (np.asarray(t, np.int32) for t in quant_tables(quality))
        _dense_encode = dense_encoder()

        def dense_tile(i):
            # Rare cap/bits overflow: dense re-encode of one tile.
            y, cb, cr = self._dense_coefficients(raw, stacked, qy, qc, i)
            return _dense_encode(y, cb, cr, group[i].w, group[i].h,
                                 quality)

        jpegs = finish_huffman_batch(
            bufs, [(p.w, p.h) for p in group], H, W, quality, cap,
            cap_words, dense_fallback=dense_tile)
        self._count_batch(n)
        return jpegs
