"""Mesh-sharded serving: the micro-batcher dispatching over a device mesh.

The reference *serves* from its cluster — worker verticles on every node
consume the same event-bus address (``-cluster``;
``ImageRegionMicroserviceVerticle.java:148-165, 406-424``).  The TPU-native
form: :class:`MeshRenderer` keeps the micro-batcher's queueing/bucketing
contract (drop-in for ``server.handler.Renderer`` / ``BatchingRenderer``)
but runs every coalesced group through the mesh-sharded steps
(``parallel.mesh.render_step_sharded_batched`` /
``render_jpeg_step_sharded_batched``): tiles data-parallel across the
mesh, channels optionally tensor-parallel with the additive composite as
one ``psum`` over ICI.

Group padding makes the fixed mesh shapes hold: the batch pads up to a
multiple of the ``data`` axis (repeating the last tile) and the channel
count pads up to a multiple of the ``chan`` axis with inert channels
(unit window, zero color tables — they contribute nothing to the
composite).

Multi-host pods: only the leader (process 0) has a request stream;
before each dispatch it replicates the group to the followers over the
pod broadcast channel (:class:`_PodChannel`), and every process —
followers via :func:`run_pod_follower` (``--role pod-worker``) — runs
the identical sharded flow.  Step outputs are all-gathered inside the
program (``replicate_output``), so the leader can materialize full
results and overflow decisions are deterministic everywhere.
"""

from __future__ import annotations

import logging
import time
from typing import List

import numpy as np

from ..server.batcher import BatchingRenderer, _Pending, _shape_label
from ..utils import telemetry
from ..utils.stopwatch import stopwatch
from .mesh import (Mesh, render_jpeg_step_sharded_batched,
                   render_step_sharded_batched, shard_batch_batched)

logger = logging.getLogger(__name__)


def _pad_group(raw: np.ndarray, stacked: dict, data: int, chan: int):
    """Pad [B, C, H, W] + stacked settings to the mesh's divisibility."""
    B, C = raw.shape[:2]
    Bp = -(-B // data) * data
    Cp = -(-C // chan) * chan
    if Bp != B:
        reps = [raw[-1:]] * (Bp - B)
        raw = np.concatenate([raw] + reps, axis=0) \
            if isinstance(raw, np.ndarray) else _jnp_cat(raw, reps)
        stacked = {
            k: (np.concatenate([v] + [v[-1:]] * (Bp - B), axis=0)
                if getattr(v, "ndim", 0) else v)
            for k, v in stacked.items()
        }
    if Cp != C:
        pad_c = Cp - C
        xp = np if isinstance(raw, np.ndarray) else _jnp()
        raw = xp.concatenate(
            [raw, xp.zeros(raw.shape[:1] + (pad_c,) + raw.shape[2:],
                           raw.dtype)], axis=1)
        Bp = raw.shape[0]

        def padc(v, fill):
            ext = np.full((Bp, pad_c) + v.shape[2:], fill, v.dtype)
            return np.concatenate([v, ext], axis=1)

        stacked = dict(stacked)
        stacked["window_start"] = padc(stacked["window_start"], 0.0)
        stacked["window_end"] = padc(stacked["window_end"], 1.0)
        stacked["family"] = padc(stacked["family"], 0)
        stacked["coefficient"] = padc(stacked["coefficient"], 1.0)
        stacked["reverse"] = padc(stacked["reverse"], 0)
        stacked["tables"] = padc(stacked["tables"], 0.0)
    return raw, stacked


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jnp_cat(raw, reps):
    jnp = _jnp()
    return jnp.concatenate([raw] + reps, axis=0)


# ------------------------------------------------------ pod replication

# Header words for the pod broadcast protocol (leader -> followers).
_POD_HDR = 16
_POD_SHUTDOWN, _POD_RENDER, _POD_JPEG = 0, 1, 2


class _PodChannel:
    """Group replication for multi-host serving.

    SPMD requires every process of a pod to launch identical sharded
    programs in identical order, but only the leader (process 0) has a
    request stream.  Before each group dispatch the leader broadcasts a
    fixed-size header plus the group's arrays
    (``multihost_utils.broadcast_one_to_all`` — one collective per
    array); followers reconstruct the group and run the IDENTICAL
    dispatch flow, so the pod stays in lockstep without any sidecar
    traffic reaching the followers.
    """

    @staticmethod
    def _bcast(x):
        from jax.experimental import multihost_utils
        return multihost_utils.broadcast_one_to_all(x)

    # ---------------------------------------------------------- leader

    def announce(self, kind: int, raw=None, stacked=None,
                 quality: int = 0, engine_id: int = 0) -> None:
        hdr = np.zeros(_POD_HDR, np.int32)
        hdr[0] = kind
        if kind != _POD_SHUTDOWN:
            B, C, H, W = raw.shape
            hdr[1:5] = (B, C, H, W)
            hdr[5] = quality
            hdr[6] = engine_id
            hdr[7] = np.asarray(stacked["tables"]).ndim
            hdr[8] = int(stacked["cd_start"])
            hdr[9] = int(stacked["cd_end"])
        self._bcast(hdr)
        if kind == _POD_SHUTDOWN:
            return
        for arr, dt in self._payload(raw, stacked):
            self._bcast(np.ascontiguousarray(np.asarray(arr, dt)))

    # -------------------------------------------------------- follower

    def recv(self):
        """Next announced group: (kind, raw, stacked, quality,
        engine_id); raw/stacked are None at shutdown."""
        hdr = np.asarray(self._bcast(np.zeros(_POD_HDR, np.int32)))
        kind = int(hdr[0])
        if kind == _POD_SHUTDOWN:
            return kind, None, None, 0, 0
        B, C, H, W = (int(v) for v in hdr[1:5])
        tables_shape = ((B, C, 3) if int(hdr[7]) == 3
                        else (B, C, 256, 3))
        shapes = self._shapes(B, C, H, W, tables_shape)
        got = [np.asarray(self._bcast(np.zeros(shape, dt)))
               for shape, dt in shapes]
        raw = got[0]
        stacked = {
            "window_start": got[1], "window_end": got[2],
            "family": got[3], "coefficient": got[4], "reverse": got[5],
            "tables": got[6],
            "cd_start": int(hdr[8]), "cd_end": int(hdr[9]),
        }
        return kind, raw, stacked, int(hdr[5]), int(hdr[6])

    # ---------------------------------------------------------- layout

    @staticmethod
    def _payload(raw, stacked):
        return (
            (raw, np.float32),
            (stacked["window_start"], np.float32),
            (stacked["window_end"], np.float32),
            (stacked["family"], np.int32),
            (stacked["coefficient"], np.float32),
            (stacked["reverse"], np.int32),
            (stacked["tables"], np.float32),
        )

    @staticmethod
    def _shapes(B, C, H, W, tables_shape):
        return (
            ((B, C, H, W), np.float32),
            ((B, C), np.float32), ((B, C), np.float32),
            ((B, C), np.int32), ((B, C), np.float32),
            ((B, C), np.int32), (tables_shape, np.float32),
        )


class MeshRenderer(BatchingRenderer):
    """Drop-in renderer serving every group through the sharded steps."""

    # Mesh-sharded programs are topology-bound and run in SPMD
    # lockstep across the whole mesh: this renderer must be its
    # process's FIRST fleet member (the mesh/bulk lane), is never
    # device-pinned narrower than its mesh, and federated builds
    # (parallel.federation.build_federated_members) warn when the
    # manifest order would pin fleet-wide bulk work to another host
    # while this one holds the mesh.
    lockstep = True

    def __init__(self, mesh: Mesh, max_batch: int | None = None,
                 linger_ms: float = 2.0, buckets=None,
                 jpeg_engine: str = "sparse", pipeline_depth: int = 4,
                 max_batch_limit: int = None, engine_controller=None,
                 device_lanes: int = 2):
        data = mesh.shape["data"]
        if max_batch is None:
            max_batch = max(8, 2 * data)
        if jpeg_engine not in ("sparse", "huffman"):
            raise ValueError(f"mesh jpeg engine must be 'sparse' or "
                             f"'huffman', got {jpeg_engine!r}")
        import jax
        multihost = jax.process_count() > 1
        if multihost and pipeline_depth != 1:
            # On a multi-host global mesh every process must launch the
            # same programs in the same order (SPMD); overlapped group
            # renders make local launch order racy, so pipelining is
            # single-host only.
            logger.warning("multi-host mesh: forcing pipeline_depth=1 "
                           "(was %d) — sharded launches must not "
                           "overlap", pipeline_depth)
            pipeline_depth = 1
        if multihost:
            # The two-stage fetch/execute split likewise must not let
            # two groups' sharded launches race a host-local gate order.
            device_lanes = 1
        kwargs = {} if buckets is None else {"buckets": buckets}
        super().__init__(max_batch=max_batch, linger_ms=linger_ms,
                         pipeline_depth=pipeline_depth,
                         max_batch_limit=max_batch_limit,
                         device_lanes=device_lanes, **kwargs)
        if multihost:
            # One launch slot shared across ALL bucket keys: without it,
            # two keys' dispatchers would interleave sharded launches in
            # a host-local order.
            import asyncio as _asyncio
            self._shared_slots = _asyncio.Semaphore(1)
            # Host-local queue-pressure batch growth would launch
            # program shapes the other processes never compile (SPMD);
            # the pod serves the configured max_batch only.
            self._growth_enabled = False
            # Likewise a host-local transient-error retry would launch
            # the sharded program a second time on one process only,
            # diverging the pod's lockstep launch sequence.
            self._transient_retry_enabled = False
            # Deadline-expired pendings DO still drop
            # (_deadline_drop_enabled stays True): the drop happens on
            # the LEADER at dispatch pop, before the group rides the
            # pod announcement, so every follower replays the identical
            # post-drop group — unlike growth/retry, no host-local
            # divergence is possible.  The watchdog's stuck-group
            # requeue (server.watchdog) is lockstep-safe for the same
            # reason: it re-enqueues pendings on the LEADER, and the
            # re-dispatch rides a fresh pod announcement like any
            # other group.  Chaos freeze/device-error
            # injection, however, fires on whatever process installed
            # it and would stall or re-launch one process's lockstep
            # sequence only — config load rejects explicit multi-host
            # + fault-injection.seed, and build_services disarms the
            # injector on auto-discovered pods.
        self.mesh = mesh
        # Never the serialized-executable cache (server.execcache):
        # sharded programs are bound to this mesh's topology and, on a
        # pod, to the lockstep compile sequence — a deserialized
        # executable on one host would diverge SPMD launch order.
        # Warm restarts here ride the trace cache
        # (renderer.compilation-cache-dir) and the bring-up dryrun.
        self.exec_cache = None
        self.jpeg_engine = jpeg_engine
        # Live wire-engine selection (utils.adaptive.AdaptiveEngine).
        # Pod-safe by construction: ONLY the leader consults it, at a
        # group boundary, and the chosen engine rides the existing
        # per-group pod announcement (engine_id) — so every process
        # launches the same sharded program for the group and SPMD
        # lockstep holds.  A pod deployed during congestion is no
        # longer frozen on its startup probe for its whole lifetime.
        self.engine_controller = engine_controller
        import threading
        # Group renders run on up to pipeline_depth concurrent worker
        # threads; without the lock a cold start would build (and
        # mesh-wide-compile) the same step twice.
        self._steps_lock = threading.Lock()
        self._render_steps: dict = {}
        self._jpeg_steps: dict = {}
        self._multihost = multihost
        # Multi-host: outputs are all-gathered inside the sharded step
        # (replicate_output) so (a) the leader can materialize the full
        # result — a data-sharded global array is not addressable
        # cross-host — and (b) overflow verdicts are computed from
        # identical replicated totals on every process, keeping the
        # cap memos in lockstep with no host collective.  The leader
        # replicates each group to the followers over the pod channel
        # before dispatching (see _PodChannel / run_pod_follower).
        self._replicated = multihost
        self._pod = _PodChannel() if multihost else None

    # ------------------------------------------------------------- steps

    def _render_step(self):
        with self._steps_lock:
            step = self._render_steps.get("render")
            if step is None:
                step = self._render_steps["render"] = \
                    render_step_sharded_batched(
                        self.mesh, replicate_output=self._replicated)
            return step

    def _jpeg_step(self, quality: int, cap: int, engine: str = "sparse",
                   cap_words: int | None = None):
        key = (engine, quality, cap, cap_words)
        with self._steps_lock:
            step = self._jpeg_steps.get(key)
            if step is None:
                step = self._jpeg_steps[key] = \
                    render_jpeg_step_sharded_batched(
                        self.mesh, quality, cap=cap, engine=engine,
                        cap_words=cap_words,
                        replicate_output=self._replicated)
            return step

    # ------------------------------------------------------------ groups

    def _stacked(self, group: List[_Pending]):
        raw, stack = self._group_arrays(group)
        s0 = group[0].settings
        stacked = {
            "window_start": stack("window_start"),
            "window_end": stack("window_end"),
            "family": stack("family"),
            "coefficient": stack("coefficient"),
            "reverse": stack("reverse"),
            "tables": stack("tables"),
            "cd_start": s0["cd_start"],
            "cd_end": s0["cd_end"],
        }
        raw, stacked = _pad_group(
            np.asarray(raw, np.float32) if isinstance(raw, np.ndarray)
            else raw,
            stacked, self.mesh.shape["data"], self.mesh.shape["chan"])
        return raw, stacked

    def _render_group(self, group: List[_Pending]) -> List[np.ndarray]:
        n = len(group)
        # Fetch/stage half outside the device gate: group N+1 stacks
        # and pads while group N executes.  The pod announce stays
        # INSIDE the gate so announce order always equals launch order
        # (single-lane on multi-host).
        t_stage = time.perf_counter()
        with stopwatch("batcher.stage"):
            raw, stacked = self._stacked(group)
        telemetry.add_cost(
            "stage_ms", (time.perf_counter() - t_stage) * 1000.0 / n)
        shape = "mesh:" + _shape_label(raw.shape)
        with self._device_gate:
            if self._pod is not None:
                self._pod.announce(_POD_RENDER, raw, stacked)
            t0 = time.perf_counter()
            with stopwatch("Renderer.renderAsPackedInt.mesh"):
                host = self._render_wire(raw, stacked)
            exec_ms = (time.perf_counter() - t0) * 1000.0
        telemetry.add_cost("device_ms", exec_ms / n)
        telemetry.SHAPE_COSTS.observe(shape, exec_ms)
        self._count_batch(n)
        return [host[i, :p.h, :p.w] for i, p in enumerate(group[:n])]

    def _render_wire(self, raw, stacked) -> np.ndarray:
        """The SPMD-identical half of a packed render: dispatch + full
        result materialization.  Leader and followers both run this."""
        args = shard_batch_batched(self.mesh, raw, stacked)
        return np.asarray(self._render_step()(*args))

    @staticmethod
    def _dense_coefficients(raw, stacked, qy, qc, i):
        """Single-tile dense coefficients on the default device — the
        rare-overflow fallback shared by both wire engines."""
        from ..ops.jpegenc import render_to_jpeg_coefficients

        y, cb, cr = render_to_jpeg_coefficients(
            np.asarray(raw[i:i + 1], np.float32),
            np.asarray(stacked["window_start"][i:i + 1]),
            np.asarray(stacked["window_end"][i:i + 1]),
            np.asarray(stacked["family"][i:i + 1]),
            np.asarray(stacked["coefficient"][i:i + 1]),
            np.asarray(stacked["reverse"][i:i + 1]),
            stacked["cd_start"], stacked["cd_end"],
            np.asarray(stacked["tables"][i:i + 1]), qy, qc)
        return np.asarray(y)[0], np.asarray(cb)[0], np.asarray(cr)[0]

    def _sparse_wire(self, raw, stacked, H, W, quality):
        """Sparse-engine dispatch with the one-shot cap-widening
        rescue; SPMD-identical on leader and followers (with replicated
        outputs every process sees the same totals, so the memo — and
        therefore the launch sequence — stays in lockstep with no host
        collective)."""
        from ..ops.jpegenc import (_CAP_MEMO, default_sparse_cap,
                                   wire_fetcher, wire_header_i32)

        cap = default_sparse_cap(H, W, quality)
        memo_key = ("mesh-sparse", H, W, quality)
        if _CAP_MEMO.get(memo_key):
            cap *= 2
        args = shard_batch_batched(self.mesh, raw, stacked)
        bufs = wire_fetcher(H, W, cap).fetch(
            self._jpeg_step(quality, cap)(*args))
        totals = wire_header_i32(bufs, 0)
        if (memo_key not in _CAP_MEMO
                and ((totals > cap) & (totals <= 2 * cap)).any()):
            _CAP_MEMO[memo_key] = True
            cap *= 2
            bufs = wire_fetcher(H, W, cap).fetch(
                self._jpeg_step(quality, cap)(*args))
        return bufs, cap

    def _huffman_wire(self, raw, stacked, H, W, quality):
        """Huffman-engine dispatch with the one-shot widening; same
        lockstep contract as :meth:`_sparse_wire`."""
        from ..ops.jpegenc import (_CAP_MEMO, default_sparse_cap,
                                   default_words_cap,
                                   huffman_wire_fetcher, wire_header_i32)

        cap = default_sparse_cap(H, W, quality)
        cap_words = default_words_cap(H, W, quality)
        memo_key = ("mesh-huffman", H, W, quality)
        if _CAP_MEMO.get(memo_key):
            cap, cap_words = cap * 2, cap_words * 2
        args = shard_batch_batched(self.mesh, raw, stacked)
        bufs = huffman_wire_fetcher(H, W, cap, cap_words).fetch(
            self._jpeg_step(quality, cap, "huffman", cap_words)(*args))
        totals = wire_header_i32(bufs, 0)
        bits = wire_header_i32(bufs, 1)
        over = (totals > cap) | (bits > cap_words * 32)
        rescuable = ((totals <= 2 * cap)
                     & (bits <= 2 * cap_words * 32))
        if memo_key not in _CAP_MEMO and (over & rescuable).any():
            _CAP_MEMO[memo_key] = True
            cap, cap_words = cap * 2, cap_words * 2
            bufs = huffman_wire_fetcher(H, W, cap, cap_words).fetch(
                self._jpeg_step(quality, cap, "huffman",
                                cap_words)(*args))
        return bufs, cap, cap_words

    def _jpeg_engine_for(self, all_exact: bool) -> str:
        # The packed Huffman stream covers the full (H, W) grid, so the
        # wire-optimal engine applies only when every tile in the group
        # is grid-exact (same policy as ``render_batch_to_jpeg``);
        # mixed groups fall back to the sparse engine as a whole.
        # A live controller (jpeg-engine: auto) decides per group; the
        # decision propagates to followers via the group announcement.
        engine = (self.engine_controller.current()
                  if self.engine_controller is not None
                  else self.jpeg_engine)
        return "huffman" if engine == "huffman" and all_exact \
            else "sparse"

    def _render_group_jpeg(self, group: List[_Pending]) -> List[bytes]:
        from ..ops.jpegenc import (dense_encoder, finish_huffman_batch,
                                   finish_sparse_to_jpegs, quant_tables)
        from ..utils.stopwatch import REGISTRY

        n = len(group)
        REGISTRY.record("batcher.groupTiles", float(n))
        t_stage = time.perf_counter()
        with stopwatch("batcher.stage"):
            raw, stacked = self._stacked(group)
        telemetry.add_cost(
            "stage_ms", (time.perf_counter() - t_stage) * 1000.0 / n)
        shape = "mesh:" + _shape_label(raw.shape, jpeg=True)
        H, W = raw.shape[-2:]
        quality = group[0].quality
        all_exact = all((p.h + 15) // 16 * 16 == H
                        and (p.w + 15) // 16 * 16 == W for p in group)
        engine = self._jpeg_engine_for(all_exact)
        qy, qc = (np.asarray(t, np.int32) for t in quant_tables(quality))
        dims = [(p.w, p.h) for p in group]
        if engine == "huffman":
            with self._device_gate:
                if self._pod is not None:
                    self._pod.announce(_POD_JPEG, raw, stacked, quality,
                                       engine_id=1)
                t0 = time.perf_counter()
                with stopwatch("Renderer.renderAsPackedInt.mesh"):
                    bufs, cap, cap_words = self._huffman_wire(
                        raw, stacked, H, W, quality)
                exec_ms = (time.perf_counter() - t0) * 1000.0
            telemetry.add_cost("device_ms", exec_ms / n)
            telemetry.SHAPE_COSTS.observe(shape, exec_ms)
            _dense_encode = dense_encoder()

            def dense_tile(i):
                # Rare cap/bits overflow: dense re-encode of one tile.
                y, cb, cr = self._dense_coefficients(raw, stacked, qy,
                                                     qc, i)
                return _dense_encode(y, cb, cr, group[i].w, group[i].h,
                                     quality)

            jpegs = finish_huffman_batch(
                bufs, dims, H, W, quality, cap, cap_words,
                dense_fallback=dense_tile,
                # First-tile-out is host-side settlement AFTER the
                # lockstep device work — safe on a pod (no launch
                # depends on it).
                on_tile=self._early_settle_cb(group))
        else:
            with self._device_gate:
                if self._pod is not None:
                    self._pod.announce(_POD_JPEG, raw, stacked, quality,
                                       engine_id=0)
                t0 = time.perf_counter()
                with stopwatch("Renderer.renderAsPackedInt.mesh"):
                    bufs, cap = self._sparse_wire(raw, stacked, H, W,
                                                  quality)
                exec_ms = (time.perf_counter() - t0) * 1000.0
            telemetry.add_cost("device_ms", exec_ms / n)
            telemetry.SHAPE_COSTS.observe(shape, exec_ms)
            jpegs = finish_sparse_to_jpegs(
                bufs, dims, H, W, quality, cap,
                lambda i: self._dense_coefficients(raw, stacked, qy,
                                                   qc, i),
                on_tile=self._early_settle_cb(group))
        self._count_batch(n)
        return jpegs

    async def close(self) -> None:
        await super().close()
        if self._pod is not None and jax_process_index() == 0:
            logger.info("pod leader: announcing shutdown")
            self._pod.announce(_POD_SHUTDOWN)
            logger.info("pod leader: shutdown announced")


def jax_process_index() -> int:
    import jax
    return jax.process_index()


def run_pod_follower(mesh: Mesh, jpeg_engine: str = "sparse") -> int:
    """Follower loop for non-leader pod processes.

    Receives each group the leader announces over the pod channel and
    runs the IDENTICAL sharded dispatch flow (including the cap-rescue
    re-dispatches, whose decisions are deterministic from the
    replicated wire totals), keeping the pod's SPMD launch sequence in
    lockstep.  Host-side JFIF finishing is skipped — followers produce
    no responses.  Returns the number of groups served; exits on the
    leader's shutdown announcement.
    """
    renderer = MeshRenderer(mesh, jpeg_engine=jpeg_engine)
    pod = renderer._pod or _PodChannel()
    groups = 0
    while True:
        kind, raw, stacked, quality, engine_id = pod.recv()
        if kind == _POD_SHUTDOWN:
            logger.info("pod follower: shutdown after %d groups", groups)
            return groups
        if kind == _POD_RENDER:
            renderer._render_wire(raw, stacked)
        else:
            H, W = raw.shape[-2:]
            if engine_id == 1:
                renderer._huffman_wire(raw, stacked, H, W, quality)
            else:
                renderer._sparse_wire(raw, stacked, H, W, quality)
        groups += 1
