"""Cross-host fleet federation: the Hazelcast analogue at rack scale.

The reference clusters its verticle fleet across JVMs/hosts with the
Vert.x event bus + Hazelcast (``-cluster``): every node joins one
cluster, the cluster's consistent view decides who consumes what, and
a joining node either agrees with that view or does not join
(PAPER.md L0/L5).  PR 8's :class:`~.fleet.FleetRouter` built the
single-host version — members, a consistent-hash shard map, drains and
failover — but membership lived in one process's config.  This module
makes the fleet span ``cluster.initialize()`` process/host boundaries:

* **Versioned fleet manifest** (:class:`FleetManifest`): the agreed
  membership document — member names, which HOST each lives on, the
  hash-ring seed and replica count, and a monotonically bumped
  ``shard epoch`` (version).  Its BLAKE2b digest over canonical JSON
  is the agreement token: two processes whose manifests share a digest
  compute IDENTICAL ring assignments for every ``plane_route_key``,
  fleet-wide, forever — the property the multihost smoke test pins
  against each peer's OWN ring math, not a local copy of it.
* **Join-time agreement** (``manifest_hello`` wire op): a process
  joining the federation sends its manifest to every remote member;
  digest match = agreed; a DIFFERENT shard epoch on either side is an
  ordered rollout in flight — the lower-epoch process records the
  newer manifest as PENDING (surfaced on /admin/federation and
  /readyz; its router keeps routing the epoch it was BUILT with until
  the operator rolls it — swapping the ring under a live router would
  silently diverge what we advertise from what we route, the exact
  split-brain this subsystem exists to prevent) — and same-epoch
  digest mismatch is a refused join (:class:`FederationError`).
* **Membership gossip** (``member_gossip`` wire op): hosts
  periodically swap member-health views (healthy / draining, newest
  timestamp wins) so cross-host drains and deaths propagate in one
  gossip interval instead of one failed request per shard.
* **Cross-host warm handoff** (``shard_transfer`` wire op): a drain
  whose successor lives on ANOTHER host ships the warm HBM bytes
  themselves over the v3 wire (ring-eligible bodies) — the successor
  cannot re-read this host's pixel store, so the hint-list prestage
  of the single-host drain would arrive cold.
* **Per-member device pinning** (:func:`partition_local_devices`): the
  combined role partitions ``jax.local_devices()`` across its local
  members, so the fleet's members own real device sets per host —
  previously only ``fleet.sockets`` sidecar topologies did.
* **Shard-aware prefetch** rides
  :meth:`~.fleet.FleetRouter.remote_prestage_for_route`: a predicted
  plane whose ring owner is remote stages on its OWNER's host.

Topology: each host runs the combined role with a ``federation:``
block naming every member fleet-wide; members whose ``host`` matches
this process's are built in-process (device-pinned lanes), the rest
are :class:`~.fleet.RemoteMember` handles over sidecar sockets.  All
hosts order members identically (manifest order), so bulk/mesh
pinning, drain victims and the ring agree everywhere.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .fleet import HashRing

logger = logging.getLogger(__name__)

# Probe keys every agreement exchange verifies against the peer's own
# ring math: deterministic, spread across the key space.  Golden
# assignments holding on these is the fleet-wide shard-map contract.
PROBE_KEYS = tuple(f"fed-probe-{i:03d}" for i in range(16))


class FederationError(RuntimeError):
    """A refused join: same shard epoch, different manifest digest —
    serving with a split-brain shard map would double-stage every
    plane and undo the fleet's whole point."""


@dataclass(frozen=True)
class MemberSpec:
    """One fleet member's identity in the manifest: its fleet name,
    the host that owns its devices, and — for members reached from
    OTHER hosts — the sidecar address (unix path or host:port)."""

    name: str
    host: str
    address: str = ""

    def to_json(self) -> dict:
        return {"name": self.name, "host": self.host,
                "address": self.address}

    @classmethod
    def from_json(cls, doc: dict) -> "MemberSpec":
        return cls(name=str(doc["name"]), host=str(doc["host"]),
                   address=str(doc.get("address") or ""))


class FleetManifest:
    """The versioned, digest-agreed membership document.

    ``version`` is the SHARD EPOCH: any membership/ring change bumps
    it, and agreement compares epochs before digests — a peer carrying
    a higher epoch wins (ordered rollout), equal epochs must match
    exactly.  The digest is BLAKE2b over canonical (sorted-key,
    compact) JSON, so agreement is byte-math, never trust.
    """

    def __init__(self, members: Sequence[MemberSpec], version: int = 1,
                 ring_seed: str = "", replicas: int = 64):
        members = tuple(members)
        if not members:
            raise ValueError("federation manifest needs >= 1 member")
        names = [m.name for m in members]
        if len(set(names)) != len(names):
            raise ValueError("duplicate member names in federation "
                             "manifest")
        if int(version) < 1:
            raise ValueError("federation shard epoch (version) must "
                             "be >= 1")
        self.members: Tuple[MemberSpec, ...] = members
        self.version = int(version)
        self.ring_seed = str(ring_seed)
        self.replicas = max(1, int(replicas))

    # ------------------------------------------------------------ identity

    def canonical_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "ring_seed": self.ring_seed,
            "replicas": self.replicas,
            "members": [m.to_json() for m in self.members],
        }, sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.blake2b(self.canonical_json().encode(),
                               digest_size=16).hexdigest()

    def to_json(self) -> dict:
        return json.loads(self.canonical_json())

    @classmethod
    def from_json(cls, doc: dict) -> "FleetManifest":
        return cls(
            members=[MemberSpec.from_json(m)
                     for m in (doc.get("members") or ())],
            version=int(doc.get("version", 1)),
            ring_seed=str(doc.get("ring_seed") or ""),
            replicas=int(doc.get("replicas", 64)))

    @classmethod
    def from_config(cls, fed) -> "FleetManifest":
        """Build from a validated ``federation:`` config block
        (``server.config.FederationConfig``)."""
        return cls(
            members=[MemberSpec(name=m["name"], host=m["host"],
                                address=m.get("address", ""))
                     for m in fed.members],
            version=fed.shard_epoch,
            ring_seed=fed.ring_seed,
            replicas=fed.hash_replicas)

    # -------------------------------------------------------------- lookup

    def names(self) -> List[str]:
        return [m.name for m in self.members]

    def host_of(self, name: str) -> str:
        """The host that owns the named member ("" when unknown) —
        the ``host`` dimension on ``fed.hop`` spans and decision
        records."""
        for m in self.members:
            if m.name == name:
                return m.host
        return ""

    def local_members(self, host: str) -> List[MemberSpec]:
        return [m for m in self.members if m.host == host]

    def remote_members(self, host: str) -> List[MemberSpec]:
        return [m for m in self.members if m.host != host]

    def ring(self) -> HashRing:
        """THE fleet-wide ring: every process with an agreeing manifest
        constructs this identically — golden ``plane_route_key``
        assignments hold across hosts by construction."""
        return HashRing(self.names(), replicas=self.replicas,
                        seed=self.ring_seed)

    def owners(self, keys: Sequence[str]) -> List[str]:
        ring = self.ring()
        return [ring.member(k) for k in keys]


# ------------------------------------------------- module-global install

# The process's ACTIVE manifest (the ``pressure.install`` idiom): the
# sidecar wire ops answer from here, so a sidecar process and its
# frontends share one source of truth per process.  The active
# manifest is immutable for the process life — the router, the
# prefetch routing and every staged plane's ownership were built from
# it; a newer epoch learned from a peer lands in ``_PENDING`` (loud on
# every status surface) and activates on the next process roll.
_MANIFEST: Optional[FleetManifest] = None
_PENDING: Optional[FleetManifest] = None
# This process's federation host identity (``federation.host``):
# stamped on hello/gossip answers so peers label clocks, spans and
# decision records without a reverse manifest lookup.
_SELF_HOST: str = ""


def install(manifest: FleetManifest,
            self_host: Optional[str] = None) -> None:
    global _MANIFEST, _SELF_HOST
    _MANIFEST = manifest
    from ..utils import decisions, telemetry
    if self_host is not None:
        _SELF_HOST = self_host
        # Decision records from this process are now attributable in
        # a merged fleet timeline.
        decisions.LEDGER.configure(host=self_host)
    telemetry.FEDERATION.set_manifest(manifest.version,
                                      len(manifest.members))
    decisions.record("epoch", "installed", detail={
        "epoch": manifest.version, "digest": manifest.digest(),
        "members": len(manifest.members)})
    logger.info("federation manifest installed: epoch %d, %d members, "
                "digest %s", manifest.version, len(manifest.members),
                manifest.digest())


def current() -> Optional[FleetManifest]:
    return _MANIFEST


def self_host() -> str:
    return _SELF_HOST


def remote_host_of(name: str) -> str:
    """The federation host of member ``name`` when it lives on a
    DIFFERENT host than this process — "" for same-host members,
    unknown names, or when no manifest is installed.  The gate the
    router's ``fed.hop`` spans key on: a federation hop is cross-host
    by definition, and single-host fleets must not pay for (or fake)
    one."""
    if _MANIFEST is None:
        return ""
    host = _MANIFEST.host_of(name)
    if not host or host == _SELF_HOST:
        return ""
    return host


def set_pending(manifest: FleetManifest) -> None:
    """Record a NEWER epoch learned from a peer.  Never activates in
    place: the live router routes the manifest it was built from, and
    agreement answers must keep describing what this process actually
    routes — the pending epoch is the operator's signal to roll."""
    global _PENDING
    if _PENDING is None or manifest.version > _PENDING.version:
        _PENDING = manifest
        from ..utils import decisions
        decisions.record("epoch", "pending", detail={
            "pending_epoch": manifest.version,
            "pending_digest": manifest.digest(),
            "active_epoch": _MANIFEST.version if _MANIFEST else None})
        logger.warning(
            "federation manifest epoch %d is pending (active epoch "
            "%s) — roll this process to activate it",
            manifest.version,
            _MANIFEST.version if _MANIFEST else None)


def pending() -> Optional[FleetManifest]:
    return _PENDING


def uninstall() -> None:
    global _MANIFEST, _PENDING, _SELF_HOST
    _MANIFEST = None
    _PENDING = None
    _SELF_HOST = ""
    _HOST_CLOCKS.clear()


# ----------------------------------------------------- cross-host clocks

# host -> {"offset": local_perf - remote_perf, "rtt_ms", "ts"}.  The
# same midpoint anchoring the sidecar ``hello`` does per connection,
# lifted to per-HOST: every ``manifest_hello`` / ``member_gossip``
# answer carries the peer's ``time.perf_counter()``, the caller takes
# the send/recv midpoint as the instant that clock was read, and the
# difference maps remote span anchors into this process's timeline.
# Re-derived on every exchange, so drift is bounded by the gossip
# interval.
_HOST_CLOCKS: Dict[str, dict] = {}


def record_host_clock(host: str, t_send: float, t_recv: float,
                      remote_clock) -> Optional[float]:
    """Derive and store the per-host clock offset from one exchange.
    Returns the offset, or None when the peer answered without the
    anchor field (an older build — callers degrade to unanchored
    spans, never error)."""
    if not host or remote_clock is None:
        return None
    try:
        remote = float(remote_clock)
    except (TypeError, ValueError):
        return None
    offset = (t_send + t_recv) / 2.0 - remote
    _HOST_CLOCKS[str(host)[:64]] = {
        "offset": offset,
        "rtt_ms": round((t_recv - t_send) * 1000.0, 3),
        "ts": time.time(),
    }
    return offset


def host_clock_offset(host: str) -> Optional[float]:
    doc = _HOST_CLOCKS.get(host)
    return doc["offset"] if doc else None


def host_clocks() -> Dict[str, dict]:
    return {k: dict(v) for k, v in _HOST_CLOCKS.items()}


def anchor_remote_time(host: str, remote_t,
                       window: Tuple[float, float]) -> Optional[float]:
    """Map a remote ``perf_counter`` instant into this process's
    timeline, CLAMPED into ``window`` (the local [send, recv] bracket
    of the exchange that carried it) — the sidecar ``_graft_response``
    contract: a skewed or stale offset may place the child oddly
    WITHIN its parent's window, never outside it.  None when the host
    has no derived offset (unanchored degrade)."""
    off = host_clock_offset(host)
    if off is None or remote_t is None:
        return None
    try:
        t = float(remote_t) + off
    except (TypeError, ValueError):
        return None
    lo, hi = window
    return min(max(t, lo), hi)


def reset_clocks() -> None:
    """Test isolation."""
    _HOST_CLOCKS.clear()


# ------------------------------------------------------ wire-op handlers

def handle_manifest_hello(header: dict) -> dict:
    """Server side of the ``manifest_hello`` op (runs in the sidecar's
    request handler).  Compares the joiner's manifest against this
    process's installed one and answers the agreement verdict plus —
    when probe keys rode along — this process's OWN ring owner for
    each (the cross-process golden-assignment check).

    No manifest installed = a legacy / un-federated process: answers
    ``{"enabled": false}`` and the coordinator degrades (counts
    ``legacy``, serves without federation features on that peer)."""
    from ..utils import decisions, telemetry
    mine = _MANIFEST
    if mine is None:
        return {"enabled": False}
    doc: dict = {
        "enabled": True,
        "version": mine.version,
        "digest": mine.digest(),
        # Clock anchor (the sidecar ``hello`` idiom, per HOST): the
        # caller midpoints its send/recv around this read and derives
        # the offset that grafts our spans onto its waterfalls.
        "clock": time.perf_counter(),
        "host": _SELF_HOST,
    }
    theirs_doc = header.get("manifest")
    if isinstance(theirs_doc, dict):
        try:
            theirs = FleetManifest.from_json(theirs_doc)
        except (KeyError, TypeError, ValueError):
            theirs = None
        if theirs is None:
            doc["agreed"] = False
            doc["reason"] = "malformed"
            telemetry.FEDERATION.count_agreement("split-brain")
            decisions.record("manifest", "split-brain",
                             detail={"reason": "malformed"})
        elif theirs.digest() == mine.digest():
            doc["agreed"] = True
            telemetry.FEDERATION.count_agreement("agreed")
            decisions.record("manifest", "agreed",
                             detail={"epoch": mine.version})
        elif theirs.version > mine.version:
            # The joiner carries a NEWER shard epoch: a rolling config
            # update reached it first.  Record it PENDING — this
            # process keeps routing the epoch its router was built
            # from until the operator rolls it; answering "agreed" to
            # a map we are not routing would be the silent split-brain
            # this op exists to refuse.
            set_pending(theirs)
            doc["agreed"] = False
            doc["reason"] = "pending"
            doc["pending_version"] = theirs.version
            telemetry.FEDERATION.count_agreement("pending")
            decisions.record("manifest", "pending", detail={
                "epoch": mine.version,
                "pending_epoch": theirs.version})
        elif theirs.version < mine.version:
            # The joiner is behind: send ours so IT records the
            # pending epoch and its operator rolls it.
            doc["agreed"] = False
            doc["reason"] = "stale-epoch"
            doc["manifest"] = mine.to_json()
            telemetry.FEDERATION.count_agreement("stale")
            decisions.record("manifest", "stale", detail={
                "epoch": mine.version,
                "joiner_epoch": theirs.version})
        else:
            doc["agreed"] = False
            doc["reason"] = "split-brain"
            telemetry.FEDERATION.count_agreement("split-brain")
            decisions.record("manifest", "split-brain",
                             detail={"epoch": mine.version})
    probe_keys = header.get("probe_keys")
    if isinstance(probe_keys, list) and probe_keys:
        doc["owners"] = mine.owners([str(k) for k in probe_keys[:64]])
    return doc


# Gossip view: member name -> {"healthy": bool, "draining": bool,
# "ts": float} — wall-clock stamped, newest observation wins on merge.
_GOSSIP_VIEW: Dict[str, dict] = {}


def local_view(router, self_host: str = "") -> Dict[str, dict]:
    """This process's authoritative member observations: LOCAL members'
    health/drain state straight from the router (a host knows its own
    members best), stamped now."""
    mine = _MANIFEST
    view: Dict[str, dict] = {}
    if router is None or mine is None:
        return view
    now = time.time()
    local = {m.name for m in mine.local_members(self_host)} \
        if self_host else set(router.order)
    for name in router.order:
        if name not in local:
            continue
        member = router.members.get(name)
        if member is None:
            continue
        obs = {"healthy": bool(member.healthy),
               "draining": bool(member.draining),
               "ts": now}
        # Hot-key posture rides the gossip wire: how many promoted
        # routes this member serves replicas for (duck-typed — drill
        # routers may predate the hot tier), so peers can see a storm
        # concentrating on one host before its queues say so.
        hot_fn = getattr(router, "hot_owned", None)
        if hot_fn is not None:
            try:
                hot = int(hot_fn(name))
            except Exception:
                hot = 0
            if hot:
                obs["hot"] = hot
        view[name] = obs
    return view


def merge_view(view: dict) -> Dict[str, dict]:
    """Fold a peer's view into the process gossip state (newest ``ts``
    per member wins) and return the merged state.

    Names are validated against the ACTIVE manifest (the socket is
    unauthenticated-by-design like every sidecar op, and the merged
    view is re-broadcast in every gossip answer — an unvalidated name
    would live in this module-global forever and propagate
    fleet-wide), so the view is bounded by the membership.  With no
    manifest installed (bare tests), a hard cap stands in."""
    mine = _MANIFEST
    known = set(mine.names()) if mine is not None else None
    if isinstance(view, dict):
        for name, obs in view.items():
            if not isinstance(obs, dict):
                continue
            # Store and look up under the SAME (bounded) key, or an
            # over-long name would bypass the newest-ts merge.
            name = str(name)[:64]
            if known is not None:
                if name not in known:
                    continue
            elif name not in _GOSSIP_VIEW \
                    and len(_GOSSIP_VIEW) >= 256:
                continue
            held = _GOSSIP_VIEW.get(name)
            if held is None or float(obs.get("ts", 0)) \
                    > float(held.get("ts", 0)):
                stored = {
                    "healthy": bool(obs.get("healthy", True)),
                    "draining": bool(obs.get("draining", False)),
                    "ts": float(obs.get("ts", 0)),
                }
                try:
                    hot = int(obs.get("hot", 0))
                except (TypeError, ValueError):
                    hot = 0
                if hot > 0:
                    stored["hot"] = min(hot, 1 << 20)
                _GOSSIP_VIEW[name] = stored
    return dict(_GOSSIP_VIEW)


def handle_member_gossip(header: dict) -> dict:
    """Server side of ``member_gossip``: merge the sender's view, answer
    ours + the manifest identity (drift between gossiping peers is a
    mismatch the coordinator surfaces).  The answer also carries this
    host's clock anchor (re-derived offset every round — reconnect
    recovery for free) and its ``SloEngine`` window buckets, so the
    gossip wire doubles as the fleet-SLO export path with no extra
    round trips."""
    from ..utils import telemetry
    mine = _MANIFEST
    merged = merge_view(header.get("view") or {})
    doc: dict = {"enabled": mine is not None, "view": merged}
    if mine is not None:
        doc["version"] = mine.version
        doc["digest"] = mine.digest()
        doc["clock"] = time.perf_counter()
        doc["host"] = _SELF_HOST
        slo = telemetry.SLO.export_buckets()
        if slo:
            doc["slo"] = slo
    return doc


def reset_gossip() -> None:
    """Test isolation."""
    _GOSSIP_VIEW.clear()


# ------------------------------------------------------- device pinning

def partition_local_devices(n_members: int,
                            devices: Optional[Sequence] = None
                            ) -> List[list]:
    """Partition this process's devices across ``n_members`` local
    members — contiguous, deterministic, remainder to the earliest
    members (so member 0, the mesh/bulk lane, is never the short one).
    Fewer devices than members leaves the tail members unpinned
    (process default device) rather than oversubscribing one chip with
    two members' pins."""
    if n_members < 1:
        raise ValueError("partition needs >= 1 member")
    if devices is None:
        import jax
        devices = jax.local_devices()
    devices = list(devices)
    n_dev = len(devices)
    if n_dev == 0:
        return [[] for _ in range(n_members)]
    base, extra = divmod(n_dev, n_members)
    out: List[list] = []
    i = 0
    for m in range(n_members):
        take = base + (1 if m < extra else 0)
        out.append(devices[i:i + take])
        i += take
    return out


# --------------------------------------------------------- construction

def build_federated_members(config, base_services, manifest,
                            client_factory, self_host: str):
    """The federated member list, in MANIFEST order: members on THIS
    host are in-process device-pinned lanes (the combined role), the
    rest are :class:`~.fleet.RemoteMember` handles over their sidecar
    addresses.  Every host building from an agreeing manifest produces
    the same order, so ring arcs, bulk pinning (order[0]) and drain
    victims agree fleet-wide.

    The FIRST local member wraps the base service stack (its renderer
    may be the lockstep ``MeshRenderer`` — ``parallel.serve`` marks it
    ``lockstep = True`` and it must stay a single lane, so device
    partitioning pins but never splits it)."""
    from .fleet import RemoteMember, build_local_members

    local_specs = manifest.local_members(self_host)
    if not local_specs:
        raise ValueError(
            f"federation.host {self_host!r} owns no manifest member — "
            f"a combined process must serve at least one")
    for spec in manifest.remote_members(self_host):
        if not spec.address:
            raise ValueError(
                f"federation member {spec.name!r} on host "
                f"{spec.host!r} has no address — this host "
                f"({self_host!r}) cannot reach it")
    if getattr(base_services.renderer, "lockstep", False) \
            and manifest.members[0].host != self_host:
        # The lockstep MeshRenderer lives HERE, but bulk/mesh work
        # pins to the fleet's first member (manifest order[0]) — on
        # another host.  Legal (that host serves bulk), but almost
        # certainly a mis-ordered manifest: the mesh host should come
        # first so full-plane jobs run on the mesh.
        logger.warning(
            "this host (%s) runs the lockstep mesh renderer but "
            "manifest member 0 (%s) lives on host %s — bulk/mesh "
            "work will pin there; list the mesh host's members first",
            self_host, manifest.members[0].name,
            manifest.members[0].host)
    device_sets = partition_local_devices(len(local_specs))
    locals_built = build_local_members(
        config, base_services, len(local_specs),
        device_sets=device_sets)
    by_name = {}
    for spec, built in zip(local_specs, locals_built):
        built.name = spec.name
        by_name[spec.name] = built
    members = []
    for spec in manifest.members:
        if spec.name in by_name:
            members.append(by_name[spec.name])
        else:
            members.append(RemoteMember(
                spec.name, client_factory(spec.address),
                down_cooldown_s=config.fleet.down_cooldown_s))
    return members


# ---------------------------------------------------------- coordinator

class FederationCoordinator:
    """The join/gossip driver for one process's federated router.

    ``agree()`` runs once at startup (and on demand): exchanges
    manifests with every remote member, verifies golden probe-key
    owners against each peer's own ring math, adopts newer epochs, and
    raises :class:`FederationError` on split-brain.  ``run()`` is the
    gossip tick loop — cross-host drain/death propagation plus
    manifest-drift detection."""

    def __init__(self, manifest: FleetManifest, self_host: str,
                 router=None, gossip_interval_s: float = 5.0):
        self.manifest = manifest
        self.self_host = self_host
        self.router = router
        self.gossip_interval_s = max(0.2, float(gossip_interval_s))
        # name -> verdict of the last agreement exchange.
        self.agreement: Dict[str, str] = {}
        self.last_gossip: Dict[str, str] = {}

    def _remote_handles(self) -> List:
        if self.router is None:
            return []
        return [self.router.members[n] for n in self.router.order
                if getattr(self.router.members[n], "remote", False)]

    async def agree(self, strict: bool = True) -> Dict[str, str]:
        """One agreement round with every remote member.  Returns the
        per-member verdict map; ``strict`` raises on split-brain only
        — every rolling-rollout verdict is tolerated and LOUD:

        * ``agreed`` — digest match, probe owners verified against
          the peer's own ring math;
        * ``pending`` — the peer is on an OLDER epoch and recorded
          ours as pending (its operator rolls it; a mid-roll fleet
          serves with both maps, each process honest about its own);
        * ``stale`` — WE are on the older epoch: the peer's newer
          manifest is recorded pending here, /readyz and
          /admin/federation say so until this process is rolled;
        * ``unreachable`` / ``legacy`` — tolerated (a dead or
          un-federated host must not block the survivors' boot);
        * ``split-brain`` — same epoch, different membership (or a
          peer whose ring math disagrees with its own digest): a
          refused join under ``strict``."""
        from ..utils import telemetry
        doc = self.manifest.to_json()
        my_owners = self.manifest.owners(list(PROBE_KEYS))
        verdicts: Dict[str, str] = {}
        for member in self._remote_handles():
            host = self.manifest.host_of(member.name)
            t_send = time.perf_counter()
            resp = await member.manifest_hello(
                doc, probe_keys=list(PROBE_KEYS))
            t_recv = time.perf_counter()
            telemetry.record_span(
                "fed.hop", t_send, (t_recv - t_send) * 1000.0,
                host=host, member=member.name, kind="hello")
            if isinstance(resp, dict):
                # Per-host clock anchor from the send/recv midpoint —
                # the sidecar hello idiom.  A peer without the field
                # (older build) simply derives no offset: its spans
                # stay unanchored, nothing errors.
                record_host_clock(resp.get("host") or host,
                                  t_send, t_recv, resp.get("clock"))
            if resp is None:
                verdicts[member.name] = "unreachable"
                telemetry.FEDERATION.count_agreement("unreachable")
                continue
            if not resp.get("enabled"):
                verdicts[member.name] = "legacy"
                telemetry.FEDERATION.count_agreement("legacy")
                continue
            if resp.get("agreed"):
                # Digest agreement is necessary; the probe owners are
                # the sufficiency check — the peer's OWN ring hashed
                # every probe key to the member we did.
                owners = resp.get("owners")
                if owners is not None and owners != my_owners:
                    verdicts[member.name] = "split-brain"
                    telemetry.FEDERATION.count_agreement("split-brain")
                    continue
                verdicts[member.name] = "agreed"
                telemetry.FEDERATION.count_agreement("agreed")
                continue
            reason = resp.get("reason")
            if reason == "pending":
                # The peer (older epoch) recorded OUR manifest as its
                # pending epoch — a rollout in flight, its side.
                verdicts[member.name] = "pending"
                telemetry.FEDERATION.count_agreement("pending")
                continue
            if reason == "stale-epoch" \
                    and isinstance(resp.get("manifest"), dict):
                # WE are the older epoch: record the newer manifest
                # pending and keep serving the map this router was
                # BUILT with — activating mid-flight would diverge
                # what we advertise from what we route.
                try:
                    newer = FleetManifest.from_json(resp["manifest"])
                except (KeyError, TypeError, ValueError):
                    verdicts[member.name] = "split-brain"
                    telemetry.FEDERATION.count_agreement("split-brain")
                    continue
                if newer.version > self.manifest.version:
                    set_pending(newer)
                    verdicts[member.name] = "stale"
                    telemetry.FEDERATION.count_agreement("stale")
                    continue
                verdicts[member.name] = "split-brain"
                telemetry.FEDERATION.count_agreement("split-brain")
            else:
                verdicts[member.name] = "split-brain"
                telemetry.FEDERATION.count_agreement("split-brain")
        from ..utils import decisions
        for name, verdict in verdicts.items():
            decisions.record("manifest", verdict, member=name, detail={
                "host": self.manifest.host_of(name),
                "epoch": self.manifest.version})
        self.agreement = verdicts
        split = [n for n, v in verdicts.items() if v == "split-brain"]
        if split and strict:
            raise FederationError(
                f"federation manifest split-brain with {split}: same "
                f"shard epoch, different membership — refusing to "
                f"serve a forked shard map (bump federation.shard-"
                f"epoch with the corrected member list)")
        return verdicts

    async def gossip_once(self) -> Dict[str, str]:
        """One gossip round: push our local-member view to every
        remote member, merge their answers, and reflect what their
        hosts report about THEIR members onto our router handles —
        a drain ordered on host B walks routing off B's members here
        within one interval, before any request fails over."""
        from ..utils import telemetry
        view = local_view(self.router, self.self_host)
        merge_view(view)
        outcome: Dict[str, str] = {}
        my_digest = self.manifest.digest()
        # Our own host's window buckets join the fleet aggregate the
        # same way every peer's do — one ingest path, no special case.
        telemetry.FED_SLO.ingest(self.self_host,
                                 telemetry.SLO.export_buckets())
        for member in self._remote_handles():
            host = self.manifest.host_of(member.name)
            t_send = time.perf_counter()
            resp = await member.member_gossip(view)
            t_recv = time.perf_counter()
            telemetry.record_span(
                "fed.hop", t_send, (t_recv - t_send) * 1000.0,
                host=host, member=member.name, kind="gossip")
            if isinstance(resp, dict):
                # Re-derive the per-host clock anchor every round:
                # reconnects and drift heal within one interval.
                record_host_clock(resp.get("host") or host,
                                  t_send, t_recv, resp.get("clock"))
                telemetry.FED_SLO.ingest(resp.get("host") or host,
                                         resp.get("slo"))
            if resp is None or not resp.get("enabled", True):
                outcome[member.name] = "unreachable"
                telemetry.FEDERATION.count_gossip("unreachable")
                continue
            their_digest = resp.get("digest")
            pend = pending()
            if their_digest not in (None, my_digest):
                if pend is not None \
                        and their_digest == pend.digest():
                    # Known rollout in flight: the peer already runs
                    # the epoch we hold PENDING — not drift, just the
                    # roll this process is still waiting for.
                    pass
                else:
                    outcome[member.name] = "mismatch"
                    telemetry.FEDERATION.count_gossip("mismatch")
                    logger.warning(
                        "federation manifest drift detected gossiping "
                        "with %s (their digest %s != ours %s)",
                        member.name, their_digest, my_digest)
                    continue
            merged = merge_view(resp.get("view") or {})
            self._apply_remote_view(merged)
            outcome[member.name] = "ok"
            telemetry.FEDERATION.count_gossip("ok")
        from ..utils import decisions
        for name, verdict in outcome.items():
            if self.last_gossip.get(name) != verdict:
                # Convergence TRANSITIONS only (the flight-ring
                # posture): a steady fleet gossips every few seconds
                # and must not churn the ledger ring with "still ok".
                decisions.record("gossip", verdict, member=name,
                                 detail={
                                     "host": self.manifest.host_of(
                                         name)})
        self.last_gossip = outcome
        return outcome

    def _apply_remote_view(self, merged: Dict[str, dict]) -> None:
        """Reflect peers' authoritative observations of THEIR OWN
        members onto our remote handles: drain state propagates both
        ways (set and cleared) UNDER the ``gossip`` intent only —
        drains THIS process ordered (operator ``/admin/drain``, an
        autoscaler scale-down holding the member in ``_scaled_down``)
        are this router's own decisions and must never be reverted by
        a peer that simply was not told about them.  Down-ness only
        marks (re-admission stays with the served-call/cooldown
        machinery — gossip must not revive a member its own host no
        longer vouches for)."""
        if self.router is None:
            return
        local = {m.name for m in
                 self.manifest.local_members(self.self_host)}
        for name, obs in merged.items():
            if name in local or name not in self.router.members:
                continue
            member = self.router.members[name]
            intent = getattr(member, "drain_intent", None)
            if member.draining and intent not in (None, "gossip"):
                # Our own drain (operator/autoscale): gossip is not
                # allowed to undo it — host B reporting "b1 not
                # draining" just means B was never told.
                continue
            draining = bool(obs.get("draining"))
            if member.draining != draining:
                member.draining = draining
                member.drain_intent = "gossip" if draining else None
                from ..utils import telemetry
                telemetry.FLIGHT.record("federation.gossip-drain",
                                        member=name,
                                        draining=draining)
            if not obs.get("healthy", True) and member.healthy:
                member.mark_down()

    def status(self) -> dict:
        """The /admin/federation + /readyz annotation document."""
        doc = {
            "host": self.self_host,
            "epoch": self.manifest.version,
            "digest": self.manifest.digest(),
            "members": [m.to_json() for m in self.manifest.members],
            "agreement": dict(self.agreement),
            "gossip": dict(self.last_gossip),
            "view": dict(_GOSSIP_VIEW),
            "clocks": host_clocks(),
        }
        pend = pending()
        if pend is not None and pend.version > self.manifest.version:
            # The operator's roll signal: a newer epoch exists in the
            # fleet and activates here on the next process restart.
            doc["pending_epoch"] = pend.version
            doc["pending_digest"] = pend.digest()
        return doc

    def summary(self) -> str:
        agreed = sum(1 for v in self.agreement.values()
                     if v == "agreed")
        line = (f"epoch {self.manifest.version}, "
                f"{agreed}/{max(1, len(self.agreement))} peers agreed")
        pend = pending()
        if pend is not None and pend.version > self.manifest.version:
            line += f" (epoch {pend.version} pending roll)"
        return line

    async def run(self) -> None:
        """Gossip tick loop (the governor idiom; the app's robustness
        startup hook owns the task)."""
        while True:
            await asyncio.sleep(self.gossip_interval_s)
            try:
                await self.gossip_once()
            except Exception:
                logger.warning("federation gossip round failed",
                               exc_info=True)
