"""Cross-host fleet federation: the Hazelcast analogue at rack scale.

The reference clusters its verticle fleet across JVMs/hosts with the
Vert.x event bus + Hazelcast (``-cluster``): every node joins one
cluster, the cluster's consistent view decides who consumes what, and
a joining node either agrees with that view or does not join
(PAPER.md L0/L5).  PR 8's :class:`~.fleet.FleetRouter` built the
single-host version — members, a consistent-hash shard map, drains and
failover — but membership lived in one process's config.  This module
makes the fleet span ``cluster.initialize()`` process/host boundaries:

* **Versioned fleet manifest** (:class:`FleetManifest`): the agreed
  membership document — member names, which HOST each lives on, the
  hash-ring seed and replica count, and a monotonically bumped
  ``shard epoch`` (version).  Its BLAKE2b digest over canonical JSON
  is the agreement token: two processes whose manifests share a digest
  compute IDENTICAL ring assignments for every ``plane_route_key``,
  fleet-wide, forever — the property the multihost smoke test pins
  against each peer's OWN ring math, not a local copy of it.
* **Join-time agreement** (``manifest_hello`` wire op): a process
  joining the federation sends its manifest to every remote member;
  digest match = agreed; a DIFFERENT shard epoch on either side is an
  ordered rollout in flight — the lower-epoch process records the
  newer manifest as PENDING (surfaced on /admin/federation and
  /readyz; its router keeps routing the epoch it was BUILT with until
  the operator rolls it — swapping the ring under a live router would
  silently diverge what we advertise from what we route, the exact
  split-brain this subsystem exists to prevent) — and same-epoch
  digest mismatch is a refused join (:class:`FederationError`).
* **Membership gossip** (``member_gossip`` wire op): hosts
  periodically swap member-health views (healthy / draining,
  ``(incarnation, seq)``-versioned per observation — a host's fresh
  state about its OWN members always supersedes stale claims, and
  wall-clock skew can never resurrect a ghost) so cross-host drains
  and deaths propagate in one gossip interval instead of one failed
  request per shard.
* **Quorum membership** (:class:`QuorumTracker`): a host's view is
  QUORATE while it exchanges gossip with a strict majority of
  manifest hosts within ``federation.suspect-after-s``; a minority
  island FENCES — it keeps serving reads it can prove from its own
  shards/byte tier but refuses shard adoption, byte-tier write-back
  authority changes, hot-key promotions and autoscaler membership
  transitions until the partition heals.
* **Orchestrated epoch rolls** (``epoch_propose`` / ``epoch_commit``
  wire ops): a coordinator proposes the next manifest to every host,
  collects a strict majority of acks, then commits — idempotent and
  crash-resumable from the pending-manifest state; routers swap rings
  only at commit, never mid-flight.
* **Cross-host warm handoff** (``shard_transfer`` wire op): a drain
  whose successor lives on ANOTHER host ships the warm HBM bytes
  themselves over the v3 wire (ring-eligible bodies) — the successor
  cannot re-read this host's pixel store, so the hint-list prestage
  of the single-host drain would arrive cold.
* **Per-member device pinning** (:func:`partition_local_devices`): the
  combined role partitions ``jax.local_devices()`` across its local
  members, so the fleet's members own real device sets per host —
  previously only ``fleet.sockets`` sidecar topologies did.
* **Shard-aware prefetch** rides
  :meth:`~.fleet.FleetRouter.remote_prestage_for_route`: a predicted
  plane whose ring owner is remote stages on its OWNER's host.

Topology: each host runs the combined role with a ``federation:``
block naming every member fleet-wide; members whose ``host`` matches
this process's are built in-process (device-pinned lanes), the rest
are :class:`~.fleet.RemoteMember` handles over sidecar sockets.  All
hosts order members identically (manifest order), so bulk/mesh
pinning, drain victims and the ring agree everywhere.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .fleet import HashRing

logger = logging.getLogger(__name__)

# Probe keys every agreement exchange verifies against the peer's own
# ring math: deterministic, spread across the key space.  Golden
# assignments holding on these is the fleet-wide shard-map contract.
PROBE_KEYS = tuple(f"fed-probe-{i:03d}" for i in range(16))


class FederationError(RuntimeError):
    """A refused join: same shard epoch, different manifest digest —
    serving with a split-brain shard map would double-stage every
    plane and undo the fleet's whole point."""


@dataclass(frozen=True)
class MemberSpec:
    """One fleet member's identity in the manifest: its fleet name,
    the host that owns its devices, and — for members reached from
    OTHER hosts — the sidecar address (unix path or host:port)."""

    name: str
    host: str
    address: str = ""

    def to_json(self) -> dict:
        return {"name": self.name, "host": self.host,
                "address": self.address}

    @classmethod
    def from_json(cls, doc: dict) -> "MemberSpec":
        return cls(name=str(doc["name"]), host=str(doc["host"]),
                   address=str(doc.get("address") or ""))


class FleetManifest:
    """The versioned, digest-agreed membership document.

    ``version`` is the SHARD EPOCH: any membership/ring change bumps
    it, and agreement compares epochs before digests — a peer carrying
    a higher epoch wins (ordered rollout), equal epochs must match
    exactly.  The digest is BLAKE2b over canonical (sorted-key,
    compact) JSON, so agreement is byte-math, never trust.
    """

    def __init__(self, members: Sequence[MemberSpec], version: int = 1,
                 ring_seed: str = "", replicas: int = 64):
        members = tuple(members)
        if not members:
            raise ValueError("federation manifest needs >= 1 member")
        names = [m.name for m in members]
        if len(set(names)) != len(names):
            raise ValueError("duplicate member names in federation "
                             "manifest")
        if int(version) < 1:
            raise ValueError("federation shard epoch (version) must "
                             "be >= 1")
        self.members: Tuple[MemberSpec, ...] = members
        self.version = int(version)
        self.ring_seed = str(ring_seed)
        self.replicas = max(1, int(replicas))

    # ------------------------------------------------------------ identity

    def canonical_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "ring_seed": self.ring_seed,
            "replicas": self.replicas,
            "members": [m.to_json() for m in self.members],
        }, sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.blake2b(self.canonical_json().encode(),
                               digest_size=16).hexdigest()

    def to_json(self) -> dict:
        return json.loads(self.canonical_json())

    @classmethod
    def from_json(cls, doc: dict) -> "FleetManifest":
        return cls(
            members=[MemberSpec.from_json(m)
                     for m in (doc.get("members") or ())],
            version=int(doc.get("version", 1)),
            ring_seed=str(doc.get("ring_seed") or ""),
            replicas=int(doc.get("replicas", 64)))

    @classmethod
    def from_config(cls, fed) -> "FleetManifest":
        """Build from a validated ``federation:`` config block
        (``server.config.FederationConfig``)."""
        return cls(
            members=[MemberSpec(name=m["name"], host=m["host"],
                                address=m.get("address", ""))
                     for m in fed.members],
            version=fed.shard_epoch,
            ring_seed=fed.ring_seed,
            replicas=fed.hash_replicas)

    # -------------------------------------------------------------- lookup

    def names(self) -> List[str]:
        return [m.name for m in self.members]

    def host_of(self, name: str) -> str:
        """The host that owns the named member ("" when unknown) —
        the ``host`` dimension on ``fed.hop`` spans and decision
        records."""
        for m in self.members:
            if m.name == name:
                return m.host
        return ""

    def local_members(self, host: str) -> List[MemberSpec]:
        return [m for m in self.members if m.host == host]

    def remote_members(self, host: str) -> List[MemberSpec]:
        return [m for m in self.members if m.host != host]

    def ring(self) -> HashRing:
        """THE fleet-wide ring: every process with an agreeing manifest
        constructs this identically — golden ``plane_route_key``
        assignments hold across hosts by construction."""
        return HashRing(self.names(), replicas=self.replicas,
                        seed=self.ring_seed)

    def owners(self, keys: Sequence[str]) -> List[str]:
        ring = self.ring()
        return [ring.member(k) for k in keys]


# ------------------------------------------------- module-global install

# The process's ACTIVE manifest (the ``pressure.install`` idiom): the
# sidecar wire ops answer from here, so a sidecar process and its
# frontends share one source of truth per process.  The active
# manifest is immutable for the process life — the router, the
# prefetch routing and every staged plane's ownership were built from
# it; a newer epoch learned from a peer lands in ``_PENDING`` (loud on
# every status surface) and activates on the next process roll.
_MANIFEST: Optional[FleetManifest] = None
_PENDING: Optional[FleetManifest] = None
# This process's federation host identity (``federation.host``):
# stamped on hello/gossip answers so peers label clocks, spans and
# decision records without a reverse manifest lookup.
_SELF_HOST: str = ""
# This process's gossip INCARNATION (the SWIM idiom): bumped past
# wall-clock seconds at install so a restarted host's fresh state
# versions ABOVE its pre-crash ghost, and bumped past any stale
# higher-versioned claim a peer holds about our own members
# (self-refutation in local_view).  ``_LOCAL_SEQ`` bumps on every
# local member state change; observations carry ``(inc, seq)`` and
# merges compare those, never wall clocks.
_INCARNATION: int = 0
_LOCAL_SEQ: int = 0
# member name -> last (healthy, draining) this process published, so
# local_view knows when to bump _LOCAL_SEQ.
_LOCAL_LAST: Dict[str, tuple] = {}
# The router swap hook (set by the serving layer): called with the
# newly-activated manifest at epoch COMMIT — the only instant a live
# ring may change.
_ROLL_HOOK = None
_QUORUM: Optional["QuorumTracker"] = None


def install(manifest: FleetManifest,
            self_host: Optional[str] = None) -> None:
    global _MANIFEST, _SELF_HOST, _INCARNATION
    _MANIFEST = manifest
    # Strictly increasing across restarts AND within a process (the
    # max() arm covers frozen/mocked clocks): a rejoining host's first
    # gossip supersedes every pre-crash observation of its members.
    _INCARNATION = max(_INCARNATION + 1, int(time.time()))
    from ..utils import decisions, telemetry
    if self_host is not None:
        _SELF_HOST = self_host
        # Decision records from this process are now attributable in
        # a merged fleet timeline.
        decisions.LEDGER.configure(host=self_host)
    telemetry.FEDERATION.set_manifest(manifest.version,
                                      len(manifest.members))
    decisions.record("epoch", "installed", detail={
        "epoch": manifest.version, "digest": manifest.digest(),
        "members": len(manifest.members)})
    if _QUORUM is not None:
        _QUORUM.set_manifest(manifest)
    logger.info("federation manifest installed: epoch %d, %d members, "
                "digest %s", manifest.version, len(manifest.members),
                manifest.digest())


def current() -> Optional[FleetManifest]:
    return _MANIFEST


def self_host() -> str:
    return _SELF_HOST


def remote_host_of(name: str) -> str:
    """The federation host of member ``name`` when it lives on a
    DIFFERENT host than this process — "" for same-host members,
    unknown names, or when no manifest is installed.  The gate the
    router's ``fed.hop`` spans key on: a federation hop is cross-host
    by definition, and single-host fleets must not pay for (or fake)
    one."""
    if _MANIFEST is None:
        return ""
    host = _MANIFEST.host_of(name)
    if not host or host == _SELF_HOST:
        return ""
    return host


def set_pending(manifest: FleetManifest) -> None:
    """Record a NEWER epoch learned from a peer.  Never activates in
    place: the live router routes the manifest it was built from, and
    agreement answers must keep describing what this process actually
    routes — the pending epoch is the operator's signal to roll."""
    global _PENDING
    if _PENDING is None or manifest.version > _PENDING.version:
        _PENDING = manifest
        from ..utils import decisions
        decisions.record("epoch", "pending", detail={
            "pending_epoch": manifest.version,
            "pending_digest": manifest.digest(),
            "active_epoch": _MANIFEST.version if _MANIFEST else None})
        logger.warning(
            "federation manifest epoch %d is pending (active epoch "
            "%s) — roll this process to activate it",
            manifest.version,
            _MANIFEST.version if _MANIFEST else None)


def pending() -> Optional[FleetManifest]:
    return _PENDING


def uninstall() -> None:
    global _MANIFEST, _PENDING, _SELF_HOST, _ROLL_HOOK, _QUORUM
    global _LOCAL_SEQ
    _MANIFEST = None
    _PENDING = None
    _SELF_HOST = ""
    _ROLL_HOOK = None
    _QUORUM = None
    _LOCAL_SEQ = 0
    _LOCAL_LAST.clear()
    _HOST_CLOCKS.clear()


# ----------------------------------------------------- quorum membership

class QuorumTracker:
    """Strict-majority membership over the manifest's DISTINCT hosts.

    A host is *heard* while its last successful gossip/hello exchange
    (either direction) is younger than ``suspect_after_s``; the view
    is QUORATE while ``heard hosts (self included)`` is a strict
    majority of manifest hosts.  Losing quorum FENCES this process:
    :meth:`allow` refuses (and counts) every state-changing action in
    :data:`ACTIONS` — reads this host can prove from its own shards
    keep serving — and regaining quorum restores.  Transitions land in
    the decision ledger (kind=``quorum``, verdicts
    ``fenced``/``restored``) and on the flight ring
    (``quorum.fence``/``quorum.restore``); /readyz and
    /admin/federation annotate from :meth:`status`.

    Liveness is tracked on ``time.monotonic()`` — the whole point is
    immunity to wall clocks.  Remote hosts start as heard-now
    (innocent until ``suspect_after_s`` of silence): fencing a booting
    majority host for the crime of not having gossiped yet would turn
    every cold start into an outage.  Single-host manifests are
    always quorate (majority of 1)."""

    ACTIONS = ("adoption", "write_authority", "promotion",
               "autoscaler", "transfer", "roll")

    def __init__(self, manifest: FleetManifest, self_host: str,
                 suspect_after_s: float = 10.0,
                 clock=time.monotonic):
        self.self_host = str(self_host)
        self.suspect_after_s = max(0.1, float(suspect_after_s))
        self.clock = clock
        self.fenced = False
        self.fence_t: Optional[float] = None
        self.restore_t: Optional[float] = None
        self.refusals: Dict[str, int] = {}
        self._hosts: set = set()
        self._heard: Dict[str, float] = {}
        self.set_manifest(manifest)

    def set_manifest(self, manifest: FleetManifest) -> None:
        """Adopt a (possibly rolled) manifest's host set; hosts new to
        the membership start heard-now, departed hosts drop out of the
        denominator."""
        self._hosts = {m.host for m in manifest.members}
        now = self.clock()
        for host in self._hosts:
            if host != self.self_host:
                self._heard.setdefault(host, now)
        for host in list(self._heard):
            if host not in self._hosts:
                del self._heard[host]

    def observe(self, host: str) -> None:
        """One successful exchange with ``host`` (either direction —
        an inbound hello/gossip proves the link exactly as well as an
        answered outbound one)."""
        host = str(host or "")
        if host and host != self.self_host and host in self._hosts:
            self._heard[host] = self.clock()

    def reachable_hosts(self) -> List[str]:
        now = self.clock()
        return sorted(
            h for h, t in self._heard.items()
            if now - t <= self.suspect_after_s)

    def quorate(self) -> bool:
        return (1 + len(self.reachable_hosts())) * 2 > \
            max(1, len(self._hosts))

    def evaluate(self) -> bool:
        """Recompute the verdict and record fence/restore transitions.
        Cheap enough for per-dispatch callers (a set scan over <=
        manifest-host-count entries)."""
        from ..utils import decisions, telemetry
        reachable = self.reachable_hosts()
        quorate = (1 + len(reachable)) * 2 > max(1, len(self._hosts))
        telemetry.QUORUM.set_quorum(quorate, 1 + len(reachable),
                                    len(self._hosts))
        if quorate and self.fenced:
            self.fenced = False
            self.restore_t = self.clock()
            telemetry.QUORUM.count_transition("restored")
            telemetry.FLIGHT.record(
                "quorum.restore", host=self.self_host,
                reachable=1 + len(reachable),
                hosts=len(self._hosts))
            decisions.record("quorum", "restored", detail={
                "reachable": [self.self_host] + reachable,
                "hosts": sorted(self._hosts),
                "fenced_s": (round(self.restore_t - self.fence_t, 3)
                             if self.fence_t is not None else None),
                "refusals": dict(self.refusals)})
            logger.warning(
                "quorum restored: %d/%d hosts reachable (refused "
                "while fenced: %s)", 1 + len(reachable),
                len(self._hosts), dict(self.refusals) or "nothing")
        elif not quorate and not self.fenced:
            self.fenced = True
            self.fence_t = self.clock()
            telemetry.QUORUM.count_transition("fenced")
            telemetry.FLIGHT.record(
                "quorum.fence", host=self.self_host,
                reachable=1 + len(reachable),
                hosts=len(self._hosts))
            decisions.record("quorum", "fenced", detail={
                "reachable": [self.self_host] + reachable,
                "hosts": sorted(self._hosts),
                "suspect_after_s": self.suspect_after_s})
            logger.warning(
                "quorum LOST: only %d/%d hosts reachable — fencing "
                "(own-shard reads keep serving; adoption, write-backs,"
                " promotions, autoscaling and rolls refuse)",
                1 + len(reachable), len(self._hosts))
        return quorate

    def allow(self, action: str) -> bool:
        """May this state-changing ``action`` proceed?  False counts a
        refusal (telemetry + the restore record's tally) — callers
        skip/fail the action, they never raise from here."""
        if self.evaluate():
            return True
        if action in self.ACTIONS:
            from ..utils import telemetry
            telemetry.QUORUM.count_refusal(action)
            self.refusals[action] = self.refusals.get(action, 0) + 1
        return False

    def status(self) -> dict:
        """The /admin/federation ``quorum`` section (and the /readyz
        annotation material)."""
        self.evaluate()
        return {
            "quorate": not self.fenced,
            "fenced": self.fenced,
            "hosts": sorted(self._hosts),
            "reachable": [self.self_host] + self.reachable_hosts(),
            "suspect_after_s": self.suspect_after_s,
            "refusals": dict(self.refusals),
        }


def install_quorum(tracker: Optional[QuorumTracker]) -> None:
    global _QUORUM
    _QUORUM = tracker


def quorum_tracker() -> Optional[QuorumTracker]:
    return _QUORUM


def observe_host(host) -> None:
    """Feed one successful cross-host exchange into the quorum
    tracker (no-op when quorum is off)."""
    if _QUORUM is not None and host:
        _QUORUM.observe(str(host))


def is_fenced() -> bool:
    """Is this process a fenced minority island right now?  False
    when quorum tracking is off — every pre-quorum behavior is then
    bit-exact."""
    return _QUORUM is not None and not _QUORUM.evaluate()


def quorum_allow(action: str) -> bool:
    """Gate a state-changing action on quorum (True when tracking is
    off).  The fence sites: ring adoption / failover re-homes
    (``adoption``), byte-tier write-backs (``write_authority``),
    hot-key promotions (``promotion``), autoscaler transitions
    (``autoscaler``), inbound shard staging (``transfer``) and epoch
    rolls (``roll``)."""
    if _QUORUM is None:
        return True
    return _QUORUM.allow(action)


def quorum_status() -> Optional[dict]:
    return _QUORUM.status() if _QUORUM is not None else None


# -------------------------------------------------- orchestrated rolls

def set_roll_hook(hook) -> None:
    """Register the serving layer's ring-swap callback: called with
    the newly-activated :class:`FleetManifest` at epoch COMMIT (the
    only instant a live ring may change)."""
    global _ROLL_HOOK
    _ROLL_HOOK = hook


def activate_manifest(manifest: FleetManifest) -> bool:
    """Activate a committed epoch: swap the process-global manifest,
    clear a pending copy it supersedes, and invoke the roll hook so
    the live router swaps rings atomically.  Idempotent — activating
    the already-active (or an older) epoch is a no-op returning
    False."""
    global _MANIFEST, _PENDING
    mine = _MANIFEST
    if mine is not None and manifest.version <= mine.version:
        return False
    from ..utils import decisions, telemetry
    _MANIFEST = manifest
    if _PENDING is not None \
            and _PENDING.version <= manifest.version:
        _PENDING = None
    telemetry.FEDERATION.set_manifest(manifest.version,
                                      len(manifest.members))
    if _QUORUM is not None:
        _QUORUM.set_manifest(manifest)
    decisions.record("epoch", "installed", detail={
        "epoch": manifest.version, "digest": manifest.digest(),
        "members": len(manifest.members), "roll": True})
    hook = _ROLL_HOOK
    if hook is not None:
        try:
            hook(manifest)
        except Exception:
            logger.exception("epoch roll hook failed (epoch %d) — "
                             "manifest activated, ring swap did not "
                             "complete", manifest.version)
    logger.info("epoch %d activated by orchestrated roll (digest %s)",
                manifest.version, manifest.digest())
    return True


# ----------------------------------------------------- cross-host clocks

# host -> {"offset": local_perf - remote_perf, "rtt_ms", "ts"}.  The
# same midpoint anchoring the sidecar ``hello`` does per connection,
# lifted to per-HOST: every ``manifest_hello`` / ``member_gossip``
# answer carries the peer's ``time.perf_counter()``, the caller takes
# the send/recv midpoint as the instant that clock was read, and the
# difference maps remote span anchors into this process's timeline.
# Re-derived on every exchange, so drift is bounded by the gossip
# interval.
_HOST_CLOCKS: Dict[str, dict] = {}


def record_host_clock(host: str, t_send: float, t_recv: float,
                      remote_clock) -> Optional[float]:
    """Derive and store the per-host clock offset from one exchange.
    Returns the offset, or None when the peer answered without the
    anchor field (an older build — callers degrade to unanchored
    spans, never error)."""
    if not host or remote_clock is None:
        return None
    try:
        remote = float(remote_clock)
    except (TypeError, ValueError):
        return None
    offset = (t_send + t_recv) / 2.0 - remote
    _HOST_CLOCKS[str(host)[:64]] = {
        "offset": offset,
        "rtt_ms": round((t_recv - t_send) * 1000.0, 3),
        "ts": time.time(),
    }
    return offset


def host_clock_offset(host: str) -> Optional[float]:
    doc = _HOST_CLOCKS.get(host)
    return doc["offset"] if doc else None


def host_clocks() -> Dict[str, dict]:
    return {k: dict(v) for k, v in _HOST_CLOCKS.items()}


def anchor_remote_time(host: str, remote_t,
                       window: Tuple[float, float]) -> Optional[float]:
    """Map a remote ``perf_counter`` instant into this process's
    timeline, CLAMPED into ``window`` (the local [send, recv] bracket
    of the exchange that carried it) — the sidecar ``_graft_response``
    contract: a skewed or stale offset may place the child oddly
    WITHIN its parent's window, never outside it.  None when the host
    has no derived offset (unanchored degrade)."""
    off = host_clock_offset(host)
    if off is None or remote_t is None:
        return None
    try:
        t = float(remote_t) + off
    except (TypeError, ValueError):
        return None
    lo, hi = window
    return min(max(t, lo), hi)


def reset_clocks() -> None:
    """Test isolation."""
    _HOST_CLOCKS.clear()


# ------------------------------------------------------ wire-op handlers

def handle_manifest_hello(header: dict) -> dict:
    """Server side of the ``manifest_hello`` op (runs in the sidecar's
    request handler).  Compares the joiner's manifest against this
    process's installed one and answers the agreement verdict plus —
    when probe keys rode along — this process's OWN ring owner for
    each (the cross-process golden-assignment check).

    No manifest installed = a legacy / un-federated process: answers
    ``{"enabled": false}`` and the coordinator degrades (counts
    ``legacy``, serves without federation features on that peer)."""
    from ..utils import decisions, telemetry
    mine = _MANIFEST
    if mine is None:
        return {"enabled": False}
    # An inbound hello proves the sender's host is reachable exactly
    # as well as an answered outbound exchange would.
    observe_host(header.get("from_host"))
    doc: dict = {
        "enabled": True,
        "version": mine.version,
        "digest": mine.digest(),
        # Clock anchor (the sidecar ``hello`` idiom, per HOST): the
        # caller midpoints its send/recv around this read and derives
        # the offset that grafts our spans onto its waterfalls.
        "clock": time.perf_counter(),
        "host": _SELF_HOST,
    }
    theirs_doc = header.get("manifest")
    if isinstance(theirs_doc, dict):
        try:
            theirs = FleetManifest.from_json(theirs_doc)
        except (KeyError, TypeError, ValueError):
            theirs = None
        if theirs is None:
            doc["agreed"] = False
            doc["reason"] = "malformed"
            telemetry.FEDERATION.count_agreement("split-brain")
            decisions.record("manifest", "split-brain",
                             detail={"reason": "malformed"})
        elif theirs.digest() == mine.digest():
            doc["agreed"] = True
            telemetry.FEDERATION.count_agreement("agreed")
            decisions.record("manifest", "agreed",
                             detail={"epoch": mine.version})
        elif theirs.version > mine.version:
            # The joiner carries a NEWER shard epoch: a rolling config
            # update reached it first.  Record it PENDING — this
            # process keeps routing the epoch its router was built
            # from until the operator rolls it; answering "agreed" to
            # a map we are not routing would be the silent split-brain
            # this op exists to refuse.
            set_pending(theirs)
            doc["agreed"] = False
            doc["reason"] = "pending"
            doc["pending_version"] = theirs.version
            telemetry.FEDERATION.count_agreement("pending")
            decisions.record("manifest", "pending", detail={
                "epoch": mine.version,
                "pending_epoch": theirs.version})
        elif theirs.version < mine.version:
            # The joiner is behind: send ours so IT records the
            # pending epoch and its operator rolls it.
            doc["agreed"] = False
            doc["reason"] = "stale-epoch"
            doc["manifest"] = mine.to_json()
            telemetry.FEDERATION.count_agreement("stale")
            decisions.record("manifest", "stale", detail={
                "epoch": mine.version,
                "joiner_epoch": theirs.version})
        else:
            doc["agreed"] = False
            doc["reason"] = "split-brain"
            telemetry.FEDERATION.count_agreement("split-brain")
            decisions.record("manifest", "split-brain",
                             detail={"epoch": mine.version})
    probe_keys = header.get("probe_keys")
    if isinstance(probe_keys, list) and probe_keys:
        doc["owners"] = mine.owners([str(k) for k in probe_keys[:64]])
    return doc


# Gossip view: member name -> {"healthy": bool, "draining": bool,
# "inc": int, "seq": int, "ts": float}.  The HIGHEST ``(inc, seq)``
# observation wins on merge — logical versions, never wall clocks (a
# skewed-ahead peer could otherwise pin a stale verdict forever).
# ``ts`` survives for display only.  Legacy observations without
# ``inc`` compare as ``(0, ts)``: among themselves they keep the old
# newest-ts behavior, and ANY versioned observation supersedes them.
_GOSSIP_VIEW: Dict[str, dict] = {}


def _obs_version(obs: dict) -> tuple:
    """An observation's logical version for merge ordering."""
    try:
        inc = int(obs.get("inc", 0))
    except (TypeError, ValueError):
        inc = 0
    if inc > 0:
        try:
            return (inc, float(obs.get("seq", 0)))
        except (TypeError, ValueError):
            return (inc, 0.0)
    try:
        return (0, float(obs.get("ts", 0)))
    except (TypeError, ValueError):
        return (0, 0.0)


def local_view(router, self_host: str = "") -> Dict[str, dict]:
    """This process's authoritative member observations: LOCAL members'
    health/drain state straight from the router (a host knows its own
    members best), stamped with this process's ``(incarnation, seq)``
    — seq bumps on every state change, so a changed truth always
    versions above the last one we published.

    Self-refutation (the SWIM rejoin rule): if the merged view holds a
    HIGHER-versioned observation about one of our own members that
    disagrees with the live router state — a pre-restart ghost of
    ourselves, or a peer's stale relay — bump our incarnation above it
    so the fresh truth supersedes fleet-wide."""
    global _INCARNATION, _LOCAL_SEQ
    mine = _MANIFEST
    view: Dict[str, dict] = {}
    if router is None or mine is None:
        return view
    now = time.time()
    local = {m.name for m in mine.local_members(self_host)} \
        if self_host else set(router.order)
    for name in router.order:
        if name not in local:
            continue
        member = router.members.get(name)
        if member is None:
            continue
        state = (bool(member.healthy), bool(member.draining))
        if _LOCAL_LAST.get(name) != state:
            _LOCAL_LAST[name] = state
            _LOCAL_SEQ += 1
        held = _GOSSIP_VIEW.get(name)
        if held is not None \
                and _obs_version(held) > (_INCARNATION, _LOCAL_SEQ) \
                and (bool(held.get("healthy", True)),
                     bool(held.get("draining", False))) != state:
            _INCARNATION = max(_INCARNATION,
                               _obs_version(held)[0]) + 1
        obs = {"healthy": state[0],
               "draining": state[1],
               "inc": _INCARNATION,
               "seq": _LOCAL_SEQ,
               "ts": now}
        # Hot-key posture rides the gossip wire: how many promoted
        # routes this member serves replicas for (duck-typed — drill
        # routers may predate the hot tier), so peers can see a storm
        # concentrating on one host before its queues say so.
        hot_fn = getattr(router, "hot_owned", None)
        if hot_fn is not None:
            try:
                hot = int(hot_fn(name))
            except Exception:
                hot = 0
            if hot:
                obs["hot"] = hot
        view[name] = obs
    return view


def merge_view(view: dict) -> Dict[str, dict]:
    """Fold a peer's view into the process gossip state (highest
    ``(incarnation, seq)`` per member wins — see ``_obs_version``)
    and return the merged state.

    Names are validated against the ACTIVE manifest (the socket is
    unauthenticated-by-design like every sidecar op, and the merged
    view is re-broadcast in every gossip answer — an unvalidated name
    would live in this module-global forever and propagate
    fleet-wide), so the view is bounded by the membership.  With no
    manifest installed (bare tests), a hard cap stands in."""
    mine = _MANIFEST
    known = set(mine.names()) if mine is not None else None
    if isinstance(view, dict):
        for name, obs in view.items():
            if not isinstance(obs, dict):
                continue
            # Store and look up under the SAME (bounded) key, or an
            # over-long name would bypass the versioned merge.
            name = str(name)[:64]
            if known is not None:
                if name not in known:
                    continue
            elif name not in _GOSSIP_VIEW \
                    and len(_GOSSIP_VIEW) >= 256:
                continue
            held = _GOSSIP_VIEW.get(name)
            if held is None or _obs_version(obs) > _obs_version(held):
                stored = {
                    "healthy": bool(obs.get("healthy", True)),
                    "draining": bool(obs.get("draining", False)),
                    "ts": float(obs.get("ts", 0)),
                }
                version = _obs_version(obs)
                if version[0] > 0:
                    stored["inc"] = version[0]
                    stored["seq"] = int(version[1])
                try:
                    hot = int(obs.get("hot", 0))
                except (TypeError, ValueError):
                    hot = 0
                if hot > 0:
                    stored["hot"] = min(hot, 1 << 20)
                _GOSSIP_VIEW[name] = stored
    return dict(_GOSSIP_VIEW)


def handle_member_gossip(header: dict) -> dict:
    """Server side of ``member_gossip``: merge the sender's view, answer
    ours + the manifest identity (drift between gossiping peers is a
    mismatch the coordinator surfaces).  The answer also carries this
    host's clock anchor (re-derived offset every round — reconnect
    recovery for free) and its ``SloEngine`` window buckets, so the
    gossip wire doubles as the fleet-SLO export path with no extra
    round trips."""
    from ..utils import telemetry
    mine = _MANIFEST
    observe_host(header.get("from_host"))
    merged = merge_view(header.get("view") or {})
    doc: dict = {"enabled": mine is not None, "view": merged}
    if mine is not None:
        doc["version"] = mine.version
        doc["digest"] = mine.digest()
        doc["clock"] = time.perf_counter()
        doc["host"] = _SELF_HOST
        slo = telemetry.SLO.export_buckets()
        if slo:
            doc["slo"] = slo
        # Perf-sentinel piggyback, same posture as the SLO buckets:
        # this host's last tick summary rides the gossip answer so
        # every peer's /debug/sentinel sees the fleet drift picture
        # with no extra round trips.
        sen = telemetry.SENTINEL.export()
        if sen:
            doc["sentinel"] = sen
    return doc


def handle_epoch_propose(header: dict) -> dict:
    """Server side of ``epoch_propose`` (two-phase roll, phase 1):
    validate the proposed manifest, record it PENDING, and ack.
    Nothing activates here — the live router keeps routing the epoch
    it was built with until the commit.  Idempotent: re-proposing the
    version already pending (a coordinator that died mid-propose and
    resumed) acks again; proposing at-or-below the active epoch
    refuses ``stale`` unless it IS the active manifest
    (``already-active`` — a crash-resumed roll finding its work done).
    A fenced minority host refuses — it cannot know whether the
    majority already rolled past this proposal."""
    from ..utils import telemetry
    mine = _MANIFEST
    if mine is None:
        return {"enabled": False}
    observe_host(header.get("from_host"))
    doc: dict = {"enabled": True, "host": _SELF_HOST,
                 "clock": time.perf_counter()}
    try:
        proposed = FleetManifest.from_json(header.get("manifest") or {})
    except (KeyError, TypeError, ValueError):
        doc.update(ack=False, reason="malformed")
        return doc
    if is_fenced():
        quorum_allow("roll")         # count the refusal
        doc.update(ack=False, reason="fenced")
        return doc
    if proposed.version <= mine.version:
        if proposed.digest() == mine.digest():
            doc.update(ack=True, reason="already-active")
        else:
            doc.update(ack=False, reason="stale",
                       active_version=mine.version)
        return doc
    set_pending(proposed)
    telemetry.FLIGHT.record("epoch.propose", epoch=proposed.version,
                            digest=proposed.digest()[:12],
                            by=str(header.get("from_host") or "?"))
    doc.update(ack=True, reason="pending",
               pending_version=proposed.version)
    return doc


def handle_epoch_commit(header: dict) -> dict:
    """Server side of ``epoch_commit`` (two-phase roll, phase 2):
    digest-verify the committed manifest and ACTIVATE it — the one
    instant the ring swaps (via the registered roll hook).  Idempotent:
    committing the already-active epoch answers ``already-active``; an
    older epoch answers ``stale`` (a superseded roll's late commit
    must not regress the fleet).  The commit carries the FULL
    manifest, so a host that never saw the propose (rebooted between
    phases, or healed from a partition after the roll) still converges
    — this is also the anti-entropy catch-up the gossip loop pushes to
    stale peers."""
    from ..utils import telemetry
    mine = _MANIFEST
    if mine is None:
        return {"enabled": False}
    observe_host(header.get("from_host"))
    doc: dict = {"enabled": True, "host": _SELF_HOST,
                 "clock": time.perf_counter()}
    try:
        committed = FleetManifest.from_json(
            header.get("manifest") or {})
    except (KeyError, TypeError, ValueError):
        doc.update(ack=False, reason="malformed")
        return doc
    claimed = header.get("digest")
    if claimed is not None and str(claimed) != committed.digest():
        # The unauthenticated-socket posture: the doc must be
        # byte-exactly what the coordinator committed fleet-wide.
        doc.update(ack=False, reason="digest-mismatch")
        return doc
    if committed.version < mine.version:
        doc.update(ack=False, reason="stale",
                   active_version=mine.version)
        return doc
    if committed.version == mine.version:
        ok = committed.digest() == mine.digest()
        doc.update(ack=ok, reason="already-active" if ok
                   else "split-brain")
        return doc
    activate_manifest(committed)
    telemetry.FLIGHT.record("epoch.commit", epoch=committed.version,
                            digest=committed.digest()[:12],
                            by=str(header.get("from_host") or "?"))
    doc.update(ack=True, reason="installed",
               active_version=committed.version)
    return doc


def reset_gossip() -> None:
    """Test isolation."""
    global _LOCAL_SEQ
    _GOSSIP_VIEW.clear()
    _LOCAL_LAST.clear()
    _LOCAL_SEQ = 0


# ------------------------------------------------------- device pinning

def partition_local_devices(n_members: int,
                            devices: Optional[Sequence] = None
                            ) -> List[list]:
    """Partition this process's devices across ``n_members`` local
    members — contiguous, deterministic, remainder to the earliest
    members (so member 0, the mesh/bulk lane, is never the short one).
    Fewer devices than members leaves the tail members unpinned
    (process default device) rather than oversubscribing one chip with
    two members' pins."""
    if n_members < 1:
        raise ValueError("partition needs >= 1 member")
    if devices is None:
        import jax
        devices = jax.local_devices()
    devices = list(devices)
    n_dev = len(devices)
    if n_dev == 0:
        return [[] for _ in range(n_members)]
    base, extra = divmod(n_dev, n_members)
    out: List[list] = []
    i = 0
    for m in range(n_members):
        take = base + (1 if m < extra else 0)
        out.append(devices[i:i + take])
        i += take
    return out


# --------------------------------------------------------- construction

def build_federated_members(config, base_services, manifest,
                            client_factory, self_host: str):
    """The federated member list, in MANIFEST order: members on THIS
    host are in-process device-pinned lanes (the combined role), the
    rest are :class:`~.fleet.RemoteMember` handles over their sidecar
    addresses.  Every host building from an agreeing manifest produces
    the same order, so ring arcs, bulk pinning (order[0]) and drain
    victims agree fleet-wide.

    The FIRST local member wraps the base service stack (its renderer
    may be the lockstep ``MeshRenderer`` — ``parallel.serve`` marks it
    ``lockstep = True`` and it must stay a single lane, so device
    partitioning pins but never splits it)."""
    from .fleet import RemoteMember, build_local_members

    local_specs = manifest.local_members(self_host)
    if not local_specs:
        raise ValueError(
            f"federation.host {self_host!r} owns no manifest member — "
            f"a combined process must serve at least one")
    for spec in manifest.remote_members(self_host):
        if not spec.address:
            raise ValueError(
                f"federation member {spec.name!r} on host "
                f"{spec.host!r} has no address — this host "
                f"({self_host!r}) cannot reach it")
    if getattr(base_services.renderer, "lockstep", False) \
            and manifest.members[0].host != self_host:
        # The lockstep MeshRenderer lives HERE, but bulk/mesh work
        # pins to the fleet's first member (manifest order[0]) — on
        # another host.  Legal (that host serves bulk), but almost
        # certainly a mis-ordered manifest: the mesh host should come
        # first so full-plane jobs run on the mesh.
        logger.warning(
            "this host (%s) runs the lockstep mesh renderer but "
            "manifest member 0 (%s) lives on host %s — bulk/mesh "
            "work will pin there; list the mesh host's members first",
            self_host, manifest.members[0].name,
            manifest.members[0].host)
    device_sets = partition_local_devices(len(local_specs))
    locals_built = build_local_members(
        config, base_services, len(local_specs),
        device_sets=device_sets)
    by_name = {}
    for spec, built in zip(local_specs, locals_built):
        built.name = spec.name
        by_name[spec.name] = built
    members = []
    for spec in manifest.members:
        if spec.name in by_name:
            members.append(by_name[spec.name])
        else:
            client = client_factory(spec.address)
            # Stamp the destination HOST on the wire client: the
            # link-partition hook (utils.faultinject.partitioned)
            # keys on (self_host, peer_host), and un-stamped clients
            # — the front-door/proxy path — never match a rule.
            try:
                client.peer_host = spec.host
            except AttributeError:
                pass               # duck-typed drill clients
            members.append(RemoteMember(
                spec.name, client,
                down_cooldown_s=config.fleet.down_cooldown_s))
    return members


# ---------------------------------------------------------- coordinator

class FederationCoordinator:
    """The join/gossip driver for one process's federated router.

    ``agree()`` runs once at startup (and on demand): exchanges
    manifests with every remote member, verifies golden probe-key
    owners against each peer's own ring math, adopts newer epochs, and
    raises :class:`FederationError` on split-brain.  ``run()`` is the
    gossip tick loop — cross-host drain/death propagation plus
    manifest-drift detection."""

    def __init__(self, manifest: FleetManifest, self_host: str,
                 router=None, gossip_interval_s: float = 5.0,
                 handles: Optional[List] = None):
        self.manifest = manifest
        self.self_host = self_host
        self.router = router
        # Router-less gossipers (sidecar member processes): explicit
        # remote handles instead — every host must gossip ACTIVELY or
        # two non-routing hosts would never prove their link to each
        # other and a partition of the one router would fence them.
        self.handles = list(handles) if handles is not None else None
        self.gossip_interval_s = max(0.2, float(gossip_interval_s))
        # Deterministic per-host tick jitter (seeded: reproducible
        # schedules, like every chaos knob): +/-20% keeps an N-host
        # fleet's gossip bursts from synchronizing into a thundering
        # herd on one member.
        import random
        self._jitter_rng = random.Random(
            f"{self_host}:{manifest.ring_seed}:gossip-jitter")
        # name -> verdict of the last agreement exchange.
        self.agreement: Dict[str, str] = {}
        self.last_gossip: Dict[str, str] = {}

    def _remote_handles(self) -> List:
        if self.router is None:
            return list(self.handles) if self.handles else []
        return [self.router.members[n] for n in self.router.order
                if getattr(self.router.members[n], "remote", False)]

    def next_interval_s(self) -> float:
        """The next gossip sleep: the configured interval jittered
        uniformly within +/-20% (seeded per host, so tests can pin
        the schedule)."""
        return self.gossip_interval_s \
            * (0.8 + 0.4 * self._jitter_rng.random())

    def _refresh_manifest(self) -> None:
        """Adopt the process-global ACTIVE manifest when an epoch
        commit landed wire-side (handle_epoch_commit / a peer's
        anti-entropy push) and outran this coordinator's copy.
        Activation already swapped the ring at commit time, so the
        identity this coordinator gossips/agrees with must follow —
        otherwise a healed host keeps advertising the pre-roll digest
        forever and every round logs phantom drift."""
        active = current()
        if active is not None \
                and active.version > self.manifest.version:
            self.manifest = active

    async def agree(self, strict: bool = True) -> Dict[str, str]:
        """One agreement round with every remote member.  Returns the
        per-member verdict map; ``strict`` raises on split-brain only
        — every rolling-rollout verdict is tolerated and LOUD:

        * ``agreed`` — digest match, probe owners verified against
          the peer's own ring math;
        * ``pending`` — the peer is on an OLDER epoch and recorded
          ours as pending (its operator rolls it; a mid-roll fleet
          serves with both maps, each process honest about its own);
        * ``stale`` — WE are on the older epoch: the peer's newer
          manifest is recorded pending here, /readyz and
          /admin/federation say so until this process is rolled;
        * ``unreachable`` / ``legacy`` — tolerated (a dead or
          un-federated host must not block the survivors' boot);
        * ``split-brain`` — same epoch, different membership (or a
          peer whose ring math disagrees with its own digest): a
          refused join under ``strict``."""
        from ..utils import telemetry
        self._refresh_manifest()
        doc = self.manifest.to_json()
        my_owners = self.manifest.owners(list(PROBE_KEYS))
        verdicts: Dict[str, str] = {}
        for member in self._remote_handles():
            host = self.manifest.host_of(member.name)
            t_send = time.perf_counter()
            resp = await member.manifest_hello(
                doc, probe_keys=list(PROBE_KEYS))
            t_recv = time.perf_counter()
            telemetry.record_span(
                "fed.hop", t_send, (t_recv - t_send) * 1000.0,
                host=host, member=member.name, kind="hello")
            if isinstance(resp, dict):
                # Per-host clock anchor from the send/recv midpoint —
                # the sidecar hello idiom.  A peer without the field
                # (older build) simply derives no offset: its spans
                # stay unanchored, nothing errors.
                record_host_clock(resp.get("host") or host,
                                  t_send, t_recv, resp.get("clock"))
                observe_host(resp.get("host") or host)
            if resp is None:
                verdicts[member.name] = "unreachable"
                telemetry.FEDERATION.count_agreement("unreachable")
                continue
            if not resp.get("enabled"):
                verdicts[member.name] = "legacy"
                telemetry.FEDERATION.count_agreement("legacy")
                continue
            if resp.get("agreed"):
                # Digest agreement is necessary; the probe owners are
                # the sufficiency check — the peer's OWN ring hashed
                # every probe key to the member we did.
                owners = resp.get("owners")
                if owners is not None and owners != my_owners:
                    verdicts[member.name] = "split-brain"
                    telemetry.FEDERATION.count_agreement("split-brain")
                    continue
                verdicts[member.name] = "agreed"
                telemetry.FEDERATION.count_agreement("agreed")
                continue
            reason = resp.get("reason")
            if reason == "pending":
                # The peer (older epoch) recorded OUR manifest as its
                # pending epoch — a rollout in flight, its side.
                verdicts[member.name] = "pending"
                telemetry.FEDERATION.count_agreement("pending")
                continue
            if reason == "stale-epoch" \
                    and isinstance(resp.get("manifest"), dict):
                # WE are the older epoch: record the newer manifest
                # pending and keep serving the map this router was
                # BUILT with — activating mid-flight would diverge
                # what we advertise from what we route.
                try:
                    newer = FleetManifest.from_json(resp["manifest"])
                except (KeyError, TypeError, ValueError):
                    verdicts[member.name] = "split-brain"
                    telemetry.FEDERATION.count_agreement("split-brain")
                    continue
                if newer.version > self.manifest.version:
                    set_pending(newer)
                    verdicts[member.name] = "stale"
                    telemetry.FEDERATION.count_agreement("stale")
                    continue
                verdicts[member.name] = "split-brain"
                telemetry.FEDERATION.count_agreement("split-brain")
            else:
                verdicts[member.name] = "split-brain"
                telemetry.FEDERATION.count_agreement("split-brain")
        from ..utils import decisions
        for name, verdict in verdicts.items():
            decisions.record("manifest", verdict, member=name, detail={
                "host": self.manifest.host_of(name),
                "epoch": self.manifest.version})
        self.agreement = verdicts
        split = [n for n, v in verdicts.items() if v == "split-brain"]
        if split and strict:
            raise FederationError(
                f"federation manifest split-brain with {split}: same "
                f"shard epoch, different membership — refusing to "
                f"serve a forked shard map (bump federation.shard-"
                f"epoch with the corrected member list)")
        return verdicts

    async def gossip_once(self) -> Dict[str, str]:
        """One gossip round: push our local-member view to every
        remote member, merge their answers, and reflect what their
        hosts report about THEIR members onto our router handles —
        a drain ordered on host B walks routing off B's members here
        within one interval, before any request fails over."""
        from ..utils import telemetry
        self._refresh_manifest()
        view = local_view(self.router, self.self_host)
        merge_view(view)
        outcome: Dict[str, str] = {}
        my_digest = self.manifest.digest()
        # Our own host's window buckets join the fleet aggregate the
        # same way every peer's do — one ingest path, no special case.
        telemetry.FED_SLO.ingest(self.self_host,
                                 telemetry.SLO.export_buckets())
        telemetry.SENTINEL.ingest(self.self_host,
                                  telemetry.SENTINEL.export())
        for member in self._remote_handles():
            host = self.manifest.host_of(member.name)
            t_send = time.perf_counter()
            resp = await member.member_gossip(view)
            t_recv = time.perf_counter()
            telemetry.record_span(
                "fed.hop", t_send, (t_recv - t_send) * 1000.0,
                host=host, member=member.name, kind="gossip")
            if isinstance(resp, dict):
                # Re-derive the per-host clock anchor every round:
                # reconnects and drift heal within one interval.
                record_host_clock(resp.get("host") or host,
                                  t_send, t_recv, resp.get("clock"))
                observe_host(resp.get("host") or host)
                telemetry.FED_SLO.ingest(resp.get("host") or host,
                                         resp.get("slo"))
                telemetry.SENTINEL.ingest(resp.get("host") or host,
                                          resp.get("sentinel"))
            if resp is None or not resp.get("enabled", True):
                outcome[member.name] = "unreachable"
                telemetry.FEDERATION.count_gossip("unreachable")
                continue
            their_version = resp.get("version")
            if isinstance(their_version, int) \
                    and their_version < self.manifest.version \
                    and not is_fenced():
                # Anti-entropy catch-up: the peer runs an OLDER epoch
                # than the one this quorate host committed (it healed
                # from a partition, or rebooted between roll phases).
                # Re-push the commit — idempotent on the receiver —
                # so the fleet converges without operator action.
                await self._catchup(member, host)
            their_digest = resp.get("digest")
            pend = pending()
            if their_digest not in (None, my_digest):
                if pend is not None \
                        and their_digest == pend.digest():
                    # Known rollout in flight: the peer already runs
                    # the epoch we hold PENDING — not drift, just the
                    # roll this process is still waiting for.
                    pass
                else:
                    outcome[member.name] = "mismatch"
                    telemetry.FEDERATION.count_gossip("mismatch")
                    logger.warning(
                        "federation manifest drift detected gossiping "
                        "with %s (their digest %s != ours %s)",
                        member.name, their_digest, my_digest)
                    continue
            merged = merge_view(resp.get("view") or {})
            self._apply_remote_view(merged)
            outcome[member.name] = "ok"
            telemetry.FEDERATION.count_gossip("ok")
        from ..utils import decisions
        for name, verdict in outcome.items():
            if self.last_gossip.get(name) != verdict:
                # Convergence TRANSITIONS only (the flight-ring
                # posture): a steady fleet gossips every few seconds
                # and must not churn the ledger ring with "still ok".
                decisions.record("gossip", verdict, member=name,
                                 detail={
                                     "host": self.manifest.host_of(
                                         name)})
        self.last_gossip = outcome
        q = quorum_tracker()
        if q is not None:
            # The round's reachability verdict — fences and restores
            # transition HERE (and lazily at any gated action), within
            # one gossip interval of the link change.
            q.evaluate()
        return outcome

    async def _catchup(self, member, host: str) -> None:
        """Push our committed epoch to a stale peer (anti-entropy;
        best-effort — the next round retries)."""
        commit_fn = getattr(member, "epoch_commit", None)
        if commit_fn is None:
            return                   # duck-typed drill stubs
        try:
            resp = await commit_fn(self.manifest.to_json(),
                                    digest=self.manifest.digest())
        except Exception:
            return
        if isinstance(resp, dict) and resp.get("ack"):
            logger.info("anti-entropy: pushed epoch %d to %s (%s)",
                        self.manifest.version, member.name,
                        resp.get("reason"))

    async def roll_epoch(self, new_manifest: FleetManifest) -> dict:
        """Coordinator-driven two-phase epoch roll.

        Phase 1 (``epoch_propose``): offer the new manifest to one
        member per remote HOST; each validating host records it
        PENDING and acks.  A strict majority of manifest hosts (self
        counts) must ack, or the roll aborts with nothing activated
        anywhere — a minority can never advance the epoch.

        Phase 2 (``epoch_commit``): push the full manifest to every
        remote host (idempotent receivers), then activate locally (the
        registered roll hook swaps the live ring — the ONLY mid-flight
        ring change the router ever performs).  A coordinator that
        dies between phases leaves peers holding a pending manifest:
        re-running the same roll re-proposes idempotently, and a
        SUPERSEDING roll (higher version) simply outversions it.
        Hosts the commit missed converge through the gossip loop's
        anti-entropy push.

        Returns ``{"committed": bool, "acks": int, "hosts": int,
        "verdicts": {host: reason}}``."""
        from ..utils import decisions, telemetry
        if new_manifest.version <= self.manifest.version:
            raise ValueError(
                f"epoch roll must raise the version (active "
                f"{self.manifest.version}, proposed "
                f"{new_manifest.version})")
        if not quorum_allow("roll"):
            decisions.record("epoch", "failed", detail={
                "epoch": new_manifest.version, "reason": "fenced"})
            return {"committed": False, "acks": 0,
                    "hosts": 0, "verdicts": {},
                    "reason": "fenced"}
        doc = new_manifest.to_json()
        digest = new_manifest.digest()
        hosts = {m.host for m in self.manifest.members}
        # One propose per remote HOST (the manifest is process-global
        # on the receiver; a host's members share one process there).
        by_host: Dict[str, object] = {}
        for member in self._remote_handles():
            host = self.manifest.host_of(member.name)
            if host and host != self.self_host:
                by_host.setdefault(host, member)
        telemetry.FLIGHT.record("epoch.propose", epoch=doc["version"],
                                digest=digest[:12], by=self.self_host)
        decisions.record("epoch", "pending", detail={
            "pending_epoch": new_manifest.version,
            "pending_digest": digest, "roll": True,
            "phase": "propose"})
        verdicts: Dict[str, str] = {}
        acks = 1                      # self: the coordinator agrees
        for host, member in by_host.items():
            propose_fn = getattr(member, "epoch_propose", None)
            if propose_fn is None:
                verdicts[host] = "legacy"
                continue
            try:
                resp = await propose_fn(doc)
            except Exception:
                resp = None
            if not isinstance(resp, dict):
                verdicts[host] = "unreachable"
                continue
            observe_host(resp.get("host") or host)
            verdicts[host] = str(resp.get("reason") or (
                "ack" if resp.get("ack") else "refused"))
            if resp.get("ack"):
                acks += 1
        if acks * 2 <= len(hosts):
            decisions.record("epoch", "failed", detail={
                "epoch": new_manifest.version, "acks": acks,
                "hosts": len(hosts), "verdicts": verdicts})
            logger.warning(
                "epoch roll %d aborted: %d/%d host acks is not a "
                "strict majority (%s)", new_manifest.version, acks,
                len(hosts), verdicts)
            return {"committed": False, "acks": acks,
                    "hosts": len(hosts), "verdicts": verdicts}
        for host, member in by_host.items():
            commit_fn = getattr(member, "epoch_commit", None)
            if commit_fn is None:
                continue
            try:
                resp = await commit_fn(doc, digest=digest)
            except Exception:
                resp = None
            if isinstance(resp, dict):
                verdicts[host] = str(resp.get("reason")
                                     or verdicts.get(host, "?"))
        activate_manifest(new_manifest)
        self.manifest = new_manifest
        telemetry.FLIGHT.record("epoch.commit", epoch=doc["version"],
                                digest=digest[:12], by=self.self_host)
        decisions.record("epoch", "done", detail={
            "epoch": new_manifest.version, "acks": acks,
            "hosts": len(hosts), "verdicts": verdicts})
        logger.info("epoch roll %d committed (%d/%d host acks)",
                    new_manifest.version, acks, len(hosts))
        return {"committed": True, "acks": acks,
                "hosts": len(hosts), "verdicts": verdicts}

    def _apply_remote_view(self, merged: Dict[str, dict]) -> None:
        """Reflect peers' authoritative observations of THEIR OWN
        members onto our remote handles: drain state propagates both
        ways (set and cleared) UNDER the ``gossip`` intent only —
        drains THIS process ordered (operator ``/admin/drain``, an
        autoscaler scale-down holding the member in ``_scaled_down``)
        are this router's own decisions and must never be reverted by
        a peer that simply was not told about them.  Down-ness only
        marks (re-admission stays with the served-call/cooldown
        machinery — gossip must not revive a member its own host no
        longer vouches for)."""
        if self.router is None:
            return
        local = {m.name for m in
                 self.manifest.local_members(self.self_host)}
        for name, obs in merged.items():
            if name in local or name not in self.router.members:
                continue
            member = self.router.members[name]
            intent = getattr(member, "drain_intent", None)
            if member.draining and intent not in (None, "gossip"):
                # Our own drain (operator/autoscale): gossip is not
                # allowed to undo it — host B reporting "b1 not
                # draining" just means B was never told.
                continue
            draining = bool(obs.get("draining"))
            if member.draining != draining:
                member.draining = draining
                member.drain_intent = "gossip" if draining else None
                from ..utils import telemetry
                telemetry.FLIGHT.record("federation.gossip-drain",
                                        member=name,
                                        draining=draining)
            if not obs.get("healthy", True) and member.healthy:
                member.mark_down()

    def status(self) -> dict:
        """The /admin/federation + /readyz annotation document."""
        doc = {
            "host": self.self_host,
            "epoch": self.manifest.version,
            "digest": self.manifest.digest(),
            "members": [m.to_json() for m in self.manifest.members],
            "agreement": dict(self.agreement),
            "gossip": dict(self.last_gossip),
            "view": dict(_GOSSIP_VIEW),
            "clocks": host_clocks(),
        }
        pend = pending()
        if pend is not None and pend.version > self.manifest.version:
            # The operator's roll signal: a newer epoch exists in the
            # fleet and activates here on the next process restart
            # (or the next orchestrated roll's commit).
            doc["pending_epoch"] = pend.version
            doc["pending_digest"] = pend.digest()
        q = quorum_status()
        if q is not None:
            doc["quorum"] = q
        return doc

    def summary(self) -> str:
        agreed = sum(1 for v in self.agreement.values()
                     if v == "agreed")
        line = (f"epoch {self.manifest.version}, "
                f"{agreed}/{max(1, len(self.agreement))} peers agreed")
        pend = pending()
        if pend is not None and pend.version > self.manifest.version:
            line += f" (epoch {pend.version} pending roll)"
        q = quorum_status()
        if q is not None:
            line += (" — FENCED minority partition (own-shard reads "
                     "only)" if q["fenced"]
                     else f" — quorate "
                          f"{len(q['reachable'])}/{len(q['hosts'])}")
        return line

    async def run(self) -> None:
        """Gossip tick loop (the governor idiom; the app's robustness
        startup hook owns the task).  Each sleep is jittered +/-20%
        (seeded) so N hosts sharing an interval never synchronize
        their gossip bursts into a thundering herd on one member."""
        while True:
            await asyncio.sleep(self.next_interval_s())
            try:
                await self.gossip_once()
            except Exception:
                logger.warning("federation gossip round failed",
                               exc_info=True)
