"""Rendering metadata value objects.

These replace the Java-serialized ``ome.model.*`` objects the reference ships
over its event bus (SURVEY.md section 2b; reference call sites
``ImageRegionRequestHandler.java:258-300``, ``:353-356``).  They are plain
dataclasses: JSON/msgpack-friendly, hashable where useful, and free of any
ORM/session machinery.
"""

from .pixels import PixelsType, Pixels, PIXELS_TYPES, pixels_type_range
from .rendering import (
    Family,
    RenderingModel,
    QuantumDef,
    ChannelBinding,
    RenderingDef,
    default_rendering_def,
)
from .mask import Mask

__all__ = [
    "PixelsType",
    "Pixels",
    "PIXELS_TYPES",
    "pixels_type_range",
    "Family",
    "RenderingModel",
    "QuantumDef",
    "ChannelBinding",
    "RenderingDef",
    "default_rendering_def",
    "Mask",
]
