"""Shape-mask value object.

Replaces the consumed surface of ``ome.model.roi.Mask``
(``ShapeMaskRequestHandler.java:96-115``: fill color, packed 1-bit bytes,
width, height).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


DEFAULT_FILL_COLOR = (255, 255, 0, 255)  # yellow; ShapeMaskRequestHandler.java:99


@dataclass
class Mask:
    """A binary ROI mask: row-major 1-bit packed bytes plus dimensions.

    ``fill_color`` is the RGBA stored on the mask object, if any; the request
    may override it (``ShapeMaskRequestHandler.java:100-106``).
    """

    shape_id: int
    width: int
    height: int
    bytes_: bytes
    fill_color: Optional[Tuple[int, int, int, int]] = None

    def resolved_fill_color(
        self, override: Optional[Tuple[int, int, int, int]] = None
    ) -> Tuple[int, int, int, int]:
        if override is not None:
            return override
        if self.fill_color is not None:
            return self.fill_color
        return DEFAULT_FILL_COLOR
