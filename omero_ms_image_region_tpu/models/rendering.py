"""Rendering definition value objects.

Replaces the consumed surface of ``ome.model.display.RenderingDef`` /
``ChannelBinding`` / ``QuantumDef`` and the canonical ``Family`` /
``RenderingModel`` enumerations the reference worker verticle holds
(``ImageRegionVerticle.java:72-81``), plus the default-settings construction
in ``ImageRegionRequestHandler.java:258-300`` (createRenderingDef).

Everything here is host-side metadata; the JAX kernels consume a packed
array-of-struct view produced by ``ops.render.pack_settings``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from .pixels import Pixels, pixels_type_range


class Family(enum.Enum):
    """Quantization family (= omeis.providers.re.quantum family strategies).

    The reference enumerates exactly these four
    (``ImageRegionVerticle.java:72-76``).
    """

    LINEAR = "linear"
    POLYNOMIAL = "polynomial"
    LOGARITHMIC = "logarithmic"
    EXPONENTIAL = "exponential"

    @property
    def index(self) -> int:
        return _FAMILY_INDEX[self]


_FAMILY_INDEX = {
    Family.LINEAR: 0,
    Family.POLYNOMIAL: 1,
    Family.LOGARITHMIC: 2,
    Family.EXPONENTIAL: 3,
}


class RenderingModel(enum.Enum):
    """Color model (= RenderingModel enumeration, greyscale/rgb;
    ``ImageRegionVerticle.java:78-81``)."""

    GREYSCALE = "greyscale"
    RGB = "rgb"


class Projection(enum.IntEnum):
    """Projection algorithm ids (= ome.api.IProjection constants consumed at
    ``ImageRegionCtx.java:377-387``)."""

    MAXIMUM_INTENSITY = 0
    MEAN_INTENSITY = 1
    SUM_INTENSITY = 2


@dataclass
class QuantumDef:
    """Codomain interval + bit resolution (= ome.model.display.QuantumDef).

    Defaults mirror createRenderingDef
    (``ImageRegionRequestHandler.java:273-276``): cd interval [0, 255],
    8-bit resolution.
    """

    cd_start: int = 0
    cd_end: int = 255
    bit_resolution: int = 255


@dataclass
class ChannelBinding:
    """Per-channel rendering settings (= ome.model.display.ChannelBinding).

    Field defaults mirror createRenderingDef
    (``ImageRegionRequestHandler.java:281-298``): coefficient 1.0, no noise
    reduction, linear family, window from the type range, first three
    channels active, red color.
    """

    active: bool = True
    input_start: float = 0.0
    input_end: float = 255.0
    family: Family = Family.LINEAR
    coefficient: float = 1.0
    noise_reduction: bool = False
    red: int = 255
    green: int = 0
    blue: int = 0
    alpha: int = 255
    lut: Optional[str] = None          # e.g. "cool.lut"; None => RGBA color
    reverse_intensity: bool = False    # codomain chain ReverseIntensityContext

    @property
    def rgba(self) -> Tuple[int, int, int, int]:
        return (self.red, self.green, self.blue, self.alpha)


@dataclass
class RenderingDef:
    """Full rendering settings for one pixels set
    (= ome.model.display.RenderingDef)."""

    pixels: Pixels
    model: RenderingModel = RenderingModel.GREYSCALE
    quantum: QuantumDef = field(default_factory=QuantumDef)
    channel_bindings: List[ChannelBinding] = field(default_factory=list)

    def active_channels(self) -> List[int]:
        return [i for i, cb in enumerate(self.channel_bindings) if cb.active]

    def copy(self) -> "RenderingDef":
        return RenderingDef(
            pixels=self.pixels,
            model=self.model,
            quantum=replace(self.quantum),
            channel_bindings=[replace(cb) for cb in self.channel_bindings],
        )


def restrict_to_active(rdef: RenderingDef
                       ) -> Tuple[RenderingDef, List[int]]:
    """Drop inactive channel bindings so a renderer never reads or
    composites planes that contribute nothing.

    The reference reads all active channels inside
    ``renderAsPackedInt``; inactive channels in our kernels would be
    zero tables — correct but wasted I/O and HBM.  Order is preserved,
    so greyscale first-active semantics survive.  Shared by the device
    pipeline (``server.handler``) and the degraded-mode CPU path
    (``server.degraded``) — ONE implementation, so the two renders
    cannot silently diverge on channel selection.
    """
    active = rdef.active_channels()
    out = rdef.copy()
    out.channel_bindings = [replace(rdef.channel_bindings[i])
                            for i in active]
    return out, active


def default_rendering_def(pixels: Pixels) -> RenderingDef:
    """Default settings for a pixels set.

    Mirrors ``ImageRegionRequestHandler.createRenderingDef``
    (``ImageRegionRequestHandler.java:258-300``): greyscale model, 8-bit
    quantum, and per channel: linear family, coefficient 1, window = pixel
    type range, active for the first three channels, red color, alpha 255.
    """
    bindings = []
    lo, hi = pixels_type_range(pixels.pixels_type)
    for c in range(pixels.size_c):
        bindings.append(
            ChannelBinding(
                active=(c < 3),
                input_start=lo,
                input_end=hi,
                family=Family.LINEAR,
                coefficient=1.0,
                red=255,
                green=0,
                blue=0,
                alpha=255,
            )
        )
    return RenderingDef(
        pixels=pixels,
        model=RenderingModel.GREYSCALE,
        quantum=QuantumDef(),
        channel_bindings=bindings,
    )
