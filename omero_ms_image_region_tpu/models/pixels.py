"""Pixels metadata value objects.

Replaces the consumed surface of ``ome.model.core.Pixels`` /
``ome.model.enums.PixelsType`` and ``omeis.providers.re.metadata.StatsFactory``
(reference call sites: ``ImageRegionRequestHandler.java:281-298`` builds
default channel windows from ``StatsFactory.initPixelsRange(pixels)``;
``ProjectionService.java:66-73`` uses the type's bit size and value range).

The reference derives the default channel window from the pixel type's value
range; here that is a static dtype table (``PIXELS_TYPES``), which is exactly
what ``StatsFactory`` computes for integer types.  Float types default to the
unit interval, a policy choice for data that nearly always arrives with
explicit windows.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class PixelsType:
    """An OMERO pixel type: name, numpy dtype, value range, bit size."""

    value: str            # OMERO enumeration value, e.g. "uint16"
    dtype: str            # numpy dtype name
    min_value: float
    max_value: float
    bit_size: int

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)


def _int_type(value: str, dtype: str) -> PixelsType:
    info = np.iinfo(dtype)
    return PixelsType(value, dtype, float(info.min), float(info.max),
                      info.bits)


PIXELS_TYPES = {
    "int8": _int_type("int8", "int8"),
    "uint8": _int_type("uint8", "uint8"),
    "int16": _int_type("int16", "int16"),
    "uint16": _int_type("uint16", "uint16"),
    "int32": _int_type("int32", "int32"),
    "uint32": _int_type("uint32", "uint32"),
    # Float ranges: see module docstring.
    "float": PixelsType("float", "float32", 0.0, 1.0, 32),
    "double": PixelsType("double", "float64", 0.0, 1.0, 64),
    # 1-bit masks (ShapeMask path); stored packed, expanded on use.
    "bit": PixelsType("bit", "uint8", 0.0, 1.0, 1),
}


def pixels_type_range(pixels_type: str) -> Tuple[float, float]:
    """Default channel window for a pixel type (= StatsFactory.initPixelsRange)."""
    pt = PIXELS_TYPES[pixels_type]
    return (pt.min_value, pt.max_value)


@dataclass
class Pixels:
    """Pixels set metadata (dimensions + type), detached from any ORM.

    Mirrors the fields of ``ome.model.core.Pixels`` the reference actually
    reads: sizeX/Y/Z/C/T, pixels type, dimension order, image id
    (``ImageRegionRequestHandler.java:543-553`` constructs one with exactly
    these).
    """

    image_id: int
    pixels_type: str                 # key into PIXELS_TYPES
    size_x: int
    size_y: int
    size_z: int = 1
    size_c: int = 1
    size_t: int = 1
    dimension_order: str = "XYZCT"
    pixels_id: Optional[int] = None
    # Physical channel metadata the reference carries along (unused by math).
    channel_names: Tuple[str, ...] = field(default_factory=tuple)

    @property
    def type(self) -> PixelsType:
        return PIXELS_TYPES[self.pixels_type]

    def type_range(self) -> Tuple[float, float]:
        return pixels_type_range(self.pixels_type)

    def to_json(self) -> dict:
        return {
            "image_id": self.image_id,
            "pixels_type": self.pixels_type,
            "size_x": self.size_x,
            "size_y": self.size_y,
            "size_z": self.size_z,
            "size_c": self.size_c,
            "size_t": self.size_t,
            "dimension_order": self.dimension_order,
            "pixels_id": self.pixels_id,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Pixels":
        return cls(
            image_id=d["image_id"],
            pixels_type=d["pixels_type"],
            size_x=d["size_x"],
            size_y=d["size_y"],
            size_z=d.get("size_z", 1),
            size_c=d.get("size_c", 1),
            size_t=d.get("size_t", 1),
            dimension_order=d.get("dimension_order", "XYZCT"),
            pixels_id=d.get("pixels_id"),
        )
