"""OME-TIFF pixel source: serve real microscopy files directly.

``OmeTiffSource`` implements the :class:`.pixelsource.PixelSource`
protocol over a tiled/pyramidal OME-TIFF — the role Bio-Formats plays
behind the reference's ``PixelsService.getPixelBuffer``
(``ImageRegionRequestHandler.java:302-309``; dependency
``build.gradle:81-83``).  With this backend the service serves existing
OMERO exports drop-in, no re-ingest through ``build_pyramid``.

Layout understood (OME-TIFF 6.0):

- OME-XML in the first IFD's ImageDescription: ``Pixels`` geometry
  (SizeX/Y/Z/C/T, DimensionOrder, Type) and optional ``TiffData``
  plane->IFD mapping;
- one IFD per (z, c, t) plane, ordered by DimensionOrder when no
  TiffData elements are present;
- pyramid levels as SubIFD chains (tag 330) of each plane IFD;
- multi-file sets: TiffData UUID FileName entries map planes to sibling
  files in the same directory (opened lazily), and BinaryOnly stubs
  follow their MetadataFile pointer to the ``*.companion.ome`` — the
  standard multi-file OMERO export layout;
- plain (non-OME) TIFFs degrade gracefully: pages become Z sections of
  a single channel, or channels when SamplesPerPixel > 1.

Decoded segments go through a bounded per-source LRU so pans that
straddle tile boundaries do not re-inflate the same compressed tile.
"""

from __future__ import annotations

import os
import re
import threading
import xml.etree.ElementTree as ET
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..server.region import RegionDef
from .tiff import (IMAGE_DESCRIPTION, NEW_SUBFILE_TYPE,
                   SAMPLES_PER_PIXEL, Ifd, TiffFile)

# OME pixel Type values are exactly the OMERO pixels-type names the
# render path already understands (models/pixels.py dtype table).
_OME_TYPES = {"int8", "int16", "int32", "uint8", "uint16", "uint32",
              "float", "double", "bit"}

_SEG_CACHE_BYTES = 64 << 20

# Process-wide segment-decode pool (daemon threads, lazily built):
# sized for I/O + GIL-released native decode overlap rather than CPU
# parallelism, so it helps even on single-core hosts.
_DECODE_POOL = None
_DECODE_POOL_LOCK = threading.Lock()


def _decode_pool():
    global _DECODE_POOL
    with _DECODE_POOL_LOCK:
        if _DECODE_POOL is None:
            import concurrent.futures as cf
            _DECODE_POOL = cf.ThreadPoolExecutor(
                max_workers=max(4, (os.cpu_count() or 1) * 2),
                thread_name_prefix="tiffdec")
        return _DECODE_POOL


def _localname(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


class _NoDoctypeTreeBuilder(ET.TreeBuilder):
    """Tree builder whose ``doctype`` callback rejects the document —
    expat invokes it when the declaration is parsed, before any entity
    is used, so a billion-laughs payload never expands."""

    def doctype(self, name, pubid, system):
        raise ValueError(
            "OME-XML with a DTD/entity declaration is rejected "
            "(entity expansion is not OME and unsafe)")


def _find_pixels(root: ET.Element) -> Optional[ET.Element]:
    for el in root.iter():
        if _localname(el.tag) == "Pixels":
            return el
    return None


class OmeTiffSource:
    """PixelSource over one OME-TIFF (or plain TIFF) file."""

    def __init__(self, path: str):
        self.path = path
        self._tf = TiffFile(path)
        self._lock = threading.Lock()
        self._seg_cache: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._seg_cache_bytes = 0
        # Multi-file OME-TIFF: sibling files referenced by TiffData UUID
        # FileName entries, opened lazily and keyed by basename.  Key
        # None = the primary file.
        self._files: Dict[Optional[str], TiffFile] = {None: self._tf}
        # Page-based pyramids (plain TIFF): full-res page -> its
        # reduced-resolution page indices, in file order.
        self._page_levels: Dict[int, List[int]] = {}
        try:
            self._parse_layout()
        except BaseException:
            # Loud metadata failures (corrupt companion, rejected DTD,
            # unsupported layout) must not leak the already-open
            # descriptors to GC timing — servers probe hostile files.
            self.close()
            raise

    # ------------------------------------------------------------- layout

    def _file(self, key: Optional[str]) -> TiffFile:
        tf = self._files.get(key)
        if tf is None:
            sibling = os.path.join(os.path.dirname(self.path), key)
            if not os.path.exists(sibling):
                raise FileNotFoundError(
                    f"{self.path}: OME TiffData references missing "
                    f"file {key!r}")
            with self._lock:
                tf = self._files.get(key)
                if tf is None:
                    tf = self._files[key] = TiffFile(sibling)
        return tf

    @staticmethod
    def _fromstring_no_dtd(text) -> ET.Element:
        """``ET.fromstring`` with any DOCTYPE rejected at the parser.

        ElementTree expands internal entities, so a hostile
        ImageDescription carrying a billion-laughs DTD would balloon
        memory before any OME validation runs.  Real OME-XML never
        declares a DTD (the schema is XSD), so the presence of one IS
        the verdict.  The rejection rides the TreeBuilder ``doctype``
        callback — which expat fires when the declaration is parsed,
        before any entity use in the body — so it cannot be dodged by
        prolog padding or an exotic document encoding the way a raw
        substring scan of a decoded prefix could.
        """
        return ET.fromstring(
            text, parser=ET.XMLParser(target=_NoDoctypeTreeBuilder()))

    def _resolve_ome_root(self, desc: str) -> Optional[ET.Element]:
        """The OME root for this file — following a BinaryOnly pointer
        to its companion metadata file (``*.companion.ome``), the
        standard multi-file OMERO export layout."""
        try:
            root = self._fromstring_no_dtd(desc)
        except (ET.ParseError, ValueError):
            # Unparseable — or DTD-carrying, which is unparseable by
            # policy — descriptions degrade to plain-TIFF semantics,
            # exactly like any other non-OME ImageDescription.
            return None
        for el in root.iter():
            if _localname(el.tag) == "BinaryOnly":
                meta = el.get("MetadataFile")
                if not meta:
                    return root
                companion = os.path.join(
                    os.path.dirname(self.path), meta)
                if not os.path.exists(companion):
                    raise FileNotFoundError(
                        f"{self.path}: BinaryOnly metadata file "
                        f"{meta!r} not found")
                with open(companion, "rb") as f:
                    try:
                        return self._fromstring_no_dtd(f.read())
                    except (ET.ParseError, ValueError) as e:
                        # A present-but-corrupt (or DTD-carrying)
                        # companion must be as loud as a missing one —
                        # degrading to plain-TIFF semantics would serve
                        # wrong dimensions — and must name the file an
                        # operator has to go look at.
                        raise ValueError(
                            f"{self.path}: companion metadata "
                            f"{meta!r} rejected: {e}")
        return root

    def _parse_layout(self) -> None:
        tf = self._tf
        first = tf.ifds[0]
        desc = first.one(IMAGE_DESCRIPTION, "") or ""
        self.size_z = self.size_c = self.size_t = 1
        self.dimension_order = "XYZCT"
        self.pixels_type: Optional[str] = None
        self._interleaved_c = False   # channels live in SamplesPerPixel
        plane_map: Dict[Tuple[int, int, int],
                        Tuple[Optional[str], int]] = {}
        spp = int(first.one(SAMPLES_PER_PIXEL, 1))
        self_names = {None, os.path.basename(self.path)}

        px = None
        if "<OME" in desc or "<ome" in desc:
            root = self._resolve_ome_root(desc)
            px = _find_pixels(root) if root is not None else None

        if px is not None:
            self.size_z = int(px.get("SizeZ", 1))
            self.size_c = int(px.get("SizeC", 1))
            self.size_t = int(px.get("SizeT", 1))
            order = px.get("DimensionOrder", "XYZCT")
            if (len(order) == 5 and order[:2] == "XY"
                    and set(order[2:]) == set("ZCT")):
                self.dimension_order = order
            ptype = (px.get("Type") or "").lower()
            if ptype and ptype not in _OME_TYPES:
                raise ValueError(
                    f"{self.path}: unsupported OME pixel type {ptype!r}")
            self.pixels_type = ptype or None
            # Interleaved detection must precede TiffData mapping: with
            # channels in SamplesPerPixel, C is not an IFD dimension and
            # _advance() must not enumerate it.
            if spp > 1 and self.size_c == spp and len(tf.ifds) < (
                    self.size_z * self.size_c * self.size_t):
                self._interleaved_c = True
            for td in px:
                if _localname(td.tag) != "TiffData":
                    continue
                # Multi-file OME-TIFF: a UUID child's FileName names the
                # sibling holding these planes (same directory).
                file_key: Optional[str] = None
                for child in td:
                    if _localname(child.tag) == "UUID":
                        name = child.get("FileName")
                        if name and name not in self_names:
                            file_key = name
                fz = int(td.get("FirstZ", 0))
                fc = int(td.get("FirstC", 0))
                ft = int(td.get("FirstT", 0))
                ifd0 = int(td.get("IFD", 0))
                if td.get("PlaneCount") is not None:
                    count = int(td.get("PlaneCount"))
                elif td.get("IFD") is not None:
                    count = 1            # spec: IFD without PlaneCount
                else:
                    # Attribute-less TiffData covers the TARGET file's
                    # own IFDs in order (spec) — never the whole set's
                    # plane count, which for a multi-file entry would
                    # wrap plane coordinates and corrupt the map.
                    count = len(self._file(file_key).ifds)
                count = min(count, self._n_ifd_planes())
                for k in range(count):
                    z, c, t = self._advance(fz, fc, ft, k)
                    plane_map[(z, c, t)] = (file_key, ifd0 + k)
        else:
            # Plain TIFF: pages = Z sections; chunky RGB = channels.
            # Reduced-resolution pages (NewSubfileType bit 0 — the
            # pre-OME page-based pyramid layout vips/openslide-style
            # exporters write) attach as pyramid levels of the
            # preceding full-resolution page instead of masquerading
            # as extra Z sections.
            full_pages = []
            for i, page_ifd in enumerate(tf.ifds):
                if int(page_ifd.one(NEW_SUBFILE_TYPE, 0)) & 1:
                    if full_pages:
                        self._page_levels[full_pages[-1]].append(i)
                else:
                    full_pages.append(i)
                    self._page_levels[i] = []
            if len(full_pages) > 1 and not any(
                    self._page_levels[i] for i in full_pages):
                # Aperio SVS-style layout: vendors historically flag
                # NOTHING — page 0 is the tiled baseline, later TILED
                # pages with strictly smaller dims are pyramid levels,
                # and STRIPPED pages (thumbnail/label/macro) are
                # associated images, not Z sections.  Only applied when
                # page 0 is tiled and every other page fits the
                # pattern; equal-size tiled pages (a real tiled Z
                # stack) never match.
                base = tf.ifds[full_pages[0]]
                levels, associated, ok = [], 0, base.tiled
                for i in full_pages[1:]:
                    p = tf.ifds[i]
                    smaller = (p.width < base.width
                               and p.height < base.height)
                    if p.tiled and smaller:
                        levels.append(i)
                    elif not p.tiled and smaller:
                        associated += 1    # thumbnail/label/macro
                    else:
                        # Equal-size page (tiled or stripped): a
                        # genuine Z section — no vendor layout here.
                        ok = False
                        break
                if ok and (levels or associated):
                    levels.sort(key=lambda i: -tf.ifds[i].width)
                    full_pages = [full_pages[0]]
                    self._page_levels = {full_pages[0]: levels}
            if spp > 1:
                self.size_c = spp
                self._interleaved_c = True
            self.size_z = max(1, len(full_pages))
            for zi, page in enumerate(full_pages):
                plane_map[(zi, 0, 0)] = (None, page)
        if self.pixels_type is None:
            if first.bits == 1:
                self.pixels_type = "bit"
            else:
                self.pixels_type = {
                    "uint8": "uint8", "uint16": "uint16",
                    "uint32": "uint32", "int8": "int8", "int16": "int16",
                    "int32": "int32", "float32": "float",
                    "float64": "double",
                }[np.dtype(first.dtype()).name]

        n_ifd_planes = self._n_ifd_planes()
        multi_file = any(k is not None for k, _ in plane_map.values())
        if not multi_file and len(tf.ifds) < n_ifd_planes:
            # Single-file: every declared plane must have an IFD here.
            # Multi-file sets validate lazily at read (sibling files
            # open on first touch).
            raise ValueError(
                f"{self.path}: {len(tf.ifds)} IFDs < {n_ifd_planes} "
                f"planes declared by OME metadata")
        if not plane_map:
            for i in range(n_ifd_planes):
                plane_map[self._plane_of_index(i)] = (None, i)
        self._plane_map = plane_map

        # Pyramid: SubIFD chain of each plane IFD (OME-TIFF 6.0), or the
        # reduced-resolution page chain for plain pyramidal TIFFs.
        # Level dims come from the first plane; every plane must agree.
        # Geometry anchors on plane (0,0,0)'s full-res IFD — for a
        # thumbnail-first plain TIFF that is NOT page 0 (multi-file
        # sets whose first plane lives elsewhere keep the primary
        # file's first page as the anchor; files are homogeneous).
        anchor_key, anchor_page = plane_map.get((0, 0, 0), (None, 0))
        self._first_ifd = (tf.ifds[anchor_page]
                           if anchor_key is None
                           and anchor_page < len(tf.ifds) else first)
        first_levels = self._page_levels.get(anchor_page, []) \
            if anchor_key is None else []
        if first_levels:
            level_ifds = [tf.ifds[i] for i in first_levels]
        else:
            level_ifds = tf.sub_ifds(self._first_ifd)
        self._n_levels = 1 + len(level_ifds)
        self._level_dims: List[Tuple[int, int]] = [
            (self._first_ifd.width, self._first_ifd.height)
        ] + [(s.width, s.height) for s in level_ifds]
        self._level_ifds: Dict[Tuple[Optional[str], int, int], Ifd] = {}

    def _n_ifd_planes(self) -> int:
        """Planes that occupy their own IFD (interleaved C shares one)."""
        return (self.size_z * self.size_t if self._interleaved_c
                else self.size_z * self.size_c * self.size_t)

    def _order_dims(self):
        sizes = {"Z": self.size_z, "C": self.size_c, "T": self.size_t}
        if self._interleaved_c:
            sizes = {"Z": self.size_z, "C": 1, "T": self.size_t}
        return [(d, sizes[d]) for d in self.dimension_order[2:]]

    def _plane_of_index(self, i: int) -> Tuple[int, int, int]:
        coords = {"Z": 0, "C": 0, "T": 0}
        for dim, size in self._order_dims():
            coords[dim] = i % size
            i //= size
        return coords["Z"], coords["C"], coords["T"]

    def _advance(self, z: int, c: int, t: int, k: int
                 ) -> Tuple[int, int, int]:
        """plane (z,c,t) advanced k steps in DimensionOrder."""
        coords = {"Z": z, "C": c, "T": t}
        idx = 0
        mult = 1
        for dim, size in self._order_dims():
            idx += coords[dim] * mult
            mult *= size
        idx += k
        return self._plane_of_index(idx)

    def _ifd_for(self, z: int, c: int, t: int, level: int
                 ) -> Tuple[TiffFile, Ifd]:
        key_c = 0 if self._interleaved_c else c
        try:
            file_key, page = self._plane_map[(z, key_c, t)]
        except KeyError:
            raise ValueError(
                f"{self.path}: no IFD for plane z={z} c={c} t={t}")
        tf = self._file(file_key)
        if page >= len(tf.ifds):
            raise ValueError(
                f"{self.path}: plane z={z} c={c} t={t} maps to IFD "
                f"{page} but {file_key or 'this file'} has only "
                f"{len(tf.ifds)}")
        key = (file_key, page, level)
        ifd = self._level_ifds.get(key)
        if ifd is None:
            base = tf.ifds[page]
            if level == 0:
                ifd = base
            else:
                page_levels = (self._page_levels.get(page, [])
                               if file_key is None else [])
                levels = ([tf.ifds[i] for i in page_levels]
                          if page_levels else tf.sub_ifds(base))
                if level - 1 >= len(levels):
                    raise ValueError(
                        f"{self.path}: page {page} has no level "
                        f"{level}")
                ifd = levels[level - 1]
            with self._lock:
                self._level_ifds[key] = ifd
        return tf, ifd

    # ----------------------------------------------------------- protocol

    @property
    def dtype(self) -> np.dtype:
        return self._first_ifd.dtype()

    def resolution_levels(self) -> int:
        return self._n_levels

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        return list(self._level_dims)

    def tile_size(self) -> Tuple[int, int]:
        ifd = self._first_ifd
        if not ifd.tiled:
            # Strips: serve a square default rather than a width x rows
            # sliver (the reference's server-side tile-size default,
            # ``ImageRegionRequestHandler.java:797``).
            return (min(1024, ifd.width), min(1024, ifd.height))
        seg_h, seg_w, _, _ = self._tf.segment_grid(ifd)
        return (seg_w, seg_h)

    def _segment(self, tf: TiffFile, ifd: Ifd, page_key: tuple,
                 gy: int, gx: int) -> np.ndarray:
        key = (page_key, gy, gx)
        with self._lock:
            seg = self._seg_cache.get(key)
            if seg is not None:
                self._seg_cache.move_to_end(key)
                return seg
        seg = tf.read_segment(ifd, gy, gx)
        with self._lock:
            if key not in self._seg_cache:
                self._seg_cache[key] = seg
                self._seg_cache_bytes += seg.nbytes
                while self._seg_cache_bytes > _SEG_CACHE_BYTES:
                    _, old = self._seg_cache.popitem(last=False)
                    self._seg_cache_bytes -= old.nbytes
        return seg

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        sx, sy = self._level_dims[level]
        x0, y0 = region.x, region.y
        x1, y1 = x0 + region.width, y0 + region.height
        if not (0 <= x0 <= x1 <= sx and 0 <= y0 <= y1 <= sy):
            raise ValueError(
                f"region {region.as_tuple()} outside level {level} "
                f"bounds ({sx}x{sy})")
        tf, ifd = self._ifd_for(z, c, t, level)
        seg_h, seg_w, grid_y, grid_x = tf.segment_grid(ifd)
        sample = c if self._interleaved_c else 0
        out = np.empty((region.height, region.width), dtype=self.dtype)
        page_key = (z, 0 if self._interleaved_c else c, t, level)
        spans = []
        for gy in range(y0 // seg_h, min(grid_y, -(-y1 // seg_h))):
            for gx in range(x0 // seg_w, min(grid_x, -(-x1 // seg_w))):
                cy0, cx0 = gy * seg_h, gx * seg_w
                ix0, ix1 = max(x0, cx0), min(x1, cx0 + seg_w)
                iy0, iy1 = max(y0, cy0), min(y1, cy0 + seg_h)
                if ix0 >= ix1 or iy0 >= iy1:
                    continue
                spans.append((gy, gx, cy0, cx0, iy0, iy1, ix0, ix1))

        def fill(span) -> None:
            gy, gx, cy0, cx0, iy0, iy1, ix0, ix1 = span
            seg = self._segment(tf, ifd, page_key, gy, gx)
            out[iy0 - y0:iy1 - y0, ix0 - x0:ix1 - x0] = \
                seg[iy0 - cy0:iy1 - cy0, ix0 - cx0:ix1 - cx0, sample]

        # Multi-segment regions decode concurrently on the shared pool
        # (disjoint output slices; the native decoders release the GIL,
        # so preads and entropy decode overlap even single-core — the
        # cold first-touch path was serialized here).  Single-segment
        # reads (the common warm tile) stay inline.
        if len(spans) > 1:
            list(_decode_pool().map(fill, spans))
        else:
            for span in spans:
                fill(span)
        return out

    def get_stack(self, c: int, t: int) -> np.ndarray:
        sx, sy = self._level_dims[0]
        region = RegionDef(0, 0, sx, sy)
        return np.stack([
            self.get_region(z, c, t, region, 0)
            for z in range(self.size_z)
        ])

    def close(self) -> None:
        with self._lock:
            self._seg_cache.clear()
            self._seg_cache_bytes = 0
            files = list(self._files.values())
        for tf in files:
            tf.close()            # idempotent (file.close() is)

    def __del__(self):  # pragma: no cover - GC timing
        # The PixelsService LRU drops evicted sources WITHOUT closing
        # them (an in-flight request may still be reading); the last
        # reference closes the file handles here.
        try:
            for tf in self._files.values():
                tf.close()
        except Exception:
            pass


_TIFF_RE = re.compile(r"\.(ome\.)?tiff?$", re.IGNORECASE)


def find_tiff(image_dir: str) -> Optional[str]:
    """The image directory's TIFF file, if it holds one (sniffing seam
    used by ``PixelsService`` and ``LocalMetadataService``)."""
    import os
    if not os.path.isdir(image_dir):
        return None
    names = sorted(n for n in os.listdir(image_dir) if _TIFF_RE.search(n))
    # Prefer .ome.tif(f) over plain .tif(f) when both are present.
    for name in names:
        if ".ome." in name.lower():
            return os.path.join(image_dir, name)
    return os.path.join(image_dir, names[0]) if names else None
