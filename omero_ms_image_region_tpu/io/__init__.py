"""Pixel I/O layer: the TPU build's ``ome.io.nio`` equivalent.

Re-provides the PixelBuffer/PixelsService surface the reference consumes
(``ImageRegionRequestHandler.java:302-309, 444-455, 789-832``;
``ProjectionService.java:72``) as a Python protocol plus two backends:

  * :class:`~.memory.InMemoryPixelSource` — ndarray-backed (tests, projection
    re-render; ≙ ``InMemoryPlanarPixelBuffer``).
  * :class:`~.store.ChunkedPyramidStore` — an on-disk chunked, multi-
    resolution format (memmap reads, no external deps) standing in for the
    OMERO binary repository layout.
  * :class:`~.ometiff.OmeTiffSource` — real tiled/pyramidal OME-TIFF files
    (plus plain TIFF), read with the in-repo container parser
    (:mod:`.tiff`); written by :func:`~.tiffwrite.write_ome_tiff`.

``PixelsService`` sniffs the backend per image directory.
"""

from .pixelsource import PixelSource, TileRead  # noqa: F401
from .memory import InMemoryPixelSource  # noqa: F401
from .store import ChunkedPyramidStore, build_pyramid  # noqa: F401
from .ometiff import OmeTiffSource  # noqa: F401
from .tiffwrite import write_ome_tiff  # noqa: F401
from .service import PixelsService  # noqa: F401
