"""Pixel I/O layer: the TPU build's ``ome.io.nio`` equivalent.

Re-provides the PixelBuffer/PixelsService surface the reference consumes
(``ImageRegionRequestHandler.java:302-309, 444-455, 789-832``;
``ProjectionService.java:72``) as a Python protocol plus two backends:

  * :class:`~.memory.InMemoryPixelSource` — ndarray-backed (tests, projection
    re-render; ≙ ``InMemoryPlanarPixelBuffer``).
  * :class:`~.store.ChunkedPyramidStore` — an on-disk chunked, multi-
    resolution format (memmap reads, no external deps) standing in for the
    OMERO binary repository + Bio-Formats pyramid.
"""

from .pixelsource import PixelSource, TileRead  # noqa: F401
from .memory import InMemoryPixelSource  # noqa: F401
from .store import ChunkedPyramidStore, build_pyramid  # noqa: F401
from .service import PixelsService  # noqa: F401
