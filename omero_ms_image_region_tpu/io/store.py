"""On-disk chunked multi-resolution pixel store.

Stands in for the OMERO binary repository + Bio-Formats pyramid that back the
reference's ``PixelsService.getPixelBuffer`` (``ImageRegionRequestHandler
.java:302-309``).  No external formats (zarr/tifffile are not in the image),
so the layout is deliberately minimal and read-optimized:

  <root>/
    meta.json             image geometry + dtype + chunk + level table
    level_{n}.dat         all chunks of level n, row-major chunk grid per
                          plane, planes ordered [t][c][z]; every chunk is
                          padded to the full (chunk_h, chunk_w) so offsets
                          are a closed form and a tile read is 1..4
                          contiguous preads.

Chunks are padded with zeros; readers slice the valid interior using the
level dimensions.  This is the same trade zarr makes (fixed chunk grid,
edge padding) and keeps the door open for an O_DIRECT / C++ pread pool.
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..server.region import RegionDef

_META = "meta.json"


class ChunkedPyramidStore:
    """PixelSource over the on-disk chunked pyramid layout."""

    def __init__(self, root: str):
        self.root = root
        with open(os.path.join(root, _META)) as f:
            self.meta = json.load(f)
        m = self.meta
        self._dtype = np.dtype(m["dtype"])
        self.size_z = m["size_z"]
        self.size_c = m["size_c"]
        self.size_t = m["size_t"]
        self.chunk_h = m["chunk_h"]
        self.chunk_w = m["chunk_w"]
        self._level_dims: List[Tuple[int, int]] = [
            (lv["size_x"], lv["size_y"]) for lv in m["levels"]
        ]
        self._maps: List[Optional[np.memmap]] = [None] * len(self._level_dims)

    # -- geometry -----------------------------------------------------------

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    def resolution_levels(self) -> int:
        return len(self._level_dims)

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        return list(self._level_dims)

    def tile_size(self) -> Tuple[int, int]:
        return (self.chunk_w, self.chunk_h)

    def _grid(self, level: int) -> Tuple[int, int]:
        sx, sy = self._level_dims[level]
        return (-(-sy // self.chunk_h), -(-sx // self.chunk_w))  # (gy, gx)

    def _map_level(self, level: int) -> np.memmap:
        mm = self._maps[level]
        if mm is None:
            gy, gx = self._grid(level)
            shape = (self.size_t, self.size_c, self.size_z, gy, gx,
                     self.chunk_h, self.chunk_w)
            mm = np.memmap(
                os.path.join(self.root, f"level_{level}.dat"),
                dtype=self._dtype, mode="r", shape=shape,
            )
            self._maps[level] = mm
        return mm

    # -- reads --------------------------------------------------------------

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        sx, sy = self._level_dims[level]
        x0, y0 = region.x, region.y
        x1, y1 = x0 + region.width, y0 + region.height
        if not (0 <= x0 <= x1 <= sx and 0 <= y0 <= y1 <= sy):
            raise ValueError(
                f"region {region.as_tuple()} outside level {level} "
                f"bounds ({sx}x{sy})"
            )
        mm = self._map_level(level)
        out = np.empty((region.height, region.width), dtype=self._dtype)
        ch, cw = self.chunk_h, self.chunk_w
        for gy in range(y0 // ch, -(-y1 // ch)):
            for gx in range(x0 // cw, -(-x1 // cw)):
                cy0, cx0 = gy * ch, gx * cw
                ix0, ix1 = max(x0, cx0), min(x1, cx0 + cw)
                iy0, iy1 = max(y0, cy0), min(y1, cy0 + ch)
                if ix0 >= ix1 or iy0 >= iy1:
                    continue
                chunk = mm[t, c, z, gy, gx]
                out[iy0 - y0:iy1 - y0, ix0 - x0:ix1 - x0] = \
                    chunk[iy0 - cy0:iy1 - cy0, ix0 - cx0:ix1 - cx0]
        return out

    def get_stack(self, c: int, t: int) -> np.ndarray:
        sx, sy = self._level_dims[0]
        region = RegionDef(0, 0, sx, sy)
        return np.stack([
            self.get_region(z, c, t, region, 0) for z in range(self.size_z)
        ])

    def close(self) -> None:
        self._maps = [None] * len(self._level_dims)


def _downsample2(plane: np.ndarray) -> np.ndarray:
    """Mean-pool by 2 (the usual pyramid reduction)."""
    h, w = plane.shape[0] // 2, plane.shape[1] // 2
    if h < 1 or w < 1:
        return plane[:1, :1]
    v = plane[: h * 2, : w * 2].astype(np.float64)
    v = v.reshape(h, 2, w, 2).mean(axis=(1, 3))
    if np.issubdtype(plane.dtype, np.integer):
        v = np.round(v)
    return v.astype(plane.dtype)


def build_pyramid(
    planes: np.ndarray,
    root: str,
    chunk: Tuple[int, int] = (256, 256),
    n_levels: Optional[int] = None,
    min_level_size: int = 256,
) -> ChunkedPyramidStore:
    """Write a [C, Z, H, W] (or [T, C, Z, H, W]) array as a chunked pyramid.

    ``n_levels=None`` halves until min(w, h) < min_level_size (the
    Bio-Formats-style pyramid the reference serves via resolution levels).
    """
    if planes.ndim == 4:
        planes = planes[None]
    if planes.ndim != 5:
        raise ValueError("planes must be [T, C, Z, H, W] or [C, Z, H, W]")
    T, C, Z, H, W = planes.shape
    ch, cw = chunk[1], chunk[0]

    levels = [planes]
    while True:
        if n_levels is not None and len(levels) >= n_levels:
            break
        _, _, _, h, w = levels[-1].shape
        if n_levels is None and min(h // 2, w // 2) < min_level_size:
            break
        if min(h // 2, w // 2) < 1:
            break
        prev = levels[-1]
        ds = np.stack([
            np.stack([
                np.stack([_downsample2(prev[t, c, z])
                          for z in range(Z)])
                for c in range(C)
            ])
            for t in range(T)
        ])
        levels.append(ds)

    os.makedirs(root, exist_ok=True)
    meta = {
        "version": 1,
        "dtype": planes.dtype.name,
        "size_z": Z, "size_c": C, "size_t": T,
        "chunk_h": ch, "chunk_w": cw,
        "levels": [
            {"size_x": lv.shape[-1], "size_y": lv.shape[-2]}
            for lv in levels
        ],
    }
    with open(os.path.join(root, _META), "w") as f:
        json.dump(meta, f)

    for n, lv in enumerate(levels):
        h, w = lv.shape[-2:]
        gy, gx = -(-h // ch), -(-w // cw)
        mm = np.memmap(
            os.path.join(root, f"level_{n}.dat"), dtype=planes.dtype,
            mode="w+", shape=(T, C, Z, gy, gx, ch, cw),
        )
        mm[:] = 0
        for t in range(T):
            for c in range(C):
                for z in range(Z):
                    for y in range(gy):
                        for x in range(gx):
                            part = lv[t, c, z, y * ch:(y + 1) * ch,
                                      x * cw:(x + 1) * cw]
                            mm[t, c, z, y, x, : part.shape[0],
                               : part.shape[1]] = part
        mm.flush()
        del mm
    return ChunkedPyramidStore(root)
