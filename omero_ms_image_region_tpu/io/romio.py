"""Pre-FS OMERO pixel buffer: raw planes under ``<data.dir>/Pixels/<id>``.

Images imported before OMERO 5's ManagedRepository keep their pixel data
in the legacy ROMIO layout the reference reads through
``ome.io.nio.PixelsService`` (the ``/OMERO/Pixels`` bean,
``beanRefContext.xml:13-16``; ``config.yaml:19-20`` ``omero.data.dir``):
one file per Pixels row holding size_z*size_c*size_t raw planes,
**big-endian**, plane order z-fastest (XYZCT: index =
z + size_z * (c + size_c * t)), no pyramid.

Geometry and pixel type are not in the file — they come from the
``pixels`` DB row, which is exactly what the resolving caller
(``services.db_metadata.resolve_image_paths`` + ``io.service``) has in
hand.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

from ..models.pixels import Pixels
from ..server.region import RegionDef


class RomioPixelSource:
    """PixelSource over one legacy ROMIO pixels file."""

    def __init__(self, path: str, pixels: Pixels):
        self.path = path
        self._px = pixels
        self._dtype = np.dtype(pixels.type.np_dtype)
        self._plane_px = pixels.size_x * pixels.size_y
        plane_bytes = self._plane_px * self._dtype.itemsize
        n_planes = pixels.size_z * pixels.size_c * pixels.size_t
        self._plane_bytes = plane_bytes
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        if size < n_planes * plane_bytes:
            self._f.close()
            raise ValueError(
                f"{path}: ROMIO file holds {size} bytes, geometry needs "
                f"{n_planes * plane_bytes}")

    # ------------------------------------------------------------- layout

    def _plane_offset(self, z: int, c: int, t: int) -> int:
        px = self._px
        if not (0 <= z < px.size_z and 0 <= c < px.size_c
                and 0 <= t < px.size_t):
            raise ValueError(f"plane ({z}, {c}, {t}) out of bounds")
        return (z + px.size_z * (c + px.size_c * t)) * self._plane_bytes

    # ----------------------------------------------------------- protocol

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    def resolution_levels(self) -> int:
        return 1

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        return [(self._px.size_x, self._px.size_y)]

    def tile_size(self) -> Tuple[int, int]:
        # The reference's server default tile for non-tiled buffers.
        return (min(self._px.size_x, 256), min(self._px.size_y, 256))

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        if level != 0:
            raise ValueError("ROMIO buffers have no pyramid levels")
        px = self._px
        x0, y0, w, h = region.x, region.y, region.width, region.height
        if not (0 <= x0 and 0 <= y0 and x0 + w <= px.size_x
                and y0 + h <= px.size_y and w > 0 and h > 0):
            raise ValueError(f"region {region.as_tuple()} out of bounds")
        base = self._plane_offset(z, c, t)
        item = self._dtype.itemsize
        if w == px.size_x:
            # Full-width rows are one contiguous span.
            off = base + y0 * px.size_x * item
            data = os.pread(self._f.fileno(), h * w * item, off)
            if len(data) != h * w * item:
                raise EOFError(f"{self.path}: short read")
            out = np.frombuffer(data, self._dtype.newbyteorder(">"),
                                count=h * w).reshape(h, w)
        else:
            rows = []
            for y in range(y0, y0 + h):
                off = base + (y * px.size_x + x0) * item
                data = os.pread(self._f.fileno(), w * item, off)
                if len(data) != w * item:
                    raise EOFError(f"{self.path}: short read")
                rows.append(np.frombuffer(
                    data, self._dtype.newbyteorder(">"), count=w))
            out = np.stack(rows)
        return np.ascontiguousarray(
            out.astype(self._dtype.newbyteorder("="), copy=False))

    def get_stack(self, c: int, t: int) -> np.ndarray:
        px = self._px
        region = RegionDef(0, 0, px.size_x, px.size_y)
        return np.stack([self.get_region(z, c, t, region, 0)
                         for z in range(px.size_z)])

    def close(self) -> None:
        self._f.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self._f.close()
        except Exception:
            pass
