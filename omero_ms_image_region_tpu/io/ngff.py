"""OME-NGFF (zarr v2) pixel source and writer, from scratch.

The reference serves any format Bio-Formats can read behind
``PixelsService.getPixelBuffer`` (``build.gradle:81-83``; call site
``ImageRegionRequestHandler.java:302-309``); OME-NGFF is the format
modern OMERO pyramids migrate to.  No zarr/numcodecs libraries exist in
this image, so — like the TIFF/JPEG/J2K stack — the format is
implemented directly against its spec:

  * zarr v2 array metadata (``.zarray``: shape, chunks, dtype as NumPy
    typestr, compressor, order, fill_value, dimension_separator);
  * chunk codecs: ``null`` (raw), ``zlib`` and ``gzip`` (both stdlib);
    blosc/lz4/zstd are rejected with a clear error naming the codec —
    they need libraries this image does not ship;
  * OME-NGFF ``multiscales`` group metadata (``.zattrs``), v0.1-0.4:
    named axes when present (v0.4), else the fixed tczyx order of the
    earlier versions; the datasets list maps to pyramid levels largest
    first — exactly the ``resolution_descriptions`` contract the
    request handler consumes.

Layout notes shared with the rest of the io/ stack: chunks are a fixed
grid with edge chunks stored FULL-SIZE and sliced on read (zarr's own
trade), missing chunk files mean ``fill_value``, and a region read
touches only the chunks it overlaps — WSI planes are never
materialized.
"""

from __future__ import annotations

import gzip
import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..server.region import RegionDef

_SUPPORTED_COMPRESSORS = (None, "zlib", "gzip")


class NgffError(ValueError):
    """Malformed or unsupported NGFF/zarr data."""


class ZarrV2Array:
    """One zarr v2 array (one pyramid level): lazy per-chunk reads."""

    def __init__(self, root: str):
        self.root = root
        try:
            with open(os.path.join(root, ".zarray")) as f:
                meta = json.load(f)
        except OSError as e:
            raise NgffError(f"not a zarr array: {root}: {e}")
        if meta.get("zarr_format") != 2:
            raise NgffError(
                f"unsupported zarr_format {meta.get('zarr_format')!r} "
                f"(only v2)")
        self.shape = tuple(int(s) for s in meta["shape"])
        self.chunks = tuple(int(c) for c in meta["chunks"])
        if len(self.shape) != len(self.chunks):
            raise NgffError("shape/chunks rank mismatch")
        try:
            self._stored_dtype = np.dtype(meta["dtype"])
        except TypeError:
            raise NgffError(f"unsupported dtype {meta['dtype']!r}")
        # Serve native byte order: big-endian zarr is spec-legal but
        # the render/staging path needs native ndarrays (the TIFF
        # reader normalizes the same way).
        self.dtype = self._stored_dtype.newbyteorder("=")
        if meta.get("order", "C") != "C":
            raise NgffError("only C-order zarr arrays are supported")
        if meta.get("filters"):
            raise NgffError("zarr filters are not supported")
        comp = meta.get("compressor")
        if comp is None:
            self.codec = None
        else:
            cid = comp.get("id")
            if cid not in _SUPPORTED_COMPRESSORS:
                raise NgffError(
                    f"unsupported zarr compressor {cid!r} (supported: "
                    f"raw, zlib, gzip; blosc/lz4/zstd need libraries "
                    f"not present in this deployment)")
            self.codec = cid
        fv = meta.get("fill_value", 0)
        self.fill_value = 0 if fv is None else fv
        self.sep = meta.get("dimension_separator", ".")
        if self.sep not in (".", "/"):
            raise NgffError(f"bad dimension_separator {self.sep!r}")

    def _chunk_path(self, idx: Tuple[int, ...]) -> str:
        name = self.sep.join(str(i) for i in idx)
        return os.path.join(self.root, name)

    def read_chunk(self, idx: Tuple[int, ...]) -> Optional[np.ndarray]:
        """Decode one chunk to its FULL chunk shape; None = missing
        (caller substitutes fill_value)."""
        path = self._chunk_path(idx)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            if self.codec == "zlib":
                raw = zlib.decompress(raw)
            elif self.codec == "gzip":
                raw = gzip.decompress(raw)
        except (zlib.error, gzip.BadGzipFile, EOFError) as e:
            # Corrupt chunk payloads surface as the reader's clean
            # error class (zlib.error is neither ValueError nor
            # OSError and would escape the server's 4xx mapping).
            raise NgffError(f"chunk {path}: {e}")
        n = int(np.prod(self.chunks))
        arr = np.frombuffer(raw, dtype=self._stored_dtype, count=-1)
        if arr.size != n:
            raise NgffError(
                f"chunk {path}: {arr.size} items, expected {n}")
        if self._stored_dtype != self.dtype:
            arr = arr.astype(self.dtype)      # byte-order normalize
        return arr.reshape(self.chunks)


def _axis_order(attrs: dict, rank: int) -> Dict[str, int]:
    """Map axis name -> dimension index.

    v0.4 lists named axes; earlier versions fixed the order as tczyx
    (truncated from the left for lower-rank arrays).
    """
    ms = attrs["multiscales"][0]
    axes = ms.get("axes")
    if axes:
        names = [a["name"] if isinstance(a, dict) else a for a in axes]
    else:
        names = list("tczyx"[-rank:])
    if len(names) != rank:
        raise NgffError(
            f"axes rank {len(names)} != array rank {rank}")
    if "x" not in names or "y" not in names:
        raise NgffError("multiscales axes must include x and y")
    return {n: i for i, n in enumerate(names)}


class NgffZarrSource:
    """PixelSource over an OME-NGFF multiscales group (or a bare zarr
    array, served as a single-level image).

    ≙ the Bio-Formats-backed ``PixelBuffer`` role
    (``ImageRegionRequestHandler.java:302-309``): region reads at a
    pyramid level, stack reads for projection, level enumeration
    largest-first, preferred tile size from the chunk grid.
    """

    def __init__(self, root: str):
        self.root = root
        self._levels: List[ZarrV2Array] = []
        if os.path.exists(os.path.join(root, ".zarray")):
            # Bare array: one level, axes by rank (tczyx tail).
            arr = ZarrV2Array(root)
            self._levels = [arr]
            self._axes = {n: i for i, n in enumerate(
                "tczyx"[-len(arr.shape):])}
            if "x" not in self._axes or "y" not in self._axes:
                raise NgffError("zarr array rank must be >= 2")
        else:
            try:
                with open(os.path.join(root, ".zattrs")) as f:
                    attrs = json.load(f)
            except OSError as e:
                raise NgffError(f"not an NGFF group: {root}: {e}")
            if "multiscales" not in attrs or not attrs["multiscales"]:
                raise NgffError(f"{root}: no multiscales metadata")
            datasets = attrs["multiscales"][0].get("datasets") or []
            if not datasets:
                raise NgffError(f"{root}: empty multiscales datasets")
            for d in datasets:
                self._levels.append(
                    ZarrV2Array(os.path.join(root, d["path"])))
            self._axes = _axis_order(attrs, len(self._levels[0].shape))
            # Spec orders datasets largest-first; verify rather than
            # trust (the request handler indexes levels by resolution).
            xs = [lv.shape[self._axes["x"]] for lv in self._levels]
            if xs != sorted(xs, reverse=True):
                raise NgffError(
                    f"{root}: multiscales datasets not largest-first")
            ranks = {len(lv.shape) for lv in self._levels}
            if len(ranks) != 1:
                raise NgffError(f"{root}: mixed-rank pyramid levels")

        lv0 = self._levels[0]
        ax = self._axes
        self.size_x = lv0.shape[ax["x"]]
        self.size_y = lv0.shape[ax["y"]]
        self.size_z = lv0.shape[ax["z"]] if "z" in ax else 1
        self.size_c = lv0.shape[ax["c"]] if "c" in ax else 1
        self.size_t = lv0.shape[ax["t"]] if "t" in ax else 1

    # -- geometry -------------------------------------------------------

    @property
    def dtype(self) -> np.dtype:
        return self._levels[0].dtype

    def resolution_levels(self) -> int:
        return len(self._levels)

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        ax = self._axes
        return [(lv.shape[ax["x"]], lv.shape[ax["y"]])
                for lv in self._levels]

    def tile_size(self) -> Tuple[int, int]:
        lv0 = self._levels[0]
        ax = self._axes
        return (lv0.chunks[ax["x"]], lv0.chunks[ax["y"]])

    # -- reads ----------------------------------------------------------

    def _index_for(self, lv: ZarrV2Array, z: int, c: int, t: int
                   ) -> List[int]:
        """Fixed (non-spatial) chunk-grid indices + a slot per axis."""
        ax = self._axes
        idx = [0] * len(lv.shape)
        for name, val in (("z", z), ("c", c), ("t", t)):
            if name in ax:
                size = lv.shape[ax[name]]
                if not (0 <= val < size):
                    raise ValueError(
                        f"{name}={val} outside [0, {size})")
                idx[ax[name]] = val
            elif val not in (0, None):
                raise ValueError(f"{name}={val} but image has no "
                                 f"{name} axis")
        return idx

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        lv = self._levels[level]
        ax = self._axes
        xi, yi = ax["x"], ax["y"]
        sx, sy = lv.shape[xi], lv.shape[yi]
        x0, y0 = region.x, region.y
        x1, y1 = x0 + region.width, y0 + region.height
        if not (0 <= x0 <= x1 <= sx and 0 <= y0 <= y1 <= sy):
            raise ValueError(
                f"region {region.as_tuple()} outside level {level} "
                f"bounds ({sx}x{sy})")
        base = self._index_for(lv, z, c, t)
        ch, cw = lv.chunks[yi], lv.chunks[xi]
        out = np.full((region.height, region.width), self._fill(lv),
                      dtype=lv.dtype)
        # Non-spatial axes: chunk index = coordinate // chunk-extent,
        # intra-chunk offset = coordinate % chunk-extent.
        fixed_chunk = [v // lv.chunks[d] for d, v in enumerate(base)]
        fixed_off = [v % lv.chunks[d] for d, v in enumerate(base)]
        for gy in range(y0 // ch, -(-y1 // ch)):
            for gx in range(x0 // cw, -(-x1 // cw)):
                cy0, cx0 = gy * ch, gx * cw
                iy0, iy1 = max(y0, cy0), min(y1, cy0 + ch)
                ix0, ix1 = max(x0, cx0), min(x1, cx0 + cw)
                if ix0 >= ix1 or iy0 >= iy1:
                    continue
                cidx = list(fixed_chunk)
                cidx[yi], cidx[xi] = gy, gx
                chunk = lv.read_chunk(tuple(cidx))
                if chunk is None:
                    continue              # stays fill_value
                sel: List[object] = [off for off in fixed_off]
                sel[yi] = slice(iy0 - cy0, iy1 - cy0)
                sel[xi] = slice(ix0 - cx0, ix1 - cx0)
                piece = chunk[tuple(sel)]
                if yi > xi:               # axes order put x before y
                    piece = piece.T
                out[iy0 - y0:iy1 - y0, ix0 - x0:ix1 - x0] = piece
        return out

    @staticmethod
    def _fill(lv: ZarrV2Array):
        fv = lv.fill_value
        if isinstance(fv, str):           # zarr spec: "NaN", "Infinity"
            fv = float(fv.replace("Infinity", "inf"))
        return np.asarray(fv, dtype=lv.dtype)

    def get_stack(self, c: int, t: int) -> np.ndarray:
        region = RegionDef(0, 0, self.size_x, self.size_y)
        return np.stack([
            self.get_region(z, c, t, region, 0)
            for z in range(self.size_z)
        ])

    def close(self) -> None:
        pass                               # per-read file handles only


# ---------------------------------------------------------------- writer

def _downsample2(plane: np.ndarray) -> np.ndarray:
    from .store import _downsample2 as ds
    return ds(plane)


def write_ngff(planes: np.ndarray, root: str,
               chunk: Tuple[int, int] = (256, 256),
               n_levels: Optional[int] = None,
               min_level_size: int = 256,
               compressor: Optional[str] = "zlib",
               dimension_separator: str = ".") -> "NgffZarrSource":
    """Write [C, Z, H, W] (or [T, C, Z, H, W]) as an OME-NGFF v0.4
    multiscales zarr-v2 group — the ingest-side counterpart of
    :class:`NgffZarrSource` (mirrors ``store.build_pyramid``'s halving
    policy so the two backends produce identical level tables)."""
    if planes.ndim == 4:
        planes = planes[None]
    if planes.ndim != 5:
        raise ValueError("planes must be [T, C, Z, H, W] or [C, Z, H, W]")
    if compressor not in _SUPPORTED_COMPRESSORS:
        raise ValueError(f"unsupported compressor {compressor!r}")
    T, C, Z, H, W = planes.shape

    levels = [planes]
    while True:
        if n_levels is not None and len(levels) >= n_levels:
            break
        h, w = levels[-1].shape[-2:]
        if n_levels is None and min(h // 2, w // 2) < min_level_size:
            break
        if min(h // 2, w // 2) < 1:
            break
        prev = levels[-1]
        levels.append(np.stack([
            np.stack([
                np.stack([_downsample2(prev[t, c, z])
                          for z in range(Z)])
                for c in range(C)
            ])
            for t in range(T)
        ]))

    os.makedirs(root, exist_ok=True)
    write_ngff_group_meta(root, len(levels))
    for n, lv in enumerate(levels):
        write_ngff_level_dir(os.path.join(root, str(n)), lv, chunk,
                             compressor, dimension_separator)
    return NgffZarrSource(root)


def write_ngff_group_meta(root: str, n_levels: int) -> None:
    """Write the group markers (``.zgroup`` + multiscales ``.zattrs``).

    Split out of :func:`write_ngff` so the crash-safe pyramid job
    (``server.jobs``) can write it LAST: :class:`NgffZarrSource` (and
    ``find_ngff``) refuse a root without these markers, which makes the
    ``.zattrs`` write the commit point of an incremental build."""
    with open(os.path.join(root, ".zgroup"), "w") as f:
        json.dump({"zarr_format": 2}, f)
    attrs = {
        "multiscales": [{
            "version": "0.4",
            "name": os.path.basename(root.rstrip("/")),
            "axes": [
                {"name": "t", "type": "time"},
                {"name": "c", "type": "channel"},
                {"name": "z", "type": "space"},
                {"name": "y", "type": "space"},
                {"name": "x", "type": "space"},
            ],
            "datasets": [
                {"path": str(n),
                 "coordinateTransformations": [
                     {"type": "scale",
                      "scale": [1.0, 1.0, 1.0,
                                float(2 ** n), float(2 ** n)]}]}
                for n in range(n_levels)
            ],
        }]
    }
    with open(os.path.join(root, ".zattrs"), "w") as f:
        json.dump(attrs, f)


def write_ngff_level_dir(adir: str, lv: np.ndarray,
                         chunk: Tuple[int, int] = (256, 256),
                         compressor: Optional[str] = "zlib",
                         dimension_separator: str = ".") -> None:
    """Write ONE level array ([T, C, Z, h, w]) as a zarr-v2 array dir.

    Deterministic output (fixed chunk grid, zlib/gzip level 1), so two
    writes of the same array produce identical bytes — what lets a
    resumed pyramid build be byte-stable against its killed
    predecessor.  The caller picks ``adir``: :func:`write_ngff` writes
    in place, the pyramid job writes a ``.tmp`` sibling and
    ``os.replace``s it in as the level's atomic commit."""
    if lv.ndim != 5:
        raise ValueError("level must be [T, C, Z, h, w]")
    if compressor not in _SUPPORTED_COMPRESSORS:
        raise ValueError(f"unsupported compressor {compressor!r}")
    T, C, Z, h, w = lv.shape
    cw, ch = chunk
    os.makedirs(adir, exist_ok=True)
    zmeta = {
        "zarr_format": 2,
        "shape": [T, C, Z, h, w],
        "chunks": [1, 1, 1, ch, cw],
        "dtype": lv.dtype.str,
        "compressor": ({"id": compressor} if compressor else None),
        "order": "C",
        "filters": None,
        "fill_value": 0,
        "dimension_separator": dimension_separator,
    }
    with open(os.path.join(adir, ".zarray"), "w") as f:
        json.dump(zmeta, f)
    gy, gx = -(-h // ch), -(-w // cw)
    for t in range(T):
        for c in range(C):
            for z in range(Z):
                for y in range(gy):
                    for x in range(gx):
                        full = np.zeros((1, 1, 1, ch, cw), lv.dtype)
                        part = lv[t, c, z, y * ch:(y + 1) * ch,
                                  x * cw:(x + 1) * cw]
                        full[0, 0, 0, :part.shape[0],
                             :part.shape[1]] = part
                        raw = full.tobytes()
                        if compressor == "zlib":
                            raw = zlib.compress(raw, 1)
                        elif compressor == "gzip":
                            raw = gzip.compress(raw, 1)
                        name = dimension_separator.join(
                            map(str, (t, c, z, y, x)))
                        path = os.path.join(adir, name)
                        if dimension_separator == "/":
                            os.makedirs(os.path.dirname(path),
                                        exist_ok=True)
                        with open(path, "wb") as f:
                            f.write(raw)


def find_ngff(d: str) -> Optional[str]:
    """Locate an NGFF/zarr root under an image directory: the directory
    itself, or a single ``*.zarr`` / ``*.ome.zarr`` child."""
    if not os.path.isdir(d):
        return None
    if (os.path.exists(os.path.join(d, ".zattrs"))
            or os.path.exists(os.path.join(d, ".zarray"))):
        return d
    kids = [k for k in sorted(os.listdir(d))
            if k.lower().endswith(".zarr")
            and os.path.isdir(os.path.join(d, k))]
    for k in kids:
        sub = os.path.join(d, k)
        if (os.path.exists(os.path.join(sub, ".zattrs"))
                or os.path.exists(os.path.join(sub, ".zarray"))):
            return sub
    return None
