"""Minimal TIFF container reader (classic + BigTIFF).

This is the format layer under :class:`..io.ometiff.OmeTiffSource` — the
capability the reference gets from Bio-Formats behind
``PixelsService.getPixelBuffer`` (``ImageRegionRequestHandler.java:302-309``,
memoizer bean ``beanRefContext.xml:19-21``).  No external TIFF library
exists in this image (tifffile/zarr absent), so the container is parsed
directly; scope is exactly what serving needs:

- classic (magic 42) and BigTIFF (magic 43), both byte orders;
- tiled (322/323/324/325) and stripped (273/278/279) image data;
- compression: none (1), old-style JPEG (6, interchange-format layout),
  LZW (5), new-style JPEG (7, baseline; tables from tag 347, via
  ``io/jpegdec``), deflate (8 / 32946), PackBits (32773), Aperio
  JPEG 2000 (33003/33005, via ``io/jp2k``);
- predictors (317): horizontal differencing (2) and floating-point
  byte differencing (3, TIFF TechNote 3); unknown ids reject loudly;
- SubIFD chains (330) — OME-TIFF 6.0 stores pyramid levels there;
- sample types: u8/u16/u32, i8/i16/i32, f32/f64 via 258/339.

Everything is read lazily with ``pread``-style slices off one file
handle; decoded segments are cached by the caller, not here.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# TIFF tag ids (TIFF 6.0 spec; names per the spec).
NEW_SUBFILE_TYPE = 254      # bit 0 = reduced-resolution page
IMAGE_WIDTH = 256
IMAGE_LENGTH = 257
BITS_PER_SAMPLE = 258
COMPRESSION = 259
PHOTOMETRIC = 262
IMAGE_DESCRIPTION = 270
STRIP_OFFSETS = 273
SAMPLES_PER_PIXEL = 277
ROWS_PER_STRIP = 278
STRIP_BYTE_COUNTS = 279
PLANAR_CONFIG = 284
PREDICTOR = 317
TILE_WIDTH = 322
TILE_LENGTH = 323
TILE_OFFSETS = 324
TILE_BYTE_COUNTS = 325
SUB_IFDS = 330
SAMPLE_FORMAT = 339
JPEG_TABLES = 347
JPEG_INTERCHANGE = 513          # old-style JPEG (compression 6)
JPEG_INTERCHANGE_LEN = 514

# field type -> (struct code, byte size); struct code None = opaque bytes
_TYPES: Dict[int, Tuple[Optional[str], int]] = {
    1: ("B", 1),    # BYTE
    2: (None, 1),   # ASCII
    3: ("H", 2),    # SHORT
    4: ("I", 4),    # LONG
    5: (None, 8),   # RATIONAL
    6: ("b", 1),    # SBYTE
    7: (None, 1),   # UNDEFINED
    8: ("h", 2),    # SSHORT
    9: ("i", 4),    # SLONG
    10: (None, 8),  # SRATIONAL
    11: ("f", 4),   # FLOAT
    12: ("d", 8),   # DOUBLE
    13: ("I", 4),   # IFD
    16: ("Q", 8),   # LONG8 (BigTIFF)
    17: ("q", 8),   # SLONG8
    18: ("Q", 8),   # IFD8
}


# (BitsPerSample, SampleFormat) -> numpy dtype — the storage dtypes
# this reader can stage.  (1, 1) is 1-bit bilevel (OME "bit", the
# ShapeMask raster class; ome.util.PixelData's 1-bit accessor is the
# reference analogue, ShapeMaskRequestHandler.java:214-221): stored
# packed MSB-first, exposed expanded as uint8 0/1.
_SAMPLE_DTYPES = {
    (1, 1): "u1",
    (8, 1): "u1", (16, 1): "u2", (32, 1): "u4",
    # 12-bit: the standard declaration for 12-bit JPEG-in-TIFF
    # microscopy exports; decoded samples are served as uint16.
    (12, 1): "u2",
    (8, 2): "i1", (16, 2): "i2", (32, 2): "i4",
    (32, 3): "f4", (64, 3): "f8",
}

# The same domain by dtype name — the single source for consumers that
# validate a configured storage dtype (server.prewarm spec suffixes).
STORAGE_DTYPE_NAMES = tuple(sorted(
    {np.dtype(v).name for v in _SAMPLE_DTYPES.values()}))


@dataclass
class Ifd:
    """One decoded image file directory."""

    offset: int
    tags: Dict[int, tuple] = field(default_factory=dict)
    # Source label for error messages (the module's convention prefixes
    # every reader error with the file path).
    path: str = ""

    def get(self, tag: int, default=None):
        v = self.tags.get(tag)
        return v if v is not None else default

    _REQUIRED = object()

    def one(self, tag: int, default=_REQUIRED):
        v = self.tags.get(tag)
        if v is None:
            if default is Ifd._REQUIRED:
                # Hostile/corrupt files can omit any tag; a clean parse
                # error (not a TypeError from int(None) downstream) is
                # the error contract.
                where = f"{self.path}: " if self.path else ""
                raise ValueError(
                    f"{where}missing required TIFF tag {tag}")
            return default
        return v[0] if isinstance(v, tuple) else v

    @property
    def width(self) -> int:
        return int(self.one(IMAGE_WIDTH))

    @property
    def height(self) -> int:
        return int(self.one(IMAGE_LENGTH))

    @property
    def tiled(self) -> bool:
        return TILE_OFFSETS in self.tags

    @property
    def bits(self) -> int:
        # TIFF 6.0: BitsPerSample DEFAULTS TO 1 (bilevel files omit the
        # tag — PIL mode-"1" output does exactly this).
        return int(self.one(BITS_PER_SAMPLE, 1))

    def dtype(self) -> np.dtype:
        key = (self.bits, int(self.one(SAMPLE_FORMAT, 1)))
        if key not in _SAMPLE_DTYPES:
            raise ValueError(f"unsupported TIFF sample: {key[0]}-bit "
                             f"format {key[1]}")
        if self.bits == 12 and int(self.one(COMPRESSION, 1)) not in (6,
                                                                     7):
            # Only the JPEG codecs deliver decoded uint16 samples for
            # 12-bit declarations; packed 12-bit raw/LZW/deflate rows
            # (1.5 bytes/sample) are not unpacked here.
            raise ValueError(
                f"unsupported TIFF sample: 12-bit outside "
                f"JPEG-compressed files (compression "
                f"{int(self.one(COMPRESSION, 1))})")
        return np.dtype(_SAMPLE_DTYPES[key])


def _lzw_decode(data: bytes) -> bytes:
    """TIFF-variant LZW (MSB-first codes, early code-size change).

    TIFF 6.0 section 13: codes start at 9 bits, ClearCode=256, EOI=257;
    the code width bumps one entry EARLY (at table sizes 511/1023/2047).
    """
    out = bytearray()
    table: List[bytes] = [bytes([i]) for i in range(256)] + [b"", b""]
    code_bits = 9
    buf = 0
    nbits = 0
    prev: Optional[bytes] = None
    for byte in data:
        buf = (buf << 8) | byte
        nbits += 8
        while nbits >= code_bits:
            nbits -= code_bits
            code = (buf >> nbits) & ((1 << code_bits) - 1)
            if code == 256:          # ClearCode
                table = table[:258]
                code_bits = 9
                prev = None
                continue
            if code == 257:          # EOI
                return bytes(out)
            if prev is None:
                if code >= len(table):
                    raise ValueError(
                        "corrupt LZW stream: code out of range")
                entry = table[code]
            elif code < len(table):
                entry = table[code]
                table.append(prev + entry[:1])
            elif code == len(table):  # the only legal KwKwK case
                entry = prev + prev[:1]
                table.append(entry)
            else:
                # Matching the native decoder's strictness: any code
                # beyond next-table-entry is a corrupt stream, not KwKwK.
                raise ValueError("corrupt LZW stream: code out of range")
            out += entry
            prev = entry
            if len(table) >= (1 << code_bits) - 1 and code_bits < 12:
                code_bits += 1
    return bytes(out)


def _packbits_decode(data: bytes) -> bytes:
    out = bytearray()
    i = 0
    n = len(data)
    while i < n:
        h = data[i]
        i += 1
        if h < 128:                  # literal run of h+1 bytes
            out += data[i:i + h + 1]
            i += h + 1
        elif h > 128:                # repeat next byte 257-h times
            out += data[i:i + 1] * (257 - h)
            i += 1
        # h == 128: no-op
    return bytes(out)


def decode_segment(data: bytes, compression: int,
                   expected_bytes: "int | None" = None) -> bytes:
    if compression == 1:
        return data
    if compression in (8, 32946):    # Adobe deflate / old deflate
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            # One error contract across codecs: corrupt streams raise
            # ValueError here like the LZW/PackBits paths do, not a
            # bare zlib.error.
            raise ValueError(f"corrupt deflate segment: {e}") from e
    if compression == 5:
        # Native LZW when available (the pure-Python fallback runs
        # ~1 MB/s — too slow for cold pans over LZW OME-TIFF exports);
        # expected_bytes bounds the output buffer.
        if expected_bytes is not None:
            try:
                from ..native import tiff_lzw_decode
                return tiff_lzw_decode(data, expected_bytes)
            except (ImportError, ValueError):
                pass
        return _lzw_decode(data)
    if compression == 32773:
        return _packbits_decode(data)
    if compression == 6:
        # Array-path codec (interchange-format layout only); handled
        # in read_segment, never through this bytes-level API.
        raise ValueError(
            "old-style JPEG segments (compression 6) decode via "
            "read_segment, not decode_segment")
    if compression in (33003, 33005):
        # Array-path codec: handled in read_segment (io/jp2k.py), never
        # through this bytes-level API.
        raise ValueError(
            f"JPEG 2000 segments (compression {compression}) decode "
            f"via read_segment, not decode_segment")
    raise ValueError(f"unsupported TIFF compression {compression}")


def _undo_predictor(rows: np.ndarray) -> np.ndarray:
    """Predictor 2 = horizontal differencing on [h, w, spp] samples.

    cumsum in the storage width wraps exactly like the encoder's
    subtraction did (modular arithmetic), so no widening is needed.
    """
    return np.cumsum(rows, axis=1, dtype=rows.dtype)


def _undo_float_predictor(data: bytes, seg_h: int, seg_w: int, spp: int,
                          dt: np.dtype) -> np.ndarray:
    """Predictor 3 = floating-point horizontal differencing (TIFF
    Technical Note 3; GDAL/ImageJ float exports).

    Per row the encoder splits each value into its bytes, regroups them
    byte-plane-major — ALL most-significant bytes first, regardless of
    the file's byte order — then byte-wise horizontally differences the
    whole row.  Undo: uint8 cumsum along the row (wrapping, mirroring
    the encoder's modular subtraction), de-interleave the byte planes,
    and view the reassembled per-value bytes big-endian.
    """
    n = seg_w * spp
    rows = np.frombuffer(data, np.uint8,
                         count=seg_h * n * dt.itemsize).reshape(
        seg_h, n * dt.itemsize)
    # The encoder (libtiff fpDiff) differences the reorganized row's
    # bytes in stride-spp chains — per-sample chains, continuing across
    # the byte-plane boundaries — so the undo accumulates the same way.
    rows = rows.reshape(seg_h, -1, spp).cumsum(
        axis=1, dtype=np.uint8).reshape(seg_h, n * dt.itemsize)
    planes = rows.reshape(seg_h, dt.itemsize, n)
    be = np.ascontiguousarray(planes.transpose(0, 2, 1))
    arr = be.reshape(seg_h, n * dt.itemsize).view(dt.newbyteorder(">"))
    return np.ascontiguousarray(
        arr.astype(dt.newbyteorder("="), copy=False)).reshape(
        seg_h, seg_w, spp)


class TiffFile:
    """Lazy random-access reader over one TIFF file."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        # Parsed-JPEGTables memo (keyed by the tag's bytes object):
        # every tile of an IFD shares one tag-347 stream, so the Huffman
        # lookup tables build once per file, not once per tile.
        self._jpeg_tables_cache: Dict[bytes, object] = {}
        # Decoded whole-image memo for old-style JPEG IFDs (keyed by
        # IFD offset); see _old_jpeg_image.
        self._old_jpeg_cache: Dict[int, np.ndarray] = {}
        try:
            self._parse_header_and_ifds(path)
        except BaseException:
            # Any parse failure must not leak the fd (servers probe
            # hostile files; GC-timed closes exhaust descriptors).
            self._f.close()
            raise

    def _parse_header_and_ifds(self, path: str) -> None:
        head = self._f.read(16)
        if len(head) < 8:
            raise ValueError(f"{path}: truncated TIFF header")
        if head[:2] == b"II":
            self.endian = "<"
        elif head[:2] == b"MM":
            self.endian = ">"
        else:
            raise ValueError(f"{path}: not a TIFF (no II/MM header)")
        magic = struct.unpack(self.endian + "H", head[2:4])[0]
        if magic == 42:
            self.big = False
            first = struct.unpack(self.endian + "I", head[4:8])[0]
        elif magic == 43:
            self.big = True
            offsize, _pad = struct.unpack(self.endian + "HH", head[4:8])
            if offsize != 8:
                raise ValueError(f"{path}: BigTIFF offset size {offsize}")
            if len(head) < 16:
                raise ValueError(f"{path}: truncated BigTIFF header")
            first = struct.unpack(self.endian + "Q", head[8:16])[0]
        else:
            raise ValueError(f"{path}: bad TIFF magic {magic}")
        self.ifds: List[Ifd] = []
        seen = set()
        off = first
        while off and off not in seen:
            seen.add(off)
            ifd, off = self._read_ifd(off)
            self.ifds.append(ifd)
        if not self.ifds:
            # TIFF 6.0 requires at least one IFD; a zeroed first-IFD
            # offset otherwise surfaces later as IndexError from
            # ifds[0] (fuzz-found escape of the error contract).
            raise ValueError(f"{path}: TIFF has no IFDs")

    # ------------------------------------------------------------ low level

    def _pread(self, offset: int, size: int) -> bytes:
        # os.pread, not seek+read: one TiffFile is shared by concurrent
        # render worker threads, and interleaved seeks on a single file
        # object would silently corrupt both readers' tiles.  pread is
        # positional and atomic per call.
        if not 0 <= offset < (1 << 63):
            # A corrupt 64-bit offset would raise OverflowError from the
            # C off_t conversion — keep the clean-failure contract.
            raise ValueError(f"{self.path}: bad file offset {offset}")
        data = os.pread(self._f.fileno(), size, offset)
        if len(data) != size:
            raise EOFError(f"{self.path}: short read at {offset}")
        return data

    def _read_ifd(self, offset: int) -> Tuple[Ifd, int]:
        e = self.endian
        if self.big:
            count = struct.unpack(e + "Q", self._pread(offset, 8))[0]
            entry_size, count_size, next_fmt = 20, 8, "Q"
        else:
            count = struct.unpack(e + "H", self._pread(offset, 2))[0]
            entry_size, count_size, next_fmt = 12, 2, "I"
        if count > 65536:
            # Hostile/corrupt count fields must not drive allocations.
            raise ValueError(f"{self.path}: IFD at {offset} claims "
                             f"{count} entries")
        next_size = 8 if self.big else 4
        raw = self._pread(offset + count_size,
                          count * entry_size + next_size)
        ifd = Ifd(offset=offset, path=self.path)
        for i in range(count):
            ent = raw[i * entry_size:(i + 1) * entry_size]
            tag, ftype = struct.unpack(e + "HH", ent[:4])
            if ftype not in _TYPES:
                continue
            code, size = _TYPES[ftype]
            if self.big:
                n = struct.unpack(e + "Q", ent[4:12])[0]
                inline = ent[12:20]
                inline_cap = 8
            else:
                n = struct.unpack(e + "I", ent[4:8])[0]
                inline = ent[8:12]
                inline_cap = 4
            nbytes = n * size
            if nbytes > (1 << 28):
                # 256 MB of tag data (offset/count arrays for huge
                # BigTIFF grids stay far below this) — corrupt counts
                # must not drive allocations.
                raise ValueError(f"{self.path}: tag {tag} claims "
                                 f"{nbytes} bytes")
            if nbytes <= inline_cap:
                data = inline[:nbytes]
            else:
                src_off = struct.unpack(
                    e + ("Q" if self.big else "I"),
                    inline[:inline_cap])[0]
                data = self._pread(src_off, nbytes)
            if ftype == 2:
                ifd.tags[tag] = data.split(b"\0")[0].decode(
                    "utf-8", "replace")
            elif code is None:
                ifd.tags[tag] = data
            else:
                ifd.tags[tag] = struct.unpack(e + code * n, data)
        next_off = struct.unpack(
            e + next_fmt,
            raw[count * entry_size:count * entry_size + next_size])[0]
        return ifd, next_off

    # ----------------------------------------------------------- segments

    def sub_ifds(self, ifd: Ifd) -> List[Ifd]:
        """Decode the SubIFD chain (tag 330) — OME-TIFF pyramid levels."""
        offs = ifd.get(SUB_IFDS)
        if not offs:
            return []
        subs = []
        for off in offs:
            sub, _next = self._read_ifd(int(off))
            subs.append(sub)
        return subs

    def segment_grid(self, ifd: Ifd) -> Tuple[int, int, int, int]:
        """(seg_h, seg_w, grid_y, grid_x) for tiles or strips."""
        if ifd.tiled:
            tw = int(ifd.one(TILE_WIDTH))
            th = int(ifd.one(TILE_LENGTH))
            return th, tw, -(-ifd.height // th), -(-ifd.width // tw)
        rps = int(ifd.one(ROWS_PER_STRIP, ifd.height))
        return min(rps, ifd.height), ifd.width, -(-ifd.height // rps), 1

    @staticmethod
    def _check_frame(img: np.ndarray, seg_h: int, seg_w: int, spp: int,
                     tiled: bool, path: str, codec: str) -> int:
        """Shared frame-vs-segment contract for the array codecs (JPEG
        variants, JPEG 2000): the decoded frame must cover the segment
        width (and height, for tiles); only the last strip's height may
        run short.  Returns the (possibly shortened) segment height."""
        if img.shape[1] < seg_w or (tiled and img.shape[0] < seg_h):
            raise ValueError(
                f"{path}: {codec} frame {img.shape[:2]} smaller than "
                f"segment {seg_h}x{seg_w}")
        if img.shape[-1] != spp:
            raise ValueError(
                f"{path}: {codec} components {img.shape[-1]} != "
                f"samples per pixel {spp}")
        return seg_h if tiled else min(seg_h, img.shape[0])

    def _read_old_jpeg_segment(self, ifd: Ifd, gy: int, seg_h: int,
                               seg_w: int, spp: int) -> np.ndarray:
        """Old-style JPEG (compression 6), interchange-format layout:
        tags 513/514 point at ONE complete JFIF stream for the whole
        image (real files often omit or garbage the 273/279 tags, so
        this path never touches them).  The deprecated per-strip tables
        variants stay rejected."""
        if ifd.tiled:
            raise ValueError(
                f"{self.path}: tiled old-style JPEG is not supported")
        off = ifd.one(JPEG_INTERCHANGE, None)
        if off is None:
            raise ValueError(
                f"{self.path}: old-style JPEG (compression 6) without "
                f"JPEGInterchangeFormat is not supported — re-export "
                f"with new-style JPEG (7)")
        img = self._old_jpeg_image(ifd, int(off))
        # The one stream must cover the declared geometry.
        if img.shape[1] < ifd.width or img.shape[0] < ifd.height:
            raise ValueError(
                f"{self.path}: JPEG frame {img.shape[:2]} smaller "
                f"than declared {ifd.height}x{ifd.width}")
        seg_h = self._check_frame(img, seg_h, seg_w, spp, False,
                                  self.path, "JPEG")
        # Slice this strip (seg_h was already shortened for the last
        # strip, so the row origin uses the nominal rows-per-strip).
        rps = min(int(ifd.one(ROWS_PER_STRIP, ifd.height)), ifd.height)
        y0 = gy * rps
        return np.ascontiguousarray(img[y0:y0 + seg_h, :seg_w])

    def _read_jp2k_segment(self, ifd: Ifd, raw: bytes, comp: int,
                           seg_h: int, seg_w: int, spp: int,
                           dt: np.dtype) -> np.ndarray:
        """Aperio JPEG 2000 tiles (raw J2K codestreams; 33003 = YCbCr
        planes, 33005 = RGB) — Bio-Formats reads these behind
        getPixelBuffer.  Tier-1 runs natively (C++) when a toolchain
        exists; pure-Python fallback otherwise."""
        from .jp2k import decode_tiff_jp2k
        img = decode_tiff_jp2k(raw, comp, int(ifd.one(PHOTOMETRIC, 1)))
        seg_h = self._check_frame(img, seg_h, seg_w, spp, ifd.tiled,
                                  self.path, "JPEG2000")
        if img.dtype.itemsize > dt.itemsize:
            # A deeper codestream cast down would wrap mod 2^bits — a
            # declaration mismatch must fail, not corrupt pixels.
            raise ValueError(
                f"{self.path}: JPEG2000 sample depth "
                f"{img.dtype.itemsize * 8} exceeds declared "
                f"{dt.itemsize * 8}-bit samples")
        return np.ascontiguousarray(
            img[:seg_h, :seg_w].astype(dt.newbyteorder("=")))

    def _read_jpeg_segment(self, ifd: Ifd, raw: bytes, seg_h: int,
                           seg_w: int, spp: int) -> np.ndarray:
        """New-style JPEG-in-TIFF (compression 7, the SVS/WSI
        vendor-pyramid class).  The abbreviated per-segment stream
        carries its tables in tag 347; photometric 6 stores YCbCr and
        converts to RGB."""
        from .jpegdec import decode_tiff_jpeg
        tables = ifd.get(JPEG_TABLES)
        img = decode_tiff_jpeg(
            raw, bytes(tables) if tables else None,
            int(ifd.one(PHOTOMETRIC, 1)),
            tables_cache=self._jpeg_tables_cache)
        seg_h = self._check_frame(img, seg_h, seg_w, spp, ifd.tiled,
                                  self.path, "JPEG")
        self._check_jpeg_depth(ifd, img)
        dt = ifd.dtype()
        return np.ascontiguousarray(
            img[:seg_h, :seg_w].astype(dt.newbyteorder("="),
                                       copy=False))

    def _check_jpeg_depth(self, ifd: Ifd, img: np.ndarray) -> None:
        """Decoded-vs-declared sample depth must MATCH, both ways: a
        12-bit stream under an 8-bit declaration cast down would wrap
        mod 256, and an 8-bit stream under a 12-bit declaration upcast
        would render ~16x dark against the declared range — either
        mismatch serves wrong pixels, so both fail loudly (same rule
        as JPEG2000); shared by the compression-6 and -7 paths."""
        if img.dtype.itemsize != ifd.dtype().itemsize:
            raise ValueError(
                f"{self.path}: JPEG sample depth "
                f"{img.dtype.itemsize * 8} does not match declared "
                f"{ifd.bits}-bit samples")

    def _read_bilevel_segment(self, ifd: Ifd, raw: bytes, comp: int,
                              seg_h: int, seg_w: int,
                              spp: int) -> np.ndarray:
        """Packed bilevel rows: each row starts on a byte boundary.
        Expanded to uint8 0/1 with 1 = bright: WhiteIsZero files
        (photometric 0, the CCITT-era default) are inverted so the
        mask/render pipeline always sees set==foreground."""
        bpr = (seg_w * spp + 7) // 8
        data = decode_segment(raw, comp, seg_h * bpr)
        rows = np.frombuffer(data, np.uint8,
                             count=seg_h * bpr).reshape(seg_h, bpr)
        arr = np.unpackbits(rows, axis=1)[:, :seg_w * spp]
        if int(ifd.one(PHOTOMETRIC, 1)) == 0:
            arr = 1 - arr
        return np.ascontiguousarray(arr.reshape(seg_h, seg_w, spp))

    def read_segment(self, ifd: Ifd, gy: int, gx: int) -> np.ndarray:
        """Decode one tile/strip as [seg_h, seg_w, spp] in storage dtype.

        Edge tiles come back full-size (TIFF pads tiles); edge strips come
        back at their true height.
        """
        seg_h, seg_w, grid_y, grid_x = self.segment_grid(ifd)
        comp = int(ifd.one(COMPRESSION, 1))
        spp = int(ifd.one(SAMPLES_PER_PIXEL, 1))
        if spp > 1 and int(ifd.one(PLANAR_CONFIG, 1)) != 1:
            raise ValueError(
                f"{self.path}: unsupported planar configuration "
                f"{ifd.one(PLANAR_CONFIG)} (only chunky is supported)")
        if not ifd.tiled and gy == grid_y - 1:
            seg_h = ifd.height - gy * seg_h  # last strip may be short
        if comp == 6:
            # Handled BEFORE the strip-offset read: see
            # _read_old_jpeg_segment.
            return self._read_old_jpeg_segment(ifd, gy, seg_h, seg_w,
                                               spp)
        idx = gy * grid_x + gx
        offsets = ifd.get(TILE_OFFSETS if ifd.tiled else STRIP_OFFSETS)
        counts = ifd.get(TILE_BYTE_COUNTS if ifd.tiled
                         else STRIP_BYTE_COUNTS)
        if offsets is None or counts is None:
            raise ValueError(f"{self.path}: IFD lacks segment "
                             f"offset/byte-count tags")
        if idx >= len(offsets) or idx >= len(counts):
            raise ValueError(f"{self.path}: segment index {idx} beyond "
                             f"declared offsets ({len(offsets)})")
        raw = self._pread(int(offsets[idx]), int(counts[idx]))
        dt = ifd.dtype().newbyteorder(self.endian)
        if comp in (33003, 33005):
            return self._read_jp2k_segment(ifd, raw, comp, seg_h,
                                           seg_w, spp, dt)
        if comp == 7:
            return self._read_jpeg_segment(ifd, raw, seg_h, seg_w, spp)
        if ifd.bits == 1:
            if (BITS_PER_SAMPLE not in ifd.tags and comp == 1
                    and len(raw) == seg_h * seg_w * spp):
                # Spec says a missing BitsPerSample means 1-bit, but
                # sloppy 8-bit writers omit the tag too; uncompressed
                # data whose length matches the byte-per-sample layout
                # (a real bilevel strip is ~8x smaller) disambiguates.
                pass
            else:
                return self._read_bilevel_segment(ifd, raw, comp,
                                                  seg_h, seg_w, spp)
        data = decode_segment(raw, comp,
                              seg_h * seg_w * spp * dt.itemsize)
        predictor = int(ifd.one(PREDICTOR, 1))
        if predictor == 3:
            # Byte-level transform: must run BEFORE the dtype view.
            if dt.kind != "f":
                raise ValueError(
                    f"{self.path}: predictor 3 (floating point) on "
                    f"non-float samples ({dt})")
            return _undo_float_predictor(data, seg_h, seg_w, spp, dt)
        if predictor not in (1, 2):
            # An unrecognized predictor silently ignored would serve
            # garbage samples; reject loudly instead.
            raise ValueError(
                f"{self.path}: unsupported TIFF predictor {predictor}")
        arr = np.frombuffer(data, dtype=dt,
                            count=seg_h * seg_w * spp)
        arr = arr.reshape(seg_h, seg_w, spp)
        arr = np.ascontiguousarray(
            arr.astype(arr.dtype.newbyteorder("="), copy=False))
        if predictor == 2:
            arr = _undo_predictor(arr)
        return arr

    def _old_jpeg_image(self, ifd: Ifd, off: int) -> np.ndarray:
        """Decode (and memoize) the one interchange-format JFIF stream
        a compression-6 IFD holds: per-strip reads would otherwise pay
        a full-image decode EACH (an 8-row-strip scan would decode the
        same stream hundreds of times)."""
        from .jpegdec import decode_tiff_jpeg

        cached = self._old_jpeg_cache.get(ifd.offset)
        if cached is not None:
            return cached
        # Bounded: one decoded image at a time (reads are sequential
        # per IFD; an unbounded memo would pin every page's pixels for
        # the file's lifetime).
        self._old_jpeg_cache.clear()
        n = ifd.one(JPEG_INTERCHANGE_LEN, None)
        jf = self._pread(off, int(n) if n else
                         os.fstat(self._f.fileno()).st_size - off)
        img = decode_tiff_jpeg(jf, None, int(ifd.one(PHOTOMETRIC, 1)),
                               tables_cache=self._jpeg_tables_cache)
        self._check_jpeg_depth(ifd, img)
        self._old_jpeg_cache[ifd.offset] = img
        return img

    def close(self) -> None:
        self._f.close()
