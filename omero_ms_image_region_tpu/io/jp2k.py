"""Baseline JPEG 2000 Part-1 decoder (ITU-T T.800) for JPEG2000-in-TIFF.

Aperio SVS exports and other vendor WSI pyramids store tiles as raw
JPEG 2000 codestreams under TIFF compression 33003/33005; the reference
reads them through Bio-Formats behind ``PixelsService.getPixelBuffer``
(``build.gradle:81-83``).  No JPEG 2000 library is importable from the
serving path's C side here, so the codec is implemented directly.

Scope (what WSI serving needs):
- raw J2K codestreams and JP2 box files (the box walk just locates the
  contiguous codestream);
- SIZ/COD/COC/QCD/QCC, multiple tiles and tile-parts, all five
  progression orders, quality layers, SOP/EPH markers;
- EBCOT Tier-1 (MQ coder per Annex C, three passes, default code-block
  style; the segmentation-symbol option is tolerated) with mid-point
  reconstruction for truncated planes;
- 5/3 reversible and 9/7 irreversible inverse DWT, RCT/ICT multiple
  component transform, scalar quantization (derived + expounded);
- default (whole-subband) and explicit precinct sizes.

This pure-Python Tier-1 is a correctness/serving-fallback
implementation (the hot WSI path should pre-convert or use JPEG
tiles); it is exact for lossless 5/3 streams and mid-point-faithful
for lossy ones, validated against openjpeg (via PIL) in
``tests/test_jp2k.py``.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class Jp2kError(ValueError):
    """Malformed or unsupported JPEG 2000 stream."""


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# ----------------------------------------------------------- MQ decoder

# Annex C probability state machine: (Qe, NMPS, NLPS, SWITCH).
_MQ = [
    (0x5601, 1, 1, 1), (0x3401, 2, 6, 0), (0x1801, 3, 9, 0),
    (0x0AC1, 4, 12, 0), (0x0521, 5, 29, 0), (0x0221, 38, 33, 0),
    (0x5601, 7, 6, 1), (0x5401, 8, 14, 0), (0x4801, 9, 14, 0),
    (0x3801, 10, 14, 0), (0x3001, 11, 17, 0), (0x2401, 12, 18, 0),
    (0x1C01, 13, 20, 0), (0x1601, 29, 21, 0), (0x5601, 15, 14, 1),
    (0x5401, 16, 14, 0), (0x5101, 17, 15, 0), (0x4801, 18, 16, 0),
    (0x3801, 19, 17, 0), (0x3401, 20, 18, 0), (0x3001, 21, 19, 0),
    (0x2801, 22, 19, 0), (0x2401, 23, 20, 0), (0x2201, 24, 21, 0),
    (0x1C01, 25, 22, 0), (0x1801, 26, 23, 0), (0x1601, 27, 24, 0),
    (0x1401, 28, 25, 0), (0x1201, 29, 26, 0), (0x1101, 30, 27, 0),
    (0x0AC1, 31, 28, 0), (0x09C1, 32, 29, 0), (0x08A1, 33, 30, 0),
    (0x0521, 34, 31, 0), (0x0441, 35, 32, 0), (0x02A1, 36, 33, 0),
    (0x0221, 37, 34, 0), (0x0141, 38, 35, 0), (0x0111, 39, 36, 0),
    (0x0085, 40, 37, 0), (0x0049, 41, 38, 0), (0x0025, 42, 39, 0),
    (0x0015, 43, 40, 0), (0x0009, 44, 41, 0), (0x0005, 45, 42, 0),
    (0x0001, 45, 43, 0), (0x5601, 46, 46, 0),
]
_MQ_QE = [s[0] for s in _MQ]
_MQ_NMPS = [s[1] for s in _MQ]
_MQ_NLPS = [s[2] for s in _MQ]
_MQ_SWITCH = [s[3] for s in _MQ]

# T1 context indices: 0-8 zero coding, 9-13 sign coding, 14-16 magnitude
# refinement, 17 run-length, 18 uniform.
_CTX_RL = 17
_CTX_UNI = 18
_N_CTX = 19


class _MQDecoder:
    """MQ arithmetic decoder (T.800 Annex C, software conventions)."""

    __slots__ = ("data", "bp", "c", "a", "ct", "i", "mps")

    def __init__(self, data: bytes):
        self.data = data
        self.i = [0] * _N_CTX
        self.mps = [0] * _N_CTX
        # Initial states (Table D.7): ctx 18 (UNIFORM) = 46, ctx 17
        # (RUN-LENGTH) = 3, ctx 0 (first zero-coding) = 4, rest 0.
        self.i[_CTX_UNI] = 46
        self.i[_CTX_RL] = 3
        self.i[0] = 4
        self.bp = 0
        b = data[0] if data else 0xFF
        self.c = b << 16
        self._bytein()
        self.c = (self.c << 7) & 0xFFFFFFFF
        self.ct -= 7
        self.a = 0x8000

    def _b(self, k: int = 0) -> int:
        p = self.bp + k
        return self.data[p] if p < len(self.data) else 0xFF

    def _bytein(self) -> None:
        if self._b() == 0xFF:
            if self._b(1) > 0x8F:
                self.c += 0xFF00
                self.ct = 8
            else:
                self.bp += 1
                self.c += self._b() << 9
                self.ct = 7
        else:
            self.bp += 1
            self.c += self._b() << 8
            self.ct = 8

    def decode(self, cx: int) -> int:
        i = self.i[cx]
        qe = _MQ_QE[i]
        self.a -= qe
        if ((self.c >> 16) & 0xFFFF) < qe:
            # LPS path (chigh < Qe)
            if self.a < qe:
                d = self.mps[cx]
                self.i[cx] = _MQ_NMPS[i]
            else:
                d = 1 - self.mps[cx]
                if _MQ_SWITCH[i]:
                    self.mps[cx] = 1 - self.mps[cx]
                self.i[cx] = _MQ_NLPS[i]
            self.a = qe
        else:
            self.c = (self.c - (qe << 16)) & 0xFFFFFFFF
            if self.a & 0x8000:
                return self.mps[cx]
            if self.a < qe:
                d = 1 - self.mps[cx]
                if _MQ_SWITCH[i]:
                    self.mps[cx] = 1 - self.mps[cx]
                self.i[cx] = _MQ_NLPS[i]
            else:
                d = self.mps[cx]
                self.i[cx] = _MQ_NMPS[i]
        # RENORMD
        while True:
            if self.ct == 0:
                self._bytein()
            self.a = (self.a << 1) & 0xFFFF
            self.c = (self.c << 1) & 0xFFFFFFFF
            self.ct -= 1
            if self.a & 0x8000:
                break
        return d


# ------------------------------------------------------------ tag trees

class _TagTree:
    """T.800 B.10.2 tag tree over a w x h leaf grid.

    Per node a lower bound rises with 0-bits; a 1-bit resolves the
    node's value at the current bound.
    """

    def __init__(self, w: int, h: int):
        self.levels: List[Tuple[int, int]] = []
        while True:
            self.levels.append((w, h))
            if w == 1 and h == 1:
                break
            w, h = _ceil_div(w, 2), _ceil_div(h, 2)
        self.low = [np.zeros((lh, lw), np.int32)
                    for (lw, lh) in self.levels]
        self.value = [np.zeros((lh, lw), np.int32)
                      for (lw, lh) in self.levels]
        self.known = [np.zeros((lh, lw), bool)
                      for (lw, lh) in self.levels]

    def decode(self, x: int, y: int, reader, threshold: int) -> bool:
        """Resolve leaf (x, y) against ``threshold``: True iff its
        value is known AND < threshold.  Consumes bits."""
        # Leaf -> root path; walk root-first.
        path = []
        lx, ly = x, y
        for li in range(len(self.levels)):
            path.append((li, lx, ly))
            lx >>= 1
            ly >>= 1
        bound = 0
        for li, lx, ly in reversed(path):
            if self.low[li][ly, lx] < bound:
                self.low[li][ly, lx] = bound
            while (not self.known[li][ly, lx]
                   and self.low[li][ly, lx] < threshold):
                if reader.bit():
                    self.known[li][ly, lx] = True
                    self.value[li][ly, lx] = self.low[li][ly, lx]
                else:
                    self.low[li][ly, lx] += 1
            bound = int(self.value[li][ly, lx]
                        if self.known[li][ly, lx]
                        else self.low[li][ly, lx])
        return bool(self.known[0][y, x]) \
            and int(self.value[0][y, x]) < threshold

    def leaf_value(self, x: int, y: int) -> int:
        return int(self.value[0][y, x])


class _PacketBitReader:
    """Packet-header bit reader with the 0xFF bit-stuffing rule
    (after an 0xFF byte only 7 bits follow)."""

    def __init__(self, data: bytes, pos: int):
        self.data = data
        self.pos = pos
        self.buf = 0
        self.nbits = 0
        self.last = 0

    def bit(self) -> int:
        if self.nbits == 0:
            if self.pos >= len(self.data):
                raise Jp2kError("truncated packet header")
            b = self.data[self.pos]
            self.pos += 1
            self.nbits = 7 if self.last == 0xFF else 8
            self.buf = b
            self.last = b
        self.nbits -= 1
        return (self.buf >> self.nbits) & 1

    def bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.bit()
        return v

    def align(self) -> None:
        """Finish the header: byte-align; a stuffed 0 bit after a
        trailing 0xFF consumes the next byte."""
        self.nbits = 0
        if self.last == 0xFF:
            if self.pos < len(self.data) and self.data[self.pos] == 0x00:
                self.pos += 1
            self.last = 0


# ----------------------------------------------------------- structures

@dataclass
class _CodingStyle:
    progression: int = 0
    layers: int = 1
    mct: int = 0
    levels: int = 5                 # decomposition levels NL
    cblk_w_exp: int = 6             # log2 widths (already +2)
    cblk_h_exp: int = 6
    cblk_style: int = 0
    transform: int = 1              # 0 = 9/7, 1 = 5/3
    precincts: Optional[List[Tuple[int, int]]] = None  # per resolution

    def precinct_exp(self, r: int) -> Tuple[int, int]:
        if self.precincts is None:
            return 15, 15
        return self.precincts[min(r, len(self.precincts) - 1)]


@dataclass
class _Quant:
    style: int = 0                  # 0 none, 1 derived, 2 expounded
    guard: int = 2
    exponents: List[int] = field(default_factory=list)
    mantissas: List[int] = field(default_factory=list)


@dataclass
class _Component:
    depth: int
    signed: bool
    dx: int
    dy: int


@dataclass
class _CodeBlock:
    x0: int
    y0: int
    x1: int
    y1: int
    included: bool = False
    zero_planes: int = 0
    lblock: int = 3
    passes: int = 0
    data: bytearray = field(default_factory=bytearray)


@dataclass
class _Band:
    orient: int                     # 0 LL, 1 HL, 2 LH, 3 HH
    x0: int
    y0: int
    x1: int
    y1: int
    blocks: List[List[_CodeBlock]] = field(default_factory=list)
    incl_tree: Dict[int, _TagTree] = field(default_factory=dict)
    zero_tree: Dict[int, _TagTree] = field(default_factory=dict)


_J2K_SOC = 0xFF4F
_J2K_SIZ = 0xFF51
_J2K_COD = 0xFF52
_J2K_COC = 0xFF53
_J2K_QCD = 0xFF5C
_J2K_QCC = 0xFF5D
_J2K_RGN = 0xFF5E
_J2K_POC = 0xFF5F
_J2K_SOT = 0xFF90
_J2K_SOP = 0xFF91
_J2K_EPH = 0xFF92
_J2K_SOD = 0xFF93
_J2K_EOC = 0xFFD9


class _Decoder:
    def __init__(self, data: bytes):
        self.data = data
        self.comps: List[_Component] = []
        self.cod = _CodingStyle()
        self.cod_per_comp: Dict[int, _CodingStyle] = {}
        self.qcd = _Quant()
        self.qcd_per_comp: Dict[int, _Quant] = {}
        self.tile_parts: Dict[int, List[Tuple[int, int]]] = {}
        self._parse()

    # -------------------------------------------------------- main parse

    def _parse(self) -> None:
        d = self.data
        if len(d) < 4 or struct.unpack(">H", d[:2])[0] != _J2K_SOC:
            raise Jp2kError("no SOC marker")
        pos = 2
        in_tile = None
        while pos + 2 <= len(d):
            marker = struct.unpack(">H", d[pos:pos + 2])[0]
            if marker == _J2K_EOC:
                return
            if marker == _J2K_SOD:
                if in_tile is None:
                    raise Jp2kError("SOD outside tile-part")
                isot, tp_end = in_tile
                self.tile_parts.setdefault(isot, []).append(
                    (pos + 2, tp_end))
                pos = tp_end
                in_tile = None
                continue
            if pos + 4 > len(d):
                raise Jp2kError("truncated marker segment")
            seglen = struct.unpack(">H", d[pos + 2:pos + 4])[0]
            if seglen < 2 or pos + 2 + seglen > len(d):
                raise Jp2kError("truncated marker segment")
            body = d[pos + 4:pos + 2 + seglen]
            if marker == _J2K_SIZ:
                self._parse_siz(body)
            elif marker in (_J2K_COD, _J2K_COC, _J2K_QCD, _J2K_QCC) \
                    and in_tile is not None:
                # Tile-part-local coding/quantization overrides are
                # spec-legal but would need per-tile style state; the
                # current decoder applies styles globally, so refusing
                # is the only honest behavior (silently-global would
                # decode OTHER tiles with the wrong tables).
                raise Jp2kError(
                    "tile-part-local COD/COC/QCD/QCC is not supported")
            elif marker in (0xFF60, 0xFF61):    # PPM / PPT
                raise Jp2kError(
                    "packed packet headers (PPM/PPT) are not supported")
            elif marker == _J2K_COD:
                self.cod = self._parse_cod(body)
            elif marker == _J2K_COC:
                ci, cs = self._parse_coc(body)
                self.cod_per_comp[ci] = cs
            elif marker == _J2K_QCD:
                self.qcd = self._parse_quant(body)
            elif marker == _J2K_QCC:
                big = len(self.comps) > 256
                if len(body) < (3 if big else 2):
                    raise Jp2kError("truncated QCC")
                if big:
                    ci = struct.unpack(">H", body[:2])[0]
                    qbody = body[2:]
                else:
                    ci = body[0]
                    qbody = body[1:]
                self.qcd_per_comp[ci] = self._parse_quant(qbody)
            elif marker == _J2K_SOT:
                if seglen != 10:
                    raise Jp2kError("bad SOT length")
                isot, psot, _tpsot, _tnsot = struct.unpack(
                    ">HIBB", body)
                tp_end = pos + psot if psot else len(d)
                if tp_end > len(d):
                    raise Jp2kError("tile-part overruns stream")
                in_tile = (isot, tp_end)
            elif marker == _J2K_RGN:
                raise Jp2kError("ROI (RGN) streams are not supported")
            elif marker == _J2K_POC:
                raise Jp2kError(
                    "progression-order changes (POC) not supported")
            # COM/TLM/PLM/PLT/CRG etc: skipped.
            pos += 2 + seglen

    def _parse_siz(self, b: bytes) -> None:
        if len(b) < 36:
            raise Jp2kError("truncated SIZ")
        (_rsiz, self.xsiz, self.ysiz, self.xosiz, self.yosiz,
         self.xtsiz, self.ytsiz, self.xtosiz, self.ytosiz,
         csiz) = struct.unpack(">HIIIIIIIIH", b[:36])
        if self.xsiz <= self.xosiz or self.ysiz <= self.yosiz:
            raise Jp2kError("empty image grid")
        if self.xtsiz == 0 or self.ytsiz == 0:
            raise Jp2kError("zero tile size")
        # Hostile/corrupt headers must not drive allocations or tile
        # loops (same posture as the TIFF parser's count caps).
        if csiz < 1 or csiz > 64:
            raise Jp2kError(f"component count {csiz} exceeds the "
                            f"64-component cap")
        if (self.xsiz - self.xosiz) * (self.ysiz - self.yosiz) \
                * csiz > (1 << 28):
            raise Jp2kError("image area exceeds the 256M-sample cap")
        if len(b) < 36 + 3 * csiz:
            raise Jp2kError("truncated SIZ components")
        self.comps = []
        for ci in range(csiz):
            ssiz, xr, yr = b[36 + 3 * ci:39 + 3 * ci]
            if xr == 0 or yr == 0:
                raise Jp2kError("zero component subsampling")
            depth = (ssiz & 0x7F) + 1
            if depth > 32:
                # T.800 allows up to 38 bits, but past 32 the output
                # dtypes would silently wrap — fail loudly instead.
                raise Jp2kError(
                    f"{depth}-bit components are not supported "
                    f"(32-bit max)")
            self.comps.append(_Component(
                depth=depth, signed=bool(ssiz & 0x80), dx=xr, dy=yr))
        self.ntx = _ceil_div(self.xsiz - self.xtosiz, self.xtsiz)
        self.nty = _ceil_div(self.ysiz - self.ytosiz, self.ytsiz)
        if self.ntx * self.nty > 65536:
            raise Jp2kError("tile grid exceeds the 65536-tile cap")

    def _parse_cod(self, b: bytes) -> _CodingStyle:
        if len(b) < 10:
            raise Jp2kError("truncated COD")
        scod = b[0]
        cs = _CodingStyle(
            progression=b[1],
            layers=struct.unpack(">H", b[2:4])[0],
            mct=b[4],
            levels=b[5],
            cblk_w_exp=(b[6] & 0xF) + 2,
            cblk_h_exp=(b[7] & 0xF) + 2,
            cblk_style=b[8],
            transform=b[9],
        )
        cs.sop = bool(scod & 2)
        cs.eph = bool(scod & 4)
        if cs.layers == 0:
            raise Jp2kError("zero quality layers")
        if cs.layers > 4096:
            # Spec allows 65535, but layers scale the packet walk per
            # precinct; real encoders use a handful.
            raise Jp2kError(f"{cs.layers} quality layers exceed the "
                            f"4096-layer cap")
        if cs.cblk_w_exp + cs.cblk_h_exp > 12:
            raise Jp2kError("code-block area > 4096")
        # Styles we cannot decode: selective bypass (1), reset (2),
        # termall (4), vertically causal (8).  Predictable termination
        # (32) and segmentation symbols (16) only ADD decoder-checkable
        # redundancy; tolerate 16, reject the rest.
        if cs.cblk_style & ~0x10:
            raise Jp2kError(
                f"unsupported code-block style {cs.cblk_style:#x}")
        if cs.transform not in (0, 1):
            raise Jp2kError(f"unknown wavelet transform {cs.transform}")
        if scod & 1:
            if len(b) < 10 + cs.levels + 1:
                raise Jp2kError("truncated COD precincts")
            cs.precincts = [(v & 0xF, v >> 4)
                            for v in b[10:10 + cs.levels + 1]]
        return cs

    def _parse_coc(self, b: bytes) -> Tuple[int, _CodingStyle]:
        big = len(self.comps) > 256
        if len(b) < (2 if big else 1) + 6:
            raise Jp2kError("truncated COC")
        ci = struct.unpack(">H", b[:2])[0] if big else b[0]
        off = 2 if big else 1
        scoc = b[off]
        sp = b[off + 1:]
        cs = _CodingStyle(
            progression=self.cod.progression, layers=self.cod.layers,
            mct=self.cod.mct,
            levels=sp[0], cblk_w_exp=(sp[1] & 0xF) + 2,
            cblk_h_exp=(sp[2] & 0xF) + 2, cblk_style=sp[3],
            transform=sp[4])
        cs.sop = getattr(self.cod, "sop", False)
        cs.eph = getattr(self.cod, "eph", False)
        if cs.cblk_w_exp + cs.cblk_h_exp > 12:
            raise Jp2kError("code-block area > 4096")
        if cs.cblk_style & ~0x10:
            raise Jp2kError(
                f"unsupported code-block style {cs.cblk_style:#x}")
        if cs.transform not in (0, 1):
            raise Jp2kError(f"unknown wavelet transform {cs.transform}")
        if scoc & 1:
            if len(sp) < 5 + cs.levels + 1:
                raise Jp2kError("truncated COC precincts")
            cs.precincts = [(v & 0xF, v >> 4)
                            for v in sp[5:5 + cs.levels + 1]]
        return ci, cs

    def _parse_quant(self, b: bytes) -> _Quant:
        if not b:
            raise Jp2kError("empty quantization segment")
        sq = b[0]
        q = _Quant(style=sq & 0x1F, guard=sq >> 5)
        if q.style == 0:            # no quantization: u8 exponents
            q.exponents = [v >> 3 for v in b[1:]]
        elif q.style in (1, 2):     # scalar derived / expounded
            vals = struct.unpack(f">{(len(b) - 1) // 2}H", b[1:])
            q.exponents = [v >> 11 for v in vals]
            q.mantissas = [v & 0x7FF for v in vals]
        else:
            raise Jp2kError(f"unknown quantization style {q.style}")
        return q

    # ------------------------------------------------------ tile decode

    def _comp_cod(self, c: int) -> _CodingStyle:
        return self.cod_per_comp.get(c, self.cod)

    def _comp_quant(self, c: int) -> _Quant:
        return self.qcd_per_comp.get(c, self.qcd)

    def decode(self) -> np.ndarray:
        """Full image -> [h, w, ncomp] (dtype per depth)."""
        out_comps = []
        for ci, comp in enumerate(self.comps):
            cw = _ceil_div(self.xsiz, comp.dx) - _ceil_div(
                self.xosiz, comp.dx)
            ch = _ceil_div(self.ysiz, comp.dy) - _ceil_div(
                self.yosiz, comp.dy)
            out_comps.append(np.zeros((ch, cw), np.float64))
        for t in range(self.ntx * self.nty):
            planes = self._decode_tile(t)
            if planes is None:
                continue
            tx = t % self.ntx
            ty = t // self.ntx
            tcx0 = max(self.xtosiz + tx * self.xtsiz, self.xosiz)
            tcy0 = max(self.ytosiz + ty * self.ytsiz, self.yosiz)
            # Inverse MCT per tile (T.800 G): applies to the first three
            # components when flagged.
            cod = self.cod
            if cod.mct and len(planes) >= 3:
                if len({p.shape for p in planes[:3]}) != 1:
                    raise Jp2kError(
                        "MCT over subsampled components is not valid")
                if cod.transform == 1:
                    planes[:3] = _inverse_rct(*planes[:3])
                else:
                    planes[:3] = _inverse_ict(*planes[:3])
            for ci, comp in enumerate(self.comps):
                px0 = _ceil_div(tcx0, comp.dx) - _ceil_div(
                    self.xosiz, comp.dx)
                py0 = _ceil_div(tcy0, comp.dy) - _ceil_div(
                    self.yosiz, comp.dy)
                p = planes[ci]
                out_comps[ci][py0:py0 + p.shape[0],
                              px0:px0 + p.shape[1]] = p
        # DC level shift + clamp to depth.
        final = []
        for ci, comp in enumerate(self.comps):
            a = out_comps[ci]
            if not comp.signed:
                a = a + (1 << (comp.depth - 1))
            lo, hi = ((-(1 << (comp.depth - 1)),
                       (1 << (comp.depth - 1)) - 1) if comp.signed
                      else (0, (1 << comp.depth) - 1))
            a = np.clip(np.round(a), lo, hi)
            dt = (np.int32 if comp.signed else np.uint32)
            if comp.depth <= 8:
                dt = np.int8 if comp.signed else np.uint8
            elif comp.depth <= 16:
                dt = np.int16 if comp.signed else np.uint16
            final.append(a.astype(dt))
        if len({c.shape for c in final}) != 1:
            # Subsampled chroma (Aperio 33003 writes 4:2:x YCbCr):
            # replicate each component up to the full grid.  Smooth
            # chroma makes pixel replication visually equivalent to
            # interpolation at WSI viewing scales.
            fh = max(c.shape[0] for c in final)
            fw = max(c.shape[1] for c in final)
            up = []
            for ci, c in enumerate(final):
                if c.shape[0] == 0 or c.shape[1] == 0:
                    # A SIZ-valid but degenerate registration can give
                    # a zero-size component grid; keep the hostile-
                    # header contract (Jp2kError, never a raw crash).
                    raise Jp2kError(
                        f"component {ci} has an empty sample grid")
                ry = _ceil_div(fh, c.shape[0])
                rx = _ceil_div(fw, c.shape[1])
                if ry > 1 or rx > 1:
                    c = np.repeat(np.repeat(c, ry, axis=0), rx,
                                  axis=1)[:fh, :fw]
                up.append(c)
            final = up
        return np.stack(final, axis=-1)

    def _decode_tile(self, t: int):
        parts = self.tile_parts.get(t)
        tx = t % self.ntx
        ty = t // self.ntx
        tcx0 = max(self.xtosiz + tx * self.xtsiz, self.xosiz)
        tcy0 = max(self.ytosiz + ty * self.ytsiz, self.yosiz)
        tcx1 = min(self.xtosiz + (tx + 1) * self.xtsiz, self.xsiz)
        tcy1 = min(self.ytosiz + (ty + 1) * self.ytsiz, self.ysiz)
        if parts is None:
            return None
        stream = b"".join(self.data[s:e] for s, e in parts)

        planes = []
        tile_bands: List[List[List[_Band]]] = []   # [comp][res][band]
        for ci, comp in enumerate(self.comps):
            cod = self._comp_cod(ci)
            cx0, cy0 = _ceil_div(tcx0, comp.dx), _ceil_div(tcy0, comp.dy)
            cx1, cy1 = _ceil_div(tcx1, comp.dx), _ceil_div(tcy1, comp.dy)
            res_bands = []
            for r in range(cod.levels + 1):
                nb = cod.levels - r
                bands = []
                if r == 0:
                    bands.append(self._make_band(
                        0, cx0, cy0, cx1, cy1, cod, r, nb))
                else:
                    for orient in (1, 2, 3):
                        bands.append(self._make_band(
                            orient, cx0, cy0, cx1, cy1, cod, r,
                            nb + 1))
                res_bands.append(bands)
            tile_bands.append(res_bands)

        self._read_packets(stream, tile_bands, tcx0, tcy0, tcx1, tcy1)

        for ci, comp in enumerate(self.comps):
            cod = self._comp_cod(ci)
            quant = self._comp_quant(ci)
            cx0, cy0 = _ceil_div(tcx0, comp.dx), _ceil_div(tcy0, comp.dy)
            cx1, cy1 = _ceil_div(tcx1, comp.dx), _ceil_div(tcy1, comp.dy)
            planes.append(self._reconstruct_component(
                ci, comp, cod, quant, tile_bands[ci],
                cx0, cy0, cx1, cy1))
        return planes

    def _make_band(self, orient: int, cx0, cy0, cx1, cy1,
                   cod: _CodingStyle, r: int, nb: int) -> _Band:
        """Band rect per T.800 B.5 (component coords -> band coords)."""
        xo = 1 if orient in (1, 3) else 0
        yo = 1 if orient in (2, 3) else 0
        if nb == 0:
            bx0, by0, bx1, by1 = cx0, cy0, cx1, cy1
        else:
            sh = 1 << nb
            half = 1 << (nb - 1)
            bx0 = _ceil_div(cx0 - half * xo, sh)
            by0 = _ceil_div(cy0 - half * yo, sh)
            bx1 = _ceil_div(cx1 - half * xo, sh)
            by1 = _ceil_div(cy1 - half * yo, sh)
        band = _Band(orient, bx0, by0, bx1, by1)
        if bx1 <= bx0 or by1 <= by0:
            return band
        # Code-block grid: global alignment on cblk-size multiples in
        # band coordinates, capped by the precinct partition.
        ppx, ppy = cod.precinct_exp(r)
        if r > 0:
            ppx, ppy = max(ppx - 1, 0), max(ppy - 1, 0)
        cbw = min(cod.cblk_w_exp, ppx)
        cbh = min(cod.cblk_h_exp, ppy)
        band.cb_w_exp, band.cb_h_exp = cbw, cbh
        gx0 = bx0 >> cbw
        gx1 = _ceil_div(bx1, 1 << cbw)
        gy0 = by0 >> cbh
        gy1 = _ceil_div(by1, 1 << cbh)
        for gy in range(gy0, gy1):
            row = []
            for gx in range(gx0, gx1):
                row.append(_CodeBlock(
                    x0=max(bx0, gx << cbw), y0=max(by0, gy << cbh),
                    x1=min(bx1, (gx + 1) << cbw),
                    y1=min(by1, (gy + 1) << cbh)))
            band.blocks.append(row)
        return band

    # ------------------------------------------------------ packet walk

    def _precinct_grid(self, comp: _Component, cod: _CodingStyle,
                       r: int, tcx0, tcy0, tcx1, tcy1):
        """Precinct count + rect helper for one resolution."""
        nb = cod.levels - r
        cx0, cy0 = _ceil_div(tcx0, comp.dx), _ceil_div(tcy0, comp.dy)
        cx1, cy1 = _ceil_div(tcx1, comp.dx), _ceil_div(tcy1, comp.dy)
        rx0, ry0 = _ceil_div(cx0, 1 << nb), _ceil_div(cy0, 1 << nb)
        rx1, ry1 = _ceil_div(cx1, 1 << nb), _ceil_div(cy1, 1 << nb)
        ppx, ppy = cod.precinct_exp(r)
        if rx1 <= rx0 or ry1 <= ry0:
            return 0, 0, (rx0, ry0, rx1, ry1), (ppx, ppy)
        npx = _ceil_div(rx1, 1 << ppx) - (rx0 >> ppx)
        npy = _ceil_div(ry1, 1 << ppy) - (ry0 >> ppy)
        return npx, npy, (rx0, ry0, rx1, ry1), (ppx, ppy)

    def _read_packets(self, stream: bytes, tile_bands,
                      tcx0, tcy0, tcx1, tcy1) -> None:
        cod = self.cod
        ncomp = len(self.comps)
        maxres = max(self._comp_cod(c).levels for c in range(ncomp)) + 1
        pos = 0

        def packet_iter():
            prog = cod.progression
            L = cod.layers
            if prog == 0:      # LRCP
                for l in range(L):
                    for r in range(maxres):
                        for c in range(ncomp):
                            yield from self._precincts_of(
                                c, r, l, tcx0, tcy0, tcx1, tcy1)
            elif prog == 1:    # RLCP
                for r in range(maxres):
                    for l in range(L):
                        for c in range(ncomp):
                            yield from self._precincts_of(
                                c, r, l, tcx0, tcy0, tcx1, tcy1)
            elif prog == 2:    # RPCL
                for r in range(maxres):
                    for p in self._positions(r, tcx0, tcy0, tcx1, tcy1):
                        for c in range(ncomp):
                            yield from self._precincts_at(
                                c, r, p, tcx0, tcy0, tcx1, tcy1)
            elif prog == 3:    # PCRL
                for p in self._positions(None, tcx0, tcy0, tcx1, tcy1):
                    for c in range(ncomp):
                        for r in range(self._comp_cod(c).levels + 1):
                            yield from self._precincts_at(
                                c, r, p, tcx0, tcy0, tcx1, tcy1)
            elif prog == 4:    # CPRL
                for c in range(ncomp):
                    for p in self._positions(None, tcx0, tcy0,
                                             tcx1, tcy1):
                        for r in range(self._comp_cod(c).levels + 1):
                            yield from self._precincts_at(
                                c, r, p, tcx0, tcy0, tcx1, tcy1)
            else:
                raise Jp2kError(f"unknown progression order {prog}")

        for (c, r, l, pi) in packet_iter():
            pos = self._read_packet(stream, pos, tile_bands, c, r, l,
                                    pi, tcx0, tcy0, tcx1, tcy1)
            if pos >= len(stream):
                # Truncated stream: whatever decoded so far stands
                # (JPEG 2000 is progressive by construction).
                break

    def _precincts_of(self, c, r, l, tcx0, tcy0, tcx1, tcy1):
        cod = self._comp_cod(c)
        if r > cod.levels:
            return
        npx, npy, _, _ = self._precinct_grid(
            self.comps[c], cod, r, tcx0, tcy0, tcx1, tcy1)
        for pi in range(npx * npy):
            yield (c, r, l, pi)

    def _positions(self, r, tcx0, tcy0, tcx1, tcy1):
        """Position (y, x) iteration for RPCL/PCRL/CPRL — the union of
        precinct origins across components (layer loop inside)."""
        seen = set()
        ncomp = len(self.comps)
        rs = [r] if r is not None else None
        for c in range(ncomp):
            cod = self._comp_cod(c)
            rr = rs if rs is not None else range(cod.levels + 1)
            for ri in rr:
                if ri > cod.levels:
                    continue
                npx, npy, (rx0, ry0, _, _), (ppx, ppy) = \
                    self._precinct_grid(self.comps[c], cod, ri,
                                        tcx0, tcy0, tcx1, tcy1)
                nb = cod.levels - ri
                for py in range(npy):
                    for px in range(npx):
                        gx = ((rx0 >> ppx) + px) << (ppx + nb)
                        gy = ((ry0 >> ppy) + py) << (ppy + nb)
                        seen.add((gy * self.comps[c].dy,
                                  gx * self.comps[c].dx))
        for p in sorted(seen):
            yield p

    def _precincts_at(self, c, r, p, tcx0, tcy0, tcx1, tcy1):
        cod = self._comp_cod(c)
        if r > cod.levels:
            return
        comp = self.comps[c]
        npx, npy, (rx0, ry0, _, _), (ppx, ppy) = self._precinct_grid(
            comp, cod, r, tcx0, tcy0, tcx1, tcy1)
        nb = cod.levels - r
        for py in range(npy):
            for px in range(npx):
                gx = ((rx0 >> ppx) + px) << (ppx + nb)
                gy = ((ry0 >> ppy) + py) << (ppy + nb)
                if (gy * comp.dy, gx * comp.dx) == p:
                    # Layers are SGcod-global (COD); a per-component
                    # COC snapshot could predate COD in the header.
                    for l in range(self.cod.layers):
                        yield (c, r, l, py * npx + px)

    def _read_packet(self, stream: bytes, pos: int, tile_bands,
                     c: int, r: int, l: int, pi: int,
                     tcx0, tcy0, tcx1, tcy1) -> int:
        cod = self._comp_cod(c)
        comp = self.comps[c]
        bands = tile_bands[c][r]
        npx, npy, (rx0, ry0, rx1, ry1), (ppx, ppy) = \
            self._precinct_grid(comp, cod, r, tcx0, tcy0, tcx1, tcy1)
        if npx == 0 or npy == 0:
            return pos
        if getattr(cod, "sop", False) and pos + 6 <= len(stream) \
                and stream[pos:pos + 2] == b"\xff\x91":
            pos += 6
        reader = _PacketBitReader(stream, pos)
        try:
            present = reader.bit()
        except Jp2kError:
            return len(stream)
        contributions = []
        if present:
            for band in bands:
                if band.x1 <= band.x0 or band.y1 <= band.y0:
                    continue
                # Precinct rect in band coords.
                pxi, pyi = pi % npx, pi // npx
                nbshift = 0 if r == 0 else 1
                bpx0 = max(band.x0,
                           (((rx0 >> ppx) + pxi) << ppx) >> nbshift)
                bpy0 = max(band.y0,
                           (((ry0 >> ppy) + pyi) << ppy) >> nbshift)
                bpx1 = min(band.x1,
                           (((rx0 >> ppx) + pxi + 1) << ppx) >> nbshift)
                bpy1 = min(band.y1,
                           (((ry0 >> ppy) + pyi + 1) << ppy) >> nbshift)
                if bpx1 <= bpx0 or bpy1 <= bpy0:
                    continue
                cbw, cbh = band.cb_w_exp, band.cb_h_exp
                gx0 = bpx0 >> cbw
                gx1 = _ceil_div(bpx1, 1 << cbw)
                gy0 = bpy0 >> cbh
                gy1 = _ceil_div(bpy1, 1 << cbh)
                band_gx0 = band.x0 >> cbw
                band_gy0 = band.y0 >> cbh
                tw, th = gx1 - gx0, gy1 - gy0
                if pi not in band.incl_tree:
                    band.incl_tree[pi] = _TagTree(tw, th)
                    band.zero_tree[pi] = _TagTree(tw, th)
                itree = band.incl_tree[pi]
                ztree = band.zero_tree[pi]
                for gy in range(gy0, gy1):
                    for gx in range(gx0, gx1):
                        cb = band.blocks[gy - band_gy0][gx - band_gx0]
                        lx, ly = gx - gx0, gy - gy0
                        if not cb.included:
                            inc = itree.decode(lx, ly, reader, l + 1)
                        else:
                            inc = bool(reader.bit())
                        if not inc:
                            continue
                        if not cb.included:
                            # Zero-bitplane tag tree, fully resolved.
                            thr = 1
                            while not ztree.decode(lx, ly, reader, thr):
                                thr += 1
                            cb.zero_planes = ztree.leaf_value(lx, ly)
                            cb.included = True
                        npasses = _decode_npasses(reader)
                        while reader.bit():
                            cb.lblock += 1
                        # Single codeword segment (no termall/bypass):
                        # one length for all new passes.
                        bits = cb.lblock + int(
                            math.floor(math.log2(npasses))
                            if npasses > 1 else 0)
                        nbytes = reader.bits(bits)
                        contributions.append((cb, npasses, nbytes))
        reader.align()
        pos = reader.pos
        if getattr(cod, "eph", False) and pos + 2 <= len(stream) \
                and stream[pos:pos + 2] == b"\xff\x92":
            pos += 2
        for cb, npasses, nbytes in contributions:
            cb.data += stream[pos:pos + nbytes]
            if pos + nbytes > len(stream):
                raise Jp2kError("packet body overruns stream")
            cb.passes += npasses
            pos += nbytes
        return pos

    # --------------------------------------------------- reconstruction

    def _reconstruct_component(self, ci, comp, cod, quant, res_bands,
                               cx0, cy0, cx1, cy1) -> np.ndarray:
        NL = cod.levels
        # Decode every code-block into its band plane, then run the
        # inverse DWT over the multi-resolution layout.
        # Band planes keyed by (resolution r, orient).
        planes: Dict[Tuple[int, int], np.ndarray] = {}
        for r in range(NL + 1):
            for band in res_bands[r]:
                bw, bh = band.x1 - band.x0, band.y1 - band.y0
                if bw <= 0 or bh <= 0:
                    planes[(r, band.orient)] = np.zeros(
                        (max(bh, 0), max(bw, 0)), np.float64)
                    continue
                arr = np.zeros((bh, bw), np.float64)
                Mb = self._band_msbs(ci, quant, r, band.orient)
                for row in band.blocks:
                    for cb in row:
                        if not cb.included or cb.passes == 0:
                            continue
                        vals = _t1(
                            bytes(cb.data), cb.x1 - cb.x0,
                            cb.y1 - cb.y0, cb.passes,
                            Mb - cb.zero_planes, band.orient,
                            bool(cod.cblk_style & 0x10),
                            quant.style != 0)
                        arr[cb.y0 - band.y0:cb.y1 - band.y0,
                            cb.x0 - band.x0:cb.x1 - band.x0] = vals
                step = self._band_step(ci, comp, quant, cod, r,
                                       band.orient)
                planes[(r, band.orient)] = arr * step
        return _inverse_dwt(planes, cod, cx0, cy0, cx1, cy1)

    def _band_gain(self, orient: int) -> int:
        return {0: 0, 1: 1, 2: 1, 3: 2}[orient]

    def _band_index(self, cod_levels: int, r: int, orient: int) -> int:
        """Index into the QCD exponent/mantissa list."""
        if r == 0:
            return 0
        return 3 * (r - 1) + orient

    def _band_msbs(self, ci: int, quant: _Quant, r: int,
                   orient: int) -> int:
        cod = self._comp_cod(ci)
        comp = self.comps[ci]
        if quant.style == 1:
            # Derived: eps_b = eps_0 - NL + nb (decomposition shift).
            eps = quant.exponents[0]
            if r == 0:
                eps_b = eps
            else:
                eps_b = eps - cod.levels + (cod.levels - r + 1)
        else:
            idx = self._band_index(cod.levels, r, orient)
            if idx >= len(quant.exponents):
                raise Jp2kError("quantization table too short")
            eps_b = quant.exponents[idx]
        # Mb = guard bits + eps_b - 1 (eps_b carries the nominal
        # range for both reversible and quantized styles).
        return quant.guard + eps_b - 1

    def _band_step(self, ci, comp, quant, cod, r, orient) -> float:
        if quant.style == 0:
            return 1.0
        gain = self._band_gain(orient)
        rb = comp.depth + gain
        if quant.style == 1:
            eps = quant.exponents[0]
            mu = quant.mantissas[0]
            eps_b = (eps - cod.levels + (cod.levels - r + 1)
                     if r else eps)
        else:
            idx = self._band_index(cod.levels, r, orient)
            eps_b = quant.exponents[idx]
            mu = quant.mantissas[idx]
        return (2.0 ** (rb - eps_b)) * (1.0 + mu / 2048.0)


def _decode_npasses(reader) -> int:
    """Number of new coding passes codeword (T.800 B.10.6)."""
    if not reader.bit():
        return 1
    if not reader.bit():
        return 2
    v = reader.bits(2)
    if v < 3:
        return 3 + v
    v = reader.bits(5)
    if v < 31:
        return 6 + v
    return 37 + reader.bits(7)


# ------------------------------------------------------------- Tier-1

def _t1(data, w, h, npasses, msbs, orient, segsym, half_at_zero):
    """Tier-1 dispatch: the native decoder when a toolchain built it
    (~100x the Python loops — what makes JPEG2000 TIFFs servable),
    else the pure-Python reference below (same LZW/JPEG pattern)."""
    try:
        from ..native import jp2k_t1_decode
        return jp2k_t1_decode(data, w, h, npasses, msbs, orient,
                              segsym, half_at_zero)
    except ImportError:
        return _t1_decode(data, w, h, npasses, msbs, orient, segsym,
                          half_at_zero)

# Zero-coding context tables per band class, indexed [h][v][d] with
# h, v in 0..2 and d in 0..4 (clamped): T.800 Table D.1.
def _zc_context(h: int, v: int, d: int, orient: int) -> int:
    if orient in (0, 2):       # LL / LH: (h, v) as-is
        hh, vv = h, v
    elif orient == 1:          # HL: swap h and v
        hh, vv = v, h
    else:                      # HH
        hv = h + v
        if d >= 3:
            return 8
        if d == 2:
            return 7 if hv >= 1 else 6
        if d == 1:
            return 5 if hv >= 2 else (4 if hv == 1 else 3)
        return 2 if hv >= 2 else hv
    if hh == 2:
        return 8
    if hh == 1:
        return 7 if vv >= 1 else (6 if d >= 1 else 5)
    if vv == 2:
        return 4
    if vv == 1:
        return 3
    return 2 if d >= 2 else d


# Sign-coding contexts + XOR bits (T.800 Table D.3): index by
# (h_contrib + 1, v_contrib + 1) where contribs are clamped to [-1, 1].
_SC_CTX = [[13, 12, 11], [10, 9, 10], [11, 12, 13]]
_SC_XOR = [[1, 1, 1], [1, 0, 0], [0, 0, 0]]


def _t1_decode(data: bytes, w: int, h: int, npasses: int, msbs: int,
               orient: int, segsym: bool,
               half_at_zero: bool = False) -> np.ndarray:
    """EBCOT Tier-1: decode one code-block's coding passes.

    Returns f64[h, w] signed coefficient values with mid-point
    reconstruction for planes never decoded.  ``half_at_zero`` adds the
    half-LSB even when every plane was decoded — the dead-zone
    quantizer's midpoint for lossy streams (reversible streams must
    stay exact, so they only midpoint truncated planes).
    """
    if msbs <= 0 or npasses <= 0:
        return np.zeros((h, w), np.float64)
    mq = _MQDecoder(data)
    sig = np.zeros((h + 2, w + 2), bool)
    sgn = np.zeros((h + 2, w + 2), np.int8)      # -1 / +1 where sig
    visited = np.zeros((h + 2, w + 2), bool)
    refined = np.zeros((h + 2, w + 2), bool)
    mag = np.zeros((h, w), np.int64)

    def neighbors(y, x):
        """(h, v, d) significance counts + sign contributions around
        padded coords (y, x)."""
        hn = int(sig[y, x - 1]) + int(sig[y, x + 1])
        vn = int(sig[y - 1, x]) + int(sig[y + 1, x])
        dn = (int(sig[y - 1, x - 1]) + int(sig[y - 1, x + 1])
              + int(sig[y + 1, x - 1]) + int(sig[y + 1, x + 1]))
        return hn, vn, dn

    def decode_sign(y, x) -> int:
        hc = min(1, max(-1, int(sgn[y, x - 1]) + int(sgn[y, x + 1])))
        vc = min(1, max(-1, int(sgn[y - 1, x]) + int(sgn[y + 1, x])))
        ctx = _SC_CTX[hc + 1][vc + 1]
        xor = _SC_XOR[hc + 1][vc + 1]
        bit = mq.decode(ctx)
        return -1 if (bit ^ xor) else 1

    plane = msbs - 1
    pass_kind = 2                  # first pass is a cleanup
    for _ in range(npasses):
        if plane < 0:
            break
        bitval = 1 << plane
        if pass_kind == 0:
            # Significance propagation.
            for y0 in range(0, h, 4):
                for x in range(w):
                    for y in range(y0, min(y0 + 4, h)):
                        py, px = y + 1, x + 1
                        if sig[py, px]:
                            continue
                        hn, vn, dn = neighbors(py, px)
                        if hn + vn + dn == 0:
                            continue
                        visited[py, px] = True
                        if mq.decode(_zc_context(
                                min(hn, 2), min(vn, 2), min(dn, 4),
                                orient)):
                            s = decode_sign(py, px)
                            sig[py, px] = True
                            sgn[py, px] = s
                            mag[y, x] = bitval
        elif pass_kind == 1:
            # Magnitude refinement.
            for y0 in range(0, h, 4):
                for x in range(w):
                    for y in range(y0, min(y0 + 4, h)):
                        py, px = y + 1, x + 1
                        if not sig[py, px] or visited[py, px]:
                            continue
                        if not refined[py, px]:
                            hn, vn, dn = neighbors(py, px)
                            ctx = 15 if hn + vn + dn else 14
                            refined[py, px] = True
                        else:
                            ctx = 16
                        if mq.decode(ctx):
                            mag[y, x] |= bitval
        else:
            # Cleanup.
            for y0 in range(0, h, 4):
                for x in range(w):
                    y = y0
                    ylim = min(y0 + 4, h)
                    # Run-length mode: full stripe column, all four
                    # insignificant with no significant neighbors.
                    if ylim - y0 == 4:
                        runnable = True
                        for yy in range(y0, ylim):
                            py, px = yy + 1, x + 1
                            if sig[py, px] or visited[py, px]:
                                runnable = False
                                break
                            hn, vn, dn = neighbors(py, px)
                            if hn + vn + dn:
                                runnable = False
                                break
                        if runnable:
                            if not mq.decode(_CTX_RL):
                                for yy in range(y0, ylim):
                                    visited[yy + 1, x + 1] = False
                                continue
                            r2 = (mq.decode(_CTX_UNI) << 1) \
                                | mq.decode(_CTX_UNI)
                            y = y0 + r2
                            py, px = y + 1, x + 1
                            s = decode_sign(py, px)
                            sig[py, px] = True
                            sgn[py, px] = s
                            mag[y, x] = bitval
                            y += 1
                    while y < ylim:
                        py, px = y + 1, x + 1
                        if sig[py, px] or visited[py, px]:
                            visited[py, px] = False
                            y += 1
                            continue
                        hn, vn, dn = neighbors(py, px)
                        if mq.decode(_zc_context(
                                min(hn, 2), min(vn, 2), min(dn, 4),
                                orient)):
                            s = decode_sign(py, px)
                            sig[py, px] = True
                            sgn[py, px] = s
                            mag[y, x] = bitval
                        y += 1
            if segsym:
                # Segmentation symbol 1010 via the uniform context;
                # mismatch means corruption — decode what we have.
                for _k in range(4):
                    mq.decode(_CTX_UNI)
            visited[:] = False
            plane -= 1
            pass_kind = 0
            continue
        if pass_kind == 0:
            pass_kind = 1      # sig-prop -> magnitude refinement
        else:
            pass_kind = 2      # magref -> cleanup (visited stays set
            #                    from sig-prop so cleanup skips those)
    # Mid-point reconstruction for undecoded planes.
    last_plane = plane + 1
    vals = mag.astype(np.float64)
    if last_plane > 0 or half_at_zero:
        nz = vals > 0
        vals[nz] += (1 << max(last_plane, 0)) * 0.5
    signs = np.where(sgn[1:h + 1, 1:w + 1] < 0, -1.0, 1.0)
    return vals * signs


# --------------------------------------------------------- inverse DWT

def _inverse_dwt(planes: Dict[Tuple[int, int], np.ndarray],
                 cod: _CodingStyle, cx0, cy0, cx1, cy1) -> np.ndarray:
    """Multi-level inverse DWT from band planes (T.800 F.3)."""
    NL = cod.levels
    ll = planes[(0, 0)]
    for r in range(1, NL + 1):
        nb = NL - r
        # Resolution rect at level r in component coords.
        ux0, uy0 = _ceil_div(cx0, 1 << nb), _ceil_div(cy0, 1 << nb)
        ux1, uy1 = _ceil_div(cx1, 1 << nb), _ceil_div(cy1, 1 << nb)
        hl = planes[(r, 1)]
        lh = planes[(r, 2)]
        hh = planes[(r, 3)]
        ll = _idwt_level(ll, hl, lh, hh, ux0, uy0, ux1, uy1,
                         cod.transform)
    return ll


def _idwt_level(ll, hl, lh, hh, ux0, uy0, ux1, uy1,
                transform: int) -> np.ndarray:
    """One 2D inverse DWT level via interleave + 1D lifting (F.3.4-8).

    ``(ux0, uy0, ux1, uy1)`` is the output rect in this level's
    coordinates; subband rects follow from its even/odd split.
    """
    h, w = uy1 - uy0, ux1 - ux0
    if h <= 0 or w <= 0:
        return np.zeros((max(h, 0), max(w, 0)), np.float64)
    a = np.zeros((h, w), np.float64)
    # Interleave: sample (u, v) is from LL/HL/LH/HH by parity of
    # (u - ?) — global coords decide parity.
    ys = np.arange(uy0, uy1)
    xs = np.arange(ux0, ux1)
    ye, yo = (ys % 2 == 0), (ys % 2 == 1)
    xe, xo = (xs % 2 == 0), (xs % 2 == 1)
    a[np.ix_(ye, xe)] = ll
    a[np.ix_(ye, xo)] = hl
    a[np.ix_(yo, xe)] = lh
    a[np.ix_(yo, xo)] = hh
    a = _lift1d(a, ux0, transform, axis=1)
    a = _lift1d(a, uy0, transform, axis=0)
    return a


def _lift1d(a: np.ndarray, i0: int, transform: int,
            axis: int) -> np.ndarray:
    """Inverse 1D lifting over axis with global offset parity (T.800
    F.3.8 symmetric extension via reflect padding)."""
    if axis == 0:
        a = a.T
        out = _lift1d(a, i0, transform, axis=1)
        return out.T
    n = a.shape[1]
    if n == 1:
        # Single-sample line: pass-through (scaled for the odd-start
        # 5/3 case per F.3.7; for 9/7 openjpeg uses the same rule).
        if i0 % 2 == 1:
            return a / 2.0 if transform == 1 else a
        return a
    # Work on an extended array so boundary taps use full symmetric
    # extension (period 2n-2, folded — lines shorter than the pad need
    # multiple reflections).  The pad must out-reach the lifting
    # cascade: each of the (up to four) steps lets a wrong outermost
    # value creep one position inward, so ext > steps keeps the output
    # region clean.
    ext = 6
    idx = np.arange(-ext, n + ext)
    period = 2 * (n - 1)
    m = np.mod(idx, period)
    ref = np.where(m >= n, period - m, m)
    x = a[:, ref]
    pos = i0 + np.arange(-ext, n + ext)
    even = (pos % 2 == 0)
    if transform == 1:
        # 5/3 reversible (F.3.8.2.1): x[2n] -= floor((x[2n-1] +
        # x[2n+1] + 2) / 4); x[2n+1] += floor((x[2n] + x[2n+2]) / 2).
        y = x.copy()
        left = np.roll(x, 1, axis=1)
        right = np.roll(x, -1, axis=1)
        upd = np.floor((left + right + 2) / 4.0)
        y = np.where(even[None, :], x - upd, y)
        yl = np.roll(y, 1, axis=1)
        yr = np.roll(y, -1, axis=1)
        pred = np.floor((yl + yr) / 2.0)
        y = np.where(~even[None, :], x + pred, y)
        return y[:, ext:ext + n]
    # 9/7 irreversible synthesis (T.800 F.4.8.2): scale low by K, high
    # by 1/K, then lifting steps -delta (even), -gamma (odd),
    # +beta (even), +alpha (odd) — each step reads already-updated
    # neighbors, symmetric extension at the borders.
    K = 1.230174104914001
    alpha, beta, gamma, delta = (1.586134342059924, 0.052980118572961,
                                 0.882911075530934, 0.443506852043971)
    y = np.where(even[None, :], x * K, x / K)
    for coef, on_even in ((-delta, True), (-gamma, False),
                          (beta, True), (alpha, False)):
        left = np.roll(y, 1, axis=1)
        right = np.roll(y, -1, axis=1)
        tgt = even if on_even else ~even
        y = np.where(tgt[None, :], y + coef * (left + right), y)
    return y[:, ext:ext + n]


# ------------------------------------------------------------------ MCT

def _inverse_rct(y, u, v):
    """T.800 G.2: comp1 = B - G, comp2 = R - G."""
    g = y - np.floor((u + v) / 4.0)
    r = v + g
    b = u + g
    return [r, g, b]


def _inverse_ict(y, cb, cr):
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    return [r, g, b]


# ------------------------------------------------------------ public API

def _find_codestream(data: bytes) -> bytes:
    """Raw J2K passes through; JP2 box files yield their ``jp2c`` box."""
    if data[:2] == b"\xff\x4f":
        return data
    if data[:12] == b"\x00\x00\x00\x0cjP  \r\n\x87\n":
        pos = 12
        while pos + 8 <= len(data):
            lbox = struct.unpack(">I", data[pos:pos + 4])[0]
            tbox = data[pos + 4:pos + 8]
            if lbox == 1:
                xl = struct.unpack(">Q", data[pos + 8:pos + 16])[0]
                body_start, box_end = pos + 16, pos + xl
            elif lbox == 0:
                body_start, box_end = pos + 8, len(data)
            else:
                body_start, box_end = pos + 8, pos + lbox
            if tbox == b"jp2c":
                return data[body_start:box_end]
            if box_end <= pos:
                break
            pos = box_end
        raise Jp2kError("JP2 file has no codestream box")
    raise Jp2kError("not a JPEG 2000 stream (no SOC / JP2 signature)")


def _jp2k_error_contract(fn):
    """Everything malformed must surface as :class:`Jp2kError` (a
    ValueError): these streams come from untrusted files, and server
    error mapping turns ValueError into a 4xx instead of a 500.  The
    explicit checks cover the known shapes; this net catches residual
    IndexError/struct.error/AttributeError/etc from hostile input
    (same pattern as jpegdec's _jpeg_error_contract)."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except (IndexError, KeyError, AttributeError, struct.error,
                OverflowError, MemoryError, ZeroDivisionError) as e:
            raise Jp2kError(f"malformed JPEG 2000 stream: {e}") from e
    return wrapped


@_jp2k_error_contract
def decode_jp2k(data: bytes) -> np.ndarray:
    """Decode a JPEG 2000 codestream (raw J2K or JP2 file) to
    ``[h, w, ncomp]``."""
    return _Decoder(_find_codestream(bytes(data))).decode()


@_jp2k_error_contract
def decode_tiff_jp2k(data: bytes, compression: int,
                     photometric: int) -> np.ndarray:
    """Decode one TIFF 33003/33005 segment (a raw J2K codestream, the
    Aperio layout) to ``u8/u16[h, w, spp]``.

    33003 stores YCbCr planes with the codestream's own MCT off
    (openslide's AperioJp2kYCbCr); the color transform happens here.
    33005 (and MCT-on streams) come back as stored.
    """
    dec = _Decoder(_find_codestream(bytes(data)))
    out = dec.decode()
    wants_ycc = compression == 33003 or photometric == 6
    if wants_ycc and out.shape[-1] == 3 and not dec.cod.mct:
        if out.dtype.itemsize != 1:
            # ycbcr_to_rgb is 8-bit; clipping deeper data would serve
            # silently saturated garbage.
            raise Jp2kError(
                f"{out.dtype.itemsize * 8}-bit YCbCr JPEG 2000 is not "
                f"supported (8-bit only)")
        from .jpegdec import ycbcr_to_rgb
        out = ycbcr_to_rgb(out.astype(np.uint8))
    return out
