"""Packed host->device staging for raw uint16 pixel data.

The cold first-touch path is wire-bound: a network-attached TPU moves
~20-30 MB/s host->HBM, and raw 16-bit WSI tiles are 8 MB each.  Pixel
content is smooth signal + sensor noise, so block bit-packed zigzag row
deltas (``native/wirepack.cpp``) carry the same planes in ~1.4x fewer
bytes — and, unlike general entropy coding, the fixed-width-per-block
layout decodes VECTORIZED on the device: a gather + shift per sample
and one row cumsum, no sequential bitstream walk (which a TPU cannot
express).  This is the H2D mirror of the D2H JPEG wire: ship transforms
of the pixels sized to the link, compute the inverse where the data
lands.

``stage(arr)`` is the drop-in for ``jax.device_put`` on storage-dtype
raw planes: it packs when the packer is available and the content
actually compresses, and falls back to a plain transfer otherwise
(including non-uint16 dtypes).  The decode cost is a few ms per 8 MB
tile — noise against the ~300 ms the saved bytes buy on a tunnel link.

Reference context: the reference's Bio-Formats path materializes raw
planes host-side and hands byte[] buffers to the renderer in-process
(``ImageRegionRequestHandler.java:302-309,559``); it never pays a
device link, so this stage has no Java counterpart — it is what makes
the TPU-offload architecture viable on thin links.
"""

from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

# Words arrays pad up to one of these lengths so the unpack kernel
# compiles once per (shape, padded-length) instead of once per
# data-dependent length (each distinct shape costs an XLA compile —
# seconds on tunnel-attached chips).  Ratio 2^(1/4) = <=19% padding.
_LADDER_RATIO = 2.0 ** 0.25
_LADDER_FLOOR = 4096          # words


def _pad_words(n: int) -> int:
    if n <= _LADDER_FLOOR:
        return _LADDER_FLOOR
    steps = math.ceil(math.log(n / _LADDER_FLOOR, _LADDER_RATIO))
    return int(math.ceil(_LADDER_FLOOR * _LADDER_RATIO ** steps))


@functools.partial(jax.jit, static_argnames=("shape",))
def unpack16_device(words, widths, shape) -> jax.Array:
    """Inverse of ``native.wirepack_pack16`` on device.

    ``words`` u32[>=n_words] (zero-padded), ``widths``
    u8[n_rows * ceil(W/32)], ``shape`` the original array shape.
    Fully vectorized: per-sample gather + shifts, then a per-row
    cumsum undoes the delta coding.
    """
    W = shape[-1]
    n_rows = 1
    for s in shape[:-1]:
        n_rows *= s
    bpr = (W + 31) // 32
    w32 = widths.astype(jnp.int32)                      # [n_rows*bpr]
    block_bits = w32 * 32
    off = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(block_bits)])[:-1]
    col = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), (n_rows, W))
    b = (jnp.arange(n_rows, dtype=jnp.int32)[:, None] * bpr
         + col // 32)                                   # [n_rows, W]
    j = col % 32
    w = w32[b]
    pos = off[b] + j * w
    wi = pos >> 5
    sh = (pos & 31).astype(jnp.uint32)
    words = words.astype(jnp.uint32)
    lo = words[wi] >> sh
    hi_shift = (jnp.uint32(32) - sh) & jnp.uint32(31)
    hi = jnp.where(sh > 0,
                   words[jnp.minimum(wi + 1, words.shape[0] - 1)]
                   << hi_shift,
                   jnp.uint32(0))
    mask = (jnp.uint32(1) << w.astype(jnp.uint32)) - jnp.uint32(1)
    z = ((lo | hi) & mask).astype(jnp.int32)
    d = (z >> 1) ^ -(z & 1)                             # un-zigzag
    x = jnp.cumsum(d, axis=1)                           # undo row delta
    return x.astype(jnp.uint16).reshape(shape)


def pack16_host(arr: np.ndarray):
    """Host-side packing via the native packer; raises ImportError when
    the toolchain is unavailable (callers fall back to raw staging)."""
    from ..native import wirepack_pack16
    return wirepack_pack16(arr)


# Skip packing below this size: dispatch + decode overhead beats the
# saved bytes on small transfers.
_MIN_STAGE_BYTES = 1 << 20
# Bit offsets are computed with int32 arithmetic on device (TPUs run
# x32); past this many samples the packed bit count could exceed 2^31
# and silently wrap, so bigger arrays take the plain transfer.
_MAX_STAGE_SAMPLES = (1 << 31) // 18


def _regular_shape(shape) -> bool:
    """Shapes worth compiling an unpack executable for.

    ``unpack16_device`` is shape-jitted and a novel shape costs a
    seconds-scale compile on tunnel-attached chips — far more than the
    packed bytes save once.  Serving traffic is dominated by bucketed
    tiles and tile-snapped bands, so packing is restricted to that
    lattice (rows % 64 == 0, width % 256 == 0); arbitrary client
    region shapes fall back to the un-compiled plain transfer.
    """
    h, w = shape[-2], shape[-1]
    lead = 1
    for s in shape[:-2]:
        lead *= s
    return h % 64 == 0 and w % 256 == 0 and lead <= 64


@contextlib.contextmanager
def pin_scope(device):
    """Run the enclosed dispatches on ``device`` — per-member device
    pinning for the combined federated role (``parallel.federation``
    partitions ``jax.local_devices()`` across a host's members, so
    each member's staging and render executes on ITS device set).
    ``None`` yields straight through: the process default device, the
    pre-federation behavior, at zero cost."""
    if device is None:
        yield
        return
    import jax
    with jax.default_device(device):
        yield


def stage(arr: np.ndarray, min_ratio: float = 1.1):
    """Packed ``device_put`` for uint16 raw planes.

    Packs on host, ships words + widths, decodes on device; returns the
    device uint16 array.  Falls back to a plain ``device_put`` when the
    packer is unavailable, the dtype is not uint16, the array is small,
    huge (int32 bit-offset budget), off the regular tile/band shape
    lattice (compile economics), or the content does not compress by at
    least ``min_ratio`` (noise floors exist: packed-but-incompressible
    data would ship 17/16 of raw).
    """
    if (not isinstance(arr, np.ndarray) or arr.dtype != np.uint16
            or arr.nbytes < _MIN_STAGE_BYTES or arr.ndim < 2
            or arr.size > _MAX_STAGE_SAMPLES
            or not _regular_shape(arr.shape)):
        return jax.device_put(arr)
    try:
        words, widths = pack16_host(arr)
    except ImportError:
        return jax.device_put(arr)
    # Judge the bytes that actually cross the link: the words buffer
    # ships at its ladder-padded length (up to ~19% over), so a pack
    # accepted at ~0.91x raw could ship ~1.08x raw after padding.
    packed_bytes = _pad_words(len(words)) * 4 + widths.nbytes
    if packed_bytes * min_ratio > arr.nbytes:
        return jax.device_put(arr)
    padded = np.zeros(_pad_words(len(words)), np.uint32)
    padded[:len(words)] = words
    return unpack16_device(jax.device_put(padded),
                           jax.device_put(widths), arr.shape)


def stage_deduped(arr: np.ndarray, cache, digest: str = None):
    """Digest-first staging: skip the upload when the content is already
    device-resident.

    ``cache`` is an ``io.devicecache.DeviceRawCache`` with its digest
    index on.  Returns ``(device_array, digest, was_resident)``:
    ``was_resident`` True means zero bytes crossed the host->device link
    (the plane was found under some key — a prior wire push, or the same
    content staged for another region identity).  On a miss the plane
    stages through :func:`stage` (packed when it pays) and is recorded
    under its content key, so the NEXT identical push — from any
    frontend, for any region identity — skips the wire.

    This is the server half of the sidecar's digest-first plane
    protocol (``server.sidecar``: ``plane_probe`` then ``plane_put``
    only on miss), and the in-process staging skip for everything else.
    """
    from .devicecache import plane_digest, plane_key

    digest = digest or plane_digest(arr)
    resident = cache.get_by_digest(digest)
    if resident is not None:
        cache.count_plane(hit=True)
        from ..utils import telemetry
        telemetry.add_cost("staged_bytes_skipped", arr.nbytes)
        return resident, digest, True
    staged = cache.get_or_load(plane_key(digest), lambda: arr,
                               digest=digest)
    return staged, digest, False


def stage_ratio(arr: np.ndarray) -> float:
    """Diagnostic: packed/raw byte ratio for ``arr`` (1.0 = raw)."""
    words, widths = pack16_host(arr)
    return (words.nbytes + widths.nbytes) / arr.nbytes
