"""Tiled OME-TIFF pyramid writer.

Ingest-side counterpart of :class:`.ometiff.OmeTiffSource` (the export
path OMERO/Bio-Formats covers for the reference): writes [T, C, Z, H, W]
arrays as a tiled OME-TIFF with SubIFD pyramid levels (OME-TIFF 6.0),
one IFD per plane in DimensionOrder, OME-XML on the first IFD.  Used by
``scripts/ingest`` tooling and the e2e tests; classic TIFF by default,
BigTIFF automatically once offsets could exceed 32 bits.

Only what the reader consumes is emitted: BlackIsZero photometric,
SamplesPerPixel=1, no predictor, compression none or deflate.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from .store import _downsample2

_ASCII = 2
_SHORT = 3
_LONG = 4
_LONG8 = 16

_CODES = {1: "B", _SHORT: "H", _LONG: "I", _LONG8: "Q"}
_SIZES = {1: 1, _ASCII: 1, _SHORT: 2, _LONG: 4, _LONG8: 8}

_DTYPE_FMT = {"u": 1, "i": 2, "f": 3}

_OME_TYPE = {
    "uint8": "uint8", "uint16": "uint16", "uint32": "uint32",
    "int8": "int8", "int16": "int16", "int32": "int32",
    "float32": "float", "float64": "double",
}


def _ome_xml(T: int, C: int, Z: int, H: int, W: int, dtype) -> str:
    ptype = _OME_TYPE[np.dtype(dtype).name]
    channels = "".join(
        f'<Channel ID="Channel:0:{c}" SamplesPerPixel="1"/>'
        for c in range(C))
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        '<OME xmlns="http://www.openmicroscopy.org/Schemas/OME/2016-06">'
        '<Image ID="Image:0"><Pixels ID="Pixels:0" '
        f'DimensionOrder="XYZCT" Type="{ptype}" Interleaved="false" '
        f'SizeX="{W}" SizeY="{H}" SizeZ="{Z}" SizeC="{C}" SizeT="{T}" '
        'BigEndian="false">'
        f'{channels}<TiffData/></Pixels></Image></OME>'
    )


class _TiffOut:
    """Sequential TIFF writer with IFD/next-pointer patching."""

    def __init__(self, f, big: bool):
        self.f = f
        self.big = big
        self.e = "<"
        f.write(b"II")
        if big:
            f.write(struct.pack("<HHHQ", 43, 8, 0, 0))
            self._first_ifd_patch = 8
        else:
            f.write(struct.pack("<HI", 42, 0))
            self._first_ifd_patch = 4

    def tell(self) -> int:
        return self.f.tell()

    def align(self) -> None:
        pos = self.f.tell()
        if pos % 2:
            self.f.write(b"\0")

    def write(self, data: bytes) -> int:
        off = self.f.tell()
        self.f.write(data)
        return off

    def patch(self, pos: int, value: int) -> None:
        cur = self.f.tell()
        self.f.seek(pos)
        self.f.write(struct.pack(self.e + ("Q" if self.big else "I"),
                                 value))
        self.f.seek(cur)

    def patch_first_ifd(self, off: int) -> None:
        self.patch(self._first_ifd_patch, off)

    def write_ifd(self, tags: List[Tuple[int, int, object]]
                  ) -> Tuple[int, int]:
        """Write one IFD; returns (ifd_offset, next_field_pos).

        ``tags`` is [(tag, type, values)]; values is bytes for ASCII or a
        sequence of ints otherwise.  The next-IFD pointer is written as
        0 for the caller to patch.
        """
        self.align()
        e = self.e
        tags = sorted(tags)
        if self.big:
            count_fmt, entry_n, off_fmt, inline_cap = "Q", 20, "Q", 8
        else:
            count_fmt, entry_n, off_fmt, inline_cap = "H", 12, "I", 4
        ifd_off = self.f.tell()
        n = len(tags)
        next_pos = (ifd_off + struct.calcsize(count_fmt)
                    + n * entry_n)
        overflow_off = next_pos + struct.calcsize(e + off_fmt)

        entries = b""
        overflow = b""
        for tag, ftype, values in tags:
            if ftype == _ASCII:
                data = bytes(values)
                if not data.endswith(b"\0"):
                    data += b"\0"
                count = len(data)
            else:
                seq = list(values)
                count = len(seq)
                data = struct.pack(e + _CODES[ftype] * count, *seq)
            ent = struct.pack(e + "HH", tag, ftype)
            ent += struct.pack(e + ("Q" if self.big else "I"), count)
            if len(data) <= inline_cap:
                ent += data + b"\0" * (inline_cap - len(data))
            else:
                pad = len(overflow) % 2
                overflow += b"\0" * pad
                ent += struct.pack(e + off_fmt,
                                   overflow_off + len(overflow))
                overflow += data
            entries += ent
        self.f.write(struct.pack(e + count_fmt, n))
        self.f.write(entries)
        self.f.write(struct.pack(e + off_fmt, 0))
        self.f.write(overflow)
        return ifd_off, next_pos


def _plane_levels(plane: np.ndarray, n_levels: Optional[int],
                  min_level_size: int) -> List[np.ndarray]:
    levels = [plane]
    while True:
        if n_levels is not None and len(levels) >= n_levels:
            break
        h, w = levels[-1].shape
        if n_levels is None and min(h // 2, w // 2) < min_level_size:
            break
        if min(h // 2, w // 2) < 1:
            break
        levels.append(_downsample2(levels[-1]))
    return levels


def _tile_bytes(plane: np.ndarray, th: int, tw: int, gy: int, gx: int,
                compression: str) -> bytes:
    tile = plane[gy * th:(gy + 1) * th, gx * tw:(gx + 1) * tw]
    if tile.shape != (th, tw):
        full = np.zeros((th, tw), dtype=plane.dtype)
        full[:tile.shape[0], :tile.shape[1]] = tile
        tile = full
    raw = np.ascontiguousarray(tile).tobytes()
    if compression == "deflate":
        return zlib.compress(raw, 6)
    return raw


def write_ome_tiff(
    planes: np.ndarray,
    path: str,
    tile: Tuple[int, int] = (256, 256),
    compression: str = "none",
    n_levels: Optional[int] = None,
    min_level_size: int = 256,
    bigtiff: Optional[bool] = None,
    description: Optional[str] = None,
) -> str:
    """Write [T, C, Z, H, W] (or [C, Z, H, W]) as a pyramidal OME-TIFF.

    ``description`` overrides the generated OME-XML — used to build
    multi-file sets (TiffData FileName entries / BinaryOnly stubs)."""
    if planes.ndim == 4:
        planes = planes[None]
    if planes.ndim != 5:
        raise ValueError("planes must be [T, C, Z, H, W] or [C, Z, H, W]")
    if compression not in ("none", "deflate"):
        raise ValueError(f"unsupported compression {compression!r}")
    T, C, Z, H, W = planes.shape
    tw, th = tile
    if bigtiff is None:
        bigtiff = planes.nbytes * 2 > (1 << 32) - (1 << 20)

    comp_code = 8 if compression == "deflate" else 1
    dt = planes.dtype
    bits = dt.itemsize * 8
    sfmt = _DTYPE_FMT[dt.kind]
    off_type = _LONG8 if bigtiff else _LONG
    ome = (description if description is not None
           else _ome_xml(T, C, Z, H, W, dt)).encode()

    with open(path, "wb") as f:
        out = _TiffOut(f, bigtiff)

        # Pass 1: all tile data, plane-major then level-major, recording
        # (offsets, counts, level_dims) per (plane_index, level).
        plane_seq = [(z, c, t) for t in range(T) for c in range(C)
                     for z in range(Z)]        # XYZCT: z fastest
        tiles_of = {}
        level_dims = None
        for p, (z, c, t) in enumerate(plane_seq):
            levels = _plane_levels(planes[t, c, z], n_levels,
                                   min_level_size)
            dims = [(lv.shape[1], lv.shape[0]) for lv in levels]
            if level_dims is None:
                level_dims = dims
            elif dims != level_dims:
                raise ValueError("planes produced inconsistent pyramids")
            for li, lv in enumerate(levels):
                h, w = lv.shape
                gy_n, gx_n = -(-h // th), -(-w // tw)
                offs, cnts = [], []
                for gy in range(gy_n):
                    for gx in range(gx_n):
                        data = _tile_bytes(lv, th, tw, gy, gx,
                                           compression)
                        out.align()
                        offs.append(out.write(data))
                        cnts.append(len(data))
                tiles_of[(p, li)] = (offs, cnts)

        n_levels_final = len(level_dims)

        def base_tags(w: int, h: int, offs, cnts):
            return [
                (256, _LONG, [w]), (257, _LONG, [h]),
                (258, _SHORT, [bits]), (259, _SHORT, [comp_code]),
                (262, _SHORT, [1]), (277, _SHORT, [1]),
                (284, _SHORT, [1]),
                (322, _LONG, [tw]), (323, _LONG, [th]),
                (324, off_type, offs), (325, off_type, cnts),
                (339, _SHORT, [sfmt]),
            ]

        # Pass 2: SubIFDs (levels >= 1) per plane, then the chained main
        # IFDs referencing them.
        sub_offsets = {}
        for p in range(len(plane_seq)):
            subs = []
            for li in range(1, n_levels_final):
                w, h = level_dims[li]
                offs, cnts = tiles_of[(p, li)]
                tags = base_tags(w, h, offs, cnts)
                tags.append((254, _LONG, [1]))   # reduced-resolution
                ifd_off, _next = out.write_ifd(tags)
                subs.append(ifd_off)
            sub_offsets[p] = subs

        prev_next_pos = None
        first_ifd = None
        for p in range(len(plane_seq)):
            w, h = level_dims[0]
            offs, cnts = tiles_of[(p, 0)]
            tags = base_tags(w, h, offs, cnts)
            if sub_offsets[p]:
                tags.append((330, off_type, sub_offsets[p]))
            if p == 0:
                tags.append((270, _ASCII, ome))
            ifd_off, next_pos = out.write_ifd(tags)
            if p == 0:
                first_ifd = ifd_off
            else:
                out.patch(prev_next_pos, ifd_off)
            prev_next_pos = next_pos
        out.patch_first_ifd(first_ifd)
    return path
