"""Baseline sequential JPEG decoder (ITU-T T.81) for JPEG-in-TIFF.

The reference reads JPEG-compressed TIFF (compression 7 — Aperio SVS,
Hamamatsu exports, most vendor WSI pyramids) through Bio-Formats behind
``PixelsService.getPixelBuffer`` (``build.gradle:81-83``).  No JPEG
*decode* library exists in this image (PIL decodes whole files, not the
abbreviated per-tile streams TIFF stores), so the decoder is implemented
directly; scope is what TIFF serving needs:

- baseline sequential DCT (SOF0/1) and progressive DCT (SOF2);
- 8-bit samples, plus 12-bit extended/progressive frames decoding to
  uint16 (the precision-over-8 microscopy exports Bio-Formats reads);
- 1..4 components, sampling factors 1-2 (4:4:4, 4:2:2, 4:2:0);
- abbreviated streams: a ``JPEGTables`` (TIFF tag 347) stream carries
  DQT/DHT once, per-tile streams reference them (T.81 Annex B.5);
- restart markers (DRI/RSTn), inter-scan DHT/DQT/DRI updates.

Lossless JPEG (SOF3) and arithmetic-coded processes reject with errors
naming the variant.

The entropy decode is a tight Python loop over Huffman codes; the heavy
math (dequantize + IDCT + upsample + color transform) is vectorized
numpy over all blocks at once.  A native C++ fast path mirrors this
module (``native.jpeg_decode_baseline``); callers go through
:func:`decode_tiff_jpeg` which prefers it — the same native-fallback
pattern the LZW path uses (``io/tiff.py``).

Output is the raw decoded component array ``[h, w, ncomp]`` (uint8, or
uint16 for 12-bit frames); the YCbCr→RGB decision belongs to the TIFF
layer (photometric 6 converts, photometric 1/2 serve components as
stored).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

# Zig-zag order: index i holds the (row-major) position of the i-th
# zig-zag coefficient (T.81 Figure A.6).
ZIGZAG = np.array([
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63,
], dtype=np.int32)

# 8x8 IDCT basis: spatial = M^T @ coeff @ M with M[u, x] scaled DCT-II.
_IDCT_M = np.array([
    [(np.sqrt(0.125) if u == 0 else 0.5)
     * np.cos((2 * x + 1) * u * np.pi / 16)
     for x in range(8)] for u in range(8)
], dtype=np.float32)


class JpegError(ValueError):
    """Malformed or unsupported JPEG stream."""


@dataclass
class _Huff:
    """Flat-lookup Huffman table: 16-bit left-aligned prefix -> (value,
    length).  Max code length is 16 bits, so one 64K table decodes any
    code in a single index — the loop stays in Python but each symbol
    is O(1)."""

    lookup_val: np.ndarray   # u8[65536]
    lookup_len: np.ndarray   # u8[65536]  (0 = invalid prefix)


def _build_huff(bits: bytes, values: bytes) -> _Huff:
    lookup_val = np.zeros(65536, np.uint8)
    lookup_len = np.zeros(65536, np.uint8)
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(bits[length - 1]):
            if k >= len(values):
                raise JpegError("DHT: counts exceed values")
            aligned = code << (16 - length)
            span = 1 << (16 - length)
            if aligned + span > 65536:
                raise JpegError("DHT: code overflow")
            lookup_val[aligned:aligned + span] = values[k]
            lookup_len[aligned:aligned + span] = length
            code += 1
            k += 1
        code <<= 1
    return _Huff(lookup_val, lookup_len)


@dataclass
class _Component:
    ident: int
    h: int                  # horizontal sampling factor
    v: int                  # vertical sampling factor
    tq: int                 # quant table id
    td: int = 0             # DC huffman id (from SOS)
    ta: int = 0             # AC huffman id (from SOS)


class _TableSet:
    """Mutable DQT/DHT/DRI state, shared between a JPEGTables stream and
    the abbreviated tile stream that follows it (T.81 B.5)."""

    def __init__(self) -> None:
        self.quant: Dict[int, np.ndarray] = {}        # id -> i32[64] zigzag
        self.huff_dc: Dict[int, _Huff] = {}
        self.huff_ac: Dict[int, _Huff] = {}
        self.restart_interval = 0


class _BitReader:
    """MSB-first bit reader over entropy-coded data with 0xFF00
    unstuffing; marker bytes terminate the stream (pad with 1s)."""

    __slots__ = ("data", "pos", "buf", "nbits", "marker")

    def __init__(self, data: bytes, pos: int) -> None:
        self.data = data
        self.pos = pos
        self.buf = 0
        self.nbits = 0
        self.marker: Optional[int] = None

    def _fill(self) -> None:
        data = self.data
        while self.nbits <= 48:
            if self.marker is not None or self.pos >= len(data):
                # Past the end: feed 1-bits (T.81 F.2.2.5 padding); a
                # well-formed stream never consumes them into samples.
                self.buf = (self.buf << 8) | 0xFF
                self.nbits += 8
                continue
            b = data[self.pos]
            if b == 0xFF:
                nxt = data[self.pos + 1] if self.pos + 1 < len(data) else 0xD9
                if nxt == 0x00:
                    self.pos += 2
                elif 0xD0 <= nxt <= 0xD7:
                    # RST markers are consumed by restart(), not here.
                    self.marker = nxt
                    continue
                else:
                    self.marker = nxt
                    continue
            else:
                self.pos += 1
            self.buf = (self.buf << 8) | b
            self.nbits += 8

    def peek16(self) -> int:
        if self.nbits < 16:
            self._fill()
        return (self.buf >> (self.nbits - 16)) & 0xFFFF

    def skip(self, n: int) -> None:
        self.nbits -= n
        self.buf &= (1 << self.nbits) - 1

    def receive(self, n: int) -> int:
        if n == 0:
            return 0
        if self.nbits < n:
            self._fill()
        v = (self.buf >> (self.nbits - n)) & ((1 << n) - 1)
        self.skip(n)
        return v

    def restart(self) -> None:
        """Byte-align and consume one RSTn marker."""
        self.buf = 0
        self.nbits = 0
        if self.marker is not None and 0xD0 <= self.marker <= 0xD7:
            self.pos += 2
            self.marker = None
            return
        # Marker not yet reached in _fill: scan forward.
        data = self.data
        while self.pos + 1 < len(data):
            if data[self.pos] == 0xFF and 0xD0 <= data[self.pos + 1] <= 0xD7:
                self.pos += 2
                # A stale non-RST marker (spurious FFxx in corrupt
                # entropy data) must not make _fill pad the rest of the
                # image with 1-bits.
                self.marker = None
                return
            self.pos += 1
        raise JpegError("missing restart marker")


def _extend(v: int, t: int) -> int:
    """T.81 F.2.2.1 EXTEND: map t-bit magnitude to signed value."""
    return v - (1 << t) + 1 if t and v < (1 << (t - 1)) else v


def _decode_huff(reader: _BitReader, table: _Huff) -> int:
    prefix = reader.peek16()
    length = int(table.lookup_len[prefix])
    if length == 0:
        raise JpegError("invalid huffman code")
    reader.skip(length)
    return int(table.lookup_val[prefix])


def _parse_segments(data: bytes, tables: _TableSet):
    """Walk marker segments until SOS (or EOI).  Returns
    (frame, first_scan, scan_start, progressive) — frame is None for a
    tables-only stream."""
    if len(data) < 2 or data[0] != 0xFF or data[1] != 0xD8:
        raise JpegError("no SOI")
    pos = 2
    frame: Optional[Tuple[int, int, List[_Component]]] = None
    progressive = False
    while pos + 2 <= len(data):
        if data[pos] != 0xFF:
            raise JpegError(f"expected marker at {pos}")
        marker = data[pos + 1]
        if marker == 0xD9:               # EOI (tables-only stream)
            return frame, None, pos, progressive
        if marker == 0x01 or 0xD0 <= marker <= 0xD7:
            pos += 2                     # standalone marker, no length
            continue
        if pos + 4 > len(data):
            raise JpegError("truncated segment")
        seglen = struct.unpack(">H", data[pos + 2:pos + 4])[0]
        if seglen < 2 or pos + 2 + seglen > len(data):
            raise JpegError("truncated segment")
        body = data[pos + 4:pos + 2 + seglen]
        if marker == 0xDB:               # DQT
            i = 0
            while i < len(body):
                pq, tq = body[i] >> 4, body[i] & 0xF
                i += 1
                if pq == 0:
                    q = np.frombuffer(body[i:i + 64], np.uint8)
                    i += 64
                else:
                    q = np.frombuffer(body[i:i + 128], ">u2")
                    i += 128
                if q.size != 64:
                    raise JpegError("truncated DQT")
                tables.quant[tq] = q.astype(np.int32)
        elif marker == 0xC4:             # DHT
            i = 0
            while i + 17 <= len(body):
                tc, th = body[i] >> 4, body[i] & 0xF
                bits = body[i + 1:i + 17]
                n = sum(bits)
                values = body[i + 17:i + 17 + n]
                if len(values) != n:
                    raise JpegError("truncated DHT")
                dst = tables.huff_dc if tc == 0 else tables.huff_ac
                dst[th] = _build_huff(bits, values)
                i += 17 + n
        elif marker == 0xDD:             # DRI
            if len(body) < 2:
                raise JpegError("truncated DRI")
            tables.restart_interval = struct.unpack(">H", body[:2])[0]
        elif marker in (0xC0, 0xC1, 0xC2):   # SOF0/1 baseline, SOF2 prog
            if len(body) < 6:
                raise JpegError("truncated SOF")
            precision = body[0]
            if marker == 0xC0 and precision != 8:
                # Baseline DCT is 8-bit by definition (T.81 4.11).
                raise JpegError(
                    f"unsupported sample precision {precision} "
                    f"for baseline SOF0 (8-bit only)")
            if precision not in (8, 12):
                # 16-bit precision exists only in lossless JPEG
                # (SOF3); DCT processes are 8/12-bit.  Decoding
                # anything else would serve silently saturated garbage.
                raise JpegError(
                    f"unsupported sample precision {precision} "
                    f"(8-bit and 12-bit extended/progressive only)")
            h, w = struct.unpack(">HH", body[1:5])
            ncomp = body[5]
            if not 1 <= ncomp <= 4 or len(body) < 6 + 3 * ncomp:
                raise JpegError("truncated SOF components")
            if h * w * ncomp > (1 << 28):
                # Hostile headers must not drive allocations (a TIFF
                # tile is orders of magnitude smaller).
                raise JpegError("frame exceeds the 256M-sample cap")
            comps = []
            for ci in range(ncomp):
                ident, hv, tq = body[6 + 3 * ci:9 + 3 * ci]
                comps.append(_Component(ident, hv >> 4, hv & 0xF, tq))
            for c in comps:
                if not (1 <= c.h <= 2 and 1 <= c.v <= 2):
                    raise JpegError(
                        f"unsupported sampling {c.h}x{c.v}")
            if h == 0 or w == 0:
                raise JpegError("zero frame dimension")
            frame = (h, w, comps, precision)
            progressive = marker == 0xC2
        elif marker == 0xC3:
            raise JpegError(
                "lossless JPEG (SOF3) is not supported")
        elif marker in (0xC5, 0xC6, 0xC7,
                        0xC9, 0xCA, 0xCB, 0xCD, 0xCE, 0xCF):
            raise JpegError(
                f"unsupported JPEG process (SOF{marker & 0xF})")
        elif marker == 0xDA:             # SOS
            if frame is None:
                raise JpegError("SOS before SOF")
            scan = _parse_sos_body(body, frame, progressive)
            return frame, scan, pos + 2 + seglen, progressive
        # APPn/COM/others: skipped.
        pos += 2 + seglen
    raise JpegError("no SOS/EOI")


def _parse_sos_body(body: bytes, frame, progressive: bool):
    """SOS body -> (selected components, Ss, Se, Ah, Al).

    Baseline keeps the one-interleaved-scan constraint; progressive
    scans may name any component subset (non-interleaved AC scans are
    mandatory there, T.81 G.1.1.1.1)."""
    if len(body) < 1:
        raise JpegError("truncated SOS")
    ns = body[0]
    if not 1 <= ns <= 4 or len(body) < 1 + 2 * ns + 3:
        raise JpegError("truncated SOS components")
    if not progressive and ns != len(frame[2]):
        # Non-interleaved multi-scan BASELINE files exist but this
        # decoder walks one interleaved scan; misparsing the entropy
        # stream would yield garbage, so fail loud.
        raise JpegError(
            "non-interleaved (multi-scan) sequential JPEG is not "
            "supported")
    sel = []
    for si in range(ns):
        cs, tdta = body[1 + 2 * si:3 + 2 * si]
        for c in frame[2]:
            if c.ident == cs:
                c.td, c.ta = tdta >> 4, tdta & 0xF
                sel.append(c)
                break
        else:
            raise JpegError(f"SOS names unknown component {cs}")
    ss, se, ahal = body[1 + 2 * ns:4 + 2 * ns]
    ah, al = ahal >> 4, ahal & 0xF
    if progressive:
        if ss > se or se > 63 or al > 13 or ah > 13:
            raise JpegError(f"bad spectral selection {ss}..{se}")
        if ss == 0 and se != 0:
            raise JpegError("progressive DC scan must have Se=0")
        if ss > 0 and len(sel) != 1:
            raise JpegError("progressive AC scan must be single-"
                            "component")
    return sel, ss, se, ah, al


def _jpeg_error_contract(fn):
    """Everything malformed must surface as :class:`JpegError` (a
    ValueError): these streams come from untrusted files, and server
    error mapping turns ValueError into a 4xx instead of a 500.  The
    explicit length checks cover the known shapes; this net catches any
    residual IndexError/struct.error/OverflowError from hostile input."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except (IndexError, struct.error, OverflowError,
                MemoryError) as e:
            raise JpegError(f"malformed JPEG stream: {e}") from e
    return wrapped


@_jpeg_error_contract
def parse_jpeg_tables(tables_bytes: bytes) -> _TableSet:
    """Parse a TIFF ``JPEGTables`` (tag 347) abbreviated stream."""
    ts = _TableSet()
    _parse_segments(tables_bytes, ts)
    return ts


@_jpeg_error_contract
def decode_baseline_jpeg(data: bytes,
                         tables: Optional[_TableSet] = None
                         ) -> np.ndarray:
    """Decode one JPEG (baseline SOF0/1 or progressive SOF2, optionally
    abbreviated) to ``u8[h, w, ncomp]`` raw components (no color
    transform)."""
    ts = _TableSet()
    if tables is not None:
        ts.quant.update(tables.quant)
        ts.huff_dc.update(tables.huff_dc)
        ts.huff_ac.update(tables.huff_ac)
        ts.restart_interval = tables.restart_interval
    frame, scan, scan_start, progressive = _parse_segments(data, ts)
    if frame is None or scan is None:
        raise JpegError("stream has no frame/scan")
    h, w, comps, precision = frame
    hmax = max(c.h for c in comps)
    vmax = max(c.v for c in comps)
    mcux = -(-w // (8 * hmax))
    mcuy = -(-h // (8 * vmax))

    for c in comps:
        if c.tq not in ts.quant:
            raise JpegError(f"missing quant table {c.tq}")

    # Per-component coefficient grids [by, bx, 64] (zigzag order).
    grids = []
    for c in comps:
        grids.append(np.zeros((mcuy * c.v, mcux * c.h, 64), np.int32))

    if progressive:
        _decode_progressive_scans(data, ts, frame, grids, scan,
                                  scan_start, hmax, vmax, mcux, mcuy)
        return _reconstruct(frame, ts, grids, hmax, vmax)

    sel, ss, se, ah, al = scan
    for c in comps:
        if c.td not in ts.huff_dc or c.ta not in ts.huff_ac:
            raise JpegError("missing huffman table")

    reader = _BitReader(data, scan_start)
    preds = [0] * len(comps)
    ri = ts.restart_interval
    mcu_index = 0
    block = np.zeros(64, np.int32)
    for my in range(mcuy):
        for mx in range(mcux):
            if ri and mcu_index and mcu_index % ri == 0:
                reader.restart()
                preds = [0] * len(comps)
            mcu_index += 1
            for ci, c in enumerate(comps):
                dc_tbl = ts.huff_dc[c.td]
                ac_tbl = ts.huff_ac[c.ta]
                grid = grids[ci]
                for by in range(c.v):
                    for bx in range(c.h):
                        block[:] = 0
                        t = _decode_huff(reader, dc_tbl)
                        if t > 15:
                            # A corrupt DHT can map codes to arbitrary
                            # byte values; DCT DC categories stop at 15
                            # at BOTH 8- and 12-bit precision (SSSS 16
                            # exists only in lossless coding).
                            raise JpegError("bad DC category")
                        diff = _extend(reader.receive(t), t)
                        preds[ci] += diff
                        block[0] = preds[ci]
                        k = 1
                        while k < 64:
                            rs = _decode_huff(reader, ac_tbl)
                            r, s = rs >> 4, rs & 0xF
                            if s == 0:
                                if r == 15:
                                    k += 16       # ZRL
                                    continue
                                break             # EOB
                            k += r
                            if k > 63:
                                raise JpegError("AC run overflow")
                            block[k] = _extend(reader.receive(s), s)
                            k += 1
                        grid[my * c.v + by, mx * c.h + bx] = block
    if reader.marker not in (None, 0xD9):
        # Trailing RST is tolerated; anything else is malformed.
        if not (0xD0 <= (reader.marker or 0) <= 0xD7):
            raise JpegError(f"unexpected marker {reader.marker:#x}")
    return _reconstruct(frame, ts, grids, hmax, vmax)


def _reconstruct(frame, ts: _TableSet, grids, hmax: int,
                 vmax: int) -> np.ndarray:
    """Vectorized dequant + IDCT + level shift, per component.

    12-bit frames (extended sequential / progressive, T.81 Table B.2)
    level-shift by 2048 and serve uint16 planes — the
    precision-over-8 microscopy exports Bio-Formats reads."""
    h, w, comps, precision = frame
    shift = 1 << (precision - 1)
    top = (1 << precision) - 1
    dtype = np.uint8 if precision == 8 else np.uint16
    planes = []
    for c, grid in zip(comps, grids):
        q = ts.quant[c.tq]
        by, bx = grid.shape[:2]
        coeff = np.zeros((by, bx, 64), np.float32)
        coeff[..., ZIGZAG] = grid * q            # un-zigzag + dequant
        coeff = coeff.reshape(by, bx, 8, 8)
        spatial = np.einsum("ux,ybuv,vz->ybxz", _IDCT_M, coeff,
                            _IDCT_M, optimize=True)
        plane = spatial.transpose(0, 2, 1, 3).reshape(by * 8, bx * 8)
        plane = np.clip(np.round(plane) + shift, 0, top).astype(dtype)
        # Upsample to full MCU-grid resolution (pixel replication).
        if c.h < hmax:
            plane = np.repeat(plane, hmax // c.h, axis=1)
        if c.v < vmax:
            plane = np.repeat(plane, vmax // c.v, axis=0)
        planes.append(plane[:h, :w])
    return np.stack(planes, axis=-1)


# ---------------------------------------------------- progressive scans

# Bound on the scan count (T.81 allows many; real encoders emit ~10):
# hostile streams must not drive unbounded re-walks of the image.
_MAX_SCANS = 256

# FLOOR of the cumulative block-visit budget across ALL scans: every
# scan re-walks its band over the frame, so scan count alone is not a
# work bound — a tiny stream declaring a huge frame plus many scans
# (which decode "successfully" off the reader's 1-bit padding) would
# amplify far past the frame-size cap.  The effective budget scales
# with the DECLARED frame (``max(floor, 64 * blocks_per_frame)``, the
# rule the native decoder shares), so a deep scan script over a
# legitimately large frame decodes while amplification beyond ~64 full
# walks is rejected.
_MAX_BLOCK_VISITS = 1 << 23


class _ScanScript:
    """Successive-approximation succession state (T.81 G.1.1.1.1):
    tracks each coefficient's current approximation level so a
    malformed-but-parseable scan script raises instead of silently
    decoding garbage — an AC scan needs its component's DC first scan,
    a first scan per coefficient happens once, and a refinement's Ah
    must continue the band's Al (with Al = Ah - 1).  Identical rules in
    the native decoder (byte-parity contract)."""

    _NONE = -2

    def __init__(self, ncomp: int) -> None:
        self.dc_al = [self._NONE] * ncomp
        self.ac_al = [[self._NONE] * 64 for _ in range(ncomp)]

    def validate(self, comps, sel, ss, se, ah, al) -> None:
        if ss == 0:
            for c in sel:
                ci = comps.index(c)
                if ah == 0:
                    if self.dc_al[ci] != self._NONE:
                        raise JpegError("duplicate DC first scan")
                elif self.dc_al[ci] != ah or al != ah - 1:
                    raise JpegError(
                        f"DC refinement Ah={ah} does not continue "
                        f"Al={self.dc_al[ci]}")
                self.dc_al[ci] = al
            return
        ci = comps.index(sel[0])
        if self.dc_al[ci] == self._NONE:
            raise JpegError("AC scan before the component's DC scan")
        band = self.ac_al[ci]
        for k in range(ss, se + 1):
            if ah == 0:
                if band[k] != self._NONE:
                    raise JpegError("duplicate AC first scan")
            elif band[k] != ah or al != ah - 1:
                raise JpegError(
                    f"AC refinement Ah={ah} does not continue "
                    f"Al={band[k]}")
            band[k] = al


def _next_marker_pos(data: bytes, pos: int) -> int:
    """First non-RST, non-stuffing marker at/after ``pos`` (the segment
    stream between progressive scans)."""
    while pos + 1 < len(data):
        if data[pos] == 0xFF and data[pos + 1] not in (0x00, 0xFF) \
                and not (0xD0 <= data[pos + 1] <= 0xD7):
            return pos
        pos += 1
    raise JpegError("no marker after scan")


def _decode_progressive_scans(data, ts, frame, grids, scan, scan_start,
                              hmax, vmax, mcux, mcuy) -> None:
    """Accumulate every progressive scan into the coefficient grids.

    DC scans (Ss=0) walk the MCU grid interleaved (or a component's own
    block grid when single-component); AC scans (Ss>0) are always
    single-component and walk the component's TRUE block grid — MCU
    padding blocks are not coded in non-interleaved scans
    (T.81 G.2 / A.2.2).
    """
    h, w, comps, _precision = frame
    visits = 0
    # Frame-scaled budget (floor _MAX_BLOCK_VISITS): see the constant's
    # comment; the native decoder applies the same rule.  The scale
    # term is CAPPED (1 << 25 visits, ~30 s worst case on this pure-
    # Python path) so attacker-declared SOF dimensions cannot buy
    # unbounded amplification headroom.
    total_blocks = sum(mcux * c.h * mcuy * c.v for c in comps)
    max_visits = max(_MAX_BLOCK_VISITS,
                     min(64 * total_blocks, 1 << 25))
    script = _ScanScript(len(comps))
    for _ in range(_MAX_SCANS):
        sel, ss, se, ah, al = scan
        script.validate(comps, sel, ss, se, ah, al)
        if ss == 0:
            visits += (sum(mcux * c.h * mcuy * c.v for c in sel)
                       if len(sel) > 1 else
                       int(np.prod(_comp_block_dims(sel[0], h, w,
                                                    hmax, vmax))))
        else:
            visits += int(np.prod(_comp_block_dims(sel[0], h, w,
                                                   hmax, vmax)))
        if visits > max_visits:
            raise JpegError("progressive stream exceeds the "
                            "cumulative block budget")
        reader = _BitReader(data, scan_start)
        if ss == 0:
            _prog_dc_scan(reader, ts, sel, comps, grids, ah, al,
                          mcux, mcuy, h, w, hmax, vmax)
        else:
            _prog_ac_scan(reader, ts, sel[0], comps, grids, ss, se,
                          ah, al, h, w, hmax, vmax)
        # Next segment stream starts at the first marker past the
        # scan's entropy bytes.
        pos = _next_marker_pos(data, reader.pos)
        scan = None
        while pos + 2 <= len(data):
            marker = data[pos + 1]
            if marker == 0xD9:           # EOI: done
                return
            if pos + 4 > len(data):
                raise JpegError("truncated segment")
            seglen = struct.unpack(">H", data[pos + 2:pos + 4])[0]
            if seglen < 2 or pos + 2 + seglen > len(data):
                raise JpegError("truncated segment")
            body = data[pos + 4:pos + 2 + seglen]
            if marker == 0xDA:
                scan = _parse_sos_body(body, frame, True)
                scan_start = pos + 2 + seglen
                break
            # Inter-scan DHT/DQT/DRI updates reuse the SOI-path parser
            # by faking a minimal stream prefix.
            _parse_segments(
                b"\xff\xd8" + data[pos:pos + 2 + seglen] + b"\xff\xd9",
                ts)
            pos += 2 + seglen
        if scan is None:
            raise JpegError("progressive stream ended without EOI")
    raise JpegError(f"more than {_MAX_SCANS} scans")


def _comp_block_dims(c, h, w, hmax, vmax):
    """A component's TRUE (non-interleaved) block-grid dimensions."""
    cw = -(-w * c.h // hmax)
    ch = -(-h * c.v // vmax)
    return -(-ch // 8), -(-cw // 8)


def _prog_dc_scan(reader, ts, sel, comps, grids, ah, al,
                  mcux, mcuy, h, w, hmax, vmax) -> None:
    for c in sel:
        if ah == 0 and c.td not in ts.huff_dc:
            raise JpegError("missing huffman table")
    ri = ts.restart_interval
    preds = {c.ident: 0 for c in sel}
    interleaved = len(sel) > 1

    def first_bit(c, grid, by, bx):
        t = _decode_huff(reader, ts.huff_dc[c.td])
        if t > 15:
            raise JpegError("bad DC category")
        preds[c.ident] += _extend(reader.receive(t), t)
        grid[by, bx, 0] = preds[c.ident] << al

    def refine_bit(c, grid, by, bx):
        if reader.receive(1):
            grid[by, bx, 0] |= (1 << al)

    visit = first_bit if ah == 0 else refine_bit
    unit = 0
    if interleaved:
        pairs = [(c, grids[comps.index(c)]) for c in sel]
        for my in range(mcuy):
            for mx in range(mcux):
                if ri and unit and unit % ri == 0:
                    reader.restart()
                    preds = {c.ident: 0 for c in sel}
                unit += 1
                for c, grid in pairs:
                    for by in range(c.v):
                        for bx in range(c.h):
                            visit(c, grid, my * c.v + by, mx * c.h + bx)
    else:
        c = sel[0]
        grid = grids[comps.index(c)]
        nby, nbx = _comp_block_dims(c, h, w, hmax, vmax)
        for by in range(nby):
            for bx in range(nbx):
                if ri and unit and unit % ri == 0:
                    reader.restart()
                    preds = {c.ident: 0 for c in sel}
                unit += 1
                visit(c, grid, by, bx)


def _prog_ac_scan(reader, ts, c, comps, grids, ss, se, ah, al,
                  h, w, hmax, vmax) -> None:
    if c.ta not in ts.huff_ac:
        raise JpegError("missing huffman table")
    ac_tbl = ts.huff_ac[c.ta]
    grid = grids[comps.index(c)]
    nby, nbx = _comp_block_dims(c, h, w, hmax, vmax)
    ri = ts.restart_interval
    eobrun = 0
    unit = 0
    for by in range(nby):
        for bx in range(nbx):
            if ri and unit and unit % ri == 0:
                reader.restart()
                eobrun = 0
            unit += 1
            block = grid[by, bx]
            if ah == 0:
                eobrun = _ac_first_block(reader, ac_tbl, block, ss, se,
                                         al, eobrun)
            else:
                eobrun = _ac_refine_block(reader, ac_tbl, block, ss, se,
                                          al, eobrun)


def _ac_first_block(reader, ac_tbl, block, ss, se, al, eobrun) -> int:
    """T.81 G.2.2: first pass over an AC spectral band."""
    if eobrun:
        return eobrun - 1
    k = ss
    while k <= se:
        rs = _decode_huff(reader, ac_tbl)
        r, s = rs >> 4, rs & 0xF
        if s == 0:
            if r == 15:
                k += 16                       # ZRL
                continue
            eobrun = 1 << r
            if r:
                eobrun += reader.receive(r)
            return eobrun - 1                 # covers this block
        k += r
        if k > se:
            raise JpegError("AC run overflow")
        block[k] = _extend(reader.receive(s), s) << al
        k += 1
    return 0


def _ac_refine_block(reader, ac_tbl, block, ss, se, al, eobrun) -> int:
    """T.81 G.2.3 correction pass (the jdphuff.c refinement walk):
    every already-nonzero coefficient in the band gets one correction
    bit as it is passed; zero-history coefficients consume the run
    lengths and receive new ±1<<Al values."""
    p1 = 1 << al
    m1 = -1 << al

    def correct(k):
        # libjpeg's jdphuff form: partially-decoded coefficients are
        # multiples of p1, where (x & p1) == (|x| & p1) in two's
        # complement, so the signed test equals the spec's magnitude
        # test.
        if reader.receive(1) and not (block[k] & p1):
            block[k] += p1 if block[k] >= 0 else m1

    k = ss
    if not eobrun:
        while k <= se:
            rs = _decode_huff(reader, ac_tbl)
            r, s = rs >> 4, rs & 0xF
            val = 0
            if s == 0:
                if r != 15:
                    eobrun = 1 << r
                    if r:
                        eobrun += reader.receive(r)
                    break
                # r == 15: run of 16 zero-history coefficients
            else:
                if s != 1:
                    raise JpegError("bad refinement size")
                val = p1 if reader.receive(1) else m1
            while k <= se:
                if block[k]:
                    correct(k)
                else:
                    if r == 0:
                        if val:
                            block[k] = val
                        k += 1
                        break
                    r -= 1
                k += 1
            else:
                if val:
                    raise JpegError("refinement value past band end")
    if eobrun:
        while k <= se:
            if block[k]:
                correct(k)
            k += 1
        eobrun -= 1
    return eobrun


def ycbcr_to_rgb(img: np.ndarray) -> np.ndarray:
    """JFIF YCbCr -> RGB on u8[h, w, 3] (BT.601 full range)."""
    y = img[..., 0].astype(np.float32)
    cb = img[..., 1].astype(np.float32) - 128.0
    cr = img[..., 2].astype(np.float32) - 128.0
    rgb = np.stack([
        y + 1.402 * cr,
        y - 0.344136 * cb - 0.714136 * cr,
        y + 1.772 * cb,
    ], axis=-1)
    return np.clip(np.round(rgb), 0, 255).astype(np.uint8)


def _sniff_precision(data: bytes) -> int:
    """The frame's SOF sample precision from a header-only walk
    (default 8 when no SOF is found before SOS — the full parse will
    produce the real error)."""
    pos = 2
    while pos + 4 <= len(data):
        if data[pos] != 0xFF:
            return 8
        marker = data[pos + 1]
        if marker in (0xD9, 0xDA):
            return 8
        if marker == 0x01 or 0xD0 <= marker <= 0xD7:
            pos += 2
            continue
        if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
            return data[pos + 4] if pos + 4 < len(data) else 8
        seglen = struct.unpack(">H", data[pos + 2:pos + 4])[0]
        if seglen < 2:
            return 8
        pos += 2 + seglen
    return 8


def decode_tiff_jpeg(data: bytes, tables_bytes: Optional[bytes],
                     photometric: int,
                     tables_cache: Optional[dict] = None) -> np.ndarray:
    """Decode one TIFF compression-7 segment to ``u8[h, w, spp]``.

    Prefers the native decoder (``native.jpeg_decode_baseline``, which
    despite the name covers baseline SOF0/1 AND progressive SOF2),
    falls back to the pure-Python implementation — the LZW pattern.
    YCbCr (photometric 6) converts to RGB here; photometric 1/2 pass
    raw components through (libtiff writes photometric 2 with RGB
    stored directly in the JPEG).  ``tables_cache`` (per-TiffFile)
    memoizes the parsed JPEGTables so the Python path builds its
    Huffman lookups once per file rather than once per tile; the native
    decoder's own table build is a ~1 MB fill, noise next to its
    per-tile decode.
    """
    out: Optional[np.ndarray] = None
    if _sniff_precision(data) == 8:
        # The native fast path is 8-bit only; 12-bit extended/
        # progressive frames take the Python decoder (uint16 output).
        try:
            from ..native import jpeg_decode_baseline
            out = jpeg_decode_baseline(data, tables_bytes)
        except ImportError:
            pass
    if out is None:
        ts = None
        if tables_bytes:
            if tables_cache is not None:
                ts = tables_cache.get(tables_bytes)
            if ts is None:
                ts = parse_jpeg_tables(tables_bytes)
                if tables_cache is not None:
                    tables_cache[tables_bytes] = ts
        out = decode_baseline_jpeg(data, ts)
    if photometric == 6:
        if out.shape[-1] != 3:
            raise JpegError(
                f"YCbCr photometric with {out.shape[-1]} components")
        if out.dtype != np.uint8:
            raise JpegError(
                "12-bit YCbCr JPEG-in-TIFF is not supported (12-bit "
                "microscopy exports store components directly)")
        out = ycbcr_to_rgb(out)
    return out
