"""The ``PixelSource`` protocol (≙ ``ome.io.nio.PixelBuffer``).

Exactly the surface the reference consumes from its pixel buffer
(SURVEY.md section 2b): region reads at a resolution level, whole-stack reads
for projection, pyramid level/size enumeration, and the server tile size.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Protocol, Tuple, runtime_checkable

import numpy as np

from ..server.region import RegionDef


@dataclass
class TileRead:
    """A raw region read: the pixels plus the region actually served."""

    data: np.ndarray          # [h, w] in the source dtype
    region: RegionDef         # region in level coordinates (post-truncation)
    level: int                # resolution level, 0 = largest


@runtime_checkable
class PixelSource(Protocol):
    """Raw pixel reader for one image (5D XYZCT, with an XY pyramid)."""

    @property
    def dtype(self) -> np.dtype:
        ...

    def resolution_levels(self) -> int:
        """Number of pyramid levels (1 = not a pyramid)
        (≙ ``PixelBuffer.getResolutionLevels``,
        call site ``ImageRegionRequestHandler.java:446``)."""
        ...

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        """[(size_x, size_y)] per level, largest first
        (≙ ``getResolutionDescriptions``, ``:447-449``)."""
        ...

    def tile_size(self) -> Tuple[int, int]:
        """(width, height) of the server-preferred tile
        (≙ ``getTileSize``, ``:797``)."""
        ...

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        """Read a rectangular region of one plane at a pyramid level.

        Region coordinates are in the level's pixel space; the caller is
        responsible for truncation to level bounds (the reference truncates
        in ``getRegionDef``, ``:751-758``).  Returns [h, w] in the source
        dtype.
        """
        ...

    def get_stack(self, c: int, t: int) -> np.ndarray:
        """Whole Z-stack of one channel at level 0: [Z, H, W]
        (≙ ``PixelBuffer.getStack``, ``ProjectionService.java:72``)."""
        ...

    def close(self) -> None:
        ...
