"""Device-resident raw tile cache: HBM as the hot tier of the tile store.

SURVEY.md §2b maps the reference's ``PixelBuffer`` to "a tile reader
service with host-pinned staging -> HBM".  This is the HBM half: raw
channel planes are settings-independent, and the interactive OMERO.web
pattern is re-requesting the same tiles with different windows/colors/
LUTs — so after the first read, a settings change costs zero host->device
bytes (the dominant cost on link-constrained deployments; the encoded
region cache above this one only covers byte-identical requests).

Keyed by (image, z, t, level, region, channels); bounded by device bytes
with LRU eviction (dropping the reference frees the HBM buffer).  Raw
planes stay in their storage dtype (uint16 halves HBM vs float32); the
render kernels cast on device.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Hashable, Tuple


class DeviceRawCache:
    """LRU of device-resident raw tile arrays.

    ``get_or_load(key, loader)`` returns a ``jax.Array``; ``loader()``
    supplies the host ndarray on miss.  Thread-safe (the render path runs
    in worker threads); the device transfer happens outside the lock, and
    concurrent misses on one key may both load — last write wins, which
    is correct for immutable pixel data.
    """

    def __init__(self, max_bytes: int = 2 * 1024 * 1024 * 1024):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, object]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get_or_load(self, key: Hashable, loader: Callable):
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return arr
            self.misses += 1
        import jax
        import numpy as np
        loaded = loader()
        if isinstance(loaded, np.ndarray):
            # Host ndarray miss: packed staging ships ~1.4x fewer wire
            # bytes for uint16 pixel content (io.staging.stage falls
            # back to a plain transfer when packing doesn't pay).
            from .staging import stage
            arr = stage(loaded)
        else:
            # Already device-resident (banded staging path).
            arr = jax.device_put(loaded)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = arr
            self._bytes += arr.nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
        return arr

    def get(self, key: Hashable):
        """Pure hit probe WITH the LRU bump; None on miss (the serving
        fast path — callers fall back to ``get_or_load`` off-loop)."""
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            return arr

    def __contains__(self, key: Hashable) -> bool:
        """Residency probe without an LRU bump (prefetch skip check)."""
        with self._lock:
            return key in self._entries

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)


def region_key(image_id: int, z: int, t: int, level: int,
               region: Tuple[int, int, int, int],
               channels: Tuple[int, ...]) -> tuple:
    """The raw-read identity: everything the pixel data depends on and
    nothing the rendering settings touch."""
    return (image_id, z, t, level, region, channels)
