"""Device-resident raw tile cache: HBM as the hot tier of the tile store.

SURVEY.md §2b maps the reference's ``PixelBuffer`` to "a tile reader
service with host-pinned staging -> HBM".  This is the HBM half: raw
channel planes are settings-independent, and the interactive OMERO.web
pattern is re-requesting the same tiles with different windows/colors/
LUTs — so after the first read, a settings change costs zero host->device
bytes (the dominant cost on link-constrained deployments; the encoded
region cache above this one only covers byte-identical requests).

Keyed by (image, z, t, level, region, channels); bounded by device bytes
with LRU eviction (dropping the reference frees the HBM buffer).  Raw
planes stay in their storage dtype (uint16 halves HBM vs float32); the
render kernels cast on device.

Content addressing: with ``digest_index`` on (the default), every host
plane stack staged through :meth:`DeviceRawCache.get_or_load` is also
indexed by its content digest (:func:`plane_digest`).  A plane whose
bytes are already resident — under ANY key: a wire-pushed
``("plane", digest)`` entry, or the same content read for a different
region identity — is never re-shipped over the host->device link; the
new key aliases the resident buffer.  This is what backs the sidecar's
digest-first wire protocol (``server.sidecar``: probe by digest, upload
only on miss).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, Hashable, Optional, Set, Tuple


def plane_digest(arr) -> str:
    """Content address of a host plane stack: dtype + shape + bytes.

    BLAKE2b-128 — collision-safe at cache scale and ~GB/s on host, so
    digesting an 8 MB tile costs ~ms against the 100s-of-ms its upload
    costs on a thin link.
    """
    import hashlib

    import numpy as np

    a = np.ascontiguousarray(arr)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(a.dtype).encode())
    h.update(",".join(str(s) for s in a.shape).encode())
    h.update(memoryview(a).cast("B"))
    return h.hexdigest()


class DeviceRawCache:
    """LRU of device-resident raw tile arrays.

    ``get_or_load(key, loader)`` returns a ``jax.Array``; ``loader()``
    supplies the host ndarray on miss.  Thread-safe (the render path runs
    in worker threads); the device transfer happens outside the lock, and
    concurrent misses on one key may both load — last write wins, which
    is correct for immutable pixel data.
    """

    def __init__(self, max_bytes: int = 2 * 1024 * 1024 * 1024,
                 digest_index: bool = True):
        self.max_bytes = max_bytes
        self.digest_index = digest_index
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, object]" = OrderedDict()
        # Content-digest index: digest -> the keys whose entries hold
        # that content (aliases share ONE device buffer).
        self._digests_of: Dict[Hashable, str] = {}
        self._keys_by_digest: Dict[str, Set[Hashable]] = {}
        # Request-routing identity of each region entry (the fleet's
        # ``plane_route_key``), recorded at staging: what lets a
        # rolling drain hand each plane of this shard to the member
        # that will actually SERVE its future requests.
        self._route_of: Dict[Hashable, str] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Uploads skipped (served) / paid because of the content digest.
        self.plane_hits = 0
        self.plane_misses = 0

    # ------------------------------------------------------------ digest

    def get_by_digest(self, digest: str, bump: bool = True):
        """Device buffer holding this content under any key; None when
        the content is not resident.  ``bump=False`` skips the LRU
        touch (the internal alias lookup: the NEW key gets its own LRU
        position, and the alias source's age must stay its own)."""
        with self._lock:
            for key in self._keys_by_digest.get(digest, ()):
                arr = self._entries.get(key)
                if arr is not None:
                    if bump:
                        self._entries.move_to_end(key)
                    return arr
        return None

    def count_plane(self, hit: bool) -> None:
        """Lock-protected plane-counter bump — every mutation of the
        hit/miss counters goes through the lock (worker threads race
        these), including the external staging helper
        (``io.staging.stage_deduped``)."""
        with self._lock:
            if hit:
                self.plane_hits += 1
            else:
                self.plane_misses += 1

    def resident_digest(self, digest: str, count: bool = True) -> bool:
        """Digest-probe residency (the sidecar wire's ``plane_probe``
        answer).  ``count`` feeds the plane-cache HIT counter only — a
        probe hit is an upload that never happens.  A probe miss is NOT
        counted here: the upload that follows lands in
        :meth:`get_or_load`, which records the one miss, so one actual
        upload is exactly one ``plane_misses`` increment."""
        with self._lock:
            resident = bool(self._keys_by_digest.get(digest))
            if count and resident:
                self.plane_hits += 1
            return resident

    def _index_digest(self, key: Hashable, digest: Optional[str]) -> None:
        """Record key->digest under the lock (caller holds it)."""
        if digest is None:
            return
        self._digests_of[key] = digest
        self._keys_by_digest.setdefault(digest, set()).add(key)

    def _drop_digest(self, key: Hashable) -> None:
        digest = self._digests_of.pop(key, None)
        if digest is None:
            return
        keys = self._keys_by_digest.get(digest)
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._keys_by_digest[digest]

    def _release_bytes(self, key: Hashable, arr) -> None:
        """Remove a key's accounting (lock held).  Digest aliases share
        ONE device buffer, so its bytes leave the budget only when the
        LAST key referencing that content goes."""
        self._route_of.pop(key, None)
        digest = self._digests_of.get(key)
        self._drop_digest(key)
        if digest is None or not self._keys_by_digest.get(digest):
            self._bytes -= arr.nbytes

    # ------------------------------------------------------------- loads

    def get_or_load(self, key: Hashable, loader: Callable,
                    digest: Optional[str] = None,
                    route_key: Optional[str] = None):
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return arr
            self.misses += 1
        import jax
        import numpy as np
        loaded = loader()
        arr = None
        if isinstance(loaded, np.ndarray):
            if self.digest_index:
                # Content-addressed staging skip: bytes already resident
                # under another key (a wire-pushed plane, or the same
                # content at a different region identity) alias the
                # resident buffer — zero host->device bytes.
                digest = digest or plane_digest(loaded)
                arr = self.get_by_digest(digest, bump=False)
                self.count_plane(hit=arr is not None)
                if arr is not None:
                    # Cost ledger: the upload this request did NOT pay
                    # (dedup-skipped HBM bytes).  No-op outside a
                    # request trace context (prefetch, prewarm).
                    from ..utils import telemetry
                    telemetry.add_cost("staged_bytes_skipped",
                                       loaded.nbytes)
            if arr is None:
                # Host ndarray miss: packed staging ships ~1.4x fewer
                # wire bytes for uint16 pixel content (io.staging.stage
                # falls back to a plain transfer when packing doesn't
                # pay).
                from .staging import stage
                arr = stage(loaded)
                from ..utils import telemetry
                telemetry.add_cost("staged_bytes", loaded.nbytes)
        else:
            # Already device-resident (banded staging path); content
            # digests are host-side only, so these entries carry none.
            arr = jax.device_put(loaded)
            digest = None
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._release_bytes(key, old)
            digest = digest if self.digest_index else None
            if digest is not None:
                # Re-probe under the lock: a racing miss for the SAME
                # content may have landed since the pre-stage check.
                # Adopt its buffer (dropping the one this thread just
                # staged) so digest aliases always share one HBM
                # allocation and the byte charge stays buffer-accurate
                # — without this, two live buffers would carry one
                # budget charge and max_bytes would no longer bound
                # real device memory.
                for k in self._keys_by_digest.get(digest, ()):
                    existing = self._entries.get(k)
                    if existing is not None:
                        arr = existing
                        break
            self._entries[key] = arr
            if route_key is not None:
                self._route_of[key] = route_key
            # Aliases share one device buffer: its bytes enter the
            # budget once, with the digest's FIRST key — so effective
            # capacity GROWS with dedup instead of shrinking under
            # double counting.
            if digest is None or not self._keys_by_digest.get(digest):
                self._bytes += arr.nbytes
            self._index_digest(key, digest)
            evicted_labels = []
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                evicted_key, evicted = self._entries.popitem(last=False)
                self._release_bytes(evicted_key, evicted)
                self.evictions += 1
                evicted_labels.append((str(evicted_key)[:80],
                                       evicted.nbytes))
        if evicted_labels:
            # Black box (outside the lock): an eviction storm right
            # before a stall is the "hot set no longer fits" signature.
            from ..utils import telemetry
            for label, nbytes in evicted_labels:
                telemetry.FLIGHT.record("rawcache.evict", key=label,
                                        bytes=nbytes)
        return arr

    def get(self, key: Hashable):
        """Pure hit probe WITH the LRU bump; None on miss (the serving
        fast path — callers fall back to ``get_or_load`` off-loop)."""
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            return arr

    def __contains__(self, key: Hashable) -> bool:
        """Residency probe without an LRU bump (prefetch skip check)."""
        with self._lock:
            return key in self._entries

    def resident_digests(self) -> Set[str]:
        """Snapshot of every content digest currently resident (fleet
        shard accounting: across members these sets should be pairwise
        disjoint — a digest on two members means a plane was staged
        twice, the duplication the consistent-hash router exists to
        prevent)."""
        with self._lock:
            return set(self._keys_by_digest)

    def resident_route(self, route_key: str) -> bool:
        """Residency by ROUTING identity (``plane_route_key``), no LRU
        bump: the explain plane's "is this plane warm on its owner"
        probe.  O(resident entries) over the recorded routes —
        operator-surface economics, never on the serving path."""
        with self._lock:
            return route_key in self._route_of.values()

    def evict_to_fraction(self, frac: float) -> int:
        """Brownout eviction (server.pressure "evict_caches"): walk
        LRU-first until resident bytes are at most ``frac`` of the
        budget, returning entries dropped.  The early, chosen form of
        the eviction that would otherwise happen per-miss at the worst
        moment — when the cache is already over budget mid-burst."""
        target = max(0, int(self.max_bytes * frac))
        evicted = []
        with self._lock:
            while self._bytes > target and len(self._entries) > 1:
                key, arr = self._entries.popitem(last=False)
                self._release_bytes(key, arr)
                self.evictions += 1
                evicted.append((str(key)[:80], arr.nbytes))
        if evicted:
            from ..utils import telemetry
            telemetry.FLIGHT.record("rawcache.pressure-evict",
                                    entries=len(evicted),
                                    bytes=sum(b for _, b in evicted))
        return len(evicted)

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot_entries(self, limit: int = 0):
        """Warm-state manifest export: the resident REGION entries
        (source coords + content digest), most-recently-used first.
        Only region keys are restageable from source at boot; content-
        only ``("plane", digest)`` entries and projection planes are
        skipped — their bytes exist nowhere but HBM.  ``limit`` 0 =
        all."""
        out = []
        with self._lock:
            keys = list(reversed(self._entries.keys()))   # MRU first
            for key in keys:
                if (not isinstance(key, tuple) or len(key) != 6
                        or not isinstance(key[0], int)):
                    continue
                image_id, z, t, level, region, channels = key
                entry = {
                    "key": [image_id, z, t, level, list(region),
                            list(channels)],
                    "digest": self._digests_of.get(key),
                }
                route = self._route_of.get(key)
                if route is not None:
                    # Routing identity for drain handoffs: which ring
                    # member will serve this plane's future requests.
                    entry["route"] = route
                out.append(entry)
                if limit and len(out) >= limit:
                    break
        return out

    def entries_for_route(self, route_key: str):
        """The restageable entries of ONE routing identity — the
        hot-key replica staging manifest (``FleetRouter
        ._stage_replicas`` ships exactly the promoted plane, not the
        whole shard).  Same entry shape as :meth:`snapshot_entries`,
        MRU first, no LRU bump."""
        out = []
        with self._lock:
            for key in reversed(self._entries.keys()):   # MRU first
                if self._route_of.get(key) != route_key:
                    continue
                if (not isinstance(key, tuple) or len(key) != 6
                        or not isinstance(key[0], int)):
                    continue
                image_id, z, t, level, region, channels = key
                out.append({
                    "key": [image_id, z, t, level, list(region),
                            list(channels)],
                    "digest": self._digests_of.get(key),
                    "route": route_key,
                })
        return out


def region_key(image_id: int, z: int, t: int, level: int,
               region: Tuple[int, int, int, int],
               channels: Tuple[int, ...]) -> tuple:
    """The raw-read identity: everything the pixel data depends on and
    nothing the rendering settings touch."""
    return (image_id, z, t, level, region, channels)


def plane_key(digest: str) -> tuple:
    """Cache key of a content-addressed (wire-pushed) plane entry."""
    return ("plane", digest)
